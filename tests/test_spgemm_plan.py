"""Plan-split Galerkin RAP (ISSUE 15 tentpole): the RapPlan structure
phase (ops/spgemm.py), the fused Pallas value kernel
(ops/pallas_spgemm.py, via force_pallas_interpret on the CPU rig), the
slab/numpy value routes, the planned level wiring (aggregation + GEO +
classical), structure-resetup plan carryover, value-resetup refresh,
the jaxpr proofs (one fused value kernel on the kernel route; zero
sort/argsort/unique prims on the slab route), and the `spgemm_plan=0`
eager escape hatch.
"""
import dataclasses

import numpy as np
import pytest
import jax
import jax.numpy as jnp

import amgx_tpu as amgx
from amgx_tpu import gallery
from amgx_tpu.config import Config
from amgx_tpu.amg.hierarchy import AMG
from amgx_tpu.ops import spgemm
from amgx_tpu.ops import pallas_spgemm as pk
from amgx_tpu.ops.pallas_spmv import force_pallas_interpret
from amgx_tpu.ops.spgemm import galerkin_rap
from amgx_tpu.telemetry import metrics as _tm

amgx.initialize()

_CLASSICAL = ("algorithm=CLASSICAL, selector=PMIS, smoother=JACOBI_L1,"
              " coarse_solver=DENSE_LU_SOLVER, min_coarse_rows=16,"
              " max_levels=10, strength_threshold=0.25")

_PCG_CLASSICAL = (
    "solver(s)=PCG, s:max_iters=60, s:tolerance=1e-8,"
    " s:convergence=RELATIVE_INI, s:monitor_residual=1,"
    " s:preconditioner(amg)=AMG, amg:algorithm=CLASSICAL,"
    " amg:selector=PMIS, amg:interpolator=D2, amg:smoother=JACOBI_L1,"
    " amg:interp_max_elements=4, amg:max_row_sum=0.9,"
    " amg:coarse_solver=DENSE_LU_SOLVER, amg:min_coarse_rows=16,"
    " amg:structure_reuse_levels=-1")


def _rel(a, b):
    a, b = np.asarray(a), np.asarray(b)
    return float(np.max(np.abs(a - b)) / max(np.max(np.abs(b)), 1e-300))


def _classical_level(n=8, dtype=jnp.float64, interp="D2"):
    A = gallery.poisson("7pt", n, n, n, dtype=dtype).init()
    amg = AMG(Config.from_string(
        _CLASSICAL + f", interpolator={interp}, interp_max_elements=4,"
        " max_row_sum=0.9")).setup(A)
    return amg.levels[0]


def _amg_of(slv):
    x = slv
    while not hasattr(x, "amg"):
        x = x.preconditioner
    return x.amg


def _scaled(A, f):
    def s(v):
        return None if v is None else v * f
    return dataclasses.replace(
        A, values=A.values * f, dia_vals=s(A.dia_vals),
        ell_vals=s(A.ell_vals), swell_vals=s(A.swell_vals),
        diag=s(A.diag))


# ---------------------------------------------------------------------------
# structure-phase + value-route parity (plan vs eager vs numpy)
# ---------------------------------------------------------------------------


def test_agg_plan_parity_vs_eager_f64():
    """The relabel plan reproduces `coarse_a_from_aggregates` exactly:
    same structure (sorted entries, row_offsets, diag_idx) and — both
    routes summing the lexsorted candidates in order — bitwise-equal
    f64 values."""
    from amgx_tpu.amg.aggregation.galerkin import coarse_a_from_aggregates
    A = gallery.poisson("7pt", 6, 6, 6, dtype=jnp.float64).init()
    rng = np.random.default_rng(0)
    agg = rng.integers(0, 40, A.num_rows)
    agg[:40] = np.arange(40)
    eager = coarse_a_from_aggregates(A, jnp.asarray(agg), 40)
    plan = spgemm.build_agg_plan(A, agg, 40)
    planned = spgemm.plan_coarse_matrix(plan, A)
    assert planned.nnz == eager.nnz
    assert np.array_equal(np.asarray(planned.row_offsets),
                          np.asarray(eager.row_offsets))
    assert np.array_equal(np.asarray(planned.col_indices),
                          np.asarray(eager.col_indices))
    assert np.array_equal(np.asarray(planned.diag_idx),
                          np.asarray(eager.diag_idx))
    assert _rel(planned.values, eager.values) < 1e-14


def test_agg_plan_external_diag_fold():
    """A DIAG-property matrix folds its external diagonal into the
    planned relabel exactly like the eager `_coarse_entries`."""
    from amgx_tpu.amg.aggregation.galerkin import coarse_a_from_aggregates
    A0 = gallery.poisson("7pt", 5, 5, 5, dtype=jnp.float64).init()
    rows, cols, vals = A0.coo()
    rows, cols, vals = (np.asarray(rows), np.asarray(cols),
                        np.asarray(vals))
    off = rows != cols
    d = np.zeros(A0.num_rows)
    np.add.at(d, rows[~off], vals[~off])
    from amgx_tpu.matrix import CsrMatrix
    A = CsrMatrix.from_coo(rows[off], cols[off], jnp.asarray(vals[off]),
                           A0.num_rows, A0.num_cols,
                           diag=jnp.asarray(d))
    assert A.has_external_diag
    agg = np.arange(A.num_rows) // 4
    nc = int(agg.max()) + 1
    eager = coarse_a_from_aggregates(A, jnp.asarray(agg), nc)
    plan = spgemm.build_agg_plan(A, agg, nc)
    assert plan.fold_diag
    planned = spgemm.plan_coarse_matrix(plan, A)
    assert _rel(planned.values, eager.values) < 1e-14


@pytest.mark.parametrize("interp", ["D1", "D2"])
def test_rap_plan_parity_vs_eager_f64(interp):
    """The two-stage plan reproduces the eager `galerkin_rap` triple
    product on real classical D1/D2 interpolation at f64 accuracy,
    with the identical output pattern."""
    lv = _classical_level(interp=interp)
    eager = galerkin_rap(lv.R, lv.A, lv.P)
    plan = spgemm.build_rap_plan(lv.R, lv.A, lv.P)
    planned = spgemm.plan_coarse_matrix(plan, lv.A, lv.R, lv.P)
    assert planned.nnz == eager.nnz
    assert np.array_equal(np.asarray(planned.col_indices),
                          np.asarray(eager.col_indices))
    assert np.array_equal(np.asarray(planned.row_offsets),
                          np.asarray(eager.row_offsets))
    assert _rel(planned.values, eager.values) < 1e-12


def test_host_vs_slab_route_parity():
    """The host route (native flat-FMA sweep, or reduceat without the
    toolchain) and the jnp slab program sum the SAME candidate sets —
    f64 agreement to summation-order roundoff."""
    lv = _classical_level()
    plan = spgemm.build_rap_plan(lv.R, lv.A, lv.P)
    np_vals = spgemm._rap_values_numpy(
        plan, np.asarray(lv.A.values), np.asarray(lv.R.values),
        np.asarray(lv.P.values))
    d = plan.dev()
    s1 = plan.stage1
    slab = spgemm._rap_values_slab(
        jnp.asarray(lv.A.values), jnp.asarray(lv.R.values),
        jnp.asarray(lv.P.values), d["sa"], d["sp"], d["seg1"],
        d["sr"], d["st"], d["seg2"], s1["nT"], plan.nU, True, True)
    assert _rel(np_vals, slab) < 1e-13


# ---------------------------------------------------------------------------
# the fused value kernel (interpret route)
# ---------------------------------------------------------------------------


def test_kernel_parity_interpret_f32():
    """Kernel route vs the slab reference (rap + agg forms), f32
    through the Pallas interpreter."""
    lv = _classical_level(dtype=jnp.float32)
    plan = spgemm.build_rap_plan(lv.R, lv.A, lv.P)
    ref = spgemm._rap_values_numpy(
        plan, np.asarray(lv.A.values), np.asarray(lv.R.values),
        np.asarray(lv.P.values))
    with force_pallas_interpret():
        assert pk.rap_kernel_ready(plan, jnp.float32)
        out = pk.rap_value_call(plan, jnp.asarray(lv.A.values),
                                lv.R.values, lv.P.values)
    assert _rel(out, ref) < 1e-6
    A = gallery.poisson("7pt", 8, 8, 8, dtype=jnp.float32).init()
    agg = np.arange(A.num_rows) // 8
    aplan = spgemm.build_agg_plan(A, agg, int(agg.max()) + 1)
    aref = spgemm._rap_values_numpy(aplan, np.asarray(A.values),
                                    None, None)
    with force_pallas_interpret():
        assert pk.rap_kernel_ready(aplan, jnp.float32)
        aout = pk.rap_value_call(aplan, jnp.asarray(A.values), None,
                                 None)
    assert _rel(aout, aref) < 1e-6


def test_kernel_chained_chunks_parity():
    """A shrunken VMEM budget forces the chained-block fallback; the
    chunked calls still reproduce the single-call values."""
    lv = _classical_level(n=10, dtype=jnp.float32)
    plan = spgemm.build_rap_plan(lv.R, lv.A, lv.P)
    ref = spgemm._rap_values_numpy(
        plan, np.asarray(lv.A.values), np.asarray(lv.R.values),
        np.asarray(lv.P.values))
    old_budget, old_min = pk._RAP_VMEM_BUDGET, pk._RAP_MIN_CHUNK
    try:
        pk._RAP_VMEM_BUDGET = 1 << 19
        pk._RAP_MIN_CHUNK = 8
        with force_pallas_interpret():
            assert pk.rap_kernel_ready(plan, jnp.float32)
            assert len(plan._kernel[0]) > 1, "budget did not chunk"
            out = pk.rap_value_call(plan, jnp.asarray(lv.A.values),
                                    lv.R.values, lv.P.values)
    finally:
        pk._RAP_VMEM_BUDGET, pk._RAP_MIN_CHUNK = old_budget, old_min
        plan._kernel = None
    assert _rel(out, ref) < 1e-6


def test_kernel_contrib_cap_declines():
    """A contributor run beyond RAP_MAX_CONTRIB declines the kernel
    route (the slab segment-sum handles any length) — never a wrong
    answer."""
    lv = _classical_level(n=8, dtype=jnp.float32)
    plan = spgemm.build_rap_plan(lv.R, lv.A, lv.P)
    old = pk.RAP_MAX_CONTRIB
    try:
        pk.RAP_MAX_CONTRIB = 1
        plan._kernel = None
        with force_pallas_interpret():
            assert not pk.rap_kernel_ready(plan, jnp.float32)
    finally:
        pk.RAP_MAX_CONTRIB = old
        plan._kernel = None
    # the declined plan still evaluates through rap_values (slab route)
    with force_pallas_interpret():
        vals = spgemm.rap_values(plan, lv.A, lv.R, lv.P)
    ref = spgemm._rap_values_numpy(
        plan, np.asarray(lv.A.values), np.asarray(lv.R.values),
        np.asarray(lv.P.values))
    assert _rel(vals, ref) < 1e-6


def test_vmap_routes_to_slab_form():
    """A vmapped coefficient stream over one plan takes the multi
    slab form in ops/batched.py (no pallas_call in the jaxpr), with
    per-system parity against the single calls."""
    A = gallery.poisson("7pt", 8, 8, 8, dtype=jnp.float32).init()
    agg = np.arange(A.num_rows) // 8
    plan = spgemm.build_agg_plan(A, agg, int(agg.max()) + 1)
    with force_pallas_interpret():
        assert pk.rap_kernel_ready(plan, jnp.float32)
        fn = lambda af: pk.rap_value_call(plan, af, None, None)  # noqa: E731
        AF = jnp.stack([jnp.asarray(A.values),
                        jnp.asarray(A.values) * 2.0])
        Y = jax.vmap(fn)(AF)
        jaxpr = str(jax.make_jaxpr(jax.vmap(fn))(AF))
        single = np.asarray(fn(jnp.asarray(A.values)))
    assert "pallas_call" not in jaxpr
    assert _rel(Y[0], single) < 1e-6
    assert _rel(Y[1], 2.0 * single) < 1e-6


def test_batched_slab_is_f64_reference():
    """rap_values_multi at f64 matches the eager triple product to
    1e-12 per system (the kernel tests' parity reference)."""
    from amgx_tpu.ops.batched import rap_values_multi
    lv = _classical_level()
    plan = spgemm.build_rap_plan(lv.R, lv.A, lv.P)
    eager = galerkin_rap(lv.R, lv.A, lv.P)
    d = plan.dev()
    AF = jnp.stack([jnp.asarray(lv.A.values),
                    jnp.asarray(lv.A.values) * 3.0])
    Y = rap_values_multi(d, AF, jnp.asarray(lv.R.values),
                         jnp.asarray(lv.P.values),
                         plan.stage1["nT"], plan.nU, True, True)
    assert _rel(Y[0], eager.values) < 1e-12
    assert _rel(Y[1], 3.0 * np.asarray(eager.values)) < 1e-12


# ---------------------------------------------------------------------------
# jaxpr proofs
# ---------------------------------------------------------------------------


def _outer_prims(closed):
    """Primitive names OUTSIDE pallas_call bodies, walking nested
    jaxprs (custom_vmap/jit call bodies included)."""
    out = []

    def walk(jaxpr):
        for eqn in jaxpr.eqns:
            if eqn.primitive.name == "pallas_call":
                out.append("pallas_call")
                continue
            out.append(eqn.primitive.name)
            for v in eqn.params.values():
                for q in jax.tree_util.tree_leaves(
                        v, is_leaf=lambda x: hasattr(x, "jaxpr")):
                    if hasattr(q, "jaxpr"):
                        walk(q.jaxpr)
    walk(closed.jaxpr)
    return out


def test_jaxpr_one_value_kernel_no_symbolic_prims():
    """THE acceptance proof (kernel route): a planned level's RAP
    numerics are exactly ONE fused value kernel, with zero standalone
    sort/argsort/gather/scatter/segment-sum prims outside it — where
    the eager formulation dispatches the whole sort/gather/segment
    chain."""
    lv = _classical_level(dtype=jnp.float32)
    plan = spgemm.build_rap_plan(lv.R, lv.A, lv.P)
    with force_pallas_interpret():
        assert pk.rap_kernel_ready(plan, jnp.float32)
        jaxpr = jax.make_jaxpr(
            lambda af: pk.rap_value_call(plan, af, lv.R.values,
                                         lv.P.values))(
            jnp.asarray(lv.A.values))
    prims = _outer_prims(jaxpr)
    assert prims.count("pallas_call") == 1, prims
    banned = {"sort", "gather", "scatter", "scatter-add", "argsort",
              "segment_sum", "cumsum"}
    hit = [p for p in prims if p in banned]
    assert not hit, hit


def test_jaxpr_slab_route_no_sort_prims():
    """THE acceptance proof (CPU slab route): zero sort / argsort /
    unique primitives — gathers and sorted segment-sums through static
    indices only."""
    lv = _classical_level()
    plan = spgemm.build_rap_plan(lv.R, lv.A, lv.P)
    d = plan.dev()
    jaxpr = jax.make_jaxpr(
        lambda af: spgemm._rap_values_slab(
            af, jnp.asarray(lv.R.values), jnp.asarray(lv.P.values),
            d["sa"], d["sp"], d["seg1"], d["sr"], d["st"], d["seg2"],
            plan.stage1["nT"], plan.nU, True, True))(
        jnp.asarray(lv.A.values))
    prims = set(_outer_prims(jaxpr))
    assert not prims & {"sort", "approx_top_k"}, prims


def test_geo_value_phase_no_symbolic_prims():
    """The planned GEO numeric phase (one jitted program) contains no
    sort primitives — reshape pair-sums + one entry gather + the DIA
    pack only."""
    from amgx_tpu.amg.aggregation.galerkin import get_geo_plan
    A = gallery.poisson("7pt", 8, 8, 8, dtype=jnp.float32).init()
    amg = AMG(Config.from_string(
        "algorithm=AGGREGATION, selector=GEO, min_coarse_rows=8,"
        " max_levels=4")).setup(A)
    lv = amg.levels[0]
    plan = get_geo_plan(lv.A, lv.geo_fine_shape, lv.geo_axes,
                        lv.geo_coarse_shape)
    assert plan is not None
    vals = lv.A.dia_vals.reshape(len(lv.A.dia_offsets), -1)[
        :, : lv.A.num_rows]
    jaxpr = jax.make_jaxpr(lambda v: plan.values(v))(vals)
    prims = set(_outer_prims(jaxpr))
    assert not prims & {"sort", "approx_top_k", "scatter-add"}, prims


# ---------------------------------------------------------------------------
# hierarchy wiring: planned levels, carryover, refresh, escape hatch
# ---------------------------------------------------------------------------


def test_geo_hierarchy_planned_equals_eager():
    """Planned GEO hierarchy == spgemm_plan=0 hierarchy, bitwise (both
    run the same _geo_compute math; the planned route only skips the
    symbolic re-derivation)."""
    from amgx_tpu.presets import FLAGSHIP
    A = gallery.poisson("7pt", 16, 16, 16).init()
    s1 = amgx.create_solver(Config.from_string(FLAGSHIP))
    s1.setup(A)
    s0 = amgx.create_solver(Config.from_string(
        FLAGSHIP + ", amg:spgemm_plan=0"))
    s0.setup(A)
    a1, a0 = _amg_of(s1), _amg_of(s0)
    assert len(a1.levels) == len(a0.levels)
    for i in range(1, len(a1.levels)):
        assert np.array_equal(np.asarray(a1.levels[i].A.values),
                              np.asarray(a0.levels[i].A.values))
        assert np.array_equal(np.asarray(a1.levels[i].A.dia_vals),
                              np.asarray(a0.levels[i].A.dia_vals))


def test_classical_hierarchy_planned_parity_and_solve():
    """Planned classical hierarchy operators match the eager build to
    f64 roundoff and the solve converges identically."""
    n = 10
    A = gallery.poisson("7pt", n, n, n).init()
    b = jnp.ones(A.num_rows)
    s1 = amgx.create_solver(Config.from_string(_PCG_CLASSICAL))
    s1.setup(A)
    r1 = s1.solve(b)
    s0 = amgx.create_solver(Config.from_string(
        _PCG_CLASSICAL + ", amg:spgemm_plan=0"))
    s0.setup(A)
    r0 = s0.solve(b)
    assert bool(r1.converged) and bool(r0.converged)
    assert int(r1.iterations) == int(r0.iterations)
    a1, a0 = _amg_of(s1), _amg_of(s0)
    for i in range(1, len(a1.levels)):
        assert _rel(a1.levels[i].A.values,
                    a0.levels[i].A.values) < 1e-12


def test_warm_setup_hits_plan_cache():
    """SATELLITE FIX: a warm setup of a known pattern — fresh level
    objects, default (host) backend — routes RAP through the plan
    value phase: plan-cache hits, ZERO plan builds, and the native
    numpy RAP is never consulted."""
    from amgx_tpu import native
    A = gallery.poisson("7pt", 10, 10, 10).init()
    s1 = amgx.create_solver(Config.from_string(_PCG_CLASSICAL))
    s1.setup(A)                              # cold: builds the plans
    b0 = int(_tm.get("amg.spgemm.plan_build"))
    h0 = int(_tm.get("amg.spgemm.plan_hit"))
    real = native.rap_native

    def _banned(*a, **kw):                   # pragma: no cover
        raise AssertionError("warm setup fell back to host-numpy RAP")
    native.rap_native = _banned
    try:
        s2 = amgx.create_solver(Config.from_string(_PCG_CLASSICAL))
        s2.setup(A)
    finally:
        native.rap_native = real
    assert int(_tm.get("amg.spgemm.plan_build")) == b0
    assert int(_tm.get("amg.spgemm.plan_hit")) > h0


def test_structure_resetup_plan_carryover():
    """A structure resetup (kept P/R/cf-split, new coefficients) rides
    the level-memoized plan: zero plan builds AND zero digest lookups
    (the memo compares object identity, not hashes)."""
    A = gallery.poisson("7pt", 10, 10, 10).init()
    slv = amgx.create_solver(Config.from_string(_PCG_CLASSICAL))
    slv.setup(A)
    b0 = int(_tm.get("amg.spgemm.plan_build"))
    h0 = int(_tm.get("amg.spgemm.plan_hit"))
    slv.resetup(_scaled(A, 1.5))
    assert int(_tm.get("amg.spgemm.plan_build")) == b0
    assert int(_tm.get("amg.spgemm.plan_hit")) == h0
    # and the resetup numerics match a from-scratch setup of 1.5*A
    ref = amgx.create_solver(Config.from_string(_PCG_CLASSICAL))
    ref.setup(_scaled(A, 1.5).init())
    a1, a2 = _amg_of(slv), _amg_of(ref)
    for i in range(1, len(a1.levels)):
        assert _rel(a1.levels[i].A.values,
                    a2.levels[i].A.values) < 1e-12


def test_resetup_pattern_change_never_serves_stale_plan():
    """REVIEW REGRESSION: a structure resetup whose new A has the same
    size and nnz but a DIFFERENT pattern (a symmetric permutation) must
    not be served the old plan through the level memo — the memo
    proves the pattern by structure-array identity, and the digest
    cache keys on content, so the rebuilt coarse operators match a
    from-scratch setup of the permuted matrix."""
    from amgx_tpu.matrix import CsrMatrix
    n = 10
    A = gallery.poisson("7pt", n, n, n).init()
    slv = amgx.create_solver(Config.from_string(_PCG_CLASSICAL))
    slv.setup(A)
    rng = np.random.default_rng(7)
    perm = rng.permutation(A.num_rows)
    rows, cols, vals = (np.asarray(x) for x in A.coo())
    Ap = CsrMatrix.from_coo(perm[rows], perm[cols], jnp.asarray(vals),
                            A.num_rows, A.num_cols).init()
    assert Ap.nnz == A.nnz
    # the eager twin runs the IDENTICAL setup+resetup sequence
    # (structure reuse keeps the old coarsening in both — the contract
    # under test is that the planned RAP sees the NEW pattern's
    # values, not the old plan's gather indices)
    ref = amgx.create_solver(Config.from_string(
        _PCG_CLASSICAL + ", amg:spgemm_plan=0"))
    ref.setup(A)
    slv.resetup(Ap)
    ref.resetup(Ap)
    b = jnp.ones(A.num_rows)
    res = slv.solve(b)
    res0 = ref.solve(b)
    rel = float(np.linalg.norm(np.asarray(
        amgx.ops.residual(Ap, res.x, b)))
        / np.linalg.norm(np.asarray(b)))
    rel0 = float(np.linalg.norm(np.asarray(
        amgx.ops.residual(Ap, res0.x, b)))
        / np.linalg.norm(np.asarray(b)))
    assert rel < max(10 * rel0, 1e-7), (rel, rel0)
    a1, a2 = _amg_of(slv), _amg_of(ref)
    for i in range(1, len(a1.levels)):
        assert _rel(a1.levels[i].A.values,
                    a2.levels[i].A.values) < 1e-12


def test_value_resetup_plan_refresh():
    """GEO flagship shape: the fused value-only resetup consumes the
    level's memoized GeoRapPlan — no symbolic rebuild — and refreshes
    every coarse operator to the full-rebuild values."""
    from amgx_tpu.presets import FLAGSHIP
    A = gallery.poisson("7pt", 16, 16, 16).init()
    slv = amgx.create_solver(Config.from_string(
        FLAGSHIP + ", amg:structure_reuse_levels=-1"))
    slv.setup(A)
    amg = _amg_of(slv)
    assert getattr(amg.levels[0], "_geo_plan_memo", None) is not None
    b0 = int(_tm.get("amg.spgemm.plan_build"))
    slv.resetup(_scaled(A, 2.0))
    assert amg._last_resetup_value_only
    assert int(_tm.get("amg.spgemm.plan_build")) == b0
    ref = amgx.create_solver(Config.from_string(FLAGSHIP))
    ref.setup(_scaled(A, 2.0).init())
    a2 = _amg_of(ref)
    for i in range(1, len(amg.levels)):
        assert _rel(amg.levels[i].A.dia_vals,
                    a2.levels[i].A.dia_vals) < 1e-6


def test_spgemm_plan_0_is_eager_bit_for_bit():
    """THE escape hatch: spgemm_plan=0 never touches the plan
    machinery (entry points monkeypatched to raise) and reproduces the
    planned build's answer exactly on the GEO shape (same jitted
    pieces), eager classical to f64 roundoff."""
    from amgx_tpu.presets import FLAGSHIP
    from amgx_tpu.amg.aggregation import galerkin as G
    A = gallery.poisson("7pt", 12, 12, 12).init()

    def _banned(*a, **kw):                   # pragma: no cover
        raise AssertionError("spgemm_plan=0 invoked plan machinery")
    saved = (spgemm.get_rap_plan, spgemm.get_agg_plan, G.get_geo_plan)
    spgemm.get_rap_plan = _banned
    spgemm.get_agg_plan = _banned
    G.get_geo_plan = _banned
    try:
        s0 = amgx.create_solver(Config.from_string(
            FLAGSHIP + ", amg:spgemm_plan=0"))
        s0.setup(A)
        c0 = amgx.create_solver(Config.from_string(
            _PCG_CLASSICAL + ", amg:spgemm_plan=0"))
        c0.setup(A)
    finally:
        (spgemm.get_rap_plan, spgemm.get_agg_plan,
         G.get_geo_plan) = saved
    s1 = amgx.create_solver(Config.from_string(FLAGSHIP))
    s1.setup(A)
    a0, a1 = _amg_of(s0), _amg_of(s1)
    for i in range(1, len(a1.levels)):
        assert np.array_equal(np.asarray(a0.levels[i].A.values),
                              np.asarray(a1.levels[i].A.values))


def test_bench_spgemm_smoke():
    """The bench phase's functional smoke: paired plan-vs-eager warm
    setups produce finite speedups and the artifact scalars."""
    import bench
    res = bench.bench_spgemm_plan(flagship_n=16, classical_n=8,
                                  reps=1)
    assert res["spgemm_plan_speedup"] > 0
    assert res["spgemm_plan_speedup_classical"] > 0
    for v in res.values():
        if isinstance(v, dict):
            assert v["plan_warm_setup_s"] > 0
            assert v["eager_warm_setup_s"] > 0


def test_plan_counters_declared():
    """Catalog presence: the plan counters exist and the span lint
    (which covers amg.L*.rap_plan / rap_values) runs clean — covered
    in depth by test_telemetry's check_spans test; this guards the
    counter names."""
    _tm.get("amg.spgemm.plan_build")
    _tm.get("amg.spgemm.plan_hit")
