"""Serving subsystem tests (amgx_tpu/serving/): chunked-solve parity,
continuous-batching parity vs one-shot solve_many, slot refill without
retrace, per-tenant deadlines (expiry -> DEADLINE_EXCEEDED, never a
hung bucket), hierarchy-cache routing to value-resetup, bytes-budgeted
eviction, AOT round-trip with zero retraces, batcher fairness/LRU
satellites, the capi + bench surfaces — and the fault-tolerance layer:
journaled crash recovery with bit-identical checkpoint resume,
persisted hierarchy structures (restart without a full setup), the
scheduler lock split (submit never waits on device work), OVERLOADED
load shedding, and the service-level chaos scenarios (builder crash,
device-step exception, wedged bucket, store corruption, clock skew —
every one must end all-tickets-terminal). No reference analog — AMGX
is consumed AS a service library; the service loop itself is new."""
import os
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import amgx_tpu as amgx
from amgx_tpu import gallery
from amgx_tpu.batch import BatchedSolver, RequestBatcher
from amgx_tpu.batch.queue import pattern_fingerprint
from amgx_tpu.config import Config
from amgx_tpu.presets import BATCHED_CG, SERVING_CG
from amgx_tpu.resilience import faultinject
from amgx_tpu.resilience.policy import parse_fallback_policy
from amgx_tpu.resilience.status import (SolveStatus, status_string,
                                        to_amgx_status)
from amgx_tpu.serving import (BucketEngine, HierarchyCache,
                              SolveService, solve_data_bytes)
from amgx_tpu.solvers.base import Solver
from amgx_tpu.telemetry import metrics

amgx.initialize()


@pytest.fixture(scope="module")
def poisson16():
    return gallery.poisson("5pt", 16, 16).init()


@pytest.fixture(scope="module")
def geo10():
    return gallery.poisson("7pt", 10, 10, 10).init()


def _shift(A, c):
    vals = np.asarray(A.values).copy()
    vals[np.asarray(A.diag_idx)] += c
    return A.with_values(vals)


def _rhs(A, seed=0):
    return np.random.default_rng(seed).standard_normal(A.num_rows)


def _svc_cfg(base=BATCHED_CG, extra=""):
    return Config.from_string(
        base + ", serving_bucket_slots=2, serving_chunk_iters=4"
        + (", " + extra if extra else ""))


def _key(A, b):
    return f"{pattern_fingerprint(A)}/{np.asarray(b).dtype}"


# ---------------------------------------------------------------------------
# chunked solve entry
# ---------------------------------------------------------------------------


def test_chunk_fns_match_one_shot_solve(poisson16):
    """Stepping the chunked entry to completion reproduces solve()
    exactly: same iterates, same packed stats, bit-identical x."""
    slv = amgx.create_solver(Config.from_string(BATCHED_CG))
    slv.setup(poisson16)
    b = _rhs(poisson16, 1)
    ref = slv.solve(b)
    init, step, fin = slv._build_chunk_fns(3)
    data = slv.solve_data()
    bj = jnp.asarray(b)
    st = jax.jit(init)(data, bj, jnp.zeros_like(bj))
    jstep = jax.jit(step)
    for _ in range(100):
        st = jstep(data, bj, st)
        if bool(st["done"]) or int(st["iters"]) >= slv.max_iters:
            break
    x, stats = jax.jit(fin)(data, bj, st)
    it, cv, sc, n0, rn, hist = Solver.unpack_stats(
        stats, slv.max_iters + 1)
    assert it == ref.iterations and cv == ref.converged
    assert sc == ref.status_code
    np.testing.assert_array_equal(np.asarray(x), np.asarray(ref.x))
    np.testing.assert_allclose(rn, ref.res_norm, rtol=1e-12)


def test_chunk_window_is_per_system_relative(poisson16):
    """A chunk advances at most `chunk` iterations from the ENTRY
    count, whatever iteration the system resumed at."""
    slv = amgx.create_solver(Config.from_string(BATCHED_CG))
    slv.setup(poisson16)
    b = jnp.asarray(_rhs(poisson16, 2))
    init, step, _fin = slv._build_chunk_fns(5)
    st = jax.jit(init)(slv.solve_data(), b, jnp.zeros_like(b))
    st = jax.jit(step)(slv.solve_data(), b, st)
    assert int(st["iters"]) == 5
    st = jax.jit(step)(slv.solve_data(), b, st)
    assert int(st["iters"]) == 10


# ---------------------------------------------------------------------------
# continuous batching parity + refill
# ---------------------------------------------------------------------------


def test_service_parity_vs_one_shot_solve_many(poisson16):
    """Continuous batching delivers the same per-system iterates as a
    one-shot batched solve_many over the same systems (same hierarchy
    structure, same while_loop body — only the chunking differs)."""
    mats = [_shift(poisson16, 0.3 * i) for i in range(4)]
    bs_rhs = np.stack([_rhs(poisson16, i) for i in range(4)])
    svc = SolveService(_svc_cfg())
    tickets = [svc.submit(m, b) for m, b in zip(mats, bs_rhs)]
    svc.drain(timeout_s=300)
    one = BatchedSolver(Config.from_string(BATCHED_CG))
    one.setup(mats[0])
    ref = one.solve_many(bs_rhs, matrices=mats)
    assert ref.all_converged
    for i, t in enumerate(tickets):
        assert t.done and t.result.converged
        assert t.result.iterations == int(ref.iterations[i])
        np.testing.assert_allclose(np.asarray(t.result.x),
                                   np.asarray(ref.x[i]),
                                   rtol=1e-12, atol=1e-12)


def test_slot_refill_without_retrace(poisson16):
    """5 systems through a 2-slot bucket: drained slots are refilled
    mid-flight and the engine's three functions trace exactly once."""
    mats = [_shift(poisson16, 0.2 * i) for i in range(5)]
    base = metrics.get("serving.retrace")
    svc = SolveService(_svc_cfg())
    tickets = [svc.submit(m, _rhs(m, i)) for i, m in enumerate(mats)]
    svc.drain(timeout_s=300)
    assert all(t.result.converged for t in tickets)
    assert len(svc.buckets) == 1
    eng = svc.buckets.peek(tickets[0].fingerprint)
    assert eng.slots == 2 and eng.idle
    assert eng.trace_count == 3          # init1 / step / finish, once
    assert metrics.get("serving.retrace") - base == 3


def test_background_build_failure_rejects_tickets(poisson16):
    """A bucket build that raises on a builder thread rejects the
    queued tickets (BREAKDOWN + .error) instead of retrying forever
    or killing the scheduler."""
    cfg = _svc_cfg(extra="scaling=DIAGONAL_SYMMETRIC")  # engine refuses
    svc = SolveService(cfg)
    svc.start()
    try:
        t = svc.submit(poisson16, _rhs(poisson16, 20))
        assert t.wait(timeout=300)
        assert t.result.status_code == int(SolveStatus.BREAKDOWN)
        assert t.error is not None and "scaling" in str(t.error)
        assert svc.idle
    finally:
        svc.stop()


def test_sync_build_failure_rejects_tickets(poisson16):
    """The inline (no background thread) build-failure path matches
    the threaded one: tickets complete with BREAKDOWN, step() never
    raises, the queue never wedges."""
    svc = SolveService(_svc_cfg(extra="scaling=DIAGONAL_SYMMETRIC"))
    t = svc.submit(poisson16, _rhs(poisson16, 21))
    done = svc.step()                  # build fails inside this cycle
    assert t in done and t.done
    assert t.result.status_code == int(SolveStatus.BREAKDOWN)
    assert t.error is not None
    assert svc.idle and svc.step() == []


def test_submit_validates_rhs_length(poisson16):
    with pytest.raises(Exception, match="rhs length"):
        SolveService(_svc_cfg()).submit(poisson16, np.ones(7))


def test_service_background_thread(poisson16):
    """The async mode: submit from the caller thread, the scheduler
    thread completes the ticket."""
    svc = SolveService(_svc_cfg())
    svc.start()
    try:
        t = svc.submit(poisson16, _rhs(poisson16, 3))
        assert t.wait(timeout=300) and t.result.converged
        assert t.latency_s > 0
    finally:
        svc.stop()


# ---------------------------------------------------------------------------
# deadlines + admission control
# ---------------------------------------------------------------------------


def test_deadline_inflight_partial_never_hangs(poisson16):
    """Mid-flight expiry completes the ticket with DEADLINE_EXCEEDED
    and the current (partial) iterate; the bucket keeps serving."""
    cfg = _svc_cfg(extra="serving_chunk_iters=1, s:tolerance=1e-14")
    svc = SolveService(cfg)
    b = _rhs(poisson16, 4)
    miss0 = metrics.get("serving.deadline_miss")
    t = svc.submit(poisson16, b, tenant="late", deadline_s=1e9)
    svc.step()                       # admitted + one cycle
    assert not t.done
    t.deadline_t = 0.0               # force expiry at the next boundary
    svc.step()
    assert t.done
    assert t.result.status_code == int(SolveStatus.DEADLINE_EXCEEDED)
    assert t.result.status == "deadline_exceeded"
    assert not t.result.converged
    assert float(np.linalg.norm(np.asarray(t.result.x))) > 0  # partial
    assert metrics.get("serving.deadline_miss") - miss0 == 1
    assert svc.stats()["tenants"]["late"]["deadline_miss"] == 1
    # the bucket is not hung: the next request completes normally
    t2 = svc.submit(poisson16, b)
    svc.drain(timeout_s=300)
    assert t2.result.converged


def test_deadline_queued_expiry_rejects(poisson16):
    """A request that expires while still queued never touches a slot:
    it completes with DEADLINE_EXCEEDED and the initial iterate."""
    svc = SolveService(_svc_cfg())
    t = svc.submit(poisson16, _rhs(poisson16, 5), deadline_s=0.0)
    svc.step()
    assert t.done and t.result.iterations == 0
    assert t.result.status_code == int(SolveStatus.DEADLINE_EXCEEDED)
    assert float(np.linalg.norm(np.asarray(t.result.x))) == 0


def test_deadline_action_reject_returns_initial_iterate(poisson16):
    """serving_deadline_action=reject: an expired in-flight request
    completes with the initial iterate, not the partial one."""
    cfg = _svc_cfg(extra="serving_deadline_action=reject, "
                         "serving_chunk_iters=1, s:tolerance=1e-14")
    svc = SolveService(cfg)
    t = svc.submit(poisson16, _rhs(poisson16, 6), deadline_s=1e9)
    svc.step()
    t.deadline_t = 0.0
    svc.step()
    assert t.done
    assert t.result.status_code == int(SolveStatus.DEADLINE_EXCEEDED)
    assert float(np.linalg.norm(np.asarray(t.result.x))) == 0


def test_admission_control_queue_bound(poisson16):
    """serving_max_queue: over-budget submits complete immediately
    with OVERLOADED (the honest shed class — DEADLINE_EXCEEDED is
    reserved for admitted work that ran out of time) instead of
    growing the queue."""
    svc = SolveService(_svc_cfg(extra="serving_max_queue=1"))
    rej0 = metrics.get("serving.rejected")
    ovl0 = metrics.get("serving.shed.overload")
    t1 = svc.submit(poisson16, _rhs(poisson16, 7))
    t2 = svc.submit(poisson16, _rhs(poisson16, 8))
    assert not t1.done
    assert t2.done and t2.result.status_code == \
        int(SolveStatus.OVERLOADED)
    assert t2.result.status == "overloaded"
    assert metrics.get("serving.rejected") - rej0 == 1
    assert metrics.get("serving.shed.overload") - ovl0 == 1
    svc.drain(timeout_s=300)
    assert t1.result.converged


def test_deadline_status_in_fallback_grammar():
    """The new status plugs into the existing policy grammar (with the
    DEADLINE alias) and the capi status mapping."""
    pol = parse_fallback_policy("DEADLINE_EXCEEDED>retry")
    assert pol == {int(SolveStatus.DEADLINE_EXCEEDED):
                   [("retry", "")]}
    assert parse_fallback_policy("DEADLINE>retry") == pol
    assert status_string(SolveStatus.DEADLINE_EXCEEDED) == \
        "deadline_exceeded"
    assert to_amgx_status(SolveStatus.DEADLINE_EXCEEDED) == 3


# ---------------------------------------------------------------------------
# hierarchy cache
# ---------------------------------------------------------------------------


def test_cache_hit_routes_to_value_resetup(geo10):
    """The setup-routing proof: after the bucket exists, every
    repeat-pattern admit goes through the fused value-resetup (the
    0.43 s path) — the full-setup counter stays flat."""
    svc = SolveService(_svc_cfg(base=SERVING_CG))
    base = metrics.snapshot()
    t0 = svc.submit(geo10, _rhs(geo10, 0))
    svc.drain(timeout_s=300)
    mid = metrics.snapshot()
    assert mid["amg.setup.full"] - base["amg.setup.full"] == 1
    assert mid["serving.cache.miss"] - base["serving.cache.miss"] == 1
    # repeat-pattern, different-values traffic: hits + value-resetups
    tickets = [svc.submit(_shift(geo10, 0.2 * i), _rhs(geo10, i))
               for i in range(1, 4)]
    svc.drain(timeout_s=300)
    cur = metrics.snapshot()
    assert all(t.result.converged for t in tickets + [t0])
    assert cur["amg.setup.full"] == mid["amg.setup.full"]
    assert cur["amg.resetup.value"] - mid["amg.resetup.value"] >= 3
    assert cur["serving.cache.hit"] > mid["serving.cache.hit"]


def test_cache_eviction_by_bytes(poisson16):
    """A 1-byte budget keeps at most one idle bucket live: the second
    pattern evicts the first, with eviction counters + gauges."""
    ev0 = metrics.get("serving.cache.evictions")
    svc = SolveService(_svc_cfg(extra="serving_cache_bytes=1"))
    other = gallery.poisson("5pt", 12, 12).init()
    svc.submit(poisson16, _rhs(poisson16, 9))
    svc.drain(timeout_s=300)
    svc.submit(other, _rhs(other, 10))
    svc.drain(timeout_s=300)
    assert len(svc.buckets) == 1
    assert svc.buckets.evictions >= 1
    assert metrics.get("serving.cache.evictions") - ev0 >= 1
    assert metrics.get("serving.live_buckets") == 1


def test_cache_never_evicts_busy_or_newest_bucket():
    """Eviction skips buckets with in-flight slots AND the most
    recently used entry (a just-built oversized bucket must survive
    its own insertion); draining the busy one makes it evictable."""
    class E:
        def __init__(self, idle):
            self.idle = idle

    cache = HierarchyCache(budget_bytes=10, counters={},
                           can_evict=lambda e: e.idle)
    busy, idle = E(False), E(True)
    cache.put("busy", busy, nbytes=100)
    assert "busy" in cache            # newest: survives its own insert
    cache.put("idle", idle, nbytes=100)
    assert "busy" in cache and "idle" in cache   # over budget, all held
    busy.idle = True
    cache.evict_to_budget()           # now the oldest is evictable
    assert "busy" not in cache and "idle" in cache
    assert cache.evictions == 1


def test_solve_data_bytes_counts_unique_leaves(poisson16):
    slv = amgx.create_solver(Config.from_string(BATCHED_CG))
    slv.setup(poisson16)
    nb = solve_data_bytes(slv)
    # at least the fine matrix values must be accounted
    assert nb >= np.asarray(poisson16.values).nbytes
    # shared leaves count once
    leaf = jnp.ones(1000)
    assert solve_data_bytes([leaf, leaf]) == leaf.nbytes


# ---------------------------------------------------------------------------
# AOT warm paths
# ---------------------------------------------------------------------------


def test_aot_round_trip_zero_retrace(poisson16, tmp_path):
    """A fresh service against a warmed AOT store solves without a
    single engine trace (the restart story), with identical results."""
    cfg = _svc_cfg(extra=f"serving_aot_dir={tmp_path}")
    b = _rhs(poisson16, 11)
    exp0 = metrics.get("serving.aot.export")
    err0 = metrics.get("serving.aot.error")
    svc1 = SolveService(cfg)
    t1 = svc1.submit(poisson16, b)
    svc1.drain(timeout_s=300)
    assert metrics.get("serving.aot.export") - exp0 == 1
    assert metrics.get("serving.aot.error") - err0 == 0

    retr0 = metrics.get("serving.retrace")
    load0 = metrics.get("serving.aot.load")
    svc2 = SolveService(cfg)           # the "restarted process"
    t2 = svc2.submit(poisson16, b)
    svc2.drain(timeout_s=300)
    assert metrics.get("serving.retrace") - retr0 == 0
    assert metrics.get("serving.aot.load") - load0 == 1
    eng = svc2.buckets.peek(t2.fingerprint)
    assert eng.aot_warm and eng.trace_count == 0
    assert t2.result.iterations == t1.result.iterations
    np.testing.assert_array_equal(np.asarray(t2.result.x),
                                  np.asarray(t1.result.x))


# ---------------------------------------------------------------------------
# batcher satellites
# ---------------------------------------------------------------------------


def test_batcher_dispatches_oldest_first(poisson16):
    """drain() orders buckets by earliest pending submit, not by
    pending-map insertion: the longest-waiting request's bucket goes
    first even when a hot fingerprint entered the map before it."""
    rb = RequestBatcher(Config.from_string(BATCHED_CG), max_buckets=4)
    cold_A = gallery.poisson("5pt", 12, 12).init()
    hot = [rb.submit(poisson16, _rhs(poisson16, i)) for i in range(3)]
    cold = rb.submit(cold_A, _rhs(cold_A, 3))
    # simulate the cold request having waited longest
    cold.submit_t = hot[0].submit_t - 1.0
    rb.drain()
    assert all(r.done for r in hot + [cold])
    assert rb.dispatch_log[0][0] == cold.fingerprint
    assert rb.dispatch_log[1][0] == hot[0].fingerprint


def test_batcher_bytes_lru_bound(poisson16):
    """max_bucket_bytes bounds the solver store; evictions surface
    through the telemetry counter and the live_buckets property."""
    ev0 = metrics.get("batch.bucket_evictions")
    rb = RequestBatcher(Config.from_string(BATCHED_CG),
                        max_buckets=8, max_bucket_bytes=1)
    other = gallery.poisson("5pt", 12, 12).init()
    rb.submit(poisson16, _rhs(poisson16, 0))
    rb.drain()
    assert rb.live_buckets == 1
    rb.submit(other, _rhs(other, 1))
    rb.drain()
    assert rb.live_buckets == 1          # first bucket evicted
    assert rb.bucket_evictions >= 1
    assert metrics.get("batch.bucket_evictions") - ev0 >= 1
    assert metrics.get("batch.live_buckets") == 1


# ---------------------------------------------------------------------------
# capi surface
# ---------------------------------------------------------------------------


def test_capi_service_roundtrip(poisson16):
    from amgx_tpu import capi
    assert capi.AMGX_initialize() == 0
    rc, cfg_h = capi.AMGX_config_create(
        BATCHED_CG + ", serving_bucket_slots=2")
    assert rc == 0
    rc, rsrc_h = capi.AMGX_resources_create_simple(cfg_h)
    assert rc == 0
    rc, svc_h = capi.AMGX_service_create(rsrc_h, "dDDI", cfg_h)
    assert rc == 0
    rc, m_h = capi.AMGX_matrix_create(rsrc_h, "dDDI")
    rc, b_h = capi.AMGX_vector_create(rsrc_h, "dDDI")
    rc, x_h = capi.AMGX_vector_create(rsrc_h, "dDDI")
    ro = np.asarray(poisson16.row_offsets)
    ci = np.asarray(poisson16.col_indices)
    v = np.asarray(poisson16.values)
    assert capi.AMGX_matrix_upload_all(
        m_h, poisson16.num_rows, v.size, 1, 1, ro, ci, v, None) == 0
    b = _rhs(poisson16, 12)
    assert capi.AMGX_vector_upload(b_h, b.size, 1, b) == 0
    rc, tkt = capi.AMGX_service_submit(svc_h, m_h, b_h, "acme", None)
    assert rc == 0
    rc, done, st = capi.AMGX_service_ticket_status(tkt)
    assert rc == 0 and done == 0 and st is None
    rc, n_done = capi.AMGX_service_drain(svc_h, 300)
    assert rc == 0 and n_done == 1
    rc, done, st = capi.AMGX_service_ticket_status(tkt)
    assert rc == 0 and done == 1 and st == 0      # AMGX_SOLVE_SUCCESS
    assert capi.AMGX_service_ticket_download(tkt, x_h) == 0
    rc, x = capi.AMGX_vector_download(x_h)
    assert rc == 0 and x.shape == (poisson16.num_rows,)
    rc, stats = capi.AMGX_service_stats(svc_h)
    assert rc == 0 and stats["tenants"]["acme"]["completed"] == 1
    assert capi.AMGX_service_ticket_destroy(tkt) == 0
    assert capi.AMGX_service_destroy(svc_h) == 0


# ---------------------------------------------------------------------------
# fault tolerance: journal, checkpoints, crash recovery
# ---------------------------------------------------------------------------


def test_checkpoint_restart_resumes_bit_identical(poisson16, tmp_path):
    """THE recovery acceptance: a service killed mid-flight is
    replaced by a successor that replays the journal and resumes the
    checkpointed solve — reaching a final iterate BIT-IDENTICAL to an
    uninterrupted run, at the same iteration count."""
    b = _rhs(poisson16, 30)
    kr = (f"serving_journal_dir={tmp_path}, serving_checkpoint_cycles=1,"
          " serving_chunk_iters=1, s:tolerance=1e-12")
    ref = SolveService(_svc_cfg(
        extra="serving_chunk_iters=1, s:tolerance=1e-12"))
    rt = ref.submit(poisson16, b)
    ref.drain(timeout_s=300)
    victim = SolveService(_svc_cfg(extra=kr))
    vt = victim.submit(poisson16, b, tenant="acme", deadline_s=1e6,
                       request_key="kr-0")
    for _ in range(4):               # build + a few cycles, then die
        victim.step()
    assert not vt.done               # genuinely mid-flight
    del victim
    rep0 = metrics.get("serving.recovery.replayed")
    res0 = metrics.get("serving.recovery.resumed")
    succ = SolveService(_svc_cfg(extra=kr))   # journal replays here
    assert metrics.get("serving.recovery.replayed") - rep0 == 1
    done = succ.drain(timeout_s=300)
    assert len(done) == 1 and done[0].done
    assert metrics.get("serving.recovery.resumed") - res0 == 1
    assert done[0].result.iterations == rt.result.iterations
    np.testing.assert_array_equal(np.asarray(done[0].result.x),
                                  np.asarray(rt.result.x))
    # deadline survived the restart (remaining budget re-anchored)
    assert done[0].result.converged
    assert succ.stats()["journal_pending"] == 0


def test_submit_request_key_idempotent(poisson16, tmp_path):
    """The idempotency satellite: a retried submit with the same
    request_key returns the LIVE ticket while in flight, and after
    completion (even across a restart) a fresh ticket completed from
    the journaled result — never a second enqueue."""
    b = _rhs(poisson16, 31)
    cfg = _svc_cfg(extra=f"serving_journal_dir={tmp_path}")
    svc = SolveService(cfg)
    ded0 = metrics.get("serving.dedupe")
    t1 = svc.submit(poisson16, b, request_key="abc")
    t2 = svc.submit(poisson16, b, request_key="abc")
    assert t2 is t1                  # live dedupe: the same ticket
    assert metrics.get("serving.dedupe") - ded0 == 1
    svc.drain(timeout_s=300)
    assert t1.result.converged
    # across a "restart": the journaled result answers the retry
    svc2 = SolveService(cfg)
    t3 = svc2.submit(poisson16, b, request_key="abc")
    assert t3.done and t3 is not t1
    assert metrics.get("serving.dedupe") - ded0 == 2
    np.testing.assert_array_equal(np.asarray(t3.result.x),
                                  np.asarray(t1.result.x))
    assert svc2.idle                 # nothing was enqueued


def test_journal_corrupt_record_dropped_not_wedged(poisson16, tmp_path):
    """A torn-write-corrupted journal record is dropped (and counted)
    at replay; the records around it still recover — corruption can
    cost one request's durability, never the service."""
    cfg = _svc_cfg(extra=f"serving_journal_dir={tmp_path},"
                         " serving_chunk_iters=1, s:tolerance=1e-12")
    svc = SolveService(cfg)
    svc.submit(poisson16, _rhs(poisson16, 32))        # clean pattern
    with faultinject.inject("journal_corrupt", fires=1):
        svc.submit(poisson16, _rhs(poisson16, 33))    # corrupt record
    svc.submit(poisson16, _rhs(poisson16, 34))        # clean record
    del svc
    jc0 = metrics.get("serving.recovery.journal_corrupt")
    rep0 = metrics.get("serving.recovery.replayed")
    succ = SolveService(cfg)
    assert metrics.get("serving.recovery.journal_corrupt") - jc0 == 1
    assert metrics.get("serving.recovery.replayed") - rep0 == 2
    done = succ.drain(timeout_s=300)
    assert len(done) == 2 and all(t.result.converged for t in done)
    assert succ.idle


def test_hierarchy_store_restart_zero_full_setups(geo10, tmp_path):
    """The persistent-hierarchy acceptance: a restarted service with a
    warm hierarchy store + AOT store services its first request via
    snapshot load + structure-reuse rebuild + AOT executables — ZERO
    full AMG setups, ZERO engine retraces, identical results."""
    cfg = _svc_cfg(base=SERVING_CG,
                   extra=f"serving_hierarchy_dir={tmp_path}/h,"
                         f" serving_aot_dir={tmp_path}/a")
    b = _rhs(geo10, 35)
    hs0 = metrics.get("serving.recovery.hstore_save")
    svc1 = SolveService(cfg)
    t1 = svc1.submit(geo10, b)
    svc1.drain(timeout_s=300)
    assert metrics.get("serving.recovery.hstore_save") - hs0 == 1
    full0 = metrics.get("amg.setup.full")
    rest0 = metrics.get("amg.setup.restored")
    retr0 = metrics.get("serving.retrace")
    svc2 = SolveService(cfg)           # the "restarted process"
    t2 = svc2.submit(geo10, b)
    svc2.drain(timeout_s=300)
    assert metrics.get("amg.setup.full") - full0 == 0
    assert metrics.get("amg.setup.restored") - rest0 == 1
    assert metrics.get("serving.retrace") - retr0 == 0
    eng = svc2.buckets.peek(t2.fingerprint)
    assert eng.hier_restored and eng.aot_warm
    np.testing.assert_array_equal(np.asarray(t2.result.x),
                                  np.asarray(t1.result.x))


# ---------------------------------------------------------------------------
# lock split (ROADMAP 3e)
# ---------------------------------------------------------------------------


def test_submit_never_waits_for_device_cycle(poisson16, monkeypatch):
    """The lock-split contention proof: while a scheduler cycle is
    blocked inside device stepping, submit() still completes — it
    contends only with bookkeeping, never with a cycle of device
    work (ROADMAP 3e)."""
    svc = SolveService(_svc_cfg(
        extra="serving_chunk_iters=1, s:tolerance=1e-14"))
    t1 = svc.submit(poisson16, _rhs(poisson16, 36))
    svc.step()                          # build + admit
    assert not t1.done
    in_step, release = threading.Event(), threading.Event()
    orig_step = BucketEngine.step

    def blocked_step(self):
        in_step.set()
        assert release.wait(30)
        return orig_step(self)

    monkeypatch.setattr(BucketEngine, "step", blocked_step)
    th = threading.Thread(target=svc.step)
    th.start()
    try:
        assert in_step.wait(30)         # cycle is inside device work
        t0 = time.monotonic()
        t2 = svc.submit(poisson16, _rhs(poisson16, 37))
        dt = time.monotonic() - t0
        assert th.is_alive()            # the cycle is STILL blocked
        assert not t2.done and dt < 5.0
    finally:
        release.set()
        th.join()
    monkeypatch.setattr(BucketEngine, "step", orig_step)
    svc.drain(timeout_s=300)
    assert t1.result.converged and t2.result.converged


# ---------------------------------------------------------------------------
# backpressure & load shedding
# ---------------------------------------------------------------------------


def test_shed_deadline_unmeetable_overloaded(poisson16):
    """serving_shed_policy=deadline: once the live estimator is
    trained, a request whose deadline cannot be met at the current
    queue depth is shed OVERLOADED at submit — before it ever queues."""
    svc = SolveService(_svc_cfg(
        extra="serving_shed_policy=deadline"))
    warm = svc.submit(poisson16, _rhs(poisson16, 38))
    svc.drain(timeout_s=300)
    assert warm.result.converged       # estimator now trained
    svc._exec_recent.extend([0.05, 0.05, 0.05])
    shd0 = metrics.get("serving.shed.deadline")
    t = svc.submit(poisson16, _rhs(poisson16, 39), deadline_s=1e-4)
    assert t.done
    assert t.result.status_code == int(SolveStatus.OVERLOADED)
    assert metrics.get("serving.shed.deadline") - shd0 == 1
    # a generous deadline is admitted and served normally
    t2 = svc.submit(poisson16, _rhs(poisson16, 40), deadline_s=1e6)
    svc.drain(timeout_s=300)
    assert t2.result.converged


def test_shed_tenant_quota(poisson16):
    """serving_tenant_quota: a tenant at its live-request quota has
    further submits shed OVERLOADED; other tenants are unaffected."""
    svc = SolveService(_svc_cfg(extra="serving_tenant_quota=1"))
    q0 = metrics.get("serving.shed.quota")
    t1 = svc.submit(poisson16, _rhs(poisson16, 41), tenant="greedy")
    t2 = svc.submit(poisson16, _rhs(poisson16, 42), tenant="greedy")
    t3 = svc.submit(poisson16, _rhs(poisson16, 43), tenant="modest")
    assert not t1.done and not t3.done
    assert t2.done and t2.result.status == "overloaded"
    assert metrics.get("serving.shed.quota") - q0 == 1
    assert svc.stats()["tenants"]["greedy"]["shed"] == 1
    svc.drain(timeout_s=300)
    assert t1.result.converged and t3.result.converged


# ---------------------------------------------------------------------------
# supervision, quarantine & the service-level chaos scenarios
# ---------------------------------------------------------------------------


def test_step_crash_quarantines_and_resumes_bit_identical(poisson16):
    """A device-step exception mid-flight quarantines the bucket: the
    in-flight slot requeues with its LIVE state, the rebuilt bucket
    resumes it, and the final iterate is bit-identical to a run that
    never crashed (default policy: STEP_FAILED>requeue)."""
    extra = "serving_chunk_iters=1, s:tolerance=1e-12"
    ref = SolveService(_svc_cfg(extra=extra))
    b = _rhs(poisson16, 44)
    rt = ref.submit(poisson16, b)
    ref.drain(timeout_s=300)
    svc = SolveService(_svc_cfg(extra=extra))
    q0 = metrics.get("serving.recovery.quarantined")
    rq0 = metrics.get("serving.recovery.requeued")
    t = svc.submit(poisson16, b)
    svc.step()                          # build + admit + first cycle
    with faultinject.inject("step_crash", fires=1):
        svc.step()                      # crashes -> quarantine
    assert metrics.get("serving.recovery.quarantined") - q0 == 1
    assert metrics.get("serving.recovery.requeued") - rq0 == 1
    assert not t.done
    svc.drain(timeout_s=300)
    assert t.result.converged
    assert t.result.iterations == rt.result.iterations
    np.testing.assert_array_equal(np.asarray(t.result.x),
                                  np.asarray(rt.result.x))


def test_wedged_bucket_detected_and_recovered(poisson16):
    """The supervisor satellite: a bucket whose progress heartbeat
    flatlines (scripted step_wedge — cycles run, iteration counters
    frozen) is quarantined after serving_supervisor_cycles and its
    work requeued; the scheduler never hangs."""
    svc = SolveService(_svc_cfg(
        extra="serving_supervisor_cycles=2, serving_chunk_iters=1,"
              " s:tolerance=1e-12"))
    q0 = metrics.get("serving.recovery.quarantined")
    t = svc.submit(poisson16, _rhs(poisson16, 45))
    svc.step()
    with faultinject.inject("step_wedge", fires=4):
        for _ in range(5):
            svc.step()
    assert metrics.get("serving.recovery.quarantined") - q0 >= 1
    svc.drain(timeout_s=300)
    assert t.done and t.result.converged


def test_build_crash_retry_backoff_converges(poisson16):
    """BUILD_FAILED>retry_backoff: a crashed bucket build leaves its
    tickets queued behind a bounded exponential backoff; the retry
    succeeds and the tickets converge (vs the default reject)."""
    svc = SolveService(_svc_cfg(
        extra="serving_fault_policy=BUILD_FAILED>retry_backoff,"
              " serving_retry_backoff_s=0.01"))
    r0 = metrics.get("serving.recovery.build_retries")
    with faultinject.inject("build_crash", fires=1):
        t = svc.submit(poisson16, _rhs(poisson16, 46))
        svc.drain(timeout_s=300)
    assert t.result.converged
    assert metrics.get("serving.recovery.build_retries") - r0 == 1


def test_build_crash_attempts_bounded_then_reject(poisson16):
    """An always-crashing build cannot retry forever: after
    serving_retry_max_attempts the tickets reject with BREAKDOWN and
    the error attached — bounded, terminal, no hang."""
    svc = SolveService(_svc_cfg(
        extra="serving_fault_policy=BUILD_FAILED>retry_backoff,"
              " serving_retry_backoff_s=0.001,"
              " serving_retry_max_attempts=2"))
    with faultinject.inject("build_crash", fires=None):
        t = svc.submit(poisson16, _rhs(poisson16, 47))
        svc.drain(timeout_s=60)
    assert t.done
    assert t.result.status_code == int(SolveStatus.BREAKDOWN)
    assert isinstance(t.error, faultinject.ChaosInjected)
    assert svc.idle


def test_step_crash_attempts_bounded_then_reject(poisson16):
    """A bucket whose device step crashes EVERY cycle cannot loop
    quarantine->rebuild->quarantine forever: a successful rebuild does
    not reset the fault-attempt counter (only a terminal completion
    does), so serving_retry_max_attempts bounds STEP_FAILED too and
    the tickets reject terminally."""
    svc = SolveService(_svc_cfg(
        extra="serving_retry_max_attempts=1, serving_chunk_iters=1"))
    with faultinject.inject("step_crash", fires=None):
        t = svc.submit(poisson16, _rhs(poisson16, 51))
        svc.drain(timeout_s=120)
    assert t.done
    assert t.result.status_code == int(SolveStatus.BREAKDOWN)
    assert svc.idle
    # ...and a healthy completion clears the counter: the same
    # fingerprint serves normally once the fault is gone
    t2 = svc.submit(poisson16, _rhs(poisson16, 52))
    svc.drain(timeout_s=300)
    assert t2.result.converged


def test_journal_corrupt_pattern_self_heals(poisson16, tmp_path):
    """A corrupt PATTERN file (shared across a fingerprint's records)
    is deleted at the failed replay read, so the next submit rewrites
    it — corruption cannot permanently poison a fingerprint's
    durability."""
    cfg = _svc_cfg(extra=f"serving_journal_dir={tmp_path},"
                         " serving_chunk_iters=1, s:tolerance=1e-12")
    svc = SolveService(cfg)
    with faultinject.inject("journal_corrupt", fires=1):
        svc.submit(poisson16, _rhs(poisson16, 53))  # pattern write torn
    del svc
    succ = SolveService(cfg)          # replay drops the corrupt record
    assert succ.stats()["journal_pending"] == 0
    # durability restored: a new journaled request round-trips a crash
    t = succ.submit(poisson16, _rhs(poisson16, 54))
    for _ in range(3):
        succ.step()
    assert not t.done
    del succ
    succ2 = SolveService(cfg)
    done = succ2.drain(timeout_s=300)
    assert len(done) == 1 and done[0].result.converged


def test_engine_admit_occupied_slot_still_raises(poisson16):
    """Direct BucketEngine users keep the strict occupied-slot guard:
    the scheduler's reservation protocol (unique occupant objects)
    must not have weakened the default-occupant path."""
    from amgx_tpu.errors import BadParametersError
    eng = BucketEngine(_svc_cfg(), "default", poisson16, slots=2,
                       chunk=4, dtype=np.float64)
    eng.admit(0, poisson16, _rhs(poisson16, 55))
    with pytest.raises(BadParametersError, match="occupied"):
        eng.admit(0, poisson16, _rhs(poisson16, 56))


def test_bucket_failure_status_does_not_poison_neighbors(poisson16):
    """Status interplay inside a chunked bucket: a slot that hits
    NAN_DETECTED mid-chunk (injected SpMV NaN baked into the bucket's
    traces) finalizes with that status while a neighbor slot in the
    SAME bucket still finalizes CONVERGED — per-slot statuses are
    independent, and the bucket keeps serving afterwards."""
    with faultinject.inject("spmv_nan", iteration=3, fires=None):
        # armed at build: the engine's chunked step trace carries the
        # iteration-3 corruption for the bucket's lifetime
        svc = SolveService(_svc_cfg(extra="serving_chunk_iters=2"))
        bad = svc.submit(poisson16, _rhs(poisson16, 48))
        zero = svc.submit(poisson16, np.zeros(poisson16.num_rows))
        svc.drain(timeout_s=300)
    assert bad.done
    assert bad.result.status_code == int(SolveStatus.NAN_DETECTED)
    assert not bad.result.converged
    # the all-zero rhs converges at iteration 0 — before the fault
    # iteration — in the SAME poisoned bucket
    assert zero.done and zero.result.converged
    assert zero.result.iterations == 0
    # and the bucket is not poisoned for the service: a fresh service
    # (clean trace epoch) serves the same pattern fine
    svc2 = SolveService(_svc_cfg(extra="serving_chunk_iters=2"))
    ok = svc2.submit(poisson16, _rhs(poisson16, 48))
    svc2.drain(timeout_s=300)
    assert ok.result.converged


def test_clock_skew_deadlines_stay_terminal(poisson16):
    """Chaos: with the service clock skewed forward, deadline
    bookkeeping stays consistent (submit and expiry read the same
    skewed clock) and every ticket still terminates."""
    with faultinject.inject("clock_skew", value=600.0, fires=None):
        svc = SolveService(_svc_cfg())
        t1 = svc.submit(poisson16, _rhs(poisson16, 49), deadline_s=1e9)
        t2 = svc.submit(poisson16, _rhs(poisson16, 50), deadline_s=0.0)
        svc.drain(timeout_s=300)
    assert t1.done and t1.result.converged
    assert t2.done and t2.result.status_code == \
        int(SolveStatus.DEADLINE_EXCEEDED)


# ---------------------------------------------------------------------------
# telemetry catalog + bench smoke
# ---------------------------------------------------------------------------


def test_serving_metrics_declared():
    snap = metrics.snapshot()
    for name in ("serving.requests", "serving.completed",
                 "serving.rejected", "serving.deadline_miss",
                 "serving.cache.hit", "serving.cache.miss",
                 "serving.cache.evictions", "serving.retrace",
                 "serving.aot.export", "serving.aot.load",
                 "serving.aot.error", "batch.bucket_evictions",
                 # fault-tolerance layer
                 "serving.recovery.checkpoints",
                 "serving.recovery.replayed",
                 "serving.recovery.resumed",
                 "serving.recovery.restart_fresh",
                 "serving.recovery.journal_corrupt",
                 "serving.recovery.quarantined",
                 "serving.recovery.salvaged",
                 "serving.recovery.requeued",
                 "serving.recovery.build_retries",
                 "serving.recovery.hstore_save",
                 "serving.recovery.hstore_load",
                 "serving.recovery.hstore_skip",
                 "serving.recovery.hstore_error",
                 "serving.dedupe", "serving.shed.overload",
                 "serving.shed.deadline", "serving.shed.quota",
                 "amg.setup.restored", "resilience.config_fallback"):
        assert name in snap
    assert "serving.exec_s" in metrics.HISTOGRAMS


def test_bench_serving_smoke():
    """The `bench.py serving --smoke` fast path: the tier-1-runnable
    slice of the acceptance gates (cache-hit rate > 0, value-resetup
    routing, zero retraces after AOT warmup, deadline statuses)."""
    import bench
    # bench.py switches the process compile-cache dir at import; point
    # it back at the suite's cache so later tests stay warm
    jax.config.update("jax_compilation_cache_dir",
                      "/tmp/amgx_tpu_jax_cache")
    res = bench.bench_serving(smoke=True)
    assert res["all_completed"]
    assert res["solves_per_s"] > 0
    assert res["p50_ms"] > 0 and res["p50_ms"] <= res["p99_ms"]
    assert res["cache_hit_rate"] > 0
    assert res["value_resetups_routed"] > 0
    assert res["retraces_after_warmup"] == 0
    assert res["aot_loads"] >= 1
    assert res["deadline_requests"] > 0
    assert res["deadline_statuses_ok"]


@pytest.mark.slow
def test_bench_chaos_smoke():
    """The `bench.py chaos --smoke` acceptance gates: kill-and-recover
    resumes bit-identically with zero full setups / zero retraces,
    every scripted fault scenario ends all-tickets-terminal, and the
    2x-saturation shed load keeps admitted work inside its deadline
    with sheds classified OVERLOADED. (slow: ~1 min of scripted
    service scenarios — the per-scenario unit tests above are the
    tier-1 subset.)"""
    import bench
    jax.config.update("jax_compilation_cache_dir",
                      "/tmp/amgx_tpu_jax_cache")
    res = bench.bench_chaos(smoke=True)
    assert res["killed_inflight"] > 0
    assert res["recover_replayed"] > 0 and res["recover_resumed"] > 0
    assert res["recover_bitwise_ok"]
    assert res["restart_full_setups"] == 0
    assert res["restart_hier_restored"] >= 1
    assert res["restart_retraces"] == 0
    assert res["recover_all_terminal"]
    assert res["chaos_recover_wall_s"] > 0
    assert res["chaos_all_terminal"], res["chaos_scenarios"]
    assert res["shed_all_overloaded"]
    assert res["shed_admitted_deadline_misses"] == 0
    assert res["shed_ok"]
