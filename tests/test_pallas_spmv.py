"""Pallas DIA SpMV kernel tests (interpreter mode — the compiled path
runs on real TPU via bench.py). Mirrors the role of the reference's
csrmv fast-path coverage (src/multiply.cu:74-121)."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

import amgx_tpu as amgx
from amgx_tpu import gallery
from amgx_tpu.ops.pallas_spmv import (dia_padded_rows, dia_spmv,
                                      pick_block_rows)
from amgx_tpu.ops.spmv import spmv_csr_segsum

amgx.initialize()


@pytest.mark.parametrize("stencil,dims", [
    ("5pt", (16, 16)),          # 2D, single block
    ("7pt", (12, 12, 12)),      # odd n (padding tail exercised)
    ("9pt", (20, 20)),          # lane-crossing offsets (+-1, +-21...)
    ("27pt", (8, 8, 8)),        # many diagonals
])
def test_dia_kernel_matches_segsum(stencil, dims):
    A = gallery.poisson(stencil, *dims, dtype=jnp.float32).init()
    assert A.dia_offsets is not None
    n = A.num_rows
    x = jnp.asarray(
        np.random.default_rng(0).standard_normal(n), jnp.float32)
    y_ref = spmv_csr_segsum(A, x)
    y = dia_spmv(A, x, interpret=True)
    np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref),
                               rtol=1e-5, atol=1e-5)


def test_dia_kernel_multiblock():
    """Problem large enough for several grid blocks + halo DMA reuse."""
    A = gallery.poisson("7pt", 48, 48, 48, dtype=jnp.float32).init()
    n = A.num_rows
    x = jnp.asarray(
        np.random.default_rng(1).standard_normal(n), jnp.float32)
    y_ref = spmv_csr_segsum(A, x)
    y = dia_spmv(A, x, interpret=True)
    np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref),
                               rtol=1e-5, atol=1e-5)


def test_tiled_layout_consistency():
    """matrix init and the kernel wrapper agree on the tile padding."""
    for stencil, dims in [("5pt", (10, 10)), ("7pt", (32, 32, 32))]:
        A = gallery.poisson(stencil, *dims, dtype=jnp.float32).init()
        k, rows_pad, lanes = A.dia_vals.shape
        assert lanes == 128
        assert rows_pad == dia_padded_rows(k, A.num_rows)
        br = pick_block_rows(k, -(-A.num_rows // 128))
        assert rows_pad % br == 0


def test_vmap_diverts_to_xla():
    """vmap over the Pallas dispatch must take the XLA form (pallas_call
    has no batching rule for ANY-space operands)."""
    from amgx_tpu.ops.spmv import _spmv_dia_pallas, _spmv_dia_xla
    A = gallery.poisson("5pt", 12, 12, dtype=jnp.float32).init()
    n = A.num_rows
    Z = jnp.asarray(
        np.random.default_rng(2).standard_normal((4, n)), jnp.float32)
    Y = jax.vmap(lambda z: _spmv_dia_pallas(A, z))(Z)
    Y_ref = jax.vmap(lambda z: _spmv_dia_xla(A, z))(Z)
    np.testing.assert_allclose(np.asarray(Y), np.asarray(Y_ref),
                               rtol=1e-6)


def test_with_values_keeps_tiled_layout():
    A = gallery.poisson("5pt", 8, 8, dtype=jnp.float32).init()
    A2 = A.with_values(A.values * 2.0)
    assert A2.dia_vals.shape == A.dia_vals.shape
    x = jnp.ones(A.num_rows, jnp.float32)
    np.testing.assert_allclose(np.asarray(amgx.ops.spmv(A2, x)),
                               2 * np.asarray(amgx.ops.spmv(A, x)),
                               rtol=1e-6)
