"""GEO structured aggregation, mixed-precision preconditioning,
defect-correction REFINEMENT, and the TPU-safe dense QR kernels.

Reference anchors: geo_selector.cu (geometric aggregation),
amgx_config.h:102-131 (precision modes), dense_lu_solver.cu:514-580
(dense factorization); the refinement loop is the TPU-native execution
strategy for dDDI-accuracy solves (LAPACK-dsgesv-style defect
correction).
"""
import numpy as np
import jax.numpy as jnp
import pytest

import amgx_tpu as amgx
from amgx_tpu import gallery, ops, registry
from amgx_tpu.config import Config
from amgx_tpu.errors import AMGXError
from amgx_tpu.ops import dense

amgx.initialize()

_GEO_AMG = (
    "solver=FGMRES, max_iters=60, monitor_residual=1, tolerance=1e-8,"
    " gmres_n_restart=20, convergence=RELATIVE_INI, norm=L2,"
    " preconditioner(amg)=AMG, amg:algorithm=AGGREGATION, amg:selector=GEO,"
    " amg:smoother=BLOCK_JACOBI, amg:relaxation_factor=0.75,"
    " amg:presweeps=0, amg:postsweeps=3, amg:max_iters=1, amg:cycle=V,"
    " amg:max_levels=10, amg:min_coarse_rows=16")


# ---------------------------------------------------------------------------
# dense QR kernels (TPU-safe LU replacements)
# ---------------------------------------------------------------------------

class TestDenseQR:
    def test_inverse_matches_numpy(self, rng):
        a = rng.standard_normal((12, 12)) + 12 * np.eye(12)
        inv = np.asarray(dense.inverse(jnp.asarray(a)))
        np.testing.assert_allclose(inv, np.linalg.inv(a), rtol=1e-9)

    def test_solve_qr_batched(self, rng):
        a = rng.standard_normal((5, 6, 6)) + 6 * np.eye(6)
        b = rng.standard_normal((5, 6))
        x = np.asarray(dense.solve_qr(jnp.asarray(a), jnp.asarray(b)))
        np.testing.assert_allclose(
            x, np.linalg.solve(a, b[..., None])[..., 0], rtol=1e-8)

    def test_abs_det(self, rng):
        a = rng.standard_normal((4, 5, 5))
        d = np.asarray(dense.abs_det(jnp.asarray(a)))
        np.testing.assert_allclose(d, np.abs(np.linalg.det(a)), rtol=1e-8)

    def test_safe_inverse_singular_block_is_identity(self, rng):
        a = np.stack([np.zeros((3, 3)),
                      np.eye(3) * 2.0])
        inv = np.asarray(dense.safe_inverse(jnp.asarray(a)))
        np.testing.assert_allclose(inv[0], np.eye(3))
        np.testing.assert_allclose(inv[1], np.eye(3) / 2.0, rtol=1e-12)


# ---------------------------------------------------------------------------
# GEO selector
# ---------------------------------------------------------------------------

class TestGeoSelector:
    @pytest.mark.parametrize("dims", [(8, 8, 8), (7, 6, 5), (16, 4, 1)])
    def test_transfers_match_generic_segment_path(self, dims, rng):
        from amgx_tpu.amg.aggregation.galerkin import (prolongate_corr,
                                                       restrict_vector)
        A = gallery.poisson("7pt", *dims).init()
        cfg = Config.from_string(
            "solver=AMG, algorithm=AGGREGATION, selector=GEO,"
            " smoother=BLOCK_JACOBI")
        lv = registry.amg_levels.get("AGGREGATION")(A, cfg, "default", 0)
        lv.create_coarse_vertices()
        data = {"aggregates": lv.aggregates}
        r = jnp.asarray(rng.standard_normal(A.num_rows))
        np.testing.assert_allclose(
            np.asarray(lv.restrict(data, r)),
            np.asarray(restrict_vector(lv.aggregates, lv.coarse_size, r)),
            rtol=1e-13)
        xc = jnp.asarray(rng.standard_normal(lv.coarse_size))
        np.testing.assert_allclose(
            np.asarray(lv.prolongate(data, xc)),
            np.asarray(prolongate_corr(lv.aggregates, xc)), rtol=1e-13)

    def test_hierarchy_stays_banded_dia(self):
        A = gallery.poisson("7pt", 16, 16, 16).init()
        slv = amgx.create_solver(Config.from_string(_GEO_AMG))
        slv.setup(A)
        amg = slv.preconditioner.amg
        assert len(amg.levels) >= 2
        for lv in amg.levels:
            assert lv.A.dia_offsets is not None, "GEO level lost DIA layout"
            assert len(lv.A.dia_offsets) <= 9
        # the 2x2x2 Galerkin of a 7-pt stencil is again a 7-pt stencil
        assert len(amg.levels[1].A.dia_offsets) == 7

    def test_geo_converges(self):
        A = gallery.poisson("7pt", 12, 12, 12).init()
        b = jnp.ones(A.num_rows)
        slv = amgx.create_solver(Config.from_string(_GEO_AMG))
        slv.setup(A)
        res = slv.solve(b)
        assert res.converged
        r = np.linalg.norm(np.asarray(ops.residual(A, res.x, b)))
        assert r < 1e-7 * np.linalg.norm(np.asarray(b)) * 10

    def test_geo_rejects_unstructured(self):
        A = gallery.random_matrix(40, max_nnz_per_row=4, seed=3,
                                  symmetric=True, diag_dominant=True)
        cfg = Config.from_string(
            "solver=AMG, algorithm=AGGREGATION, selector=GEO,"
            " smoother=BLOCK_JACOBI")
        lv = registry.amg_levels.get("AGGREGATION")(A.init(), cfg,
                                                    "default", 0)
        with pytest.raises(AMGXError):
            lv.create_coarse_vertices()


# ---------------------------------------------------------------------------
# mixed-precision preconditioning (amg_precision)
# ---------------------------------------------------------------------------

class TestAmgPrecision:
    def test_float_cycle_converges_same_iters(self):
        A = gallery.poisson("7pt", 10, 10, 10).init()
        b = jnp.ones(A.num_rows)
        ref = amgx.create_solver(Config.from_string(_GEO_AMG))
        ref.setup(A)
        r_ref = ref.solve(b)
        slv = amgx.create_solver(Config.from_string(
            _GEO_AMG + ", amg:amg_precision=float"))
        slv.setup(A)
        res = slv.solve(b)
        assert res.converged
        # flexible GMRES tolerates the f32 preconditioner: same counts
        # up to a small slack
        assert abs(res.iterations - r_ref.iterations) <= 2
        # hierarchy data is actually stored reduced
        data = slv.preconditioner.amg.solve_data()
        assert data["levels"][0]["A"].values.dtype == jnp.float32

    def test_precision_param_validated(self):
        with pytest.raises(AMGXError):
            Config.from_string(_GEO_AMG + ", amg:amg_precision=half8")


# ---------------------------------------------------------------------------
# REFINEMENT (defect correction)
# ---------------------------------------------------------------------------

_REFINE = (
    "solver=REFINEMENT, max_iters=20, monitor_residual=1, tolerance=1e-11,"
    " convergence=RELATIVE_INI, norm=L2,"
    " preconditioner(in)=FGMRES, in:max_iters=60, in:monitor_residual=1,"
    " in:tolerance=1e-6, in:gmres_n_restart=10, in:convergence=RELATIVE_INI,"
    " in:norm=L2, in:preconditioner(amg)=AMG, amg:algorithm=AGGREGATION,"
    " amg:selector=GEO, amg:smoother=BLOCK_JACOBI,"
    " amg:relaxation_factor=0.75, amg:presweeps=0, amg:postsweeps=3,"
    " amg:max_iters=1, amg:cycle=V, amg:max_levels=10,"
    " amg:min_coarse_rows=16")


class TestRefinement:
    def test_f64_accuracy_from_f32_inner(self):
        A = gallery.poisson("7pt", 10, 10, 10).init()
        assert A.dtype == jnp.float64
        b = jnp.ones(A.num_rows)
        slv = amgx.create_solver(Config.from_string(_REFINE))
        slv.setup(A)
        # the inner tree really is f32
        assert slv.preconditioner.A.dtype == jnp.float32
        res = slv.solve(b)
        assert res.converged
        rel = (np.linalg.norm(np.asarray(ops.residual(A, res.x, b)))
               / np.linalg.norm(np.asarray(b)))
        # beyond f32 epsilon: provably f64 accumulation
        assert rel < 1e-10
        assert res.x.dtype == jnp.float64

    def test_needs_inner_solver(self):
        A = gallery.poisson("5pt", 8, 8).init()
        slv = amgx.create_solver(Config.from_string(
            "solver=REFINEMENT, max_iters=5, preconditioner=NOSOLVER"))
        with pytest.raises(AMGXError):
            slv.setup(A)


# ---------------------------------------------------------------------------
# packed stats round trip
# ---------------------------------------------------------------------------

def test_unpack_stats_roundtrip():
    from amgx_tpu.solvers.base import Solver
    hist = np.linspace(1.0, 0.1, 7)
    stats = np.concatenate([[3.0, 1.0, 0.0], [2.5], [0.25], hist])
    iters, conv, status, n0, rn, h = Solver.unpack_stats(stats, 7)
    assert iters == 3 and conv is True and status == 0
    assert n0 == 2.5 and rn == 0.25
    # history is trimmed to the actual iteration count (iters + 1)
    np.testing.assert_allclose(h, hist[:4])
