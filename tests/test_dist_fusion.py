"""Distributed cycle fusion (distributed/fused.py): the halo-folded
per-shard fused smoother kernels under shard_map.

Runs on the CPU mesh with the kernels routed through the Pallas
interpreter (force_pallas_interpret); the compiled path runs on real
TPU. Covers: the affine window-sweep mirror's exactness, sharded
fused-vs-unfused V-cycle parity (2 and 4 shards, f32 1e-6, including a
ragged last shard), the jaxpr proofs — a fused sharded level traces
exactly TWO pallas_calls per shard per cycle with the edge-window halo
collective count independent of the sweep schedule (no per-sweep
exchange), and the consolidation boundary feeding the single-chip VMEM
coarse-tail megakernel — the `dist_cycle_fusion=0` escape hatch
(bit-for-bit the payload-free composition), value-resetup refresh of
the halo-extended slabs, and the f64 XLA window route."""
import re

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

import amgx_tpu as amgx
from amgx_tpu import gallery
from amgx_tpu._compat import shard_map
from amgx_tpu.config import Config
from amgx_tpu.distributed import DistributedSolver, default_mesh
from amgx_tpu.distributed import comms
from amgx_tpu.amg.cycles import run_cycle
from amgx_tpu.ops import pallas_spmv as ps
from amgx_tpu.ops.spmv import spmv

amgx.initialize()


def _cfg(extra="", smoother="JACOBI_L1", max_levels=3):
    return (
        "solver=FGMRES, max_iters=40, monitor_residual=1,"
        " tolerance=1e-7, gmres_n_restart=20, preconditioner(amg)=AMG,"
        " amg:algorithm=AGGREGATION, amg:selector=SIZE_2,"
        f" amg:smoother={smoother}, amg:relaxation_factor=0.9,"
        f" amg:max_iters=1, amg:cycle=V, amg:max_levels={max_levels},"
        " amg:min_coarse_rows=16, amg:coarse_solver=DENSE_LU_SOLVER,"
        " amg:distributed_setup_mode=global" + extra)


def _setup(cfg_str, n_dev, A):
    ds = DistributedSolver(Config.from_string(cfg_str),
                           default_mesh(n_dev))
    ds.setup(A)
    return ds


def _amg_data(ds):
    return ds.solver.preconditioner.amg, ds._data["precond"]["amg"]


def _one_cycle(ds, b, x):
    """Apply one V-cycle of the distributed AMG hierarchy to global
    (b, x); returns the global result (numpy)."""
    amg, data = _amg_data(ds)
    nl = ds.part.n_local
    R = ds.n_ranks

    def body(d, bb, xx):
        dl = jax.tree.map(lambda a: a[0], d)
        with comms.collective_axis(ds.axis):
            return run_cycle(amg, "V", dl, bb[0], xx[0])[None]

    pspec = jax.tree.map(lambda _: P(ds.axis), data)
    fn = shard_map(body, mesh=ds.mesh,
                   in_specs=(pspec, P(ds.axis), P(ds.axis)),
                   out_specs=P(ds.axis), check_vma=False)
    n = ds.part.n_global
    pad = R * nl - n
    bl = jnp.pad(jnp.asarray(b), (0, pad)).reshape(R, nl)
    xl = jnp.pad(jnp.asarray(x), (0, pad)).reshape(R, nl)
    return np.asarray(fn(data, bl, xl)).reshape(-1)[:n]


def _cycle_jaxpr(ds):
    amg, data = _amg_data(ds)
    nl = ds.part.n_local
    R = ds.n_ranks

    def body(d, bb, xx):
        dl = jax.tree.map(lambda a: a[0], d)
        with comms.collective_axis(ds.axis):
            return run_cycle(amg, "V", dl, bb[0], xx[0])[None]

    pspec = jax.tree.map(lambda _: P(ds.axis), data)
    fn = shard_map(body, mesh=ds.mesh,
                   in_specs=(pspec, P(ds.axis), P(ds.axis)),
                   out_specs=P(ds.axis), check_vma=False)
    dt = ds.shard_A.dtype
    return str(jax.make_jaxpr(fn)(data, jnp.ones((R, nl), dt),
                                  jnp.zeros((R, nl), dt)))


def _kcount(jaxpr_str, kernel):
    return len(re.findall(r'name=[^ ]*' + kernel, jaxpr_str))


def _rel(a, b):
    return float(np.linalg.norm(a - b)
                 / max(np.linalg.norm(b), 1e-300))


# ---------------------------------------------------------------------------
# the XLA window-sweep mirror (ops/batched.py affine_window_sweeps)
# ---------------------------------------------------------------------------


def test_affine_window_sweeps_exact_f64():
    """The element-unit temporal-blocking mirror reproduces the global
    sweep chain exactly on an interior target window (f64, 1e-14)."""
    from amgx_tpu.ops.batched import affine_window_sweeps
    A = gallery.poisson("7pt", 6, 6, 12).init()
    n = A.num_rows
    offsets = A.dia_offsets
    k = len(offsets)
    m, M = max(0, -min(offsets)), max(0, max(offsets))
    rng = np.random.default_rng(3)
    b = jnp.asarray(rng.standard_normal(n))
    x = jnp.asarray(rng.standard_normal(n))
    dinv = 1.0 / A.diagonal()
    taus = jnp.asarray([0.8, 0.7])
    n_app = 3                           # 2 sweeps + residual
    xr, rr = x, b
    for t in range(2):
        xr = xr + taus[t] * dinv * (b - spmv(A, xr))
    rr = b - spmv(A, xr)
    # target window strictly interior
    t0, W = 2 * (m + M), 96
    vflat = jnp.asarray(np.asarray(A.dia_vals).reshape(k, -1))
    Wv = W + (n_app - 1) * (m + M)
    lo = t0 - (n_app - 1) * m
    y, r = affine_window_sweeps(
        offsets, vflat[:, lo: lo + Wv], b[lo: lo + Wv],
        x[t0 - n_app * m: t0 + W + n_app * M], taus,
        dinv[lo: lo + Wv], W, True)
    assert _rel(np.asarray(y), np.asarray(xr)[t0:t0 + W]) < 1e-14
    assert _rel(np.asarray(r), np.asarray(rr)[t0:t0 + W]) < 1e-13


# ---------------------------------------------------------------------------
# sharded fused-vs-unfused cycle parity
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("n_dev,nz,smoother,presweeps", [
    (2, 32, "JACOBI_L1", 1),
    (2, 32, "CHEBYSHEV_POLY", 1),          # dinv-less tau schedule
    pytest.param(2, 32, "JACOBI_L1", 2, marks=pytest.mark.slow),
    pytest.param(4, 32, "JACOBI_L1", 1, marks=pytest.mark.slow),
    # ragged: 1080 rows over 4 shards -> padded last shard
    pytest.param(4, 30, "JACOBI_L1", 1, marks=pytest.mark.slow),
])
def test_sharded_fused_cycle_parity_f32(n_dev, nz, smoother, presweeps):
    """One V-cycle through the halo-folded fused kernels equals the
    per-sweep halo-exchange composition (f32, 1e-6)."""
    A = gallery.poisson("7pt", 6, 6, nz, dtype=jnp.float32).init()
    n = A.num_rows
    rng = np.random.default_rng(7)
    b = rng.standard_normal(n).astype(np.float32)
    x = rng.standard_normal(n).astype(np.float32)
    extra = f", amg:presweeps={presweeps}"
    with ps.force_pallas_interpret():
        ds_f = _setup(_cfg(extra, smoother=smoother), n_dev, A)
        smd0 = ds_f._data["precond"]["amg"]["levels"][0]["smoother"]
        assert "dist_fused" in smd0, "payload did not attach"
        y_f = _one_cycle(ds_f, b, x)
        ds_u = _setup(_cfg(extra + ", amg:dist_cycle_fusion=0",
                           smoother=smoother), n_dev, A)
        assert "dist_fused" not in \
            ds_u._data["precond"]["amg"]["levels"][0]["smoother"]
        y_u = _one_cycle(ds_u, b, x)
    # f32 reordering noise only: the same CHEBYSHEV_POLY config agrees
    # to 2e-15 in f64 (the per-step taus > 1 amplify the fused kernel's
    # different accumulation order slightly past 1e-6)
    assert _rel(y_f, y_u) < 4e-6, _rel(y_f, y_u)


def test_sharded_fused_full_solve_matches_iterations():
    """The fused distributed solve converges with the same iteration
    count as the unfused distributed AND the single-device run."""
    A = gallery.poisson("7pt", 6, 6, 32, dtype=jnp.float32).init()
    b = np.ones(A.num_rows, np.float32)
    with ps.force_pallas_interpret():
        ds = _setup(_cfg(), 2, A)
        res = ds.solve(b)
        ds0 = _setup(_cfg(", amg:dist_cycle_fusion=0"), 2, A)
        res0 = ds0.solve(b)
    assert res.converged and res0.converged
    assert res.iterations == res0.iterations
    slv = amgx.create_solver(Config.from_string(_cfg()))
    slv.setup(A)
    ref = slv.solve(jnp.asarray(b))
    assert res.iterations == ref.iterations


# ---------------------------------------------------------------------------
# jaxpr proofs
# ---------------------------------------------------------------------------


def test_jaxpr_two_kernels_no_per_sweep_collective():
    """A fused sharded DIA level's per-cycle work is exactly TWO
    pallas_calls per shard (presmooth+residual, postsmooth), and the
    halo collective count does not grow with the sweep schedule — the
    exchange is one packed edge-window pair per fused call, never
    serialized between sweeps. The unfused composition keeps zero
    kernels and more collectives."""
    A = gallery.poisson("7pt", 6, 6, 32, dtype=jnp.float32).init()

    def counts(extra):
        with ps.force_pallas_interpret():
            ds = _setup(_cfg(extra, max_levels=2), 2, A)
            s = _cycle_jaxpr(ds)
        return (_kcount(s, "_dia_smooth_call"), s.count("pallas_call"),
                s.count("ppermute"))

    k1, p1, c1 = counts("")
    k3, p3, c3 = counts(", amg:presweeps=3")
    assert k1 == 2 and p1 == 2, (k1, p1)
    assert (k3, p3) == (2, 2), (k3, p3)
    assert c1 == c3, ("collective count must be sweep-independent",
                      c1, c3)
    ku, pu, cu = counts(", amg:dist_cycle_fusion=0")
    assert ku == 0 and pu == 0
    assert c1 < cu, ("fused cycle must trace fewer halo collectives",
                     c1, cu)


def test_jaxpr_kernel_inputs_independent_of_collective():
    """Overlap proof: the fused kernels' operands are NOT produced by
    the edge-window collective — only the (tiny) XLA boundary strips
    consume it, so XLA's latency-hiding scheduler is free to run the
    exchange concurrently with the interior kernel."""
    A = gallery.poisson("7pt", 6, 6, 32, dtype=jnp.float32).init()
    with ps.force_pallas_interpret():
        ds = _setup(_cfg(max_levels=2), 2, A)
        amg, data = _amg_data(ds)
        nl = ds.part.n_local

        def body(d, bb, xx):
            dl = jax.tree.map(lambda a: a[0], d)
            with comms.collective_axis(ds.axis):
                return run_cycle(amg, "V", dl, bb[0], xx[0])[None]

        pspec = jax.tree.map(lambda _: P(ds.axis), data)
        fn = shard_map(body, mesh=ds.mesh,
                       in_specs=(pspec, P(ds.axis), P(ds.axis)),
                       out_specs=P(ds.axis), check_vma=False)
        jaxpr = jax.make_jaxpr(fn)(
            data, jnp.ones((2, nl), jnp.float32),
            jnp.zeros((2, nl), jnp.float32))

    # walk every eqn (descending into sub-jaxprs); collect collective
    # outputs and check no pallas_call takes one as a DIRECT input
    tainted = set()
    kernels_seen = 0

    def walk(jx):
        nonlocal kernels_seen
        for eqn in jx.eqns:
            if eqn.primitive.name == "ppermute":
                for v in eqn.outvars:
                    tainted.add(id(v))
            if eqn.primitive.name == "pallas_call":
                kernels_seen += 1
                for v in eqn.invars:
                    assert id(v) not in tainted, (
                        "fused kernel consumes the halo collective "
                        "output — the overlap is broken")
            for p in eqn.params.values():
                for q in (p if isinstance(p, (tuple, list)) else (p,)):
                    if isinstance(q, jax.core.ClosedJaxpr):
                        walk(q.jaxpr)
                    elif isinstance(q, jax.core.Jaxpr):
                        walk(q)

    walk(jaxpr.jaxpr)
    assert kernels_seen >= 2


def test_dist_cycle_fusion_0_bit_for_bit():
    """dist_cycle_fusion=0 under the fused runtime traces EXACTLY the
    program of a rig where the halo-folded payload never exists (the
    pre-PR composition): the knob gates the payload attach and nothing
    else, so knob-off IS the old code path (the PR-5 structural-
    fallback proof technique — a no-interpret rig can't serve as the
    reference because it also skips the single-chip slab builds that
    ride in the solve-data)."""
    from amgx_tpu.distributed import fused as dfused
    A = gallery.poisson("7pt", 6, 6, 32, dtype=jnp.float32).init()
    with ps.force_pallas_interpret():
        ds0 = _setup(_cfg(", amg:dist_cycle_fusion=0"), 2, A)
        assert "dist_fused" not in \
            ds0._data["precond"]["amg"]["levels"][0]["smoother"]
        j0 = _cycle_jaxpr(ds0)
        old = dfused.attach_shard_fused
        try:
            dfused.attach_shard_fused = lambda *a, **k: False
            ds_sim = _setup(_cfg(), 2, A)
        finally:
            dfused.attach_shard_fused = old
        jsim = _cycle_jaxpr(ds_sim)
    assert j0 == jsim


# ---------------------------------------------------------------------------
# consolidation boundary -> VMEM coarse tail
# ---------------------------------------------------------------------------


def test_consolidation_boundary_feeds_vmem_tail():
    """With coarse-level consolidation, the gathered replicated tail of
    a distributed GEO/DIA hierarchy runs as ONE VMEM-resident coarse
    tail megakernel per cycle while the sharded finest level keeps its
    two halo-folded kernels; fused and unfused solves agree."""
    A = gallery.poisson("7pt", 8, 8, 32, dtype=jnp.float32).init()
    b = np.ones(A.num_rows, np.float32)
    cfg = ("solver=PCG, max_iters=40, monitor_residual=1,"
           " tolerance=1e-7, preconditioner(amg)=AMG,"
           " amg:algorithm=AGGREGATION, amg:selector=GEO,"
           " amg:smoother=CHEBYSHEV_POLY,"
           " amg:chebyshev_polynomial_order=2, amg:max_iters=1,"
           " amg:cycle=V, amg:max_levels=5, amg:min_coarse_rows=16,"
           " amg:coarse_solver=DENSE_LU_SOLVER,"
           " amg:distributed_setup_mode=global,"
           " amg:amg_consolidation_flag=1,"
           " amg:matrix_consolidation_lower_threshold=300")
    with ps.force_pallas_interpret():
        ds = _setup(cfg, 2, A)
        s = _cycle_jaxpr(ds)
        assert _kcount(s, "_dia_coarse_tail_call") == 1, s.count(
            "pallas_call")
        assert _kcount(s, "_dia_smooth_call") == 2
        res = ds.solve(b)
        ds_u = _setup(cfg + ", amg:dist_cycle_fusion=0,"
                      " amg:cycle_fusion=0, amg:fused_smoother=0", 2, A)
        res_u = ds_u.solve(b)
    assert res.converged and res_u.converged
    assert res.iterations == res_u.iterations
    assert _rel(np.asarray(res.x), np.asarray(res_u.x)) < 1e-5


@pytest.mark.slow
def test_sharded_setup_level0_fused_parity():
    """The per-shard (device-resident) setup attaches the halo-folded
    payload to its FINEST level (the only one with a visible global
    DIA operator); the fused sharded solve matches dist_cycle_fusion=0
    and converges identically."""
    A = gallery.poisson("7pt", 6, 6, 32, dtype=jnp.float32).init()
    b = np.ones(A.num_rows, np.float32)
    cfg = _cfg(", amg:matrix_consolidation_lower_threshold=100",
               max_levels=4).replace(
        "distributed_setup_mode=global", "distributed_setup_mode=sharded")
    with ps.force_pallas_interpret():
        ds = _setup(cfg, 2, A)
        from amgx_tpu.distributed.setup import DistAMGLevel
        amg = ds.solver.preconditioner.amg
        assert any(isinstance(lv, DistAMGLevel) for lv in amg.levels)
        smd0 = ds._data["precond"]["amg"]["levels"][0]["smoother"]
        assert "dist_fused" in smd0
        res = ds.solve(b)
        ds_u = _setup(cfg + ", amg:dist_cycle_fusion=0", 2, A)
        res_u = ds_u.solve(b)
    assert res.converged and res.iterations == res_u.iterations
    assert _rel(np.asarray(res.x), np.asarray(res_u.x)) < 1e-5


# ---------------------------------------------------------------------------
# payload build: value refresh, f64 route
# ---------------------------------------------------------------------------


def test_value_resetup_refreshes_halo_slabs():
    """The payload memo is keyed on the identity of the value-carrying
    arrays: same values reuse the slabs, a value resetup rebuilds them
    with the NEW coefficients folded into the halo quota rows."""
    import dataclasses
    from amgx_tpu.distributed.fused import attach_shard_fused
    from amgx_tpu.solvers.base import make_solver
    cfg = Config.from_string("solver=BLOCK_JACOBI")
    A = gallery.poisson("7pt", 8, 8, 16, dtype=jnp.float32).init()
    sm = make_solver("BLOCK_JACOBI", cfg, "default")
    sm.setup(A)
    smd = {}
    with ps.force_pallas_interpret():
        assert attach_shard_fused(smd, A, sm, 2, A.num_rows // 2,
                                  cfg, "default")
        fd1 = smd["dist_fused"]
        # memo hit: identical value arrays -> identical payload object
        smd2 = {}
        assert attach_shard_fused(smd2, A, sm, 2, A.num_rows // 2,
                                  cfg, "default")
        assert smd2["dist_fused"] is fd1
        # value change (the value-resetup splice swaps dia_vals)
        A2 = dataclasses.replace(A, dia_vals=A.dia_vals * 2.0)
        sm2 = make_solver("BLOCK_JACOBI", cfg, "default")
        sm2.setup(A2)
        smd3 = {}
        assert attach_shard_fused(smd3, A2, sm2, 2, A.num_rows // 2,
                                  cfg, "default")
        fd2 = smd3["dist_fused"]
    assert fd2 is not fd1
    # the refreshed slab's halo rows carry the NEW neighbor values:
    # shard 1's front quota tail == shard 0's last rows, doubled
    qf, _, _ = ps.smooth_quota_rows(A.dia_offsets, A.num_rows // 2)
    L = ps.LANES
    f1 = np.asarray(fd1.vals_q[1]).reshape(len(A.dia_offsets), -1)
    f2 = np.asarray(fd2.vals_q[1]).reshape(len(A.dia_offsets), -1)
    halo1 = f1[:, :qf * L]
    halo2 = f2[:, :qf * L]
    assert np.abs(halo1).max() > 0, "front quota rows are not folded"
    np.testing.assert_allclose(halo2, 2.0 * halo1, rtol=1e-6)


@pytest.mark.slow
def test_f64_xla_window_route_parity():
    """f64 solves decline the Pallas kernel and take the whole-shard
    XLA window sweep — still one edge-window exchange per fused call;
    parity with the unfused compose at 1e-12."""
    A = gallery.poisson("7pt", 6, 6, 32).init()      # f64 default
    n = A.num_rows
    rng = np.random.default_rng(11)
    b = rng.standard_normal(n)
    x = rng.standard_normal(n)
    with ps.force_pallas_interpret():
        ds_f = _setup(_cfg(), 2, A)
        assert "dist_fused" in \
            ds_f._data["precond"]["amg"]["levels"][0]["smoother"]
        s = _cycle_jaxpr(ds_f)
        assert s.count("pallas_call") == 0    # XLA route, no kernels
        y_f = _one_cycle(ds_f, b, x)
        ds_u = _setup(_cfg(", amg:dist_cycle_fusion=0"), 2, A)
        y_u = _one_cycle(ds_u, b, x)
    assert _rel(y_f, y_u) < 1e-12
