"""Classical-path cycle fusion (ISSUE 12 tentpole): the weighted
row-segment transfer slabs (`ops/smooth.py build_csr_transfer_slabs`),
the generalized restriction-epilogue / prolongation-prologue kernels
(`ops/pallas_spmv.py`, weighted ctab/cwt + multi-entry ptab/pwt), and
the classical `AMGLevel` fusion hooks consumed through the existing
`_fusion_caps` dispatch in `amg/cycles.py`.

Kernels run through the Pallas interpreter (force_pallas_interpret, the
CPU test path); the compiled path runs on real TPU via bench.py.
Mirrors tests/test_cycle_fusion.py's aggregation proofs: kernel parity
f32 (interpret) and f64 (the XLA slab fallback in ops/batched.py — the
parity reference), the jaxpr HBM-pass proof (a smoothed classical DIA
level runs EXACTLY two fused kernels per cycle with zero standalone
SpMV/transfer primitives outside them), and the cycle_fusion=0 escape
hatch reproducing the unfused composition bit-for-bit."""
import re

import numpy as np
import pytest
import jax
import jax.numpy as jnp

import amgx_tpu as amgx
from amgx_tpu import gallery
from amgx_tpu.config import Config
from amgx_tpu.amg.hierarchy import AMG
from amgx_tpu.ops import pallas_spmv as ps
from amgx_tpu.ops import smooth as fused
from amgx_tpu.ops.spmv import spmv

amgx.initialize()

# the benched classical shape: PMIS + truncated D2 (the reference's
# production settings) — short P rows, so the fused plans single-kernel
_AMG_CFG = ("algorithm=CLASSICAL, selector=PMIS, interpolator=D2,"
            " smoother=JACOBI_L1, coarse_solver=DENSE_LU_SOLVER,"
            " strength_threshold=0.25, interp_max_elements=4,"
            " max_row_sum=0.9, min_coarse_rows=16, max_levels=10")

_CYCLE_CFG = (
    "solver(s)=PCG, s:max_iters=40, s:tolerance=1e-7,"
    " s:convergence=RELATIVE_INI, s:monitor_residual=1,"
    " s:preconditioner(amg)=AMG, amg:algorithm=CLASSICAL,"
    " amg:selector=PMIS, amg:interpolator=D2, amg:smoother=JACOBI_L1,"
    " amg:presweeps=2, amg:postsweeps=1, amg:max_iters=1,"
    " amg:strength_threshold=0.25, amg:interp_max_elements=4,"
    " amg:max_row_sum=0.9, amg:coarse_solver=DENSE_LU_SOLVER,"
    " amg:min_coarse_rows=16")


def _rel(a, b):
    return float(jnp.linalg.norm(a - b) /
                 jnp.maximum(jnp.linalg.norm(b), 1e-300))


def _ref_sweeps(A, b, x, taus, dinv=None):
    for t in range(taus.shape[0]):
        upd = taus[t] * (b - spmv(A, x))
        if dinv is not None:
            upd = upd * dinv
        x = x + upd
    return x, b - spmv(A, x)


def _classical_level(n=10, dtype=jnp.float64, extra=""):
    """Finest classical level of a 7-pt Poisson hierarchy: DIA A plus
    real D2 interpolation P / R = P^T (the weighted-slab source)."""
    A = gallery.poisson("7pt", n, n, n, dtype=dtype).init()
    amg = AMG(Config.from_string(_AMG_CFG + extra)).setup(A)
    return amg.levels[0]


def _vectors(lv, dtype, seed=0):
    n = lv.A.num_rows
    nc = int(lv.P.num_cols)
    rng = np.random.default_rng(seed)
    b = jnp.asarray(rng.standard_normal(n), dtype)
    x = jnp.asarray(rng.standard_normal(n), dtype)
    xc = jnp.asarray(rng.standard_normal(nc), dtype)
    dinv = jnp.asarray(1.0 / rng.uniform(4, 8, n), dtype)
    return b, x, xc, dinv


# ---------------------------------------------------------------------------
# slab build + XLA fallback (the f64 parity reference)
# ---------------------------------------------------------------------------


def test_csr_slab_fallback_parity_f64():
    """The weighted slab forms (what f64 and vmapped callers run)
    reproduce R @ r and x + P @ xc to f64 accuracy against the
    explicit transfer-operator SpMVs."""
    from amgx_tpu.ops.batched import prolong_corr_multi, restrict_multi
    lv = _classical_level()
    xfer = fused.build_csr_transfer_slabs(lv.A, lv.P, lv.R)
    assert xfer is not None and xfer.cwt is not None \
        and xfer.ptab is not None
    n, nc = lv.A.num_rows, int(lv.P.num_cols)
    rng = np.random.default_rng(3)
    Rs = jnp.asarray(rng.standard_normal((3, n)))
    X = jnp.asarray(rng.standard_normal((3, n)))
    XC = jnp.asarray(rng.standard_normal((3, nc)))
    BC = restrict_multi(Rs, xfer)
    OUT = prolong_corr_multi(lv.A, X, XC, xfer)
    for i in range(3):
        assert _rel(BC[i], spmv(lv.R, Rs[i])) < 1e-12
        assert _rel(OUT[i], X[i] + spmv(lv.P, XC[i])) < 1e-12


def test_csr_slab_caps_decline():
    """A P/R row beyond the kernel child caps builds no slabs (the
    cycle then composes the explicit SpMVs — never a wrong answer)."""
    lv = _classical_level(n=8)
    old = ps.CSR_TRANSFER_MAX_CHILD
    try:
        ps.CSR_TRANSFER_MAX_CHILD = 1
        assert fused.build_csr_transfer_slabs(lv.A, lv.P, lv.R) is None
    finally:
        ps.CSR_TRANSFER_MAX_CHILD = old


def test_smooth_restrict_dia_multi_weighted_f64():
    """The fused multi-RHS compose (smoother sweeps + weighted
    restriction) matches the unfused reference at 1e-12 — this is the
    slab route solve_many takes under vmap."""
    from amgx_tpu.ops.batched import (corr_smooth_dia_multi,
                                      smooth_restrict_dia_multi)
    lv = _classical_level()
    xfer = fused.build_csr_transfer_slabs(lv.A, lv.P, lv.R)
    n, nc = lv.A.num_rows, int(lv.P.num_cols)
    rng = np.random.default_rng(5)
    B = jnp.asarray(rng.standard_normal((2, n)))
    X = jnp.asarray(rng.standard_normal((2, n)))
    XC = jnp.asarray(rng.standard_normal((2, nc)))
    dinv = jnp.asarray(1.0 / rng.uniform(4, 8, n))
    taus = jnp.asarray(np.full(2, 0.85))
    XF, BCF = smooth_restrict_dia_multi(lv.A, B, X, taus, dinv, xfer)
    XF2 = corr_smooth_dia_multi(lv.A, B, X, XC, taus, dinv, xfer)
    for i in range(2):
        xr, rr = _ref_sweeps(lv.A, B[i], X[i], taus, dinv)
        assert _rel(XF[i], xr) < 1e-12
        assert _rel(BCF[i], spmv(lv.R, rr)) < 1e-12
        xr2, _ = _ref_sweeps(lv.A, B[i], X[i] + spmv(lv.P, XC[i]),
                             taus, dinv)
        assert _rel(XF2[i], xr2) < 1e-12


# ---------------------------------------------------------------------------
# kernel parity (interpret mode)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("with_dinv", [True, False])
def test_weighted_restrict_epilogue_parity_f32(with_dinv):
    lv = _classical_level(dtype=jnp.float32)
    b, x, _, dinv = _vectors(lv, jnp.float32, seed=1)
    dinv = dinv if with_dinv else None
    taus = jnp.asarray(np.full(2, 0.9), jnp.float32)
    xr, rr = _ref_sweeps(lv.A, b, x, taus, dinv)
    bc_ref = spmv(lv.R, rr)
    with ps.force_pallas_interpret():
        slabs = fused.build_fused_slabs(lv.A, dinv)
        xfer = fused.build_csr_transfer_slabs(lv.A, lv.P, lv.R)
        assert ps.dia_restrict_supported(lv.A, jnp.float32, 2, xfer)
        out = fused.fused_smooth_restrict(
            {"A": lv.A, "fused": slabs}, b, x, taus, xfer, dinv=dinv)
    assert out is not None
    assert _rel(out[0], xr) < 1e-6
    assert _rel(out[1], bc_ref) < 1e-6


@pytest.mark.parametrize("with_dinv", [True, False])
def test_weighted_prolong_prologue_parity_f32(with_dinv):
    lv = _classical_level(dtype=jnp.float32)
    b, x, xc, dinv = _vectors(lv, jnp.float32, seed=2)
    dinv = dinv if with_dinv else None
    taus = jnp.asarray(np.full(2, 0.85), jnp.float32)
    xr, _ = _ref_sweeps(lv.A, b, x + spmv(lv.P, xc), taus, dinv)
    with ps.force_pallas_interpret():
        slabs = fused.build_fused_slabs(lv.A, dinv)
        xfer = fused.build_csr_transfer_slabs(lv.A, lv.P, lv.R)
        out = fused.fused_corr_smooth(
            {"A": lv.A, "fused": slabs}, b, x, xc, taus, xfer,
            dinv=dinv)
    assert out is not None
    assert _rel(out, xr) < 1e-6


@pytest.mark.slow
def test_weighted_transfer_parity_multiblock_and_chained():
    """Small VMEM budgets force the multi-block path (R rows straddling
    fine-block windows complete in the per-block combine) and the
    chained dispatch (plain fused chunks + the transfer chunk)."""
    lv = _classical_level(n=16, dtype=jnp.float32)
    b, x, xc, dinv = _vectors(lv, jnp.float32, seed=4)
    taus = jnp.asarray(np.full(3, 0.8), jnp.float32)
    xr, rr = _ref_sweeps(lv.A, b, x, taus, dinv)
    bc_ref = spmv(lv.R, rr)
    xr2, _ = _ref_sweeps(lv.A, b, x + spmv(lv.P, xc), taus, dinv)
    old = ps._SMOOTH_VMEM_BUDGET
    try:
        for budget in (1400 * 1024, 700 * 1024):
            ps._SMOOTH_VMEM_BUDGET = budget
            with ps.force_pallas_interpret():
                slabs = fused.build_fused_slabs(lv.A, dinv)
                xfer = fused.build_csr_transfer_slabs(lv.A, lv.P, lv.R)
                data = {"A": lv.A, "fused": slabs}
                out = fused.fused_smooth_restrict(data, b, x, taus,
                                                  xfer, dinv=dinv)
                out2 = fused.fused_corr_smooth(data, b, x, xc, taus,
                                               xfer, dinv=dinv)
            if out is not None:
                assert _rel(out[0], xr) < 1e-6
                assert _rel(out[1], bc_ref) < 1e-6
            if out2 is not None:
                assert _rel(out2, xr2) < 1e-6
            assert out is not None or out2 is not None, \
                "both fused routes declined at this budget"
    finally:
        ps._SMOOTH_VMEM_BUDGET = old


def test_weighted_transfer_vmap_routes_to_slab():
    """Under jax.vmap (solve_many's shape) the fused transfer calls
    must land in the weighted multi-RHS slab forms and match
    per-system references — the single-RHS kernels have no batching
    rule."""
    lv = _classical_level(n=8, dtype=jnp.float32)
    n, nc = lv.A.num_rows, int(lv.P.num_cols)
    rng = np.random.default_rng(6)
    B = jnp.asarray(rng.standard_normal((3, n)), jnp.float32)
    X = jnp.asarray(rng.standard_normal((3, n)), jnp.float32)
    XC = jnp.asarray(rng.standard_normal((3, nc)), jnp.float32)
    dinv = jnp.asarray(1.0 / rng.uniform(4, 8, n), jnp.float32)
    taus = jnp.asarray(np.full(2, 0.9), jnp.float32)
    with ps.force_pallas_interpret():
        slabs = fused.build_fused_slabs(lv.A, dinv)
        xfer = fused.build_csr_transfer_slabs(lv.A, lv.P, lv.R)
        data = {"A": lv.A, "fused": slabs}
        XF, BCF = jax.vmap(
            lambda bb, xx: fused.fused_smooth_restrict(
                data, bb, xx, taus, xfer, dinv=dinv))(B, X)
        XF2 = jax.vmap(
            lambda bb, xx, xcc: fused.fused_corr_smooth(
                data, bb, xx, xcc, taus, xfer, dinv=dinv))(B, X, XC)
    for i in range(3):
        xr, rr = _ref_sweeps(lv.A, B[i], X[i], taus, dinv)
        assert _rel(XF[i], xr) < 1e-6
        assert _rel(BCF[i], spmv(lv.R, rr)) < 1e-6
        xr2, _ = _ref_sweeps(lv.A, B[i], X[i] + spmv(lv.P, XC[i]),
                             taus, dinv)
        assert _rel(XF2[i], xr2) < 1e-6


# ---------------------------------------------------------------------------
# cycle integration: jaxpr proof, escape hatch, solves
# ---------------------------------------------------------------------------


def _trace_cycle(extra_cfg="", n=12):
    A = gallery.poisson("7pt", n, n, n, dtype=jnp.float32).init()
    b = jnp.ones(A.num_rows, jnp.float32)
    with ps.force_pallas_interpret():
        slv = amgx.create_solver(Config.from_string(_CYCLE_CFG
                                                    + extra_cfg))
        slv.setup(A)
        pc = slv.preconditioner
        d = pc.solve_data()
        jaxpr = jax.make_jaxpr(
            lambda bb, xx: pc.amg.cycle(d["amg"], bb, xx))(
                b, jnp.zeros_like(b))
    return pc.amg, jaxpr


def _kernel_counts(jaxpr):
    names = re.findall(r"name=\"?([A-Za-z_0-9]+)\"?", str(jaxpr))
    out = {}
    for nm in names:
        for key in ("_dia_smooth_restrict_call",
                    "_dia_prolong_smooth_call", "_dia_coarse_tail_call",
                    "_dia_smooth_call", "_dia_spmv_call",
                    "_swell_spmv_call", "_swell_smooth_call"):
            if nm == key:
                out[key] = out.get(key, 0) + 1
    return out


def _outer_prims(closed_jaxpr):
    prims = []

    def walk(jaxpr):
        for eqn in jaxpr.eqns:
            if eqn.primitive.name == "pallas_call":
                continue
            prims.append(eqn.primitive.name)
            for p in eqn.params.values():
                for q in (p if isinstance(p, (tuple, list)) else (p,)):
                    if isinstance(q, jax.core.ClosedJaxpr):
                        walk(q.jaxpr)
                    elif isinstance(q, jax.core.Jaxpr):
                        walk(q)

    walk(closed_jaxpr.jaxpr)
    return prims


def test_jaxpr_proof_classical_fused_kernel_budget():
    """HBM-pass proof (the ISSUE 12 acceptance gate): a smoothed
    classical DIA level runs EXACTLY two fused Pallas kernels per
    cycle — presmooth+weighted-restriction, weighted-prolongation+
    postsmooth — with zero standalone dia/SWELL SpMV kernels and zero
    standalone transfer primitives (gather/scatter/pad) outside them,
    exactly like the aggregation proof in tests/test_cycle_fusion.py."""
    amg, jaxpr = _trace_cycle(", amg:max_levels=2")
    assert len(amg.levels) == 1
    assert amg.levels[0].A.dia_vals is not None
    c = _kernel_counts(jaxpr)
    assert c.get("_dia_smooth_restrict_call", 0) == 1, c
    assert c.get("_dia_prolong_smooth_call", 0) == 1, c
    assert c.get("_dia_smooth_call", 0) == 0, c
    assert c.get("_dia_spmv_call", 0) == 0, c
    assert c.get("_swell_spmv_call", 0) == 0, c
    assert c.get("_swell_smooth_call", 0) == 0, c
    outer = set(_outer_prims(jaxpr))
    assert not outer & {"pad", "gather", "scatter-add", "scatter"}, \
        sorted(outer & {"pad", "gather", "scatter-add", "scatter"})


def test_cycle_fusion_off_restores_composition():
    """cycle_fusion=0 must trace the unfused classical composition
    (fused smoother kernels + standalone SWELL transfer SpMVs, zero
    transfer kernels) — and the same jaxpr as the fusion path's
    structural fallback (hooks declining), proving the escape hatch IS
    the old code path bit-for-bit."""
    amg, jaxpr = _trace_cycle(", amg:max_levels=2, amg:cycle_fusion=0")
    c = _kernel_counts(jaxpr)
    assert c.get("_dia_smooth_restrict_call", 0) == 0, c
    assert c.get("_dia_prolong_smooth_call", 0) == 0, c
    assert c.get("_swell_spmv_call", 0) == 2, c   # restrict + prolong
    from amgx_tpu.amg.classical import ClassicalAMGLevel
    old_r = ClassicalAMGLevel.restrict_fused
    old_p = ClassicalAMGLevel.prolongate_smooth
    try:
        ClassicalAMGLevel.restrict_fused = lambda *a, **k: None
        ClassicalAMGLevel.prolongate_smooth = lambda *a, **k: None
        _, jaxpr2 = _trace_cycle(", amg:max_levels=2")
    finally:
        ClassicalAMGLevel.restrict_fused = old_r
        ClassicalAMGLevel.prolongate_smooth = old_p
    assert str(jaxpr2) == str(jaxpr)


def test_classical_fused_solve_parity():
    """Fused-vs-unfused full classical solve: same iterations (+-1),
    matching answers, through a DEEP hierarchy (the fused DIA fine
    level above unfused SWELL coarse levels)."""
    A = gallery.poisson("7pt", 12, 12, 12, dtype=jnp.float32).init()
    b = jnp.ones(A.num_rows, jnp.float32)
    with ps.force_pallas_interpret():
        s1 = amgx.create_solver(Config.from_string(_CYCLE_CFG))
        s1.setup(A)
        r1 = s1.solve(b)
    s0 = amgx.create_solver(Config.from_string(
        _CYCLE_CFG + ", amg:cycle_fusion=0, amg:fused_smoother=0"))
    s0.setup(A)
    r0 = s0.solve(b)
    assert r1.converged and r0.converged
    assert abs(int(r1.iterations) - int(r0.iterations)) <= 1
    assert _rel(r1.x, r0.x) < 1e-4


def test_supports_fusion_gates():
    """The capability surface: slabs present -> advertises both hooks;
    no slabs (cycle_fusion=0) -> advertises nothing and the data
    carries no xfer leaf."""
    lv = _classical_level(n=8, dtype=jnp.float32)
    with ps.force_pallas_interpret():
        amg = AMG(Config.from_string(_AMG_CFG)).setup(
            gallery.poisson("7pt", 8, 8, 8, dtype=jnp.float32).init())
        d = amg.levels[0].level_data()
        assert "xfer" in d
        assert set(amg.levels[0].supports_fusion(d)) == \
            {"restrict", "prolongate"}
        amg0 = AMG(Config.from_string(
            _AMG_CFG + ", cycle_fusion=0")).setup(
            gallery.poisson("7pt", 8, 8, 8, dtype=jnp.float32).init())
        d0 = amg0.levels[0].level_data()
        assert "xfer" not in d0
        assert amg0.levels[0].supports_fusion(d0) == ()


@pytest.mark.slow
def test_structure_resetup_keeps_slabs_and_solves():
    """structure_reuse_levels=-1: the reused classical levels carry
    their weighted slabs over (P/R are kept, values included), and the
    resetup solve matches an unfused fresh setup on the new
    coefficients."""
    A = gallery.poisson("7pt", 12, 12, 12, dtype=jnp.float32).init()
    b = jnp.ones(A.num_rows, jnp.float32)
    with ps.force_pallas_interpret():
        slv = amgx.create_solver(Config.from_string(
            _CYCLE_CFG + ", amg:structure_reuse_levels=-1"))
        slv.setup(A)
        lv0 = slv.preconditioner.amg.levels[0]
        x1 = lv0._transfer_slabs()
        assert x1 is not None
        assert lv0._transfer_slabs() is x1, "xfer slab memo broken"
        slv.solve(b)
        A2 = A.with_values(A.values * 2.0)
        slv.resetup(A2 if A2.initialized else A2.init())
        lv0b = slv.preconditioner.amg.levels[0]
        assert lv0b._transfer_slabs() is x1, \
            "structure reuse rebuilt the kept P/R's slabs"
        r2 = slv.solve(b)
    ref = amgx.create_solver(Config.from_string(
        _CYCLE_CFG + ", amg:cycle_fusion=0, amg:fused_smoother=0"))
    A2r = A.with_values(A.values * 2.0)
    ref.setup(A2r if A2r.initialized else A2r.init())
    r0 = ref.solve(b)
    assert r2.converged
    assert abs(int(r2.iterations) - int(r0.iterations)) <= 1
    assert _rel(r2.x, r0.x) < 1e-4
