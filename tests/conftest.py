"""Test harness configuration.

Runs the whole suite on CPU with 8 virtual devices so the distributed
(mesh/shard_map) paths are unit-testable on a single host — the gap the
reference leaves open (its unit binary is single-process; multi-rank
coverage only via MPI example programs, SURVEY.md §4).
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from _cpu_backend import force_cpu  # noqa: E402

force_cpu(8)

import jax  # noqa: E402

# persistent compilation cache makes repeated test runs cheap (eager setup
# ops compile one XLA executable per shape bucket)
jax.config.update("jax_compilation_cache_dir", "/tmp/amgx_tpu_jax_cache")
jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
jax.config.update("jax_persistent_cache_enable_xla_caches",
                  "xla_gpu_per_fusion_autotune_cache_dir")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: heavyweight tests excluded from the tier-1 budgeted run "
        "(`-m 'not slow'`); run them with `-m slow` on a capable rig")


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(1234)
