"""Multicolor smoothers + coloring validity.

Mirrors the reference tests src/tests/valid_coloring.cu,
ilu_dilu_equivalence.cu, and the scalar/block smoother poisson
convergence tests (src/tests/).
"""
import jax.numpy as jnp
import numpy as np
import pytest

import amgx_tpu as amgx
from amgx_tpu.config import Config
from amgx_tpu.ops.coloring import color_matrix
from amgx_tpu.solvers.base import make_solver

amgx.initialize()


def _poisson(n=8):
    return amgx.gallery.poisson("5pt", n, n).init()


def _valid(A, colors):
    rows, cols, _ = A.coo()
    rows, cols = np.asarray(rows), np.asarray(cols)
    c = np.asarray(colors)
    offd = rows != cols
    return not np.any(c[rows[offd]] == c[cols[offd]])


@pytest.mark.parametrize("scheme", ["MIN_MAX", "MULTI_HASH",
                                    "SERIAL_GREEDY_BFS"])
def test_valid_coloring(scheme):
    """No edge joins two same-colored vertices (valid_coloring.cu)."""
    A = _poisson(12)
    cfg = Config.from_string(f"matrix_coloring_scheme={scheme}")
    col = color_matrix(A, cfg, "default")
    assert _valid(A, col.row_colors)
    assert col.num_colors >= 2


def test_greedy_recolor_shrinks_color_count():
    """GREEDY_RECOLOR (greedy_recolor.cu role): valid coloring with a
    STRICTLY smaller-or-equal color count than plain MIN_MAX — fewer
    colors means shallower DILU/GS sweep chains."""
    for A in (_poisson(16), amgx.gallery.poisson("9pt", 12, 12).init(),
              amgx.gallery.poisson("27pt", 7, 7, 7).init(),
              amgx.gallery.random_matrix(300, max_nnz_per_row=9, seed=3,
                                         symmetric=True,
                                         diag_dominant=True).init()):
        base = color_matrix(A, Config.from_string(
            "matrix_coloring_scheme=MIN_MAX"), "default")
        rec = color_matrix(A, Config.from_string(
            "matrix_coloring_scheme=GREEDY_RECOLOR"), "default")
        assert _valid(A, rec.row_colors)
        assert rec.num_colors <= base.num_colors
        assert int(np.asarray(rec.row_colors).max()) + 1 == rec.num_colors
    # the 27pt stencil must actually shrink (MIN_MAX overshoots there)
    A = amgx.gallery.poisson("27pt", 8, 8, 8).init()
    base = color_matrix(A, Config.from_string(
        "matrix_coloring_scheme=MIN_MAX"), "default")
    rec = color_matrix(A, Config.from_string(
        "matrix_coloring_scheme=GREEDY_RECOLOR"), "default")
    assert rec.num_colors < base.num_colors


def test_greedy_recolor_dilu_converges():
    A = _poisson(12)
    n = A.num_rows
    cfg = Config.from_string(
        "solver=PCG, max_iters=80, monitor_residual=1, tolerance=1e-10,"
        " preconditioner(sm)=MULTICOLOR_DILU,"
        " sm:matrix_coloring_scheme=GREEDY_RECOLOR")
    slv = amgx.create_solver(cfg)
    slv.setup(A)
    b = np.ones(n)
    r = slv.solve(b)
    assert bool(r.converged)
    resid = np.asarray(A.to_dense()) @ np.asarray(r.x) - b
    assert np.linalg.norm(resid) < 1e-8


def test_valid_coloring_distance2():
    A = _poisson(8)
    cfg = Config.from_string("matrix_coloring_scheme=MIN_MAX,"
                             "coloring_level=2")
    col = color_matrix(A, cfg, "default")
    # distance-2 valid: no two rows sharing a neighbor share a color
    import scipy.sparse as sp
    rows, cols, vals = map(np.asarray, A.coo())
    S = sp.csr_matrix((np.ones_like(vals), (rows, cols)), shape=A.shape)
    S2 = (S @ S).tocoo()
    c = np.asarray(col.row_colors)
    offd = S2.row != S2.col
    assert not np.any(c[S2.row[offd]] == c[S2.col[offd]])


@pytest.mark.parametrize("name", ["MULTICOLOR_GS", "MULTICOLOR_DILU",
                                  "MULTICOLOR_ILU", "FIXCOLOR_GS", "GS"])
def test_smoother_converges_poisson(name):
    """Standalone smoother iteration converges on SPD Poisson (the
    scalar smoother poisson tests of src/tests/)."""
    A = _poisson(10)
    n = A.num_rows
    rng = np.random.default_rng(0)
    x_true = rng.standard_normal(n)
    b = jnp.asarray(np.asarray(amgx.ops.spmv(A, jnp.asarray(x_true))))
    cfg = Config.from_string(
        f"solver={name}, max_iters=500, monitor_residual=1, tolerance=1e-8,"
        " relaxation_factor=0.9" + (", symmetric_GS=1" if "GS" in name else ""))
    slv = make_solver(name, cfg, "default")
    slv.setup(A)
    res = slv.solve(b)
    assert res.converged, (name, res.res_norm)
    np.testing.assert_allclose(np.asarray(res.x), x_true, atol=1e-5)


def test_dilu_beats_jacobi_as_amg_smoother():
    """AMG with MULTICOLOR_DILU needs fewer FGMRES iterations than
    BLOCK_JACOBI (the reason the reference defaults to DILU)."""
    A = amgx.gallery.poisson("7pt", 16, 16, 16).init()
    b = jnp.ones(A.num_rows)
    iters = {}
    for sm in ["BLOCK_JACOBI", "MULTICOLOR_DILU"]:
        cfg = Config.from_string(
            "solver=FGMRES, max_iters=60, monitor_residual=1,"
            " tolerance=1e-8, gmres_n_restart=30,"
            " preconditioner(amg)=AMG, amg:algorithm=AGGREGATION,"
            " amg:selector=SIZE_2,"
            f" amg:smoother={sm}, amg:max_iters=1, amg:cycle=V,"
            " amg:max_levels=10, amg:relaxation_factor=0.9")
        slv = amgx.create_solver(cfg)
        slv.setup(A)
        res = slv.solve(b)
        assert res.converged
        iters[sm] = res.iterations
    assert iters["MULTICOLOR_DILU"] < iters["BLOCK_JACOBI"], iters


def test_ilu_dilu_equivalence_tridiag():
    """For a (properly colored) tridiagonal matrix ILU(0) and DILU give
    the same preconditioner action (ilu_dilu_equivalence.cu analog:
    both reduce to the same E on matrices with no fill)."""
    n = 32
    main = 2.0 * np.ones(n)
    off = -1.0 * np.ones(n - 1)
    rows = np.concatenate([np.arange(n), np.arange(n - 1), np.arange(1, n)])
    cols = np.concatenate([np.arange(n), np.arange(1, n), np.arange(n - 1)])
    vals = np.concatenate([main, off, off])
    A = amgx.CsrMatrix.from_coo(rows, cols, vals, n, n).init()
    b = jnp.asarray(np.random.default_rng(1).standard_normal(n))
    outs = {}
    for name in ["MULTICOLOR_DILU", "MULTICOLOR_ILU"]:
        cfg = Config.from_string(
            f"solver={name}, max_iters=1, relaxation_factor=1.0")
        slv = make_solver(name, cfg, "default")
        slv.setup(A)
        outs[name] = np.asarray(slv.smooth(slv.solve_data(), b,
                                           jnp.zeros(n), 1))
    np.testing.assert_allclose(outs["MULTICOLOR_DILU"],
                               outs["MULTICOLOR_ILU"], rtol=1e-10)


def test_ilu_exact_factors_small():
    """The color-sweep fixed point reproduces exact ILU(0) factors on a
    small matrix (checked against a dense reference factorization)."""
    rng = np.random.default_rng(3)
    A = _poisson(5)
    n = A.num_rows
    cfg = Config.from_string("solver=MULTICOLOR_ILU, max_iters=1")
    slv = make_solver("MULTICOLOR_ILU", cfg, "default")
    slv.setup(A)
    # dense IKJ ILU(0) on the color-permuted matrix; the solver stores
    # the factors back in ORIGINAL ordering (distribution-aware form),
    # so map the reference the same way
    perm = np.asarray(np.argsort(np.asarray(slv.row_colors),
                                 kind="stable"))
    Ad = np.asarray(A.to_dense())[np.ix_(perm, perm)]
    pattern = Ad != 0
    M = Ad.copy()
    for i in range(n):
        for k in range(i):
            if pattern[i, k] and M[k, k] != 0:
                M[i, k] = M[i, k] / M[k, k]
                for j in range(k + 1, n):
                    if pattern[i, j]:
                        M[i, j] -= M[i, k] * M[k, j]
    L_ref_o = np.zeros((n, n))
    U_ref_o = np.zeros((n, n))
    L_ref_o[np.ix_(perm, perm)] = np.tril(M, -1)
    U_ref_o[np.ix_(perm, perm)] = np.triu(M)
    L_got = np.asarray(slv._Lp.to_dense())
    U_got = np.asarray(slv._Up.to_dense())
    np.testing.assert_allclose(L_got, L_ref_o, atol=1e-12)
    np.testing.assert_allclose(U_got, U_ref_o, atol=1e-12)


def test_block_dilu_converges():
    """DILU on a block matrix (block Poisson) converges."""
    A = amgx.gallery.poisson("5pt", 8, 8).init()
    # expand to 2x2 blocks: A (x) I2 + small coupling
    rows, cols, vals = map(np.asarray, A.coo())
    n = A.num_rows
    bvals = np.einsum("n,xy->nxy", vals, np.eye(2))
    bvals[:, 0, 1] = 0.05 * vals
    Ab = amgx.CsrMatrix.from_coo(rows, cols, jnp.asarray(bvals), n, n,
                                 block_dims=(2, 2)).init()
    nb = 2 * n
    rng = np.random.default_rng(5)
    x_true = rng.standard_normal(nb)
    b = jnp.asarray(np.asarray(amgx.ops.spmv(Ab, jnp.asarray(x_true))))
    cfg = Config.from_string(
        "solver=MULTICOLOR_DILU, max_iters=300, monitor_residual=1,"
        " tolerance=1e-8, relaxation_factor=0.9")
    slv = make_solver("MULTICOLOR_DILU", cfg, "default")
    slv.setup(Ab)
    res = slv.solve(b)
    assert res.converged
    np.testing.assert_allclose(np.asarray(res.x), x_true, atol=1e-5)


@pytest.mark.parametrize("name", ["GS", "MULTICOLOR_ILU",
                                  "MULTICOLOR_DILU", "MULTICOLOR_GS"])
def test_smoothers_with_external_diag(name):
    """DIAG-property matrices (externally stored diagonal) must give the
    same smoother fixed point as in-CSR storage."""
    A = _poisson(8)
    rows, cols, vals = map(np.asarray, A.coo())
    offd = rows != cols
    d = np.asarray(A.diagonal())
    Ax = amgx.CsrMatrix.from_coo(rows[offd], cols[offd],
                                 jnp.asarray(vals[offd]),
                                 A.num_rows, A.num_cols,
                                 diag=jnp.asarray(d)).init()
    rng = np.random.default_rng(2)
    x_true = rng.standard_normal(A.num_rows)
    b = jnp.asarray(np.asarray(amgx.ops.spmv(A, jnp.asarray(x_true))))
    cfg = Config.from_string(
        f"solver={name}, max_iters=500, monitor_residual=1,"
        " tolerance=1e-8, relaxation_factor=0.9")
    slv = make_solver(name, cfg, "default")
    slv.setup(Ax)
    res = slv.solve(b)
    assert res.converged, (name, res.res_norm)
    np.testing.assert_allclose(np.asarray(res.x), x_true, atol=1e-5)


def test_cf_jacobi_under_classical_amg():
    """CF_JACOBI as the smoother of a classical AMG-preconditioned
    solve (cf_jacobi gets its CF map from the level)."""
    A = amgx.gallery.poisson("5pt", 24, 24).init()
    b = jnp.ones(A.num_rows)
    cfg = Config.from_string(
        "solver=PCG, max_iters=60, monitor_residual=1, tolerance=1e-8,"
        " preconditioner(amg)=AMG, amg:algorithm=CLASSICAL,"
        " amg:smoother=CF_JACOBI, amg:max_iters=1, amg:cycle=V,"
        " amg:relaxation_factor=0.9")
    slv = amgx.create_solver(cfg)
    slv.setup(A)
    res = slv.solve(b)
    assert res.converged
    r = np.asarray(amgx.ops.residual(A, res.x, b))
    assert np.linalg.norm(r) < 1e-6 * np.linalg.norm(np.asarray(b))
