"""Cycle-fusion test suite (ops/smooth.py transfer dispatch,
ops/pallas_spmv.py dia_smooth_restrict / dia_prolong_smooth /
dia_coarse_tail kernels, amg/cycles.py hooks).

Kernels run through the Pallas interpreter (force_pallas_interpret, the
CPU test path); the compiled path runs on real TPU via bench.py.
Covers: kernel parity for the restriction epilogue and the
prolongation/correction prologue vs the unfused reference (f32 through
the kernels, f64 through the XLA slab fallback in ops/batched.py),
single-RHS / multi-block / chained schedules / vmapped batches; the
VMEM-resident coarse-tail kernel against the per-level composition; the
jaxpr HBM-pass proof (<= 2 kernels per fused smoothed DIA level
including its grid transfers, 1 kernel for the tail, zero standalone
restrict/prolongate/correction ops outside the kernels); and the
cycle_fusion=0 escape hatch reproducing the PR 4 composition."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

import amgx_tpu as amgx
from amgx_tpu import gallery
from amgx_tpu.config import Config
from amgx_tpu.ops import pallas_spmv as ps
from amgx_tpu.ops import smooth as fused
from amgx_tpu.ops.spmv import spmv

import _census

amgx.initialize()


def _rel(a, b):
    return float(jnp.linalg.norm(a - b) /
                 jnp.maximum(jnp.linalg.norm(b), 1e-300))


def _ref_sweeps(A, b, x, taus, dinv=None):
    for t in range(taus.shape[0]):
        upd = taus[t] * (b - spmv(A, x))
        if dinv is not None:
            upd = upd * dinv
        x = x + upd
    return x, b - spmv(A, x)


def _geo_agg(nx, ny, nz):
    """The GEO selector's 2x2x2 aggregates map (host numpy)."""
    n = nx * ny * nz
    i = np.arange(n)
    x, t = i % nx, i // nx
    y, z = t % ny, t // ny
    cnx, cny, cnz = (nx + 1) // 2, (ny + 1) // 2, (nz + 1) // 2
    agg = ((z // 2) * cny + (y // 2)) * cnx + (x // 2)
    return agg.astype(np.int32), cnx * cny * cnz


def _problem(n=10, dtype=jnp.float32, seed=0):
    A = gallery.poisson("7pt", n, n, n, dtype=dtype).init()
    agg, nc = _geo_agg(n, n, n)
    rng = np.random.default_rng(seed)
    b = jnp.asarray(rng.standard_normal(A.num_rows), dtype)
    x = jnp.asarray(rng.standard_normal(A.num_rows), dtype)
    dinv = jnp.asarray(1.0 / rng.uniform(4, 8, A.num_rows), dtype)
    xc = jnp.asarray(rng.standard_normal(nc), dtype)
    return A, agg, nc, b, x, dinv, xc


# ---------------------------------------------------------------------------
# kernel parity (interpret mode)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("schedule,with_dinv", [
    ("jacobi", True),       # constant tau + dinv (JACOBI / JACOBI_L1)
    ("cheb", False),        # per-step taus, no dinv (CHEBYSHEV_POLY)
])
def test_restrict_epilogue_parity_f32(schedule, with_dinv):
    A, agg, nc, b, x, dinv, _ = _problem()
    dinv = dinv if with_dinv else None
    rng = np.random.default_rng(7)
    taus = jnp.asarray(np.full(2, 0.9) if schedule == "jacobi"
                       else rng.uniform(0.05, 0.2, 2), jnp.float32)
    xr, rr = _ref_sweeps(A, b, x, taus, dinv)
    bc_ref = jax.ops.segment_sum(rr, jnp.asarray(agg), num_segments=nc)
    with ps.force_pallas_interpret():
        slabs = fused.build_fused_slabs(A, dinv)
        xfer = fused.build_transfer_slabs(A, agg, nc)
        out = fused.fused_smooth_restrict(
            {"A": A, "fused": slabs}, b, x, taus, xfer, dinv=dinv)
    assert out is not None
    assert _rel(out[0], xr) < 1e-6
    assert _rel(out[1], bc_ref) < 1e-6


@pytest.mark.parametrize("with_dinv", [True, False])
def test_prolong_prologue_parity_f32(with_dinv):
    A, agg, nc, b, x, dinv, xc = _problem(seed=1)
    dinv = dinv if with_dinv else None
    taus = jnp.asarray(np.full(2, 0.85), jnp.float32)
    xr, _ = _ref_sweeps(A, b, x + xc[jnp.asarray(agg)], taus, dinv)
    with ps.force_pallas_interpret():
        slabs = fused.build_fused_slabs(A, dinv)
        xfer = fused.build_transfer_slabs(A, agg, nc)
        out = fused.fused_corr_smooth(
            {"A": A, "fused": slabs}, b, x, xc, taus, xfer, dinv=dinv)
    assert out is not None
    assert _rel(out, xr) < 1e-6


def test_transfer_parity_multiblock_and_chained():
    """Small VMEM budgets force the multi-block path (straddling
    aggregates complete in the per-block window combine) and the
    chained dispatch (plain fused chunks + the transfer chunk)."""
    A, agg, nc, b, x, dinv, xc = _problem(n=16, seed=2)
    taus = jnp.asarray(np.full(3, 0.8), jnp.float32)
    xr, rr = _ref_sweeps(A, b, x, taus, dinv)
    bc_ref = jax.ops.segment_sum(rr, jnp.asarray(agg), num_segments=nc)
    xr2, _ = _ref_sweeps(A, b, x + xc[jnp.asarray(agg)], taus, dinv)
    old = ps._SMOOTH_VMEM_BUDGET
    try:
        for budget in (400 * 1024, 300 * 1024):  # multi-block; chained
            ps._SMOOTH_VMEM_BUDGET = budget
            with ps.force_pallas_interpret():
                slabs = fused.build_fused_slabs(A, dinv)
                xfer = fused.build_transfer_slabs(A, agg, nc)
                data = {"A": A, "fused": slabs}
                xf, bcf = fused.fused_smooth_restrict(
                    data, b, x, taus, xfer, dinv=dinv)
                xf2 = fused.fused_corr_smooth(
                    data, b, x, xc, taus, xfer, dinv=dinv)
            assert _rel(xf, xr) < 1e-6
            assert _rel(bcf, bc_ref) < 1e-6
            assert _rel(xf2, xr2) < 1e-6
    finally:
        ps._SMOOTH_VMEM_BUDGET = old


def test_transfer_slab_fallback_parity_f64():
    """The XLA slab forms (what f64 and vmapped callers run) match the
    unfused reference to f64 accuracy."""
    from amgx_tpu.ops.batched import (corr_smooth_dia_multi,
                                      smooth_restrict_dia_multi)
    A, agg, nc, _, _, _, _ = _problem(n=8)      # f64 below
    A = gallery.poisson("7pt", 8, 8, 8).init()
    agg, nc = _geo_agg(8, 8, 8)
    n = A.num_rows
    rng = np.random.default_rng(3)
    B = jnp.asarray(rng.standard_normal((3, n)))
    X = jnp.asarray(rng.standard_normal((3, n)))
    XC = jnp.asarray(rng.standard_normal((3, nc)))
    dinv = jnp.asarray(1.0 / rng.uniform(4, 8, n))
    taus = jnp.asarray(np.full(2, 0.85))
    xfer = fused.build_transfer_slabs(A, agg, nc)
    assert xfer is not None
    XF, BCF = smooth_restrict_dia_multi(A, B, X, taus, dinv, xfer)
    XF2 = corr_smooth_dia_multi(A, B, X, XC, taus, dinv, xfer)
    for i in range(3):
        xr, rr = _ref_sweeps(A, B[i], X[i], taus, dinv)
        bc = jax.ops.segment_sum(rr, jnp.asarray(agg), num_segments=nc)
        assert _rel(XF[i], xr) < 1e-12
        assert _rel(BCF[i], bc) < 1e-12
        xr2, _ = _ref_sweeps(A, B[i], X[i] + XC[i][jnp.asarray(agg)],
                             taus, dinv)
        assert _rel(XF2[i], xr2) < 1e-12


def test_transfer_vmap_routes_to_slab():
    """Under jax.vmap (the batched-solve subsystem's shape) the fused
    transfer calls must take the multi-RHS slab forms and match
    per-system references — the single-RHS kernels have no batching
    rule."""
    A, agg, nc, _, _, dinv, _ = _problem(n=8, seed=4)
    n = A.num_rows
    rng = np.random.default_rng(4)
    B = jnp.asarray(rng.standard_normal((4, n)), jnp.float32)
    X = jnp.asarray(rng.standard_normal((4, n)), jnp.float32)
    XC = jnp.asarray(rng.standard_normal((4, nc)), jnp.float32)
    taus = jnp.asarray(np.full(2, 0.9), jnp.float32)
    with ps.force_pallas_interpret():
        slabs = fused.build_fused_slabs(A, dinv)
        xfer = fused.build_transfer_slabs(A, agg, nc)
        data = {"A": A, "fused": slabs}
        XF, BCF = jax.vmap(
            lambda bb, xx: fused.fused_smooth_restrict(
                data, bb, xx, taus, xfer, dinv=dinv))(B, X)
        XF2 = jax.vmap(
            lambda bb, xx, xcc: fused.fused_corr_smooth(
                data, bb, xx, xcc, taus, xfer, dinv=dinv))(B, X, XC)
    for i in range(4):
        xr, rr = _ref_sweeps(A, B[i], X[i], taus, dinv)
        bc = jax.ops.segment_sum(rr, jnp.asarray(agg), num_segments=nc)
        assert _rel(XF[i], xr) < 1e-6
        assert _rel(BCF[i], bc) < 1e-6
        xr2, _ = _ref_sweeps(A, B[i], X[i] + XC[i][jnp.asarray(agg)],
                             taus, dinv)
        assert _rel(XF2[i], xr2) < 1e-6


# ---------------------------------------------------------------------------
# cycle integration: kernel counts, tail, escape hatch
# ---------------------------------------------------------------------------

_CYCLE_CFG = (
    "solver(s)=PCG, s:max_iters=30, s:tolerance=1e-7,"
    " s:convergence=RELATIVE_INI, s:monitor_residual=1,"
    " s:preconditioner(amg)=AMG, amg:algorithm=AGGREGATION,"
    " amg:selector=GEO, amg:smoother=JACOBI_L1, amg:presweeps=2,"
    " amg:postsweeps=1, amg:max_iters=1,"
    " amg:coarse_solver=DENSE_LU_SOLVER, amg:min_coarse_rows=16,"
    " amg:max_levels=10")


def _trace_cycle(extra_cfg="", n=16):
    A = gallery.poisson("7pt", n, n, n, dtype=jnp.float32).init()
    b = jnp.ones(A.num_rows, jnp.float32)
    with ps.force_pallas_interpret():
        slv = amgx.create_solver(Config.from_string(_CYCLE_CFG
                                                    + extra_cfg))
        slv.setup(A)
        pc = slv.preconditioner
        d = pc.solve_data()
        jaxpr = jax.make_jaxpr(
            lambda bb, xx: pc.amg.cycle(d["amg"], bb, xx))(
                b, jnp.zeros_like(b))
    return pc.amg, jaxpr


# jaxpr census helpers shared across the fusion suites (tests/_census.py)
_kernel_counts = _census.kernel_counts
_outer_prims = _census.outer_prims


def test_jaxpr_proof_fused_cycle_kernel_budget():
    """HBM-pass proof: with the tail capped below L1, the fused GEO
    cycle runs EXACTLY two kernels for the smoothed fine level
    (presmooth+restrict, prolongate+postsmooth) and ONE kernel for the
    whole coarse tail — no standalone dia-SpMV passes, and zero
    standalone restrict / prolongate / correction ops (gather, scatter,
    interior pad) outside the kernels."""
    amg, jaxpr = _trace_cycle(", amg:cycle_fusion_tail_rows=600")
    assert len(amg.levels) == 2
    c = _kernel_counts(jaxpr)
    assert c.get("_dia_smooth_restrict_call", 0) == 1, c
    assert c.get("_dia_prolong_smooth_call", 0) == 1, c
    assert c.get("_dia_coarse_tail_call", 0) == 1, c
    assert c.get("_dia_smooth_call", 0) == 0, c
    assert c.get("_dia_spmv_call", 0) == 0, c
    outer = set(_outer_prims(jaxpr))
    # the unfused GEO transfers show up as interior pads (prolongation
    # broadcast) / gathers (generic aggregation) / scatter-adds
    # (segment-sum restriction); the fused trace must have none
    assert not outer & {"pad", "gather", "scatter-add", "scatter"}, \
        sorted(outer & {"pad", "gather", "scatter-add", "scatter"})


def test_jaxpr_proof_whole_cycle_tail():
    """With every level under the tail threshold the ENTIRE cycle is
    one pallas_call."""
    amg, jaxpr = _trace_cycle()
    c = _kernel_counts(jaxpr)
    assert c == {"_dia_coarse_tail_call": 1}, c


def test_cycle_fusion_off_restores_pr4_composition():
    """cycle_fusion=0 must trace the PR 4 composition exactly: two
    fused smoother kernels per level, zero transfer/tail kernels — and
    the same jaxpr as the fusion path's structural fallback (hooks
    returning None), proving the escape hatch IS the old code path."""
    amg, jaxpr = _trace_cycle(", amg:cycle_fusion=0")
    c = _kernel_counts(jaxpr)
    n_levels = len(amg.levels)
    assert c.get("_dia_smooth_call", 0) == 2 * n_levels
    assert c.get("_dia_smooth_restrict_call", 0) == 0
    assert c.get("_dia_prolong_smooth_call", 0) == 0
    assert c.get("_dia_coarse_tail_call", 0) == 0
    # structural fallback == knob off: force every hook to decline
    from amgx_tpu.amg.aggregation import AggregationAMGLevel
    old_r = AggregationAMGLevel.restrict_fused
    old_p = AggregationAMGLevel.prolongate_smooth
    try:
        AggregationAMGLevel.restrict_fused = lambda *a, **k: None
        AggregationAMGLevel.prolongate_smooth = lambda *a, **k: None
        amg2, jaxpr2 = _trace_cycle(", amg:cycle_fusion_tail_rows=0")
    finally:
        AggregationAMGLevel.restrict_fused = old_r
        AggregationAMGLevel.prolongate_smooth = old_p
    assert str(jaxpr2) == str(_trace_cycle(", amg:cycle_fusion=0")[1])


def test_classical_fused_cycle_matches_unfused():
    """Classical hierarchies now RIDE the fused hooks (ISSUE 12:
    weighted row-segment slabs — see tests/test_classical_fusion.py
    for the kernel-level proofs); this guards the integration from the
    aggregation suite's angle: the fused classical cycle solves to the
    same answer as the cycle_fusion=0 composition."""
    cfg = ("solver(s)=PCG, s:max_iters=40, s:tolerance=1e-7,"
           " s:convergence=RELATIVE_INI, s:monitor_residual=1,"
           " s:preconditioner(amg)=AMG, amg:algorithm=CLASSICAL,"
           " amg:smoother=JACOBI_L1, amg:max_iters=1,"
           " amg:coarse_solver=DENSE_LU_SOLVER")
    A = gallery.poisson("7pt", 8, 8, 8, dtype=jnp.float32).init()
    b = jnp.ones(A.num_rows, jnp.float32)
    with ps.force_pallas_interpret():
        s1 = amgx.create_solver(Config.from_string(cfg))
        s1.setup(A)
        r1 = s1.solve(b)
    s0 = amgx.create_solver(Config.from_string(cfg
                                               + ", amg:cycle_fusion=0"))
    s0.setup(A)
    r0 = s0.solve(b)
    assert r1.converged and r0.converged
    assert abs(int(r1.iterations) - int(r0.iterations)) <= 1


# ---------------------------------------------------------------------------
# coarse tail: parity + shapes
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("cycle", ["V", "W", "F"])
def test_tail_cycle_matches_per_level_composition(cycle):
    """The VMEM-resident tail kernel reproduces the per-level fused
    composition (same hierarchy, tail disabled) to f32 accuracy for
    every fixed cycle shape."""
    A = gallery.poisson("7pt", 12, 12, 12, dtype=jnp.float32).init()
    b = jnp.ones(A.num_rows, jnp.float32)
    base = _CYCLE_CFG + f", amg:cycle={cycle}"
    with ps.force_pallas_interpret():
        s_tail = amgx.create_solver(Config.from_string(base))
        s_tail.setup(A)
        r_tail = s_tail.solve(b)
        s_lvl = amgx.create_solver(Config.from_string(
            base + ", amg:cycle_fusion_tail_rows=0"))
        s_lvl.setup(A)
        r_lvl = s_lvl.solve(b)
    assert r_tail.converged and r_lvl.converged
    assert abs(int(r_tail.iterations) - int(r_lvl.iterations)) <= 1
    assert _rel(r_tail.x, r_lvl.x) < 1e-4


def test_tail_respects_row_threshold():
    """cycle_fusion_tail_rows gates the tail entry level."""
    amg, jaxpr = _trace_cycle(", amg:cycle_fusion_tail_rows=0")
    c = _kernel_counts(jaxpr)
    assert c.get("_dia_coarse_tail_call", 0) == 0
    assert c.get("_dia_smooth_restrict_call", 0) == len(amg.levels)


def test_cheb_tail_and_transfers_end_to_end():
    """Flagship-shaped smoother (CHEBYSHEV_POLY, no dinv) through the
    fused cycle: converges to the unfused answer."""
    cfg = (_CYCLE_CFG.replace("amg:smoother=JACOBI_L1",
                              "amg:smoother=CHEBYSHEV_POLY,"
                              " amg:chebyshev_polynomial_order=2"))
    A = gallery.poisson("7pt", 12, 12, 12, dtype=jnp.float32).init()
    b = jnp.ones(A.num_rows, jnp.float32)
    ref = amgx.create_solver(Config.from_string(
        cfg + ", amg:cycle_fusion=0, amg:fused_smoother=0"))
    ref.setup(A)
    r0 = ref.solve(b)
    with ps.force_pallas_interpret():
        slv = amgx.create_solver(Config.from_string(cfg))
        slv.setup(A)
        r1 = slv.solve(b)
    assert r1.converged
    assert abs(int(r1.iterations) - int(r0.iterations)) <= 1
    assert _rel(r1.x, r0.x) < 1e-4


# ---------------------------------------------------------------------------
# lifecycle: no-retrace, resetup, memoization
# ---------------------------------------------------------------------------


def test_fused_cycle_does_not_retrace():
    A = gallery.poisson("7pt", 12, 12, 12, dtype=jnp.float32).init()
    n = A.num_rows
    rng = np.random.default_rng(6)
    with ps.force_pallas_interpret():
        slv = amgx.create_solver(Config.from_string(_CYCLE_CFG))
        slv.setup(A)
        r1 = slv.solve(jnp.asarray(rng.standard_normal(n), jnp.float32))
        assert len(slv._jit_cache) == 1
        r2 = slv.solve(jnp.asarray(rng.standard_normal(n), jnp.float32))
        assert len(slv._jit_cache) == 1, \
            "fused cycle retraced on a value-only change of b"
        assert r1.converged and r2.converged


def test_transfer_slabs_memoized_and_resetup_refreshes():
    """level_data() serves one TransferSlabs object per level build
    (structure-only payload); a structure-reuse resetup builds new
    level objects and fresh slabs, and the resetup solve still matches
    the unfused answer."""
    A = gallery.poisson("7pt", 12, 12, 12, dtype=jnp.float32).init()
    b = jnp.ones(A.num_rows, jnp.float32)
    with ps.force_pallas_interpret():
        slv = amgx.create_solver(Config.from_string(
            _CYCLE_CFG + ", amg:structure_reuse_levels=-1"))
        slv.setup(A)
        lv0 = slv.preconditioner.amg.levels[0]
        x1 = lv0._transfer_slabs()
        assert x1 is not None
        assert lv0._transfer_slabs() is x1, "xfer slab memo broken"
        slv.solve(b)
        A2 = A.with_values(A.values * 2.0)
        slv.resetup(A2 if A2.initialized else A2.init())
        r2 = slv.solve(b)
    ref = amgx.create_solver(Config.from_string(
        _CYCLE_CFG + ", amg:cycle_fusion=0, amg:fused_smoother=0"))
    A2r = A.with_values(A.values * 2.0)
    ref.setup(A2r if A2r.initialized else A2r.init())
    r0 = ref.solve(b)
    assert r2.converged
    assert abs(int(r2.iterations) - int(r0.iterations)) <= 1
    assert _rel(r2.x, r0.x) < 1e-4


def test_value_resetup_keeps_fused_cycle_correct():
    """The one-dispatch value-only resetup (amg/value_resetup.py, the
    flagship/northstar production path: GEO + CHEBYSHEV_POLY +
    DENSE_LU) splices new coefficients under the fused cycle: the
    structure-only transfer slabs are reused, the coarse inverse
    refreshes from the new QR factors, and the resetup solve matches
    an unfused fresh setup."""
    cfg = (_CYCLE_CFG.replace("amg:smoother=JACOBI_L1",
                              "amg:smoother=CHEBYSHEV_POLY,"
                              " amg:chebyshev_polynomial_order=2")
           + ", amg:structure_reuse_levels=-1")
    A = gallery.poisson("7pt", 12, 12, 12, dtype=jnp.float32).init()
    b = jnp.ones(A.num_rows, jnp.float32)
    with ps.force_pallas_interpret():
        slv = amgx.create_solver(Config.from_string(cfg))
        slv.setup(A)
        amg = slv.preconditioner.amg
        x1 = amg.levels[0]._transfer_slabs()
        slv.solve(b)
        A2 = A.with_values(A.values * 1.5)
        slv.resetup(A2 if A2.initialized else A2.init())
        assert amg._last_resetup_value_only, \
            "value-only resetup did not engage on the GEO/Cheb shape"
        assert amg.levels[0]._transfer_slabs() is x1, \
            "structure-only slabs rebuilt on a value-only resetup"
        r2 = slv.solve(b)
    ref = amgx.create_solver(Config.from_string(
        cfg + ", amg:cycle_fusion=0, amg:fused_smoother=0"))
    A2r = A.with_values(A.values * 1.5)
    ref.setup(A2r if A2r.initialized else A2r.init())
    r0 = ref.solve(b)
    assert r2.converged
    assert abs(int(r2.iterations) - int(r0.iterations)) <= 1
    assert _rel(r2.x, r0.x) < 1e-4


def test_solve_many_fused_cycle_parity():
    """solve_many drives the fused cycle under vmap: the custom_vmap
    rules must land in the slab forms and match per-system solves."""
    A = gallery.poisson("7pt", 12, 12, 12, dtype=jnp.float32).init()
    n = A.num_rows
    rng = np.random.default_rng(8)
    Bs = jnp.asarray(rng.standard_normal((3, n)), jnp.float32)
    with ps.force_pallas_interpret():
        slv = amgx.create_solver(Config.from_string(_CYCLE_CFG))
        slv.setup(A)
        res = slv.solve_many(Bs)
        singles = [slv.solve(Bs[i]).x for i in range(3)]
    for i in range(3):
        assert _rel(res.x[i], singles[i]) < 1e-5
