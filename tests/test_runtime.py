"""L0 runtime components: Resources, memory info, thread manager /
async setup, signal handler (reference analogs: include/resources.h:21,
include/memory_info.h:33, src/thread_manager.cu + amg_level.h:25-39,
src/amg_signal.cu)."""
import numpy as np
import jax.numpy as jnp
import pytest

import amgx_tpu as amgx
from amgx_tpu import capi, gallery, memory_info, thread_manager
from amgx_tpu.config import Config
from amgx_tpu.errors import AMGXError, RC

amgx.initialize()


class TestResources:
    def test_device_selection_and_platform(self):
        import jax
        rs = amgx.Resources()
        assert rs.num_devices == len(jax.devices())
        assert rs.platform in ("cpu", "tpu")
        with rs.device_context():
            x = jnp.ones(4)
        assert list(x.devices())[0] == rs.device
        rs1 = amgx.Resources(device_num=min(1, rs.num_devices - 1))
        assert rs1.device == jax.devices()[min(1, rs.num_devices - 1)]
        # explicit ordinal list restricts ownership
        rs2 = amgx.Resources(devices=[0])
        assert rs2.num_devices == 1

    def test_bad_device_num_rejected(self):
        with pytest.raises(AMGXError):
            amgx.Resources(device_num=99)

    def test_mesh(self):
        rs = amgx.Resources()
        mesh = rs.mesh(8)
        assert mesh.devices.size == 8
        with pytest.raises(AMGXError):
            rs.mesh(4096)

    def test_capi_resources_surface(self):
        rc, cfg_h = capi.AMGX_config_create("solver=CG, max_iters=5")
        assert rc == RC.OK
        rc, rsrc = capi.AMGX_resources_create(cfg_h, None, 0, None)
        assert rc == RC.OK
        rc, cur, peak = capi.AMGX_resources_get_memory_usage(rsrc)
        assert rc == RC.OK and peak >= cur >= 0
        assert capi.AMGX_resources_destroy(rsrc) == RC.OK
        assert capi.AMGX_config_destroy(cfg_h) == RC.OK


class TestMemoryInfo:
    def test_high_water_mark_monotone(self):
        memory_info.reset()
        a = memory_info.update_max_memory_usage()
        peak = memory_info.get_max_memory_usage()
        assert peak >= a >= 0
        assert memory_info.get_memory_usage_gb() >= 0.0


class TestAsyncSetup:
    def test_async_setup_matches_sync(self):
        A = gallery.poisson("7pt", 8, 8, 8).init()
        b = jnp.ones(A.num_rows)
        cfg = Config.from_string(
            "solver=FGMRES, max_iters=40, monitor_residual=1,"
            " tolerance=1e-8, gmres_n_restart=10,"
            " preconditioner(amg)=AMG, amg:algorithm=AGGREGATION,"
            " amg:selector=GEO, amg:smoother=BLOCK_JACOBI,"
            " amg:max_iters=1, amg:cycle=V")
        ref = amgx.create_solver(cfg)
        ref.setup(A)
        r_ref = ref.solve(b)

        slv = amgx.create_solver(cfg)
        task = slv.setup_async(A)
        assert task.wait() is slv
        assert task.done()
        res = slv.solve(b)
        assert res.converged == r_ref.converged
        assert res.iterations == r_ref.iterations

    def test_async_setup_propagates_errors(self):
        slv = amgx.create_solver(Config.from_string(
            "solver=REFINEMENT, max_iters=5, preconditioner=NOSOLVER"))
        task = slv.setup_async(gallery.poisson("5pt", 6, 6).init())
        with pytest.raises(AMGXError):
            task.wait()

    def test_parallel_setups(self):
        As = [gallery.poisson("5pt", 10 + i, 10).init() for i in range(3)]
        cfg = Config.from_string("solver=BLOCK_JACOBI, max_iters=4")
        solvers = [amgx.create_solver(cfg) for _ in As]
        tasks = [s.setup_async(A) for s, A in zip(solvers, As)]
        for t in tasks:
            t.wait()
        for s, A in zip(solvers, As):
            res = s.solve(jnp.ones(A.num_rows))
            assert np.all(np.isfinite(np.asarray(res.x)))


def test_signal_handler_install_reset():
    import faulthandler
    assert capi.AMGX_install_signal_handler() == RC.OK
    assert faulthandler.is_enabled()
    assert capi.AMGX_reset_signal_handler() == RC.OK
    assert not faulthandler.is_enabled()


class TestAttachGeometry:
    """AMGX_matrix_attach_geometry (src/amgx_c.cu:3143): coordinates of
    a lexicographic structured grid collapse to the grid_shape
    annotation the GEO selector consumes."""

    def _upload(self, A):
        rc, cfg_h = capi.AMGX_config_create("solver=CG, max_iters=5")
        rc, rsrc = capi.AMGX_resources_create(cfg_h, None, 0, None)
        rc, mtx = capi.AMGX_matrix_create(rsrc, "dDDI")
        assert capi.AMGX_matrix_upload_all(
            mtx, A.num_rows, A.nnz, 1, 1, np.asarray(A.row_offsets),
            np.asarray(A.col_indices), np.asarray(A.values)) == RC.OK
        return mtx

    @staticmethod
    def _coords(nx, ny, nz):
        ix, iy, iz = np.meshgrid(np.arange(nx), np.arange(ny),
                                 np.arange(nz), indexing="ij")
        order = np.argsort(((iz * ny + iy) * nx + ix).ravel())
        return (ix.ravel()[order].astype(float),
                iy.ravel()[order].astype(float),
                iz.ravel()[order].astype(float))

    def test_attach_sets_grid_shape(self):
        A = gallery.poisson("7pt", 6, 5, 4)
        mtx = self._upload(A)
        gx, gy, gz = self._coords(6, 5, 4)
        assert capi.AMGX_matrix_attach_geometry(mtx, gx, gy, gz,
                                                A.num_rows) == RC.OK
        assert capi._get(mtx, capi._CMatrix).A.grid_shape == (6, 5, 4)

    def test_attach_rejects_non_grid(self):
        A = gallery.poisson("5pt", 4, 4)
        mtx = self._upload(A)
        rng = np.random.default_rng(0)
        gx = rng.random(16); gy = rng.random(16)
        assert capi.AMGX_matrix_attach_geometry(mtx, gx, gy) != RC.OK

    def test_attach_rejects_wrong_order(self):
        A = gallery.poisson("5pt", 4, 4)
        mtx = self._upload(A)
        gx, gy, gz = self._coords(4, 4, 1)
        # y-fastest ordering: not the layout grid_shape asserts
        assert capi.AMGX_matrix_attach_geometry(
            mtx, gy, gx, gz, A.num_rows) != RC.OK
