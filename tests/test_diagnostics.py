"""Convergence diagnostics, histogram metrics + OpenMetrics, and the
bench-regression sentinel (the observability PR's acceptance contracts):

- the diagnostics probe's per-level stage norms match a MANUALLY
  composed cycle on the same hierarchy (the recorded numbers are the
  cycle's real arithmetic, not an estimate);
- `diagnostics=0` emits a jaxpr IDENTICAL to a build that never heard
  of the knob, and `diagnostics=1` leaves the solve itself untouched
  (same iterates, same iteration count — the probe is appended, not
  interleaved);
- the probe works at the flagship's nesting depth (REFINEMENT ->
  FGMRES -> AMG) and the report names a bottleneck level;
- `grid_stats_dict()` is the single source of truth the text report
  renders from, feeds `SolveReport.hierarchy`, and is reachable from
  the C API;
- histogram bucket/quantile arithmetic is exact on known samples;
  labels split series; snapshots include histograms;
- the OpenMetrics exposition parses under the format's line grammar,
  has monotone cumulative buckets, and terminates with `# EOF`;
- `tools/bench_history.py` flags a seeded synthetic regression (exit
  nonzero, offending metric named), flags the known r05 warm-setup
  regression over copies of the checked-in artifacts, and its --smoke
  self-check passes on well-formed artifacts / fails on malformed ones.
"""
import json
import os
import re
import shutil
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import amgx_tpu as amgx
from amgx_tpu import gallery, output
from amgx_tpu.config import Config
from amgx_tpu.errors import RC
from amgx_tpu.telemetry import diagnostics, metrics, validate_report

amgx.initialize()

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH_HISTORY = os.path.join(REPO, "tools", "bench_history.py")

AMG_PCG = (
    "solver(s)=PCG, s:max_iters=60, s:tolerance=1e-8,"
    " s:convergence=RELATIVE_INI, s:monitor_residual=1,"
    " s:preconditioner(amg)=AMG, amg:algorithm=AGGREGATION,"
    " amg:selector=SIZE_2, amg:smoother(sm)=JACOBI_L1, sm:max_iters=1,"
    " amg:presweeps=1, amg:postsweeps=1, amg:max_iters=1,"
    " amg:coarse_solver=DENSE_LU_SOLVER, amg:min_coarse_rows=16,"
    " amg:max_levels=10")

FLAGSHIP_SHAPE = (
    "solver=REFINEMENT, max_iters=15, monitor_residual=1,"
    " tolerance=1e-9, convergence=RELATIVE_INI,"
    " preconditioner(in)=FGMRES, in:max_iters=20,"
    " in:monitor_residual=1, in:tolerance=1e-5, in:gmres_n_restart=10,"
    " in:convergence=RELATIVE_INI, in:preconditioner(amg)=AMG,"
    " amg:algorithm=AGGREGATION, amg:selector=SIZE_2,"
    " amg:smoother(sm)=JACOBI_L1, sm:max_iters=1, amg:presweeps=1,"
    " amg:postsweeps=1, amg:max_iters=1, amg:cycle=V,"
    " amg:min_coarse_rows=16, amg:max_levels=10")


@pytest.fixture(scope="module")
def poisson16():
    return gallery.poisson("5pt", 16, 16).init()


@pytest.fixture(scope="module")
def poisson10_3d():
    return gallery.poisson("7pt", 10, 10, 10).init()


def _solve(cfg_str, A, b=None):
    slv = amgx.create_solver(Config.from_string(cfg_str))
    slv.setup(A)
    if b is None:
        b = jnp.ones(A.num_rows)
    return slv, slv.solve(b)


# ---------------------------------------------------------------------------
# diagnostics probe
# ---------------------------------------------------------------------------


def test_diagnostics_report_present_and_schema_valid(poisson16):
    slv, res = _solve(AMG_PCG + ", diagnostics=1", poisson16)
    d = res.report.diagnostics
    assert d is not None
    assert d["stages"] == list(diagnostics.STAGES)
    amg = slv.preconditioner.amg
    assert len(d["levels"]) == len(amg.levels)
    assert d["bottleneck_level"] is not None
    assert 0 <= d["bottleneck_level"] < len(amg.levels)
    for row in d["levels"]:
        for k in ("entry_norm", "post_presmooth_norm",
                  "post_correction_norm", "post_postsmooth_norm",
                  "level_reduction", "smoother_effectiveness"):
            assert row[k] is not None and row[k] > 0
    acf = d["asymptotic_convergence_factor"]
    assert acf is not None and 0 < acf < 1   # the solve converged
    # the whole report (hierarchy + diagnostics blocks included)
    # validates against the checked-in schema
    assert validate_report(res.report.to_dict()) == []


def _manual_stage_norms(amg, data, b, x0):
    """A hand-composed V-cycle recording the probe's stage norms with
    the hierarchy's own pieces — the parity reference for the in-trace
    recorder."""
    from amgx_tpu.amg.cycles import _coarse_solve
    from amgx_tpu.ops.spmv import residual

    norms = {}

    def l2(v):
        return float(jnp.sqrt(jnp.sum(v * v)))

    def rec(lvl, b, x):
        if lvl == len(amg.levels):
            return _coarse_solve(amg, data, b, x)
        level = amg.levels[lvl]
        ld = data["levels"][lvl]
        A = ld["A"]
        norms[(lvl, 0)] = l2(residual(A, x, b))
        x = level.smoother.smooth(ld["smoother"], b, x,
                                  amg._sweeps(lvl, pre=True))
        r = residual(A, x, b)
        norms[(lvl, 1)] = l2(r)
        bc = level.restrict(ld, r)
        xc = rec(lvl + 1, bc, jnp.zeros_like(bc))
        x = x + level.prolongate(ld, xc)
        norms[(lvl, 2)] = l2(residual(A, x, b))
        x = level.smoother.smooth(ld["smoother"], b, x,
                                  amg._sweeps(lvl, pre=False))
        norms[(lvl, 3)] = l2(residual(A, x, b))
        return x

    rec(0, b, x0)
    return norms


def test_per_level_reduction_parity_vs_manual_cycle(poisson10_3d):
    """The recorded stage norms ARE the cycle's arithmetic: a manually
    composed V-cycle on the final residual reproduces every per-level
    stage norm (and hence every derived reduction factor)."""
    A = poisson10_3d
    b = jnp.ones(A.num_rows)
    slv, res = _solve(AMG_PCG + ", diagnostics=1", A, b)
    amg = slv.preconditioner.amg
    assert len(amg.levels) >= 2        # multi-level parity, not 1-level
    d = res.report.diagnostics
    from amgx_tpu.ops.spmv import residual
    r_fin = residual(A, res.x, b)
    pb = r_fin.astype(amg.levels[0].A.values.dtype)
    manual = _manual_stage_norms(amg, amg.solve_data(), pb,
                                 jnp.zeros_like(pb))
    for lvl, row in enumerate(d["levels"]):
        for st, key in enumerate(("entry_norm", "post_presmooth_norm",
                                  "post_correction_norm",
                                  "post_postsmooth_norm")):
            assert row[key] == pytest.approx(
                manual[(lvl, st)], rel=1e-5), (lvl, key)
    # derived factors follow from the norms they divide
    row0 = d["levels"][0]
    assert row0["level_reduction"] == pytest.approx(
        manual[(0, 3)] / manual[(0, 0)], rel=1e-5)


def test_diagnostics_off_jaxpr_identical(poisson16):
    """diagnostics=0 must compile to a jaxpr identical to a pre-PR
    solve (the knob-off path never touches the trace) — the PR-7-style
    zero-overhead proof, which doubles as the overhead gate."""
    b = jnp.ones(poisson16.num_rows)
    jaxprs = {}
    for tag, cfg in (("unset", AMG_PCG),
                     ("off", AMG_PCG + ", diagnostics=0"),
                     ("on", AMG_PCG + ", diagnostics=1")):
        slv = amgx.create_solver(Config.from_string(cfg))
        slv.setup(poisson16)
        jaxprs[tag] = str(jax.make_jaxpr(slv._build_solve_fn())(
            slv.solve_data(), b, jnp.zeros_like(b)))
    assert jaxprs["unset"] == jaxprs["off"]
    assert jaxprs["on"] != jaxprs["off"]   # the probe IS in the trace


def test_diagnostics_probe_leaves_solve_untouched(poisson16):
    """The probe is appended AFTER the while_loop: the solve's
    iterates, iteration count and residual norms are bit-identical
    with the knob on vs off."""
    b = jnp.ones(poisson16.num_rows)
    _s0, r0 = _solve(AMG_PCG + ", diagnostics=0", poisson16, b)
    _s1, r1 = _solve(AMG_PCG + ", diagnostics=1", poisson16, b)
    assert r0.iterations == r1.iterations
    assert float(r0.res_norm) == float(r1.res_norm)
    np.testing.assert_array_equal(np.asarray(r0.x), np.asarray(r1.x))


def test_diagnostics_stats_packing_layout(poisson16):
    """The packed stats gain exactly 4*num_levels trailing slots with
    the knob on — and the host-side strip recovers the bare layout
    (history length, iteration count) exactly."""
    b = jnp.ones(poisson16.num_rows)
    slv0 = amgx.create_solver(Config.from_string(AMG_PCG))
    slv1 = amgx.create_solver(Config.from_string(
        AMG_PCG + ", diagnostics=1"))
    slv0.setup(poisson16)
    slv1.setup(poisson16)
    _x0, st0 = jax.jit(slv0._build_solve_fn())(
        slv0.solve_data(), b, jnp.zeros_like(b))
    _x1, st1 = jax.jit(slv1._build_solve_fn())(
        slv1.solve_data(), b, jnp.zeros_like(b))
    n_levels = len(slv1.preconditioner.amg.levels)
    assert st1.shape[0] == st0.shape[0] + 4 * n_levels
    res = slv1.solve(b)
    assert len(res.report.residuals) == res.iterations + 1


def test_flagship_shaped_nested_diagnostics(poisson10_3d):
    """The probe reaches an AMG nested two preconditioner levels deep
    (REFINEMENT -> FGMRES -> AMG, the flagship shape, with the
    hierarchy living in the inner f32 tree) and the report names a
    bottleneck level with per-level reduction factors."""
    slv, res = _solve(FLAGSHIP_SHAPE + ", amg:diagnostics=1",
                      poisson10_3d)
    assert res.converged
    d = res.report.diagnostics
    assert d is not None
    assert d["bottleneck_level"] is not None
    assert all(r["level_reduction"] is not None for r in d["levels"])
    # the inner hierarchy is f32 (built against REFINEMENT's A32):
    # the probe cast the f64 outer residual down to run the cycle
    amg = slv.preconditioner.preconditioner.amg
    assert amg.levels[0].A.values.dtype == jnp.float32
    assert len(d["levels"]) == len(amg.levels)


def test_diagnostics_batched_path_unaffected(poisson16):
    """solve_many builds its vmapped fn with diag=False: a
    diagnostics=1 solver still serves batched solves with the bare
    stats layout (no misparsed iteration counts)."""
    slv = amgx.create_solver(Config.from_string(
        AMG_PCG + ", diagnostics=1, amg:structure_reuse_levels=-1"))
    slv.setup(poisson16)
    rng = np.random.default_rng(3)
    B = jnp.asarray(rng.standard_normal((3, poisson16.num_rows)))
    res = slv.solve_many(B)
    assert res.all_converged
    assert int(np.max(res.iterations)) < 60


# ---------------------------------------------------------------------------
# grid stats: one source of truth
# ---------------------------------------------------------------------------


def test_grid_stats_dict_and_text_render(poisson16):
    slv, res = _solve(AMG_PCG, poisson16)
    amg = slv.preconditioner.amg
    d = amg.grid_stats_dict()
    assert d["num_levels"] == len(amg.levels) + 1
    assert d["levels"][0]["rows"] == poisson16.num_rows
    assert d["grid_complexity"] >= 1.0
    assert d["operator_complexity"] >= 1.0
    assert sum(r["rows"] for r in d["levels"]) == d["total_rows"]
    for row in d["levels"]:
        assert row["layout"] in ("dia", "ell", "swell", "csr")
    # the text report renders FROM the dict (same numbers, same count)
    text = amg.grid_stats()
    assert f"Number of Levels: {d['num_levels']}" in text
    assert f"{d['grid_complexity']:.5g}" in text
    assert f"{d['operator_complexity']:.5g}" in text
    # and the standard report carries the dict
    assert res.report.hierarchy == d


def test_grid_stats_capi_getter(poisson16):
    from amgx_tpu import capi
    assert capi.AMGX_initialize() == RC.OK
    try:
        rc, cfg = capi.AMGX_config_create(AMG_PCG)
        rc, rsrc = capi.AMGX_resources_create_simple(cfg)
        rc, Ah = capi.AMGX_matrix_create(rsrc, "dDDI")
        rc, slv = capi.AMGX_solver_create(rsrc, "dDDI", cfg)
        n = poisson16.num_rows
        assert capi.AMGX_matrix_upload_all(
            Ah, n, poisson16.nnz, 1, 1,
            np.asarray(poisson16.row_offsets),
            np.asarray(poisson16.col_indices),
            np.asarray(poisson16.values)) == RC.OK
        # before setup: BAD_PARAMETERS, not a crash
        rc, d = capi.AMGX_solver_get_grid_stats(slv)
        assert rc == RC.BAD_PARAMETERS and d is None
        assert capi.AMGX_solver_setup(slv, Ah) == RC.OK
        rc, d = capi.AMGX_solver_get_grid_stats(slv)
        assert rc == RC.OK
        assert d["levels"][0]["rows"] == n
        assert d["operator_complexity"] >= 1.0
    finally:
        capi.AMGX_finalize()


# ---------------------------------------------------------------------------
# histogram metrics
# ---------------------------------------------------------------------------


def test_histogram_buckets_and_quantiles():
    metrics.reset()
    name = "serving.solve_latency_s"
    edges = metrics.HISTOGRAM_EDGES[name]
    # one sample per chosen bucket, with exact le-boundary semantics:
    # a sample EQUAL to an edge lands in that edge's bucket
    metrics.observe(name, edges[0])            # bucket 0 (le first)
    metrics.observe(name, edges[0] * 0.5)      # bucket 0
    metrics.observe(name, 0.3)                 # 0.25 < 0.3 <= 0.5
    metrics.observe(name, 1e9)                 # overflow bucket
    snap = metrics.snapshot()[name]
    assert snap["count"] == 4
    assert snap["counts"][0] == 2
    assert snap["counts"][list(edges).index(0.5)] == 1
    assert snap["counts"][-1] == 1
    assert snap["sum"] == pytest.approx(edges[0] * 1.5 + 0.3 + 1e9)
    # quantiles interpolate within the holding bucket and saturate at
    # the declared range for the overflow bucket
    assert 0 < metrics.quantile(name, 0.25) <= edges[0]
    assert 0.25 <= metrics.quantile(name, 0.74) <= 0.5
    assert metrics.quantile(name, 0.999) == edges[-1]
    # empty histogram: None, not a crash
    assert metrics.quantile("serving.queue_wait_s", 0.5) is None


def test_histogram_labels_split_series():
    metrics.reset()
    name = "serving.solve_latency_s"
    for v in (0.002, 0.004, 0.008):
        metrics.observe(name, v, labels={"tenant": "hot"})
    metrics.observe(name, 40.0, labels={"tenant": "cold"})
    snap = metrics.snapshot()
    assert snap[name]["count"] == 4                 # merged
    assert snap[name + '{tenant="hot"}']["count"] == 3
    assert snap[name + '{tenant="cold"}']["count"] == 1
    # per-label quantile vs the aggregate
    assert metrics.quantile(name, 0.5,
                            labels={"tenant": "hot"}) <= 0.01
    assert metrics.quantile(name, 0.99) > 1.0       # cold outlier


def test_histogram_undeclared_raises_did_you_mean():
    with pytest.raises(KeyError, match="did you mean"):
        metrics.observe("serving.solve_latency", 1.0)
    with pytest.raises(ValueError):
        metrics.declare_histogram("tmp.bad_edges", "x", (1.0, 1.0))
    # get() understands histograms too (merged snapshot entry), and
    # its did-you-mean pool covers the histogram catalog
    metrics.reset()
    metrics.observe("serving.queue_wait_s", 0.02)
    assert metrics.get("serving.queue_wait_s")["count"] == 1
    with pytest.raises(KeyError, match="did you mean"):
        metrics.get("serving.queue_wait")


def test_openmetrics_escapes_label_quotes():
    """A caller-provided tenant id containing a double quote must not
    break the whole scrape payload's grammar."""
    metrics.reset()
    metrics.observe("serving.solve_latency_s", 0.01,
                    labels={"tenant": 'acme"prod'})
    text = metrics.to_openmetrics()
    assert 'tenant="acme\\"prod"' in text
    for ln in text.rstrip("\n").split("\n"):
        assert ln == "# EOF" or _OM_META.match(ln) \
            or _OM_SAMPLE.match(ln), ln


def test_snapshot_and_emit_include_histograms(poisson16):
    """Satellite contract: histogram snapshots appear in
    metrics.snapshot() (stable key set — empty ones included) and ride
    report.emit(include_counters=True)."""
    metrics.reset()
    snap = metrics.snapshot()
    assert snap["serving.solve_latency_s"]["count"] == 0
    assert snap["serving.solve_latency_s"]["edges"] == \
        list(metrics.HISTOGRAM_EDGES["serving.solve_latency_s"])
    metrics.observe("serving.queue_wait_s", 0.01)
    _slv, res = _solve(AMG_PCG, poisson16)
    lines = []
    output.register_print_callback(lambda msg, _n: lines.append(msg))
    try:
        res.report.emit(include_counters=True)
    finally:
        output.register_print_callback(None)
    doc = json.loads("".join(lines))
    counters = doc["amgx_report"]["counters"]
    assert counters["serving.queue_wait_s"]["count"] == 1


# ---------------------------------------------------------------------------
# OpenMetrics exposition
# ---------------------------------------------------------------------------

_OM_META = re.compile(
    r"^# (HELP|TYPE|UNIT) [a-zA-Z_:][a-zA-Z0-9_:]*( .*)?$")
_OM_LABEL_VALUE = r'"(?:[^"\\\n]|\\.)*"'   # escaped quotes allowed
_OM_SAMPLE = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*'
    r'(\{[a-zA-Z_][a-zA-Z0-9_]*=' + _OM_LABEL_VALUE +
    r'(,[a-zA-Z_][a-zA-Z0-9_]*=' + _OM_LABEL_VALUE + r')*\})?'
    r' (-?[0-9]+(\.[0-9]+)?([eE][+-]?[0-9]+)?|\+Inf|-Inf|NaN)$')


def test_openmetrics_wellformed():
    metrics.reset()
    metrics.inc("serving.requests", 2)
    metrics.set_gauge("serving.queue_depth", 1)
    for v in (0.003, 0.2, 3.0):
        metrics.observe("serving.solve_latency_s", v,
                        labels={"tenant": "t1"})
    text = metrics.to_openmetrics()
    assert text.endswith("# EOF\n")
    lines = text.rstrip("\n").split("\n")
    assert lines[-1] == "# EOF"
    for ln in lines[:-1]:
        assert _OM_META.match(ln) or _OM_SAMPLE.match(ln), ln
    # counters expose as <name>_total; the registry names are dotted,
    # the exposition's are underscored under the amgx_ namespace
    assert "amgx_serving_requests_total 2" in lines
    assert "amgx_serving_queue_depth 1" in lines
    # histogram grammar: cumulative non-decreasing buckets, +Inf ==
    # count, sum/count present per label set
    bucket = re.compile(
        r'^amgx_serving_solve_latency_s_bucket\{tenant="t1",'
        r'le="([^"]+)"\} (\d+)$')
    cums = [int(m.group(2)) for ln in lines
            for m in [bucket.match(ln)] if m]
    assert cums == sorted(cums) and cums[-1] == 3
    assert 'amgx_serving_solve_latency_s_count{tenant="t1"} 3' in lines
    # TYPE metadata names the right family kinds
    assert "# TYPE amgx_serving_requests counter" in lines
    assert "# TYPE amgx_serving_queue_depth gauge" in lines
    assert "# TYPE amgx_serving_solve_latency_s histogram" in lines


def test_openmetrics_capi():
    from amgx_tpu import capi
    assert capi.AMGX_initialize() == RC.OK
    try:
        rc, text = capi.AMGX_read_metrics_openmetrics()
        assert rc == RC.OK
        assert text.endswith("# EOF\n")
        assert "amgx_amg_setup_full_total" in text
    finally:
        capi.AMGX_finalize()


def test_serving_latency_histograms_wired():
    """The service records per-tenant solve-latency and queue-wait
    samples, and stats() reports live p50/p99."""
    from amgx_tpu.presets import BATCHED_CG
    from amgx_tpu.serving import SolveService
    metrics.reset()
    A = gallery.poisson("5pt", 8, 8).init()
    svc = SolveService(Config.from_string(
        BATCHED_CG + ", serving_bucket_slots=2, serving_chunk_iters=8"))
    rng = np.random.default_rng(2)
    tickets = [svc.submit(A, rng.standard_normal(A.num_rows),
                          tenant="hot") for _ in range(3)]
    svc.drain(timeout_s=300)
    assert all(t.done for t in tickets)
    snap = metrics.snapshot()
    assert snap["serving.solve_latency_s"]["count"] == 3
    assert snap['serving.solve_latency_s{tenant="hot"}']["count"] == 3
    assert snap["serving.queue_wait_s"]["count"] == 3
    st = svc.stats()
    assert st["solve_latency_p50_s"] is not None
    assert st["solve_latency_p99_s"] >= st["solve_latency_p50_s"]
    assert st["queue_wait_p50_s"] is not None


# ---------------------------------------------------------------------------
# bench-regression sentinel
# ---------------------------------------------------------------------------


def _wrapper(n, extra, parsed=True, tail_extra=""):
    payload = {"schema_version": 2, "round": n,
               "metric": "m", "value": 1.0, "unit": "s",
               "vs_baseline": 0.0, "extra": extra}
    w = {"n": n, "cmd": "bench", "rc": 0,
         "tail": tail_extra or json.dumps(payload),
         "parsed": payload if parsed else None}
    return w


def _run_history(args):
    return subprocess.run(
        [sys.executable, BENCH_HISTORY] + args,
        capture_output=True, text=True, timeout=120)


def test_sentinel_flags_synthetic_regression(tmp_path):
    """Seed a two-round history where the tracked warm-setup series
    regresses 3x: exit must be nonzero and the offending metric named
    in both stdout and the written history."""
    good = {"northstar_256^3_setup_warm_s": 5.0,
            "flagship_128^3_solve_s": 0.30}
    bad = {"northstar_256^3_setup_warm_s": 15.0,
           "flagship_128^3_solve_s": 0.31}
    for n, extra in ((1, good), (2, bad)):
        with open(tmp_path / f"BENCH_r{n:02d}.json", "w") as f:
            json.dump(_wrapper(n, extra), f)
    p = _run_history(["--root", str(tmp_path)])
    assert p.returncode != 0
    assert "northstar_256^3_setup_warm_s" in p.stdout
    assert "flagship_128^3_solve_s" not in \
        [r["metric"] for r in json.load(
            open(tmp_path / "BENCH_HISTORY.json"))["regressions"]]
    hist = json.load(open(tmp_path / "BENCH_HISTORY.json"))
    assert [r["metric"] for r in hist["regressions"]] == \
        ["northstar_256^3_setup_warm_s"]
    assert (tmp_path / "BENCH_HISTORY.md").exists()
    # an improvement round clears the flag
    with open(tmp_path / "BENCH_r03.json", "w") as f:
        json.dump(_wrapper(3, good), f)
    p = _run_history(["--root", str(tmp_path)])
    assert p.returncode == 0


def test_sentinel_recovers_metrics_from_truncated_tail(tmp_path):
    """A round whose `parsed` came back null (the r05 failure mode)
    still contributes every scalar its captured tail kept."""
    with open(tmp_path / "BENCH_r01.json", "w") as f:
        json.dump(_wrapper(1, {"northstar_256^3_setup_warm_s": 5.0}), f)
    tail = ('...log noise... "northstar_256^3_setup_warm_s": 17.37,'
            ' "northstar_256^3_solve_s": 3.0, "truncated_key": 1')
    with open(tmp_path / "BENCH_r02.json", "w") as f:
        json.dump(_wrapper(2, {}, parsed=False, tail_extra=tail), f)
    p = _run_history(["--root", str(tmp_path)])
    assert p.returncode != 0
    assert "northstar_256^3_setup_warm_s" in p.stdout
    hist = json.load(open(tmp_path / "BENCH_HISTORY.json"))
    pts = hist["series"]["northstar_256^3_setup_warm_s"]["points"]
    assert pts == [{"round": 1, "value": 5.0},
                   {"round": 2, "value": 17.37}]


def test_sentinel_single_round_judges_nothing(tmp_path):
    """A history of ONE round has nothing to regress against — every
    direction (the absolute-bound obs gate included) stays quiet."""
    with open(tmp_path / "BENCH_r01.json", "w") as f:
        json.dump(_wrapper(1, {"northstar_256^3_setup_warm_s": 99.0,
                               "obs_overhead_pct": 50.0}), f)
    p = _run_history(["--root", str(tmp_path)])
    assert p.returncode == 0, p.stdout
    assert json.load(
        open(tmp_path / "BENCH_HISTORY.json"))["regressions"] == []


def test_sentinel_flags_checked_in_r05_regression(tmp_path):
    """The acceptance demo over COPIES of the checked-in r01-r05
    artifacts (copies so the assertion stays stable as later rounds
    land): >= 5 tracked series populate and the r05 warm-setup
    regression (17.37 s vs r03's 5.87 s) is flagged."""
    for name in os.listdir(REPO):
        if re.match(r"(BENCH|MULTICHIP)_r0[1-5]\.json$", name):
            shutil.copy(os.path.join(REPO, name), tmp_path / name)
    p = _run_history(["--root", str(tmp_path)])
    assert p.returncode != 0
    hist = json.load(open(tmp_path / "BENCH_HISTORY.json"))
    populated = [k for k, s in hist["series"].items() if s["points"]]
    assert len(populated) >= 5
    flagged = {r["metric"]: r for r in hist["regressions"]}
    assert "northstar_256^3_setup_warm_s" in flagged
    r = flagged["northstar_256^3_setup_warm_s"]
    assert r["value"] == pytest.approx(17.37)
    assert r["best_prior"] == pytest.approx(5.87)
    assert r["best_prior_round"] == 3 and r["round"] == 5


def test_sentinel_smoke_ok_and_catches_malformed(tmp_path):
    """--smoke (the tier-1-reachable self-check): passes on the
    checked-in artifacts, fails fast on a malformed one."""
    p = _run_history(["--smoke"])
    assert p.returncode == 0, p.stdout + p.stderr
    assert "OK" in p.stdout
    with open(tmp_path / "BENCH_r01.json", "w") as f:
        f.write("{not json")
    p = _run_history(["--smoke", "--root", str(tmp_path)])
    assert p.returncode != 0
    assert "BENCH_r01.json" in p.stdout


def test_bench_stamps_round_and_schema(tmp_path, monkeypatch):
    """bench.py's artifact writer stamps schema_version + the driver's
    round id (satellite: bench_history keys rounds without parsing
    filenames)."""
    sys.path.insert(0, REPO)
    try:
        import bench
    finally:
        sys.path.pop(0)
    monkeypatch.setenv("AMGX_BENCH_ROUND", "17")
    assert bench._round_stamp() == 17
    monkeypatch.delenv("AMGX_BENCH_ROUND")
    assert bench._round_stamp() is None
    assert bench.BENCH_SCHEMA_VERSION >= 2


def test_phase_artifacts_feed_series(tmp_path):
    """BENCH_serving.json / BENCH_fleet.json phase artifacts (round
    stamp + `extra` scalars) contribute series points alongside the
    wrapper rounds; an unstamped artifact contributes nothing; a
    malformed one fails --smoke by name."""
    with open(tmp_path / "BENCH_r06.json", "w") as f:
        json.dump(_wrapper(6, {"northstar_256^3_setup_warm_s": 5.0}), f)
    with open(tmp_path / "BENCH_fleet.json", "w") as f:
        json.dump({"metric": "fleet scaling", "value": 2.0, "unit": "x",
                   "round": 6,
                   "extra": {"fleet_scaling_efficiency": 1.3,
                             "fleet_p99_at_2x_ms": 900.0,
                             "fleet_ok": True}}, f)
    # unstamped (standalone run outside the driver): ignored, not fatal
    with open(tmp_path / "BENCH_serving.json", "w") as f:
        json.dump({"metric": "serving", "value": 9.0,
                   "extra": {"serving_solves_per_s": 9.0}}, f)
    p = _run_history(["--root", str(tmp_path)])
    assert p.returncode == 0, p.stdout + p.stderr
    hist = json.load(open(tmp_path / "BENCH_HISTORY.json"))
    assert hist["series"]["fleet_scaling_efficiency"]["points"] == \
        [{"round": 6, "value": 1.3}]
    assert hist["series"]["fleet_p99_at_2x_ms"]["points"] == \
        [{"round": 6, "value": 900.0}]
    assert hist["series"]["serving_solves_per_s"]["points"] == []
    assert "BENCH_fleet.json" in hist["rounds"][0]["files"]
    # a wrapper round carrying the same key wins over the artifact
    with open(tmp_path / "BENCH_r06.json", "w") as f:
        json.dump(_wrapper(6, {"fleet_scaling_efficiency": 1.9}), f)
    p = _run_history(["--root", str(tmp_path)])
    assert p.returncode == 0, p.stdout + p.stderr
    hist = json.load(open(tmp_path / "BENCH_HISTORY.json"))
    assert hist["series"]["fleet_scaling_efficiency"]["points"] == \
        [{"round": 6, "value": 1.9}]
    with open(tmp_path / "BENCH_fleet.json", "w") as f:
        f.write("{not json")
    p = _run_history(["--smoke", "--root", str(tmp_path)])
    assert p.returncode != 0
    assert "BENCH_fleet.json" in p.stdout


# ---------------------------------------------------------------------------
# metric-name lint (tools/check_spans.py contract 3)
# ---------------------------------------------------------------------------


def test_check_spans_metric_lint_clean_and_catches_typo(tmp_path):
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "check_spans", os.path.join(REPO, "tools", "check_spans.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    # the package as checked in lints clean (all three contracts)
    assert mod.check() == []
    # a typo'd literal on a registry receiver is extracted...
    src = tmp_path / "bad.py"
    src.write_text(
        "from amgx_tpu.telemetry import metrics as _tm\n"
        "def f(chk):\n"
        "    _tm.inc('serving.request')\n"
        "    _tm.observe('serving.solve_latency_s', 1.0)\n"
        "    chk.observe('residual', 1.0)\n"    # foreign receiver:
        "    _tm.set_gauge(f'dyn.{f}', 1)\n")   # skipped, not flagged
    found = mod.extract_metric_literals(str(tmp_path))
    names = [(kind, name) for _p, _l, kind, name in found]
    assert ("counter", "serving.request") in names
    assert ("histogram", "serving.solve_latency_s") in names
    assert all(n != "residual" for _k, n in names)
    # ...and fails the catalog membership check
    from amgx_tpu.telemetry import metrics as M
    assert "serving.request" not in M.COUNTERS
    assert "serving.solve_latency_s" in M.HISTOGRAMS
