"""Classical (Ruge-Stuben) AMG tests (analogs of classical_pmis.cu,
classical_strength.cu, classical_strength_affinity.cu and the D2
interpolation coverage)."""
import jax.numpy as jnp
import numpy as np
import pytest

import amgx_tpu as amgx
from amgx_tpu import gallery, ops, registry
from amgx_tpu.config import Config
from amgx_tpu.solvers import make_solver
from amgx_tpu.amg.classical.selectors import pmis_split
from amgx_tpu.amg.classical.interpolators import (Distance1Interpolator,
                                                  Distance2Interpolator)

amgx.initialize()


@pytest.fixture(scope="module")
def A16():
    return gallery.poisson("5pt", 16, 16).init()


@pytest.fixture(scope="module")
def strength16(A16):
    cfg = Config.from_string("strength_threshold=0.25")
    return registry.strength.create("AHAT", cfg, "default").strong_mask(A16)


class TestStrength:
    def test_ahat_poisson_all_offdiag_strong(self, A16, strength16):
        """Equal-coefficient Poisson: every off-diagonal is strong."""
        rows, cols, _ = A16.coo()
        offd = np.asarray(rows != cols)
        s = np.asarray(strength16)
        assert np.array_equal(s, offd)

    def test_ahat_threshold_filters_weak(self):
        # anisotropic 5pt: weak y-coupling filtered at theta=0.25
        import numpy as np
        from amgx_tpu.matrix import CsrMatrix
        n = 9
        rows, cols, vals = [], [], []
        for i in range(3):
            for j in range(3):
                k = i * 3 + j
                rows.append(k); cols.append(k); vals.append(2.2)
                if j > 0: rows.append(k); cols.append(k - 1); vals.append(-1.0)
                if j < 2: rows.append(k); cols.append(k + 1); vals.append(-1.0)
                if i > 0: rows.append(k); cols.append(k - 3); vals.append(-0.1)
                if i < 2: rows.append(k); cols.append(k + 3); vals.append(-0.1)
        A = CsrMatrix.from_coo(rows, cols, vals, n, n).init()
        cfg = Config.from_string("strength_threshold=0.25")
        s = registry.strength.create("AHAT", cfg, "default").strong_mask(A)
        r, c, v = A.coo()
        weak = np.asarray(jnp.abs(v) < 0.5) & np.asarray(r != c)
        assert not np.any(np.asarray(s) & weak)   # weak edges not strong

    def test_all_strength(self, A16):
        cfg = Config.from_string("strength_threshold=0.25")
        s = registry.strength.create("ALL", cfg, "default").strong_mask(A16)
        rows, cols, _ = A16.coo()
        assert np.array_equal(np.asarray(s), np.asarray(rows != cols))

    def test_affinity_runs(self, A16):
        cfg = Config.from_string("strength_threshold=0.25")
        s = registry.strength.create("AFFINITY", cfg,
                                     "default").strong_mask(A16)
        assert bool(jnp.any(s))


class TestPMIS:
    def test_valid_cf_splitting(self, A16, strength16):
        """Every F point has a strong C neighbor; C points form an
        independent set-ish cover (classical_pmis.cu semantics)."""
        cf = np.asarray(pmis_split(A16, strength16))
        assert set(np.unique(cf)) <= {0, 1}
        rows, cols, _ = (np.asarray(a) for a in A16.coo())
        s = np.asarray(strength16)
        has_c_nbr = np.zeros(A16.num_rows, bool)
        np.logical_or.at(has_c_nbr, rows[s], cf[cols[s]] == 1)
        f_pts = cf == 0
        assert np.all(has_c_nbr[f_pts]), "F point without strong C neighbor"

    def test_determinism(self, A16, strength16):
        a = np.asarray(pmis_split(A16, strength16))
        b = np.asarray(pmis_split(A16, strength16))
        assert np.array_equal(a, b)

    def test_aggressive_coarser(self, A16, strength16):
        cfg = Config.from_string("strength_threshold=0.25")
        sel = registry.classical_selectors.create("AGGRESSIVE_PMIS", cfg,
                                                  "default")
        cf_a = np.asarray(sel.mark_coarse_fine_points(A16, strength16))
        cf_p = np.asarray(pmis_split(A16, strength16))
        assert cf_a.sum() < cf_p.sum()


class TestInterpolation:
    @pytest.mark.parametrize("cls", [Distance1Interpolator,
                                     Distance2Interpolator])
    def test_rows_partition_of_unity_interior(self, A16, strength16, cls):
        """Interior Poisson rows (zero row sum) must interpolate constants
        exactly: P row sums == 1."""
        cf = pmis_split(A16, strength16)
        cfg = Config.from_string("strength_threshold=0.25")
        P = cls(cfg, "default").generate(A16, cf, strength16)
        Pd = np.asarray(P.to_dense())
        Ad = np.asarray(A16.to_dense())
        interior = np.abs(Ad.sum(1)) < 1e-12
        f_int = interior & (np.asarray(cf) == 0)
        np.testing.assert_allclose(Pd[f_int].sum(1), 1.0, rtol=1e-12)

    def test_d2_better_than_d1_twogrid(self, A16, strength16):
        cf = pmis_split(A16, strength16)
        cfg = Config.from_string("strength_threshold=0.25")
        rates = {}
        for name, cls in (("D1", Distance1Interpolator),
                          ("D2", Distance2Interpolator)):
            Pd = np.asarray(cls(cfg, "default").generate(
                A16, cf, strength16).to_dense())
            Ad = np.asarray(A16.to_dense())
            n = A16.num_rows
            Ac = Pd.T @ Ad @ Pd
            S = np.eye(n) - 0.8 * np.diag(1 / np.diag(Ad)) @ Ad
            CGC = np.eye(n) - Pd @ np.linalg.solve(Ac, Pd.T @ Ad)
            rates[name] = np.abs(np.linalg.eigvals(S @ CGC @ S)).max()
        assert rates["D2"] < rates["D1"] < 1.0

    def test_truncation_caps_row_length(self, A16, strength16):
        cf = pmis_split(A16, strength16)
        cfg = Config.from_string(
            "strength_threshold=0.25, interp_max_elements=2")
        P = Distance2Interpolator(cfg, "default").generate(
            A16, cf, strength16)
        row_nnz = np.diff(np.asarray(P.row_offsets))
        assert row_nnz.max() <= 2
        # rows still sum to ~1 on interior (rescaled truncation)
        Pd = np.asarray(P.to_dense())
        Ad = np.asarray(A16.to_dense())
        f_int = (np.abs(Ad.sum(1)) < 1e-12) & (np.asarray(cf) == 0)
        np.testing.assert_allclose(Pd[f_int].sum(1), 1.0, rtol=1e-10)


class TestClassicalSolve:
    def test_standalone_vcycle_scalable_rate(self):
        A = gallery.poisson("5pt", 48, 48).init()
        b = jnp.ones(A.num_rows)
        cfg = Config.from_string(
            "solver(amg)=AMG, amg:algorithm=CLASSICAL, amg:selector=PMIS,"
            " amg:interpolator=D2, amg:smoother(sm)=JACOBI_L1,"
            " sm:relaxation_factor=1.0, sm:max_iters=1, amg:presweeps=2,"
            " amg:postsweeps=2, amg:coarse_solver=DENSE_LU_SOLVER,"
            " amg:max_iters=30, amg:monitor_residual=1, amg:tolerance=1e-8,"
            " amg:convergence=RELATIVE_INI, amg:min_coarse_rows=16")
        s = make_solver("AMG", cfg, "amg")
        s.setup(A)
        res = s.solve(b)
        assert res.converged
        rate = (float(np.max(res.res_norm)) /
                float(np.max(res.norm0))) ** (1 / max(res.iterations, 1))
        assert rate < 0.45, f"V-cycle rate {rate}"

    @pytest.mark.slow     # 3D classical-from-config smoke; the 2D
    # gmres reference-config test below keeps the family in tier-1
    def test_pcg_classical_config_file(self):
        A = gallery.poisson("7pt", 16, 16, 16).init()
        b = jnp.ones(A.num_rows)
        cfg = Config.from_file("configs/PCG_CLASSICAL_V_JACOBI.json")
        s = amgx.create_solver(cfg)
        s.setup(A)
        res = s.solve(b)
        assert res.converged
        assert res.iterations <= 25
        tr = float(np.linalg.norm(np.asarray(ops.residual(A, res.x, b))))
        # faithful reference config: RELATIVE_INI tolerance 1e-6
        assert tr / float(np.linalg.norm(np.asarray(b))) < 2e-6

    def test_gmres_classical_pmis_reference_config(self):
        A = gallery.poisson("5pt", 32, 32).init()
        b = jnp.ones(A.num_rows)
        cfg = Config.from_file("configs/AMG_CLASSICAL_PMIS.json")
        s = amgx.create_solver(cfg)
        s.setup(A)
        res = s.solve(b)
        assert res.converged
        rel = float(np.max(res.res_norm)) / float(np.max(res.norm0))
        assert rel <= 1e-6


def test_d2_host_and_device_paths_agree():
    """The numpy host-setup formulation of D2 (interpolators.py
    _generate_host) and the accelerator-shaped jnp formulation compute
    the same interpolation operator."""
    from amgx_tpu import native
    if native.lib() is None:
        pytest.skip("native toolchain unavailable: _generate_host "
                    "falls back to the jnp path (nothing to compare)")
    A = gallery.poisson("7pt", 8, 8, 8).init()
    cfg = Config.from_string("strength_threshold=0.25")
    strong = registry.strength.create("AHAT", cfg,
                                      "default").strong_mask(A)
    cf_map = pmis_split(A, strong)
    interp = Distance2Interpolator(cfg, "default")
    P1 = interp._generate_host(A, cf_map, strong)
    P2 = interp._generate_jnp(A, cf_map, strong)
    d1 = np.asarray(P1.to_dense())
    d2 = np.asarray(P2.to_dense())
    np.testing.assert_allclose(d1, d2, rtol=1e-13, atol=1e-14)
