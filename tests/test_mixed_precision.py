"""Mixed-precision fused path (ISSUE 14): bf16 operand slabs with f32
in-kernel accumulation inside the f64 refinement shell.

Covers: the shared precision policy (solve_precision / amg_precision /
tpu_dtype resolution + contradiction rejection), interpret-mode kernel
parity for bf16 slabs vs the f32 reference at bf16 tolerances (single /
multiblock+chained / restrict+prolong epilogues / SWELL / vmap->slab
routing), the jaxpr proofs — a bf16 smoothed DIA level still runs
exactly 2 fused kernels per cycle plus 1 VMEM-tail kernel with zero
standalone SpMV/transfer prims, and `solve_precision` unset is
bitwise-off — the REFINEMENT-shell acceptance (bf16 cycle reaching the
f64 relative tolerance on the flagship and a classical config, with
per-precision iteration counts recorded), halved slab bytes (plan
accounting) and halved modeled distributed exchange bytes on a 4-shard
mesh, and the fusion.declined_dtype counter + per-level routing column
that make falling off the fused path visible."""
import re

import numpy as np
import pytest
import jax
import jax.numpy as jnp

import amgx_tpu as amgx
from amgx_tpu import gallery
from amgx_tpu.config import Config
from amgx_tpu.errors import BadConfigurationError
from amgx_tpu.ops import pallas_spmv as ps
from amgx_tpu.ops import smooth as fused
from amgx_tpu.ops.spmv import spmv
from amgx_tpu.precision import resolve_precision
from amgx_tpu.presets import FLAGSHIP
from amgx_tpu.telemetry import metrics

amgx.initialize()

BF = jnp.bfloat16


def _rel(a, b):
    a = np.asarray(a, np.float64)
    b = np.asarray(b, np.float64)
    return float(np.linalg.norm(a - b)
                 / max(np.linalg.norm(b), 1e-300))


def _ref_sweeps(A, b, x, taus, dinv=None, with_residual=True):
    for t in range(taus.shape[0]):
        upd = taus[t] * (b - spmv(A, x))
        if dinv is not None:
            upd = upd * dinv
        x = x + upd
    if with_residual:
        return x, b - spmv(A, x)
    return x


def _problem(n=10, seed=0, with_dinv=True):
    A = gallery.poisson("7pt", n, n, n, dtype=jnp.float32).init()
    rng = np.random.default_rng(seed)
    m = A.num_rows
    b = jnp.asarray(rng.standard_normal(m), jnp.float32)
    x = jnp.asarray(rng.standard_normal(m), jnp.float32)
    dinv = jnp.asarray(1.0 / rng.uniform(4, 8, m), jnp.float32) \
        if with_dinv else None
    return A, b, x, dinv


# ---------------------------------------------------------------------------
# precision policy (precision.py)
# ---------------------------------------------------------------------------


def test_policy_resolution_and_ownership():
    p = resolve_precision(Config.from_string(""))
    assert p.name == "double" and not p.active and p.cast_dtype is None
    p = resolve_precision(Config.from_string("solve_precision=bfloat16"))
    assert p.name == "bfloat16" and p.active
    assert p.cast_dtype == "bfloat16"
    # reductions / coarse tail stay f32+ under bf16
    assert p.coarse_dtype == "float32"
    p = resolve_precision(Config.from_string("amg_precision=float"))
    assert p.name == "float" and not p.active \
        and p.source == "amg_precision"
    # agreement between knobs is fine
    p = resolve_precision(Config.from_string(
        "solve_precision=float, amg_precision=float"))
    assert p.name == "float" and p.source == "solve_precision"


def test_policy_tpu_dtype_alias():
    p = resolve_precision(Config.from_string("tpu_dtype=bfloat16"))
    assert p.name == "bfloat16" and p.source == "tpu_dtype"
    p = resolve_precision(Config.from_string("tpu_dtype=float64"))
    assert p.name == "double"
    with pytest.raises(BadConfigurationError):
        Config.from_string("tpu_dtype=f16")   # off the allowed list


def test_policy_contradictions_raise():
    with pytest.raises(BadConfigurationError):
        resolve_precision(Config.from_string(
            "solve_precision=float, amg_precision=bfloat16"))
    with pytest.raises(BadConfigurationError):
        resolve_precision(Config.from_string(
            "tpu_dtype=float32, amg_precision=bfloat16"))
    # the contradiction also fails solver CONSTRUCTION (base __init__
    # resolves the policy), not first solve
    with pytest.raises(BadConfigurationError):
        amgx.create_solver(Config.from_string(
            "solver=PCG, solve_precision=bfloat16, tpu_dtype=float32"))


# ---------------------------------------------------------------------------
# kernel parity at bf16 (interpret mode)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("schedule,with_dinv", [
    ("jacobi", True),       # constant tau + dinv (JACOBI / JACOBI_L1)
    ("cheb", False),        # per-step taus, no dinv (CHEBYSHEV_POLY)
])
def test_dia_fused_parity_bf16(schedule, with_dinv):
    A, b, x, dinv = _problem(with_dinv=with_dinv)
    rng = np.random.default_rng(3)
    taus = jnp.asarray(np.full(3, 0.9) if schedule == "jacobi"
                       else rng.uniform(0.05, 0.2, 3), jnp.float32)
    ref = _ref_sweeps(A, b, x, taus, dinv, True)
    Ab = A.astype(BF)
    with ps.force_pallas_interpret():
        slabs = fused.build_fused_slabs(
            Ab, None if dinv is None else dinv.astype(BF))
        assert slabs["vals_q"].dtype == BF
        out = fused.dia_fused_smooth(
            Ab, slabs, b.astype(BF), x.astype(BF),
            taus, dinv=None if dinv is None else dinv.astype(BF),
            with_residual=True)
    assert out is not None, "bf16 declined the fused path"
    assert out[0].dtype == BF
    assert _rel(out[0], ref[0]) < 2e-2
    assert _rel(out[1], ref[1]) < 2e-1   # residual: catastrophic-
    #                                      cancellation amplified


def test_dia_bf16_multiblock_and_chained():
    """Shrunk VMEM budget: multi-block double-buffered DMA and the
    chained per-chunk dispatch, both at bf16."""
    A, b, x, dinv = _problem(n=16, seed=1)
    taus = jnp.asarray(np.full(3, 0.8), jnp.float32)
    ref = _ref_sweeps(A, b, x, taus, dinv, True)
    Ab = A.astype(BF)
    old = ps._SMOOTH_VMEM_BUDGET
    try:
        for budget in (300 * 1024, 120 * 1024):
            ps._SMOOTH_VMEM_BUDGET = budget
            with ps.force_pallas_interpret():
                slabs = fused.build_fused_slabs(Ab, dinv.astype(BF))
                xf, rf = fused.dia_fused_smooth(
                    Ab, slabs, b.astype(BF), x.astype(BF), taus,
                    dinv=dinv.astype(BF), with_residual=True)
            assert _rel(xf, ref[0]) < 2e-2
            assert _rel(rf, ref[1]) < 2e-1
    finally:
        ps._SMOOTH_VMEM_BUDGET = old


def _geo_agg(nx, ny, nz):
    """2x2x2 pairing aggregate map (x fastest), like the GEO selector."""
    ix, iy, iz = np.meshgrid(np.arange(nx), np.arange(ny),
                             np.arange(nz), indexing="ij")
    cx, cy, cz = (nx + 1) // 2, (ny + 1) // 2, (nz + 1) // 2
    agg = (ix // 2) + cx * (iy // 2) + cx * cy * (iz // 2)
    return agg.transpose(2, 1, 0).reshape(-1), cx * cy * cz


def test_restrict_prolong_epilogue_parity_bf16():
    A, b, x, dinv = _problem(n=8, seed=2)
    n = A.num_rows
    agg, nc = _geo_agg(8, 8, 8)
    taus = jnp.asarray(np.full(2, 0.85), jnp.float32)
    xs, rs = _ref_sweeps(A, b, x, taus, dinv, True)
    bc_ref = jnp.zeros(nc, jnp.float32).at[jnp.asarray(agg)].add(rs)
    Ab = A.astype(BF)
    rng = np.random.default_rng(5)
    xc = jnp.asarray(rng.standard_normal(nc), jnp.float32)
    corr_ref = _ref_sweeps(A, b, x + xc[jnp.asarray(agg)], taus, dinv,
                           False)
    with ps.force_pallas_interpret():
        slabs = fused.build_fused_slabs(Ab, dinv.astype(BF))
        xfer = fused.build_transfer_slabs(Ab, agg, nc)
        assert xfer is not None
        data = {"A": Ab, "fused": slabs}
        out = fused.fused_smooth_restrict(
            data, b.astype(BF), x.astype(BF), taus, xfer,
            dinv=dinv.astype(BF))
        assert out is not None, "bf16 restrict epilogue declined"
        xk, bck = out
        outc = fused.fused_corr_smooth(
            data, b.astype(BF), x.astype(BF), xc.astype(BF), taus,
            xfer, dinv=dinv.astype(BF))
        assert outc is not None, "bf16 prolong prologue declined"
    assert _rel(xk, xs) < 2e-2
    assert _rel(bck, bc_ref) < 2e-1
    assert _rel(outc, corr_ref) < 2e-2


def test_swell_parity_bf16():
    from tests.test_fused_smoother import _swell_matrix
    A = _swell_matrix(n=24)
    n = A.num_rows
    rng = np.random.default_rng(7)
    b = jnp.asarray(rng.standard_normal(n), jnp.float32)
    x = jnp.asarray(rng.standard_normal(n), jnp.float32)
    dinv = jnp.asarray(1.0 / rng.uniform(4, 8, n), jnp.float32)
    taus = jnp.asarray(np.full(2, 0.9), jnp.float32)
    ref = _ref_sweeps(A, b, x, taus, dinv, True)
    Ab = A.astype(BF)
    with ps.force_pallas_interpret():
        out = fused.swell_fused_smooth(
            Ab, b.astype(BF), x.astype(BF), taus,
            dinv=dinv.astype(BF), with_residual=True)
    assert out is not None, "bf16 SWELL fused sweep declined"
    assert out[0].dtype == BF
    assert _rel(out[0], ref[0]) < 2e-2
    assert _rel(out[1], ref[1]) < 3e-1


def test_vmap_routes_to_slab_bf16():
    """Vector-only batches at bf16 take the multi-RHS slab forms (the
    custom_vmap rule), accumulate in f32, and match the f32 reference
    at bf16 tolerance."""
    A, _, _, dinv = _problem(n=8, seed=4)
    n = A.num_rows
    rng = np.random.default_rng(8)
    B = jnp.asarray(rng.standard_normal((3, n)), jnp.float32)
    X = jnp.asarray(rng.standard_normal((3, n)), jnp.float32)
    taus = jnp.asarray(np.full(2, 0.9), jnp.float32)
    refs = [_ref_sweeps(A, B[i], X[i], taus, dinv, True)
            for i in range(3)]
    Ab = A.astype(BF)
    with ps.force_pallas_interpret():
        slabs = fused.build_fused_slabs(Ab, dinv.astype(BF))

        def one(bb, xx):
            return fused.dia_fused_smooth(
                Ab, slabs, bb, xx, taus, dinv=dinv.astype(BF),
                with_residual=True)

        Xo, Ro = jax.vmap(one)(B.astype(BF), X.astype(BF))
    for i in range(3):
        assert _rel(Xo[i], refs[i][0]) < 2e-2
        assert _rel(Ro[i], refs[i][1]) < 2e-1


# ---------------------------------------------------------------------------
# slab bytes: plan accounting halves at bf16
# ---------------------------------------------------------------------------


def test_fused_slab_bytes_halved():
    A, _, _, dinv = _problem(n=12)
    with ps.force_pallas_interpret():
        s32 = fused.build_fused_slabs(A, dinv)
        s16 = fused.build_fused_slabs(A.astype(BF), dinv.astype(BF))
    assert s32["vals_q"].nbytes == 2 * s16["vals_q"].nbytes
    assert s32["dinv_q"].nbytes == 2 * s16["dinv_q"].nbytes
    # dtype-targeted emission (the hierarchy path): narrow from birth
    with ps.force_pallas_interpret():
        st = fused.build_fused_slabs(A, dinv, dtype="bfloat16")
    assert st["vals_q"].dtype == BF and st["dinv_q"].dtype == BF
    assert st["vals_q"].nbytes == s16["vals_q"].nbytes
    # plan accounting: the halved DMA windows never fit FEWER rows —
    # at a constrained budget bf16 fits a strictly larger block
    k = A.dia_vals.shape[0]
    old = ps._SMOOTH_VMEM_BUDGET
    try:
        ps._SMOOTH_VMEM_BUDGET = 220 * 1024
        p32 = ps.dia_smooth_plan(A.dia_offsets, k, A.num_rows, 3, True,
                                 itemsize=4)
        p16 = ps.dia_smooth_plan(A.dia_offsets, k, A.num_rows, 3, True,
                                 itemsize=2)
    finally:
        ps._SMOOTH_VMEM_BUDGET = old
    assert p16 is not None
    assert p32 is None or p16[0] >= p32[0]


def test_csr_transfer_weight_slabs_emit_narrow():
    """Classical weighted slabs: cwt/pwt emit at the policy dtype,
    index tables stay int32."""
    cfg = Config.from_string(
        "solver(s)=PCG, s:max_iters=5, s:monitor_residual=1,"
        " s:preconditioner(amg)=AMG, amg:algorithm=CLASSICAL,"
        " amg:selector=PMIS, amg:interpolator=D1,"
        " amg:smoother=JACOBI_L1, amg:max_iters=1,"
        " amg:min_coarse_rows=8, amg:max_levels=3,"
        " amg:interp_max_elements=4, amg:solve_precision=bfloat16")
    A = gallery.poisson("7pt", 8, 8, 8, dtype=jnp.float32).init()
    with ps.force_pallas_interpret():
        slv = amgx.create_solver(cfg)
        slv.setup(A)
        amg = slv.preconditioner.amg
        xfer = amg.levels[0]._transfer_slabs()
    assert xfer is not None and xfer.cwt is not None
    assert xfer.cwt.dtype == BF and xfer.pwt.dtype == BF
    assert xfer.ctab.dtype == jnp.int32
    assert xfer.ptab.dtype == jnp.int32


# ---------------------------------------------------------------------------
# jaxpr proofs: kernel census at bf16, unset is bitwise-off
# ---------------------------------------------------------------------------

_CYCLE_CFG = (
    "solver(s)=PCG, s:max_iters=30, s:tolerance=1e-7,"
    " s:convergence=RELATIVE_INI, s:monitor_residual=1,"
    " s:preconditioner(amg)=AMG, amg:algorithm=AGGREGATION,"
    " amg:selector=GEO, amg:smoother=JACOBI_L1, amg:presweeps=2,"
    " amg:postsweeps=1, amg:max_iters=1,"
    " amg:coarse_solver=DENSE_LU_SOLVER, amg:min_coarse_rows=16,"
    " amg:max_levels=10")


def _trace_cycle(extra_cfg="", n=16):
    A = gallery.poisson("7pt", n, n, n, dtype=jnp.float32).init()
    b = jnp.ones(A.num_rows, jnp.float32)
    with ps.force_pallas_interpret():
        slv = amgx.create_solver(Config.from_string(_CYCLE_CFG
                                                    + extra_cfg))
        slv.setup(A)
        pc = slv.preconditioner
        d = pc.solve_data()
        jaxpr = jax.make_jaxpr(
            lambda bb, xx: pc.amg.cycle(d["amg"], bb, xx))(
                b, jnp.zeros_like(b))
    return pc.amg, jaxpr


def _kernel_counts(jaxpr):
    names = re.findall(r"name=\"?([A-Za-z_0-9]+)\"?", str(jaxpr))
    out = {}
    for nm in names:
        for key in ("_dia_smooth_restrict_call",
                    "_dia_prolong_smooth_call", "_dia_coarse_tail_call",
                    "_dia_smooth_call", "_dia_spmv_call"):
            if nm == key:
                out[key] = out.get(key, 0) + 1
    return out


def _outer_prims(closed_jaxpr):
    prims = []

    def walk(jaxpr):
        for eqn in jaxpr.eqns:
            if eqn.primitive.name == "pallas_call":
                continue
            prims.append(eqn.primitive.name)
            for p in eqn.params.values():
                for q in (p if isinstance(p, (tuple, list)) else (p,)):
                    if isinstance(q, jax.core.ClosedJaxpr):
                        walk(q.jaxpr)
                    elif isinstance(q, jax.core.Jaxpr):
                        walk(q)

    walk(closed_jaxpr.jaxpr)
    return prims


def test_jaxpr_bf16_cycle_kernel_census():
    """ISSUE 14 acceptance: a bf16 smoothed DIA level runs EXACTLY 2
    fused kernels per cycle, the tail is 1 kernel, and there are zero
    standalone SpMV/transfer prims outside the kernels."""
    amg, jaxpr = _trace_cycle(
        ", amg:solve_precision=bfloat16, amg:cycle_fusion_tail_rows=600")
    c = _kernel_counts(jaxpr)
    nfused = (amg._tail_entry_level if amg._tail_entry_level is not None
              else len(amg.levels))
    assert nfused >= 1
    assert c.get("_dia_smooth_restrict_call", 0) == nfused
    assert c.get("_dia_prolong_smooth_call", 0) == nfused
    assert c.get("_dia_coarse_tail_call", 0) == 1
    assert c.get("_dia_smooth_call", 0) == 0
    assert c.get("_dia_spmv_call", 0) == 0
    outer = set(_outer_prims(jaxpr))
    assert "gather" not in outer and "scatter" not in outer \
        and "scatter_add" not in outer


def test_jaxpr_bf16_cycle_value_parity():
    """The bf16 cycle's output tracks the f32 cycle at bf16 tolerance
    (one V-cycle application on the same hierarchy)."""
    A = gallery.poisson("7pt", 12, 12, 12, dtype=jnp.float32).init()
    b = jnp.asarray(np.random.default_rng(0).standard_normal(
        A.num_rows), jnp.float32)
    with ps.force_pallas_interpret():
        s32 = amgx.create_solver(Config.from_string(_CYCLE_CFG))
        s32.setup(A)
        d32 = s32.preconditioner.solve_data()
        y32 = s32.preconditioner.amg.cycle(d32["amg"], b,
                                           jnp.zeros_like(b))
        s16 = amgx.create_solver(Config.from_string(
            _CYCLE_CFG + ", amg:solve_precision=bfloat16"))
        s16.setup(A)
        d16 = s16.preconditioner.solve_data()
        y16 = s16.preconditioner.amg.cycle(d16["amg"], b,
                                           jnp.zeros_like(b))
    assert y16.dtype == jnp.float32   # caller dtype restored
    assert _rel(y16, y32) < 3e-2


def test_solve_precision_unset_bitwise_off():
    """Unset solve_precision emits a jaxpr identical to the explicit
    all-f32 cast (identity on an f32 hierarchy) — i.e. the policy
    refactor and kernel dtype plumbing changed nothing for the
    default path — and the REFINEMENT driver declares no extra state
    or stats."""
    _, j0 = _trace_cycle("")
    _, j1 = _trace_cycle(", amg:amg_precision=float")
    assert str(j0) == str(j1)
    # flagship driver: no accounting machinery when unset
    slv = amgx.create_solver(Config.from_string(FLAGSHIP))
    assert slv._extra_stats_spec() == ()
    assert not slv._precision_policy.active
    on = amgx.create_solver(Config.from_string(
        FLAGSHIP + ", solve_precision=bfloat16"))
    assert on._extra_stats_spec() == ("inner_iters",)


# ---------------------------------------------------------------------------
# REFINEMENT shell acceptance
# ---------------------------------------------------------------------------


def test_refinement_shell_bf16_flagship():
    """The f64-restoring shell: solve_precision=bfloat16 on the
    flagship config reaches the f64 relative tolerance, with
    per-precision iteration counts recorded in SolveReport.precision
    and the per-level effective dtype + routing in the activity
    table."""
    n = 16
    A = gallery.poisson("7pt", n, n, n).init()     # f64 system
    b = jnp.ones(A.num_rows)
    with ps.force_pallas_interpret():
        base = amgx.create_solver(Config.from_string(FLAGSHIP))
        base.setup(A)
        r0 = base.solve(b)
        slv = amgx.create_solver(Config.from_string(
            FLAGSHIP + ", solve_precision=bfloat16"))
        slv.setup(A)
        r1 = slv.solve(b)
    assert r0.converged and r1.converged
    rel0 = float(np.max(np.asarray(r0.res_norm))
                 / np.max(np.asarray(r0.norm0)))
    rel1 = float(np.max(np.asarray(r1.res_norm))
                 / np.max(np.asarray(r1.norm0)))
    # matched f64 final residuals: both under the flagship tolerance
    assert rel0 <= 1e-8 and rel1 <= 1e-8
    # per-precision accounting
    pb = r1.report.precision
    assert pb is not None
    assert pb["solve_precision"] == "bfloat16"
    assert pb["cycle_dtype"] == "bfloat16"
    assert pb["outer_dtype"] == "float64"
    assert pb["inner_dtype"] == "float32"
    assert pb["outer_iterations"] == r1.iterations >= 1
    assert pb["inner_iterations"] >= pb["outer_iterations"]
    assert r1.extra_stats["inner_iters"] == pb["inner_iterations"]
    # baseline report carries NO precision block (bitwise-off)
    assert r0.report.precision is None
    assert r0.extra_stats is None
    # activity table: bf16 levels route fused
    lv = r1.report.levels[0]
    assert lv["dtype"] == "bfloat16"
    assert lv["fused_routing"] == "fused"


def test_refinement_shell_bf16_classical():
    """Same shell over a CLASSICAL hierarchy (weighted transfer slabs
    at bf16): matched f64 relative tolerance, counts recorded."""
    cfg = (
        "solver=REFINEMENT, max_iters=25, monitor_residual=1,"
        " tolerance=1e-8, convergence=RELATIVE_INI,"
        " preconditioner(in)=FGMRES, in:max_iters=60,"
        " in:monitor_residual=1, in:tolerance=1e-6,"
        " in:gmres_n_restart=10, in:convergence=RELATIVE_INI,"
        " in:preconditioner(amg)=AMG, amg:algorithm=CLASSICAL,"
        " amg:selector=PMIS, amg:interpolator=D2,"
        " amg:smoother=JACOBI_L1, amg:presweeps=1, amg:postsweeps=1,"
        " amg:max_iters=1, amg:min_coarse_rows=8, amg:max_levels=4,"
        " amg:interp_max_elements=4, amg:max_row_sum=0.9,"
        " solve_precision=bfloat16")
    A = gallery.poisson("7pt", 10, 10, 10).init()
    b = jnp.ones(A.num_rows)
    with ps.force_pallas_interpret():
        slv = amgx.create_solver(Config.from_string(cfg))
        slv.setup(A)
        res = slv.solve(b)
    assert res.converged
    rel = float(np.max(np.asarray(res.res_norm))
                / np.max(np.asarray(res.norm0)))
    assert rel <= 1e-8
    pb = res.report.precision
    assert pb is not None and pb["inner_iterations"] >= 1
    assert res.report.levels[0]["dtype"] == "bfloat16"


# ---------------------------------------------------------------------------
# fused-vs-unfused routing observability
# ---------------------------------------------------------------------------


def test_fusion_declined_dtype_counted_and_reported():
    """An f64 hierarchy on the fused runtime builds payloads whose
    dtype the kernels decline: the decline is COUNTED and the report
    says declined_dtype per level — the silent reroute is gone."""
    A = gallery.poisson("7pt", 8, 8, 8).init()    # f64
    b = jnp.ones(A.num_rows)
    before = metrics.get("fusion.declined_dtype")
    with ps.force_pallas_interpret():
        slv = amgx.create_solver(Config.from_string(
            _CYCLE_CFG.replace("s:max_iters=30", "s:max_iters=5")))
        slv.setup(A)
        res = slv.solve(b)
    assert metrics.get("fusion.declined_dtype") > before
    rows = res.report.levels
    declined = [r for r in rows if r.get("fused_routing")
                == "declined_dtype"]
    assert declined, f"no declined_dtype rows in {rows}"
    assert declined[0]["dtype"] == "float64"
    assert declined[0]["kernels_per_visit"] is None


def test_bf16_solve_fusion_counters_clean():
    """The motivating fix: a bf16 solve does NOT count dtype declines
    anymore (it rides the fused path)."""
    A = gallery.poisson("7pt", 8, 8, 8, dtype=jnp.float32).init()
    b = jnp.ones(A.num_rows, jnp.float32)
    with ps.force_pallas_interpret():
        slv = amgx.create_solver(Config.from_string(
            _CYCLE_CFG.replace("s:max_iters=30", "s:max_iters=5")
            + ", amg:solve_precision=bfloat16"))
        slv.setup(A)
        before = metrics.get("fusion.declined_dtype")
        res = slv.solve(b)
    assert metrics.get("fusion.declined_dtype") == before
    assert all(r["fused_routing"] == "fused"
               for r in res.report.levels if r["fused_smoother"])


# ---------------------------------------------------------------------------
# distributed: halved modeled exchange bytes + sharded parity
# ---------------------------------------------------------------------------


def _dist_cycle_rig(n_dev=4):
    from jax.sharding import PartitionSpec as P
    from amgx_tpu._compat import shard_map
    from amgx_tpu.distributed import DistributedSolver, default_mesh
    from amgx_tpu.distributed import comms
    from amgx_tpu.amg.cycles import run_cycle
    cfg = (
        "solver=FGMRES, max_iters=40, monitor_residual=1,"
        " tolerance=1e-7, gmres_n_restart=20, preconditioner(amg)=AMG,"
        " amg:algorithm=AGGREGATION, amg:selector=SIZE_2,"
        " amg:smoother=JACOBI_L1, amg:relaxation_factor=0.9,"
        " amg:max_iters=1, amg:cycle=V, amg:max_levels=3,"
        " amg:min_coarse_rows=16, amg:coarse_solver=DENSE_LU_SOLVER,"
        " amg:distributed_setup_mode=global")
    A = gallery.poisson("7pt", 8, 8, 16, dtype=jnp.float32).init()
    ds = DistributedSolver(Config.from_string(cfg), default_mesh(n_dev))
    ds.setup(A)
    amg, data = ds.solver.preconditioner.amg, \
        ds._data["precond"]["amg"]
    n = ds.part.n_global
    nl, R = ds.part.n_local, ds.n_ranks
    b = np.random.default_rng(0).standard_normal(n)

    def one_cycle(data, dtype):
        def body(d, bb, xx):
            dl = jax.tree.map(lambda a: a[0], d)
            with comms.collective_axis(ds.axis):
                return run_cycle(amg, "V", dl, bb[0], xx[0])[None]
        pspec = jax.tree.map(lambda _: P(ds.axis), data)
        fn = shard_map(body, mesh=ds.mesh,
                       in_specs=(pspec, P(ds.axis), P(ds.axis)),
                       out_specs=P(ds.axis), check_vma=False)
        pad = R * nl - n
        bl = jnp.pad(jnp.asarray(b, dtype), (0, pad)).reshape(R, nl)
        xl = jnp.zeros((R, nl), dtype)
        with ps.force_pallas_interpret():
            return np.asarray(fn(data, bl, xl),
                              np.float64).reshape(-1)[:n]

    return data, one_cycle


def _cast_tree(tree, dt):
    return jax.tree.map(
        lambda a: a.astype(dt) if hasattr(a, "dtype")
        and jnp.issubdtype(a.dtype, jnp.inexact) else a, tree)


def test_dist_bf16_exchange_bytes_exactly_half():
    """4-shard acceptance: the bf16 run's MODELED dist.comms bytes are
    exactly half the f32 run's (same window elements, itemsize 2 vs
    4 — PR-13's hand-computed-window discipline), and the bf16 sharded
    cycle tracks the f32 one at bf16 tolerance."""
    data, one_cycle = _dist_cycle_rig(n_dev=4)
    f0 = metrics.get("dist.comms.bytes_fwd")
    b0 = metrics.get("dist.comms.bytes_bwd")
    y32 = one_cycle(data, jnp.float32)
    f32b = metrics.get("dist.comms.bytes_fwd") - f0
    b32b = metrics.get("dist.comms.bytes_bwd") - b0
    assert f32b > 0 and b32b > 0
    data16 = _cast_tree(data, BF)
    f0 = metrics.get("dist.comms.bytes_fwd")
    b0 = metrics.get("dist.comms.bytes_bwd")
    y16 = one_cycle(data16, BF)
    f16b = metrics.get("dist.comms.bytes_fwd") - f0
    b16b = metrics.get("dist.comms.bytes_bwd") - b0
    assert f32b == 2 * f16b
    assert b32b == 2 * b16b
    assert _rel(y16, y32) < 5e-2


def test_dist_bf16_fused_vs_unfused_parity():
    """Sharded fused-vs-unfused parity at bf16: stripping the
    halo-folded payload (the dist_cycle_fusion=0 shape) composes the
    per-sweep exchange path; both answers agree at bf16 tolerance."""
    data, one_cycle = _dist_cycle_rig(n_dev=2)
    data16 = _cast_tree(data, BF)
    y_f = one_cycle(data16, BF)

    def strip(d):
        if isinstance(d, dict):
            return {k: strip(v) for k, v in d.items()
                    if k != "dist_fused"}
        if isinstance(d, list):
            return [strip(v) for v in d]
        return d

    y_u = one_cycle(strip(data16), BF)
    assert _rel(y_f, y_u) < 3e-2
