"""Fused smoother+residual kernel suite tests (ops/smooth.py,
ops/pallas_spmv.py dia_smooth, ops/pallas_swell.py swell_smooth_step).

The kernels run through the Pallas interpreter (force_pallas_interpret,
the CPU test path); the compiled path runs on real TPU via bench.py.
Covers: multi-sweep parity vs the sweep-by-sweep reference for
Jacobi-L1 and Chebyshev tau schedules on DIA and SWELL layouts, f32
(kernel) and f64 (the XLA slab fallback the custom_vmap routes to),
single-RHS and batched; a trace-count test proving the cycle does not
retrace when smooth_residual is enabled; and the HBM-pass regression
tooling: jaxpr inspection of the traced cycle asserting the fused path
removes the standalone residual SpMV at smoothed levels."""
import dataclasses
import numpy as np
import pytest
import jax
import jax.numpy as jnp

import amgx_tpu as amgx
from amgx_tpu import gallery
from amgx_tpu.config import Config
from amgx_tpu.ops import pallas_spmv as ps
from amgx_tpu.ops import smooth as fused
from amgx_tpu.ops.spmv import spmv

import _census

amgx.initialize()


def _ref_sweeps(A, b, x, taus, dinv=None, with_residual=True):
    """Sweep-by-sweep reference: x += tau_s * dinv . (b - A x)."""
    for t in range(taus.shape[0]):
        upd = taus[t] * (b - spmv(A, x))
        if dinv is not None:
            upd = upd * dinv
        x = x + upd
    if with_residual:
        return x, b - spmv(A, x)
    return x


def _rel(a, b):
    return float(jnp.linalg.norm(a - b) /
                 jnp.maximum(jnp.linalg.norm(b), 1e-300))


def _swell_matrix(n=24, dtype=jnp.float32):
    """Poisson 5-pt with the layout forced to SWELL."""
    from amgx_tpu.ops.pallas_swell import build_swell_host
    A = gallery.poisson("5pt", n, n, dtype=dtype).init()
    out = build_swell_host(np.asarray(A.row_offsets),
                           np.asarray(A.col_indices),
                           np.asarray(A.values, np.float32),
                           A.num_rows, A.num_cols)
    assert out is not None
    c4, v4, c0r, nch, w128 = out
    return dataclasses.replace(
        A, dia_offsets=None, dia_vals=None, ell_cols=None, ell_vals=None,
        swell_cols=jnp.asarray(c4), swell_vals=jnp.asarray(v4),
        swell_c0row=jnp.asarray(c0r), swell_nchunk=jnp.asarray(nch),
        swell_w128=int(w128))


# ---------------------------------------------------------------------------
# kernel parity (interpret mode)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("schedule,with_dinv", [
    ("jacobi", True),       # constant tau + dinv (JACOBI / JACOBI_L1)
    ("cheb", False),        # per-step taus, no dinv (CHEBYSHEV_POLY)
])
@pytest.mark.parametrize("with_residual", [True, False])
def test_dia_fused_parity_f32(schedule, with_dinv, with_residual):
    A = gallery.poisson("7pt", 10, 10, 10, dtype=jnp.float32).init()
    n = A.num_rows
    rng = np.random.default_rng(0)
    b = jnp.asarray(rng.standard_normal(n), jnp.float32)
    x = jnp.asarray(rng.standard_normal(n), jnp.float32)
    dinv = jnp.asarray(1.0 / rng.uniform(4, 8, n), jnp.float32) \
        if with_dinv else None
    taus = jnp.asarray(np.full(3, 0.9) if schedule == "jacobi"
                       else rng.uniform(0.05, 0.2, 3), jnp.float32)
    ref = _ref_sweeps(A, b, x, taus, dinv, with_residual)
    with ps.force_pallas_interpret():
        slabs = fused.build_fused_slabs(A, dinv)
        out = fused.dia_fused_smooth(A, slabs, b, x, taus, dinv=dinv,
                                     with_residual=with_residual)
    assert out is not None
    if with_residual:
        assert _rel(out[0], ref[0]) < 1e-6
        assert _rel(out[1], ref[1]) < 1e-6
    else:
        assert _rel(out, ref) < 1e-6


def test_dia_fused_parity_multiblock_and_chained():
    """Small VMEM budget forces both the multi-block double-buffered
    DMA path and the chained (per-chunk) dispatch."""
    A = gallery.poisson("7pt", 16, 16, 16, dtype=jnp.float32).init()
    n = A.num_rows
    rng = np.random.default_rng(1)
    b = jnp.asarray(rng.standard_normal(n), jnp.float32)
    x = jnp.asarray(rng.standard_normal(n), jnp.float32)
    dinv = jnp.asarray(1.0 / rng.uniform(4, 8, n), jnp.float32)
    taus = jnp.asarray(np.full(3, 0.8), jnp.float32)
    ref = _ref_sweeps(A, b, x, taus, dinv, True)
    old = ps._SMOOTH_VMEM_BUDGET
    try:
        for budget in (300 * 1024, 120 * 1024):   # multi-block; chained
            ps._SMOOTH_VMEM_BUDGET = budget
            with ps.force_pallas_interpret():
                slabs = fused.build_fused_slabs(A, dinv)
                xf, rf = fused.dia_fused_smooth(A, slabs, b, x, taus,
                                                dinv=dinv,
                                                with_residual=True)
            assert _rel(xf, ref[0]) < 1e-6
            assert _rel(rf, ref[1]) < 1e-6
    finally:
        ps._SMOOTH_VMEM_BUDGET = old


def test_dia_slab_fallback_parity_f64():
    """The XLA multi-RHS slab form (what f64 and vmapped callers run)
    matches the sweep-by-sweep reference to f64 accuracy."""
    from amgx_tpu.ops.batched import smooth_dia_multi
    A = gallery.poisson("7pt", 8, 8, 8).init()      # f64
    n = A.num_rows
    rng = np.random.default_rng(2)
    B = jnp.asarray(rng.standard_normal((3, n)))
    X = jnp.asarray(rng.standard_normal((3, n)))
    dinv = jnp.asarray(1.0 / rng.uniform(4, 8, n))
    taus = jnp.asarray(np.full(2, 0.85))
    XF, RF = smooth_dia_multi(A, B, X, taus, dinv, True)
    for i in range(3):
        xr, rr = _ref_sweeps(A, B[i], X[i], taus, dinv, True)
        assert _rel(XF[i], xr) < 1e-12
        assert _rel(RF[i], rr) < 1e-12


def test_dia_fused_vmap_routes_to_slab():
    """Under jax.vmap (the batched-solve subsystem's shape) the fused
    dispatch must take the multi-RHS slab form and match per-system
    references — single-RHS kernels have no batching rule."""
    A = gallery.poisson("7pt", 8, 8, 8, dtype=jnp.float32).init()
    n = A.num_rows
    rng = np.random.default_rng(3)
    B = jnp.asarray(rng.standard_normal((4, n)), jnp.float32)
    X = jnp.asarray(rng.standard_normal((4, n)), jnp.float32)
    dinv = jnp.asarray(1.0 / rng.uniform(4, 8, n), jnp.float32)
    taus = jnp.asarray(np.full(2, 0.9), jnp.float32)
    with ps.force_pallas_interpret():
        slabs = fused.build_fused_slabs(A, dinv)
        XF, RF = jax.vmap(
            lambda bb, xx: fused.dia_fused_smooth(
                A, slabs, bb, xx, taus, dinv=dinv, with_residual=True)
        )(B, X)
    for i in range(4):
        xr, rr = _ref_sweeps(A, B[i], X[i], taus, dinv, True)
        assert _rel(XF[i], xr) < 1e-6
        assert _rel(RF[i], rr) < 1e-6


@pytest.mark.parametrize("with_dinv", [True, False])
def test_swell_fused_step_parity(with_dinv):
    A = _swell_matrix()
    n = A.num_rows
    rng = np.random.default_rng(4)
    b = jnp.asarray(rng.standard_normal(n), jnp.float32)
    x = jnp.asarray(rng.standard_normal(n), jnp.float32)
    dinv = jnp.asarray(1.0 / rng.uniform(3, 6, n), jnp.float32) \
        if with_dinv else None
    taus = jnp.asarray(np.full(2, 0.7), jnp.float32)
    ref = _ref_sweeps(A, b, x, taus, dinv, True)
    with ps.force_pallas_interpret():
        out = fused.swell_fused_smooth(A, b, x, taus, dinv=dinv,
                                       with_residual=True)
    assert out is not None
    assert _rel(out[0], ref[0]) < 1e-6
    assert _rel(out[1], ref[1]) < 1e-6


def test_fused_smooth_solver_entry_matches_unfused():
    """Solver-level parity: JACOBI_L1.smooth_residual with the fused
    path engaged equals the fused_smoother=0 compose."""
    from amgx_tpu.solvers.base import make_solver
    A = gallery.poisson("7pt", 10, 10, 10, dtype=jnp.float32).init()
    n = A.num_rows
    rng = np.random.default_rng(5)
    b = jnp.asarray(rng.standard_normal(n), jnp.float32)
    x = jnp.asarray(rng.standard_normal(n), jnp.float32)
    cfg = Config.from_string("solver=JACOBI_L1, max_iters=2")
    off = make_solver("JACOBI_L1", cfg, "default")
    off.fused_smoother = False
    off.setup(A)
    x_off, r_off = off.smooth_residual(off.solve_data(), b, x, 2)
    with ps.force_pallas_interpret():
        on = make_solver("JACOBI_L1", cfg, "default")
        on.setup(A)
        d = on.solve_data()
        assert "fused" in d, "fused payload missing from solve_data"
        x_on, r_on = on.smooth_residual(d, b, x, 2)
    assert _rel(x_on, x_off) < 1e-6
    assert _rel(r_on, r_off) < 1e-6


# ---------------------------------------------------------------------------
# cycle integration: trace count + HBM passes per level
# ---------------------------------------------------------------------------

_CYCLE_CFG = (
    "solver(s)=PCG, s:max_iters=30, s:tolerance=1e-7,"
    " s:convergence=RELATIVE_INI, s:monitor_residual=1,"
    " s:preconditioner(amg)=AMG, amg:algorithm=AGGREGATION,"
    " amg:selector=GEO, amg:smoother=JACOBI_L1, amg:presweeps=2,"
    " amg:postsweeps=1, amg:max_iters=1,"
    " amg:coarse_solver=DENSE_LU_SOLVER, amg:min_coarse_rows=16,"
    " amg:max_levels=10")


def _cycle_pallas_counts(extra_cfg=""):
    """Trace one V-cycle with the Pallas gates forced on; return
    (n_levels, fused_calls, plain_spmv_calls) from the jaxpr. Pinned
    to cycle_fusion=0: this file proves the PR-4 smoother+residual
    composition (which the cycle_fusion knob's escape hatch must keep
    reproducing); the fused grid-transfer / coarse-tail shapes are
    proven by tests/test_cycle_fusion.py."""
    A = gallery.poisson("7pt", 16, 16, 16, dtype=jnp.float32).init()
    b = jnp.ones(A.num_rows, jnp.float32)
    with ps.force_pallas_interpret():
        slv = amgx.create_solver(
            Config.from_string(_CYCLE_CFG + ", amg:cycle_fusion=0"
                               + extra_cfg))
        slv.setup(A)
        pc = slv.preconditioner
        d = pc.solve_data()
        jaxpr = str(jax.make_jaxpr(
            lambda bb, xx: pc.amg.cycle(d["amg"], bb, xx))(
                b, jnp.zeros_like(b)))
    names = _census.KERNEL_NAME_RE.findall(jaxpr)
    fused_calls = sum(1 for nm in names if "dia_smooth" in nm)
    plain = sum(1 for nm in names if "dia_spmv" in nm)
    return len(pc.amg.levels), fused_calls, plain


def test_cycle_hbm_passes_fused_removes_residual_spmv():
    """HBM-pass regression tooling: per smoothed DIA level the fused
    cycle must run exactly TWO single-pass kernels (presmooth+residual
    fused; postsmooth fused) and ZERO standalone dia-SpMV kernels —
    i.e. the presmooth->residual pair costs one pass over A instead of
    presweeps+1, at every level. The unfused trace of the same cycle
    shows the removed passes."""
    n_levels, fused_calls, plain = _cycle_pallas_counts()
    assert n_levels >= 2
    assert fused_calls == 2 * n_levels, \
        f"expected 2 fused kernels per level, got {fused_calls} for " \
        f"{n_levels} levels"
    assert plain == 0, \
        f"{plain} standalone dia-SpMV kernels remain in the fused cycle"
    n2, fused_off, plain_off = _cycle_pallas_counts(
        ", fused_smoother=0")
    assert n2 == n_levels
    assert fused_off == 0
    # the jaxpr counts SpMV *sites*, not dynamic passes (a fori_loop
    # body traces once for all sweeps): per level the unfused cycle
    # keeps >= 3 dia-SpMV sites — the smoother's sweep body (pre and
    # post) plus the standalone residual the fused path eliminates
    assert plain_off >= 3 * n_levels, \
        f"unfused cycle expected >= {3 * n_levels} dia-SpMV sites, " \
        f"got {plain_off}"


def test_cycle_does_not_retrace_with_fused_smoother():
    """One jit trace serves repeated solves (and a value-only change)
    when smooth_residual/fused kernels are enabled."""
    A = gallery.poisson("7pt", 12, 12, 12, dtype=jnp.float32).init()
    n = A.num_rows
    rng = np.random.default_rng(6)
    with ps.force_pallas_interpret():
        slv = amgx.create_solver(Config.from_string(_CYCLE_CFG))
        slv.setup(A)
        r1 = slv.solve(jnp.asarray(rng.standard_normal(n), jnp.float32))
        assert len(slv._jit_cache) == 1
        r2 = slv.solve(jnp.asarray(rng.standard_normal(n), jnp.float32))
        assert len(slv._jit_cache) == 1, \
            "cycle retraced on a value-only change of b"
        assert r1.converged and r2.converged


def test_cycle_fused_matches_unfused_solution():
    """End-to-end: the fused cycle converges to the same answer in the
    same iteration count as the unfused one."""
    A = gallery.poisson("7pt", 12, 12, 12, dtype=jnp.float32).init()
    b = jnp.ones(A.num_rows, jnp.float32)
    ref = amgx.create_solver(
        Config.from_string(_CYCLE_CFG + ", fused_smoother=0"))
    ref.setup(A)
    r0 = ref.solve(b)
    with ps.force_pallas_interpret():
        slv = amgx.create_solver(Config.from_string(_CYCLE_CFG))
        slv.setup(A)
        r1 = slv.solve(b)
    assert r1.converged
    assert abs(r1.iterations - r0.iterations) <= 1
    assert _rel(r1.x, r0.x) < 1e-4


def test_fused_payload_refreshes_on_resetup():
    """The quota-padded operand slabs are rebuilt when the matrix
    coefficients change (the solve-data resetup contract)."""
    from amgx_tpu.solvers.base import make_solver
    A = gallery.poisson("7pt", 8, 8, 8, dtype=jnp.float32).init()
    cfg = Config.from_string("solver=JACOBI_L1, max_iters=2")
    with ps.force_pallas_interpret():
        s = make_solver("JACOBI_L1", cfg, "default")
        s.setup(A)
        v1 = s.solve_data()["fused"]["vals_q"]
        A2 = A.with_values(A.values * 2.0)
        s.resetup(A2 if A2.initialized else A2.init())
        v2 = s.solve_data()["fused"]["vals_q"]
    assert v1 is not v2
    np.testing.assert_allclose(np.asarray(v2), 2.0 * np.asarray(v1),
                               rtol=1e-6)
