"""Solver-layer tests (analogs of fgmres_convergence_poisson.cu,
nested_solvers.cu, solver behavior tests in src/tests/)."""
import jax.numpy as jnp
import numpy as np
import pytest

import amgx_tpu as amgx
from amgx_tpu import gallery, ops
from amgx_tpu.config import Config
from amgx_tpu.solvers import make_solver

amgx.initialize()


@pytest.fixture(scope="module")
def poisson32():
    return gallery.poisson("5pt", 32, 32).init()


@pytest.fixture(scope="module")
def rhs32(poisson32):
    return jnp.ones(poisson32.num_rows)


def true_res(A, x, b):
    return float(np.linalg.norm(np.asarray(ops.residual(A, x, b))))


KRYLOV_CONFIGS = [
    ("CG", "max_iters=400, monitor_residual=1, tolerance=1e-10", 400),
    ("BICGSTAB", "max_iters=400, monitor_residual=1, tolerance=1e-10", 400),
    ("GMRES", "max_iters=500, monitor_residual=1, tolerance=1e-10,"
     " gmres_n_restart=20, preconditioner=NOSOLVER", 500),
    ("PCG", "max_iters=400, monitor_residual=1, tolerance=1e-10,"
     " preconditioner(j)=BLOCK_JACOBI, j:max_iters=3", 200),
    ("PCGF", "max_iters=400, monitor_residual=1, tolerance=1e-10,"
     " preconditioner(j)=BLOCK_JACOBI, j:max_iters=3", 200),
    ("PBICGSTAB", "max_iters=400, monitor_residual=1, tolerance=1e-10,"
     " preconditioner(j)=BLOCK_JACOBI, j:max_iters=3", 200),
    ("FGMRES", "max_iters=400, monitor_residual=1, tolerance=1e-10,"
     " gmres_n_restart=20, preconditioner(j)=BLOCK_JACOBI, j:max_iters=3",
     200),
]


@pytest.mark.parametrize("name,opts,max_expected", KRYLOV_CONFIGS,
                         ids=[c[0] for c in KRYLOV_CONFIGS])
def test_krylov_converges_poisson(poisson32, rhs32, name, opts, max_expected):
    """Residual must beat the configured tolerance (reference:
    fgmres_convergence_poisson.cu semantics)."""
    s = make_solver(name, Config.from_string(opts))
    s.setup(poisson32)
    res = s.solve(rhs32)
    assert res.converged, f"{name} did not converge"
    assert res.iterations <= max_expected
    # the solver's own residual claim must match the true residual
    tr = true_res(poisson32, res.x, rhs32)
    assert tr <= 5e-9, f"{name}: true residual {tr}"


def test_cg_matches_dense_solution(poisson32, rhs32):
    s = make_solver("CG", Config.from_string(
        "max_iters=2000, monitor_residual=1, tolerance=1e-12"))
    s.setup(poisson32)
    res = s.solve(rhs32)
    x_ref = np.linalg.solve(np.asarray(poisson32.to_dense()),
                            np.asarray(rhs32))
    assert np.allclose(np.asarray(res.x), x_ref, atol=1e-8)


def test_jacobi_reduces_residual(poisson32, rhs32):
    s = make_solver("BLOCK_JACOBI", Config.from_string(
        "max_iters=100, monitor_residual=1, tolerance=1e-30,"
        " relaxation_factor=0.8"))
    s.setup(poisson32)
    res = s.solve(rhs32)
    assert float(np.max(res.res_norm)) < float(np.max(res.norm0))


def test_jacobi_l1_spd_monotone():
    A = gallery.random_matrix(60, max_nnz_per_row=5, seed=3, symmetric=True,
                              diag_dominant=True).init()
    b = jnp.ones(60)
    s = make_solver("JACOBI_L1", Config.from_string(
        "max_iters=50, monitor_residual=1, tolerance=1e-12,"
        " relaxation_factor=1.0, store_res_history=1"))
    s.setup(A)
    res = s.solve(b)
    hist = res.res_history
    assert hist is not None
    assert hist[-1] < hist[0]


def test_block_matrix_pcg():
    A = gallery.random_matrix(50, max_nnz_per_row=4, seed=7, symmetric=True,
                              diag_dominant=True, block_dims=(2, 2)).init()
    b = jnp.ones(100)
    s = make_solver("PCG", Config.from_string(
        "max_iters=300, monitor_residual=1, tolerance=1e-10,"
        " preconditioner(j)=BLOCK_JACOBI, j:max_iters=2"))
    s.setup(A)
    res = s.solve(b)
    assert res.converged
    assert true_res(A, res.x, b) < 1e-8


def test_dense_lu_direct(poisson32, rhs32):
    s = make_solver("DENSE_LU_SOLVER", Config.from_string(
        "max_iters=1, monitor_residual=1, tolerance=1e-10"))
    s.setup(poisson32)
    res = s.solve(rhs32)
    assert res.iterations == 1
    assert true_res(poisson32, res.x, rhs32) < 1e-10


def test_nested_solvers():
    """Nested preconditioning: FGMRES <- PCG <- Jacobi
    (nested_solvers.cu analog)."""
    A = gallery.poisson("5pt", 16, 16).init()
    b = jnp.ones(A.num_rows)
    cfg = Config.from_string(
        "max_iters=100, monitor_residual=1, tolerance=1e-10,"
        " gmres_n_restart=10, preconditioner(p1)=PCG,"
        " p1:max_iters=3, p1:preconditioner(p2)=BLOCK_JACOBI,"
        " p2:max_iters=2")
    s = make_solver("FGMRES", cfg)
    s.setup(A)
    res = s.solve(b)
    assert res.converged
    assert true_res(A, res.x, b) < 1e-8


def test_convergence_criteria_relative_ini(poisson32, rhs32):
    cfg = Config.from_string(
        "max_iters=400, monitor_residual=1, tolerance=1e-6,"
        " convergence=RELATIVE_INI")
    s = make_solver("CG", cfg)
    s.setup(poisson32)
    res = s.solve(rhs32)
    assert res.converged
    assert float(np.max(res.res_norm)) <= 1e-6 * float(np.max(res.norm0))


def test_divergence_detection():
    """rel_div_tolerance aborts a diverging iteration."""
    # -A is negative definite: plain CG diverges/stalls
    A = gallery.poisson("5pt", 8, 8)
    import jax.numpy as jnp2
    A = A.with_values(A.values)  # keep structure
    b = jnp2.ones(64)
    s = make_solver("BLOCK_JACOBI", Config.from_string(
        "max_iters=100, monitor_residual=1, tolerance=1e-12,"
        " relaxation_factor=1.9, rel_div_tolerance=1e3"))
    s.setup(A.init())
    res = s.solve(b)
    assert not res.converged
    assert res.iterations < 100  # stopped early by divergence check


def test_zero_rhs(poisson32):
    """b = 0 must return x = 0 and converge immediately."""
    s = make_solver("CG", Config.from_string(
        "max_iters=10, monitor_residual=1, tolerance=1e-10"))
    s.setup(poisson32)
    res = s.solve(jnp.zeros(poisson32.num_rows))
    assert res.converged
    assert res.iterations == 0
    assert float(np.max(np.abs(np.asarray(res.x)))) == 0.0


def test_initial_guess(poisson32, rhs32):
    """Starting from the exact solution converges in 0 iterations."""
    x_ref = jnp.asarray(np.linalg.solve(np.asarray(poisson32.to_dense()),
                                        np.asarray(rhs32)))
    s = make_solver("CG", Config.from_string(
        "max_iters=10, monitor_residual=1, tolerance=1e-8"))
    s.setup(poisson32)
    res = s.solve(rhs32, x0=x_ref)
    assert res.iterations == 0


def test_res_history_monotone_cg(poisson32, rhs32):
    s = make_solver("PCG", Config.from_string(
        "max_iters=200, monitor_residual=1, tolerance=1e-10,"
        " store_res_history=1, preconditioner(j)=BLOCK_JACOBI,"
        " j:max_iters=2"))
    s.setup(poisson32)
    res = s.solve(rhs32)
    hist = res.res_history
    assert hist[-1] <= 1e-10 * 1e12  # sanity
    assert hist.shape[0] == res.iterations + 1


@pytest.mark.slow
def test_chebyshev_resetup_rebakes_spectrum(poisson32, rhs32):
    """CHEBYSHEV bakes its lambda estimates into the trace as Python
    floats; a value-only resetup must re-trace (base.py jit-cache gate
    consults _resetup_kept_static), or the solve silently runs with the
    OLD smoothing interval."""
    import numpy as np
    s = make_solver("CHEBYSHEV", Config.from_string(
        "max_iters=300, monitor_residual=1, tolerance=1e-5,"
        " convergence=RELATIVE_INI,"
        " chebyshev_lambda_estimate_mode=2"))
    s.setup(poisson32)
    r1 = s.solve(rhs32)
    assert bool(r1.converged)
    A2 = poisson32.with_values(poisson32.values * 50.0)
    s.resetup(A2)
    r2 = s.solve(rhs32)
    assert bool(r2.converged), "stale spectrum bounds after resetup"
    resid = np.asarray(rhs32) - np.asarray(A2.to_dense()) @ np.asarray(r2.x)
    assert np.linalg.norm(resid) < 1e-3 * np.linalg.norm(np.asarray(rhs32))
