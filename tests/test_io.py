"""IO tests (analogs of capi_upload_tests.cu / matrix IO paths)."""
import os

import numpy as np
import jax.numpy as jnp
import pytest

from amgx_tpu import gallery
from amgx_tpu.io import read_system, write_system
from amgx_tpu.matrix import CsrMatrix


def dense(A):
    return np.asarray(A.to_dense())


@pytest.mark.skipif(
    not os.path.exists("/root/reference/examples/matrix.mtx"),
    reason="reference checkout not present on this machine")
def test_reference_example_matrix():
    # the 12-row demo matrix shipped with the reference (examples/matrix.mtx)
    A, b, x = read_system("/root/reference/examples/matrix.mtx")
    assert A.shape == (12, 12)
    assert A.nnz == 61
    assert b is None and x is None
    d = dense(A)
    assert d[0, 0] == 1.0 and d[0, 1] == 2.0 and d[0, 3] == 3.0


def test_roundtrip_matrixmarket(tmp_path):
    A = gallery.poisson("5pt", 6, 5)
    rng = np.random.default_rng(0)
    b = jnp.asarray(rng.standard_normal(A.num_rows))
    p = str(tmp_path / "sys.mtx")
    write_system(p, A, b=b)
    A2, b2, x2 = read_system(p)
    assert np.allclose(dense(A2), dense(A))
    assert np.allclose(np.asarray(b2), np.asarray(b))
    assert x2 is None


def test_roundtrip_block_diag(tmp_path):
    A = gallery.random_matrix(10, max_nnz_per_row=4, seed=5,
                              block_dims=(2, 2))
    p = str(tmp_path / "blk.mtx")
    write_system(p, A)
    A2, _, _ = read_system(p)
    assert A2.block_dimx == 2 and A2.block_dimy == 2
    assert np.allclose(dense(A2), dense(A))


def test_symmetric_expansion(tmp_path):
    p = tmp_path / "sym.mtx"
    p.write_text(
        "%%MatrixMarket matrix coordinate real symmetric\n"
        "3 3 4\n1 1 2.0\n2 1 -1.0\n2 2 2.0\n3 3 1.0\n")
    A, _, _ = read_system(str(p))
    d = dense(A)
    assert np.allclose(d, [[2, -1, 0], [-1, 2, 0], [0, 0, 1]])


def test_pattern_accepted(tmp_path):
    p = tmp_path / "pat.mtx"
    p.write_text(
        "%%MatrixMarket matrix coordinate pattern general\n"
        "2 2 3\n1 1\n1 2\n2 2\n")
    A, _, _ = read_system(str(p))
    assert np.allclose(dense(A), [[1, 1], [0, 1]])


def test_roundtrip_binary(tmp_path):
    A = gallery.poisson("7pt", 4, 4, 4)
    rng = np.random.default_rng(1)
    b = jnp.asarray(rng.standard_normal(A.num_rows))
    x = jnp.asarray(rng.standard_normal(A.num_rows))
    p = str(tmp_path / "sys.bin")
    write_system(p, A, b=b, x=x, fmt="binary")
    A2, b2, x2 = read_system(p)
    assert np.allclose(dense(A2), dense(A))
    assert np.allclose(np.asarray(b2), np.asarray(b))
    assert np.allclose(np.asarray(x2), np.asarray(x))


def test_external_diag_roundtrip(tmp_path):
    A = CsrMatrix.from_coo([0, 1], [1, 0], [-1.0, -2.0], 2, 2,
                           diag=jnp.asarray([3.0, 4.0]))
    p = str(tmp_path / "diag.mtx")
    write_system(p, A)
    A2, _, _ = read_system(p)
    assert A2.has_external_diag
    assert np.allclose(dense(A2), dense(A))


def test_native_body_parser_matches_fallback(tmp_path):
    """The C parser and the numpy tokenizer agree on the full body
    (matrix entries + trailing vector section, comments interleaved)."""
    from amgx_tpu.io.matrix_market import _parse_body
    body = ["1 1 4.0\n", "% interleaved comment\n", "1 2 -1.5\n",
            "2 2 3.25e1\n", "  2 1 -7e-2\n", "0.5 0.25\n"]
    expect = np.array([1, 1, 4.0, 1, 2, -1.5, 2, 2, 32.5,
                       2, 1, -7e-2, 0.5, 0.25])
    out = _parse_body(body, 14)          # full token count, no truncation
    np.testing.assert_allclose(out, expect)
    # fallback path parses identically
    import amgx_tpu.native as nat
    orig = nat.lib
    try:
        nat.lib = lambda: None
        out_py = _parse_body(body, 14)
    finally:
        nat.lib = orig
    np.testing.assert_allclose(out_py[:14], expect)


def test_native_parser_roundtrip(tmp_path):
    """write_system -> read_system through the native parser is exact."""
    A = gallery.poisson("9pt", 12, 12).init()
    rng = np.random.default_rng(0)
    b = rng.standard_normal(144)
    p = str(tmp_path / "rt.mtx")
    write_system(p, A, b=jnp.asarray(b))
    A2, b2, _ = read_system(p)
    np.testing.assert_allclose(dense(A2), dense(A), rtol=1e-15)
    np.testing.assert_allclose(np.asarray(b2), b, rtol=1e-15)
