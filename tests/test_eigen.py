"""Eigensolver subsystem tests.

Mirrors the reference's eigensolver coverage (eigen_examples/, power
method on Poisson): every registered eigensolver must find the requested
eigenpairs of a 5-pt Poisson (or small nonsymmetric) matrix to tolerance
against a dense numpy reference.
"""
import numpy as np
import pytest

import amgx_tpu as amgx
from amgx_tpu.config import Config
from amgx_tpu.eigen import create_eigensolver
from amgx_tpu.gallery import poisson5pt
from amgx_tpu.matrix import CsrMatrix

amgx.initialize()


def _dense_eigs(A):
    return np.linalg.eigvalsh(np.asarray(A.to_dense()))


@pytest.fixture(scope="module")
def poisson():
    # rectangular grid -> distinct eigenvalues (a square grid's spectrum
    # has multiplicity-2 pairs that single-vector Krylov cannot resolve)
    A = poisson5pt(10, 7)            # n = 70
    lam = _dense_eigs(A)
    return A, lam


def _solve(A, cfg_str):
    es = create_eigensolver(Config.from_string(cfg_str))
    es.setup(A)
    return es.solve()


def test_power_iteration_largest(poisson):
    A, lam = poisson
    res = _solve(A, "eig_solver=POWER_ITERATION, eig_max_iters=2000, "
                    "eig_tolerance=1e-8, eig_eigenvector=1")
    assert res.converged
    np.testing.assert_allclose(res.eigenvalues[0], lam[-1], rtol=1e-6)
    # eigenvector residual
    v = res.eigenvectors[:, 0]
    Ad = np.asarray(A.to_dense())
    assert np.linalg.norm(Ad @ v - res.eigenvalues[0] * v) < 1e-5


def test_power_iteration_shifted(poisson):
    A, lam = poisson
    # shift past the dominant end: power iteration on A - s I converges
    # to the SMALLEST eigenvalue when s > (lam_max+lam_min)/2
    res = _solve(A, "eig_solver=POWER_ITERATION, eig_shift=8.0, "
                    "eig_max_iters=4000, eig_tolerance=1e-8")
    assert res.converged
    np.testing.assert_allclose(res.eigenvalues[0], lam[0], atol=1e-5)


def test_inverse_iteration_smallest(poisson):
    A, lam = poisson
    res = _solve(A, "eig_solver=INVERSE_ITERATION, eig_max_iters=50, "
                    "eig_tolerance=1e-9, solver=CG, max_iters=200, "
                    "tolerance=1e-12, monitor_residual=1")
    assert res.converged
    np.testing.assert_allclose(res.eigenvalues[0], lam[0], rtol=1e-6)


def test_lanczos_extreme_pairs(poisson):
    A, lam = poisson
    res = _solve(A, "eig_solver=LANCZOS, eig_wanted_count=3, "
                    "eig_which=largest, eig_max_iters=40, "
                    "eig_subspace_size=40, eig_tolerance=1e-8, "
                    "eig_eigenvector=1")
    assert res.converged
    np.testing.assert_allclose(np.sort(res.eigenvalues), lam[-3:],
                               rtol=1e-6)
    # Ritz vectors are real eigenvectors
    Ad = np.asarray(A.to_dense())
    for i in range(3):
        v, l = res.eigenvectors[:, i], res.eigenvalues[i]
        assert np.linalg.norm(Ad @ v - l * v) < 1e-5


def test_lanczos_smallest(poisson):
    A, lam = poisson
    res = _solve(A, "eig_solver=LANCZOS, eig_wanted_count=2, "
                    "eig_which=smallest, eig_max_iters=60, "
                    "eig_subspace_size=50, eig_tolerance=1e-7")
    assert res.converged
    np.testing.assert_allclose(np.sort(res.eigenvalues), lam[:2],
                               rtol=1e-5)


def test_arnoldi_nonsymmetric():
    # convection-diffusion-like: Poisson + asymmetric first-order term
    A = poisson5pt(8, 8)
    ro = np.asarray(A.row_offsets)
    ci = np.asarray(A.col_indices)
    vals = np.asarray(A.values).copy()
    row_ids = np.repeat(np.arange(A.num_rows), np.diff(ro))
    vals[ci > row_ids] += 0.3       # upwind bias
    B = CsrMatrix.from_scipy_like(ro, ci, vals, A.num_rows, A.num_cols)
    lam_ref = np.linalg.eigvals(np.asarray(B.to_dense()))
    lam_max = lam_ref[np.argmax(lam_ref.real)]
    res = _solve(B, "eig_solver=ARNOLDI, eig_wanted_count=1, "
                    "eig_subspace_size=40, eig_tolerance=1e-7")
    assert res.converged
    np.testing.assert_allclose(res.eigenvalues[0], lam_max.real, rtol=1e-6)


def test_lobpcg_smallest_preconditioned(poisson):
    A, lam = poisson
    res = _solve(A, "eig_solver=LOBPCG, eig_which=smallest, "
                    "eig_wanted_count=3, eig_max_iters=200, "
                    "eig_tolerance=1e-7, eig_eigenvector=1, "
                    "preconditioner=BLOCK_JACOBI, max_iters=3")
    assert res.converged
    np.testing.assert_allclose(np.sort(res.eigenvalues), lam[:3],
                               rtol=1e-5)


def test_subspace_iteration_largest(poisson):
    A, lam = poisson
    res = _solve(A, "eig_solver=SUBSPACE_ITERATION, eig_wanted_count=2, "
                    "eig_max_iters=500, eig_tolerance=1e-7, "
                    "eig_subspace_size=6")
    assert res.converged
    np.testing.assert_allclose(np.sort(res.eigenvalues), lam[-2:],
                               rtol=1e-5)


def test_jacobi_davidson_largest(poisson):
    A, lam = poisson
    res = _solve(A, "eig_solver=JACOBI_DAVIDSON, eig_max_iters=200, "
                    "eig_tolerance=1e-7, eig_subspace_size=12")
    assert res.converged
    np.testing.assert_allclose(res.eigenvalues[0], lam[-1], rtol=1e-6)


def test_jacobi_davidson_smallest(poisson):
    A, lam = poisson
    res = _solve(A, "eig_solver=JACOBI_DAVIDSON, eig_which=smallest, "
                    "eig_max_iters=300, eig_tolerance=1e-7, "
                    "eig_subspace_size=12")
    assert res.converged
    np.testing.assert_allclose(res.eigenvalues[0], lam[0], atol=1e-5)


def test_pagerank_stationary_distribution():
    # small directed graph: ring with a chord and one dangling node
    n = 6
    edges = [(0, 1), (1, 2), (2, 3), (3, 4), (4, 0), (1, 3), (0, 2)]
    # node 5 dangles (no out-edges); add incoming edge 4->5
    edges.append((4, 5))
    rows = np.array([e[0] for e in edges])
    cols = np.array([e[1] for e in edges])
    vals = np.ones(len(edges))
    A = CsrMatrix.from_coo(rows, cols, vals, n, n)
    d = 0.85
    # dense reference Google matrix
    P = np.zeros((n, n))
    for r, c in edges:
        P[r, c] = 1.0
    deg = P.sum(1)
    dang = deg == 0
    Pn = np.divide(P, np.maximum(deg[:, None], 1), out=np.zeros_like(P),
                   where=deg[:, None] > 0)
    G = d * Pn + np.outer(d * dang + (1 - d), np.ones(n) / n)
    pi = np.ones(n) / n
    for _ in range(500):
        pi = G.T @ pi
        pi /= pi.sum()
    res = _solve(A, "eig_solver=PAGERANK, eig_damping_factor=0.85, "
                    "eig_max_iters=500, eig_tolerance=1e-10")
    assert res.converged
    np.testing.assert_allclose(res.eigenvalues[0], 1.0, atol=1e-6)
    v = res.eigenvectors[:, 0]
    v = v / v.sum()
    np.testing.assert_allclose(v, pi, atol=1e-8)


def test_eigensolver_factory_names():
    from amgx_tpu import registry
    for name in ("POWER_ITERATION", "SINGLE_ITERATION", "PAGERANK",
                 "INVERSE_ITERATION", "SUBSPACE_ITERATION", "LANCZOS",
                 "ARNOLDI", "LOBPCG", "JACOBI_DAVIDSON"):
        assert registry.eigensolvers.has(name), name
