"""Resilience subsystem tests (amgx_tpu/resilience/).

Proves, via deterministic fault injection, that EVERY SolveStatus code
is reachable and that every fallback action recovers from its
designated fault — the acceptance contract of the resilience layer.
"""
import numpy as np
import pytest
import scipy.sparse as sp

import amgx_tpu as amgx
from amgx_tpu import gallery
from amgx_tpu.config import Config
from amgx_tpu.errors import AMGXError, BadConfigurationError
from amgx_tpu.resilience import SolveStatus, faultinject as fi
from amgx_tpu.resilience.policy import (ResilientSolver,
                                        parse_fallback_policy)

amgx.initialize()


def _csr(Asp):
    Asp = Asp.tocsr()
    n = Asp.shape[0]
    return amgx.CsrMatrix.from_scipy_like(
        Asp.indptr, Asp.indices, Asp.data, n, n).init()


def _poisson16():
    return gallery.poisson("5pt", 16, 16).init()


def _indefinite(n=64):
    """Symmetric indefinite tridiagonal: CG's p.Ap <= 0 breakdown."""
    d = np.ones(n)
    d[::2] = -1.0
    off = 0.1 * np.ones(n - 1)
    return _csr(sp.diags([d, off, off], [0, 1, -1]))


def _nondominant(n=32):
    """Jacobi iteration matrix has spectral radius > 1: divergence."""
    return _csr(sp.diags([np.ones(n), 2.0 * np.ones(n - 1),
                          2.0 * np.ones(n - 1)], [0, 1, -1]))


def _badly_scaled(n_side=16, seed=0):
    """D A D with a 8-decade diagonal spread: CG crawls unscaled,
    converges after a DIAGONAL_SYMMETRIC rescale."""
    A = gallery.poisson("5pt", n_side, n_side).init()
    n = A.num_rows
    Ap = sp.csr_matrix((np.asarray(A.values), np.asarray(A.col_indices),
                        np.asarray(A.row_offsets)), shape=(n, n))
    d = 10.0 ** np.random.default_rng(seed).uniform(-4, 4, n)
    D = sp.diags(d)
    return _csr(D @ Ap @ D)


def _cg(extra="", max_iters=200, tol="1e-8"):
    return amgx.create_solver(Config.from_string(
        f"solver=CG, max_iters={max_iters}, monitor_residual=1,"
        f" tolerance={tol}, convergence=RELATIVE_INI" +
        (", " + extra if extra else "")))


# ---------------------------------------------------------------------------
# every SolveStatus code is reachable
# ---------------------------------------------------------------------------


class TestStatusReachability:
    def test_converged(self):
        A = _poisson16()
        slv = _cg().setup(A)
        res = slv.solve(np.ones(A.num_rows))
        assert res.status_code == SolveStatus.CONVERGED
        assert res.status == "success" and res.converged

    def test_zero_rhs_is_converged_at_zero_iters(self):
        # norm0 == 0 guard: x = x0 with CONVERGED instead of feeding a
        # zero norm into the relative-tolerance arithmetic
        A = _poisson16()
        slv = _cg().setup(A)
        res = slv.solve(np.zeros(A.num_rows))
        assert res.status_code == SolveStatus.CONVERGED
        assert res.iterations == 0
        assert np.all(np.asarray(res.x) == 0)

    def test_max_iters(self):
        A = _poisson16()
        slv = _cg(max_iters=3, tol="1e-12").setup(A)
        res = slv.solve(np.ones(A.num_rows))
        assert res.status_code == SolveStatus.MAX_ITERS
        assert res.iterations == 3 and not res.converged

    def test_nan_detected_via_spmv_injection(self):
        A = _poisson16()
        slv = _cg(max_iters=50).setup(A)
        with fi.inject("spmv_nan", iteration=3):
            res = slv.solve(np.ones(A.num_rows))
        assert res.status_code == SolveStatus.NAN_DETECTED
        # fault fires at 0-based iteration 3 -> detected on iteration 4
        assert res.iterations == 4
        # disarmed: the epoch-keyed jit cache retraces clean
        res2 = slv.solve(np.ones(A.num_rows))
        assert res2.status_code == SolveStatus.CONVERGED

    def test_breakdown_cg_indefinite(self):
        A = _indefinite()
        slv = _cg(max_iters=30, tol="1e-10").setup(A)
        res = slv.solve(np.ones(A.num_rows))
        assert res.status_code == SolveStatus.BREAKDOWN
        # the loop exited at the breakdown, not at max_iters, and the
        # iterate stayed finite (no NaN propagation)
        assert res.iterations < 30
        assert np.all(np.isfinite(np.asarray(res.x)))

    def test_diverged(self):
        A = _nondominant()
        slv = amgx.create_solver(Config.from_string(
            "solver=BLOCK_JACOBI, max_iters=50, monitor_residual=1,"
            " tolerance=1e-8, convergence=RELATIVE_INI,"
            " rel_div_tolerance=1e4")).setup(A)
        res = slv.solve(np.ones(A.num_rows))
        assert res.status_code == SolveStatus.DIVERGED
        assert res.iterations < 50

    def test_stalled(self):
        # AMG V-cycle with ZERO smoothing sweeps: coarse-grid correction
        # alone never damps the high-frequency error — the residual
        # plateaus and the sliding-window guard calls it
        A = _poisson16()
        slv = amgx.create_solver(Config.from_string(
            "solver(amg)=AMG, amg:max_iters=40, amg:monitor_residual=1,"
            " amg:tolerance=1e-8, amg:convergence=RELATIVE_INI,"
            " amg:algorithm=AGGREGATION, amg:selector=SIZE_2,"
            " amg:smoother(sm)=JACOBI_L1, sm:max_iters=1,"
            " amg:presweeps=0, amg:postsweeps=0, amg:cycle=V,"
            " amg:coarse_solver=DENSE_LU_SOLVER, amg:min_coarse_rows=8,"
            " amg:stall_detection_window=4")).setup(A)
        res = slv.solve(np.ones(A.num_rows))
        assert res.status_code == SolveStatus.STALLED
        assert res.iterations < 40

    def test_breakdown_amg_nonfinite_cycle(self):
        # a non-finite AMG cycle output classifies as BREAKDOWN (the
        # hierarchy is broken), not as the NAN storm it also causes in
        # the residual — BREAKDOWN outranks NAN in the guard priority,
        # while Krylov NaN storms still classify NAN_DETECTED because
        # their breakdown predicates are NaN-comparison-False
        A = _poisson16()
        slv = amgx.create_solver(Config.from_string(
            "solver(amg)=AMG, amg:max_iters=30, amg:monitor_residual=1,"
            " amg:tolerance=1e-6, amg:convergence=RELATIVE_INI,"
            " amg:algorithm=AGGREGATION, amg:selector=SIZE_2,"
            " amg:smoother(sm)=JACOBI_L1, sm:max_iters=1,"
            " amg:presweeps=1, amg:postsweeps=1, amg:cycle=V,"
            " amg:coarse_solver=DENSE_LU_SOLVER,"
            " amg:min_coarse_rows=8")).setup(A)
        with fi.inject("spmv_nan", iteration=2):
            res = slv.solve(np.ones(A.num_rows))
        assert res.status_code == SolveStatus.BREAKDOWN
        assert res.iterations == 3

    def test_guards_off_restores_plain_monitor(self):
        # health_guards=0: a NaN storm runs to max_iters (the old
        # behavior) instead of being classified
        A = _poisson16()
        slv = _cg("health_guards=0", max_iters=10).setup(A)
        with fi.inject("spmv_nan", iteration=1):
            res = slv.solve(np.ones(A.num_rows))
        assert res.status_code == SolveStatus.MAX_ITERS
        assert res.iterations == 10


# ---------------------------------------------------------------------------
# fault injection harness
# ---------------------------------------------------------------------------


class TestFaultInjection:
    def test_spec_consumed_after_one_trace(self):
        A = _poisson16()
        slv = _cg().setup(A)
        with fi.inject("spmv_nan", iteration=0, fires=1):
            bad = slv.solve(np.ones(A.num_rows))
            # fires exhausted: the very next solve (same arm scope)
            # compiles a clean trace
            good = slv.solve(np.ones(A.num_rows))
        assert bad.status_code == SolveStatus.NAN_DETECTED
        assert good.status_code == SolveStatus.CONVERGED

    def test_galerkin_perturbation_breaks_amg(self):
        # sign-flipping one level's Galerkin values turns the coarse
        # correction into an amplifier: the clean hierarchy converges,
        # the perturbed one diverges — and the guards SAY so
        A = _poisson16()
        cfg_s = (
            "solver(amg)=AMG, amg:max_iters=60, amg:monitor_residual=1,"
            " amg:tolerance=1e-6, amg:convergence=RELATIVE_INI,"
            " amg:algorithm=AGGREGATION, amg:selector=SIZE_2,"
            " amg:smoother(sm)=JACOBI_L1, sm:max_iters=1,"
            " amg:presweeps=2, amg:postsweeps=2, amg:cycle=V,"
            " amg:coarse_solver=DENSE_LU_SOLVER, amg:min_coarse_rows=8,"
            " amg:rel_div_tolerance=1e6")
        clean = amgx.create_solver(Config.from_string(cfg_s)).setup(A)
        ok = clean.solve(np.ones(A.num_rows))
        assert ok.status_code == SolveStatus.CONVERGED
        with fi.inject("galerkin_perturb", index=0, scale=-1.0):
            broken = amgx.create_solver(
                Config.from_string(cfg_s)).setup(A)
        res = broken.solve(np.ones(A.num_rows))
        assert res.status_code == SolveStatus.DIVERGED

    def test_env_toggle(self, monkeypatch):
        # AMGX_TPU_FAULT_INJECT arms a spec without touching code
        monkeypatch.setenv("AMGX_TPU_FAULT_INJECT",
                           "spmv_nan:iteration=2:fires=1")
        monkeypatch.setattr(fi, "_ENV_CHECKED", False)
        monkeypatch.setattr(fi, "_SPEC", None)
        A = _poisson16()
        slv = _cg().setup(A)
        res = slv.solve(np.ones(A.num_rows))
        assert res.status_code == SolveStatus.NAN_DETECTED
        fi.disarm()

    def test_unknown_kind_rejected(self):
        with pytest.raises(ValueError):
            fi.FaultSpec("bitflip_everywhere")

    def test_loop_fault_not_spent_by_unrelated_solve(self):
        # a fires-limited halo fault must survive solves whose traces
        # contain no halo hook (per-kind hook-hit consumption)
        A = _poisson16()
        with fi.inject("halo_corrupt", iteration=0, fires=1):
            slv = _cg().setup(A)
            res = slv.solve(np.ones(A.num_rows))
            assert res.status_code == SolveStatus.CONVERGED
            assert fi.active("halo_corrupt") is not None


# ---------------------------------------------------------------------------
# fallback chains (resilience/policy.py)
# ---------------------------------------------------------------------------


class TestFallbackChains:
    def test_nan_retry_converges(self):
        # transient NaN (fires=1): plain retry gets a clean retrace
        A = _poisson16()
        rs = amgx.create_solver(Config.from_string(
            "solver=CG, max_iters=200, monitor_residual=1,"
            " tolerance=1e-8, convergence=RELATIVE_INI,"
            " fallback_policy=NAN_DETECTED>retry,"
            " max_fallback_attempts=2"))
        assert isinstance(rs, ResilientSolver)
        rs.setup(A)
        with fi.inject("spmv_nan", iteration=2, fires=1):
            res = rs.solve(np.ones(A.num_rows))
        assert res.status_code == SolveStatus.CONVERGED
        assert res.fallback_history == [
            ("initial", "nan_detected"), ("retry", "success")]

    def test_breakdown_switches_to_gmres(self):
        A = _indefinite()
        rs = amgx.create_solver(Config.from_string(
            "solver=CG, max_iters=80, monitor_residual=1,"
            " tolerance=1e-8, convergence=RELATIVE_INI,"
            " gmres_n_restart=40,"
            " fallback_policy=BREAKDOWN>switch_solver=GMRES,"
            " max_fallback_attempts=1"))
        rs.setup(A)
        res = rs.solve(np.ones(A.num_rows))
        assert res.status_code == SolveStatus.CONVERGED
        assert res.fallback_history[0] == ("initial", "breakdown")
        assert res.fallback_history[1][0] == "switch_solver=GMRES"
        # the recovered configuration is adopted for later solves
        assert rs.solver.name == "GMRES"

    def test_stalled_escalates_sweeps(self):
        A = _poisson16()
        rs = amgx.create_solver(Config.from_string(
            "solver(amg)=AMG, amg:max_iters=40, amg:monitor_residual=1,"
            " amg:tolerance=1e-8, amg:convergence=RELATIVE_INI,"
            " amg:algorithm=AGGREGATION, amg:selector=SIZE_2,"
            " amg:smoother(sm)=JACOBI_L1, sm:max_iters=1,"
            " amg:presweeps=0, amg:postsweeps=0, amg:cycle=V,"
            " amg:coarse_solver=DENSE_LU_SOLVER, amg:min_coarse_rows=8,"
            " amg:stall_detection_window=4,"
            " fallback_policy=STALLED>escalate_sweeps,"
            " max_fallback_attempts=1"))
        rs.setup(A)
        res = rs.solve(np.ones(A.num_rows))
        assert res.status_code == SolveStatus.CONVERGED
        assert res.fallback_history[0] == ("initial", "stalled")

    def test_max_iters_rescale_retry(self):
        A = _badly_scaled()
        rs = amgx.create_solver(Config.from_string(
            "solver=CG, max_iters=60, monitor_residual=1,"
            " tolerance=1e-6, convergence=RELATIVE_INI,"
            " fallback_policy=MAX_ITERS>rescale_retry,"
            " max_fallback_attempts=1"))
        rs.setup(A)
        res = rs.solve(np.ones(A.num_rows))
        assert res.status_code == SolveStatus.CONVERGED
        assert res.fallback_history[0] == ("initial", "max_iters")

    def test_attempts_are_bounded(self):
        # a PERSISTENT fault (fires=None): the chain must stop at
        # max_fallback_attempts, not loop forever
        A = _poisson16()
        rs = amgx.create_solver(Config.from_string(
            "solver=CG, max_iters=30, monitor_residual=1,"
            " tolerance=1e-8, convergence=RELATIVE_INI,"
            " fallback_policy=NAN_DETECTED>retry|NAN_DETECTED>retry,"
            " max_fallback_attempts=2"))
        rs.setup(A)
        with fi.inject("spmv_nan", iteration=1, fires=None):
            res = rs.solve(np.ones(A.num_rows))
        assert res.status_code == SolveStatus.NAN_DETECTED
        assert len(res.fallback_history) == 3   # initial + 2 attempts

    def test_policy_parse_errors_suggest(self):
        with pytest.raises(BadConfigurationError) as ei:
            parse_fallback_policy("NAN_DETECTD>retry")
        assert "NAN_DETECTED" in str(ei.value)
        with pytest.raises(BadConfigurationError) as ei:
            parse_fallback_policy("BREAKDOWN>swich_solver=GMRES")
        assert "switch_solver" in str(ei.value)
        with pytest.raises(BadConfigurationError):
            parse_fallback_policy("BREAKDOWN>switch_solver")  # no arg


# ---------------------------------------------------------------------------
# surfacing: batch, distributed, capi, history trimming, config errors
# ---------------------------------------------------------------------------


class TestSurfacing:
    def test_batch_per_system_status(self):
        A = _poisson16()
        n = A.num_rows
        slv = _cg("store_res_history=1", max_iters=12,
                  tol="1e-10").setup(A)
        res = slv.solve_many(np.stack([np.zeros(n), np.ones(n)]))
        assert res.status.tolist() == [int(SolveStatus.CONVERGED),
                                       int(SolveStatus.MAX_ITERS)]
        # zero-RHS system froze at iteration 0; its history rows past
        # its own stop are NaN-masked, and per_system() trims them
        assert res.iterations.tolist() == [0, 12]
        assert np.isnan(res.res_history[0, 1:]).all()
        per = res.per_system()
        assert per[0].status_code == SolveStatus.CONVERGED
        assert per[1].status_code == SolveStatus.MAX_ITERS
        assert len(per[0].res_history) == 1
        assert np.isfinite(per[1].res_history).all()

    def test_res_history_trimmed_single(self):
        A = _poisson16()
        slv = _cg("store_res_history=1", max_iters=100).setup(A)
        res = slv.solve(np.ones(A.num_rows))
        assert res.res_history.shape[0] == res.iterations + 1
        assert np.isfinite(res.res_history).all()

    def test_distributed_status_agrees_after_halo_fault(self):
        from amgx_tpu.distributed import DistributedSolver, default_mesh
        A = _poisson16()
        ds = DistributedSolver(Config.from_string(
            "solver=CG, max_iters=100, monitor_residual=1,"
            " tolerance=1e-8, convergence=RELATIVE_INI"),
            default_mesh(4))
        ds.setup(A)
        b = np.ones(A.num_rows)
        assert ds.solve(b).status_code == SolveStatus.CONVERGED
        with fi.inject("halo_corrupt", iteration=2):
            res = ds.solve(b)
        # the pmax all-reduce makes every shard report the worst code
        assert res.status_code == SolveStatus.NAN_DETECTED
        # and the epoch-keyed program cache recovers afterwards
        assert ds.solve(b).status_code == SolveStatus.CONVERGED

    def test_capi_amgx_solve_status_codes(self):
        from amgx_tpu import capi
        rc, cfg_h = capi.AMGX_config_create(
            "solver=CG, max_iters=3, monitor_residual=1,"
            " tolerance=1e-12, convergence=RELATIVE_INI")
        rc, rsrc = capi.AMGX_resources_create_simple(cfg_h)
        rc, mtx = capi.AMGX_matrix_create(rsrc, "dDDI")
        rc, bh = capi.AMGX_vector_create(rsrc, "dDDI")
        rc, xh = capi.AMGX_vector_create(rsrc, "dDDI")
        A = _poisson16()
        n = A.num_rows
        capi.AMGX_matrix_upload_all(
            mtx, n, A.nnz, 1, 1, np.asarray(A.row_offsets),
            np.asarray(A.col_indices), np.asarray(A.values))
        capi.AMGX_vector_upload(bh, n, 1, np.ones(n))
        rc, slv = capi.AMGX_solver_create(rsrc, "dDDI", cfg_h)
        capi.AMGX_solver_setup(slv, mtx)
        capi.AMGX_solver_solve_with_0_initial_guess(slv, bh, xh)
        rc, status = capi.AMGX_solver_get_status(slv)
        assert (rc, status) == (capi.RC.OK,
                                capi.AMGX_SOLVE_NOT_CONVERGED)
        # a converged re-run reports AMGX_SOLVE_SUCCESS
        rc2, cfg2 = capi.AMGX_config_create(
            "solver=CG, max_iters=200, monitor_residual=1,"
            " tolerance=1e-8, convergence=RELATIVE_INI")
        rc2, slv2 = capi.AMGX_solver_create(rsrc, "dDDI", cfg2)
        capi.AMGX_solver_setup(slv2, mtx)
        capi.AMGX_solver_solve_with_0_initial_guess(slv2, bh, xh)
        rc2, status2 = capi.AMGX_solver_get_status(slv2)
        assert status2 == capi.AMGX_SOLVE_SUCCESS

    def test_unknown_config_key_did_you_mean(self):
        with pytest.raises(BadConfigurationError) as ei:
            Config.from_string("tolerence=1e-8")
        assert "tolerance" in str(ei.value)

    def test_unknown_solver_name_did_you_mean(self):
        with pytest.raises(AMGXError) as ei:
            amgx.create_solver(Config.from_string("solver=GMRS"))
        assert "GMRES" in str(ei.value)


# ---------------------------------------------------------------------------
# service-level resilience (PR 11): policy grammar, chaos hooks, the
# OVERLOADED status, and the known-fault config guard
# ---------------------------------------------------------------------------


class TestServicePolicy:
    def test_parse_service_policy_grammar(self):
        from amgx_tpu.resilience.policy import parse_service_policy
        pol = parse_service_policy(
            "BUILD_FAILED>retry_backoff|BUILD_FAILED>reject"
            "|STEP_FAILED>requeue|WEDGED>requeue")
        assert pol == {"BUILD_FAILED": ["retry_backoff", "reject"],
                       "STEP_FAILED": ["requeue"],
                       "WEDGED": ["requeue"]}
        assert parse_service_policy("") == {}

    def test_parse_service_policy_did_you_mean(self):
        from amgx_tpu.resilience.policy import parse_service_policy
        with pytest.raises(BadConfigurationError) as ei:
            parse_service_policy("BUILD_FAILD>reject")
        assert "BUILD_FAILED" in str(ei.value)
        with pytest.raises(BadConfigurationError) as ei:
            parse_service_policy("WEDGED>retry_bakoff")
        assert "retry_backoff" in str(ei.value)
        with pytest.raises(BadConfigurationError):
            parse_service_policy("WEDGED-requeue")

    def test_overloaded_status_surfaces(self):
        from amgx_tpu.resilience.status import (status_string,
                                                to_amgx_status)
        assert int(SolveStatus.OVERLOADED) == 7
        assert status_string(SolveStatus.OVERLOADED) == "overloaded"
        # C API coarsens it to NOT_CONVERGED like the deadline class
        assert to_amgx_status(SolveStatus.OVERLOADED) == 3
        # and it plugs into the solve-level fallback grammar
        assert parse_fallback_policy("OVERLOADED>retry") == {
            int(SolveStatus.OVERLOADED): [("retry", "")]}


class TestServiceChaosKinds:
    def test_service_crash_consumes_fires(self):
        with fi.inject("build_crash", fires=1):
            with pytest.raises(fi.ChaosInjected):
                fi.service_crash("build_crash")
            fi.service_crash("build_crash")     # fires spent: inert
        fi.service_crash("build_crash")         # disarmed: inert

    def test_kinds_are_independent(self):
        """An armed step fault never triggers the build hook (and vice
        versa) — scripted scenarios target one seam at a time."""
        with fi.inject("step_crash", fires=1):
            fi.service_crash("build_crash")     # inert
            assert not fi.step_wedged()
            with pytest.raises(fi.ChaosInjected):
                fi.service_crash("step_crash")

    def test_corrupt_blob_torn_write(self):
        blob = b"0123456789abcdef"
        assert fi.corrupt_blob("journal_corrupt", blob) == blob
        with fi.inject("journal_corrupt", fires=1):
            out = fi.corrupt_blob("journal_corrupt", blob)
            assert out != blob and len(out) < len(blob)
            # one firing: the next write goes through clean
            assert fi.corrupt_blob("journal_corrupt", blob) == blob

    def test_service_now_skew(self):
        import time as _time
        base = _time.monotonic()
        with fi.inject("clock_skew", value=500.0, fires=None):
            assert fi.service_now() - base > 400.0
        assert abs(fi.service_now() - _time.monotonic()) < 5.0

    def test_step_wedge_consumes_per_cycle(self):
        with fi.inject("step_wedge", fires=2):
            assert fi.step_wedged()
            assert fi.step_wedged()
            assert not fi.step_wedged()


class TestKnownFaultGuard:
    def test_dilu_tpu_guard_reroutes_to_jacobi_l1(self, monkeypatch):
        """The known MULTICOLOR_DILU >96^3 single-chip TPU runtime
        fault is caught at setup/config-validation time: the smoother
        reroutes to the documented JACOBI_L1 fallback with a counter
        and a warning — instead of faulting at solve time."""
        import jax
        from amgx_tpu.amg.hierarchy import AMG
        from amgx_tpu.telemetry import metrics
        monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
        monkeypatch.setattr(jax, "device_count", lambda backend=None: 1)
        monkeypatch.setattr(AMG, "DILU_TPU_FAULT_MIN_ROWS", 100)
        cf0 = metrics.get("resilience.config_fallback")
        slv = amgx.create_solver(Config.from_string(
            "solver(s)=PCG, s:max_iters=80, s:tolerance=1e-8,"
            " s:convergence=RELATIVE_INI, s:monitor_residual=1,"
            " s:preconditioner(amg)=AMG, amg:algorithm=CLASSICAL,"
            " amg:selector=PMIS, amg:interpolator=D1,"
            " amg:smoother=MULTICOLOR_DILU, amg:max_iters=1,"
            " amg:coarse_solver=DENSE_LU_SOLVER"))
        A = _poisson16()
        slv.setup(A)
        assert metrics.get("resilience.config_fallback") - cf0 >= 1
        amg_node = slv.preconditioner.amg
        assert all(lvl.smoother.name == "JACOBI_L1"
                   for lvl in amg_node.levels)
        res = slv.solve(np.ones(A.num_rows))
        assert res.converged

    def test_dilu_guard_inert_below_threshold_and_off_tpu(self):
        """On non-TPU rigs (and below the validated size) the guard
        never fires: the configured smoother is honored."""
        from amgx_tpu.telemetry import metrics
        cf0 = metrics.get("resilience.config_fallback")
        slv = amgx.create_solver(Config.from_string(
            "solver(s)=PCG, s:max_iters=80, s:tolerance=1e-8,"
            " s:convergence=RELATIVE_INI, s:monitor_residual=1,"
            " s:preconditioner(amg)=AMG, amg:algorithm=CLASSICAL,"
            " amg:selector=PMIS, amg:interpolator=D1,"
            " amg:smoother=MULTICOLOR_DILU, amg:max_iters=1,"
            " amg:coarse_solver=DENSE_LU_SOLVER"))
        A = _poisson16()
        slv.setup(A)
        assert metrics.get("resilience.config_fallback") - cf0 == 0
        amg_node = slv.preconditioner.amg
        assert any(lvl.smoother.name == "MULTICOLOR_DILU"
                   for lvl in amg_node.levels)
