"""Distributed-layer tests on the 8-device CPU mesh — the unit-testable
distributed coverage the reference lacks (its multi-rank tests are MPI
example programs only, SURVEY §4)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import amgx_tpu as amgx
from amgx_tpu import gallery, ops
from amgx_tpu.config import Config
from amgx_tpu.distributed import (DistributedSolver, default_mesh,
                                  partition_matrix, partition_vector,
                                  shard_matrix_from_partition,
                                  unpartition_vector)
from jax.sharding import PartitionSpec as P

amgx.initialize()

NDEV = len(jax.devices())


@pytest.fixture(scope="module")
def mesh():
    return default_mesh()


def dist_spmv_global(A, n_ranks, mesh, x):
    """Run the distributed SpMV and return the global result."""
    part = partition_matrix(A, n_ranks)
    sm = shard_matrix_from_partition(part)
    xl = partition_vector(x, n_ranks)

    def fn(smat, xs):
        local = jax.tree.map(lambda a: a[0], smat)
        return local.spmv(xs[0])[None]

    pspec = jax.tree.map(lambda _: P("p"), sm)
    from amgx_tpu._compat import shard_map
    mapped = shard_map(fn, mesh=mesh, in_specs=(pspec, P("p")),
                       out_specs=P("p"), check_vma=False)
    yl = mapped(sm, xl)
    return np.asarray(unpartition_vector(yl, A.num_rows)), part


class TestPartition:
    def test_partition_roundtrip_vector(self):
        v = np.arange(37, dtype=np.float64)
        vl = partition_vector(v, 8)
        assert vl.shape == (8, 5)
        assert np.allclose(np.asarray(unpartition_vector(vl, 37)), v)

    def test_poisson_slab_is_ring(self):
        A = gallery.poisson("7pt", 6, 6, 16)
        part = partition_matrix(A, 8)
        assert part.neighbor_only  # z-slabs touch only rank +/- 1

    def test_random_matrix_not_ring(self):
        A = gallery.random_matrix(64, max_nnz_per_row=6, seed=0)
        part = partition_matrix(A, 8)
        assert not part.neighbor_only  # random cols reach far ranks


class TestDistSpmv:
    @pytest.mark.parametrize("shape", [("7pt", 6, 6, 16), ("5pt", 12, 11, 1)])
    def test_ring_exchange_matches_dense(self, mesh, shape):
        stencil, nx, ny, nz = shape
        A = gallery.poisson(stencil, nx, ny, nz)
        n = A.num_rows
        x = np.random.default_rng(0).standard_normal(n)
        y, part = dist_spmv_global(A, NDEV, mesh, x)
        ref = np.asarray(A.init().to_dense()) @ x
        np.testing.assert_allclose(y, ref, rtol=1e-12, atol=1e-12)

    def test_allgather_exchange_matches_dense(self, mesh):
        A = gallery.random_matrix(96, max_nnz_per_row=7, seed=4)
        x = np.random.default_rng(1).standard_normal(96)
        y, part = dist_spmv_global(A, NDEV, mesh, x)
        assert not part.neighbor_only
        ref = np.asarray(A.init().to_dense()) @ x
        np.testing.assert_allclose(y, ref, rtol=1e-12, atol=1e-12)

    def test_a2a_exchange_matches_dense(self, mesh):
        """Far-neighbor but sparse coupling selects the all-to-all
        exchange (per-pair B2L buffers, not the O(n) gather)."""
        n = 32 * NDEV
        k = 2 * (n // NDEV)          # couples rank r with rank r+2
        far = np.arange(0, n - k, 4)   # sparse far coupling
        rows = np.concatenate([np.arange(n), np.arange(n - 1),
                               np.arange(1, n), far, far + k])
        cols = np.concatenate([np.arange(n), np.arange(1, n),
                               np.arange(n - 1), far + k, far])
        vals = np.concatenate([np.full(n, 6.0), np.full(2 * (n - 1), -1.0),
                               np.full(2 * far.size, -0.5)])
        from amgx_tpu.matrix import CsrMatrix
        A = CsrMatrix.from_coo(rows, cols, vals, n, n)
        x = np.random.default_rng(5).standard_normal(n)
        y, part = dist_spmv_global(A, NDEV, mesh, x)
        assert part.exchange_mode == "a2a"
        ref = np.asarray(A.init().to_dense()) @ x
        np.testing.assert_allclose(y, ref, rtol=1e-12, atol=1e-12)

    def test_split_entries_cover_matrix(self):
        """Owned + halo entry sets together reproduce every nnz."""
        A = gallery.poisson("7pt", 8, 8, 24)
        part = partition_matrix(A.init(), NDEV)
        total = int((np.asarray(part.rid_own) < part.n_local).sum() +
                    (np.asarray(part.rid_halo) < part.n_local).sum())
        assert total == A.nnz


class TestDistSolve:
    @pytest.fixture(scope="class")
    def A(self):
        return gallery.poisson("7pt", 8, 8, 24)

    @pytest.fixture(scope="class")
    def b(self, A):
        return np.ones(A.num_rows)

    def test_dist_cg_matches_single_device(self, mesh, A, b):
        """Distributed CG must match the single-device iteration count and
        solution (domain decomposition changes nothing mathematically)."""
        cfg = Config.from_string(
            "solver=CG, max_iters=300, monitor_residual=1, tolerance=1e-10")
        ds = DistributedSolver(cfg, mesh)
        ds.setup(A)
        res_d = ds.solve(b)
        s = amgx.solvers.make_solver("CG", cfg)
        s.setup(A.init())
        res_s = s.solve(jnp.asarray(b))
        assert res_d.converged
        assert res_d.iterations == res_s.iterations
        np.testing.assert_allclose(np.asarray(res_d.x), np.asarray(res_s.x),
                                   rtol=1e-8, atol=1e-10)

    def test_dist_pcg_jacobi(self, mesh, A, b):
        cfg = Config.from_string(
            "solver=PCG, max_iters=300, monitor_residual=1, tolerance=1e-10,"
            " preconditioner(j)=BLOCK_JACOBI, j:max_iters=2")
        ds = DistributedSolver(cfg, mesh)
        ds.setup(A)
        res = ds.solve(b)
        assert res.converged
        r = np.asarray(A.init().to_dense()) @ np.asarray(res.x) - b
        assert np.linalg.norm(r) < 1e-8

    def test_dist_fgmres(self, mesh, A, b):
        cfg = Config.from_string(
            "solver=FGMRES, max_iters=300, monitor_residual=1,"
            " tolerance=1e-10, gmres_n_restart=15,"
            " preconditioner(j)=JACOBI_L1, j:max_iters=2")
        ds = DistributedSolver(cfg, mesh)
        ds.setup(A)
        res = ds.solve(b)
        assert res.converged
        r = np.asarray(A.init().to_dense()) @ np.asarray(res.x) - b
        assert np.linalg.norm(r) < 1e-8

    def test_dist_bicgstab_general_pattern(self, mesh):
        """all_gather fallback path end-to-end."""
        A = gallery.random_matrix(80, max_nnz_per_row=5, seed=9,
                                  symmetric=True, diag_dominant=True)
        b = np.ones(80)
        cfg = Config.from_string(
            "solver=BICGSTAB, max_iters=200, monitor_residual=1,"
            " tolerance=1e-10")
        ds = DistributedSolver(cfg, mesh)
        ds.setup(A)
        res = ds.solve(b)
        assert res.converged
        r = np.asarray(A.init().to_dense()) @ np.asarray(res.x) - b
        assert np.linalg.norm(r) < 1e-8

    @pytest.mark.slow     # heaviest DistSolve member; the other
    # admitted-preconditioner tests keep the family in tier-1
    def test_strong_precond_admitted_data_driven(self, mesh):
        """The preconditioner envelope is data-driven: MULTICOLOR_ILU is
        admitted when its solve-data partitions row-wise (construction
        no longer rejects by name; setup() shards the triangular
        factors as halo-exchanging shards)."""
        A = gallery.poisson5pt(12, 12)
        b = np.ones(A.num_rows)
        cfg = Config.from_string(
            "solver=PCG, max_iters=200, monitor_residual=1,"
            " tolerance=1e-8, preconditioner(ilu)=MULTICOLOR_ILU")
        ds = DistributedSolver(cfg, mesh)   # must NOT raise
        ds.setup(A)
        res = ds.solve(b)
        assert res.converged
        r = np.asarray(A.init().to_dense()) @ np.asarray(res.x) - b
        assert np.linalg.norm(r) < 1e-6

    def test_precond_from_pieces_rejected_at_setup(self, mesh):
        """Setting up a global-matrix-needing preconditioner from
        per-rank pieces (no controller-global A) raises at setup()."""
        from amgx_tpu.distributed.partition import partition_from_pieces
        A = gallery.poisson5pt(12, 12).init()
        cfg = Config.from_string(
            "solver=PCG, preconditioner(ilu)=MULTICOLOR_ILU")
        ds = DistributedSolver(cfg, mesh)
        n_ranks = int(mesh.devices.size)
        ro = np.asarray(A.row_offsets)
        ci = np.asarray(A.col_indices)
        va = np.asarray(A.values)
        n_local = -(-A.num_rows // n_ranks)
        pieces = []
        for r in range(n_ranks):
            lo = min(r * n_local, A.num_rows)
            hi = min(lo + n_local, A.num_rows)
            s, e = int(ro[lo]), int(ro[hi])
            pieces.append((ro[lo:hi + 1] - ro[lo], ci[s:e], va[s:e]))
        part = partition_from_pieces(pieces, A.num_rows)
        with pytest.raises(amgx.errors.AMGXError):
            ds.setup_from_partition(part)


# ---------------------------------------------------------------------------
# distributed AMG (round 2): sharded hierarchy cycles + replicated coarse
# ---------------------------------------------------------------------------

_AMG_BASE = (
    "solver=FGMRES, max_iters=60, monitor_residual=1, tolerance=1e-8,"
    " gmres_n_restart=30, preconditioner(amg)=AMG, amg:max_iters=1,"
    " amg:cycle=V, amg:max_levels=6")


def _single_device_iters(cfg_str, A, b):
    cfg = Config.from_string(cfg_str)
    slv = amgx.create_solver(cfg)
    slv.setup(A)
    return slv.solve(b)


@pytest.mark.parametrize("algo,extra", [
    ("AGGREGATION", ", amg:selector=SIZE_2, amg:smoother=BLOCK_JACOBI,"
     " amg:relaxation_factor=0.9"),
    ("AGGREGATION", ", amg:selector=SIZE_2, amg:smoother=MULTICOLOR_DILU,"
     " amg:relaxation_factor=0.9"),
    ("AGGREGATION", ", amg:selector=SIZE_2, amg:smoother=MULTICOLOR_ILU,"
     " amg:relaxation_factor=1.0, amg:distributed_setup_mode=global"),
    ("AGGREGATION", ", amg:selector=SIZE_2, amg:smoother=BLOCK_JACOBI,"
     " amg:relaxation_factor=0.9, amg:cycle=CG,"
     " amg:distributed_setup_mode=global"),
    ("AGGREGATION", ", amg:selector=SIZE_2, amg:smoother=BLOCK_JACOBI,"
     " amg:relaxation_factor=0.9, amg:cycle=CGF,"
     " amg:distributed_setup_mode=global"),
    ("CLASSICAL", ", amg:smoother=BLOCK_JACOBI, amg:relaxation_factor=0.9"),
])
@pytest.mark.slow
def test_distributed_amg_matches_single_device(mesh, algo, extra):
    """Distributed FGMRES+AMG must converge with iteration counts equal
    to the single-device run (the hierarchy and smoother math are
    identical; only the execution is sharded)."""
    A = gallery.poisson("7pt", 6, 6, 4 * NDEV).init()
    b = jnp.ones(A.num_rows)
    cfg_str = _AMG_BASE + f", amg:algorithm={algo}" + extra
    ref = _single_device_iters(cfg_str, A, b)
    assert ref.converged

    ds = DistributedSolver(Config.from_string(cfg_str), mesh)
    ds.setup(A)
    res = ds.solve(np.asarray(b))
    assert res.converged
    assert res.iterations == ref.iterations, (res.iterations,
                                              ref.iterations)
    r = np.asarray(ops.residual(A, jnp.asarray(np.asarray(res.x)), b))
    assert np.linalg.norm(r) < 1e-6 * np.linalg.norm(np.asarray(b))


def test_distributed_amg_kcycle_small(mesh):
    """K-cycle over the mesh on a small system (coarse-grid CG matvecs
    gather/slice through the replicated coarsest level)."""
    A = gallery.poisson("7pt", 4, 4, 2 * NDEV).init()
    b = jnp.ones(A.num_rows)
    cfg_str = (_AMG_BASE.replace("amg:cycle=V", "amg:cycle=CG")
               + ", amg:algorithm=AGGREGATION, amg:selector=SIZE_2,"
               " amg:smoother=BLOCK_JACOBI, amg:relaxation_factor=0.9,"
               " amg:distributed_setup_mode=global")
    ref = _single_device_iters(cfg_str, A, b)
    ds = DistributedSolver(Config.from_string(cfg_str), mesh)
    ds.setup(A)
    res = ds.solve(np.asarray(b))
    assert res.converged and res.iterations == ref.iterations


@pytest.mark.parametrize("extra,expect_boundary", [
    # the consolidation-OFF baseline is the heavy redundant
    # parametrization (plain distributed AMG is covered broadly
    # elsewhere); the flag=1 boundary case stays in tier-1
    pytest.param("", False, marks=pytest.mark.slow),
    (", amg:amg_consolidation_flag=1,"
     " amg:matrix_consolidation_lower_threshold=40", True),
])
def test_distributed_amg_consolidation(mesh, extra, expect_boundary):
    """Coarse-level consolidation (glue_matrices analog, glue.h:200):
    levels whose per-shard row count falls below the threshold run
    replicated; iteration counts must still match the single-device
    hierarchy exactly."""
    from amgx_tpu.distributed.amg import _ConsolidationBoundaryLevel
    A = gallery.poisson("7pt", 6, 6, 4 * NDEV).init()
    b = jnp.ones(A.num_rows)
    # this test exercises the controller-global setup's consolidation
    # machinery specifically (the sharded setup has its own boundary,
    # tests/test_distributed_setup.py)
    cfg_str = (_AMG_BASE + ", amg:algorithm=AGGREGATION,"
               " amg:selector=SIZE_2, amg:smoother=BLOCK_JACOBI,"
               " amg:relaxation_factor=0.9,"
               " amg:distributed_setup_mode=global" + extra)
    ref = _single_device_iters(cfg_str, A, b)
    assert ref.converged

    ds = DistributedSolver(Config.from_string(cfg_str), mesh)
    ds.setup(A)
    amg_h = ds.solver.preconditioner.amg
    wrapped = any(isinstance(lv, _ConsolidationBoundaryLevel)
                  for lv in amg_h.levels)
    assert wrapped == expect_boundary
    res = ds.solve(np.asarray(b))
    assert res.converged
    assert res.iterations == ref.iterations
    r = np.asarray(ops.residual(A, jnp.asarray(np.asarray(res.x)), b))
    assert np.linalg.norm(r) < 1e-6 * np.linalg.norm(np.asarray(b))


def test_distributed_block_matrix_krylov(mesh):
    """Block systems distribute via exact scalar expansion with block
    rows kept rank-local; BLOCK_JACOBI uses the true block-diagonal
    inverse, so iteration counts match the single-device block solve."""
    A = gallery.random_matrix(96, max_nnz_per_row=4, seed=11,
                              symmetric=True, diag_dominant=True,
                              block_dims=(2, 2)).init()
    b = jnp.ones(A.num_rows * 2)
    cfg_str = ("solver=PBICGSTAB, max_iters=120, monitor_residual=1,"
               " tolerance=1e-9, preconditioner(j)=BLOCK_JACOBI,"
               " j:max_iters=2")
    ref = amgx.create_solver(Config.from_string(cfg_str))
    ref.setup(A)
    r_ref = ref.solve(b)
    assert r_ref.converged

    ds = DistributedSolver(Config.from_string(cfg_str), mesh)
    ds.setup(A)
    res = ds.solve(np.asarray(b))
    assert res.converged
    assert res.iterations == r_ref.iterations
    r = np.asarray(A.to_dense()) @ np.asarray(res.x) - np.asarray(b)
    assert np.linalg.norm(r) < 1e-7 * np.linalg.norm(np.asarray(b))


def test_distributed_amg_block_matches_single_device(mesh):
    """Block systems in distributed AMG: levels scalar-expand, the
    transfers expand P (x) I_b, block-Jacobi smoother data partitions
    by block rows; iteration counts match single-device."""
    A = gallery.random_matrix(64, max_nnz_per_row=4, seed=3,
                              symmetric=True, diag_dominant=True,
                              block_dims=(2, 2)).init()
    b = jnp.ones(A.num_rows * 2)
    cfg_str = (
        "solver=FGMRES, max_iters=60, monitor_residual=1, tolerance=1e-8,"
        " gmres_n_restart=30, preconditioner(amg)=AMG, amg:max_iters=1,"
        " amg:cycle=V, amg:max_levels=4, amg:algorithm=AGGREGATION,"
        " amg:selector=SIZE_2, amg:smoother=BLOCK_JACOBI,"
        " amg:relaxation_factor=0.9, amg:min_coarse_rows=8")
    ref = _single_device_iters(cfg_str, A, b)
    assert ref.converged
    ds = DistributedSolver(Config.from_string(cfg_str), mesh)
    ds.setup(A)
    res = ds.solve(np.asarray(b))
    assert res.converged
    assert res.iterations == ref.iterations, (res.iterations,
                                              ref.iterations)


def test_distributed_block_odd_rounding(mesh):
    """Block rounding: ceil(n_scalar/n_ranks) not a multiple of the
    block size (98 block rows x 2x2 on 8 ranks -> 25 vs 26) must not
    crash; vectors partition with the matrix's rounded n_local."""
    A = gallery.random_matrix(98, max_nnz_per_row=4, seed=13,
                              symmetric=True, diag_dominant=True,
                              block_dims=(2, 2)).init()
    b = np.ones(A.num_rows * 2)
    cfg = Config.from_string(
        "solver=PCG, max_iters=200, monitor_residual=1, tolerance=1e-9,"
        " preconditioner(j)=BLOCK_JACOBI, j:max_iters=2")
    ref = amgx.create_solver(cfg)
    ref.setup(A)
    r_ref = ref.solve(jnp.asarray(b))
    ds = DistributedSolver(cfg, mesh)
    ds.setup(A)
    res = ds.solve(b)
    assert res.converged and res.iterations == r_ref.iterations
    r = np.asarray(A.to_dense()) @ np.asarray(res.x) - b
    assert np.linalg.norm(r) < 1e-7 * np.linalg.norm(b)


def test_distributed_amg_block_consolidation(mesh):
    """Blocks + coarse-level consolidation: the boundary wrapper's local
    slice must use the block-aligned rounding of the sharded transfer
    operators (iteration parity is the contract)."""
    A = gallery.random_matrix(501, max_nnz_per_row=4, seed=11,
                              symmetric=True, diag_dominant=True,
                              block_dims=(2, 2)).init()
    b = jnp.ones(A.num_rows * 2)
    cfg_str = (
        "solver=FGMRES, max_iters=60, monitor_residual=1, tolerance=1e-8,"
        " gmres_n_restart=30, preconditioner(amg)=AMG, amg:max_iters=1,"
        " amg:cycle=V, amg:max_levels=4, amg:algorithm=AGGREGATION,"
        " amg:selector=SIZE_2, amg:smoother=BLOCK_JACOBI,"
        " amg:relaxation_factor=0.9, amg:min_coarse_rows=8,"
        " amg:amg_consolidation_flag=1,"
        " amg:matrix_consolidation_lower_threshold=100")
    ref = _single_device_iters(cfg_str, A, b)
    assert ref.converged
    ds = DistributedSolver(Config.from_string(cfg_str), mesh)
    ds.setup(A)
    from amgx_tpu.distributed.amg import _ConsolidationBoundaryLevel
    amg_h = ds.solver.preconditioner.amg
    assert any(isinstance(lv, _ConsolidationBoundaryLevel)
               for lv in amg_h.levels)
    res = ds.solve(np.asarray(b))
    assert res.converged
    assert res.iterations == ref.iterations, (res.iterations,
                                              ref.iterations)
