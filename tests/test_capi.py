"""C-API shim tests (capi_upload_tests.cu, capi_graceful_failure.cu,
amgx_capi.c flow analogs)."""
import os
import subprocess
import sys

import numpy as np
import pytest

from amgx_tpu import capi, gallery
from amgx_tpu.config import Config
from amgx_tpu.errors import RC
from amgx_tpu.io import write_system

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _init():
    assert capi.AMGX_initialize() == RC.OK
    yield
    capi.AMGX_finalize()


def _poisson_csr(nx=8, ny=8):
    A = gallery.poisson("5pt", nx, ny)
    return (A.num_rows, A.nnz, np.asarray(A.row_offsets),
            np.asarray(A.col_indices), np.asarray(A.values))


def test_full_capi_flow():
    """The amgx_capi.c call sequence end to end."""
    rc, cfg = capi.AMGX_config_create(
        "config_version=2, solver=PCG, preconditioner=BLOCK_JACOBI, "
        "max_iters=200, tolerance=1e-8, monitor_residual=1, "
        "convergence=RELATIVE_INI_CORE, store_res_history=1")
    assert rc == RC.OK
    rc, rsrc = capi.AMGX_resources_create_simple(cfg)
    assert rc == RC.OK
    rc, A = capi.AMGX_matrix_create(rsrc, "dDDI")
    assert rc == RC.OK
    rc, b = capi.AMGX_vector_create(rsrc, "dDDI")
    rc, x = capi.AMGX_vector_create(rsrc, "dDDI")
    rc, slv = capi.AMGX_solver_create(rsrc, "dDDI", cfg)
    assert rc == RC.OK

    n, nnz, ro, ci, vals = _poisson_csr()
    assert capi.AMGX_matrix_upload_all(A, n, nnz, 1, 1, ro, ci, vals) \
        == RC.OK
    rc, nn, bx, by = capi.AMGX_matrix_get_size(A)
    assert (rc, nn, bx, by) == (RC.OK, n, 1, 1)

    assert capi.AMGX_vector_upload(b, n, 1, np.ones(n)) == RC.OK
    assert capi.AMGX_vector_set_zero(x, n, 1) == RC.OK
    assert capi.AMGX_solver_setup(slv, A) == RC.OK
    assert capi.AMGX_solver_solve(slv, b, x) == RC.OK

    rc, status = capi.AMGX_solver_get_status(slv)
    assert (rc, status) == (RC.OK, 0)
    rc, iters = capi.AMGX_solver_get_iterations_number(slv)
    assert rc == RC.OK and 0 < iters <= 200
    rc, res0 = capi.AMGX_solver_get_iteration_residual(slv, 0)
    rc, resN = capi.AMGX_solver_get_iteration_residual(slv, iters)
    assert resN < 1e-8 * res0 * 10

    rc, sol = capi.AMGX_vector_download(x)
    assert rc == RC.OK
    import jax.numpy as jnp
    from amgx_tpu.ops.spmv import spmv
    Am = gallery.poisson("5pt", 8, 8).init()
    r = np.asarray(spmv(Am, jnp.asarray(sol))) - 1.0
    assert np.linalg.norm(r) < 1e-6

    for h, d in ((slv, capi.AMGX_solver_destroy),
                 (x, capi.AMGX_vector_destroy),
                 (b, capi.AMGX_vector_destroy),
                 (A, capi.AMGX_matrix_destroy),
                 (rsrc, capi.AMGX_resources_destroy),
                 (cfg, capi.AMGX_config_destroy)):
        assert d(h) == RC.OK


def test_replace_coefficients_and_resetup():
    rc, cfg = capi.AMGX_config_create(
        "solver=CG, max_iters=300, tolerance=1e-8, monitor_residual=1, "
        "convergence=RELATIVE_INI_CORE")
    rc, rsrc = capi.AMGX_resources_create_simple(cfg)
    rc, A = capi.AMGX_matrix_create(rsrc, "dDDI")
    rc, slv = capi.AMGX_solver_create(rsrc, "dDDI", cfg)
    rc, b = capi.AMGX_vector_create(rsrc, "dDDI")
    rc, x = capi.AMGX_vector_create(rsrc, "dDDI")
    n, nnz, ro, ci, vals = _poisson_csr()
    capi.AMGX_matrix_upload_all(A, n, nnz, 1, 1, ro, ci, vals)
    capi.AMGX_vector_upload(b, n, 1, np.ones(n))
    capi.AMGX_vector_set_zero(x, n, 1)
    capi.AMGX_solver_setup(slv, A)
    capi.AMGX_solver_solve(slv, b, x)
    # scale the coefficients: solution halves
    assert capi.AMGX_matrix_replace_coefficients(A, n, nnz, 2.0 * vals) \
        == RC.OK
    assert capi.AMGX_solver_resetup(slv, A) == RC.OK
    capi.AMGX_vector_set_zero(x, n, 1)
    capi.AMGX_solver_solve(slv, b, x)
    rc, sol2 = capi.AMGX_vector_download(x)
    Am = gallery.poisson("5pt", 8, 8).init()
    import jax.numpy as jnp
    from amgx_tpu.ops.spmv import spmv
    r = np.asarray(spmv(Am, 2.0 * jnp.asarray(sol2))) - 1.0
    assert np.linalg.norm(r) < 1e-6


def test_graceful_failure():
    """capi_graceful_failure.cu analog: bad calls return RCs, never
    raise."""
    assert capi.AMGX_solver_setup(99999, 99998) == RC.BAD_PARAMETERS
    rc, _ = capi.AMGX_vector_download(12345)
    assert rc == RC.BAD_PARAMETERS
    rc, cfg = capi.AMGX_config_create_from_file("/nonexistent/cfg.json")
    assert rc in (RC.IO_ERROR, RC.BAD_CONFIGURATION) and cfg is None
    rc, rsrc = capi.AMGX_resources_create_simple(None)
    assert rc == RC.OK
    rc, A = capi.AMGX_matrix_create(rsrc, "zZZZ")   # invalid mode
    assert rc != RC.OK and A is None
    rc, A = capi.AMGX_matrix_create(rsrc, "dDDI")
    rc, cfg = capi.AMGX_config_create("solver=CG, max_iters=10")
    rc, slv = capi.AMGX_solver_create(rsrc, "dDDI", cfg)
    # solve before setup
    rc, b = capi.AMGX_vector_create(rsrc, "dDDI")
    rc, x = capi.AMGX_vector_create(rsrc, "dDDI")
    capi.AMGX_vector_upload(b, 4, 1, np.ones(4))
    assert capi.AMGX_solver_solve(slv, b, x) == RC.BAD_PARAMETERS
    # bad config string
    rc2, _ = capi.AMGX_config_create("no_such_param=1")
    assert rc2 != RC.OK


def test_read_write_system_roundtrip(tmp_path):
    rc, rsrc = capi.AMGX_resources_create_simple(None)
    rc, A = capi.AMGX_matrix_create(rsrc, "dDDI")
    rc, b = capi.AMGX_vector_create(rsrc, "dDDI")
    rc, x = capi.AMGX_vector_create(rsrc, "dDDI")
    Am = gallery.poisson("5pt", 6, 6)
    path = str(tmp_path / "sys.mtx")
    write_system(path, Am, b=np.arange(36, dtype=float))
    assert capi.AMGX_read_system(A, b, x, path) == RC.OK
    rc, n, bx, by = capi.AMGX_matrix_get_size(A)
    assert n == 36
    rc, bv = capi.AMGX_vector_download(b)
    np.testing.assert_allclose(bv, np.arange(36, dtype=float))
    # write back
    out = str(tmp_path / "out.mtx")
    assert capi.AMGX_write_system(A, b, None, out) == RC.OK
    assert os.path.exists(out)


def test_print_callback_captures_output():
    lines = []
    capi.AMGX_register_print_callback(lambda m, l: lines.append(m))
    rc, cfg = capi.AMGX_config_create(
        "solver=CG, max_iters=50, tolerance=1e-8, monitor_residual=1, "
        "print_solve_stats=1, convergence=RELATIVE_INI_CORE")
    rc, rsrc = capi.AMGX_resources_create_simple(cfg)
    rc, A = capi.AMGX_matrix_create(rsrc, "dDDI")
    rc, slv = capi.AMGX_solver_create(rsrc, "dDDI", cfg)
    rc, b = capi.AMGX_vector_create(rsrc, "dDDI")
    rc, x = capi.AMGX_vector_create(rsrc, "dDDI")
    n, nnz, ro, ci, vals = _poisson_csr(6, 6)
    capi.AMGX_matrix_upload_all(A, n, nnz, 1, 1, ro, ci, vals)
    capi.AMGX_vector_upload(b, n, 1, np.ones(n))
    capi.AMGX_vector_set_zero(x, n, 1)
    capi.AMGX_solver_setup(slv, A)
    capi.AMGX_solver_solve(slv, b, x)
    capi.AMGX_register_print_callback(None)
    text = "".join(lines)
    assert "Total Iterations" in text and "Solve Status" in text


def test_generate_poisson_7pt():
    rc, rsrc = capi.AMGX_resources_create_simple(None)
    rc, A = capi.AMGX_matrix_create(rsrc, "dDDI")
    rc, b = capi.AMGX_vector_create(rsrc, "dDDI")
    assert capi.AMGX_generate_distributed_poisson_7pt(
        A, b, None, 1, 1, 8, 8, 8) == RC.OK
    rc, n, _, _ = capi.AMGX_matrix_get_size(A)
    assert n == 512


def test_eigensolver_capi():
    rc, cfg = capi.AMGX_config_create(
        "eig_solver=POWER_ITERATION, eig_max_iters=2000, "
        "eig_tolerance=1e-8, eig_eigenvector=1")
    rc, rsrc = capi.AMGX_resources_create_simple(cfg)
    rc, A = capi.AMGX_matrix_create(rsrc, "dDDI")
    n, nnz, ro, ci, vals = _poisson_csr(10, 7)
    capi.AMGX_matrix_upload_all(A, n, nnz, 1, 1, ro, ci, vals)
    rc, es = capi.AMGX_eigensolver_create(rsrc, "dDDI", cfg)
    assert rc == RC.OK
    rc, x = capi.AMGX_vector_create(rsrc, "dDDI")
    assert capi.AMGX_eigensolver_setup(es, A) == RC.OK
    assert capi.AMGX_eigensolver_solve(es, x) == RC.OK
    rc, eigs = capi.AMGX_eigensolver_get_eigenvalues(es)
    assert rc == RC.OK
    Ad = np.asarray(gallery.poisson("5pt", 10, 7).to_dense())
    lam_ref = np.linalg.eigvalsh(Ad)[-1]
    np.testing.assert_allclose(eigs[0], lam_ref, rtol=1e-6)


def test_write_parameters_description(tmp_path):
    path = str(tmp_path / "params.txt")
    assert capi.AMGX_write_parameters_description(path) == RC.OK
    text = open(path).read()
    assert "max_iters" in text and "tolerance" in text


def test_cli_example(tmp_path):
    """Run the amgx_capi.py CLI end to end (reference example run)."""
    Am = gallery.poisson("5pt", 8, 8)
    path = str(tmp_path / "sys.mtx")
    write_system(path, Am, b=np.ones(64))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", "amgx_capi.py"),
         "-m", path, "-c",
         os.path.join(REPO, "configs", "FGMRES_AGGREGATION.json")],
        capture_output=True, text=True, env=env, timeout=600)
    assert out.returncode == 0, out.stderr
    assert "status: success" in out.stdout


class TestCApiTail:
    """The misc function tail (include/amgx_c.h): download_all,
    matrix_vector_multiply, residual norm, set_random, check_symmetry,
    attach_coloring, build info, default rings."""

    def _system(self):
        capi.AMGX_initialize()
        cfg = capi.AMGX_config_create(
            "config_version=2, solver=PCG, max_iters=50, tolerance=1e-8,"
            " monitor_residual=1")[1]
        rs = capi.AMGX_resources_create_simple(cfg)[1]
        mtx = capi.AMGX_matrix_create(rs, "dDDI")[1]
        A = gallery.poisson("7pt", 6, 6, 6).init()
        n = A.num_rows
        capi.AMGX_matrix_upload_all(
            mtx, n, A.nnz, 1, 1, np.asarray(A.row_offsets),
            np.asarray(A.col_indices), np.asarray(A.values))
        return cfg, rs, mtx, A, n

    def test_download_all_roundtrip(self):
        _, _, mtx, A, n = self._system()
        rc, ro, ci, va, diag = capi.AMGX_matrix_download_all(mtx)
        assert rc == capi.RC.OK
        assert np.array_equal(ro, np.asarray(A.row_offsets))
        assert np.array_equal(ci, np.asarray(A.col_indices))
        assert np.allclose(va, np.asarray(A.values))
        assert diag is None

    def test_matrix_vector_multiply(self):
        _, rs, mtx, A, n = self._system()
        x = capi.AMGX_vector_create(rs, "dDDI")[1]
        y = capi.AMGX_vector_create(rs, "dDDI")[1]
        xv = np.random.default_rng(0).standard_normal(n)
        capi.AMGX_vector_upload(x, n, 1, xv)
        assert capi.AMGX_matrix_vector_multiply(mtx, x, y) == capi.RC.OK
        got = capi.AMGX_vector_download(y)[1]
        ref = np.asarray(A.to_dense()) @ xv
        np.testing.assert_allclose(got, ref, rtol=1e-12)

    def test_calculate_residual_norm(self):
        cfg, rs, mtx, A, n = self._system()
        slv = capi.AMGX_solver_create(rs, "dDDI", cfg)[1]
        capi.AMGX_solver_setup(slv, mtx)
        b = capi.AMGX_vector_create(rs, "dDDI")[1]
        x = capi.AMGX_vector_create(rs, "dDDI")[1]
        capi.AMGX_vector_upload(b, n, 1, np.ones(n))
        capi.AMGX_vector_set_zero(x, n, 1)
        rc, nrm = capi.AMGX_solver_calculate_residual_norm(slv, mtx, b, x)
        assert rc == capi.RC.OK
        assert np.allclose(nrm, np.linalg.norm(np.ones(n)))

    def test_vector_set_random(self):
        _, rs, _, _, n = self._system()
        v = capi.AMGX_vector_create(rs, "dDDI")[1]
        assert capi.AMGX_vector_set_random(v, 100) == capi.RC.OK
        out = capi.AMGX_vector_download(v)[1]
        assert out.shape == (100,) and (out >= 0).all() and (out < 1).all()

    def test_check_symmetry(self):
        _, _, mtx, _, _ = self._system()
        rc, struct, sym = capi.AMGX_matrix_check_symmetry(mtx)
        assert rc == capi.RC.OK and struct == 1 and sym == 1

    def test_check_symmetry_nonsym(self):
        capi.AMGX_initialize()
        cfg = capi.AMGX_config_create("solver=PCG")[1]
        rs = capi.AMGX_resources_create_simple(cfg)[1]
        mtx = capi.AMGX_matrix_create(rs, "dDDI")[1]
        # pattern-symmetric, value-nonsymmetric
        ro = np.array([0, 2, 4])
        ci = np.array([0, 1, 0, 1])
        va = np.array([2.0, -1.0, -0.5, 2.0])
        capi.AMGX_matrix_upload_all(mtx, 2, 4, 1, 1, ro, ci, va)
        rc, struct, sym = capi.AMGX_matrix_check_symmetry(mtx)
        assert rc == capi.RC.OK and struct == 1 and sym == 0

    def test_attach_coloring_overrides_scheme(self):
        from amgx_tpu.ops.coloring import color_matrix
        _, _, mtx, _, n = self._system()
        colors = (np.arange(n) % 3).astype(np.int32)
        assert capi.AMGX_matrix_attach_coloring(
            mtx, colors, n, 3) == capi.RC.OK
        m = capi._get(mtx)
        cl = color_matrix(m.A, Config.from_string(""), "default")
        assert np.array_equal(np.asarray(cl.row_colors), colors)
        assert cl.num_colors == 3

    def test_build_info_and_rings(self):
        rc, ver, date, system = capi.AMGX_get_build_info_strings()
        assert rc == capi.RC.OK and ver.startswith("amgx_tpu")
        cfg = capi.AMGX_config_create(
            "solver=PCG, preconditioner(amg)=AMG,"
            " amg:algorithm=CLASSICAL")[1]
        rc, rings = capi.AMGX_config_get_default_number_of_rings(cfg)
        assert rc == capi.RC.OK and rings == 2
        cfg2 = capi.AMGX_config_create(
            "solver=PCG, preconditioner(amg)=AMG,"
            " amg:algorithm=AGGREGATION")[1]
        assert capi.AMGX_config_get_default_number_of_rings(cfg2)[1] == 1

    def test_boundary_separation_accepted(self):
        _, _, mtx, _, _ = self._system()
        assert capi.AMGX_matrix_set_boundary_separation(mtx, 1) == \
            capi.RC.OK
