"""Device-resident setup pipeline tests (setup_backend=device|host|auto).

Parity contract: a hierarchy built through the forced device (jnp)
pipeline must match the host (numpy/native) build — identical CF
splits / aggregates (the PMIS weights and round structure are bit-exact
across implementations), identical level row counts (hence identical
grid complexity), and operator entries equal to dtype tolerance (the
two backends sum the same Galerkin products in different orders).
"""
import dataclasses

import jax.numpy as jnp
import numpy as np
import pytest

import amgx_tpu as amgx
from amgx_tpu import gallery
from amgx_tpu.amg.hierarchy import AMG
from amgx_tpu.config import Config
from amgx_tpu.errors import BadConfigurationError
from amgx_tpu.matrix import device_setup_forced, forced_device_setup

amgx.initialize()


def _amg(extra: str, A):
    cfg = Config.from_string(
        "algorithm=CLASSICAL, selector=PMIS, interpolator=D2,"
        " smoother=JACOBI_L1, coarse_solver=DENSE_LU_SOLVER,"
        " min_coarse_rows=8, max_levels=10, " + extra)
    return AMG(cfg).setup(A)


def _level_rows(amg):
    return [lv.A.num_rows for lv in amg.levels] + [amg.coarsest_A.num_rows]


def _assert_parity(h, d, atol=1e-11):
    assert len(h.levels) == len(d.levels)
    assert _level_rows(h) == _level_rows(d), "grid complexity drifted"
    for lh, ld in zip(h.levels, d.levels):
        if getattr(lh, "cf_map", None) is not None:
            assert np.array_equal(np.asarray(lh.cf_map),
                                  np.asarray(ld.cf_map)), \
                "CF split differs between backends"
        if getattr(lh, "aggregates", None) is not None:
            assert np.array_equal(np.asarray(lh.aggregates),
                                  np.asarray(ld.aggregates)), \
                "aggregates differ between backends"
    mats_h = [lv.A for lv in h.levels] + [h.coarsest_A]
    mats_d = [lv.A for lv in d.levels] + [d.coarsest_A]
    for Mh, Md in zip(mats_h, mats_d):
        np.testing.assert_allclose(
            np.asarray(Mh.to_dense()), np.asarray(Md.to_dense()),
            rtol=1e-10, atol=atol)


class TestClassicalParity:
    # the forced-device pipeline is eager-dispatch-bound on a CPU rig,
    # so only ONE representative parity test per family stays in the
    # tier-1 budget; the broader matrix runs with `-m slow`
    @pytest.mark.parametrize("interp", ["D2"])
    def test_pmis_parity_2d(self, interp):
        A = gallery.poisson("5pt", 24, 24).init()
        h = _amg(f"interpolator={interp}, setup_backend=host", A)
        d = _amg(f"interpolator={interp}, setup_backend=device", A)
        assert all(lv.built_backend == "device" for lv in d.levels)
        assert all(lv.built_backend == "host" for lv in h.levels)
        _assert_parity(h, d)

    @pytest.mark.slow
    def test_pmis_d1_parity_2d(self):
        A = gallery.poisson("5pt", 24, 24).init()
        _assert_parity(_amg("interpolator=D1, setup_backend=host", A),
                       _amg("interpolator=D1, setup_backend=device", A))

    @pytest.mark.slow
    def test_pmis_d2_parity_3d(self):
        A = gallery.poisson("7pt", 10, 10, 10).init()
        _assert_parity(_amg("setup_backend=host", A),
                       _amg("setup_backend=device", A))

    @pytest.mark.slow
    def test_truncated_production_config_parity(self):
        """The reference's D2 production knobs (truncation + row-sum
        weakening) through both backends."""
        extra = ("interp_max_elements=4, max_row_sum=0.9,"
                 " strength_threshold=0.25, ")
        A = gallery.poisson("9pt", 20, 20).init()
        _assert_parity(_amg(extra + "setup_backend=host", A),
                       _amg(extra + "setup_backend=device", A))

    @pytest.mark.slow
    def test_hmis_parity_queue_escape_hatch(self):
        """selector_device_sweep=0 pins the host-serial bucket queue in
        BOTH backends (the pre-ISSUE-12 composition): splits must be
        bit-identical across backends — the escape hatch that restores
        the old host-RS-everywhere behavior."""
        A = gallery.poisson("5pt", 18, 18).init()
        extra = "selector=HMIS, selector_device_sweep=0, setup_backend="
        _assert_parity(_amg(extra + "host", A),
                       _amg(extra + "device", A))


class TestSelectorDeviceSweep:
    """The device-parallel RS/HMIS first pass (ISSUE 12: rs_sweep, a
    PMIS-style fixpoint with the live RS weight as priority). The
    sweep is a DIFFERENT algorithm from the serial bucket queue (whose
    dynamic LIFO tie-break is inherently serial), so its parity
    contract is across BACKENDS: integer-keyed, bit-identical splits
    whether it runs in the host or the forced-device pipeline."""

    @pytest.mark.parametrize("selector", ["HMIS", "RS"])
    def test_sweep_backend_parity(self, selector):
        A = gallery.poisson("5pt", 18, 18).init()
        extra = (f"selector={selector}, selector_device_sweep=1,"
                 " setup_backend=")
        _assert_parity(_amg(extra + "host", A),
                       _amg(extra + "device", A))

    def test_device_backend_routes_to_sweep(self):
        """setup_backend=device + selector_device_sweep=auto takes the
        sweep (counted); the host backend keeps the bucket queue."""
        from amgx_tpu.telemetry import metrics as _tm
        A = gallery.poisson("5pt", 12, 12).init()
        c0 = int(_tm.get("amg.selector.device_sweep"))
        _amg("selector=HMIS, setup_backend=host", A)
        assert int(_tm.get("amg.selector.device_sweep")) == c0
        d = _amg("selector=HMIS, setup_backend=device", A)
        assert int(_tm.get("amg.selector.device_sweep")) > c0
        assert all(lv.built_backend == "device" for lv in d.levels)

    def test_sweep_covers_fine_points(self):
        """Every FINE point with a strong edge must see a COARSE
        neighbor (classical interpolation's hard requirement) — the
        sweep's equivalent of the queue's coverage invariant."""
        from amgx_tpu import registry
        from amgx_tpu.amg.classical.selectors import rs_sweep
        A = gallery.poisson("9pt", 16, 16).init()
        cfg = Config.from_string(
            "algorithm=CLASSICAL, strength_threshold=0.25")
        st = registry.strength.create("AHAT", cfg, "default")
        strong = st.strong_mask(A)
        cf = np.asarray(rs_sweep(A, strong))
        n = A.num_rows
        ro = np.asarray(A.row_offsets)
        ci = np.asarray(A.col_indices)
        stn = np.asarray(strong, bool)
        rows = np.repeat(np.arange(n), np.diff(ro))
        mask = stn & (ci < n) & (ci != rows)
        er, ec = rows[mask], ci[mask]
        covered = np.zeros(n, bool)
        np.maximum.at(covered, er, cf[ec] == 1)
        has_edge = np.zeros(n, bool)
        has_edge[er] = True
        assert not ((cf == 0) & has_edge & ~covered).any()
        assert 0.1 < cf.mean() < 0.9      # a real split, not all-C/F

    @pytest.mark.slow
    def test_sweep_hierarchy_converges_like_queue(self):
        """Solver quality oracle: a sweep-coarsened HMIS hierarchy
        converges within a few iterations of the bucket-queue build."""
        A = gallery.poisson("7pt", 12, 12, 12).init()
        b = np.ones(A.num_rows)
        iters = {}
        for mode in ("0", "1"):
            cfg = Config.from_string(
                "solver(s)=PCG, s:max_iters=80, s:tolerance=1e-8,"
                " s:convergence=RELATIVE_INI, s:monitor_residual=1,"
                " s:preconditioner(amg)=AMG, amg:algorithm=CLASSICAL,"
                " amg:selector=HMIS, amg:interpolator=D2,"
                " amg:smoother=JACOBI_L1, amg:max_iters=1,"
                " amg:min_coarse_rows=16, amg:max_levels=10,"
                f" amg:selector_device_sweep={mode}")
            s = amgx.create_solver(cfg)
            s.setup(A)
            r = s.solve(jnp.asarray(b))
            assert bool(r.converged), mode
            iters[mode] = int(r.iterations)
        assert abs(iters["0"] - iters["1"]) <= 5, iters


class TestAggregationParity:
    def test_size2_parity(self):
        A = gallery.poisson("7pt", 8, 8, 8).init()
        base = ("algorithm=AGGREGATION, selector=SIZE_2,"
                " smoother=JACOBI_L1, coarse_solver=DENSE_LU_SOLVER,"
                " min_coarse_rows=8, max_levels=10, setup_backend=")
        h = AMG(Config.from_string(base + "host")).setup(A)
        d = AMG(Config.from_string(base + "device")).setup(A)
        _assert_parity(h, d)

    @pytest.mark.slow
    def test_device_solve_converges(self):
        """End-to-end: a solver whose AMG preconditioner was built by
        the device pipeline converges like the host-built one."""
        A = gallery.poisson("7pt", 12, 12, 12).init()
        b = np.ones(A.num_rows)
        iters = {}
        for be in ("host", "device"):
            cfg = Config.from_string(
                "solver(s)=PCG, s:max_iters=60, s:tolerance=1e-8,"
                " s:convergence=RELATIVE_INI, s:monitor_residual=1,"
                " s:preconditioner(amg)=AMG, amg:algorithm=CLASSICAL,"
                " amg:selector=PMIS, amg:interpolator=D2,"
                " amg:smoother=JACOBI_L1, amg:max_iters=1,"
                " amg:min_coarse_rows=16, amg:max_levels=10,"
                f" amg:setup_backend={be}")
            s = amgx.create_solver(cfg)
            s.setup(A)
            r = s.solve(b)
            assert bool(r.converged), be
            iters[be] = int(r.iterations)
        assert iters["host"] == iters["device"]


class TestBackendDispatch:
    def test_auto_uses_host_impls_on_cpu(self):
        A = gallery.poisson("5pt", 16, 16).init()
        amg = _amg("setup_backend=auto", A)
        assert amg._setup_backend_used == "auto"
        assert all(lv.built_backend == "host" for lv in amg.levels)

    @pytest.mark.slow     # forced-device dispatch is also proven by
    # TestClassicalParity (built_backend asserts); eager-bound on CPU
    def test_device_forces_jnp_impls(self):
        A = gallery.poisson("5pt", 16, 16).init()
        amg = _amg("setup_backend=device", A)
        assert amg._setup_backend_used == "device"
        assert all(lv.built_backend == "device" for lv in amg.levels)

    def test_min_rows_threshold_lifts_forcing(self):
        """setup_device_min_rows: tiny levels drop back to the host
        numpy fast paths (the dispatch-overhead escape hatch)."""
        A = gallery.poisson("5pt", 16, 16).init()
        amg = _amg("setup_backend=device, setup_device_min_rows=100", A)
        backends = [lv.built_backend for lv in amg.levels]
        assert backends[0] == "device"          # 256 rows: forced
        assert all(b == "host" for lv, b in zip(amg.levels, backends)
                   if lv.A.num_rows < 100)

    def test_bad_backend_value_rejected(self):
        with pytest.raises(BadConfigurationError):
            Config.from_string("setup_backend=banana")

    def test_forcing_context_restores(self):
        assert not device_setup_forced()
        with forced_device_setup():
            assert device_setup_forced()
            with forced_device_setup(False):
                assert not device_setup_forced()
            assert device_setup_forced()
        assert not device_setup_forced()


class TestL0LayoutReuse:
    def test_pull_host_l0_reuses_built_layout(self, monkeypatch):
        """When the caller's matrix already carries its SpMV layout,
        the host pull serves every piece (incl. DIA payloads) without
        re-packing — init() must never run."""
        A = gallery.poisson("7pt", 8, 8, 8).init()
        assert A.dia_vals is not None
        amg = AMG(Config.from_string("algorithm=AGGREGATION"))
        from amgx_tpu.matrix import CsrMatrix

        def boom(self, *a, **k):  # pragma: no cover - guard
            raise AssertionError("layout was rebuilt instead of reused")

        monkeypatch.setattr(CsrMatrix, "init", boom)
        Af = amg._pull_host_l0(A)
        assert Af.initialized
        assert Af.dia_offsets == A.dia_offsets
        np.testing.assert_array_equal(np.asarray(Af.dia_vals),
                                      np.asarray(A.dia_vals))

    def test_pull_host_l0_falls_back_uninitialized(self):
        A = gallery.poisson("5pt", 8, 8)       # no layout yet
        amg = AMG(Config.from_string("algorithm=AGGREGATION"))
        Af = amg._pull_host_l0(A)
        assert Af.initialized


class TestSetupAttribution:
    def test_breakdown_accounts_for_wall(self):
        """The amg.* regions are disjoint leaves covering the setup's
        main-thread wall: their sum must reach >= 85% of a warm setup
        at test scale (bench enforces >= 90% at bench scale, where
        fixed per-call overheads amortize). Wall time at test scale is
        tens of milliseconds, so a scheduler preemption between two
        regions under full-suite load can push one sample just under
        the floor — the invariant holds if ANY of three warm attempts
        reaches it."""
        import time

        from amgx_tpu import profiling
        from amgx_tpu.presets import FLAGSHIP
        import jax
        A = gallery.poisson("7pt", 16, 16, 16).init()
        warm = amgx.create_solver(Config.from_string(FLAGSHIP))
        warm.setup(A)
        jax.block_until_ready(warm.solve_data())
        attempts = []
        for _ in range(3):
            slv = amgx.create_solver(Config.from_string(FLAGSHIP))
            profiling.reset_timers()
            t0 = time.perf_counter()
            slv.setup(A)
            with profiling.trace_region("amg.device_sync"):
                jax.block_until_ready(slv.solve_data())
            wall = time.perf_counter() - t0
            accounted = profiling.timers_total("amg.")
            attempts.append(accounted / wall)
            if attempts[-1] >= 0.85:
                break
        assert max(attempts) >= 0.85, (attempts, profiling.timers())

    def test_layout_timer_measures_packing(self):
        """Satellite regression: amg.Lx.layout must wrap the actual
        packing call sites (it used to report 0.0 on the GEO path,
        whose DIA pack hid inside the galerkin bucket)."""
        from amgx_tpu import profiling
        from amgx_tpu.presets import FLAGSHIP
        A = gallery.poisson("7pt", 16, 16, 16).init()
        slv = amgx.create_solver(Config.from_string(FLAGSHIP))
        profiling.reset_timers()
        slv.setup(A)
        t = profiling.timers()
        layout = [k for k in t if ".layout" in k and k.startswith("amg.L")]
        assert layout, t.keys()
        assert sum(t[k][1] for k in layout) > 0.0
