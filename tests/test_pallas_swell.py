"""Windowed-ELL (SWELL) layout + SpMV tests.

The Pallas kernel itself (ops/pallas_swell.py) only runs on a real TPU;
these tests exercise the layout construction, the XLA gather form (the
semantics the kernel reproduces), the init()-time layout choice, the
interpreter form of the kernel, and coefficient replacement.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
import scipy.sparse as sp

from amgx_tpu.matrix import CsrMatrix
from amgx_tpu.ops.pallas_swell import (build_swell_host, swell_spmv,
                                       swell_spmv_xla, swell_vals_host)
from amgx_tpu.ops.spmv import spmv


def _random_local(rng, n, m, width, kmax=12):
    rows = np.repeat(np.arange(n), rng.integers(1, kmax, n))
    center = (rows * m) // max(n, 1)
    cols = np.clip(center + rng.integers(-width, width, rows.shape[0]),
                   0, m - 1)
    vals = rng.standard_normal(rows.shape[0])
    S = sp.csr_matrix((vals, (rows, cols)), shape=(n, m))
    S.sum_duplicates()
    return S


def _swell_matrix(S, dtype=np.float64):
    sw = build_swell_host(S.indptr, S.indices, S.data.astype(dtype),
                          S.shape[0], S.shape[1])
    assert sw is not None
    cols4, vals4, c0row, nchunk, w128 = sw
    return CsrMatrix(
        row_offsets=jnp.asarray(S.indptr, jnp.int32),
        col_indices=jnp.asarray(S.indices, jnp.int32),
        values=jnp.asarray(S.data.astype(dtype)),
        num_rows=S.shape[0], num_cols=S.shape[1], initialized=True,
        swell_cols=jnp.asarray(cols4), swell_vals=jnp.asarray(vals4),
        swell_c0row=jnp.asarray(c0row), swell_nchunk=jnp.asarray(nchunk),
        swell_w128=w128)


@pytest.mark.parametrize("shape", [(3000, 3000), (4000, 900), (900, 4000)])
def test_swell_xla_matches_scipy(shape):
    rng = np.random.default_rng(3)
    S = _random_local(rng, *shape, width=300)
    A = _swell_matrix(S)
    x = jnp.asarray(rng.standard_normal(shape[1]))
    y = np.asarray(swell_spmv_xla(A, x))
    y_ref = S @ np.asarray(x)
    assert np.allclose(y, y_ref, atol=1e-10)


def test_swell_kernel_interpret_matches_scipy():
    rng = np.random.default_rng(5)
    S = _random_local(rng, 2100, 2100, width=200)
    A = _swell_matrix(S, np.float32)
    x = jnp.asarray(rng.standard_normal(2100), jnp.float32)
    y = np.asarray(swell_spmv(A, x, interpret=True))
    y_ref = (S @ np.asarray(x, np.float64)).astype(np.float32)
    assert np.allclose(y, y_ref, rtol=2e-5, atol=2e-5)


def test_init_host_builds_swell_for_unstructured():
    rng = np.random.default_rng(11)
    S = _random_local(rng, 3000, 3000, width=400, kmax=30)
    A = CsrMatrix.from_scipy_like(S.indptr, S.indices, S.data, 3000, 3000)
    Ai = A.init()
    # banded-but-not-DIA local matrix: the host layout choice lands on
    # SWELL (irregular offsets exceed the DIA budget)
    assert Ai.dia_offsets is None
    assert Ai.swell_cols is not None
    x = jnp.asarray(rng.standard_normal(3000))
    assert np.allclose(np.asarray(spmv(Ai, x)), S @ np.asarray(x),
                       atol=1e-10)
    # slim view keeps the layout and still SpMVs
    sl = Ai.slim_for_spmv()
    assert sl.swell_cols is not None
    assert np.allclose(np.asarray(spmv(sl, x)), S @ np.asarray(x),
                       atol=1e-10)


def test_swell_with_values_rescatter():
    rng = np.random.default_rng(13)
    S = _random_local(rng, 1500, 1500, width=150)
    A = CsrMatrix.from_scipy_like(S.indptr, S.indices, S.data,
                                  1500, 1500).init()
    assert A.swell_cols is not None
    new_vals = jnp.asarray(rng.standard_normal(S.nnz))
    A2 = A.with_values(new_vals)
    S2 = sp.csr_matrix((np.asarray(new_vals), S.indices, S.indptr),
                       shape=S.shape)
    x = jnp.asarray(rng.standard_normal(1500))
    assert np.allclose(np.asarray(spmv(A2, x)), S2 @ np.asarray(x),
                       atol=1e-10)


def test_swell_bails_on_wide_rows():
    # one dense row exceeds the slot budget -> layout not built
    n = 600
    rows = np.concatenate([np.arange(n), np.zeros(520, np.int64)])
    cols = np.concatenate([np.arange(n), np.arange(520) * 1])
    vals = np.ones(rows.shape[0])
    S = sp.csr_matrix((vals, (rows, cols)), shape=(n, n))
    S.sum_duplicates()
    out = build_swell_host(S.indptr, S.indices, S.data, n, n)
    assert out is None


def test_swell_empty_rows_and_tail():
    # rows with no entries + n not a multiple of 1024
    rng = np.random.default_rng(17)
    n = 1500
    rows = np.repeat(np.arange(0, n, 3), 2)
    cols = np.clip(rows + rng.integers(-40, 40, rows.shape[0]), 0, n - 1)
    S = sp.csr_matrix((np.ones(rows.shape[0]), (rows, cols)), shape=(n, n))
    S.sum_duplicates()
    A = _swell_matrix(S)
    x = jnp.asarray(rng.standard_normal(n))
    assert np.allclose(np.asarray(swell_spmv_xla(A, x)), S @ np.asarray(x),
                       atol=1e-12)
