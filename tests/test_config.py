"""Config-system tests (analog of src/tests/config_parsing.cu)."""
import json

import pytest

from amgx_tpu.config import Config
from amgx_tpu.errors import AMGXError


def test_defaults():
    cfg = Config()
    assert cfg.get("max_iters") == 100
    assert cfg.get("tolerance") == 1e-12
    assert cfg.get("solver") == "AMG"
    assert cfg.get("cycle") == "V"


def test_flat_string():
    cfg = Config.from_string(
        "max_iters=42, tolerance=1e-8; monitor_residual=1")
    assert cfg.get("max_iters") == 42
    assert cfg.get("tolerance") == 1e-8
    assert cfg.get("monitor_residual") == 1


def test_scoped_string():
    cfg = Config.from_string(
        "solver(amg)=AMG, amg:presweeps=2, amg:max_iters=1, max_iters=50")
    assert cfg.get("solver") == "AMG"
    assert cfg.get_scope("solver") == "amg"
    assert cfg.get("presweeps", "amg") == 2
    assert cfg.get("max_iters", "amg") == 1
    assert cfg.get("max_iters") == 50
    # fallback: unset in scope -> default scope
    assert cfg.get("postsweeps", "amg") == 1


def test_json_v2_nested_scopes():
    obj = {
        "config_version": 2,
        "solver": {
            "scope": "main",
            "solver": "FGMRES",
            "max_iters": 100,
            "gmres_n_restart": 10,
            "preconditioner": {
                "scope": "amg",
                "solver": "AMG",
                "algorithm": "AGGREGATION",
                "selector": "SIZE_2",
                "max_iters": 1,
                "smoother": "MULTICOLOR_DILU",
            },
        },
    }
    cfg = Config.from_dict(obj)
    name, scope = cfg.get_solver("solver")
    assert (name, scope) == ("FGMRES", "main")
    assert cfg.get("max_iters", "main") == 100
    pname, pscope = cfg.get_solver("preconditioner", "main")
    assert (pname, pscope) == ("AMG", "amg")
    assert cfg.get("selector", "amg") == "SIZE_2"
    assert cfg.get("max_iters", "amg") == 1
    assert cfg.get("algorithm", "amg") == "AGGREGATION"


def test_reference_config_file_parses(tmp_path):
    # shipped-config shape (mirrors src/configs/FGMRES_AGGREGATION.json)
    obj = {
        "config_version": 2,
        "solver": {
            "preconditioner": {
                "error_scaling": 0,
                "algorithm": "AGGREGATION",
                "solver": "AMG",
                "smoother": "MULTICOLOR_DILU",
                "presweeps": 0,
                "selector": "SIZE_2",
                "coarse_solver": "DENSE_LU_SOLVER",
                "max_iters": 1,
                "postsweeps": 3,
                "min_coarse_rows": 32,
                "relaxation_factor": 0.75,
                "scope": "amg",
                "max_levels": 50,
                "cycle": "V",
            },
            "use_scalar_norm": 1,
            "solver": "FGMRES",
            "max_iters": 100,
            "monitor_residual": 1,
            "gmres_n_restart": 10,
            "convergence": "RELATIVE_INI",
            "scope": "main",
            "tolerance": 1e-06,
            "norm": "L2",
        },
    }
    p = tmp_path / "cfg.json"
    p.write_text(json.dumps(obj))
    cfg = Config.from_file(str(p))
    assert cfg.get_solver("solver") == ("FGMRES", "main")
    assert cfg.get_solver("coarse_solver", "amg") == ("DENSE_LU_SOLVER",
                                                      "default")
    assert cfg.get("relaxation_factor", "amg") == 0.75
    assert cfg.get("norm", "main") == "L2"


def test_validation_errors():
    with pytest.raises(AMGXError):
        Config.from_string("no_such_param=3")
    with pytest.raises(AMGXError):
        Config.from_string("cycle=Q")
    with pytest.raises(AMGXError):
        Config.from_string("relaxation_factor=5.0")  # above max 2.0
    with pytest.raises(AMGXError):
        # non-solver param cannot open a scope
        Config.from_string("max_iters(foo)=3")


def test_case_tolerant_enums():
    cfg = Config.from_string("norm=l2")
    assert cfg.get("norm") == "L2"
