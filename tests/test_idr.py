"""IDR(s) convergence tests (IDR_Convergence_Poisson.cu /
IDRMSYNC_Convergence_Poisson.cu analogs)."""
import jax.numpy as jnp
import numpy as np
import pytest

import amgx_tpu as amgx
from amgx_tpu.config import Config

amgx.initialize()


@pytest.mark.parametrize("name", ["IDR", "IDRMSYNC"])
@pytest.mark.parametrize("s", [2, 4, 8])
def test_idr_convergence_poisson(name, s):
    A = amgx.gallery.poisson("5pt", 20, 20).init()
    b = jnp.ones(A.num_rows)
    cfg = Config.from_string(
        f"solver={name}, subspace_dim_s={s}, max_iters=120,"
        " monitor_residual=1, tolerance=1e-8, convergence=RELATIVE_INI,"
        " preconditioner=NOSOLVER")
    slv = amgx.create_solver(cfg)
    slv.setup(A)
    res = slv.solve(b)
    assert res.converged, (name, s, res.res_norm)
    r = np.asarray(amgx.ops.residual(A, res.x, b))
    assert np.linalg.norm(r) < 1e-7 * np.linalg.norm(np.asarray(b))


def test_idr_with_jacobi_preconditioner():
    A = amgx.gallery.poisson("7pt", 12, 12, 12).init()
    b = jnp.ones(A.num_rows)
    cfg = Config.from_string(
        "solver=IDR, subspace_dim_s=4, max_iters=120, monitor_residual=1,"
        " tolerance=1e-8, preconditioner(j)=BLOCK_JACOBI, j:max_iters=2")
    slv = amgx.create_solver(cfg)
    slv.setup(A)
    res = slv.solve(b)
    assert res.converged


def test_idr_beats_unpreconditioned_iteration_budget():
    """IDR(8) should converge in substantially fewer cycles than IDR(1)
    on the same problem (the point of larger shadow spaces); each cycle
    does s+1 SpMVs, so compare matvec counts loosely."""
    A = amgx.gallery.poisson("5pt", 24, 24).init()
    b = jnp.ones(A.num_rows)
    cycles = {}
    for s in (1, 8):
        cfg = Config.from_string(
            f"solver=IDR, subspace_dim_s={s}, max_iters=400,"
            " monitor_residual=1, tolerance=1e-8, preconditioner=NOSOLVER")
        slv = amgx.create_solver(cfg)
        slv.setup(A)
        res = slv.solve(b)
        assert res.converged, (s, res.res_norm)
        cycles[s] = res.iterations
    assert cycles[8] < cycles[1]
