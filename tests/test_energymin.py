"""Energymin AMG tests (energymin_algorithm.cu analog)."""
import numpy as np
import pytest

import amgx_tpu as amgx
from amgx_tpu import gallery, registry
from amgx_tpu.config import Config
from amgx_tpu.solvers import make_solver

amgx.initialize()


@pytest.fixture(scope="module")
def A():
    return gallery.poisson("5pt", 16, 16).init()


def test_em_interpolation_properties(A):
    cfg = Config.from_string("strength_threshold=0.25")
    strong = registry.strength.create("AHAT", cfg, "default"
                                      ).strong_mask(A)
    from amgx_tpu.amg.classical.selectors import pmis_split
    cf = pmis_split(A, strong)
    em = registry.energymin_interpolators.create("EM", cfg, "default")
    P = em.generate(A, cf, strong)
    Pd = np.asarray(P.to_dense())
    cfn = np.asarray(cf)
    # C rows are injection
    crows = np.where(cfn == 1)[0]
    cidx = np.cumsum(cfn == 1) - 1
    for r in crows[:10]:
        row = Pd[r]
        assert row[cidx[r]] == 1.0 and np.count_nonzero(row) == 1
    # covered F rows preserve constants
    frows = np.abs(Pd).sum(1) > 0
    fine = cfn == 0
    sums = Pd.sum(1)[fine & frows]
    np.testing.assert_allclose(sums, 1.0, atol=1e-10)


def test_energymin_amg_converges(A):
    cfg = Config.from_string(
        "solver=AMG, algorithm=ENERGYMIN, energymin_selector=CR, "
        "max_iters=60, tolerance=1e-8, monitor_residual=1, "
        "convergence=RELATIVE_INI_CORE")
    slv = make_solver("AMG", cfg, "default").setup(A)
    res = slv.solve(np.ones(A.num_rows))
    assert res.converged


def test_energymin_pmis_selector(A):
    """energymin_selector accepts any classical selector (the reference
    allocates from the classical SelectorFactory)."""
    cfg = Config.from_string(
        "solver=AMG, algorithm=ENERGYMIN, energymin_selector=PMIS, "
        "max_iters=60, tolerance=1e-8, monitor_residual=1, "
        "convergence=RELATIVE_INI_CORE")
    slv = make_solver("AMG", cfg, "default").setup(A)
    res = slv.solve(np.ones(A.num_rows))
    assert res.converged


def test_energymin_as_preconditioner(A):
    slv = amgx.create_solver(Config.from_string(
        "solver=PCG, preconditioner=AMG, algorithm=ENERGYMIN, "
        "energymin_selector=PMIS, max_iters=100, tolerance=1e-8, "
        "monitor_residual=1, convergence=RELATIVE_INI_CORE"))
    slv.setup(A)
    res = slv.solve(np.ones(A.num_rows))
    assert res.converged
