"""Online config autotuner tests (amgx_tpu/serving/autotune.py): the
diagnostics->candidate mapping shared with the convergence doctor, the
per-fingerprint exec-time estimator (mixed-size traffic must not shed
the small tenant on the big tenant's median), the default-off inertness
contract (autotune=0 builds no tuner, applies no overlay, changes no
trace counts), shadow isolation (a saturated service runs ZERO shadow
solves and the search introduces no deadline misses), chaos absorption
(an injected shadow-solve crash is counted + backed off, never a failed
ticket), the promote path end to end (mistuned fingerprint converges
strictly faster after promotion), restart durability (the tuned config
survives via the hstore and serves from the first request with zero
full setups), drain quiescing, and the fleet drain_replica tuned-config
handoff. No reference analog — AMGX has no online tuner; the invariants
are the service's own contracts."""
import os
import time

import numpy as np
import pytest

import amgx_tpu as amgx
from amgx_tpu import gallery
from amgx_tpu.config import Config
from amgx_tpu.presets import BATCHED_CG
from amgx_tpu.resilience import faultinject
from amgx_tpu.resilience.status import SolveStatus
from amgx_tpu.serving import FleetRouter, SolveService
from amgx_tpu.telemetry import metrics
from amgx_tpu.telemetry.diagnostics import (HINT_CORRECTION,
                                            HINT_SMOOTHER,
                                            suggest_config_deltas)

amgx.initialize()


@pytest.fixture(scope="module")
def geo10():
    return gallery.poisson("7pt", 10, 10, 10).init()


def _rhs(A, seed=0):
    return np.random.default_rng(seed).standard_normal(A.num_rows)


# a deliberately mistuned config: overdamped BLOCK_JACOBI (the
# convergence-doctor demo's classic) on the aggregation path — the
# diagnostics probe attributes it, the smoother/relaxation candidates
# fix it
MISTUNED = (BATCHED_CG +
            ", amg:smoother(sm2)=BLOCK_JACOBI, sm2:max_iters=1,"
            " sm2:relaxation_factor=0.15,"
            " serving_bucket_slots=2, serving_chunk_iters=8")


def _at_cfg(extra=""):
    return Config.from_string(
        MISTUNED + ", autotune=1, autotune_hot_requests=4,"
        " autotune_hot_exec_share=0.0"
        + (", " + extra if extra else ""))


def _heat(svc, A, n=5, seed0=0):
    """Submit + drain `n` same-fingerprint requests (makes the
    fingerprint hot without letting the tuner act: drain quiesces)."""
    tix = [svc.submit(A, _rhs(A, seed0 + i)) for i in range(n)]
    svc.drain(timeout_s=600)
    assert all(t.done for t in tix)
    return tix


def _search(svc, max_steps=16):
    """Idle scheduler cycles: each may run one shadow solve."""
    for _ in range(max_steps):
        svc.step()
        if svc.stats()["autotune"]["promoted"]:
            break


# ---------------------------------------------------------------------------
# diagnostics -> candidate mapping (shared with the convergence doctor)
# ---------------------------------------------------------------------------


def test_suggest_config_deltas_rules():
    diag = {"levels": [
        {"level": 0, "smoother_effectiveness": 0.95,
         "correction_reduction": 1.5}],
        "bottleneck_level": 0,
        "asymptotic_convergence_factor": 0.9}
    out = suggest_config_deltas(diag)
    knobs = [s["knob"] for s in out]
    assert knobs == ["smoother_swap", "relaxation", "strength",
                     "interp", "cycle"]
    by = {s["knob"]: s for s in out}
    # doctor hints ride the suggestions they came from
    assert by["smoother_swap"]["hint"] == HINT_SMOOTHER
    assert by["relaxation"]["hint"] == HINT_SMOOTHER
    assert by["strength"]["hint"] == HINT_CORRECTION
    assert by["cycle"]["hint"] is None
    assert by["smoother_swap"]["deltas"] == [
        {"param": "smoother", "value": "JACOBI_L1"},
        {"param": "relaxation_factor", "value": 0.9}]
    assert by["cycle"]["deltas"] == [{"param": "cycle", "value": "W"}]
    # comfortable convergence -> the precision wall lever, alone
    fast = {"levels": [{"level": 0, "smoother_effectiveness": 0.2,
                        "correction_reduction": 0.5}],
            "bottleneck_level": 0,
            "asymptotic_convergence_factor": 0.2}
    assert [s["knob"] for s in suggest_config_deltas(fast)] \
        == ["precision"]
    # no diagnostics -> no candidates (the tuner then retires the
    # search instead of guessing)
    assert suggest_config_deltas(None) == []
    assert suggest_config_deltas({}) == []


def test_doctor_output_comes_from_shared_mapping():
    """The doctor's printed sentences are exactly the mapping's hint
    strings, deduplicated in rule order — refactor-proven by deriving
    them the way examples/convergence_doctor.py now does."""
    diag = {"levels": [
        {"level": 1, "smoother_effectiveness": 0.9,
         "correction_reduction": 1.3}],
        "bottleneck_level": 1,
        "asymptotic_convergence_factor": 0.95}
    hints = []
    for s in suggest_config_deltas(diag):
        if s["hint"] and s["hint"] not in hints:
            hints.append(s["hint"])
    assert hints == [HINT_SMOOTHER, HINT_CORRECTION]


# ---------------------------------------------------------------------------
# per-fingerprint exec-time estimator (satellite: mixed-size traffic)
# ---------------------------------------------------------------------------


def test_estimator_prefers_fingerprint_window(geo10):
    svc = SolveService(Config.from_string(
        BATCHED_CG + ", serving_bucket_slots=2,"
        " serving_chunk_iters=8, serving_shed_policy=deadline"))
    t0 = svc.submit(geo10, _rhs(geo10))
    svc.drain(timeout_s=600)
    assert t0.result.converged
    fp = t0.fingerprint
    # a co-resident big tenant polluted the GLOBAL window...
    svc._exec_recent.clear()
    svc._exec_recent.extend([5.0] * 10)
    # ...but this fingerprint's own window is trained and tight
    svc._exec_fp[fp].clear()
    svc._exec_fp[fp].extend([0.01] * 8)
    with svc._lock:
        est_fp = svc._estimate_latency_s(fp)
        est_global = svc._estimate_latency_s()
    assert est_fp < 0.1 < est_global


def test_small_tenant_not_shed_on_big_tenants_median(geo10):
    """The regression the satellite demands: under mixed-size traffic
    the small tenant's tight deadline used to be judged on the global
    median the big tenant dominates — now it is judged on its own
    fingerprint's history and admitted."""
    svc = SolveService(Config.from_string(
        BATCHED_CG + ", serving_bucket_slots=2,"
        " serving_chunk_iters=8, serving_shed_policy=deadline"))
    t0 = svc.submit(geo10, _rhs(geo10))
    svc.drain(timeout_s=600)
    fp = t0.fingerprint
    svc._exec_recent.clear()
    svc._exec_recent.extend([5.0] * 10)   # big tenant's medians
    svc._exec_fp[fp].clear()
    svc._exec_fp[fp].extend([0.01] * 8)   # the small tenant's own
    base_shed = metrics.get("serving.shed.deadline")
    t1 = svc.submit(geo10, _rhs(geo10, 1), deadline_s=1.0)
    assert not (t1.done and t1.result.status_code
                == int(SolveStatus.OVERLOADED))
    svc.drain(timeout_s=600)
    assert t1.result.converged
    assert metrics.get("serving.shed.deadline") == base_shed
    # an untrained fingerprint still falls back to the global window:
    # the same deadline against the polluted median sheds
    other = gallery.poisson("5pt", 12, 12).init()
    t2 = svc.submit(other, _rhs(other), deadline_s=1.0)
    assert t2.done and t2.result.status_code \
        == int(SolveStatus.OVERLOADED)
    assert metrics.get("serving.shed.deadline") == base_shed + 1


# ---------------------------------------------------------------------------
# default-off inertness (autotune=0)
# ---------------------------------------------------------------------------


def test_autotune_off_is_inert(geo10):
    base = {k: metrics.get(k) for k in (
        "autotune.hot", "autotune.shadow.runs",
        "autotune.overlay.applied", "autotune.promotions")}
    svc = SolveService(Config.from_string(MISTUNED))
    assert svc._tuner is None
    tix = _heat(svc, geo10, n=5)
    for _ in range(8):
        svc.step()                       # idle cycles: no tuner tick
    for k, v in base.items():
        assert metrics.get(k) == v, k
    # the engine was built from the SERVICE config object — no clone,
    # no overlay — and a tuner-enabled service that never promoted
    # solves bit-identically
    svc2 = SolveService(_at_cfg("autotune_hot_requests=1000"))
    tix2 = _heat(svc2, geo10, n=5)
    for a, b in zip(tix, tix2):
        assert a.result.iterations == b.result.iterations
        np.testing.assert_array_equal(np.asarray(a.result.x),
                                      np.asarray(b.result.x))
    eng = svc.buckets.peek(tix[0].fingerprint)
    eng2 = svc2.buckets.peek(tix2[0].fingerprint)
    assert eng.trace_count == eng2.trace_count


# ---------------------------------------------------------------------------
# shadow isolation + chaos absorption
# ---------------------------------------------------------------------------


def test_saturated_service_runs_no_shadows(geo10):
    """Shadow solves only ever occupy capacity production is not
    using: while the queue is non-empty not one shadow runs, and the
    search adds zero deadline misses to admitted traffic."""
    svc = SolveService(_at_cfg())
    base_runs = metrics.get("autotune.shadow.runs")
    base_miss = metrics.get("serving.deadline_miss")
    # a burst deeper than one bucket's slots: the queue stays
    # non-empty across many scheduler cycles
    tix = [svc.submit(geo10, _rhs(geo10, i)) for i in range(8)]
    saturated_cycles = 0
    for _ in range(400):
        with svc._lock:
            queued = len(svc._queue)
        svc.step()
        if queued:
            saturated_cycles += 1
            assert metrics.get("autotune.shadow.runs") == base_runs
        if svc.idle:
            break
    assert saturated_cycles >= 1          # the burst did queue
    assert all(t.done and t.result.converged for t in tix)
    assert metrics.get("serving.deadline_miss") == base_miss


def test_shadow_crash_absorbed_and_backed_off(geo10):
    """Chaos drill: an injected shadow-solve crash is counted and
    backs the fingerprint's search off — no ticket fails, the service
    stays serviceable, and the search recovers after the backoff."""
    svc = SolveService(_at_cfg())
    tix = _heat(svc, geo10, n=5)
    assert all(t.result.converged for t in tix)
    base_err = metrics.get("autotune.shadow.errors")
    with faultinject.inject("shadow_crash", fires=1):
        svc.step()                        # the baseline shadow crashes
    assert metrics.get("autotune.shadow.errors") == base_err + 1
    snap = svc.stats()["autotune"]["fingerprints"]
    rec = next(iter(snap.values()))
    assert rec["errors"] == 1 and rec["phase"] in ("hot", "search")
    # production is untouched: every ticket still terminal-converged,
    # and new traffic solves
    assert all(t.done and t.result.converged for t in tix)
    t2 = svc.submit(geo10, _rhs(geo10, 50))
    svc.drain(timeout_s=600)
    assert t2.result.converged
    # backoff elapses -> the search resumes and completes
    time.sleep(0.3)
    _search(svc)
    assert svc.stats()["autotune"]["promoted"] == 1


def test_second_shadow_crash_retires_search(geo10):
    svc = SolveService(_at_cfg())
    _heat(svc, geo10, n=5)
    with faultinject.inject("shadow_crash", fires=None):
        svc.step()
        time.sleep(0.3)
        svc.step()
    snap = svc.stats()["autotune"]["fingerprints"]
    rec = next(iter(snap.values()))
    assert rec["phase"] == "exhausted" and rec["errors"] == 2


# ---------------------------------------------------------------------------
# the promote path + drain quiesce
# ---------------------------------------------------------------------------


def test_promotion_fixes_mistuned_fingerprint(geo10):
    svc = SolveService(_at_cfg())
    base_runs = metrics.get("autotune.shadow.runs")
    tix = _heat(svc, geo10, n=5)
    pre = int(np.median([t.result.iterations for t in tix]))
    # drain() quiesced the tuner: not one shadow ran during it
    assert metrics.get("autotune.shadow.runs") == base_runs
    assert not svc._draining and not svc._tuner._quiesced
    _search(svc)
    snap = svc.stats()["autotune"]
    assert snap["promoted"] == 1
    rec = next(iter(snap["fingerprints"].values()))
    assert rec["phase"] == "promoted" and rec["overlay"]
    base_applied = metrics.get("autotune.overlay.applied")
    t2 = svc.submit(geo10, _rhs(geo10, 90))
    svc.drain(timeout_s=600)
    assert t2.result.converged
    assert metrics.get("autotune.overlay.applied") == base_applied + 1
    assert t2.result.iterations < pre


def test_fleet_drain_hands_off_tuned_config(tmp_path):
    """PR-17's rolling-restart path carries the tuner state: draining
    a replica hands its promoted overlays to the surviving replica
    its fingerprints rehome to, live + persisted in the adopter's
    hstore."""
    cfg = Config.from_string(
        MISTUNED + ", autotune=1, fleet_replicas=2,"
        f" serving_hierarchy_dir={tmp_path}/hier")
    fleet = FleetRouter.build(cfg, 2)
    rids = list(fleet.replicas)
    fp = "handoff-test-fingerprint/float64"
    state = {"deltas": [{"param": "relaxation_factor", "value": 0.9}],
             "knob": "relaxation", "trace": "tr-1"}
    fleet.replicas[rids[0]]._tuner.adopt(fp, state)
    base = metrics.get("autotune.handoffs")
    fleet.drain_replica(rids[0])
    assert metrics.get("autotune.handoffs") == base + 1
    adopted = fleet.replicas[rids[1]]._tuner.overlay_for(fp)
    assert adopted == state["deltas"]
    # ... and the adopter persisted it: ITS hstore resolves the
    # overlay for a fresh service too
    assert fleet.replicas[rids[1]].hstore.load_tuned(fp)["deltas"] \
        == state["deltas"]


# ---------------------------------------------------------------------------
# restart durability (extends the PR-11 recovery-guarantees table)
# ---------------------------------------------------------------------------


def test_tuned_config_survives_restart_zero_full_setups(geo10,
                                                        tmp_path):
    dirs = (f"serving_hierarchy_dir={tmp_path}/hier,"
            f" serving_journal_dir={tmp_path}/journal")
    svc = SolveService(_at_cfg(dirs))
    _heat(svc, geo10, n=5)
    _search(svc)
    assert svc.stats()["autotune"]["promoted"] == 1
    # one tuned build in THIS incarnation persists the tuned
    # hierarchy structure under the tuned config's keys
    t1 = svc.submit(geo10, _rhs(geo10, 91))
    svc.drain(timeout_s=600)
    tuned_iters = t1.result.iterations
    assert svc.hstore.load_tuned(t1.fingerprint) is not None

    # the restarted replica: overlay resolves from the hstore BEFORE
    # the first build — tuned from the first request, zero full
    # setups (hierarchy restored, not re-coarsened)
    base_restored = metrics.get("autotune.overlay.restored")
    base_full = metrics.get("amg.setup.full")
    svc2 = SolveService(_at_cfg(dirs))
    t2 = svc2.submit(geo10, _rhs(geo10, 91))   # t1's system again
    svc2.drain(timeout_s=600)
    assert t2.result.converged
    assert t2.result.iterations == tuned_iters
    assert metrics.get("autotune.overlay.restored") == base_restored + 1
    assert metrics.get("amg.setup.full") == base_full
    snap = svc2.stats()["autotune"]["fingerprints"]
    assert next(iter(snap.values()))["restored"]


def test_demotion_drops_overlay_and_record(geo10, tmp_path):
    """Hysteresis: a live regression past autotune_demote_factor over
    the demote window drops the overlay and deletes the persisted
    record."""
    svc = SolveService(_at_cfg(
        f"serving_hierarchy_dir={tmp_path}/hier,"
        " autotune_demote_window=2"))
    _heat(svc, geo10, n=5)
    _search(svc)
    assert svc.stats()["autotune"]["promoted"] == 1
    fp = next(iter(svc._tuner._fp))
    rec = svc._tuner._fp[fp]
    assert svc.hstore.load_tuned(fp) is not None
    # fake the regression: promoted-era completions far above the
    # pre-promotion median
    rec["pre_exec"] = 0.01
    rec["post"].extend([1.0, 1.0])
    base = metrics.get("autotune.demotions")
    svc.step()
    assert metrics.get("autotune.demotions") == base + 1
    assert rec["phase"] == "demoted" and rec["overlay"] is None
    assert svc.hstore.load_tuned(fp) is None
    assert svc._tuner.overlay_for(fp) is None
