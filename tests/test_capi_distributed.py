"""Distributed upload C API (include/amgx_c.h:235-586).

The reference's acceptance bar: uploading per-rank pieces through
AMGX_matrix_upload_distributed / AMGX_matrix_upload_all_global must
reproduce the global-upload solve. Here the pieces path never assembles
a global matrix (the arranger builds halo maps from global column ids,
distributed/partition.py partition_from_pieces) and the solve runs
distributed over the 8-device CPU mesh.
"""
import jax.numpy as jnp
import numpy as np
import pytest

import amgx_tpu as amgx
from amgx_tpu import capi
from amgx_tpu import gallery
from amgx_tpu.config import Config

N_DEV = 8

CFG = ("config_version=2, solver(s)=FGMRES, s:max_iters=60,"
       " s:tolerance=1e-8, s:convergence=RELATIVE_INI,"
       " s:gmres_n_restart=30, s:monitor_residual=1,"
       " s:preconditioner(amg)=AMG, amg:algorithm=AGGREGATION,"
       " amg:selector=SIZE_2, amg:smoother=JACOBI_L1, amg:presweeps=1,"
       " amg:postsweeps=1, amg:max_iters=1,"
       " amg:coarse_solver=DENSE_LU_SOLVER, amg:min_coarse_rows=16")


def _safe(rc, *out):
    assert rc == capi.RC.OK, capi.AMGX_get_error_string(rc)
    return out[0] if len(out) == 1 else out


def _pieces_of(A, offsets):
    ro = np.asarray(A.row_offsets)
    ci = np.asarray(A.col_indices)
    va = np.asarray(A.values)
    out = []
    for r in range(len(offsets) - 1):
        lo, hi = int(offsets[r]), int(offsets[r + 1])
        s, e = int(ro[lo]), int(ro[hi])
        out.append((ro[lo:hi + 1] - ro[lo], ci[s:e], va[s:e]))
    return out


def _global_solve(A, b):
    s = amgx.create_solver(Config.from_string(CFG))
    s.setup(A)
    return s.solve(jnp.asarray(b))


@pytest.fixture(scope="module")
def system():
    A = gallery.poisson("7pt", 12, 12, 12).init()
    b = np.ones(A.num_rows)
    return A, b


class TestUploadDistributed:
    @pytest.mark.slow
    def test_pieces_reproduce_global_solve(self, system):
        A, b = system
        n = A.num_rows
        n_local = -(-n // N_DEV)
        offsets = np.minimum(np.arange(N_DEV + 1) * n_local, n)

        capi.AMGX_initialize()
        cfg_h = _safe(*capi.AMGX_config_create(CFG))
        rs = _safe(*capi.AMGX_resources_create_simple(cfg_h))
        mtx = _safe(*capi.AMGX_matrix_create(rs, "dDDI"))
        dist = _safe(*capi.AMGX_distribution_create(cfg_h))
        _safe(capi.AMGX_distribution_set_partition_data(
            dist, capi.AMGX_DIST_PARTITION_OFFSETS, offsets))
        for ro, ci, va in _pieces_of(A, offsets):
            _safe(capi.AMGX_matrix_upload_distributed(
                mtx, n, len(ro) - 1, len(ci), 1, 1, ro, ci, va, None,
                dist))
        m = capi._get(mtx)
        assert m.part is not None and m.A is None   # no global assembly

        slv = _safe(*capi.AMGX_solver_create(rs, "dDDI", cfg_h))
        _safe(capi.AMGX_solver_setup(slv, mtx))
        rhs = _safe(*capi.AMGX_vector_create(rs, "dDDI"))
        sol = _safe(*capi.AMGX_vector_create(rs, "dDDI"))
        _safe(capi.AMGX_vector_bind(rhs, mtx))
        for r in range(N_DEV):
            lo, hi = int(offsets[r]), int(offsets[r + 1])
            _safe(capi.AMGX_vector_upload_distributed(
                rhs, hi - lo, 1, b[lo:hi]))
        _safe(capi.AMGX_solver_solve_with_0_initial_guess(slv, rhs, sol))
        rc, its = capi.AMGX_solver_get_iterations_number(slv)
        x = _safe(*capi.AMGX_vector_download(sol))

        ref = _global_solve(A, b)
        assert int(its) == int(ref.iterations)
        r = b - np.asarray(amgx.ops.spmv(A, jnp.asarray(x)))
        assert np.linalg.norm(r) / np.linalg.norm(b) < 1e-7

    @pytest.mark.slow     # heaviest upload variant; the other
    # distributed-upload tests keep the family in tier-1
    def test_upload_all_global_partition_vector(self, system):
        """Non-contiguous partition vector: rows renumbered to
        contiguous blocks (renumberMatrixOneRing analog), solve matches
        the global solve and the solution maps back to the original
        numbering."""
        A, b = system
        n = A.num_rows
        rng = np.random.default_rng(7)
        # contiguous blocks but shuffled rank labels: rank of block k
        # is labels[k] (a genuine renumbering exercise)
        n_local = -(-n // N_DEV)
        labels = rng.permutation(N_DEV)
        pv = labels[np.minimum(np.arange(n) // n_local, N_DEV - 1)]
        perm = np.argsort(pv, kind="stable")     # new -> old
        iperm = np.empty(n, np.int64)
        iperm[perm] = np.arange(n)

        capi.AMGX_initialize()
        cfg_h = _safe(*capi.AMGX_config_create(CFG))
        rs = _safe(*capi.AMGX_resources_create_simple(cfg_h))
        mtx = _safe(*capi.AMGX_matrix_create(rs, "dDDI"))
        ro = np.asarray(A.row_offsets)
        ci = np.asarray(A.col_indices)
        va = np.asarray(A.values)
        for r in range(N_DEV):
            rows_r = np.nonzero(pv == r)[0]      # ascending original ids
            counts = np.diff(ro)[rows_r]
            ro_r = np.concatenate([[0], np.cumsum(counts)])
            idx = np.concatenate(
                [np.arange(ro[i], ro[i + 1]) for i in rows_r]) \
                if rows_r.size else np.zeros(0, np.int64)
            _safe(capi.AMGX_matrix_upload_all_global(
                mtx, n, rows_r.size, idx.size, 1, 1, ro_r, ci[idx],
                va[idx], None, 1, 1, pv))
        m = capi._get(mtx)
        assert m.part is not None and m.A is None

        slv = _safe(*capi.AMGX_solver_create(rs, "dDDI", cfg_h))
        _safe(capi.AMGX_solver_setup(slv, mtx))
        rhs = _safe(*capi.AMGX_vector_create(rs, "dDDI"))
        sol = _safe(*capi.AMGX_vector_create(rs, "dDDI"))
        _safe(capi.AMGX_vector_bind(rhs, mtx))
        for r in range(N_DEV):
            rows_r = np.nonzero(pv == r)[0]
            _safe(capi.AMGX_vector_upload_distributed(
                rhs, rows_r.size, 1, b[rows_r]))
        _safe(capi.AMGX_solver_solve_with_0_initial_guess(slv, rhs, sol))
        x_new = _safe(*capi.AMGX_vector_download(sol))
        # solution is in renumbered space; map back: x_old = x_new[iperm]
        x_old = np.asarray(x_new)[iperm]
        r = b - np.asarray(amgx.ops.spmv(A, jnp.asarray(x_old)))
        assert np.linalg.norm(r) / np.linalg.norm(b) < 1e-7

    def test_uneven_pieces_resliced(self, system):
        """Uneven contiguous blocks are re-sliced to the equal-block
        physical layout (pure slicing, no renumbering)."""
        A, b = system
        import jax
        from amgx_tpu._compat import shard_map
        from jax.sharding import Mesh, PartitionSpec as P
        from amgx_tpu.distributed.partition import (
            partition_from_pieces, partition_vector, unpartition_vector)
        from amgx_tpu.distributed.dist_matrix import \
            shard_matrix_from_partition
        ro = np.asarray(A.row_offsets)
        ci = np.asarray(A.col_indices)
        va = np.asarray(A.values)
        cuts = [0, 100, 350, 600, 850, 1100, 1350, 1600, A.num_rows]
        pieces = []
        for r in range(8):
            lo, hi = cuts[r], cuts[r + 1]
            s, e = int(ro[lo]), int(ro[hi])
            pieces.append((ro[lo:hi + 1] - ro[lo], ci[s:e], va[s:e]))
        part = partition_from_pieces(pieces, A.num_rows)
        M = shard_matrix_from_partition(part, "p")
        mesh = Mesh(np.array(jax.devices()[:8]), ("p",))
        x = np.random.default_rng(0).standard_normal(A.num_rows)
        xl = partition_vector(x, 8, part.n_local)

        def fs(Ms, xs):
            return Ms.local().spmv(xs[0])[None]

        ps = jax.tree.map(lambda _: P("p"), M)
        y = jax.jit(shard_map(fs, mesh=mesh, in_specs=(ps, P("p")),
                              out_specs=P("p"), check_vma=False))(M, xl)
        y = np.asarray(unpartition_vector(y, A.num_rows))
        yref = np.asarray(amgx.ops.spmv(A, jnp.asarray(x)))
        assert np.abs(y - yref).max() < 1e-12

    def test_read_system_global_roundtrip(self, tmp_path, system):
        A, b = system
        from amgx_tpu.io.matrix_market import write_system
        p = str(tmp_path / "sys.mtx")
        write_system(p, A, b=jnp.asarray(b))
        rc, pieces = capi.AMGX_read_system_global(
            None, "dDDI", p, 1, N_DEV)
        assert rc == capi.RC.OK and len(pieces) == N_DEV
        assert sum(pc["n"] for pc in pieces) == A.num_rows
        # pieces feed upload_distributed unchanged
        capi.AMGX_initialize()
        cfg_h = _safe(*capi.AMGX_config_create(CFG))
        rs = _safe(*capi.AMGX_resources_create_simple(cfg_h))
        mtx = _safe(*capi.AMGX_matrix_create(rs, "dDDI"))
        dist = _safe(*capi.AMGX_distribution_create(cfg_h))
        _safe(capi.AMGX_distribution_set_partition_data(
            dist, capi.AMGX_DIST_PARTITION_OFFSETS,
            pieces[0]["partition_offsets"]))
        for pc in pieces:
            _safe(capi.AMGX_matrix_upload_distributed(
                mtx, A.num_rows, pc["n"], pc["nnz"], 1, 1,
                pc["row_ptrs"], pc["col_indices_global"], pc["data"],
                None, dist))
        assert capi._get(mtx).part is not None


@pytest.mark.slow
def test_replace_coefficients_pieces_path(system):
    """Coefficient replacement on the pieces path: per-rank value
    updates re-run the arranger against the stored structure; resetup
    then solves the updated system."""
    A, b = system
    n = A.num_rows
    n_local = -(-n // N_DEV)
    offsets = np.minimum(np.arange(N_DEV + 1) * n_local, n)
    capi.AMGX_initialize()
    cfg_h = _safe(*capi.AMGX_config_create(CFG))
    rs = _safe(*capi.AMGX_resources_create_simple(cfg_h))
    mtx = _safe(*capi.AMGX_matrix_create(rs, "dDDI"))
    dist = _safe(*capi.AMGX_distribution_create(cfg_h))
    _safe(capi.AMGX_distribution_set_partition_data(
        dist, capi.AMGX_DIST_PARTITION_OFFSETS, offsets))
    for ro, ci, va in _pieces_of(A, offsets):
        _safe(capi.AMGX_matrix_upload_distributed(
            mtx, n, len(ro) - 1, len(ci), 1, 1, ro, ci, va, None, dist))
    slv = _safe(*capi.AMGX_solver_create(rs, "dDDI", cfg_h))
    _safe(capi.AMGX_solver_setup(slv, mtx))
    # scale the system by 2: same structure, new values
    for ro, ci, va in _pieces_of(A, offsets):
        _safe(capi.AMGX_matrix_replace_coefficients(
            mtx, len(ro) - 1, len(ci), 2.0 * va))
    _safe(capi.AMGX_solver_resetup(slv, mtx))
    rhs = _safe(*capi.AMGX_vector_create(rs, "dDDI"))
    sol = _safe(*capi.AMGX_vector_create(rs, "dDDI"))
    _safe(capi.AMGX_vector_bind(rhs, mtx))
    for r in range(N_DEV):
        lo, hi = int(offsets[r]), int(offsets[r + 1])
        _safe(capi.AMGX_vector_upload_distributed(
            rhs, hi - lo, 1, b[lo:hi]))
    _safe(capi.AMGX_solver_solve_with_0_initial_guess(slv, rhs, sol))
    x = _safe(*capi.AMGX_vector_download(sol))
    # solution of (2A) x = b
    r2 = b - 2.0 * np.asarray(amgx.ops.spmv(A, jnp.asarray(x)))
    assert np.linalg.norm(r2) / np.linalg.norm(b) < 1e-7


@pytest.mark.slow
def test_replace_coefficients_pieces_with_diag(system):
    """Pieces uploaded WITH external diag_data: replacement re-folds
    per rank against the stored pre-fold structure."""
    A, b = system
    n = A.num_rows
    n_local = -(-n // N_DEV)
    offsets = np.minimum(np.arange(N_DEV + 1) * n_local, n)
    capi.AMGX_initialize()
    cfg_h = _safe(*capi.AMGX_config_create(CFG))
    rs = _safe(*capi.AMGX_resources_create_simple(cfg_h))
    mtx = _safe(*capi.AMGX_matrix_create(rs, "dDDI"))
    dist = _safe(*capi.AMGX_distribution_create(cfg_h))
    _safe(capi.AMGX_distribution_set_partition_data(
        dist, capi.AMGX_DIST_PARTITION_OFFSETS, offsets))
    # split each piece into off-diagonal CSR + external diagonal
    diag_g = np.asarray(A.diagonal())
    for r, (ro, ci, va) in enumerate(_pieces_of(A, offsets)):
        lo = int(offsets[r])
        nr = len(ro) - 1
        rows_l = np.repeat(np.arange(nr), np.diff(ro))
        offd = ci != (rows_l + lo)
        counts = np.bincount(rows_l[offd], minlength=nr)
        ro2 = np.concatenate([[0], np.cumsum(counts)])
        _safe(capi.AMGX_matrix_upload_distributed(
            mtx, n, nr, int(offd.sum()), 1, 1, ro2, ci[offd], va[offd],
            diag_g[lo:lo + nr], dist))
    slv = _safe(*capi.AMGX_solver_create(rs, "dDDI", cfg_h))
    _safe(capi.AMGX_solver_setup(slv, mtx))
    # replace: scale by 3 (values AND diag)
    for r, (ro, ci, va) in enumerate(_pieces_of(A, offsets)):
        lo = int(offsets[r])
        nr = len(ro) - 1
        rows_l = np.repeat(np.arange(nr), np.diff(ro))
        offd = ci != (rows_l + lo)
        _safe(capi.AMGX_matrix_replace_coefficients(
            mtx, nr, int(offd.sum()), 3.0 * va[offd],
            3.0 * diag_g[lo:lo + nr]))
    _safe(capi.AMGX_solver_resetup(slv, mtx))
    rhs = _safe(*capi.AMGX_vector_create(rs, "dDDI"))
    sol = _safe(*capi.AMGX_vector_create(rs, "dDDI"))
    _safe(capi.AMGX_vector_bind(rhs, mtx))
    for r in range(N_DEV):
        lo, hi = int(offsets[r]), int(offsets[r + 1])
        _safe(capi.AMGX_vector_upload_distributed(
            rhs, hi - lo, 1, b[lo:hi]))
    _safe(capi.AMGX_solver_solve_with_0_initial_guess(slv, rhs, sol))
    x = _safe(*capi.AMGX_vector_download(sol))
    r3 = b - 3.0 * np.asarray(amgx.ops.spmv(A, jnp.asarray(x)))
    assert np.linalg.norm(r3) / np.linalg.norm(b) < 1e-7


def test_replace_coefficients_bad_length_recovers(system):
    """A wrong-length replacement fails with BAD_PARAMETERS and does
    NOT poison the accumulator: a subsequent correct round succeeds."""
    A, b = system
    n = A.num_rows
    n_local = -(-n // N_DEV)
    offsets = np.minimum(np.arange(N_DEV + 1) * n_local, n)
    capi.AMGX_initialize()
    cfg_h = _safe(*capi.AMGX_config_create(CFG))
    rs = _safe(*capi.AMGX_resources_create_simple(cfg_h))
    mtx = _safe(*capi.AMGX_matrix_create(rs, "dDDI"))
    dist = _safe(*capi.AMGX_distribution_create(cfg_h))
    _safe(capi.AMGX_distribution_set_partition_data(
        dist, capi.AMGX_DIST_PARTITION_OFFSETS, offsets))
    for ro, ci, va in _pieces_of(A, offsets):
        _safe(capi.AMGX_matrix_upload_distributed(
            mtx, n, len(ro) - 1, len(ci), 1, 1, ro, ci, va, None, dist))
    rc = capi.AMGX_matrix_replace_coefficients(mtx, 5, 3,
                                               np.ones(3))
    assert rc == capi.RC.BAD_PARAMETERS
    for ro, ci, va in _pieces_of(A, offsets):
        _safe(capi.AMGX_matrix_replace_coefficients(
            mtx, len(ro) - 1, len(ci), 2.0 * va))
    assert capi._get(mtx).new_vals is None  # rebuild completed


CLS_CFG = ("config_version=2, solver(s)=FGMRES, s:max_iters=60,"
           " s:tolerance=1e-8, s:convergence=RELATIVE_INI,"
           " s:gmres_n_restart=30, s:monitor_residual=1,"
           " s:preconditioner(amg)=AMG, amg:algorithm=CLASSICAL,"
           " amg:selector=PMIS, amg:interpolator=D1,"
           " amg:smoother=JACOBI_L1, amg:presweeps=1,"
           " amg:postsweeps=1, amg:max_iters=1,"
           " amg:coarse_solver=DENSE_LU_SOLVER, amg:min_coarse_rows=16,"
           " amg:amg_host_setup=never")


@pytest.mark.slow
def test_classical_pieces_path_parity(system):
    """CLASSICAL from per-rank pieces: the sharded PMIS+D1 setup
    (distributed/setup_classical.py) makes the pieces path work for
    classical AMG — previously it raised (the controller-global
    fallback needs the global matrix)."""
    A, b = system
    n = A.num_rows
    n_local = -(-n // N_DEV)
    offsets = np.minimum(np.arange(N_DEV + 1) * n_local, n)

    capi.AMGX_initialize()
    cfg_h = _safe(*capi.AMGX_config_create(CLS_CFG))
    rs = _safe(*capi.AMGX_resources_create_simple(cfg_h))
    mtx = _safe(*capi.AMGX_matrix_create(rs, "dDDI"))
    dist = _safe(*capi.AMGX_distribution_create(cfg_h))
    _safe(capi.AMGX_distribution_set_partition_data(
        dist, capi.AMGX_DIST_PARTITION_OFFSETS, offsets))
    for ro, ci, va in _pieces_of(A, offsets):
        _safe(capi.AMGX_matrix_upload_distributed(
            mtx, n, len(ro) - 1, len(ci), 1, 1, ro, ci, va, None,
            dist))
    m = capi._get(mtx)
    assert m.part is not None and m.A is None     # no global assembly

    slv = _safe(*capi.AMGX_solver_create(rs, "dDDI", cfg_h))
    _safe(capi.AMGX_solver_setup(slv, mtx))
    rhs = _safe(*capi.AMGX_vector_create(rs, "dDDI"))
    sol = _safe(*capi.AMGX_vector_create(rs, "dDDI"))
    _safe(capi.AMGX_vector_bind(rhs, mtx))
    for r in range(N_DEV):
        lo, hi = int(offsets[r]), int(offsets[r + 1])
        _safe(capi.AMGX_vector_upload_distributed(
            rhs, hi - lo, 1, b[lo:hi]))
    _safe(capi.AMGX_solver_solve_with_0_initial_guess(slv, rhs, sol))
    rc, its = capi.AMGX_solver_get_iterations_number(slv)
    x = _safe(*capi.AMGX_vector_download(sol))

    s = amgx.create_solver(Config.from_string(CLS_CFG))
    s.setup(A)
    ref = s.solve(jnp.asarray(b))
    assert int(its) == int(ref.iterations)
    r = b - np.asarray(amgx.ops.spmv(A, jnp.asarray(x)))
    assert np.linalg.norm(r) / np.linalg.norm(b) < 1e-7
    capi.AMGX_solver_destroy(slv)
    capi.AMGX_matrix_destroy(mtx)


def test_read_system_maps_one_ring(tmp_path, system):
    """amgx_c.h:452/:478 analog: one-ring local numbering + B2L maps
    reconstruct the global matrix exactly."""
    A, b = system
    path = str(tmp_path / "sys.mtx")
    from amgx_tpu.io import write_system
    write_system(path, A, np.asarray(b))
    capi.AMGX_initialize()
    cfg_h = _safe(*capi.AMGX_config_create(CFG))
    rs = _safe(*capi.AMGX_resources_create_simple(cfg_h))
    rc, parts = capi.AMGX_read_system_maps_one_ring(
        rs, "dDDI", path, 1, N_DEV)
    assert rc == capi.RC.OK and len(parts) == N_DEV
    n = A.num_rows
    n_local = -(-n // N_DEV)
    offsets = np.minimum(np.arange(N_DEV + 1) * n_local, n)
    dense = np.zeros((n, n))
    for r, p in enumerate(parts):
        lo = int(offsets[r])
        n_r = p["n"]
        # local one-ring numbering: cols < n_r owned, >= n_r halo
        halo_globals = np.full(max(p["col_indices"].max() + 1 - n_r, 0),
                               -1, np.int64)
        # reconstruct halo globals via the neighbors' send maps
        for nb, rmap in zip(p["neighbors"], p["recv_maps"]):
            q = parts[int(nb)]
            # neighbor's send map FOR ME: find my rank in its lists
            at = list(q["neighbors"]).index(r)
            gsend = q["send_maps"][at] + int(offsets[int(nb)])
            assert len(gsend) == len(rmap)
            halo_globals[rmap - n_r] = gsend
        ro = np.asarray(p["row_ptrs"])
        ci = np.asarray(p["col_indices"])
        va = np.asarray(p["data"])
        for i in range(n_r):
            for e in range(ro[i], ro[i + 1]):
                c = ci[e]
                g = lo + c if c < n_r else halo_globals[c - n_r]
                assert g >= 0
                dense[lo + i, g] += va[e]
    ref = np.asarray(A.to_dense())
    assert np.allclose(dense, ref, atol=1e-12)
    # free analog is a no-op that returns OK
    assert capi.AMGX_free_system_maps_one_ring() == capi.RC.OK


def test_solver_register_print_callback():
    capi.AMGX_initialize()
    seen = []
    rc = capi.AMGX_solver_register_print_callback(
        lambda msg, _n: seen.append(msg))
    assert rc == capi.RC.OK
    from amgx_tpu.output import amgx_printf, register_print_callback
    amgx_printf("one-ring-test")
    register_print_callback(None)
    assert any("one-ring-test" in m for m in seen)
