"""Scaler tests (src/scalers/ analog coverage)."""
import jax.numpy as jnp
import numpy as np
import pytest

import amgx_tpu as amgx
from amgx_tpu.config import Config
from amgx_tpu.scalers import make_scaler

amgx.initialize()


def _badly_scaled(n=80, seed=0):
    """SPD matrix with wildly varying row scales."""
    rng = np.random.default_rng(seed)
    A = amgx.gallery.poisson("5pt", 9, 9).init()
    rows, cols, vals = map(np.asarray, A.coo())
    s = 10.0 ** rng.uniform(-3, 3, A.num_rows)
    svals = vals * s[rows] * s[cols]        # keep SPD: S A S
    return amgx.CsrMatrix.from_coo(rows, cols, jnp.asarray(svals),
                                   A.num_rows, A.num_cols).init()


def test_diagonal_symmetric_unit_diagonal():
    A = _badly_scaled()
    cfg = Config.from_string("scaling=DIAGONAL_SYMMETRIC")
    sc = make_scaler("DIAGONAL_SYMMETRIC", cfg, "default").setup(A)
    As = sc.scale_matrix(A)
    d = np.asarray(As.diagonal())
    np.testing.assert_allclose(np.abs(d), 1.0, rtol=1e-12)


def test_binormalization_equalizes_row_norms():
    A = _badly_scaled()
    cfg = Config.from_string("scaling=BINORMALIZATION")
    sc = make_scaler("BINORMALIZATION", cfg, "default").setup(A)
    As = sc.scale_matrix(A)
    rows, cols, vals = map(np.asarray, As.coo())
    rn = np.sqrt(np.bincount(rows, weights=vals * vals,
                             minlength=A.num_rows))
    # scaled row 2-norms should be nearly equal (cv < 5%)
    assert np.std(rn) / np.mean(rn) < 0.05, (np.std(rn), np.mean(rn))


def test_nbinormalization_row_and_col_norms():
    A = _badly_scaled(seed=3)
    cfg = Config.from_string("scaling=NBINORMALIZATION")
    sc = make_scaler("NBINORMALIZATION", cfg, "default").setup(A)
    As = sc.scale_matrix(A)
    rows, cols, vals = map(np.asarray, As.coo())
    rn = np.sqrt(np.bincount(rows, weights=vals * vals,
                             minlength=A.num_rows))
    cn = np.sqrt(np.bincount(cols, weights=vals * vals,
                             minlength=A.num_cols))
    assert np.std(rn) / np.mean(rn) < 0.05
    assert np.std(cn) / np.mean(cn) < 0.05


@pytest.mark.parametrize("scaling", [
    "BINORMALIZATION",
    # DIAGONAL_SYMMETRIC is the heavy redundant parametrization:
    # the recovery mechanics are identical, BINORMALIZATION stays
    # as the tier-1 representative
    pytest.param("DIAGONAL_SYMMETRIC", marks=pytest.mark.slow)])
def test_scaled_solve_recovers_unscaled_solution(scaling):
    """End-to-end: solver with scaling=... returns x in the ORIGINAL
    coordinates and converges faster (or equal) on the badly scaled
    system."""
    A = _badly_scaled(seed=5)
    n = A.num_rows
    x_true = np.random.default_rng(11).standard_normal(n)
    b = jnp.asarray(np.asarray(amgx.ops.spmv(A, jnp.asarray(x_true))))
    base = ("solver=PBICGSTAB, preconditioner=BLOCK_JACOBI, max_iters=400,"
            " monitor_residual=1, tolerance=1e-12")
    its = {}
    for sc in ["NONE", scaling]:
        cfg = Config.from_string(base + f", scaling={sc}")
        slv = amgx.create_solver(cfg)
        slv.setup(A)
        res = slv.solve(b)
        r = np.asarray(amgx.ops.residual(A, res.x, b))
        assert np.linalg.norm(r) <= 1e-6 * np.linalg.norm(np.asarray(b)), sc
        np.testing.assert_allclose(np.asarray(res.x), x_true, atol=1e-4)
        its[sc] = res.iterations
    assert its[scaling] <= its["NONE"] + 5, its


def test_binormalization_external_diag():
    """The external diagonal must participate in the equilibration."""
    A = _badly_scaled(seed=9)
    rows, cols, vals = map(np.asarray, A.coo())
    offd = rows != cols
    d = np.asarray(A.diagonal())
    Ax = amgx.CsrMatrix.from_coo(rows[offd], cols[offd],
                                 jnp.asarray(vals[offd]),
                                 A.num_rows, A.num_cols,
                                 diag=jnp.asarray(d)).init()
    cfg = Config.from_string("scaling=BINORMALIZATION")
    sc = make_scaler("BINORMALIZATION", cfg, "default").setup(Ax)
    sc_ref = make_scaler("BINORMALIZATION", cfg, "default").setup(A)
    np.testing.assert_allclose(np.asarray(sc.left), np.asarray(sc_ref.left),
                               rtol=1e-10)


def test_scaling_applies_only_at_tree_root():
    """Child solvers must not re-scale the already-scaled matrix: the
    preconditioner sees the parent's scaled A and creates no scaler of
    its own (double-scaling regression)."""
    A = _badly_scaled(seed=7)
    cfg = Config.from_string(
        "solver=PCG, preconditioner=BLOCK_JACOBI, max_iters=50,"
        " monitor_residual=1, tolerance=1e-10, scaling=BINORMALIZATION")
    slv = amgx.create_solver(cfg)
    slv.setup(A)
    assert slv.scaler is not None
    assert slv.preconditioner.scaler is None
    # the preconditioner was set up on the parent's scaled matrix
    assert slv.preconditioner.A is slv.A


def test_unknown_scaling_raises():
    from amgx_tpu.errors import BadConfigurationError, BadParametersError
    A = _badly_scaled()
    with pytest.raises((BadParametersError, BadConfigurationError,
                        ValueError)):
        cfg = Config.from_string("scaling=BOGUS")
        slv = amgx.create_solver(cfg)
        slv.setup(A)
