"""Matrix-free GEO levels (ISSUE 18 tentpole): constant-coefficient
stencil detection (ops/stencil.py), the coeffs-mode fused kernels
(pallas_spmv's SMEM-scalar operand form, via force_pallas_interpret on
the CPU rig), the f64/XLA slab-fallback route, hierarchy routing
(`matrix_free=auto|0|1`, capability surface, level_data forms), the
jaxpr census (NO value-slab operand on matrix-free levels;
`matrix_free=0` jaxpr-identical to the default slab build), the
value-resetup coefficient refresh, GeoRapPlan.coarse_coeffs, and the
serving-cache footprint of a matrix-free hierarchy.
"""
import dataclasses

import numpy as np
import pytest
import jax
import jax.numpy as jnp

import amgx_tpu as amgx
from amgx_tpu import gallery
from amgx_tpu.config import Config
import amgx_tpu.ops.pallas_spmv as ps
import amgx_tpu.ops.stencil as stencil
from amgx_tpu.ops import smooth as fused
from amgx_tpu.ops.spmv import spmv
from amgx_tpu.solvers.relaxation import safe_recip, l1_strengthened_diag

import _census

amgx.initialize()

_GEO_CORE = (
    "solver=FGMRES, max_iters=40, monitor_residual=1, tolerance=1e-8,"
    " gmres_n_restart=20, convergence=RELATIVE_INI, norm=L2,"
    " preconditioner(amg)=AMG, amg:algorithm=AGGREGATION,"
    " amg:selector=GEO, amg:max_iters=1, amg:max_levels=10,"
    " amg:min_coarse_rows=16,")


def _rel(a, b):
    a = np.asarray(a, np.float64)
    b = np.asarray(b, np.float64)
    return float(np.linalg.norm(a - b) / max(np.linalg.norm(b), 1e-300))


def _ref_sweeps(A, dinv, taus, b, x, with_residual=False):
    for t in np.asarray(taus):
        upd = jnp.asarray(t, x.dtype) * (b - spmv(A, x))
        if dinv is not None:
            upd = (upd * dinv).astype(x.dtype)
        x = x + upd
    if with_residual:
        return x, b - spmv(A, x)
    return x


def _geo_agg(nx, ny, nz):
    n = nx * ny * nz
    i = np.arange(n)
    x, t = i % nx, i // nx
    y, z = t % ny, t // ny
    cnx, cny, cnz = (nx + 1) // 2, (ny + 1) // 2, (nz + 1) // 2
    agg = ((z // 2) * cny + (y // 2)) * cnx + (x // 2)
    return agg.astype(np.int32), cnx * cny * cnz


def _amg_of(slv):
    x = slv
    while not hasattr(x, "amg"):
        x = x.preconditioner
    return x.amg


def _scaled(A, f):
    def s(v):
        return None if v is None else v * f
    return dataclasses.replace(
        A, values=A.values * f, dia_vals=s(A.dia_vals),
        ell_vals=s(A.ell_vals), swell_vals=s(A.swell_vals),
        diag=s(A.diag))


# ---------------------------------------------------------------------------
# detection
# ---------------------------------------------------------------------------


class TestDetection:
    def test_detects_constant_poisson(self):
        A = gallery.poisson("7pt", 12, 12, 12, dtype=np.float32).init()
        st = stencil.detect_stencil(A, dinv_mode="l1")
        assert st is not None
        assert st.offsets == tuple(int(d) for d in A.dia_offsets)
        assert st.shape == (12, 12, 12)
        c = np.asarray(st.coeffs)
        ctr = st.offsets.index(0)
        assert c[ctr] == 6.0
        assert all(c[t] == -1.0 for t in range(len(c)) if t != ctr)

    def test_rejects_variable_coefficients(self):
        A = gallery.poisson("7pt", 10, 10, 10, dtype=np.float32).init()
        vals = np.array(A.dia_vals)
        vals[0, 1, 3] *= 1.5         # one in-grid entry off the constant
        Av = dataclasses.replace(A, dia_vals=jnp.asarray(vals))
        assert stencil.detect_stencil(Av) is None

    def test_rejects_no_grid_annotation(self):
        A = gallery.poisson("7pt", 10, 10, 10, dtype=np.float32).init()
        Ag = dataclasses.replace(A, grid_shape=None)
        assert stencil.detect_stencil(Ag) is None

    def test_stencil_matrix_roundtrip(self):
        """stencil_matrix rebuilds the exact value slab the detector
        consumed — the materialization escape every generic consumer
        routes through (level_operator)."""
        A = gallery.poisson("7pt", 10, 10, 10, dtype=np.float32).init()
        st = stencil.detect_stencil(A)
        M = stencil.stencil_matrix(stencil.mf_slim(A), st)
        np.testing.assert_array_equal(np.asarray(M.dia_vals),
                                      np.asarray(A.dia_vals))
        ld = {"A": stencil.mf_slim(A), "stencil": st}
        np.testing.assert_array_equal(
            np.asarray(stencil.level_operator(ld).dia_vals),
            np.asarray(A.dia_vals))


# ---------------------------------------------------------------------------
# kernel parity (coeffs mode vs slab reference, interpret mode)
# ---------------------------------------------------------------------------


class TestKernelParity:
    @pytest.mark.parametrize("n_steps", [1, 3, 9])
    def test_smooth_parity_f32(self, n_steps):
        A = gallery.poisson("7pt", 16, 16, 16, dtype=np.float32).init()
        st = stencil.detect_stencil(A, dinv_mode="l1")
        rng = np.random.default_rng(0)
        n = A.num_rows
        b = jnp.asarray(rng.standard_normal(n), jnp.float32)
        x0 = jnp.asarray(rng.standard_normal(n), jnp.float32)
        dinv = jnp.asarray(safe_recip(np.asarray(
            l1_strengthened_diag(A))), jnp.float32)
        taus = jnp.full((n_steps,), 0.8, jnp.float32)
        ref_x, ref_r = _ref_sweeps(A, dinv, taus, b, x0, True)
        with ps.force_pallas_interpret():
            mx, mr = stencil.stencil_fused_smooth(
                st, taus, b, x0, with_residual=True)
        assert _rel(mx, ref_x) < 1e-6
        assert _rel(mr, ref_r) < 1e-6

    def test_smooth_parity_jacobi_dinv(self):
        A = gallery.poisson("7pt", 12, 12, 12, dtype=np.float32).init()
        st = stencil.detect_stencil(A, dinv_mode="jacobi")
        rng = np.random.default_rng(1)
        n = A.num_rows
        b = jnp.asarray(rng.standard_normal(n), jnp.float32)
        x0 = jnp.asarray(rng.standard_normal(n), jnp.float32)
        dinv = jnp.asarray(safe_recip(np.asarray(A.diagonal())),
                           jnp.float32)
        taus = jnp.full((2,), 0.8, jnp.float32)
        ref_x = _ref_sweeps(A, dinv, taus, b, x0)
        with ps.force_pallas_interpret():
            mx = stencil.stencil_fused_smooth(st, taus, b, x0,
                                              with_residual=False)
        assert _rel(mx, ref_x) < 1e-6

    def test_smooth_parity_bf16(self):
        A = gallery.poisson("7pt", 12, 12, 12, dtype=np.float32).init()
        st = stencil.detect_stencil(A, dinv_mode="l1")
        rng = np.random.default_rng(2)
        n = A.num_rows
        b32 = jnp.asarray(rng.standard_normal(n), jnp.float32)
        x32 = jnp.asarray(rng.standard_normal(n), jnp.float32)
        dinv = jnp.asarray(safe_recip(np.asarray(
            l1_strengthened_diag(A))), jnp.float32)
        taus = jnp.full((2,), 0.8, jnp.float32)
        ref_x = _ref_sweeps(A, dinv, taus, b32, x32)
        with ps.force_pallas_interpret():
            mx = stencil.stencil_fused_smooth(
                st, taus.astype(jnp.bfloat16), b32.astype(jnp.bfloat16),
                x32.astype(jnp.bfloat16), with_residual=False)
        assert mx.dtype == jnp.bfloat16
        assert _rel(mx.astype(jnp.float32), ref_x) < 2e-2

    def test_restrict_and_corr_parity(self):
        nn = 10
        A = gallery.poisson("7pt", nn, nn, nn, dtype=np.float32).init()
        agg, nc = _geo_agg(nn, nn, nn)
        st = stencil.detect_stencil(A, dinv_mode="l1")
        rng = np.random.default_rng(5)
        n = A.num_rows
        b = jnp.asarray(rng.standard_normal(n), jnp.float32)
        x0 = jnp.asarray(rng.standard_normal(n), jnp.float32)
        xc = jnp.asarray(rng.standard_normal(nc), jnp.float32)
        dinv = jnp.asarray(safe_recip(np.asarray(
            l1_strengthened_diag(A))), jnp.float32)
        taus = jnp.full((2,), 0.8, jnp.float32)
        xr, rr = _ref_sweeps(A, dinv, taus, b, x0, True)
        bc_ref = jax.ops.segment_sum(rr, jnp.asarray(agg),
                                     num_segments=nc)
        xr2 = _ref_sweeps(A, dinv, taus, b, x0 + xc[jnp.asarray(agg)])
        with ps.force_pallas_interpret():
            xfer = fused.build_transfer_slabs(A, agg, nc)
            out = stencil.stencil_smooth_restrict(st, taus, b, x0, xfer)
            out2 = stencil.stencil_corr_smooth(st, taus, b, x0, xc,
                                               xfer)
        assert out is not None and out2 is not None
        assert _rel(out[0], xr) < 1e-6
        assert _rel(out[1], bc_ref) < 1e-6
        assert _rel(out2, xr2) < 1e-6

    def test_chained_blocks_under_tight_budget(self):
        """A 9-sweep schedule under a ~300 KB VMEM budget must chain
        multiple kernel launches and still match the reference."""
        nn = 10
        A = gallery.poisson("7pt", nn, nn, nn, dtype=np.float32).init()
        agg, nc = _geo_agg(nn, nn, nn)
        st = stencil.detect_stencil(A, dinv_mode="l1")
        rng = np.random.default_rng(7)
        n = A.num_rows
        b = jnp.asarray(rng.standard_normal(n), jnp.float32)
        x0 = jnp.asarray(rng.standard_normal(n), jnp.float32)
        dinv = jnp.asarray(safe_recip(np.asarray(
            l1_strengthened_diag(A))), jnp.float32)
        taus9 = jnp.full((9,), 0.8, jnp.float32)
        xr, rr = _ref_sweeps(A, dinv, taus9, b, x0, True)
        bc_ref = jax.ops.segment_sum(rr, jnp.asarray(agg),
                                     num_segments=nc)
        old = ps._SMOOTH_VMEM_BUDGET
        try:
            ps._SMOOTH_VMEM_BUDGET = 300 * 1024
            with ps.force_pallas_interpret():
                mx = stencil.stencil_fused_smooth(
                    st, taus9, b, x0, with_residual=False)
                xfer = fused.build_transfer_slabs(A, agg, nc)
                out = stencil.stencil_smooth_restrict(st, taus9, b,
                                                      x0, xfer)
        finally:
            ps._SMOOTH_VMEM_BUDGET = old
        assert _rel(mx, xr) < 1e-6
        if out is not None:      # restrict may decline under the budget
            assert _rel(out[0], xr) < 1e-6
            assert _rel(out[1], bc_ref) < 1e-6

    def test_f64_slab_fallback_parity(self):
        """f64 is outside SMOOTH_DTYPES: the dispatch must compose the
        XLA masked-coefficient form and agree with the slab reference
        to f64 roundoff."""
        A = gallery.poisson("7pt", 12, 12, 12, dtype=np.float64).init()
        st = stencil.detect_stencil(A, dinv_mode="l1")
        rng = np.random.default_rng(3)
        n = A.num_rows
        b = jnp.asarray(rng.standard_normal(n))
        x0 = jnp.asarray(rng.standard_normal(n))
        dinv = safe_recip(l1_strengthened_diag(A))
        taus = jnp.full((3,), 0.8)
        ref_x, ref_r = _ref_sweeps(A, dinv, taus, b, x0, True)
        mx, mr = stencil.stencil_fused_smooth(st, taus, b, x0,
                                              with_residual=True)
        assert _rel(mx, ref_x) < 1e-12
        assert _rel(mr, ref_r) < 1e-12


# ---------------------------------------------------------------------------
# hierarchy routing + end-to-end parity
# ---------------------------------------------------------------------------


_SMOOTHERS = {
    "bj": (" amg:smoother=BLOCK_JACOBI, amg:relaxation_factor=0.75,"
           " amg:presweeps=0, amg:postsweeps=3, amg:cycle=V"),
    "l1": (" amg:smoother=JACOBI_L1, amg:relaxation_factor=0.75,"
           " amg:presweeps=1, amg:postsweeps=2, amg:cycle=V"),
    "cheb": (" amg:smoother=CHEBYSHEV_POLY,"
             " amg:chebyshev_polynomial_order=4,"
             " amg:presweeps=1, amg:postsweeps=1, amg:cycle=V"),
}


class TestRouting:
    @pytest.mark.parametrize("sm", sorted(_SMOOTHERS))
    def test_e2e_solve_parity(self, sm):
        A = gallery.poisson("7pt", 16, 16, 16, dtype=np.float32).init()
        b = jnp.ones(A.num_rows, jnp.float32)
        xs = {}
        for mf in ("0", "1"):
            slv = amgx.create_solver(Config.from_string(
                _GEO_CORE + _SMOOTHERS[sm] + ", amg:matrix_free=" + mf))
            slv.setup(A)
            amg = _amg_of(slv)
            nmf = sum(getattr(lv.smoother, "_mf_stencil", None)
                      is not None for lv in amg.levels)
            if mf == "1":
                assert nmf == len(amg.levels)
                for ld in amg.solve_data()["levels"]:
                    assert "stencil" in ld
                    assert ld["A"].dia_vals is None
            else:
                assert nmf == 0
            res = slv.solve(b)
            assert res.converged
            xs[mf] = res.x
        assert _rel(xs["1"], xs["0"]) < 1e-4

    def test_e2e_solve_parity_f64(self):
        A = gallery.poisson("7pt", 12, 12, 12, dtype=np.float64).init()
        b = jnp.ones(A.num_rows)
        xs = {}
        for mf in ("0", "1"):
            slv = amgx.create_solver(Config.from_string(
                _GEO_CORE + _SMOOTHERS["l1"]
                + ", amg:matrix_free=" + mf))
            slv.setup(A)
            res = slv.solve(b)
            assert res.converged
            xs[mf] = res.x
        assert _rel(xs["1"], xs["0"]) < 1e-10

    def test_auto_stays_off_on_cpu(self):
        """The default `auto` routes matrix-free only on a real TPU
        backend — the CPU tier-1 build must stay bit-identical to the
        slab path, so no stencil may install here."""
        A = gallery.poisson("7pt", 10, 10, 10, dtype=np.float32).init()
        slv = amgx.create_solver(Config.from_string(
            _GEO_CORE + _SMOOTHERS["bj"]))
        slv.setup(A)
        amg = _amg_of(slv)
        assert all(getattr(lv.smoother, "_mf_stencil", None) is None
                   for lv in amg.levels)
        assert all("stencil" not in ld
                   for ld in amg.solve_data()["levels"])

    def test_variable_coefficients_route_to_slabs(self):
        """matrix_free=1 with a variable-coefficient operator must
        keep every level on the slab path and still solve."""
        A = gallery.poisson("7pt", 12, 12, 12, dtype=np.float32).init()
        n = A.num_rows
        d = np.ones(n, np.float32)
        d[n // 3] = 1.5
        k = len(A.dia_offsets)
        dv = np.asarray(A.dia_vals).reshape(k, -1).copy()
        dv[:, :n] *= d
        Av = dataclasses.replace(
            A, values=A.values * jnp.asarray(d)[A.row_ids],
            dia_vals=jnp.asarray(dv).reshape(A.dia_vals.shape),
            diag=None if A.diag is None else A.diag * jnp.asarray(d))
        slv = amgx.create_solver(Config.from_string(
            _GEO_CORE + _SMOOTHERS["l1"] + ", amg:matrix_free=1"))
        slv.setup(Av)
        amg = _amg_of(slv)
        assert getattr(amg.levels[0].smoother, "_mf_stencil",
                       None) is None
        assert all("stencil" not in ld
                   for ld in amg.solve_data()["levels"])
        res = slv.solve(jnp.ones(n, jnp.float32))
        assert res.converged

    def test_capability_surface(self):
        """A matrix-free level's supports_fusion advertises the
        matrix_free capability on top of the level's fusion caps."""
        A = gallery.poisson("7pt", 12, 12, 12, dtype=np.float32).init()
        slv = amgx.create_solver(Config.from_string(
            _GEO_CORE + _SMOOTHERS["l1"] + ", amg:matrix_free=1"))
        slv.setup(A)
        amg = _amg_of(slv)
        lv = amg.levels[0]
        caps = lv.supports_fusion(amg.solve_data()["levels"][0])
        assert "matrix_free" in caps


# ---------------------------------------------------------------------------
# jaxpr census
# ---------------------------------------------------------------------------


def _trace_cycle(extra="", n=12):
    A = gallery.poisson("7pt", n, n, n, dtype=jnp.float32).init()
    b = jnp.ones(A.num_rows, jnp.float32)
    slv = amgx.create_solver(Config.from_string(
        _GEO_CORE + _SMOOTHERS["l1"] + extra))
    slv.setup(A)
    amg = _amg_of(slv)
    d = amg.solve_data()
    jaxpr = jax.make_jaxpr(lambda bb, xx: amg.cycle(d, bb, xx))(
        b, jnp.zeros_like(b))
    return amg, jaxpr


# shared census helper (tests/_census.py)
def _slab_consts(jaxpr, k):
    return _census.slab_consts(jaxpr, k, lanes=ps.LANES)


class TestJaxprCensus:
    def test_no_value_slab_operand_on_matrix_free_levels(self):
        amg0, j0 = _trace_cycle(", amg:matrix_free=0")
        amg1, j1 = _trace_cycle(", amg:matrix_free=1")
        k = len(amg0.levels[0].A.dia_offsets)
        assert _slab_consts(j0, k), "slab build lost its DIA operand?"
        assert not _slab_consts(j1, k), _slab_consts(j1, k)
        # and the whole closed-over constant footprint shrinks
        by = lambda j: sum(int(np.size(c) * c.dtype.itemsize)
                           for c in j.consts if np.ndim(c))
        assert by(j1) < by(j0)

    def test_matrix_free_0_is_jaxpr_identical_to_default(self):
        """The escape hatch: matrix_free=0 must be THE slab build —
        same jaxpr text as the default (auto routes off on CPU)."""
        _, j_def = _trace_cycle()
        _, j_off = _trace_cycle(", amg:matrix_free=0")
        assert str(j_off) == str(j_def)

    def test_interpret_cycle_keeps_fused_kernels(self):
        """Under the Pallas runtime the matrix-free cycle still runs
        the fused kernel set (smoother + transfer epilogues/prologues
        — the coeffs mode replaces the operand, not the fusion), and
        solves to the same answer as the slab kernels."""
        A = gallery.poisson("7pt", 12, 12, 12, dtype=np.float32).init()
        b = jnp.ones(A.num_rows, jnp.float32)
        xs, kernels = {}, {}
        for mf in ("0", "1"):
            with ps.force_pallas_interpret():
                slv = amgx.create_solver(Config.from_string(
                    _GEO_CORE + _SMOOTHERS["l1"]
                    + ", amg:matrix_free=" + mf))
                slv.setup(A)
                amg = _amg_of(slv)
                d = amg.solve_data()
                jaxpr = jax.make_jaxpr(
                    lambda bb, xx: amg.cycle(d, bb, xx))(
                        b, jnp.zeros_like(b))
                res = slv.solve(b)
            assert res.converged
            xs[mf] = res.x
            kernels[mf] = set(
                nm for nm in _census.kernel_names(jaxpr)
                if nm.startswith("_dia_"))
        assert kernels["1"], kernels
        assert kernels["1"] == kernels["0"], kernels
        assert _rel(xs["1"], xs["0"]) < 1e-5


# ---------------------------------------------------------------------------
# value resetup + coarse coefficients
# ---------------------------------------------------------------------------


class TestResetup:
    def test_value_resetup_refreshes_coefficients(self):
        from amgx_tpu.presets import FLAGSHIP
        A = gallery.poisson("7pt", 16, 16, 16).init()
        slv = amgx.create_solver(Config.from_string(
            FLAGSHIP + ", amg:structure_reuse_levels=-1,"
            " amg:matrix_free=1"))
        slv.setup(A)
        amg = _amg_of(slv)
        assert all(lv.smoother._mf_stencil is not None
                   for lv in amg.levels)
        c0 = [np.asarray(lv.smoother._mf_stencil.coeffs)
              for lv in amg.levels]
        slv.resetup(_scaled(A, 2.0))
        assert amg._last_resetup_value_only
        for lv, c in zip(amg.levels, c0):
            np.testing.assert_allclose(
                np.asarray(lv.smoother._mf_stencil.coeffs), 2.0 * c,
                rtol=1e-6)
        # and the spliced hierarchy answers exactly like a fresh setup
        b = jnp.ones(A.num_rows, jnp.float32)
        ref = amgx.create_solver(Config.from_string(
            FLAGSHIP + ", amg:matrix_free=1"))
        ref.setup(_scaled(A, 2.0).init())
        assert _rel(slv.solve(b).x, ref.solve(b).x) < 1e-6

    def test_value_resetup_declines_non_constant_values(self):
        """New values that break the constant-stencil invariant must
        fall back to the generic resetup, which re-detects and drops
        the stencils — never serve stale coefficients."""
        from amgx_tpu.presets import FLAGSHIP
        A = gallery.poisson("7pt", 16, 16, 16).init()
        slv = amgx.create_solver(Config.from_string(
            FLAGSHIP + ", amg:structure_reuse_levels=-1,"
            " amg:matrix_free=1"))
        slv.setup(A)
        amg = _amg_of(slv)
        n = A.num_rows
        d = np.ones(n, np.float32)
        d[n // 2] = 1.5
        k = len(A.dia_offsets)
        dv = np.asarray(A.dia_vals).reshape(k, -1).copy()
        dv[:, :n] *= d
        An = dataclasses.replace(
            A, values=A.values * jnp.asarray(d)[A.row_ids],
            dia_vals=jnp.asarray(dv).reshape(A.dia_vals.shape),
            diag=None if A.diag is None else A.diag * jnp.asarray(d))
        slv.resetup(An)
        assert not amg._last_resetup_value_only
        assert all(getattr(lv.smoother, "_mf_stencil", None) is None
                   for lv in amg.levels)
        b = jnp.ones(n, jnp.float32)
        res = slv.solve(b)
        rr = _rel(np.asarray(spmv(An.init(), res.x)), np.asarray(b))
        assert rr < 1e-4


class TestCoarseCoeffs:
    def test_matches_detected_coarse_stencil(self):
        from amgx_tpu.presets import FLAGSHIP
        A = gallery.poisson("7pt", 16, 16, 16).init()
        slv = amgx.create_solver(Config.from_string(
            FLAGSHIP + ", amg:matrix_free=1"))
        slv.setup(A)
        amg = _amg_of(slv)
        gp = amg.levels[0]._geo_plan_memo[0]
        derived = gp.coarse_coeffs(
            amg.levels[0].smoother._mf_stencil.coeffs)
        assert derived is not None
        np.testing.assert_allclose(
            np.asarray(derived),
            np.asarray(amg.levels[1].smoother._mf_stencil.coeffs),
            rtol=1e-6)

    def test_odd_extent_returns_none(self):
        from amgx_tpu.amg.aggregation.galerkin import GeoRapPlan
        shifts = ((0, 0, 0), (1, 0, 0), (-1, 0, 0))
        offsets = (0, 1, -1)
        plan = GeoRapPlan(offsets, shifts, (5, 4, 4), (0, 1, 2),
                          (3, 2, 2))
        assert plan.coarse_coeffs(jnp.ones(3, jnp.float32)) is None


# ---------------------------------------------------------------------------
# serving-cache footprint (satellite: solve_data_bytes)
# ---------------------------------------------------------------------------


def test_serving_cache_counts_matrix_free_payload_tiny():
    """A matrix-free bucket's byte estimate must be the stencil's true
    O(k) payload, not a phantom slab: the estimate drops by at least
    the fine level's DIA slab size versus the slab twin."""
    from amgx_tpu.serving.cache import solve_data_bytes
    A = gallery.poisson("7pt", 16, 16, 16, dtype=np.float32).init()
    sizes = {}
    for mf in ("0", "1"):
        slv = amgx.create_solver(Config.from_string(
            _GEO_CORE + _SMOOTHERS["l1"] + ", amg:matrix_free=" + mf))
        slv.setup(A)
        sizes[mf] = solve_data_bytes(_amg_of(slv).solve_data())
    slab_bytes = int(np.asarray(A.dia_vals).nbytes)
    assert sizes["1"] <= sizes["0"] - slab_bytes, (sizes, slab_bytes)
    st = stencil.detect_stencil(A)
    assert solve_data_bytes({"stencil": st}) == \
        int(np.asarray(st.coeffs).nbytes)
