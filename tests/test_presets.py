"""All shipped preset configs parse and smoke-solve.

The reference treats its 63 shipped configs (src/configs/) as the product
UX; its factories/config tests (src/tests/config_parsing.cu,
src/tests/factories.cu) assert every shipped string builds a solver tree.
This is the analog: every JSON preset in configs/ must parse, build a
solver, and reduce the residual on a small Poisson problem; every
scoped-string eigen preset in configs/eigen_configs/ must parse and build
an eigensolver.
"""
import glob
import os

import jax.numpy as jnp
import numpy as np
import pytest

import amgx_tpu as amgx
from amgx_tpu import gallery
from amgx_tpu.config import Config

_CONFIG_DIR = os.path.join(os.path.dirname(__file__), "..", "configs")
_PRESETS = sorted(
    os.path.basename(p) for p in glob.glob(os.path.join(_CONFIG_DIR, "*.json")))
_EIGEN_PRESETS = sorted(
    os.path.basename(p)
    for p in glob.glob(os.path.join(_CONFIG_DIR, "eigen_configs", "*")))


def test_all_reference_presets_shipped():
    # the reference ships 62 solver presets + 8 eigen presets; the product
    # promise is that they all work here unchanged
    assert len(_PRESETS) >= 62, _PRESETS
    assert len(_EIGEN_PRESETS) == 8, _EIGEN_PRESETS


@pytest.mark.parametrize("name", _PRESETS)
def test_preset_parses_and_builds(name):
    cfg = Config.from_file(os.path.join(_CONFIG_DIR, name))
    slv = amgx.create_solver(cfg)
    assert slv is not None


@pytest.mark.parametrize("name", _PRESETS)
def test_preset_smoke_solve(name):
    A = gallery.poisson("7pt", 8, 8, 8).init()
    cfg = Config.from_file(os.path.join(_CONFIG_DIR, name))
    # keep the smoke solve cheap and quiet on CPU
    for scope in ("main", "default"):
        try:
            cfg.set("print_solve_stats", 0, scope)
            cfg.set("print_grid_stats", 0, scope)
            cfg.set("obtain_timings", 0, scope)
        except Exception:
            pass
    slv = amgx.create_solver(cfg)
    slv.setup(A)
    b = jnp.ones(A.num_rows)
    res = slv.solve(b)
    x = np.asarray(res.x)
    assert np.all(np.isfinite(x)), f"{name}: non-finite solution"
    r = np.asarray(b) - np.asarray(amgx.ops.spmv(A, res.x))
    rel = np.linalg.norm(r) / np.linalg.norm(np.asarray(b))
    # smoke bar: the preset must make real progress on 8^3 Poisson
    # (most converge to their 1e-6 tolerance; single-sweep smoother-style
    # presets at least cut the residual by 10x)
    assert rel < 1e-1, f"{name}: relative residual {rel} after solve"


@pytest.mark.parametrize("name", _EIGEN_PRESETS)
def test_eigen_preset_parses_and_builds(name):
    cfg = Config.from_file(os.path.join(_CONFIG_DIR, "eigen_configs", name))
    slv = amgx.create_eigensolver(cfg)
    assert slv is not None
