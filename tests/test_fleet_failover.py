"""Fleet fault tolerance (amgx_tpu/serving/health.py + fleet.py
failover paths): replica liveness detection (dead scheduler, wedged
cycle counter, slow pace) driving the per-replica circuit breaker
through the fleet_fault_policy chains; the zero-loss DOWN path (ticket
move + fingerprint rehome + cross-replica journal adoption with
bit-identical resumes under original trace ids); deadline re-anchoring
as remaining budget, including under clock_skew chaos; rolling
restarts (drain_replica/restore_replica) with affinity snap-back by
natural eviction only; HALF_OPEN single-fingerprint probes; the
dead-thread drain fix (BREAKDOWN + ticket.error, never a wedged
drain); and the AMGX_fleet_drain_replica/AMGX_fleet_health capi
surface. No reference analog — AMGX ships no replica failover."""
import time

import numpy as np
import pytest

import amgx_tpu as amgx
from amgx_tpu import gallery
from amgx_tpu.config import Config
from amgx_tpu.errors import BadConfigurationError
from amgx_tpu.presets import BATCHED_CG
from amgx_tpu.resilience import faultinject
from amgx_tpu.resilience.faultinject import ChaosInjected
from amgx_tpu.resilience.policy import parse_fleet_policy
from amgx_tpu.resilience.status import SolveStatus
from amgx_tpu.serving import FleetRouter, SolveService
from amgx_tpu.serving.health import CLOSED, HALF_OPEN, OPEN
from amgx_tpu.telemetry import flightrec as _frec
from amgx_tpu.telemetry import metrics

amgx.initialize()


@pytest.fixture(scope="module")
def poisson16():
    return gallery.poisson("5pt", 16, 16).init()


@pytest.fixture(scope="module")
def poisson14():
    return gallery.poisson("5pt", 14, 14).init()


def _rhs(A, seed=0):
    return np.random.default_rng(seed).standard_normal(A.num_rows)


def _svc_cfg(extra=""):
    return Config.from_string(
        BATCHED_CG + ", serving_bucket_slots=2, serving_chunk_iters=4"
        + (", " + extra if extra else ""))


def _fleet(extra="", n=2):
    return FleetRouter.build(_svc_cfg(extra=extra), n)


def _fast_health(fleet, check_s=0.01, suspect=2):
    """Tighten the heartbeat for tests (the production default of a
    0.25 s window would make wedge detection a multi-second wait)."""
    fleet.health.check_s = check_s
    fleet.health.suspect_checks = suspect
    return fleet


# ---------------------------------------------------------------------------
# policy grammar
# ---------------------------------------------------------------------------


def test_parse_fleet_policy():
    p = parse_fleet_policy("REPLICA_DEAD>failover"
                           "|REPLICA_WEDGED>probe_backoff"
                           "|REPLICA_WEDGED>failover")
    assert p["REPLICA_DEAD"] == ["failover"]
    assert p["REPLICA_WEDGED"] == ["probe_backoff", "failover"]
    with pytest.raises(BadConfigurationError, match="REPLICA_DEAD"):
        parse_fleet_policy("REPLICA_DED>failover")
    with pytest.raises(BadConfigurationError, match="failover"):
        parse_fleet_policy("REPLICA_DEAD>failovr")


# ---------------------------------------------------------------------------
# kill failover: zero loss, bit-identical resume, original traces,
# journal settles on the DEAD replica's records
# ---------------------------------------------------------------------------


def test_kill_failover_zero_loss_bit_identical(poisson16, poisson14,
                                               tmp_path):
    kr = (f"serving_checkpoint_cycles=1, serving_chunk_iters=1")
    reqs = [(poisson16, _rhs(poisson16, 1)),
            (poisson14, _rhs(poisson14, 2)),
            (poisson16, _rhs(poisson16, 3)),
            (poisson14, _rhs(poisson14, 4))]

    ref = FleetRouter.build(
        _svc_cfg(extra=kr + f", serving_journal_dir={tmp_path}/ref"), 2)
    ref_ts = [ref.submit(A, b) for A, b in reqs]
    ref.drain(timeout_s=300)
    xrefs = [np.asarray(t.result.x) for t in ref_ts]

    fleet = FleetRouter.build(
        _svc_cfg(extra=kr + f", serving_journal_dir={tmp_path}/f"), 2)
    ts = [fleet.submit(A, b) for A, b in reqs]
    victim = ts[0].replica
    orig = [(t.replica, t.trace_id) for t in ts]
    for _ in range(3):     # admit + checkpoint work on the victim
        fleet.step()
    seq0 = _frec.last_seq()
    with faultinject.inject("replica_kill", fires=1, target=victim):
        fleet.drain(timeout_s=300)

    # zero loss: every submit terminal and converged
    assert all(t.done and t.result.converged for t in ts)
    # bit-identical to the uninterrupted twin fleet
    for t, xr in zip(ts, xrefs):
        assert np.array_equal(np.asarray(t.result.x), xr)
    # original trace ids survived the move
    assert [t.trace_id for t in ts] == [tr for _r, tr in orig]
    # victim-homed tickets actually changed replicas
    moved = [t for t, (r0, _t) in zip(ts, orig) if r0 == victim]
    assert moved and all(t.replica != victim for t in moved)
    # the victim is DOWN; survivors untouched
    hs = fleet.health_snapshot()
    assert hs[victim]["down"] and hs[victim]["state"] == OPEN
    assert sum(1 for s in hs.values() if s["down"]) == 1
    # moved completions settled the DEAD replica's journal (via
    # journal_ref): nothing left to replay, nothing double-solves
    assert fleet.replicas[victim].journal.pending() == []
    # the postmortem trail names the whole incident
    assert _frec.events(kind="fleet.failover", since_seq=seq0)
    assert _frec.events(kind="fleet.health", since_seq=seq0)


def test_kill_failover_background_then_restore(poisson16):
    # Dead-thread detection is never rate-limited, so this test does not
    # need tight heartbeat windows -- and tight windows would false-trip
    # the wedge detector on a survivor's long admission resetup.
    fleet = _fleet()
    fleet.start()
    try:
        ts = [fleet.submit(poisson16, _rhs(poisson16, s))
              for s in range(3)]
        victim = ts[0].replica
        with faultinject.inject("replica_kill", fires=1,
                                target=victim):
            fleet.drain(timeout_s=300)
        assert all(t.done and t.result.converged for t in ts)
        hs = fleet.health_snapshot()
        assert hs[victim]["down"] and not hs[victim]["thread_alive"]
        # restore: breaker reset, a fresh scheduler thread, traffic OK
        fleet.restore_replica(victim)
        hs = fleet.health_snapshot()
        assert hs[victim]["state"] == CLOSED and not hs[victim]["down"]
        assert hs[victim]["thread_alive"]
        t2 = fleet.submit(poisson16, _rhs(poisson16, 9))
        fleet.drain(timeout_s=300)
        assert t2.done and t2.result.converged
    finally:
        fleet.stop()


def test_no_survivor_breakdown_not_wedged(poisson16):
    """Satellite: a dead scheduler must never wedge fleet drain. With
    no survivor, outstanding tickets complete BREAKDOWN with the
    captured exception on ticket.error."""
    fleet = _fleet(n=1)
    t = fleet.submit(poisson16, _rhs(poisson16, 5))
    t0 = time.monotonic()
    with faultinject.inject("replica_kill", fires=1):
        done = fleet.drain(timeout_s=60)
    assert time.monotonic() - t0 < 30      # returned, didn't spin out
    assert t.done
    assert t.result.status_code == int(SolveStatus.BREAKDOWN)
    assert isinstance(t.error, ChaosInjected)
    assert any(d is t for d in done)


# ---------------------------------------------------------------------------
# wedge + slow detection through the policy chain
# ---------------------------------------------------------------------------


def test_wedge_detected_and_failed_over(poisson16):
    fleet = _fast_health(_fleet())
    t = fleet.submit(poisson16, _rhs(poisson16, 6))
    victim = t.replica
    with faultinject.inject("replica_wedge", fires=None,
                            target=victim):
        fleet.drain(timeout_s=300)
    # default chain: WEDGED>probe_backoff then WEDGED>failover
    assert t.done and t.result.converged and t.replica != victim
    hs = fleet.health_snapshot()
    assert hs[victim]["down"]
    assert hs[victim]["last_event"] == "REPLICA_WEDGED"


def test_slow_pace_opens_breaker(poisson16):
    fleet = _fast_health(_fleet(
        extra="fleet_slow_cycle_s=0.05, fleet_probe_backoff_s=30"))
    t = fleet.submit(poisson16, _rhs(poisson16, 7))
    victim = t.replica
    base = metrics.snapshot().get("fleet.health.slow", 0)
    with faultinject.inject("replica_slow", fires=3, value=0.2,
                            target=victim):
        fleet.drain(timeout_s=300)
    # the replica still finishes its work (OPEN blocks ROUTING, not
    # stepping) but the pace detector fired and opened the breaker
    assert t.done and t.result.converged
    assert metrics.snapshot().get("fleet.health.slow", 0) > base
    hs = fleet.health_snapshot()
    assert hs[victim]["last_event"] == "REPLICA_SLOW"
    assert hs[victim]["state"] in (OPEN, HALF_OPEN)


# ---------------------------------------------------------------------------
# cross-replica journal adoption + deadline re-anchoring
# ---------------------------------------------------------------------------


def test_adopt_journal_replays_with_original_trace(poisson16,
                                                   tmp_path):
    """The replay half of adoption: pending records of a dead
    replica's journal enter the adopter's queue under their ORIGINAL
    trace ids, with deadlines re-anchored as remaining budget."""
    a = SolveService(_svc_cfg(
        extra=f"serving_journal_dir={tmp_path}/a"))
    t0 = a.submit(poisson16, _rhs(poisson16, 8), deadline_s=500.0)
    orig_trace = t0.trace_id
    assert orig_trace
    # service a "dies" without ever stepping: its journal holds one
    # pending record
    b = SolveService(_svc_cfg(
        extra=f"serving_journal_dir={tmp_path}/b"))
    base = metrics.snapshot().get("fleet.health.adopted", 0)
    n = b.adopt_journal(a.journal)
    assert n == 1
    assert metrics.snapshot().get("fleet.health.adopted", 0) == base + 1
    adopted = b._queue[0]
    assert adopted.trace_id == orig_trace
    assert adopted.journal_ref is a.journal
    # remaining budget re-anchored against the adopter's clock
    remaining = adopted.deadline_t - faultinject.service_now()
    assert 0 < remaining <= 500.0 + 1e-6
    b.drain(timeout_s=300)
    assert adopted.done and adopted.result.converged
    # the completion settled the ADOPTED journal, not b's own
    assert a.journal.pending() == []


def test_adopt_deadline_reanchor_under_clock_skew(poisson16,
                                                  tmp_path):
    """Satellite: the re-anchor math must hold when the service clock
    itself is skewed (clock_skew chaos) — remaining budget is a
    DELTA, immune to the absolute shift, matching the PR 11
    same-replica recover() contract."""
    with faultinject.inject("clock_skew", value=600.0, fires=None):
        a = SolveService(_svc_cfg(
            extra=f"serving_journal_dir={tmp_path}/a"))
        a.submit(poisson16, _rhs(poisson16, 9), deadline_s=50.0)
        b = SolveService(_svc_cfg(
            extra=f"serving_journal_dir={tmp_path}/b"))
        assert b.adopt_journal(a.journal) == 1
        adopted = b._queue[0]
        remaining = adopted.deadline_t - faultinject.service_now()
        assert 0 < remaining <= 50.0 + 1e-6
        b.drain(timeout_s=300)
    assert adopted.done and adopted.result.converged


# ---------------------------------------------------------------------------
# rolling restarts: drain/restore + affinity snap-back + warm-up
# ---------------------------------------------------------------------------


def test_drain_replica_hands_off_and_restore_returns_home(poisson16):
    fleet = _fleet(extra="fleet_warmup_s=0")
    t = fleet.submit(poisson16, _rhs(poisson16, 10))
    home = t.replica
    moved = fleet.drain_replica(home)
    assert moved == 1 and t.replica != home     # queued work handed off
    fleet.drain(timeout_s=300)
    assert t.done and t.result.converged
    # draining diverts but does NOT rehome: restore brings it back
    t2 = fleet.submit(poisson16, _rhs(poisson16, 11))
    assert t2.replica != home and t2.route == "spill"
    fleet.restore_replica(home)
    t3 = fleet.submit(poisson16, _rhs(poisson16, 12))
    assert t3.replica == home and t3.route == "warm"
    fleet.drain(timeout_s=300)
    assert t2.done and t3.done


def test_affinity_snap_back_only_by_eviction(poisson16):
    """After a kill + restore, the rehomed fingerprint STAYS with its
    adopter (no thundering-herd snap-back); and during the restore
    warm-up grace a NEW fingerprint's cold placement avoids the
    returnee."""
    fleet = _fleet(extra="fleet_warmup_s=30")
    t = fleet.submit(poisson16, _rhs(poisson16, 13))
    victim = t.replica
    with faultinject.inject("replica_kill", fires=1, target=victim):
        fleet.drain(timeout_s=300)
    adopter = t.replica
    assert adopter != victim
    fleet.restore_replica(victim)
    # rehomed fingerprint stays with the adopter
    t2 = fleet.submit(poisson16, _rhs(poisson16, 14))
    assert t2.replica == adopter and t2.route == "warm"
    # a new fingerprint cold-places AWAY from the warming returnee
    small = gallery.poisson("5pt", 12, 12).init()
    t3 = fleet.submit(small, _rhs(small, 15))
    assert t3.replica != victim and t3.route == "cold"
    fleet.drain(timeout_s=300)
    assert t2.done and t3.done


# ---------------------------------------------------------------------------
# breaker probe admission
# ---------------------------------------------------------------------------


def test_half_open_admits_exactly_one_fingerprint(poisson16):
    fleet = _fleet()
    rid = next(iter(fleet.replicas))
    br = fleet.health.breaker(rid)
    br.state = HALF_OPEN
    br.probe_fp = None
    base = metrics.snapshot().get("fleet.health.probe_trials", 0)
    assert fleet.health.probe_admit(rid, "fpA")       # the one trial
    assert not fleet.health.probe_admit(rid, "fpB")   # diverted
    assert fleet.health.probe_admit(rid, "fpA")       # trial retries OK
    assert metrics.snapshot().get(
        "fleet.health.probe_trials", 0) == base + 1
    # a completion since the probe began closes the breaker
    br.probe_base = fleet.replicas[rid].completed_total - 1
    fleet.health.check()
    assert br.state == CLOSED and br.failures == 0


def test_route_diverts_off_open_breaker(poisson16):
    fleet = _fleet()
    t = fleet.submit(poisson16, _rhs(poisson16, 16))
    home = t.replica
    fleet.drain(timeout_s=300)
    br = fleet.health.breaker(home)
    br.state = OPEN
    br.not_before = time.monotonic() + 60
    t2 = fleet.submit(poisson16, _rhs(poisson16, 17))
    assert t2.replica != home and t2.route == "spill"
    # placement NOT rehomed by a breaker divert (affinity retained)
    assert fleet._placed[
        f"{__import__('amgx_tpu.batch.queue', fromlist=['pattern_fingerprint']).pattern_fingerprint(poisson16)}/float64"] == home
    br.state = CLOSED
    fleet.drain(timeout_s=300)
    assert t2.done and t2.result.converged


# ---------------------------------------------------------------------------
# capi surface
# ---------------------------------------------------------------------------


def test_capi_fleet_health_and_rolling_restart(poisson16):
    from amgx_tpu import capi
    assert capi.AMGX_initialize() == 0
    rc, cfg_h = capi.AMGX_config_create(
        BATCHED_CG + ", serving_bucket_slots=2, fleet_replicas=2,"
        " fleet_warmup_s=0")
    assert rc == 0
    rc, rsrc_h = capi.AMGX_resources_create_simple(cfg_h)
    rc, fleet_h = capi.AMGX_fleet_create(rsrc_h, "dDDI", cfg_h)
    assert rc == 0
    rc, m_h = capi.AMGX_matrix_create(rsrc_h, "dDDI")
    rc, b_h = capi.AMGX_vector_create(rsrc_h, "dDDI")
    ro = np.asarray(poisson16.row_offsets)
    ci = np.asarray(poisson16.col_indices)
    v = np.asarray(poisson16.values)
    assert capi.AMGX_matrix_upload_all(
        m_h, poisson16.num_rows, v.size, 1, 1, ro, ci, v, None) == 0
    b = _rhs(poisson16, 18)
    assert capi.AMGX_vector_upload(b_h, b.size, 1, b) == 0
    rc, health = capi.AMGX_fleet_health(fleet_h)
    assert rc == 0 and set(health) == {"r0", "r1"}
    assert all(s["state"] == CLOSED and not s["down"]
               for s in health.values())
    rc, t1 = capi.AMGX_fleet_submit(fleet_h, m_h, b_h, "acme", None)
    assert rc == 0
    rc, home = capi.AMGX_fleet_ticket_replica(t1)
    rc, n_moved = capi.AMGX_fleet_drain_replica(fleet_h, home)
    assert rc == 0 and n_moved == 1
    rc, health = capi.AMGX_fleet_health(fleet_h)
    assert rc == 0 and health[home]["draining"]
    rc, _n = capi.AMGX_fleet_drain(fleet_h, 300)
    assert rc == 0
    rc, done, st = capi.AMGX_service_ticket_status(t1)
    assert rc == 0 and done == 1 and st == 0
    assert capi.AMGX_fleet_restore_replica(fleet_h, home) == 0
    rc, health = capi.AMGX_fleet_health(fleet_h)
    assert rc == 0 and not health[home]["draining"]
    assert capi.AMGX_service_ticket_destroy(t1) == 0
    assert capi.AMGX_fleet_destroy(fleet_h) == 0


# ---------------------------------------------------------------------------
# telemetry catalog
# ---------------------------------------------------------------------------


def test_fleet_health_metrics_declared():
    snap = metrics.snapshot()
    for name in ("fleet.health.suspect", "fleet.health.wedged",
                 "fleet.health.slow", "fleet.health.dead",
                 "fleet.health.down", "fleet.health.breaker_open",
                 "fleet.health.breaker_half_open",
                 "fleet.health.breaker_closed",
                 "fleet.health.probe_trials",
                 "fleet.health.rehomed", "fleet.health.requeued",
                 "fleet.health.adopted", "fleet.health.drains",
                 "fleet.health.restores", "fleet.health.available"):
        assert name in snap, name
