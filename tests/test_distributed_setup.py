"""Sharded (per-shard) distributed AMG setup tests.

The reference builds every AMG level per-rank (distributed Galerkin RAP
with halo rows, classical_amg_level.cu:297-315, distributed_manager.cu
createOneRingHaloRows); tests there are the MPI example programs. Here
the sharded build (distributed/setup.py) is validated against the
single-device hierarchy on the 8-virtual-device CPU mesh:

- the sharded selector makes bit-identical aggregation decisions, so
  hierarchy depth, level sizes and iteration counts all match the
  single-device (and controller-global distributed) setup exactly;
- no rank materializes a global level: every stacked array of a sharded
  level is O(n/p) per shard, and the replicated tail is bounded by one
  shard's budget.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import amgx_tpu as amgx
from amgx_tpu import gallery
from amgx_tpu.config import Config
from amgx_tpu.errors import BadParametersError
from amgx_tpu.distributed import DistributedSolver, default_mesh
from amgx_tpu.distributed.setup import (DistAMGLevel,
                                        ShardedConsolidationLevel)

N_DEV = 8

BASE = ("config_version=2, solver(s)=FGMRES, s:max_iters=60,"
        " s:tolerance=1e-8, s:convergence=RELATIVE_INI,"
        " s:gmres_n_restart=30, s:monitor_residual=1,"
        " s:preconditioner(amg)=AMG, amg:algorithm=AGGREGATION,"
        " amg:selector=SIZE_2, amg:smoother=JACOBI_L1, amg:presweeps=1,"
        " amg:postsweeps=1, amg:max_iters=1,"
        " amg:coarse_solver=DENSE_LU_SOLVER, amg:min_coarse_rows=16,"
        " amg:max_levels=12")


def _poisson():
    return gallery.poisson("7pt", 12, 12, 12).init()


def _solve_single(A, extra=""):
    s = amgx.create_solver(Config.from_string(BASE + extra))
    s.setup(A)
    return s, s.solve(jnp.ones(A.num_rows))


def _solve_dist(A, mode, extra=""):
    mesh = default_mesh(N_DEV)
    cfg = Config.from_string(
        BASE + extra + f", amg:distributed_setup_mode={mode}")
    d = DistributedSolver(cfg, mesh)
    d.setup(A)
    return d, d.solve(jnp.ones(A.num_rows))


def _n_sharded_levels(d):
    amg = d.solver.preconditioner.amg
    return sum(isinstance(lv, (DistAMGLevel, ShardedConsolidationLevel))
               for lv in amg.levels)


@pytest.mark.slow
class TestShardedSetupParity:
    def test_iteration_and_hierarchy_parity(self):
        A = _poisson()
        s, r1 = _solve_single(A)
        d, r2 = _solve_dist(A, "sharded")
        assert bool(r1.converged) and bool(r2.converged)
        assert int(r1.iterations) == int(r2.iterations)
        amg_s = s.preconditioner.amg
        amg_d = d.solver.preconditioner.amg
        # identical hierarchy: same depth, same coarsest size (the
        # sharded selector's decisions are bit-identical)
        assert len(amg_d.levels) == len(amg_s.levels)
        assert amg_d.coarsest_A.num_rows == amg_s.coarsest_A.num_rows
        assert _n_sharded_levels(d) >= 1
        b = np.ones(A.num_rows)
        tr = np.linalg.norm(b - np.asarray(amgx.ops.spmv(A, r2.x)))
        assert tr / np.linalg.norm(b) < 1e-7

    def test_matches_global_setup_iterations(self):
        A = _poisson()
        _, rg = _solve_dist(A, "global")
        _, rs = _solve_dist(A, "sharded")
        assert int(rg.iterations) == int(rs.iterations)

    def test_jacobi_smoother_and_w_cycle(self):
        A = _poisson()
        extra = ", amg:smoother=BLOCK_JACOBI, amg:relaxation_factor=0.8," \
                " amg:cycle=W"
        _, r1 = _solve_single(A, extra)
        d, r2 = _solve_dist(A, "sharded", extra)
        assert bool(r2.converged)
        assert int(r1.iterations) == int(r2.iterations)
        assert _n_sharded_levels(d) >= 1

    def test_anisotropic_operator(self):
        # anisotropy drives non-trivial matching decisions; parity must
        # survive them
        A = _poisson()
        rows, cols, vals = (np.asarray(x) for x in A.coo())
        n = A.num_rows
        # stretch x-direction couplings (stride-1 neighbors) by 50x
        stretch = np.where(np.abs(rows - cols) == 1, 50.0, 1.0)
        v2 = vals * stretch
        diag_fix = np.zeros(n)
        np.add.at(diag_fix, rows, np.where(rows != cols, v2, 0.0))
        v2 = np.where(rows == cols, -diag_fix[rows] + 1e-3, v2)
        from amgx_tpu.matrix import CsrMatrix
        A2 = CsrMatrix.from_scipy_like(
            np.asarray(A.row_offsets), cols.astype(np.int32),
            jnp.asarray(v2), n, n).init()
        _, r1 = _solve_single(A2)
        d, r2 = _solve_dist(A2, "sharded")
        assert int(r1.iterations) == int(r2.iterations)


class TestShardedSetupMemory:
    def test_per_shard_memory_is_o_n_over_p(self):
        """No stacked array of a sharded level may exceed a constant
        multiple of one shard's share of the global problem."""
        A = _poisson()
        mesh = default_mesh(N_DEV)
        cfg = Config.from_string(
            BASE + ", amg:distributed_setup_mode=sharded")
        d = DistributedSolver(cfg, mesh)
        d.setup(A)
        data = d._sharded_amg[id(d.solver.preconditioner)]
        n_sh = _n_sharded_levels(d) - 0
        nnz_budget = (A.nnz + A.num_rows) / N_DEV
        CAP = 16 * nnz_budget  # generous constant, NOT a function of n
        for k, ld in enumerate(data["levels"][:n_sh]):
            for path, leaf in jax.tree_util.tree_leaves_with_path(ld):
                per_shard = leaf.size / N_DEV
                assert per_shard <= CAP, (
                    f"level {k} leaf {path}: {per_shard} elements/shard "
                    f"exceeds O(n/p) cap {CAP}")
        # the replicated tail is bounded by one shard's budget
        amg = d.solver.preconditioner.amg
        boundary = _n_sharded_levels(d)
        for lv in amg.levels[boundary:]:
            assert lv.A.num_rows <= A.num_rows / N_DEV * 2


class TestShardedSetupFallback:
    def test_geo_selector_falls_back_to_global(self):
        A = _poisson()
        extra = ", amg:selector=GEO"
        d, r = _solve_dist(A, "auto", extra)
        assert _n_sharded_levels(d) == 0      # global path used
        assert bool(r.converged)

    def test_mode_sharded_rejects_unsupported_smoother(self):
        A = _poisson()
        mesh = default_mesh(N_DEV)
        # MULTICOLOR_ILU's triangular factors do not build per-shard
        cfg = Config.from_string(
            BASE + ", amg:smoother=MULTICOLOR_ILU,"
            " amg:distributed_setup_mode=sharded")
        d = DistributedSolver(cfg, mesh)
        with pytest.raises(BadParametersError, match="row-partitionable"):
            d.setup(A)

    def test_mode_sharded_rejects_non_minmax_coloring(self):
        A = _poisson()
        mesh = default_mesh(N_DEV)
        cfg = Config.from_string(
            BASE + ", amg:smoother(sm)=MULTICOLOR_DILU,"
            " sm:matrix_coloring_scheme=MULTI_HASH,"
            " amg:distributed_setup_mode=sharded")
        d = DistributedSolver(cfg, mesh)
        with pytest.raises(BadParametersError, match="coloring scheme"):
            d.setup(A)

    def test_auto_uses_sharded_when_supported(self):
        A = _poisson()
        d, r = _solve_dist(A, "auto")
        assert _n_sharded_levels(d) >= 1
        assert bool(r.converged)


@pytest.mark.slow
class TestShardedMultipass:
    """SIZE_4/SIZE_8/MULTI_PAIRWISE sharded: later matching passes run
    on the coarse weight graph (its own device-built halo maps), the
    composed cids drive one final RAP — iteration counts must match the
    single-device multipass selector exactly."""

    @pytest.mark.parametrize("sel,extra", [
        ("SIZE_4", ""),
        ("SIZE_8", ""),
        ("MULTI_PAIRWISE",
         ", amg:aggregation_passes=2, amg:notay_weights=1"),
    ])
    def test_multipass_parity(self, sel, extra):
        A = _poisson()
        sel_extra = extra + f", amg:selector={sel}"
        base = BASE.replace(", amg:selector=SIZE_2", "")
        s = amgx.create_solver(Config.from_string(base + sel_extra))
        s.setup(A)
        r1 = s.solve(jnp.ones(A.num_rows))
        mesh = default_mesh(N_DEV)
        d = DistributedSolver(Config.from_string(
            base + sel_extra + ", amg:distributed_setup_mode=sharded"),
            mesh)
        d.setup(A)
        r2 = d.solve(np.ones(A.num_rows))
        assert bool(r2.converged)
        assert int(r1.iterations) == int(r2.iterations)
        amg1 = s.preconditioner.amg
        amg2 = d.solver.preconditioner.amg
        assert len(amg1.levels) == len(amg2.levels)
        assert amg1.coarsest_A.num_rows == amg2.coarsest_A.num_rows
        assert _n_sharded_levels(d) >= 1


@pytest.mark.slow
def test_sharded_chebyshev_poly_smoother():
    """CHEBYSHEV_POLY in the sharded setup: the taus come from the
    global (psum'd via stacked max) Gershgorin bound — iteration parity
    with the single-device hierarchy."""
    A = _poisson()
    extra = (", amg:smoother=CHEBYSHEV_POLY,"
             " amg:chebyshev_polynomial_order=2")
    s, r1 = _solve_single(A, extra)
    d, r2 = _solve_dist(A, "sharded", extra)
    assert bool(r2.converged)
    assert int(r1.iterations) == int(r2.iterations)
    assert _n_sharded_levels(d) >= 1


@pytest.mark.slow
class TestShardedStrongSmoothers:
    """MULTICOLOR_DILU / MULTICOLOR_GS built per-shard (VERDICT-r4 #1):
    the sharded JPL coloring hashes SEMANTIC global ids with a halo
    color-state exchange each round (boundary_coloring=SYNC_COLORS,
    src/core.cu:353-354), so colors — and hence the DILU Einv
    recurrence (multicolor_dilu_solver.cu:650-810) — are bit-identical
    to the single-device setup: iteration counts must MATCH."""

    @pytest.mark.parametrize("extra", [
        ", amg:smoother=MULTICOLOR_DILU, amg:relaxation_factor=0.9",
        ", amg:smoother=MULTICOLOR_GS, amg:relaxation_factor=0.9",
        ", amg:smoother=MULTICOLOR_GS, amg:relaxation_factor=0.9,"
        " amg:symmetric_GS=1",
    ])
    def test_sharded_setup_parity(self, extra):
        A = _poisson()
        s, r1 = _solve_single(A, extra)
        d, r2 = _solve_dist(A, "sharded", extra)
        assert bool(r1.converged) and bool(r2.converged)
        assert int(r1.iterations) == int(r2.iterations)
        assert _n_sharded_levels(d) >= 1

    def test_sharded_coloring_matches_single_device(self):
        """The per-shard coloring IS the single-device MIN_MAX coloring
        (semantic-id hashing): per-row colors equal after reassembly."""
        from amgx_tpu.distributed.setup import sharded_coloring
        from amgx_tpu.distributed.partition import partition_matrix
        from amgx_tpu.distributed.dist_matrix import \
            shard_matrix_from_partition
        from amgx_tpu.ops.coloring import color_matrix
        A = _poisson()
        ref = color_matrix(A, Config.from_string("config_version=2"))
        mesh = default_mesh(N_DEV)
        part = partition_matrix(A, N_DEV)
        M = shard_matrix_from_partition(part, mesh.axis_names[0])
        offsets = np.minimum(np.arange(N_DEV + 1) * part.n_local,
                             A.num_rows).astype(np.int32)
        colors_s, nc = sharded_coloring(M, mesh, mesh.axis_names[0],
                                        offsets)
        got = np.asarray(colors_s).reshape(-1)[: A.num_rows]
        assert nc == ref.num_colors
        np.testing.assert_array_equal(got, np.asarray(ref.row_colors))

    def test_dilu_classical_sharded_parity(self):
        A = gallery.poisson("7pt", 16, 16, 16).init()
        extra = (", amg:algorithm=CLASSICAL, amg:selector=PMIS,"
                 " amg:interpolator=D1, amg:smoother=MULTICOLOR_DILU,"
                 " amg:relaxation_factor=0.9, amg:amg_host_setup=never")
        s, r1 = _solve_single(A, extra)
        d, r2 = _solve_dist(A, "sharded", extra)
        assert bool(r2.converged)
        assert int(r1.iterations) == int(r2.iterations)
        assert _n_sharded_levels(d) >= 1


CLS_BASE = ("config_version=2, solver(s)=FGMRES, s:max_iters=60,"
            " s:tolerance=1e-8, s:convergence=RELATIVE_INI,"
            " s:gmres_n_restart=30, s:monitor_residual=1,"
            " s:preconditioner(amg)=AMG, amg:algorithm=CLASSICAL,"
            " amg:selector=PMIS, amg:interpolator=D1,"
            " amg:smoother=JACOBI_L1, amg:presweeps=1,"
            " amg:postsweeps=1, amg:max_iters=1,"
            " amg:coarse_solver=DENSE_LU_SOLVER, amg:min_coarse_rows=16,"
            " amg:max_levels=12, amg:amg_host_setup=never")


@pytest.mark.slow
class TestShardedClassicalSetup:
    """Sharded classical PMIS+D1 build (distributed/setup_classical.py
    — the classical_amg_level.cu:254-341 per-rank analog)."""

    def _solve_single(self, A):
        s = amgx.create_solver(Config.from_string(CLS_BASE))
        s.setup(A)
        return s, s.solve(jnp.ones(A.num_rows))

    def _solve_dist(self, A, mode):
        mesh = default_mesh(N_DEV)
        cfg = Config.from_string(
            CLS_BASE + f", amg:distributed_setup_mode={mode}")
        d = DistributedSolver(cfg, mesh)
        d.setup(A)
        return d, d.solve(jnp.ones(A.num_rows))

    def test_classical_sharded_parity(self):
        A = gallery.poisson("7pt", 16, 16, 16).init()
        s, r1 = self._solve_single(A)
        d, r2 = self._solve_dist(A, "sharded")
        assert bool(r1.converged) and bool(r2.converged)
        assert int(r1.iterations) == int(r2.iterations)
        amg_s = s.preconditioner.amg
        amg_d = d.solver.preconditioner.amg
        assert _n_sharded_levels(d) >= 2
        # L0's CF split is bit-identical (same input values): the first
        # coarse size matches the single-device hierarchy exactly.
        # Deeper levels may differ by ulp-rounded RAP values (the
        # sharded triple sum associates differently than R@A then @P).
        assert amg_d.levels[1].A.n_global >= amg_s.levels[1].A.num_rows
        x1, x2 = np.asarray(r1.x), np.asarray(r2.x)
        assert np.allclose(x1, x2, rtol=1e-6, atol=1e-9)

    @pytest.mark.parametrize("extra", [
        ", amg:interp_max_elements=2",
        ", amg:interp_truncation_factor=0.25",
        ", amg:interp_max_elements=3, amg:interp_truncation_factor=0.1",
    ])
    def test_classical_sharded_truncation_parity(self, extra):
        """interp_max_elements / interp_truncation_factor in the
        sharded D1 path (VERDICT-r4 #6 — the production classical
        presets use interp_max_elements=4): per-row top-k on the slot
        vectors with the single-device tie-break order, so iteration
        counts match the single-device truncated hierarchy."""
        A = gallery.poisson("7pt", 16, 16, 16).init()
        s = amgx.create_solver(Config.from_string(CLS_BASE + extra))
        s.setup(A)
        r1 = s.solve(jnp.ones(A.num_rows))
        mesh = default_mesh(N_DEV)
        d = DistributedSolver(Config.from_string(
            CLS_BASE + extra + ", amg:distributed_setup_mode=sharded"),
            mesh)
        d.setup(A)
        r2 = d.solve(jnp.ones(A.num_rows))
        assert bool(r1.converged) and bool(r2.converged)
        assert _n_sharded_levels(d) >= 2
        assert abs(int(r1.iterations) - int(r2.iterations)) <= 1, (
            int(r1.iterations), int(r2.iterations))

    def test_classical_sharded_explicit_mode_unsupported_raises(self):
        A = gallery.poisson("7pt", 12, 12, 12).init()
        mesh = default_mesh(N_DEV)
        cfg = Config.from_string(
            CLS_BASE.replace("amg:interpolator=D1",
                             "amg:interpolator=D2")
            + ", amg:distributed_setup_mode=sharded")
        d = DistributedSolver(cfg, mesh)
        with pytest.raises(BadParametersError):
            d.setup(A)

    def test_classical_auto_falls_back_global_for_d2(self):
        A = gallery.poisson("7pt", 12, 12, 12).init()
        mesh = default_mesh(N_DEV)
        cfg = Config.from_string(
            CLS_BASE.replace("amg:interpolator=D1",
                             "amg:interpolator=D2")
            + ", amg:distributed_setup_mode=auto")
        d = DistributedSolver(cfg, mesh)
        d.setup(A)
        r = d.solve(jnp.ones(A.num_rows))
        assert bool(r.converged)


class TestShardedValueSymmetryGuard:
    def _asym(self):
        import dataclasses
        A = gallery.poisson("7pt", 12, 12, 12).init()
        va = np.asarray(A.values).copy()
        ro = np.asarray(A.row_offsets)
        ci = np.asarray(A.col_indices)
        # perturb one off-diagonal entry (pattern kept, |values| broken)
        for e in range(ro[5], ro[6]):
            if ci[e] != 5:
                va[e] *= 1.5
                break
        return dataclasses.replace(
            A, values=jnp.asarray(va), dia_vals=None, dia_offsets=None,
            ell_cols=None, ell_vals=None, swell_cols=None,
            swell_vals=None, swell_c0row=None, swell_nchunk=None,
            swell_w128=0, initialized=False).init(ell="never")

    def test_sharded_mode_rejects_value_asymmetric(self):
        A = self._asym()
        mesh = default_mesh(N_DEV)
        cfg = Config.from_string(
            BASE + ", amg:distributed_setup_mode=sharded")
        d = DistributedSolver(cfg, mesh)
        with pytest.raises(BadParametersError, match="value-symmetric"):
            d.setup(A)

    def test_auto_mode_falls_back_global_and_solves(self):
        A = self._asym()
        mesh = default_mesh(N_DEV)
        cfg = Config.from_string(
            BASE + ", amg:distributed_setup_mode=auto")
        d = DistributedSolver(cfg, mesh)
        d.setup(A)
        r = d.solve(jnp.ones(A.num_rows))
        assert bool(r.converged)


class TestWidenedOuterPreconditioners:
    """The distributed preconditioner envelope is data-driven (any
    solver whose solve-data partitions row-wise is admitted —
    include/solvers/solver.h:271 composability), replacing the round-3
    whitelist. Each admitted solver: mesh-vs-single-device iteration
    parity."""

    @pytest.mark.parametrize("name", ["MULTICOLOR_DILU",
                                      "MULTICOLOR_GS",
                                      "CHEBYSHEV_POLY"])
    def test_outer_precond_parity(self, name):
        A = gallery.poisson("7pt", 12, 12, 12).init()
        cfg = Config.from_string(
            "config_version=2, solver(s)=FGMRES, s:max_iters=80,"
            " s:tolerance=1e-8, s:convergence=RELATIVE_INI,"
            " s:gmres_n_restart=40, s:monitor_residual=1,"
            f" s:preconditioner(p)={name}, p:max_iters=2")
        s = amgx.create_solver(cfg)
        s.setup(A)
        r1 = s.solve(jnp.ones(A.num_rows))
        d = DistributedSolver(cfg, default_mesh(N_DEV))
        d.setup(A)
        r2 = d.solve(np.ones(A.num_rows))
        assert bool(r1.converged) and bool(r2.converged)
        assert int(r1.iterations) == int(r2.iterations)

    def test_non_rowwise_precond_rejected(self):
        A = gallery.poisson("7pt", 8, 8, 8).init()
        cfg = Config.from_string(
            "config_version=2, solver(s)=FGMRES, s:max_iters=10,"
            " s:monitor_residual=1, s:preconditioner(p)=GS")
        d = DistributedSolver(cfg, default_mesh(N_DEV))
        with pytest.raises(BadParametersError,
                           match="not distribution-aware"):
            d.setup(A)
