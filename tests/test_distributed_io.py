"""Distributed IO tests (distributed_io.cu analog): partition vectors,
renumbering, consolidation-on-read, capi distributed read/write."""
import numpy as np
import pytest

import amgx_tpu as amgx
from amgx_tpu import capi, gallery
from amgx_tpu.errors import RC
from amgx_tpu.io import write_system
from amgx_tpu.io.distributed import (consolidate_partitions,
                                     read_partition_vector,
                                     read_system_distributed,
                                     renumber_by_partition,
                                     write_system_distributed)

amgx.initialize()


@pytest.fixture()
def system(tmp_path):
    A = gallery.poisson("5pt", 8, 8)
    path = str(tmp_path / "sys.mtx")
    b = np.arange(64, dtype=float)
    write_system(path, A, b=b)
    return A, b, path


def test_partition_vector_roundtrip(tmp_path):
    pv = np.array([0, 0, 1, 1, 2, 2, 3, 3], np.int32)
    p = str(tmp_path / "pv.bin")
    with open(p, "wb") as f:
        f.write(pv.tobytes())
    np.testing.assert_array_equal(read_partition_vector(p, 8), pv)
    # text format
    p2 = str(tmp_path / "pv.txt")
    with open(p2, "w") as f:
        f.write(" ".join(map(str, pv)))
    np.testing.assert_array_equal(read_partition_vector(p2, 8), pv)


def test_consolidate_partitions():
    pv = np.array([0, 1, 2, 3, 4, 5, 6, 7])
    c = consolidate_partitions(pv, 2)
    assert set(np.unique(c)) == {0, 1}
    # locality: contiguous partition groups
    assert np.all(np.diff(c) >= 0)
    # no-op when targets >= partitions
    np.testing.assert_array_equal(consolidate_partitions(pv, 8), pv)


def test_renumber_preserves_system(system):
    """The permuted system solves to the permuted solution."""
    A, b, _ = system
    A = A.init()
    rng = np.random.default_rng(3)
    pv = rng.integers(0, 4, size=64)
    A2, b2, _, offs, perm = renumber_by_partition(A, pv, b=b)
    # ranks contiguous after renumbering
    pv_new = pv[perm]
    assert np.all(np.diff(pv_new) >= 0)
    assert offs[-1] == 64 and len(offs) == 5
    # spectrum-preserving permutation: dense compare
    Ad = np.asarray(A.to_dense())
    A2d = np.asarray(A2.to_dense())
    np.testing.assert_allclose(A2d, Ad[np.ix_(perm, perm)], atol=0)
    np.testing.assert_allclose(b2, np.asarray(b)[perm])


def test_read_system_distributed_solve(system, tmp_path):
    """Renumbered system gives the same solution (un-permuted) as the
    original — the correctness contract of distributed read."""
    Aorig, b, path = system
    A2, b2, _, offs, perm = read_system_distributed(
        path, num_ranks=4)
    from amgx_tpu.config import Config
    from amgx_tpu.solvers import make_solver
    cfg = Config.from_string(
        "solver=CG, max_iters=400, tolerance=1e-10, monitor_residual=1, "
        "convergence=RELATIVE_INI_CORE")
    s1 = make_solver("CG", cfg, "default").setup(Aorig.init())
    x_ref = np.asarray(s1.solve(b).x)
    s2 = make_solver("CG", cfg, "default").setup(A2)
    x_perm = np.asarray(s2.solve(b2).x)
    x_unperm = np.empty_like(x_perm)
    x_unperm[perm] = x_perm
    np.testing.assert_allclose(x_unperm, x_ref, atol=1e-7)


def test_write_system_distributed_sidecar(system, tmp_path):
    A, b, _ = system
    out = str(tmp_path / "out.mtx")
    pv = np.arange(64) // 16
    write_system_distributed(out, A, b=b, partition_vector=pv)
    back = read_partition_vector(out + ".partition", 64)
    np.testing.assert_array_equal(back, pv)


def test_partition_sizes(system):
    _, _, path = system
    A2, b2, _, offs, perm = read_system_distributed(
        path, partition_sizes=[10, 54])
    np.testing.assert_array_equal(offs, [0, 10, 64])
    with pytest.raises(Exception):
        read_system_distributed(path, partition_sizes=[10, 10])


def test_trailing_empty_ranks(system):
    """part_offsets covers every rank even when trailing ranks own no
    rows (offsets contract: len == num_ranks + 1)."""
    _, _, path = system
    pv = np.zeros(64, np.int64)
    pv[32:] = 1
    A2, _, _, offs, _ = read_system_distributed(
        path, partition_vector=pv, num_ranks=4)
    np.testing.assert_array_equal(offs, [0, 32, 64, 64, 64])


def test_malformed_partition_vector(tmp_path):
    p = str(tmp_path / "bad.txt")
    with open(p, "w") as f:
        f.write("0 1 1-2 3")
    from amgx_tpu.errors import IOError_
    with pytest.raises(IOError_):
        read_partition_vector(p)
    p2 = str(tmp_path / "bad.bin")
    with open(p2, "wb") as f:
        f.write(b"\xff\xfe\xfd")   # 3 bytes: not a whole int32
    with pytest.raises(IOError_):
        read_partition_vector(p2)


def test_renumber_preserves_external_diag():
    """%%AMGX-diagonal matrices keep their diagonal through renumbering."""
    from amgx_tpu.matrix import CsrMatrix
    A = gallery.poisson("5pt", 4, 4).init()
    rows, cols, vals = [np.asarray(v) for v in A.coo()]
    off = rows != cols
    Ad = CsrMatrix.from_coo(rows[off], cols[off], vals[off], 16, 16,
                            diag=np.full(16, 4.0)).init()
    pv = np.array([1, 0] * 8)
    A2, _, _, _, perm = renumber_by_partition(Ad, pv)
    assert A2.has_external_diag
    np.testing.assert_allclose(
        np.asarray(A2.to_dense()),
        np.asarray(Ad.to_dense())[np.ix_(perm, perm)])


def test_negative_rank_rejected(system):
    from amgx_tpu.errors import IOError_
    _, _, path = system
    pv = np.zeros(64, np.int64)
    pv[5] = -1
    with pytest.raises(IOError_):
        read_system_distributed(path, partition_vector=pv, num_ranks=2)


def test_renumber_block_vectors():
    """b/x are scalar-length (n*block_dimy); permutation must move whole
    blocks."""
    A = gallery.poisson("5pt", 4, 4).init()
    from amgx_tpu.matrix import CsrMatrix
    rows, cols, vals = [np.asarray(v) for v in A.coo()]
    bvals = np.repeat(vals, 4).reshape(-1, 2, 2)
    Ab = CsrMatrix.from_coo(rows, cols, bvals, 16, 16,
                            block_dims=(2, 2)).init()
    b = np.arange(32, dtype=float)
    pv = np.array([1, 0] * 8)
    _, b2, _, _, perm = renumber_by_partition(Ab, pv, b=b)
    expect = b.reshape(16, 2)[perm].ravel()
    np.testing.assert_array_equal(b2, expect)


def test_capi_write_after_read_sidecar_alignment(system, tmp_path):
    """After a distributed read renumbers rows, a distributed write with
    the original-order partition vector must permute the sidecar to the
    written row order (round-trip stays consistent)."""
    _, _, path = system
    rng = np.random.default_rng(7)
    pv = rng.integers(0, 4, size=64)
    assert capi.AMGX_initialize() == RC.OK
    rc, rsrc = capi.AMGX_resources_create_simple(None)
    rc, Ah = capi.AMGX_matrix_create(rsrc, "dDDI")
    assert capi.AMGX_read_system_distributed(
        Ah, None, None, path, partition_vector=pv) == RC.OK
    out = str(tmp_path / "o.mtx")
    assert capi.AMGX_write_system_distributed(
        Ah, None, None, out, partition_vector=pv) == RC.OK
    back = read_partition_vector(out + ".partition", 64)
    # written rows are partition-contiguous, so the sidecar must be too
    assert np.all(np.diff(back) >= 0)
    np.testing.assert_array_equal(np.bincount(back), np.bincount(pv))
    capi.AMGX_finalize()


def test_capi_distributed_read(system, tmp_path):
    A, b, path = system
    pv = np.arange(64) // 16
    pvp = str(tmp_path / "pv.bin")
    with open(pvp, "wb") as f:
        f.write(pv.astype(np.int32).tobytes())
    assert capi.AMGX_initialize() == RC.OK
    rc, rsrc = capi.AMGX_resources_create_simple(None)
    rc, Ah = capi.AMGX_matrix_create(rsrc, "dDDI")
    rc, bh = capi.AMGX_vector_create(rsrc, "dDDI")
    assert capi.AMGX_read_system_distributed(
        Ah, bh, None, path, partition_vector=pvp,
        num_partitions=4) == RC.OK
    rc, n, _, _ = capi.AMGX_matrix_get_size(Ah)
    assert n == 64
    out = str(tmp_path / "o.mtx")
    assert capi.AMGX_write_system_distributed(
        Ah, bh, None, out, partition_vector=pv) == RC.OK
    import os
    assert os.path.exists(out) and os.path.exists(out + ".partition")
    capi.AMGX_finalize()
