"""Mode system consumption: mixed-precision solves (dDFI / TPU bf16
extension dDBI) and the INTERIOR/BOUNDARY view split (VERDICT round-1
items 1 and 2)."""
import numpy as np
import pytest
import jax.numpy as jnp

import amgx_tpu as amgx
from amgx_tpu import capi, gallery
from amgx_tpu.errors import RC
from amgx_tpu.modes import parse_mode

amgx.initialize()


def test_parse_extended_modes():
    m = parse_mode("dDFI")
    assert m.vec_dtype == np.float64 and m.mat_dtype == np.float32
    mb = parse_mode("dDBI")
    assert mb.mat_dtype == np.dtype(jnp.bfloat16)
    mh = parse_mode("dFHI")
    assert mh.mat_dtype == np.float16
    with pytest.raises(Exception):
        parse_mode("dXDI")


@pytest.mark.parametrize("mode,mat_dt,tol", [
    ("dDFI", np.float32, 1e-8),
    ("dDBI", np.dtype(jnp.bfloat16), 1e-8),
])
def test_mixed_precision_solve(mode, mat_dt, tol):
    """dDFI semantics: matrix stored in low precision, vectors and
    iteration in float64 — the solve still reaches the f64 tolerance
    because the Krylov iteration corrects the low-precision operator
    application (the reference's mixed-precision build; for bf16 this
    is the TPU-native extension)."""
    assert capi.AMGX_initialize() == RC.OK
    rc, cfg = capi.AMGX_config_create(
        "config_version=2, solver=PCG, preconditioner=BLOCK_JACOBI, "
        "max_iters=400, tolerance=1e-8, monitor_residual=1, "
        "convergence=RELATIVE_INI_CORE")
    rc, rsc = capi.AMGX_resources_create_simple(cfg)
    rc, mh = capi.AMGX_matrix_create(rsc, mode)
    rc, bh = capi.AMGX_vector_create(rsc, mode)
    rc, xh = capi.AMGX_vector_create(rsc, mode)
    A = gallery.poisson("7pt", 10, 10, 10).init()
    n = A.num_rows
    assert capi.AMGX_matrix_upload_all(
        mh, n, A.nnz, 1, 1, np.asarray(A.row_offsets),
        np.asarray(A.col_indices), np.asarray(A.values), None) == RC.OK
    m = capi._get(mh, capi._CMatrix)
    assert m.A.values.dtype == mat_dt          # low-precision storage
    b = np.ones(n)
    assert capi.AMGX_vector_upload(bh, n, 1, b) == RC.OK
    assert capi.AMGX_vector_upload(xh, n, 1, np.zeros(n)) == RC.OK
    v = capi._get(bh, capi._CVector)
    assert v.v.dtype == np.float64             # f64 iteration vectors
    rc, sh = capi.AMGX_solver_create(rsc, mode, cfg)
    assert capi.AMGX_solver_setup(sh, mh) == RC.OK
    assert capi.AMGX_solver_solve(sh, bh, xh) == RC.OK
    rc, x = capi.AMGX_vector_download(xh)
    r = b - np.asarray(amgx.ops.spmv(A, jnp.asarray(np.asarray(x))))
    assert np.linalg.norm(r) / np.linalg.norm(b) < tol
    capi.AMGX_finalize()


def test_unsorted_columns_edge_weights():
    """CSR with unsorted columns within rows must aggregate identically
    to its sorted-column equivalent (regression: positional transpose
    alignment requires canonicalization first)."""
    from amgx_tpu.amg.aggregation.selectors import _edge_weights
    from amgx_tpu.matrix import CsrMatrix
    A = gallery.poisson("5pt", 6, 6).init()
    rows, cols, vals = [np.asarray(v) for v in A.coo()]
    # scramble column order inside each row
    rng = np.random.default_rng(3)
    ro = np.asarray(A.row_offsets)
    perm = np.arange(len(cols))
    for i in range(36):
        seg = perm[ro[i]:ro[i + 1]]
        rng.shuffle(seg)
    B = CsrMatrix(
        row_offsets=A.row_offsets,
        col_indices=jnp.asarray(cols[perm]),
        values=jnp.asarray(vals[perm]),
        diag=None, row_ids=None, diag_idx=None, ell_cols=None,
        ell_vals=None, dia_offsets=None, dia_vals=None,
        num_rows=36, num_cols=36).init(ell="never")
    ra, ca, wa = [np.asarray(v) for v in _edge_weights(A)]
    rb, cb, wb = [np.asarray(v) for v in _edge_weights(B)]
    oa = np.lexsort((ca, ra))
    ob = np.lexsort((cb, rb))
    np.testing.assert_array_equal(ra[oa], rb[ob])
    np.testing.assert_array_equal(ca[oa], cb[ob])
    np.testing.assert_allclose(wa[oa], wb[ob], rtol=1e-14)


def test_split_uninitialized_matrix():
    A = gallery.poisson("5pt", 4, 4)          # NOT initialized
    Ai, Ab = A.interior_exterior_split(8)
    d = np.asarray(Ai.diagonal())             # must not crash
    # diagonals of rows < 8 are interior entries; rows >= 8 have their
    # diagonal in the boundary part
    assert d.shape == (16,)
    np.testing.assert_allclose(d[:8], 4.0)
    np.testing.assert_allclose(d[8:], 0.0)


def test_interior_exterior_split():
    A = gallery.poisson("5pt", 8, 8).init()
    n = A.num_rows
    k = 40
    Ai, Ab = A.interior_exterior_split(k)
    x = jnp.asarray(np.random.default_rng(0).standard_normal(n))
    y = np.asarray(amgx.ops.spmv(A, x))
    yi = np.asarray(amgx.ops.spmv(Ai, x))
    yb = np.asarray(amgx.ops.spmv(Ab, x))
    np.testing.assert_allclose(yi + yb, y, rtol=1e-12)
    # boundary part only touches columns >= k
    np.testing.assert_allclose(
        yb, y - np.asarray(amgx.ops.spmv(A, x.at[k:].set(0.0))),
        rtol=1e-10, atol=1e-12)
