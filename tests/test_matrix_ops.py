"""Container + kernel tests (analogs of src/tests/csr_multiply.cu,
matrix_vector_multiply_tests.cu, norm_tests.cu)."""
import jax.numpy as jnp
import numpy as np
import pytest

from amgx_tpu import gallery, ops
from amgx_tpu.matrix import CsrMatrix


def dense_of(A):
    return np.asarray(A.to_dense())


class TestMatrix:
    def test_poisson_5pt_structure(self):
        A = gallery.poisson("5pt", 4, 4)
        assert A.shape == (16, 16)
        d = dense_of(A)
        assert np.allclose(d, d.T)
        assert np.all(np.diag(d) == 4.0)
        # row sums are >= 0 (boundary rows positive)
        assert np.all(d.sum(1) >= 0)

    def test_poisson_7pt_rowsum(self):
        A = gallery.poisson("7pt", 3, 4, 5)
        d = dense_of(A)
        assert d.shape == (60, 60)
        assert np.all(np.diag(d) == 6.0)
        interior = d.sum(1) == 0
        assert interior.sum() == (3 - 2) * (4 - 2) * (5 - 2)

    def test_from_coo_coalesce(self):
        rows = [0, 0, 1, 0]
        cols = [1, 1, 0, 0]
        vals = [2.0, 3.0, 4.0, 1.0]
        A = CsrMatrix.from_coo(rows, cols, vals, 2, 2)
        d = dense_of(A)
        assert np.allclose(d, [[1.0, 5.0], [4.0, 0.0]])

    def test_diagonal_and_init(self):
        A = gallery.poisson("5pt", 5, 5).init()
        assert np.allclose(np.asarray(A.diagonal()), 4.0)
        # stencil matrix -> banded DIA layout chosen (TPU fast path)
        assert A.dia_offsets is not None
        assert len(A.dia_offsets) == 5

    def test_external_diag(self):
        # A with diagonal stored outside (DIAG property)
        rows = [0, 1]
        cols = [1, 0]
        vals = [-1.0, -2.0]
        diag = jnp.asarray([3.0, 4.0])
        A = CsrMatrix.from_coo(rows, cols, vals, 2, 2, diag=diag).init()
        d = dense_of(A)
        assert np.allclose(d, [[3.0, -1.0], [-2.0, 4.0]])
        x = jnp.asarray([1.0, 2.0])
        assert np.allclose(np.asarray(ops.spmv(A, x)), d @ np.asarray(x))

    def test_replace_coefficients(self):
        A = gallery.poisson("5pt", 4, 4).init()
        A2 = A.with_values(A.values * 2.0)
        assert np.allclose(dense_of(A2), 2 * dense_of(A))

    def test_host_mirror_hit_and_eviction(self):
        # the mirror must actually store (jax ArrayImpl is unhashable,
        # so a WeakKeyDictionary would silently drop every entry) and
        # must evict when the device array dies
        import gc
        from amgx_tpu.matrix import (_HOST_MIRROR, _register_host_mirror,
                                     host_mirror_asarray)
        src = np.arange(8, dtype=np.float64)
        dev = jnp.asarray(src)
        before = len(_HOST_MIRROR)
        _register_host_mirror(dev, src)
        assert len(_HOST_MIRROR) == before + 1
        assert host_mirror_asarray(dev) is src     # no device pull
        del dev
        gc.collect()
        assert len(_HOST_MIRROR) == before         # finalizer evicted


class TestSpmv:
    @pytest.mark.parametrize("stencil,dims", [("5pt", (7, 5, 1)),
                                              ("9pt", (6, 6, 1)),
                                              ("27pt", (4, 3, 5))])
    def test_vs_dense(self, stencil, dims):
        A = gallery.poisson(stencil, *dims).init()
        n = A.num_rows
        x = jnp.asarray(np.random.default_rng(0).standard_normal(n))
        y = ops.spmv(A, x)
        assert np.allclose(np.asarray(y), dense_of(A) @ np.asarray(x))

    def test_segsum_vs_ell_vs_dia(self):
        A = gallery.poisson("7pt", 5, 5, 5)
        a_dia = A.init()                    # auto -> DIA for stencils
        a_seg = A.init(ell="never")
        assert a_dia.dia_offsets is not None and len(a_dia.dia_offsets) == 7
        x = jnp.asarray(np.random.default_rng(1).standard_normal(A.num_rows))
        np.testing.assert_allclose(np.asarray(ops.spmv(a_dia, x)),
                                   np.asarray(ops.spmv(a_seg, x)), rtol=1e-13)
        # ell="always" forces the ELL path (DIA only under "auto")
        a_ell = A.init(ell="always")
        assert a_ell.ell_cols is not None and a_ell.dia_offsets is None
        np.testing.assert_allclose(np.asarray(ops.spmv(a_ell, x)),
                                   np.asarray(ops.spmv(a_seg, x)), rtol=1e-13)

    def test_random_irregular(self):
        A = gallery.random_matrix(120, max_nnz_per_row=9, seed=3).init()
        x = jnp.asarray(np.random.default_rng(2).standard_normal(120))
        np.testing.assert_allclose(np.asarray(ops.spmv(A, x)),
                                   dense_of(A) @ np.asarray(x), rtol=1e-12)

    def test_block_spmv(self):
        A = gallery.random_matrix(40, max_nnz_per_row=5, seed=4,
                                  block_dims=(3, 3)).init()
        x = jnp.asarray(np.random.default_rng(5).standard_normal(40 * 3))
        np.testing.assert_allclose(np.asarray(ops.spmv(A, x)),
                                   dense_of(A) @ np.asarray(x), rtol=1e-12)

    def test_residual(self):
        A = gallery.poisson("5pt", 6, 6).init()
        n = A.num_rows
        rng = np.random.default_rng(7)
        x = jnp.asarray(rng.standard_normal(n))
        b = jnp.asarray(rng.standard_normal(n))
        r = ops.residual(A, x, b)
        assert np.allclose(np.asarray(r),
                           np.asarray(b) - dense_of(A) @ np.asarray(x))


class TestBlas:
    def test_norms(self):
        x = jnp.asarray([3.0, -4.0, 0.0])
        assert float(ops.nrm1(x)) == 7.0
        assert float(ops.nrm2(x)) == 5.0
        assert float(ops.nrmmax(x)) == 4.0
        assert float(ops.norm(x, "L2")) == 5.0

    def test_block_norm(self):
        x = jnp.asarray([3.0, 0.0, 0.0, 4.0])  # 2 blocks of size 2
        bn = ops.norm(x, "L2", block_size=2, use_scalar_norm=False)
        assert np.allclose(np.asarray(bn), [3.0, 4.0])

    def test_dot(self):
        x = jnp.asarray([1.0, 2.0])
        y = jnp.asarray([3.0, 4.0])
        assert float(ops.dot(x, y)) == 11.0


class TestTranspose:
    def test_transpose(self):
        A = gallery.random_matrix(50, max_nnz_per_row=6, seed=9)
        At = ops.transpose(A)
        assert np.allclose(dense_of(At), dense_of(A).T)

    def test_block_transpose(self):
        A = gallery.random_matrix(12, max_nnz_per_row=4, seed=10,
                                  block_dims=(2, 2))
        At = ops.transpose(A)
        assert np.allclose(dense_of(At), dense_of(A).T)


class TestSpgemm:
    def test_vs_dense(self):
        A = gallery.random_matrix(40, max_nnz_per_row=5, seed=11)
        B = gallery.random_matrix(40, max_nnz_per_row=4, seed=12)
        C = ops.csr_multiply(A, B)
        np.testing.assert_allclose(dense_of(C), dense_of(A) @ dense_of(B),
                                   rtol=1e-12, atol=1e-12)

    def test_poisson_squared(self):
        A = gallery.poisson("5pt", 8, 8)
        C = ops.csr_multiply(A, A)
        np.testing.assert_allclose(dense_of(C), dense_of(A) @ dense_of(A),
                                   rtol=1e-12)

    def test_galerkin_rap(self):
        A = gallery.poisson("5pt", 6, 6)
        # a simple aggregation P: 2 fine -> 1 coarse
        n = A.num_rows
        nc = n // 2
        rows = np.arange(n)
        cols = rows // 2
        P = CsrMatrix.from_coo(rows, cols, np.ones(n), n, nc)
        R = ops.transpose(P)
        Ac = ops.galerkin_rap(R, A, P)
        Pd = dense_of(P)
        np.testing.assert_allclose(dense_of(Ac), Pd.T @ dense_of(A) @ Pd,
                                   rtol=1e-12, atol=1e-12)
