"""Accepted-parameter effectiveness (VERDICT round-1 item 6): registered
parameters must change observable behavior — accept-and-ignore is a
correctness trap. Mirrors the reference's config-driven tests
(src/tests/config_parsing.cu role)."""
import numpy as np
import pytest
import jax.numpy as jnp

import amgx_tpu as amgx
from amgx_tpu import gallery
from amgx_tpu.config import Config
from amgx_tpu.solvers import make_solver

amgx.initialize()


def _solver(extra=""):
    cfg = Config.from_string(
        "config_version=2, solver=AMG, algorithm=AGGREGATION, "
        "selector=SIZE_2, smoother=BLOCK_JACOBI, coarse_solver=DENSE_LU_SOLVER, "
        "max_levels=10, max_iters=40, tolerance=1e-8, "
        "monitor_residual=1, convergence=RELATIVE_INI_CORE" +
        (", " + extra if extra else ""))
    return make_solver("AMG", cfg, "default")


def test_fine_smoother_split():
    """fine_levels>0 makes the first levels use fine_smoother."""
    A = gallery.poisson("5pt", 48, 48).init()
    s = _solver("fine_smoother=JACOBI_L1, coarse_smoother=JACOBI, "
                "fine_levels=2, "
                "min_coarse_rows=8, dense_lu_num_rows=8").setup(A)
    lv = s.amg.levels
    assert len(lv) >= 3
    assert lv[0].smoother.name == "JACOBI_L1"
    assert lv[1].smoother.name == "JACOBI_L1"
    assert lv[2].smoother.name == "JACOBI"
    # -1 (default): no split
    s2 = _solver("fine_smoother=JACOBI_L1").setup(A)
    assert all(l.smoother.name == "BLOCK_JACOBI" for l in s2.amg.levels)


def test_structure_reuse_levels():
    """resetup with structure_reuse_levels=-1 keeps the aggregates and
    still solves the updated system correctly."""
    A = gallery.poisson("5pt", 24, 24).init()
    s = _solver("structure_reuse_levels=-1").setup(A)
    agg0 = np.asarray(s.amg.levels[0].aggregates)
    nlev0 = s.amg.num_levels
    A2 = A.with_values(A.values * 2.0)
    s.resetup(A2)
    np.testing.assert_array_equal(
        np.asarray(s.amg.levels[0].aggregates), agg0)
    assert s.amg.num_levels == nlev0
    # coarse operator picked up the new coefficients (2x scaling)
    b = jnp.ones(A.num_rows)
    res = s.solve(b)
    r = np.asarray(b) - np.asarray(amgx.ops.spmv(A2, res.x))
    assert np.linalg.norm(r) / np.sqrt(A.num_rows) < 1e-6


def test_structure_reuse_zero_rebuilds():
    """structure_reuse_levels=0 (default) rebuilds the hierarchy."""
    A = gallery.poisson("5pt", 24, 24).init()
    s = _solver().setup(A)
    A2 = A.with_values(A.values * 2.0)
    s.resetup(A2)
    b = jnp.ones(A.num_rows)
    res = s.solve(b)
    r = np.asarray(b) - np.asarray(amgx.ops.spmv(A2, res.x))
    assert np.linalg.norm(r) / np.sqrt(A.num_rows) < 1e-6


def test_gmres_krylov_dim_caps_restart():
    cfg = Config.from_string(
        "solver=GMRES, gmres_n_restart=30, gmres_krylov_dim=5")
    g = make_solver("GMRES", cfg, "default")
    assert g.m == 5
    cfg2 = Config.from_string("solver=GMRES, gmres_n_restart=30")
    assert make_solver("GMRES", cfg2, "default").m == 30


def test_classical_structure_reuse():
    A = gallery.poisson("5pt", 20, 20).init()
    cfg = Config.from_string(
        "config_version=2, solver=AMG, algorithm=CLASSICAL, "
        "selector=PMIS, interpolator=D2, smoother=BLOCK_JACOBI, "
        "coarse_solver=DENSE_LU_SOLVER, max_iters=40, tolerance=1e-8, "
        "monitor_residual=1, structure_reuse_levels=-1")
    s = make_solver("AMG", cfg, "default").setup(A)
    P0 = s.amg.levels[0].P
    A2 = A.with_values(A.values * 3.0)
    s.resetup(A2)
    # transfer operators kept, coarse matrix rebuilt against new values
    assert s.amg.levels[0].P is P0
    Ac = s.amg.levels[0 + 1].A if len(s.amg.levels) > 1 \
        else s.amg.coarsest_A
    b = jnp.ones(A.num_rows)
    res = s.solve(b)
    r = np.asarray(b) - np.asarray(amgx.ops.spmv(A2, res.x))
    assert np.linalg.norm(r) / np.sqrt(A.num_rows) < 1e-6
