"""Polynomial-family + Kaczmarz smoother tests (analogs of the
reference's scalar smoother Poisson tests)."""
import numpy as np
import pytest

import amgx_tpu as amgx
from amgx_tpu import gallery
from amgx_tpu.config import Config
from amgx_tpu.solvers import make_solver

amgx.initialize()

SMOOTHERS = ["POLYNOMIAL", "KPZ_POLYNOMIAL", "CHEBYSHEV_POLY", "KACZMARZ"]


@pytest.fixture(scope="module")
def A():
    return gallery.poisson("5pt", 16, 16).init()


@pytest.fixture(scope="module")
def b(A):
    return np.ones(A.num_rows)


@pytest.mark.parametrize("name", SMOOTHERS)
def test_smoother_reduces_residual(A, b, name):
    # Kaczmarz iterates on the normal equations (condition number
    # squared), so its standalone bar is necessarily looser — its job is
    # high-frequency damping, which the AMG test below checks
    bar = 0.9 if name == "KACZMARZ" else 0.5
    cfg = Config.from_string(
        f"solver={name}, max_iters=30, monitor_residual=1, "
        "tolerance=1e-12, convergence=RELATIVE_INI_CORE")
    slv = make_solver(name, cfg, "default").setup(A)
    res = slv.solve(b)
    rel = float(np.max(res.res_norm) / np.max(res.norm0))
    assert rel < bar, f"{name}: relative residual {rel}"


@pytest.mark.parametrize("name", SMOOTHERS)
def test_amg_with_smoother_converges(A, b, name):
    cfg = Config.from_string(
        "solver=AMG, algorithm=AGGREGATION, selector=SIZE_2, "
        f"smoother={name}, presweeps=2, postsweeps=2, max_iters=60, "
        "tolerance=1e-8, monitor_residual=1, "
        "convergence=RELATIVE_INI_CORE")
    slv = make_solver("AMG", cfg, "default").setup(A)
    res = slv.solve(b)
    assert res.converged, f"AMG+{name} did not converge"


def test_kaczmarz_naive_mode(A, b):
    cfg = Config.from_string(
        "solver=KACZMARZ, kaczmarz_coloring_needed=0, max_iters=50, "
        "monitor_residual=1, tolerance=1e-12, "
        "convergence=RELATIVE_INI_CORE")
    slv = make_solver("KACZMARZ", cfg, "default").setup(A)
    assert slv.num_colors == 1
    res = slv.solve(b)
    rel = float(np.max(res.res_norm) / np.max(res.norm0))
    assert rel < 1.0                     # contractive (no divergence)
    hist = res.res_history
    assert hist is None or np.all(np.diff(np.max(np.atleast_2d(hist), axis=-1)) <= 1e-12)


def test_kaczmarz_deterministic(A, b):
    cfg = Config.from_string(
        "solver=KACZMARZ, max_iters=10, monitor_residual=1")
    x1 = make_solver("KACZMARZ", cfg, "default").setup(A).solve(b).x
    x2 = make_solver("KACZMARZ", cfg, "default").setup(A).solve(b).x
    np.testing.assert_array_equal(np.asarray(x1), np.asarray(x2))


def test_chebyshev_poly_order_clamped():
    cfg = Config.from_string(
        "solver=CHEBYSHEV_POLY, chebyshev_polynomial_order=99")
    slv = make_solver("CHEBYSHEV_POLY", cfg, "default")
    assert slv.order == 10               # reference clamps to [1, 10]
