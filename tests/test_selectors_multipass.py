"""RS / HMIS / CR selectors + MULTIPASS interpolation tests
(analogs of the reference's selector coverage and the aggressive
coarsening + multipass configs)."""
import jax.numpy as jnp
import numpy as np
import pytest

import amgx_tpu as amgx
from amgx_tpu import gallery, registry
from amgx_tpu.config import Config
from amgx_tpu.solvers import make_solver
from amgx_tpu.amg.classical.selectors import (rs_split, rs_split_python,
                                              pmis_split)

amgx.initialize()


@pytest.fixture(scope="module")
def A16():
    return gallery.poisson("5pt", 16, 16).init()


@pytest.fixture(scope="module")
def strength16(A16):
    cfg = Config.from_string("strength_threshold=0.25")
    return registry.strength.create("AHAT", cfg, "default").strong_mask(A16)


def _check_valid_split(A, strong, cf):
    """Every F point must have at least one strong C neighbor (the RS
    first-pass invariant; interpolation needs it)."""
    rows, cols, _ = A.coo()
    rows, cols = np.asarray(rows), np.asarray(cols)
    s = np.asarray(strong)
    cfn = np.asarray(cf)
    has_c = np.zeros(A.num_rows, bool)
    m = s & (cfn[cols] == 1)
    has_c[rows[m]] = True
    fine = cfn == 0
    assert np.all(has_c[fine]), "F point without strong C neighbor"


class TestRS:
    def test_rs_valid_split(self, A16, strength16):
        cf = rs_split(A16, strength16)
        _check_valid_split(A16, strength16, cf)
        ratio = float(jnp.mean((cf == 1).astype(jnp.float64)))
        assert 0.15 < ratio < 0.75

    def test_native_matches_python(self, A16, strength16):
        from amgx_tpu.native import rs_coarsen_native
        n = A16.num_rows
        ro = np.asarray(A16.row_offsets)
        ci = np.asarray(A16.col_indices)
        st = np.asarray(strength16, np.uint8)
        nat = rs_coarsen_native(n, ro, ci, st)
        if nat is None:
            pytest.skip("no C++ toolchain; python fallback is the contract")
        py = rs_split_python(n, ro, ci, st)
        np.testing.assert_array_equal(nat, py)

    def test_native_matches_python_random(self):
        """Tie-breaking must agree on irregular graphs too, or the same
        config builds different hierarchies with/without a compiler."""
        from amgx_tpu.native import rs_coarsen_native
        rng = np.random.default_rng(9)
        n = 60
        D = (rng.random((n, n)) < 0.08)
        D = D | D.T
        np.fill_diagonal(D, True)
        rows, cols = np.nonzero(D)
        ro = np.zeros(n + 1, np.int32)
        np.add.at(ro, rows + 1, 1)
        np.cumsum(ro, out=ro)
        strong = ((rows != cols) & (rng.random(len(rows)) < 0.8)
                  ).astype(np.uint8)
        nat = rs_coarsen_native(n, ro, cols.astype(np.int32), strong)
        if nat is None:
            pytest.skip("no C++ toolchain; python fallback is the contract")
        py = rs_split_python(n, ro, cols.astype(np.int32), strong)
        np.testing.assert_array_equal(nat, py)

    def test_hmis_is_rs_single_device(self, A16, strength16):
        """Single-device HMIS keeps the RS assignment (the PMIS pass only
        fixes partition boundaries, hmis.cu:55-82)."""
        sel = registry.classical_selectors.create(
            "HMIS", Config.from_string(""), "default")
        cf_h = sel.mark_coarse_fine_points(A16, strength16)
        cf_rs = rs_split(A16, strength16)
        np.testing.assert_array_equal(np.asarray(cf_h), np.asarray(cf_rs))

    def test_hmis_amg_converges(self):
        A = gallery.poisson("5pt", 24, 24).init()
        cfg = Config.from_string(
            "solver=AMG, algorithm=CLASSICAL, selector=HMIS, "
            "interpolator=D2, max_iters=60, tolerance=1e-8, "
            "monitor_residual=1, convergence=RELATIVE_INI_CORE")
        slv = make_solver("AMG", cfg, "default").setup(A)
        res = slv.solve(np.ones(A.num_rows))
        assert res.converged and res.iterations <= 50

    def test_rs_high_indegree_hub(self):
        """Bucket weights can reach 2x the max in-degree (one bump per
        in-edge); regression for the head[] overflow: a hub node whose
        weight doubles after its dependents turn FINE."""
        from amgx_tpu.matrix import CsrMatrix
        # nodes 1-4 strongly depend on hub 0 and on node 5; plus a
        # 1->2->3->4->1 cycle so the bumps land on the hub
        edges = [(i, 0) for i in range(1, 5)] + \
                [(i, 5) for i in range(1, 5)] + \
                [(1, 2), (2, 3), (3, 4), (4, 1)]
        n = 6
        rows = np.array([e[0] for e in edges])
        cols = np.array([e[1] for e in edges])
        A = CsrMatrix.from_coo(
            np.concatenate([rows, np.arange(n)]),
            np.concatenate([cols, np.arange(n)]),
            np.concatenate([-np.ones(len(edges)), 4.0 * np.ones(n)]),
            n, n).init()
        strong = np.asarray(A.coo()[0]) != np.asarray(A.coo()[1])
        cf_py = rs_split_python(n, np.asarray(A.row_offsets),
                                np.asarray(A.col_indices),
                                strong.astype(np.uint8))
        from amgx_tpu.native import rs_coarsen_native
        cf_nat = rs_coarsen_native(n, np.asarray(A.row_offsets),
                                   np.asarray(A.col_indices),
                                   strong.astype(np.uint8))
        if cf_nat is not None:
            np.testing.assert_array_equal(cf_nat, cf_py)
        assert set(np.unique(cf_py)) <= {0, 1}

    def test_rs_isolated_point_coarse(self):
        """Strong-isolated (Dirichlet) rows must be COARSE like
        pmis_split makes them, or their P row is empty."""
        from amgx_tpu.matrix import CsrMatrix
        A = gallery.poisson("5pt", 8, 8)
        n = A.num_rows
        rows, cols, vals = [np.asarray(x) for x in A.coo()]
        # cut row 10 and column 10 couplings: fully isolated point
        keep = ~(((rows == 10) | (cols == 10)) & (rows != cols))
        A2 = CsrMatrix.from_coo(rows[keep], cols[keep], vals[keep],
                                n, n).init()
        r2, c2, _ = A2.coo()
        strong = np.asarray(r2 != c2)
        cf = np.asarray(rs_split(A2, strong))
        assert cf[10] == 1
        _check_valid_split(A2, strong, cf)

    def test_hmis_differs_from_pmis(self, A16, strength16):
        """HMIS (serial RS) and PMIS make different grids — guard against
        re-aliasing."""
        cf_h = np.asarray(rs_split(A16, strength16))
        cf_p = np.asarray(pmis_split(A16, strength16))
        assert not np.array_equal(cf_h, cf_p)


class TestCR:
    def test_cr_valid_selector(self, A16, strength16):
        sel = registry.classical_selectors.create(
            "CR", Config.from_string(""), "default")
        cf = np.asarray(sel.mark_coarse_fine_points(A16, strength16))
        assert set(np.unique(cf)) <= {0, 1}
        ratio = cf.mean()
        assert 0.0 < ratio < 0.9          # picked something, not all

    def test_cr_amg_converges(self):
        A = gallery.poisson("5pt", 16, 16).init()
        cfg = Config.from_string(
            "solver=AMG, algorithm=CLASSICAL, selector=CR, "
            "interpolator=D1, max_iters=60, tolerance=1e-8, "
            "monitor_residual=1, convergence=RELATIVE_INI_CORE")
        slv = make_solver("AMG", cfg, "default").setup(A)
        res = slv.solve(np.ones(A.num_rows))
        assert res.converged


class TestMultipass:
    def test_pass_one_equals_d1_on_direct_points(self, A16, strength16):
        """Where every F point has a strong C neighbor (pass 1
        everywhere), MULTIPASS reduces to D1 exactly."""
        cf = pmis_split(A16, strength16)
        cfg = Config.from_string("")
        d1 = registry.interpolators.create("D1", cfg, "default")
        mp = registry.interpolators.create("MULTIPASS", cfg, "default")
        P1 = d1.generate(A16, cf, strength16)
        P2 = mp.generate(A16, cf, strength16)
        np.testing.assert_allclose(np.asarray(P1.to_dense()),
                                   np.asarray(P2.to_dense()), atol=1e-12)

    def test_multipass_covers_aggressive_f_points(self):
        """After aggressive (two-hop) coarsening some F points have no
        strong C neighbor; multipass must still give them interpolation
        weights (D1 leaves their rows empty)."""
        A = gallery.poisson("5pt", 20, 20).init()
        cfg = Config.from_string("strength_threshold=0.25")
        strong = registry.strength.create("AHAT", cfg, "default"
                                          ).strong_mask(A)
        sel = registry.classical_selectors.create("AGGRESSIVE_PMIS", cfg,
                                                  "default")
        cf = sel.mark_coarse_fine_points(A, strong)
        d1 = registry.interpolators.create("D1", cfg, "default")
        mp = registry.interpolators.create("MULTIPASS", cfg, "default")
        P1 = np.asarray(d1.generate(A, cf, strong).to_dense())
        P2 = np.asarray(mp.generate(A, cf, strong).to_dense())
        fine = np.asarray(cf) == 0
        empty_d1 = fine & (np.abs(P1).sum(1) == 0)
        assert empty_d1.any(), "expected distance>1 F points"
        assert np.all(np.abs(P2).sum(1)[empty_d1] > 0)
        # near-constant preservation: interior F rows sum to ~1 (rows
        # whose substitution chain touches the boundary legitimately sum
        # below 1, mirroring D1's boundary behavior)
        rowsums = P2.sum(1)
        interior = np.abs(np.asarray(A.to_dense()).sum(1)) < 1e-12
        chk = fine & interior
        assert chk.any()
        assert np.all(rowsums[chk] <= 1.0 + 1e-10)
        assert np.all(rowsums[chk] >= 0.5)
        assert (np.abs(rowsums[chk] - 1.0) < 1e-10).mean() > 0.9

    @pytest.mark.slow
    def test_aggressive_multipass_amg_converges(self):
        A = gallery.poisson("27pt", 10, 10, 10).init()
        cfg = Config.from_string(
            "solver=AMG, algorithm=CLASSICAL, selector=PMIS, "
            "aggressive_levels=1, aggressive_interpolator=MULTIPASS, "
            "interpolator=D2, max_iters=60, tolerance=1e-8, "
            "monitor_residual=1, convergence=RELATIVE_INI_CORE")
        slv = make_solver("AMG", cfg, "default").setup(A)
        res = slv.solve(np.ones(A.num_rows))
        assert res.converged and res.iterations <= 40
        # aggressive coarsening really shrank level 1
        lvl1 = slv.amg.levels[0].coarse_size
        assert lvl1 < 0.25 * A.num_rows
