"""AMG tests (analogs of aggregates_*.cu, amg_levels_reuse.cu,
nested_amg_equivalence.cu and the convergence tests in src/tests/)."""
import jax.numpy as jnp
import numpy as np
import pytest

import amgx_tpu as amgx
from amgx_tpu import gallery, ops
from amgx_tpu.config import Config
from amgx_tpu.solvers import make_solver

amgx.initialize()


def agg_cfg(extra=""):
    return Config.from_string(
        "solver(amg)=AMG, amg:algorithm=AGGREGATION, amg:selector=SIZE_2,"
        " amg:smoother(sm)=BLOCK_JACOBI, sm:relaxation_factor=0.75,"
        " sm:max_iters=1, amg:presweeps=1, amg:postsweeps=1,"
        " amg:coarse_solver=DENSE_LU_SOLVER, amg:max_iters=1,"
        " amg:min_coarse_rows=16" + (", " + extra if extra else ""))


class TestAggregates:
    def test_coarsening_factor(self):
        """SIZE_2 must roughly halve the grid (aggregates_coarsening_
        factor test analog)."""
        A = gallery.poisson("5pt", 32, 32).init()
        from amgx_tpu.registry import aggregation_selectors
        sel = aggregation_selectors.create("SIZE_2", agg_cfg(), "amg")
        agg, nc = sel.set_aggregates(A)
        ratio = A.num_rows / nc
        assert 1.7 <= ratio <= 2.6, f"coarsening ratio {ratio}"
        # every vertex belongs to a valid aggregate
        a = np.asarray(agg)
        assert a.min() >= 0 and a.max() == nc - 1
        assert np.unique(a).size == nc

    def test_determinism(self):
        """Same input -> identical aggregates (aggregates_determinism
        test analog; determinism comes from hash tie-breaking)."""
        A = gallery.poisson("9pt", 24, 24).init()
        from amgx_tpu.registry import aggregation_selectors
        sel = aggregation_selectors.create("SIZE_2", agg_cfg(), "amg")
        a1, n1 = sel.set_aggregates(A)
        a2, n2 = sel.set_aggregates(A)
        assert n1 == n2
        assert np.array_equal(np.asarray(a1), np.asarray(a2))

    def test_size4_coarser(self):
        A = gallery.poisson("5pt", 32, 32).init()
        from amgx_tpu.registry import aggregation_selectors
        s2 = aggregation_selectors.create("SIZE_2", agg_cfg(), "amg")
        s4 = aggregation_selectors.create("SIZE_4", agg_cfg(), "amg")
        _, n2 = s2.set_aggregates(A)
        _, n4 = s4.set_aggregates(A)
        assert n4 < n2

    def test_dummy_selector(self):
        A = gallery.poisson("5pt", 8, 8).init()
        from amgx_tpu.registry import aggregation_selectors
        cfg = agg_cfg("amg:aggregate_size=4")
        sel = aggregation_selectors.create("DUMMY", cfg, "amg")
        agg, nc = sel.set_aggregates(A)
        assert nc == 16
        assert np.array_equal(np.asarray(agg), np.arange(64) // 4)

    def test_galerkin_matches_explicit_rap(self):
        """Aggregation coarse A == R A P with piecewise-constant P
        (low_deg determinism/correctness analog)."""
        A = gallery.poisson("5pt", 12, 12).init()
        from amgx_tpu.registry import aggregation_selectors
        sel = aggregation_selectors.create("SIZE_2", agg_cfg(), "amg")
        agg, nc = sel.set_aggregates(A)
        from amgx_tpu.amg.aggregation.galerkin import coarse_a_from_aggregates
        Ac = coarse_a_from_aggregates(A, agg, nc)
        n = A.num_rows
        P = np.zeros((n, nc))
        P[np.arange(n), np.asarray(agg)] = 1.0
        ref = P.T @ np.asarray(A.to_dense()) @ P
        np.testing.assert_allclose(np.asarray(Ac.to_dense()), ref,
                                   rtol=1e-12, atol=1e-12)


class TestAMGSolve:
    @pytest.fixture(scope="class")
    def A64(self):
        return gallery.poisson("5pt", 64, 64).init()

    def test_fgmres_aggregation_flagship(self, A64):
        """The reference's flagship config (FGMRES_AGGREGATION.json)."""
        cfg = Config.from_file("configs/FGMRES_AGGREGATION.json")
        s = amgx.create_solver(cfg)
        s.setup(A64)
        b = jnp.ones(A64.num_rows)
        res = s.solve(b)
        assert res.converged
        assert res.iterations <= 40
        rel = float(np.max(res.res_norm)) / float(np.max(res.norm0))
        assert rel <= 1e-6

    def test_amg_preconditions_pcg(self, A64):
        cfg = Config.from_string(
            "max_iters=60, monitor_residual=1, tolerance=1e-10,"
            " preconditioner(amg)=AMG, amg:algorithm=AGGREGATION,"
            " amg:selector=SIZE_2, amg:smoother(sm)=BLOCK_JACOBI,"
            " sm:relaxation_factor=0.75, sm:max_iters=1, amg:presweeps=1,"
            " amg:postsweeps=1, amg:coarse_solver=DENSE_LU_SOLVER,"
            " amg:max_iters=1, amg:min_coarse_rows=16")
        s = make_solver("PCG", cfg)
        s.setup(A64)
        res = s.solve(jnp.ones(A64.num_rows))
        assert res.converged
        assert res.iterations <= 40

    @pytest.mark.parametrize("cycle", ["V", "W", "F", "CG"])
    def test_cycles_reduce_error(self, A64, cycle):
        """Each cycle shape must contract the error (cycle tests analog)."""
        cfg = agg_cfg(f"amg:cycle={cycle}, amg:max_iters=6,"
                      " amg:monitor_residual=1, amg:tolerance=1e-30")
        s = make_solver("AMG", cfg, "amg")
        s.setup(A64)
        b = jnp.ones(A64.num_rows)
        res = s.solve(b)
        red = float(np.max(res.res_norm)) / float(np.max(res.norm0))
        # unsmoothed aggregation with 1+1 Jacobi is a slow standalone
        # solver by design (the reference ships it as a preconditioner);
        # the contract here is monotone contraction, W/K-cycles are faster
        assert red < 0.8, f"{cycle}-cycle reduction {red}"

    def test_block_matrix_amg(self):
        A = gallery.random_matrix(120, max_nnz_per_row=4, seed=11,
                                  symmetric=True, diag_dominant=True,
                                  block_dims=(2, 2)).init()
        cfg = agg_cfg("amg:min_coarse_rows=8")
        s = make_solver("AMG", cfg, "amg")
        s.setup(A)
        b = jnp.ones(A.num_rows * 2)
        # diag-dominant matrix: a couple of cycles give strong reduction
        x = s.smooth(s.solve_data(), b, jnp.zeros_like(b), 3)
        r = float(np.linalg.norm(np.asarray(ops.residual(A, x, b))))
        assert r < 1e-3 * float(np.linalg.norm(np.asarray(b)))

    def test_grid_stats_report(self, A64):
        s = make_solver("AMG", agg_cfg(), "amg")
        s.setup(A64)
        stats = s.grid_stats()
        assert "Number of Levels" in stats
        assert "Operator Complexity" in stats

    def test_structure_reuse_with_values(self, A64):
        """with_values + resetup path (amg_levels_reuse analog)."""
        cfg = Config.from_file("configs/FGMRES_AGGREGATION.json")
        s = amgx.create_solver(cfg)
        s.setup(A64)
        b = jnp.ones(A64.num_rows)
        r1 = s.solve(b)
        A2 = A64.with_values(A64.values * 2.0)
        s.resetup(A2)
        r2 = s.solve(b)
        assert r2.converged
        # scaled matrix: solution should be half
        np.testing.assert_allclose(np.asarray(r2.x), np.asarray(r1.x) / 2.0,
                                   rtol=1e-3, atol=1e-9)


class TestValueOnlyResetup:
    """Fused one-dispatch value-only resetup (amg/value_resetup.py —
    src/amg.cu:232-262 structure-reuse economics, done as ONE jitted
    program of the new fine values)."""

    def _flagship(self):
        from amgx_tpu.presets import FLAGSHIP
        return Config.from_string(
            FLAGSHIP + ", amg:structure_reuse_levels=-1")

    def test_engages_and_matches_fresh_setup(self):
        A = amgx.gallery.poisson("7pt", 16, 16, 16).init()
        b = np.ones(A.num_rows)
        s = amgx.create_solver(self._flagship())
        s.setup(A)
        s.solve(b)
        amg = s.preconditioner.preconditioner.amg
        A2 = A.with_values(np.asarray(A.values) * 1.8)
        s.resetup(A2)
        assert getattr(amg, "_last_resetup_value_only", False), \
            "fused value-resetup did not engage on the flagship shape"
        r = s.solve(b)
        assert bool(r.converged)
        resid = np.asarray(amgx.ops.residual(A2.init(), r.x,
                                             jnp.asarray(b)))
        assert np.linalg.norm(resid) < 1e-6 * max(
            1.0, np.linalg.norm(b))
        # iteration parity with a from-scratch setup on the new values
        # (±1: the fused path sums the Gershgorin bound over DIA slabs,
        # the eager path over CSR entries — not bit-associated)
        s2 = amgx.create_solver(self._flagship())
        s2.setup(A2)
        r2 = s2.solve(b)
        assert abs(int(r.iterations) - int(r2.iterations)) <= 1

    def test_falls_back_on_unstructured(self):
        A = amgx.gallery.random_matrix(400, max_nnz_per_row=5, seed=2,
                                       symmetric=True,
                                       diag_dominant=True).init()
        cfg = Config.from_string(
            "solver=FGMRES, max_iters=60, monitor_residual=1,"
            " tolerance=1e-8, gmres_n_restart=30,"
            " preconditioner(amg)=AMG, amg:algorithm=AGGREGATION,"
            " amg:selector=SIZE_2, amg:smoother=BLOCK_JACOBI,"
            " amg:max_iters=1, amg:structure_reuse_levels=-1")
        s = amgx.create_solver(cfg)
        s.setup(A)
        b = np.ones(A.num_rows)
        A2 = A.with_values(np.asarray(A.values) * 1.5)
        s.resetup(A2)          # generic reuse path, must still be right
        amg = s.preconditioner.amg
        assert not getattr(amg, "_last_resetup_value_only", False)
        r = s.solve(b)
        assert bool(r.converged)


class TestSelectorVariants:
    """serial_greedy.cu / adaptive.cu / multi_pairwise.cu analogs."""

    def _solve(self, sel, extra=""):
        A = gallery.poisson("7pt", 8, 8, 8).init()
        b = jnp.ones(A.num_rows)
        cfg = Config.from_string(
            "solver(s)=FGMRES, s:max_iters=80, s:tolerance=1e-8,"
            " s:monitor_residual=1, s:preconditioner(amg)=AMG,"
            " amg:algorithm=AGGREGATION, amg:smoother=JACOBI_L1,"
            " amg:max_iters=1, amg:min_coarse_rows=16,"
            f" amg:selector={sel}" + extra)
        s = amgx.create_solver(cfg)
        s.setup(A)
        r = s.solve(b)
        tr = np.linalg.norm(
            np.asarray(b) - np.asarray(ops.spmv(A, r.x)))
        assert bool(r.converged) and tr < 1e-6 * np.linalg.norm(
            np.asarray(b))
        return s.preconditioner.amg

    def test_serial_greedy_respects_aggregate_size(self):
        amg_h = self._solve("SERIAL_GREEDY", ", amg:aggregate_size=4")
        n0, n1 = (amg_h.levels[0].A.num_rows,
                  amg_h.levels[0].coarse_size)
        # greedy size-4 growth: coarsening ratio between 2x and 4x
        assert 2.0 <= n0 / n1 <= 4.5

    def test_adaptive_bins_smooth_error(self):
        amg_h = self._solve("ADAPTIVE")
        assert amg_h.levels[0].coarse_size <= amg_h.levels[0].A.num_rows // 3

    def test_multi_pairwise_notay_weights(self):
        # Notay coupling -0.5(a_ij/a_ii + a_ji/a_jj) must produce a
        # usable pairwise hierarchy (it collapsed to zero weights when
        # the transpose term was taken in absolute value)
        amg_h = self._solve("MULTI_PAIRWISE",
                            ", amg:notay_weights=1,"
                            " amg:aggregation_passes=2")
        n0, n1 = (amg_h.levels[0].A.num_rows,
                  amg_h.levels[0].coarse_size)
        assert n0 / n1 >= 3.0      # two pairwise passes ~ 4x
