"""Telemetry subsystem tests (amgx_tpu/telemetry/).

The acceptance contracts:
- zero-overhead: the instrumented solve emits an IDENTICAL jaxpr and
  performs no extra device->host transfers vs telemetry=0 (the report
  rides the stats array the monitor already returns);
- counter correctness under deterministic conditions (structure-cache
  hit/miss, setup routing, batcher occupancy/pad waste, fallback
  events under fault injection, retrace counts);
- SolveReport present and schema-valid on the single, batched,
  distributed and C-API solve paths;
- hierarchical spans record parent/child structure, export as valid
  Perfetto trace-event JSON, and keep the flat-timer API (the PR-3
  accounted-fraction contract) intact;
- tools/check_spans.py (registry coverage + accounted-leaf
  disjointness) passes on the package as checked in.
"""
import importlib.util
import json
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import amgx_tpu as amgx
from amgx_tpu import gallery, output, profiling
from amgx_tpu.config import Config
from amgx_tpu.errors import RC
from amgx_tpu.telemetry import (SolveReport, build_report, metrics,
                                spans, validate_report)

amgx.initialize()

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CG = ("solver=CG, max_iters=200, monitor_residual=1, tolerance=1e-8,"
      " convergence=RELATIVE_INI")

AMG_PCG = (
    "solver(s)=PCG, s:max_iters=60, s:tolerance=1e-8,"
    " s:convergence=RELATIVE_INI, s:monitor_residual=1,"
    " s:preconditioner(amg)=AMG, amg:algorithm=AGGREGATION,"
    " amg:selector=SIZE_2, amg:smoother(sm)=JACOBI_L1, sm:max_iters=1,"
    " amg:presweeps=1, amg:postsweeps=1, amg:max_iters=1,"
    " amg:coarse_solver=DENSE_LU_SOLVER, amg:min_coarse_rows=16,"
    " amg:max_levels=10, amg:structure_reuse_levels=-1")


@pytest.fixture(scope="module")
def poisson16():
    return gallery.poisson("5pt", 16, 16).init()


@pytest.fixture(scope="module")
def poisson12_3d():
    return gallery.poisson("7pt", 12, 12, 12).init()


def _solve(cfg_str, A, b=None):
    slv = amgx.create_solver(Config.from_string(cfg_str))
    slv.setup(A)
    if b is None:
        b = jnp.ones(A.num_rows)
    return slv, slv.solve(b)


# ---------------------------------------------------------------------------
# metrics registry
# ---------------------------------------------------------------------------


def test_metrics_registry_basics():
    metrics.reset()
    metrics.inc("amg.setup.full")
    metrics.inc("amg.setup.full", 2)
    metrics.set_gauge("batch.bucket_occupancy", 0.75)
    metrics.max_gauge("memory.setup_peak_bytes", 10)
    metrics.max_gauge("memory.setup_peak_bytes", 5)   # keeps the max
    snap = metrics.snapshot()
    assert snap["amg.setup.full"] == 3
    assert snap["batch.bucket_occupancy"] == 0.75
    assert snap["memory.setup_peak_bytes"] == 10
    # declared-but-untouched counters appear as zeros (stable key set)
    assert snap["resilience.fallback.retry"] == 0
    metrics.reset()
    assert metrics.get("amg.setup.full") == 0


def test_metrics_undeclared_name_raises():
    with pytest.raises(KeyError, match="did you mean"):
        metrics.inc("amg.setup.ful")
    with pytest.raises(KeyError):
        metrics.set_gauge("no.such.gauge", 1)


def test_setup_routing_counters(poisson16):
    metrics.reset()
    slv, _res = _solve(AMG_PCG, poisson16)
    assert metrics.get("amg.setup.full") == 1
    before_v = metrics.get("amg.resetup.value")
    before_s = metrics.get("amg.resetup.structure")
    slv.resetup(poisson16)
    after_v = metrics.get("amg.resetup.value")
    after_s = metrics.get("amg.resetup.structure")
    # a structure-reuse resetup routes to exactly ONE of the resetup
    # counters and never back through the full-setup counter
    assert (after_v - before_v) + (after_s - before_s) == 1
    assert metrics.get("amg.setup.full") == 1


def test_geo_structure_cache_counters():
    """Warm GEO setup must HIT the device structure cache (the 256^3
    warm-setup regression fix, PR 4/6): same offsets + shape + device
    on the second build."""
    cfg = (
        "solver(s)=PCG, s:max_iters=40, s:tolerance=1e-8,"
        " s:convergence=RELATIVE_INI, s:monitor_residual=1,"
        " s:preconditioner(amg)=AMG, amg:algorithm=AGGREGATION,"
        " amg:selector=GEO, amg:smoother(sm)=JACOBI_L1, sm:max_iters=1,"
        " amg:presweeps=1, amg:postsweeps=1, amg:max_iters=1,"
        " amg:coarse_solver=DENSE_LU_SOLVER, amg:min_coarse_rows=32,"
        " amg:max_levels=10")
    A = gallery.poisson("7pt", 16, 16, 16).init()
    b = jnp.ones(A.num_rows)
    metrics.reset()
    slv1 = amgx.create_solver(Config.from_string(cfg))
    slv1.setup(A)
    cold_miss = metrics.get("amg.geo_struct_cache.miss")
    cold_hit = metrics.get("amg.geo_struct_cache.hit")
    slv2 = amgx.create_solver(Config.from_string(cfg))
    slv2.setup(A)
    warm_miss = metrics.get("amg.geo_struct_cache.miss")
    warm_hit = metrics.get("amg.geo_struct_cache.hit")
    if cold_miss == 0 and cold_hit == 0:
        pytest.skip("GEO structured Galerkin path inactive on this rig")
    # the warm setup registers ZERO new device-structure entries
    assert warm_miss == cold_miss
    assert warm_hit > cold_hit
    assert slv2.solve(b).converged


def test_batcher_occupancy_counters(poisson16):
    from amgx_tpu.batch import RequestBatcher
    from amgx_tpu.presets import BATCHED_CG
    metrics.reset()
    rb = RequestBatcher(Config.from_string(BATCHED_CG))
    rng = np.random.default_rng(3)
    for _ in range(3):
        rb.submit(poisson16, rng.standard_normal(poisson16.num_rows))
    rb.drain()
    snap = metrics.snapshot()
    assert snap["batch.requests"] == 3
    assert snap["batch.dispatches"] == 1
    # 3 requests pad to the 4-rung: 1 padded system, occupancy 0.75
    assert snap["batch.padded_systems"] == 1
    assert snap["batch.bucket_occupancy"] == pytest.approx(0.75)
    assert snap["batch.live_buckets"] == 1


def test_fallback_event_counters(poisson16):
    """Deterministic fault injection -> the retry chain runs and the
    fallback counters record it."""
    from amgx_tpu.resilience import faultinject as fi
    metrics.reset()
    slv = amgx.create_solver(Config.from_string(
        CG + ", health_guards=1, fallback_policy=NAN_DETECTED>retry,"
        " max_fallback_attempts=2"))
    slv.setup(poisson16)
    b = jnp.ones(poisson16.num_rows)
    with fi.inject("spmv_nan", iteration=3):
        res = slv.solve(b)
    assert res.converged          # the retry recovered
    assert metrics.get("resilience.fallback_attempts") == 1
    assert metrics.get("resilience.fallback.retry") == 1
    assert metrics.get("resilience.fallback.switch_solver") == 0


def test_retrace_counters(poisson16):
    metrics.reset()
    slv, _ = _solve(CG, poisson16)
    assert metrics.get("solver.retrace.solve") == 1
    slv.solve(jnp.ones(poisson16.num_rows))     # same shape: cached
    assert metrics.get("solver.retrace.solve") == 1
    _solve(CG, poisson16)          # a fresh tree pays its own trace
    assert metrics.get("solver.retrace.solve") == 2


# ---------------------------------------------------------------------------
# SolveReport: zero-overhead contracts
# ---------------------------------------------------------------------------


def test_jaxpr_identical_telemetry_on_off(poisson16):
    """telemetry=1 and telemetry=0 must trace the SAME solve program —
    the in-trace metrics ride state the monitor already computes."""
    b = jnp.ones(poisson16.num_rows)
    jaxprs = {}
    for knob in (0, 1):
        slv = amgx.create_solver(Config.from_string(
            CG + f", telemetry={knob}"))
        slv.setup(poisson16)
        fn = slv._build_solve_fn()
        jaxprs[knob] = str(jax.make_jaxpr(fn)(
            slv.solve_data(), b, jnp.zeros_like(b)))
    assert jaxprs[0] == jaxprs[1]


def test_no_extra_transfers_or_syncs(poisson16):
    """Same number of blocking device fetches with telemetry on/off,
    and the report builder itself runs clean under a transfer guard
    that forbids ALL transfers (even explicit ones)."""
    b = jnp.ones(poisson16.num_rows)
    counts = {}
    real_block = jax.block_until_ready
    for knob in (0, 1):
        slv = amgx.create_solver(Config.from_string(
            CG + f", telemetry={knob}"))
        slv.setup(poisson16)
        slv.solve(b)                     # compile + first fetch
        n = 0

        def counting(x):
            nonlocal n
            n += 1
            return real_block(x)

        jax.block_until_ready = counting
        try:
            res = slv.solve(b)
        finally:
            jax.block_until_ready = real_block
        counts[knob] = n
        if knob:
            assert res.report is not None
    assert counts[0] == counts[1]
    # the builder touches only host data + shapes: rebuild under the
    # strictest guard
    slv, res = _solve(CG + ", telemetry=1", poisson16)
    with jax.transfer_guard("disallow_explicit"):
        rep = build_report(slv, res,
                           hist=np.asarray(res.report.residuals))
    assert rep.iterations == res.iterations


def test_solve_report_contents(poisson12_3d):
    slv, res = _solve(AMG_PCG, poisson12_3d)
    rep = res.report
    assert isinstance(rep, SolveReport)
    assert rep.solver == "PCG"
    assert rep.converged and rep.status_code == 0
    assert rep.iterations == res.iterations
    assert len(rep.residuals) == res.iterations + 1
    assert rep.residuals[0] == pytest.approx(float(res.norm0))
    assert rep.residuals[-1] == pytest.approx(float(res.res_norm))
    assert rep.cycle == "V"
    # level table covers the hierarchy + coarsest, with activity cols
    assert len(rep.levels) >= 2
    assert rep.levels[0]["rows"] == poisson12_3d.num_rows
    for row in rep.levels:
        assert row["layout"] in ("dia", "ell", "swell", "csr")
    assert rep.levels[-1].get("coarse_solver") == "DENSE_LU_SOLVER"
    assert rep.solve_time_s > 0


def test_report_schema_validates(poisson12_3d):
    slv, res = _solve(AMG_PCG, poisson12_3d)
    d = res.report.to_dict()
    assert validate_report(d) == []
    # corrupted reports FAIL: missing required key, wrong type
    bad = dict(d)
    bad.pop("iterations")
    assert any("iterations" in e for e in validate_report(bad))
    bad = dict(d)
    bad["status_code"] = "zero"
    assert validate_report(bad)
    bad = dict(d)
    bad["levels"] = [{"level": 0}]
    assert validate_report(bad)


def test_report_level_cache_lifecycle(poisson16):
    """The memoized level table (and the recorded VMEM-tail boundary)
    must not survive a hierarchy rebuild — a stale memo would report
    the OLD hierarchy's rows/kinds for the new one."""
    from amgx_tpu.telemetry.report import _amg_of
    slv, res = _solve(AMG_PCG, poisson16)
    amg = _amg_of(slv)
    assert amg._telemetry_level_cache is not None   # memoized by report
    amg.setup(poisson16)          # full rebuild drops memo + tail
    assert amg._telemetry_level_cache is None
    assert amg._tail_entry_level is None


def test_telemetry_off_no_report(poisson16):
    _slv, res = _solve(CG + ", telemetry=0", poisson16)
    assert res.report is None


def test_report_json_strict_on_nan(poisson16):
    """A NAN_DETECTED solve's report must still serialize as STRICT
    JSON (NaN residuals -> null, never the bare NaN token only Python
    accepts) — exactly the failure case telemetry exists to report."""
    from amgx_tpu.resilience import faultinject as fi
    slv = amgx.create_solver(Config.from_string(CG))
    slv.setup(poisson16)
    with fi.inject("spmv_nan", iteration=3):
        res = slv.solve(jnp.ones(poisson16.num_rows))
    assert res.status == "nan_detected"
    rep = res.report
    assert not np.all(np.isfinite(np.asarray(rep.residuals)))
    s = rep.to_json()
    assert "NaN" not in s
    doc = json.loads(s)
    assert doc["status"] == "nan_detected"
    assert doc["residuals"][-1] is None      # the NaN that tripped it
    lines = []
    output.register_print_callback(lambda msg, _n: lines.append(msg))
    try:
        rep.emit()
    finally:
        output.register_print_callback(None)
    assert "NaN" not in "".join(lines)
    assert json.loads("".join(lines))["amgx_report"]["converged"] is False


def test_report_emit_through_callback(poisson16):
    _slv, res = _solve(CG, poisson16)
    lines = []
    output.register_print_callback(lambda msg, _n: lines.append(msg))
    try:
        res.report.emit(include_counters=True)
    finally:
        output.register_print_callback(None)
    doc = json.loads("".join(lines))
    assert doc["amgx_report"]["converged"] is True
    assert "solver.retrace.solve" in doc["amgx_report"]["counters"]


# ---------------------------------------------------------------------------
# batched / distributed / C-API report surfaces
# ---------------------------------------------------------------------------


def test_batched_reports(poisson16):
    from amgx_tpu.batch import BatchedSolver
    from amgx_tpu.presets import BATCHED_CG
    metrics.reset()
    bs = BatchedSolver(Config.from_string(BATCHED_CG))
    bs.setup(poisson16)
    rng = np.random.default_rng(5)
    B = jnp.asarray(rng.standard_normal((3, poisson16.num_rows)))
    res = bs.solve_many(B)
    assert metrics.get("solver.retrace.solve_batched") == 1
    assert res.reports is not None and len(res.reports) == 3
    for i, (rep, sysr) in enumerate(zip(res.reports,
                                        res.per_system())):
        assert rep.iterations == int(res.iterations[i])
        assert len(rep.residuals) == rep.iterations + 1
        assert validate_report(rep.to_dict()) == []
        assert sysr.report is rep
    bs.solve_many(B)                     # same bucket: no retrace
    assert metrics.get("solver.retrace.solve_batched") == 1


def test_distributed_report():
    from amgx_tpu.distributed import DistributedSolver, default_mesh
    A = gallery.poisson("7pt", 8, 8, 8)
    cfg = Config.from_string(
        "solver=CG, max_iters=300, monitor_residual=1, tolerance=1e-8,"
        " convergence=RELATIVE_INI")
    ds = DistributedSolver(cfg, default_mesh(2))
    ds.setup(A)
    res = ds.solve(np.ones(A.num_rows))
    assert res.converged
    rep = res.report
    assert rep is not None
    dist = rep.distributed
    assert dist["n_ranks"] == 2 and dist["axis"] == "p"
    assert dist["n_global"] == A.num_rows
    assert dist["rows_per_shard"] == A.num_rows // 2
    # comms/shard telemetry (ISSUE 13): the traced exchange-site table
    # with modeled bytes, and the per-shard rows/nnz tallies
    assert dist["comms"] and all(
        e["mode"] == "ring" and e["bytes_fwd"] > 0
        for e in dist["comms"])
    assert dist["shards"]["rows"] == [A.num_rows // 2] * 2
    assert dist["shards"]["rows_imbalance"] == 1.0
    assert validate_report(rep.to_dict()) == []


def test_capi_report_metrics_timers(poisson16):
    from amgx_tpu import capi
    assert capi.AMGX_initialize() == RC.OK
    try:
        rc, cfg = capi.AMGX_config_create(
            "solver=PCG, preconditioner=BLOCK_JACOBI, max_iters=200,"
            " tolerance=1e-8, monitor_residual=1,"
            " convergence=RELATIVE_INI_CORE")
        rc, rsrc = capi.AMGX_resources_create_simple(cfg)
        rc, Ah = capi.AMGX_matrix_create(rsrc, "dDDI")
        rc, bh = capi.AMGX_vector_create(rsrc, "dDDI")
        rc, xh = capi.AMGX_vector_create(rsrc, "dDDI")
        rc, slv = capi.AMGX_solver_create(rsrc, "dDDI", cfg)
        n = poisson16.num_rows
        assert capi.AMGX_matrix_upload_all(
            Ah, n, poisson16.nnz, 1, 1,
            np.asarray(poisson16.row_offsets),
            np.asarray(poisson16.col_indices),
            np.asarray(poisson16.values)) == RC.OK
        assert capi.AMGX_vector_upload(bh, n, 1, np.ones(n)) == RC.OK
        assert capi.AMGX_vector_set_zero(xh, n, 1) == RC.OK
        # report before any solve: BAD_PARAMETERS, not a crash
        rc, rep = capi.AMGX_solver_get_report(slv)
        assert rc == RC.BAD_PARAMETERS and rep is None
        assert capi.AMGX_solver_setup(slv, Ah) == RC.OK
        assert capi.AMGX_solver_solve(slv, bh, xh) == RC.OK
        rc, rep = capi.AMGX_solver_get_report(slv)
        assert rc == RC.OK
        assert rep["converged"] is True and rep["solver"] == "PCG"
        assert validate_report(rep) == []
        rc, snap = capi.AMGX_read_metrics()
        assert rc == RC.OK and snap["solver.retrace.solve"] >= 1
        lines = []
        capi.AMGX_register_print_callback(
            lambda msg, _n: lines.append(msg))
        try:
            assert capi.AMGX_print_timers() == RC.OK
        finally:
            capi.AMGX_register_print_callback(None)
        table = "".join(lines)
        assert "region" in table and "mean_ms" in table
        assert "PCG.solve" in table
    finally:
        capi.AMGX_finalize()


# ---------------------------------------------------------------------------
# spans: tree, flat-timer compatibility, Perfetto export, sync knob
# ---------------------------------------------------------------------------


def test_span_tree_and_flat_timers():
    profiling.reset_timers()
    with profiling.trace_region("amg.l0_layout"):
        with profiling.trace_region("telemetry.child"):
            pass
    recs = {r["name"]: r for r in spans.records()}
    assert recs["telemetry.child"]["parent"] == "amg.l0_layout"
    assert recs["telemetry.child"]["depth"] == 1
    assert recs["amg.l0_layout"]["parent"] is None
    # the flat accumulator (the PR-3 accounted-fraction surface) sees
    # both names, and timers_total sums by prefix exactly as before
    t = profiling.timers()
    assert t["amg.l0_layout"][0] == 1
    assert profiling.timers_total("amg.") == \
        pytest.approx(t["amg.l0_layout"][1])


def test_span_export_perfetto(tmp_path):
    profiling.reset_timers()
    with profiling.trace_region("amg.l0_layout"):
        pass
    path = tmp_path / "trace.json"
    n = spans.export_chrome_trace(str(path))
    assert n >= 1
    doc = json.loads(path.read_text())
    evs = doc["traceEvents"]
    assert len(evs) == n
    ev = next(e for e in evs if e["name"] == "amg.l0_layout")
    assert ev["ph"] == "X" and ev["dur"] >= 0 and ev["cat"] == "amg"


def test_telemetry_sync_knob(poisson16):
    assert not spans.sync_enabled()
    try:
        slv = amgx.create_solver(Config.from_string(
            CG + ", telemetry_sync=1"))
        assert spans.sync_enabled()
        slv.setup(poisson16)
        res = slv.solve(jnp.ones(poisson16.num_rows))
        assert res.converged         # fencing changes timing, not math
        # latched BOTH ways: a later telemetry_sync=0 root construction
        # turns fencing back off (no one-way ratchet)
        amgx.create_solver(Config.from_string(CG))
        assert not spans.sync_enabled()
    finally:
        spans.set_sync(False)


def test_env_sync_survives_config_latch(monkeypatch):
    """AMGX_TPU_TELEMETRY_SYNC=1 must keep fencing on even when a
    config with the default telemetry_sync=0 latches afterwards."""
    monkeypatch.setenv("AMGX_TPU_TELEMETRY_SYNC", "1")
    try:
        amgx.create_solver(Config.from_string(CG))
        assert spans.sync_enabled()
    finally:
        spans.set_sync(False)


def test_format_timers_sorted_aligned():
    profiling.reset_timers()
    import time as _t
    with profiling.trace_region("amg.l0_layout"):
        _t.sleep(0.01)
    with profiling.trace_region("telemetry.fast"):
        pass
    table = profiling.format_timers()
    lines = table.splitlines()
    assert "calls" in lines[0] and "mean_ms" in lines[0] \
        and "share" in lines[0]
    body = lines[2:]
    # sorted by total time: the slow region leads
    assert body[0].startswith("amg.l0_layout")
    assert "%" in body[0]


# ---------------------------------------------------------------------------
# static span checker
# ---------------------------------------------------------------------------


def _load_check_spans():
    path = os.path.join(REPO, "tools", "check_spans.py")
    spec = importlib.util.spec_from_file_location("check_spans", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_check_spans_clean():
    """Registry coverage + accounted-leaf disjointness hold for the
    package as checked in (the setup_accounted_fraction >= 0.9
    contract depends on no amg.* span double-counting a child)."""
    mod = _load_check_spans()
    assert mod.check() == []


def test_check_spans_catches_violations():
    mod = _load_check_spans()
    # typo'd region names match no declared pattern — literal typos,
    # f-string-placeholder typos, and typos in the dynamic-solver-name
    # family all fail
    for typo in ("amg.L3.stregth", "amg.L*.stregth", "*.solv",
                 "amg.L*.galerkin.extra"):
        assert not any(mod._compatible(typo, d)
                       for d in spans.DECLARED_SPANS), typo
    # literal names extracted from the package all resolve
    lits = mod.extract_span_literals()
    assert lits and all(name is not None for _f, _l, name in lits)
    assert any(name == "amg.L*.galerkin" for _f, _l, name in lits)


# ---------------------------------------------------------------------------
# output flush satellite
# ---------------------------------------------------------------------------


def test_amgx_output_flushes_stdout(monkeypatch):
    class Rec:
        def __init__(self):
            self.wrote = []
            self.flushed = 0

        def write(self, s):
            self.wrote.append(s)

        def flush(self):
            self.flushed += 1

    rec = Rec()
    monkeypatch.setattr(sys, "stdout", rec)
    output.amgx_output("status line\n")
    assert rec.wrote == ["status line\n"] and rec.flushed == 1
