"""Request-path tracing, comms/shard telemetry and the crash-surviving
flight recorder (ISSUE 13).

- spans.py extensions: span args, instant marks, retroactive spans,
  trace-id minting, Perfetto flow-event export;
- serving trace propagation: one flow chain per request across the
  lifecycle stages, the journal persisting trace ids so a
  submitted->crashed->recovered->finalized request yields ONE
  connected chain across both service incarnations (the acceptance);
- comms telemetry: modeled per-direction bytes matching hand-computed
  halo window sizes EXACTLY on a 4-shard mesh, the report comms
  table, shard-imbalance gauges;
- flight recorder: append-and-rotate durability, corruption-tolerant
  reads, the event sources (shed/quarantine/build/fallback/resetup/
  chaos), the BREAKDOWN last-N dump through the output callback;
- satellites: the OpenMetrics replica label and the check_spans
  dead-metric contract."""
import importlib.util
import json
import os

import numpy as np
import pytest

import amgx_tpu as amgx
from amgx_tpu import gallery
from amgx_tpu.config import Config
from amgx_tpu.presets import BATCHED_CG
from amgx_tpu.resilience import faultinject
from amgx_tpu.serving import SolveService
from amgx_tpu.telemetry import flightrec, metrics, spans
from amgx_tpu.telemetry.flightrec import FlightRecorder

amgx.initialize()

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def poisson16():
    return gallery.poisson("5pt", 16, 16).init()


def _svc_cfg(extra=""):
    return Config.from_string(
        BATCHED_CG + ", serving_bucket_slots=2, serving_chunk_iters=4"
        + (", " + extra if extra else ""))


def _rhs(A, seed=0):
    return np.random.default_rng(seed).standard_normal(A.num_rows)


def _flow_events(trace_id):
    """The exported flow-chain events of one request trace, plus the
    slice/mark events tagged with it, in export (time) order."""
    evs = spans.chrome_trace_events()
    flow = [e for e in evs if e.get("cat") == "trace.flow"
            and e["args"].get("trace") == trace_id]
    tagged = [e for e in evs if e.get("cat") != "trace.flow"
              and (e["args"].get("trace") == trace_id
                   or trace_id in (e["args"].get("traces") or ()))]
    flow.sort(key=lambda e: e["ts"])
    tagged.sort(key=lambda e: e["ts"])
    return flow, tagged


# ---------------------------------------------------------------------------
# spans: args / marks / retroactive spans / flow export
# ---------------------------------------------------------------------------


def test_span_args_and_flow_export():
    tr = spans.new_trace_id()
    with spans.span("serving.submit", annotate=False,
                    args={"trace": tr, "tenant": "acme"}):
        pass
    spans.mark("serving.complete", args={"trace": tr})
    flow, tagged = _flow_events(tr)
    assert [e["name"] for e in tagged] == ["serving.submit",
                                           "serving.complete"]
    assert tagged[0]["args"]["tenant"] == "acme"
    # a two-anchor chain: one start, one finish, ids equal, each
    # anchored at its slice's pid/tid so Perfetto binds them
    assert [e["ph"] for e in flow] == ["s", "f"]
    assert flow[0]["id"] == flow[1]["id"]
    assert flow[1]["bp"] == "e"
    for fe, sl in zip(flow, tagged):
        assert (fe["pid"], fe["tid"]) == (sl["pid"], sl["tid"])
        assert fe["ts"] == sl["ts"]


def test_mark_is_instant_event():
    spans.mark("serving.shed", args={"reason": "quota"})
    ev = [e for e in spans.chrome_trace_events()
          if e["name"] == "serving.shed"][-1]
    assert ev["ph"] == "i" and ev["s"] == "t" and "dur" not in ev
    assert ev["args"]["reason"] == "quota"


def test_record_span_retroactive_and_tid_override():
    import time
    t0 = time.perf_counter() - 0.25
    spans.record_span("shard.solve", t0, 0.125,
                      args={"shard": 3}, tid=1_000_003)
    rec = [r for r in spans.records()
           if r["name"] == "shard.solve"][-1]
    assert rec["tid"] == 1_000_003
    assert rec["dur"] == pytest.approx(0.125)
    # flat-timer accounting matches span() semantics
    assert spans.flat_timers()["shard.solve"][0] >= 1


def test_new_trace_ids_unique():
    ids = {spans.new_trace_id() for _ in range(100)}
    assert len(ids) == 100


def test_single_anchor_trace_yields_no_flow():
    tr = spans.new_trace_id()
    spans.mark("serving.shed", args={"trace": tr})
    flow, _ = _flow_events(tr)
    assert flow == []            # nothing to connect


# ---------------------------------------------------------------------------
# serving trace propagation
# ---------------------------------------------------------------------------


def test_request_flow_chain_connects_lifecycle(poisson16):
    svc = SolveService(_svc_cfg())
    t = svc.submit(poisson16, _rhs(poisson16, 1), tenant="acme")
    assert t.trace_id
    svc.drain(timeout_s=300)
    assert t.result.converged
    flow, tagged = _flow_events(t.trace_id)
    names = [e["name"] for e in tagged]
    # the lifecycle stages, in order: submit bookkeeping, the build
    # this (oldest unserved) ticket triggered, the retroactive queue
    # wait, the admit splice, chunk cycles, finalize, completion
    for stage in ("serving.submit", "serving.build", "serving.queue",
                  "serving.admit", "serving.step", "serving.finalize",
                  "serving.complete"):
        assert stage in names, f"missing lifecycle stage {stage}"
    assert names[0] == "serving.submit"
    assert names[-1] == "serving.complete"
    # one connected arrow chain: s ... t ... f, a single flow id
    assert len(flow) == len(tagged)
    assert flow[0]["ph"] == "s" and flow[-1]["ph"] == "f"
    assert all(e["ph"] == "t" for e in flow[1:-1])
    assert len({e["id"] for e in flow}) == 1


def test_shed_decision_on_chain_with_estimate(poisson16):
    svc = SolveService(_svc_cfg(extra="serving_max_queue=1"))
    seq0 = flightrec.last_seq()
    t1 = svc.submit(poisson16, _rhs(poisson16, 2))
    t2 = svc.submit(poisson16, _rhs(poisson16, 3))  # shed: queue bound
    assert t2.done and t2.result.status == "overloaded"
    _, tagged = _flow_events(t2.trace_id)
    assert [e["name"] for e in tagged] == ["serving.submit",
                                           "serving.shed",
                                           "serving.complete"]
    ev = flightrec.events(kind="shed", since_seq=seq0)[-1]
    assert ev["trace"] == t2.trace_id
    assert ev["reason"] == "overload"
    svc.drain(timeout_s=300)
    assert t1.result.converged


def test_deadline_miss_flight_event(poisson16):
    seq0 = flightrec.last_seq()
    svc = SolveService(_svc_cfg())
    t = svc.submit(poisson16, _rhs(poisson16, 12), deadline_s=0.0)
    svc.step()                       # queued expiry fires immediately
    assert t.done and t.result.status == "deadline_exceeded"
    ev = flightrec.events(kind="deadline.miss", since_seq=seq0)
    assert ev and ev[-1]["trace"] == t.trace_id
    assert ev[-1]["where"] == "queued"
    svc.drain(timeout_s=300)


def test_tracing_off_restores_pretracing_span_set(poisson16):
    before = {r["name"] for r in spans.records()}
    n_submit = sum(1 for r in spans.records()
                   if r["name"] == "serving.submit")
    svc = SolveService(_svc_cfg(extra="serving_tracing=0"))
    t = svc.submit(poisson16, _rhs(poisson16, 4))
    assert t.trace_id is None
    svc.drain(timeout_s=300)
    assert t.result.converged
    after = sum(1 for r in spans.records()
                if r["name"] == "serving.submit")
    assert after == n_submit     # no lifecycle spans minted
    del before


def test_crash_recovered_request_is_one_chain(poisson16, tmp_path):
    """THE acceptance: a submitted->crashed->recovered->finalized
    request yields one Perfetto trace whose flow events connect
    submit through finalize across BOTH service incarnations under a
    single trace id."""
    kr = (f"serving_journal_dir={tmp_path}, serving_checkpoint_cycles=1,"
          " serving_chunk_iters=1, s:tolerance=1e-12")
    victim = SolveService(_svc_cfg(extra=kr))
    vt = victim.submit(poisson16, _rhs(poisson16, 5),
                       request_key="trace-kr")
    orig_trace = vt.trace_id
    assert orig_trace
    for _ in range(4):
        victim.step()
    assert not vt.done           # genuinely mid-flight
    del victim                   # the "crash"
    succ = SolveService(_svc_cfg(extra=kr))   # journal replays here
    done = succ.drain(timeout_s=300)
    assert len(done) == 1 and done[0].done
    # the successor's ticket carries the ORIGINAL trace id (persisted
    # in the journal at submit)
    assert done[0].trace_id == orig_trace
    flow, tagged = _flow_events(orig_trace)
    names = [e["name"] for e in tagged]
    # incarnation 1 contributed the submit, incarnation 2 the resume
    # and the completion — all under one trace id
    assert names[0] == "serving.submit"
    assert "serving.resume" in names
    assert "serving.checkpoint" in names
    assert names[-1] == "serving.complete"
    # one connected chain: single flow id, s first, f last
    assert len(flow) >= 4
    assert flow[0]["ph"] == "s" and flow[-1]["ph"] == "f"
    assert len({e["id"] for e in flow}) == 1


def test_journal_persists_trace_id(poisson16, tmp_path):
    svc = SolveService(_svc_cfg(
        extra=f"serving_journal_dir={tmp_path}"))
    t = svc.submit(poisson16, _rhs(poisson16, 6))
    meta = svc.journal.pending()[0]
    assert meta["trace"] == t.trace_id
    svc.drain(timeout_s=300)


def test_capi_ticket_trace(poisson16):
    from amgx_tpu import capi
    assert capi.AMGX_initialize() == 0
    rc, cfg_h = capi.AMGX_config_create(
        BATCHED_CG + ", serving_bucket_slots=2")
    rc, rsrc_h = capi.AMGX_resources_create_simple(cfg_h)
    rc, svc_h = capi.AMGX_service_create(rsrc_h, "dDDI", cfg_h)
    rc, m_h = capi.AMGX_matrix_create(rsrc_h, "dDDI")
    rc, b_h = capi.AMGX_vector_create(rsrc_h, "dDDI")
    ro = np.asarray(poisson16.row_offsets)
    ci = np.asarray(poisson16.col_indices)
    v = np.asarray(poisson16.values)
    assert capi.AMGX_matrix_upload_all(
        m_h, poisson16.num_rows, v.size, 1, 1, ro, ci, v, None) == 0
    b = _rhs(poisson16, 7)
    assert capi.AMGX_vector_upload(b_h, b.size, 1, b) == 0
    rc, tkt = capi.AMGX_service_submit(svc_h, m_h, b_h, "acme", None)
    assert rc == 0
    rc, trace = capi.AMGX_ticket_trace(tkt)
    assert rc == 0 and trace        # the flow/journal correlation key
    rc, _n = capi.AMGX_service_drain(svc_h, 300)
    assert rc == 0
    # same id after completion (stable across the lifecycle)
    rc, trace2 = capi.AMGX_ticket_trace(tkt)
    assert rc == 0 and trace2 == trace
    capi.AMGX_service_ticket_destroy(tkt)
    capi.AMGX_service_destroy(svc_h)


# ---------------------------------------------------------------------------
# comms/shard telemetry
# ---------------------------------------------------------------------------


def test_ring_comms_bytes_match_hand_computed_windows():
    """The acceptance's exactness clause: on a 4-shard ring mesh the
    modeled bytes counters equal the hand-computed halo window sizes.
    poisson 5pt at 8x8 (n=64, n_local=16) has band reach 8, so each
    boundary window is 8 elements; f64 => 8 els * 8 B * 3 sending
    ranks = 192 bytes per direction per traced exchange site."""
    import jax
    from jax.sharding import Mesh
    from amgx_tpu.distributed import DistributedSolver
    mesh = Mesh(np.array(jax.devices()[:4]), ("p",))
    A = gallery.poisson("5pt", 8, 8).init()
    ds = DistributedSolver(Config.from_string(
        "config_version=2, solver(s)=CG, s:max_iters=200,"
        " s:tolerance=1e-8, s:monitor_residual=1"), mesh)
    ds.setup(A)
    f0 = metrics.get("dist.comms.bytes_fwd")
    b0 = metrics.get("dist.comms.bytes_bwd")
    c0 = metrics.get("dist.exchange.calls")
    res = ds.solve(np.ones(64))
    assert res.converged
    tbl = res.report.distributed["comms"]
    assert tbl and all(e["mode"] == "ring" for e in tbl)
    for e in tbl:
        assert e["elems_fwd"] == 8 and e["elems_bwd"] == 8
        assert e["itemsize"] == 8 and e["n_ranks"] == 4
        assert e["bytes_fwd"] == 8 * 8 * 3 == 192
        assert e["bytes_bwd"] == 192
    # the counters advanced by exactly the table's totals
    assert metrics.get("dist.comms.bytes_fwd") - f0 == \
        sum(e["bytes_fwd"] for e in tbl)
    assert metrics.get("dist.comms.bytes_bwd") - b0 == \
        sum(e["bytes_bwd"] for e in tbl)
    assert metrics.get("dist.exchange.calls") - c0 == len(tbl)
    # per-shard tallies + imbalance gauges + one track per shard
    sh = res.report.distributed["shards"]
    assert sh["rows"] == [16, 16, 16, 16]
    assert sum(sh["nnz"]) == 288          # 5pt nnz at 8x8
    assert sh["rows_imbalance"] == 1.0
    assert metrics.get("dist.shard.nnz_imbalance") == \
        sh["nnz_imbalance"]
    shard_tracks = {r["tid"] for r in spans.records()
                    if r["name"] == "shard.solve"
                    and r.get("args", {}).get("rows") == 16}
    assert len(shard_tracks) == 4         # one synthetic track each
    # the report block still validates against the schema
    from amgx_tpu.telemetry import validate_report
    assert validate_report(res.report.to_dict()) == []


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------


def test_flightrec_rotation_and_load(tmp_path):
    rec = FlightRecorder(str(tmp_path), rotate_events=5)
    for i in range(12):
        rec.record("test.ev", n=i)
    rec.close()
    evs = FlightRecorder.load(str(tmp_path))
    # generation discipline: after 12 writes at rotate=5, 6..10 live
    # in flight.log.1, 11..12 in flight.log — bounded, ordered
    assert [e["n"] for e in evs] == list(range(5, 12))
    assert os.path.exists(tmp_path / "flight.log.1")


def test_flightrec_corrupt_line_dropped(tmp_path):
    rec = FlightRecorder(str(tmp_path))
    rec.record("test.ev", n=1)
    rec.close()
    with open(tmp_path / "flight.log", "a") as f:
        f.write('{"torn": tr')      # the crash's torn final write
    d0 = metrics.get("flightrec.dropped")
    evs = FlightRecorder.load(str(tmp_path))
    assert [e["n"] for e in evs] == [1]
    assert metrics.get("flightrec.dropped") - d0 == 1


def test_flightrec_disk_mirror_survives_reopen(tmp_path):
    rec = FlightRecorder(str(tmp_path))
    rec.record("test.ev", n=1)
    rec.close()
    rec2 = FlightRecorder(str(tmp_path))     # the successor process
    rec2.record("test.ev", n=2)
    rec2.close()
    assert [e["n"] for e in FlightRecorder.load(str(tmp_path))] \
        == [1, 2]


def test_breakdown_dumps_recent_events(poisson16):
    """On a BREAKDOWN completion the last-N flight events go through
    output.py's callback — the injected build crash must be in the
    dump, naming its own cause."""
    from amgx_tpu import output
    lines = []
    output.register_print_callback(lambda msg, _n: lines.append(msg))
    try:
        svc = SolveService(_svc_cfg())     # default BUILD_FAILED>reject
        with faultinject.inject("build_crash", fires=1):
            t = svc.submit(poisson16, _rhs(poisson16, 8))
            svc.drain(timeout_s=300)
        assert t.result.status == "breakdown"
    finally:
        output.register_print_callback(None)
    text = "".join(lines)
    assert "flight recorder" in text
    assert "build_crash" in text
    assert "ticket.breakdown" in text


def test_quarantine_and_requeue_events(poisson16):
    seq0 = flightrec.last_seq()
    svc = SolveService(_svc_cfg(
        extra="serving_chunk_iters=1, s:tolerance=1e-12"))
    t = svc.submit(poisson16, _rhs(poisson16, 9))
    svc.step()
    with faultinject.inject("step_crash", fires=1):
        svc.step()
    svc.drain(timeout_s=300)
    assert t.result.converged
    kinds = [e["kind"] for e in flightrec.events(since_seq=seq0)]
    assert "bucket.quarantine" in kinds
    assert "slot.requeue" in kinds
    req = flightrec.events(kind="slot.requeue", since_seq=seq0)[-1]
    assert req["trace"] == t.trace_id     # stamped with the request


def test_resetup_routing_events(poisson16):
    seq0 = flightrec.last_seq()
    slv = amgx.create_solver(Config.from_string(
        "config_version=2, solver(s)=PCG, s:max_iters=60,"
        " s:tolerance=1e-8, s:monitor_residual=1,"
        " s:preconditioner(amg)=AMG, amg:algorithm=AGGREGATION,"
        " amg:selector=SIZE_2, amg:smoother=JACOBI_L1,"
        " amg:structure_reuse_levels=-1,"
        " amg:coarse_solver=DENSE_LU_SOLVER, amg:min_coarse_rows=16"))
    slv.setup(poisson16)
    routes = [e["route"] for e in
              flightrec.events(kind="resetup.route", since_seq=seq0)]
    assert routes[0] == "full"
    seq1 = flightrec.last_seq()
    vals = np.asarray(poisson16.values).copy() * 1.5
    slv.resetup(poisson16.with_values(vals))
    routes = [e["route"] for e in
              flightrec.events(kind="resetup.route", since_seq=seq1)]
    assert routes and routes[0] in ("value", "structure")


def test_fallback_hop_event(poisson16):
    seq0 = flightrec.last_seq()
    rs = amgx.create_solver(Config.from_string(
        "solver=CG, max_iters=200, monitor_residual=1,"
        " tolerance=1e-8, convergence=RELATIVE_INI,"
        " fallback_policy=NAN_DETECTED>retry,"
        " max_fallback_attempts=2"))
    rs.setup(poisson16)
    with faultinject.inject("spmv_nan", iteration=2, fires=1):
        res = rs.solve(np.ones(poisson16.num_rows))
    assert res.converged
    hops = flightrec.events(kind="fallback.hop", since_seq=seq0)
    assert hops and hops[0]["action"] == "retry"
    assert hops[0]["from_status"] == "NAN_DETECTED"
    # the chaos injection itself is on the trail too
    chaos = flightrec.events(kind="chaos", since_seq=seq0)
    assert any(e.get("fault") == "spmv_nan" for e in chaos)


# ---------------------------------------------------------------------------
# satellites: replica label + dead-metric lint
# ---------------------------------------------------------------------------


def test_replica_label_on_every_openmetrics_sample(poisson16):
    try:
        svc = SolveService(_svc_cfg(extra="serving_replica_id=r7"))
        t = svc.submit(poisson16, _rhs(poisson16, 10),
                       tenant="acme")
        svc.drain(timeout_s=300)
        assert t.result.converged
        om = metrics.to_openmetrics()
        samples = [ln for ln in om.splitlines()
                   if ln and not ln.startswith("#")]
        assert samples
        assert all('replica="r7"' in ln for ln in samples)
        # label-set samples keep their own labels alongside
        assert any('replica="r7"' in ln and 'tenant="acme"' in ln
                   for ln in samples)
    finally:
        metrics.set_replica_label(None)
    # cleared: back to unlabeled samples
    om = metrics.to_openmetrics()
    assert 'replica="r7"' not in om


def test_replica_label_env_default(poisson16, monkeypatch):
    import amgx_tpu.telemetry.metrics as M
    monkeypatch.setenv("AMGX_REPLICA_ID", "env-3")
    monkeypatch.setattr(M, "_replica", None)
    monkeypatch.setattr(M, "_replica_env_checked", False)
    try:
        assert M.replica_label() == "env-3"
        assert 'replica="env-3"' in metrics.to_openmetrics()
    finally:
        M.set_replica_label(None)


def _load_check_spans():
    path = os.path.join(REPO, "tools", "check_spans.py")
    spec = importlib.util.spec_from_file_location("check_spans_t", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_dead_metric_lint_catches_catalog_rot():
    mod = _load_check_spans()
    assert mod.check() == []           # the real package is clean
    from amgx_tpu.telemetry import metrics as M
    M.declare_counter("zz.dead.counter", "never incremented anywhere")
    try:
        errs = mod.check()
        assert any("dead metric" in e and "zz.dead.counter" in e
                   for e in errs)
    finally:
        del M.COUNTERS["zz.dead.counter"]
    assert mod.check() == []


def test_flow_chain_valid_in_exported_file(poisson16, tmp_path):
    """End-to-end artifact check: the exported trace file is valid
    JSON whose flow events reference slices present in the file."""
    svc = SolveService(_svc_cfg())
    t = svc.submit(poisson16, _rhs(poisson16, 11))
    svc.drain(timeout_s=300)
    path = tmp_path / "trace.json"
    n = spans.export_chrome_trace(str(path))
    doc = json.loads(path.read_text())
    assert len(doc["traceEvents"]) == n
    flows = [e for e in doc["traceEvents"]
             if e.get("cat") == "trace.flow"
             and e["args"].get("trace") == t.trace_id]
    assert flows and flows[0]["ph"] == "s"
    # BINDABILITY: every flow anchor (including the terminal 'f',
    # bp='e') needs an ENCLOSING 'X' slice on its pid/tid — instant
    # marks alone cannot bind, which is why trace-tagged marks export
    # as 1us slices
    xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    for f in flows:
        assert any(e["pid"] == f["pid"] and e["tid"] == f["tid"]
                   and e["ts"] <= f["ts"] <= e["ts"] + e["dur"]
                   for e in xs), f"unbindable flow anchor: {f}"
