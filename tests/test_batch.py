"""Batched solve subsystem tests (amgx_tpu/batch/): batched-vs-loop
parity, per-system convergence masks, request bucketing/padding, and
the single-trace acceptance contract. No reference analog — the
reference serves one matrix/RHS per solve handle (amgx_c.h)."""
import dataclasses

import jax.numpy as jnp
import numpy as np
import pytest

import amgx_tpu as amgx
from amgx_tpu import gallery, ops
from amgx_tpu.batch import (BatchedSolver, RequestBatcher,
                            pattern_fingerprint)
from amgx_tpu.batch.queue import pad_to_bucket_size
from amgx_tpu.config import Config
from amgx_tpu.errors import BadParametersError
from amgx_tpu.presets import BATCHED_CG, BATCHED_GMRES

amgx.initialize()


@pytest.fixture(scope="module")
def poisson16():
    return gallery.poisson("5pt", 16, 16).init()


def _diag_shift(A, c):
    """Same-pattern SPD perturbation: A + c*I through the values array."""
    vals = np.asarray(A.values).copy()
    vals[np.asarray(A.diag_idx)] += c
    return A.with_values(vals)


def _rhs(A, n_sys, seed=0):
    return np.random.default_rng(seed).standard_normal((n_sys, A.num_rows))


# ---------------------------------------------------------------------------
# batched-vs-loop parity
# ---------------------------------------------------------------------------


def test_multi_rhs_parity_cg_amg(poisson16):
    """Batched multi-RHS Jacobi-L1 V-cycle CG matches N sequential
    solves in iteration counts and solutions."""
    bs = BatchedSolver(Config.from_string(BATCHED_CG))
    bs.setup(poisson16)
    B = _rhs(poisson16, 4, seed=1)
    res = bs.solve_many(B)
    assert res.all_converged
    for i in range(4):
        ref = bs.solver.solve(B[i])
        assert int(res.iterations[i]) == ref.iterations
        np.testing.assert_allclose(np.asarray(res.x[i]), np.asarray(ref.x),
                                   rtol=1e-12, atol=1e-12)
        np.testing.assert_allclose(res.res_norm[i], ref.res_norm,
                                   rtol=1e-10)


def test_multi_matrix_parity(poisson16):
    """Same-pattern matrices with per-system values: batched solve
    matches the sequential resetup+solve loop (same reused hierarchy
    structure on both sides)."""
    mats = [_diag_shift(poisson16, 0.3 * i) for i in range(4)]
    bs = BatchedSolver(Config.from_string(BATCHED_CG))
    bs.setup(mats[0])
    B = _rhs(poisson16, 4, seed=2)
    res = bs.solve_many(B, matrices=mats)
    assert res.all_converged
    for i in range(4):
        bs.solver.resetup(mats[i])
        ref = bs.solver.solve(B[i])
        assert int(res.iterations[i]) == ref.iterations
        np.testing.assert_allclose(np.asarray(res.x[i]), np.asarray(ref.x),
                                   rtol=1e-12, atol=1e-12)


def test_gmres_multi_rhs_parity(poisson16):
    bs = BatchedSolver(Config.from_string(BATCHED_GMRES))
    bs.setup(poisson16)
    B = _rhs(poisson16, 3, seed=3)
    res = bs.solve_many(B)
    assert res.all_converged
    for i in range(3):
        ref = bs.solver.solve(B[i])
        assert int(res.iterations[i]) == ref.iterations
        np.testing.assert_allclose(np.asarray(res.x[i]), np.asarray(ref.x),
                                   rtol=1e-10, atol=1e-12)


def test_convergence_masks_freeze_early_systems(poisson16):
    """Systems conditioned differently converge at different iteration
    counts inside ONE batched program; each frozen system's state is
    what its solo solve would have produced."""
    mats = [_diag_shift(poisson16, c) for c in (0.0, 0.5, 4.0)]
    bs = BatchedSolver(Config.from_string(BATCHED_CG))
    bs.setup(mats[0])
    B = _rhs(poisson16, 3, seed=4)
    res = bs.solve_many(B, matrices=mats)
    assert res.all_converged
    it = res.iterations
    assert it[0] > it[2], f"shifted system should converge first: {it}"
    assert len(set(it.tolist())) > 1, f"expected distinct counts: {it}"
    for i in range(3):
        bs.solver.resetup(mats[i])
        ref = bs.solver.solve(B[i])
        assert int(it[i]) == ref.iterations
        np.testing.assert_allclose(np.asarray(res.x[i]), np.asarray(ref.x),
                                   rtol=1e-12, atol=1e-12)
        # the frozen per-system residual is the one its own stopping
        # iteration produced, not the batch's last iteration's
        np.testing.assert_allclose(res.res_norm[i], ref.res_norm,
                                   rtol=1e-10)


def test_solver_solve_many_method(poisson16):
    """Solver.solve_many: the batched entry point on any solver tree."""
    s = amgx.create_solver(Config.from_string(
        "solver=CG, max_iters=400, monitor_residual=1, tolerance=1e-10"))
    s.setup(poisson16)
    B = _rhs(poisson16, 3, seed=5)
    res = s.solve_many(B)
    assert res.all_converged
    ref = s.solve(B[1])
    assert int(res.iterations[1]) == ref.iterations
    np.testing.assert_allclose(np.asarray(res.x[1]), np.asarray(ref.x),
                               rtol=1e-12, atol=1e-12)


# ---------------------------------------------------------------------------
# acceptance: 32^3 bucket, one trace
# ---------------------------------------------------------------------------


@pytest.mark.slow     # 32^3 scale acceptance: the 16^3 multi-matrix
# parity + cache tests cover the semantics in the tier-1 budget
def test_batched_32cubed_bucket_single_trace():
    """ISSUE acceptance: solve_many over N=8 stacked 32^3 Poisson
    systems (shared pattern, perturbed values) matches sequential solves
    in iteration counts and final residuals within dtype tolerance, and
    ONE jit trace serves the bucket across repeat batches."""
    A = gallery.poisson("7pt", 32, 32, 32).init()
    mats = [_diag_shift(A, 0.15 * i) for i in range(8)]
    bs = BatchedSolver(Config.from_string(BATCHED_CG))
    bs.setup(mats[0])
    B = _rhs(A, 8, seed=6)
    res = bs.solve_many(B, matrices=mats)
    assert res.all_converged
    assert bs.trace_count == 1
    # a second batch in the same bucket (new values, same pattern)
    # reuses the trace — the serving contract
    mats2 = [_diag_shift(A, 0.1 + 0.2 * i) for i in range(8)]
    res2 = bs.solve_many(B, matrices=mats2)
    assert res2.all_converged
    assert bs.trace_count == 1, "bucket re-traced on a value-only change"
    # parity of the first batch against the sequential loop
    for i in range(0, 8, 3):
        bs.solver.resetup(mats[i])
        ref = bs.solver.solve(B[i])
        assert int(res.iterations[i]) == ref.iterations
        np.testing.assert_allclose(res.res_norm[i], ref.res_norm,
                                   rtol=1e-9)
        tr = np.linalg.norm(np.asarray(
            ops.residual(mats[i].init(), res.x[i], jnp.asarray(B[i]))))
        assert tr <= 1e-7 * np.linalg.norm(B[i])


def test_multi_matrix_rejects_trace_baking_solver(poisson16):
    """CHEBYSHEV bakes its spectrum into the trace as Python floats —
    one batched trace cannot serve per-system spectra, so multi-matrix
    batching must refuse it instead of silently using the last
    system's."""
    bs = BatchedSolver(Config.from_string(
        "solver(s)=PCG, s:max_iters=100, s:monitor_residual=1,"
        " s:tolerance=1e-8, s:preconditioner(c)=CHEBYSHEV,"
        " c:max_iters=2, c:chebyshev_lambda_estimate_mode=2,"
        " c:preconditioner=NOSOLVER"))
    bs.setup(poisson16)
    with pytest.raises(BadParametersError, match="bakes"):
        bs.solve_many(_rhs(poisson16, 2), matrices=[
            poisson16, _diag_shift(poisson16, 1.0)])


def test_batched_cache_invalidated_with_solver_traces(poisson16):
    """A resetup that invalidates the wrapped solver's traces (value-
    baking CHEBYSHEV) must also invalidate the batched wrapper's cache,
    or solve_many would replay the OLD spectrum."""
    s = amgx.create_solver(Config.from_string(
        "solver=CHEBYSHEV, max_iters=150, monitor_residual=1,"
        " tolerance=1e-8, chebyshev_lambda_estimate_mode=2"))
    s.setup(poisson16)
    B = _rhs(poisson16, 2, seed=11)
    s.solve_many(B)
    A2 = _diag_shift(poisson16, 3.0)
    s.resetup(A2)                     # re-bakes the spectrum
    res = s.solve_many(B)
    s2 = amgx.create_solver(Config.from_string(
        "solver=CHEBYSHEV, max_iters=150, monitor_residual=1,"
        " tolerance=1e-8, chebyshev_lambda_estimate_mode=2"))
    s2.setup(A2)
    for i in range(2):
        ref = s2.solve(B[i])
        assert int(res.iterations[i]) == ref.iterations
        np.testing.assert_allclose(np.asarray(res.x[i]), np.asarray(ref.x),
                                   rtol=1e-12, atol=1e-12)


def test_multi_matrix_requires_structure_reuse(poisson16):
    """Multi-matrix batching without structure_reuse_levels=-1 would
    re-coarsen per system; it must be rejected up front."""
    cfg = Config.from_string(
        BATCHED_CG.replace("amg:structure_reuse_levels=-1",
                           "amg:structure_reuse_levels=0"))
    bs = BatchedSolver(cfg)
    bs.setup(poisson16)
    with pytest.raises(BadParametersError, match="structure_reuse"):
        bs.solve_many(_rhs(poisson16, 2), matrices=[
            poisson16, _diag_shift(poisson16, 1.0)])


# ---------------------------------------------------------------------------
# multi-RHS SpMV paths
# ---------------------------------------------------------------------------


def test_spmv_multi_matches_loop():
    from amgx_tpu.ops.batched import spmv_multi
    A = gallery.poisson("5pt", 12, 12)
    X = np.random.default_rng(7).standard_normal((5, A.num_rows))
    for layout in ("auto", "always", "never"):
        M = A.init(ell=layout)
        Y = np.asarray(spmv_multi(M, jnp.asarray(X)))
        for i in range(5):
            np.testing.assert_allclose(
                Y[i], np.asarray(ops.spmv(M, jnp.asarray(X[i]))),
                rtol=1e-13, atol=1e-13)


def test_spmv_multi_layout_coverage():
    """The dispatch must actually exercise the DIA and ELL fast paths."""
    A = gallery.poisson("5pt", 12, 12)
    dia = A.init(ell="auto")
    assert dia.dia_offsets is not None
    ell = A.init(ell="always")
    assert ell.ell_cols is not None and ell.dia_offsets is None


# ---------------------------------------------------------------------------
# request batcher
# ---------------------------------------------------------------------------


def test_pattern_fingerprint(poisson16):
    other = gallery.poisson("7pt", 6, 6, 6).init()
    fp = pattern_fingerprint(poisson16)
    assert fp == pattern_fingerprint(
        poisson16.with_values(np.asarray(poisson16.values) * 3.0))
    assert fp == pattern_fingerprint(_diag_shift(poisson16, 2.0))
    assert fp != pattern_fingerprint(other)


def test_pad_ladder():
    assert [pad_to_bucket_size(n) for n in (1, 2, 3, 5, 8, 9, 31, 32, 99)] \
        == [1, 2, 4, 8, 8, 16, 32, 32, 32]


def test_request_batcher_buckets_and_pads(poisson16):
    """Mixed-pattern stream: one drain dispatches one padded batch per
    (pattern, dtype) bucket and every ticket gets its own solution."""
    other = gallery.poisson("7pt", 6, 6, 6).init()
    rb = RequestBatcher(Config.from_string(BATCHED_CG))
    rng = np.random.default_rng(8)
    reqs = [rb.submit(poisson16, rng.standard_normal(poisson16.num_rows))
            for _ in range(3)]
    reqs += [rb.submit(other, rng.standard_normal(other.num_rows))
             for _ in range(2)]
    assert rb.pending_count() == 5
    done = rb.drain()
    assert len(done) == 5 and rb.pending_count() == 0
    # two buckets; 3 requests pad to 4, 2 to 2
    sizes = sorted((real, padded) for _, real, padded in rb.dispatch_log)
    assert sizes == [(2, 2), (3, 4)]
    for r in reqs:
        assert r.done and r.result.converged
        tr = np.linalg.norm(np.asarray(
            ops.residual(r.A, r.result.x, jnp.asarray(r.b))))
        assert tr <= 1e-6 * np.linalg.norm(r.b)


def test_request_batcher_same_pattern_values_differ(poisson16):
    """Same-pattern different-values requests land in ONE bucket and run
    as a multi-matrix batch."""
    rb = RequestBatcher(Config.from_string(BATCHED_CG))
    rng = np.random.default_rng(9)
    mats = [_diag_shift(poisson16, 0.5 * i) for i in range(3)]
    reqs = [rb.submit(M, rng.standard_normal(M.num_rows)) for M in mats]
    rb.drain()
    assert len(rb.dispatch_log) == 1 and rb.dispatch_log[0][2] == 4
    for i, r in enumerate(reqs):
        assert r.result.converged
        tr = np.linalg.norm(np.asarray(
            ops.residual(mats[i], r.result.x, jnp.asarray(r.b))))
        assert tr <= 1e-6 * np.linalg.norm(r.b)
    # shifted systems are better conditioned: counts must be per-system
    its = [r.result.iterations for r in reqs]
    assert its[0] >= its[-1]


def test_request_batcher_template_not_stale_after_duplicates(poisson16):
    """Interleaved duplicate matrices in a multi-matrix dispatch leave
    the solver holding the last FIRST-seen system's values; a following
    single-matrix drain must not trust stale template bookkeeping."""
    rb = RequestBatcher(Config.from_string(BATCHED_CG))
    rng = np.random.default_rng(12)
    A1 = _diag_shift(poisson16, 5.0)
    A2 = poisson16
    for M in (A2, A1, A2):                      # duplicate interleaved
        rb.submit(M, rng.standard_normal(M.num_rows))
    rb.drain()
    b = rng.standard_normal(A2.num_rows)
    reqs = [rb.submit(A2, b), rb.submit(A2, rng.standard_normal(
        A2.num_rows))]
    rb.drain()
    # solved against A2, not the leftover A1 coefficients
    tr = np.linalg.norm(np.asarray(
        ops.residual(A2, reqs[0].result.x, jnp.asarray(b))))
    assert tr <= 1e-6 * np.linalg.norm(b)


# ---------------------------------------------------------------------------
# C-API surface
# ---------------------------------------------------------------------------


def test_capi_batched_solve(poisson16):
    from amgx_tpu import capi
    from amgx_tpu.errors import RC
    rc, cfg_h = capi.AMGX_config_create(BATCHED_CG)
    assert rc == RC.OK
    rc, rs_h = capi.AMGX_resources_create_simple(cfg_h)
    rc, m_h = capi.AMGX_matrix_create(rs_h, "dDDI")
    n = poisson16.num_rows
    assert capi.AMGX_matrix_upload_all(
        m_h, n, poisson16.nnz, 1, 1,
        np.asarray(poisson16.row_offsets), np.asarray(poisson16.col_indices),
        np.asarray(poisson16.values), None) == RC.OK
    rc, s_h = capi.AMGX_solver_create(rs_h, "dDDI", cfg_h)
    rc, b_h = capi.AMGX_vector_create(rs_h, "dDDI")
    rc, x_h = capi.AMGX_vector_create(rs_h, "dDDI")
    B = np.random.default_rng(10).standard_normal((4, n))
    assert capi.AMGX_vector_upload_batched(b_h, 4, n, 1, B) == RC.OK
    rc, nn, bd = capi.AMGX_vector_get_size(b_h)
    assert (nn, bd) == (n, 1)
    assert capi.AMGX_solver_setup(s_h, m_h) == RC.OK
    assert capi.AMGX_solver_solve_batched(s_h, b_h, x_h) == RC.OK
    rc, status = capi.AMGX_solver_get_status(s_h)
    assert (rc, status) == (RC.OK, 0)
    rc, statuses = capi.AMGX_solver_get_batch_status(s_h)
    assert rc == RC.OK and statuses.tolist() == [0, 0, 0, 0]
    rc, X = capi.AMGX_vector_download(x_h)
    assert rc == RC.OK and X.shape == (4, n)
    for i in range(4):
        tr = np.linalg.norm(np.asarray(
            ops.residual(poisson16, jnp.asarray(X[i]), jnp.asarray(B[i]))))
        assert tr <= 1e-6 * np.linalg.norm(B[i])
    # a plain (unbatched) rhs must be rejected by the batched entry
    rc, b2_h = capi.AMGX_vector_create(rs_h, "dDDI")
    capi.AMGX_vector_upload(b2_h, n, 1, B[0])
    assert capi.AMGX_solver_solve_batched(s_h, b2_h, x_h) == \
        RC.BAD_PARAMETERS


# ---------------------------------------------------------------------------
# resetup-contract satellites
# ---------------------------------------------------------------------------


def test_value_resetup_plan_rejects_ell_swell_cache():
    """amg/value_resetup.py invariant: the fused splice rewrites only
    values/dia_vals, so a hierarchy whose matrices carry ELL/SWELL
    caches must be ineligible (they would keep serving old values)."""
    from amgx_tpu.amg.value_resetup import build_plan
    from amgx_tpu.presets import FLAGSHIP
    A = gallery.poisson("7pt", 16, 16, 16).init()
    s = amgx.create_solver(Config.from_string(
        FLAGSHIP + ", amg:structure_reuse_levels=-1"))
    s.setup(A)
    amg = s.preconditioner.preconditioner.amg
    assert build_plan(amg) is not None, "flagship 16^3 should be eligible"
    lv = amg.levels[1]
    nr = lv.A.num_rows
    lv.A = dataclasses.replace(
        lv.A, ell_cols=jnp.zeros((nr, 1), jnp.int32),
        ell_vals=jnp.zeros((nr, 1), lv.A.dtype))
    assert build_plan(amg) is None, \
        "ELL cache on a level matrix must disqualify the fused splice"


def test_debug_resetup_contract_ok(monkeypatch, poisson16):
    """AMGX_TPU_DEBUG_RESETUP: a conforming solver resetups cleanly with
    the contract checks on."""
    monkeypatch.setenv("AMGX_TPU_DEBUG_RESETUP", "1")
    s = amgx.create_solver(Config.from_string(
        "solver=PCG, max_iters=200, monitor_residual=1, tolerance=1e-8,"
        " preconditioner(j)=BLOCK_JACOBI, j:max_iters=2"))
    s.setup(poisson16)
    r1 = s.solve(np.ones(poisson16.num_rows))
    s.resetup(_diag_shift(poisson16, 1.0))
    r2 = s.solve(np.ones(poisson16.num_rows))
    assert r1.converged and r2.converged
    assert r2.iterations < r1.iterations   # new values really applied


def test_debug_resetup_contract_catches_stale_solve_data(monkeypatch,
                                                         poisson16):
    """A solver that caches value-derived state outside solve_data
    violates the _resetup_kept_static contract; the debug assertion
    must catch it at resetup time."""
    from amgx_tpu.solvers.base import Solver

    class StaleDataSolver(Solver):
        def solver_setup(self):
            if not hasattr(self, "_data"):      # BUG: cached across
                self._data = {"A": self.A,      # resetups — new values
                              "dinv": 1.0 / self.A.diagonal()}  # never
                                                # reach the solve

        def solve_data(self):
            return self._data

        def computes_residual(self):
            return False

        def solve_iteration(self, data, b, st):
            out = dict(st)
            out["x"] = st["x"] + data["dinv"] * (b - ops.spmv(
                data["A"], st["x"]))
            return out

    monkeypatch.setenv("AMGX_TPU_DEBUG_RESETUP", "1")
    s = StaleDataSolver(Config.from_string("max_iters=2"), name="STALE")
    s.setup(poisson16)
    with pytest.raises(AssertionError, match="solve_data"):
        s.resetup(_diag_shift(poisson16, 1.0))
