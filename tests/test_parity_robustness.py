"""Iteration-regression + robustness harness (VERDICT round-1 item 10).

Regression table: four shipped configs run on fixed fixtures and must
reproduce the recorded iteration counts exactly. The recorded counts
are THIS FRAMEWORK'S (captured when the faithful reference preset
files were adopted) — a self-regression table, NOT verified AmgX
output: without GPU hardware the reference's counts for these fixtures
cannot be produced, and its repo publishes none for them (the only
cross-checked number is the 12-row README sample). What the table
guards is drift: a change to any selector, smoother, or convergence
component that alters convergence behavior trips these.

Robustness: NaN rhs, zero diagonal, and zero-row inputs must not hang
or crash — mirroring src/tests/smoother_nan_random.cu and the
zero_in_diagonal tests of the reference.
"""
import json
import os

import numpy as np
import pytest
import jax.numpy as jnp

import amgx_tpu as amgx
from amgx_tpu import gallery
from amgx_tpu.config import Config
from amgx_tpu.matrix import CsrMatrix
from amgx_tpu.solvers import make_solver

amgx.initialize()

_CONFIG_DIR = os.path.join(os.path.dirname(__file__), "..", "configs")

# parity table: (config file, fixture, recorded iteration count).
# Regenerate deliberately (and update here) when algorithm changes are
# intended; see docstring.
_PARITY = [
    # counts regenerated when configs/ switched to the verbatim
    # reference presets (MULTICOLOR_DILU smoother, aggressive levels,
    # reference tolerances)
    ("FGMRES_AGGREGATION.json", ("7pt", (16, 16, 16)), 7),
    ("AMG_CLASSICAL_PMIS.json", ("7pt", (16, 16, 16)), 13),
    ("PCG_CLASSICAL_V_JACOBI.json", ("7pt", (16, 16, 16)), 14),
    ("PBICGSTAB_AGGREGATION_W_JACOBI.json", ("7pt", (16, 16, 16)), 6),
]


def _run(config_name, fixture):
    stencil, dims = fixture
    A = gallery.poisson(stencil, *dims).init()
    cfg = Config.from_file(os.path.join(_CONFIG_DIR, config_name))
    slv = amgx.create_solver(cfg)
    slv.setup(A)
    b = jnp.ones(A.num_rows)
    return A, b, slv.solve(b)


@pytest.mark.parametrize("config_name,fixture,expected_iters", _PARITY)
def test_iteration_parity(config_name, fixture, expected_iters):
    A, b, res = _run(config_name, fixture)
    assert bool(res.converged), f"{config_name} did not converge"
    assert int(res.iterations) == expected_iters, (
        f"{config_name}: {int(res.iterations)} iterations, parity table "
        f"records {expected_iters} — update the table only if the "
        "algorithm change is intended")
    r = np.asarray(b) - np.asarray(amgx.ops.spmv(A, res.x))
    assert np.linalg.norm(r) / np.linalg.norm(np.asarray(b)) < 1e-5


# ---------------------------------------------------------------------
# EXTERNAL parity anchors: the two runs the reference README publishes
# verbatim (reference README.md "Running examples": examples/matrix.mtx
# with src/configs/FGMRES_AGGREGATION.json) — the only AmgX iteration
# counts published anywhere in its repo. Unlike the self-regression
# table above, these rows are cross-checked against REAL AmgX output.
# ---------------------------------------------------------------------

def _readme_system():
    from amgx_tpu.io import read_system
    A, b, _x = read_system("/root/reference/examples/matrix.mtx")
    if b is None:
        b = np.ones(A.num_rows)
    return A.init(), np.asarray(b)


@pytest.mark.skipif(
    not os.path.exists("/root/reference/examples/matrix.mtx"),
    reason="reference checkout not present")
def test_external_anchor_readme_single_device():
    """Published single-GPU run: 'Total Iterations: 1' (Final Residual
    1.6e-14). Must reproduce exactly."""
    A, b = _readme_system()
    cfg = Config.from_file(os.path.join(_CONFIG_DIR,
                                        "FGMRES_AGGREGATION.json"))
    slv = amgx.create_solver(cfg)
    slv.setup(A)
    res = slv.solve(jnp.asarray(b))
    assert bool(res.converged)
    assert int(res.iterations) == 1      # published AmgX count
    r = np.asarray(b) - np.asarray(amgx.ops.spmv(A, res.x))
    assert np.linalg.norm(r) < 1e-10


@pytest.mark.skipif(
    not os.path.exists("/root/reference/examples/matrix.mtx"),
    reason="reference checkout not present")
def test_external_anchor_readme_two_rank_distributed():
    """Published 2-rank MPI run of the SAME system and config: 'Total
    Iterations: 9' — AmgX's rank-local aggregation degrades the tiny
    hierarchy. Our distributed path preserves the single-device
    decisions (consolidation at this size), so it must converge at
    least as fast as the published 9 — and in fact matches the
    single-GPU count of 1 (documented design difference: semantic-id
    decisions make the sharded hierarchy partition-independent)."""
    from amgx_tpu.distributed import DistributedSolver, default_mesh
    A, b = _readme_system()
    cfg = Config.from_file(os.path.join(_CONFIG_DIR,
                                        "FGMRES_AGGREGATION.json"))
    d = DistributedSolver(cfg, default_mesh(2))
    d.setup(A)
    res = d.solve(b)
    assert bool(res.converged)
    assert int(res.iterations) <= 9      # published AmgX 2-rank count
    assert int(res.iterations) == 1      # our partition-independence
    r = np.asarray(b) - np.asarray(A.to_dense()) @ np.asarray(res.x)
    assert np.linalg.norm(r) < 1e-10


# ---------------------------------------------------------------------
# robustness (smoother_nan_random.cu / zero_in_diagonal analogs)
# ---------------------------------------------------------------------

def _simple_solver(extra=""):
    cfg = Config.from_string(
        "config_version=2, solver=PCG, preconditioner=BLOCK_JACOBI, "
        "max_iters=30, tolerance=1e-8, monitor_residual=1" +
        (", " + extra if extra else ""))
    return make_solver("PCG", cfg, "default")


def test_nan_rhs_does_not_hang():
    """NaN in the rhs must terminate (diverged/not-converged), not hang
    or return converged."""
    A = gallery.poisson("5pt", 12, 12).init()
    b = np.ones(144)
    b[7] = np.nan
    res = _simple_solver().setup(A).solve(jnp.asarray(b))
    assert not bool(res.converged)


def test_nan_matrix_smoothers():
    """Smoothers fed NaN coefficients must not crash (they may return
    NaN — the solver monitor then reports divergence)."""
    A = gallery.poisson("5pt", 8, 8)
    vals = np.asarray(A.values).copy()
    vals[3] = np.nan
    An = A.with_values(jnp.asarray(vals))
    An = An if An.initialized else An.init()
    for name in ["BLOCK_JACOBI", "JACOBI_L1", "GS"]:
        s = make_solver(name, Config.from_string(
            f"solver={name}, max_iters=2"), "default").setup(An)
        out = s.solve(jnp.ones(64))
        assert out.x.shape == (64,)     # no crash, shape preserved


def test_zero_in_diagonal():
    """A zero diagonal entry must not produce inf/NaN in Jacobi-family
    smoothers (guarded inverse), matching the reference's
    zero-in-diagonal robustness tests."""
    A = gallery.poisson("5pt", 8, 8)
    vals = np.asarray(A.values).copy()
    ro = np.asarray(A.row_offsets)
    ci = np.asarray(A.col_indices)
    # zero out row 5's diagonal
    for p in range(ro[5], ro[6]):
        if ci[p] == 5:
            vals[p] = 0.0
    Az = A.with_values(jnp.asarray(vals))
    Az = Az if Az.initialized else Az.init()
    for name in ["BLOCK_JACOBI", "JACOBI_L1"]:
        s = make_solver(name, Config.from_string(
            f"solver={name}, max_iters=4"), "default").setup(Az)
        out = s.solve(jnp.ones(64))
        assert np.all(np.isfinite(np.asarray(out.x)))


def test_zero_row():
    """A fully zero row (no connections at all) must not crash setup or
    produce non-finite smoother output."""
    n = 36
    A5 = gallery.poisson("5pt", 6, 6)
    rows, cols, vals = [np.asarray(v) for v in A5.init().coo()]
    keep = rows != 17
    Az = CsrMatrix.from_coo(rows[keep], cols[keep], vals[keep],
                            n, n).init()
    s = _simple_solver().setup(Az)
    out = s.solve(jnp.ones(n))
    assert out.x.shape == (n,)


def test_singular_system_reports_nonconvergence():
    """An all-zero matrix cannot converge on a nonzero rhs; the solver
    must terminate with converged=False (capi_graceful_failure role)."""
    n = 16
    Az = CsrMatrix.from_coo(np.arange(n), np.arange(n), np.zeros(n),
                            n, n).init()
    res = _simple_solver().setup(Az).solve(jnp.ones(n))
    assert not bool(res.converged)
