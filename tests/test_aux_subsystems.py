"""Auxiliary subsystems: tracing/profiling, permute/sort/analysis
kernels, determinism checker, complex->real ERF conversion (SURVEY §5 /
§2.1 items 10, 14, 15, 60, 61)."""
import numpy as np
import pytest
import jax.numpy as jnp

import amgx_tpu as amgx
from amgx_tpu import gallery, profiling
from amgx_tpu.config import Config
from amgx_tpu.determinism import (DeterminismChecker, DeterminismError,
                                  fingerprint)
from amgx_tpu.matrix import CsrMatrix
from amgx_tpu.ops.permute import (analyze_matrix, permute_matrix,
                                  permute_vector, sort_rows_by)
from amgx_tpu.solvers import make_solver

amgx.initialize()


# -- profiling ---------------------------------------------------------

def test_trace_regions_accumulate():
    profiling.reset_timers()
    A = gallery.poisson("5pt", 8, 8).init()
    s = make_solver("PCG", Config.from_string(
        "solver=PCG, max_iters=5, preconditioner=BLOCK_JACOBI"),
        "default").setup(A)
    s.solve(jnp.ones(64))
    t = profiling.timers()
    assert any(k.endswith(".setup") for k in t)
    assert any(k.endswith(".solve") for k in t)
    rpt = profiling.format_timers()
    assert "calls" in rpt and "PCG.solve" in rpt
    profiling.reset_timers()
    assert profiling.timers() == {}


# -- permute / analysis ------------------------------------------------

def test_symmetric_permute_preserves_spectrum():
    A = gallery.poisson("5pt", 6, 6).init()
    n = A.num_rows
    rng = np.random.default_rng(0)
    perm = jnp.asarray(rng.permutation(n), jnp.int32)
    B = permute_matrix(A, row_perm=perm, col_perm=perm).init()
    Ad = np.asarray(A.to_dense())
    Bd = np.asarray(B.to_dense())
    p = np.asarray(perm)
    np.testing.assert_allclose(Bd, Ad[np.ix_(p, p)], atol=0)
    # vector permute consistency: (PAP^T)(Px) = P(Ax)
    x = rng.standard_normal(n)
    lhs = np.asarray(amgx.ops.spmv(B, permute_vector(jnp.asarray(x), perm)))
    rhs = np.asarray(permute_vector(amgx.ops.spmv(A, jnp.asarray(x)), perm))
    np.testing.assert_allclose(lhs, rhs, rtol=1e-12)


def test_sort_rows_by():
    A = gallery.poisson("5pt", 4, 4).init()
    key = -jnp.arange(16.0)          # reversal
    B, perm = sort_rows_by(A, key)
    np.testing.assert_array_equal(np.asarray(perm), np.arange(15, -1, -1))


def test_analyze_matrix():
    A = gallery.poisson("5pt", 8, 8).init()
    info = analyze_matrix(A)
    assert info.is_structurally_symmetric and info.is_symmetric
    assert info.diag_dominant_rows == 64          # Poisson: weakly dominant
    assert info.bandwidth == 8
    assert not info.has_zero_diag
    assert info.min_row_nnz == 3 and info.max_row_nnz == 5
    # asymmetric matrix detected
    B = CsrMatrix.from_coo(np.array([0, 0, 1]), np.array([0, 1, 1]),
                           np.array([2.0, -1.0, 2.0]), 2, 2).init()
    info2 = analyze_matrix(B)
    assert not info2.is_structurally_symmetric


# -- determinism checker ----------------------------------------------

def test_determinism_checker_pass_and_fail():
    chk = DeterminismChecker()
    A = gallery.poisson("5pt", 8, 8).init()
    s = make_solver("PCG", Config.from_string(
        "solver=PCG, max_iters=8, preconditioner=BLOCK_JACOBI"),
        "default").setup(A)
    b = jnp.ones(64)
    r1 = s.solve(b)
    chk.observe("x", r1.x)
    chk.start_verification()
    r2 = s.solve(b)
    chk.observe("x", r2.x)      # bit-exact repeat must pass
    chk.finish()
    # drift is caught
    chk2 = DeterminismChecker()
    chk2.observe("x", r1.x)
    chk2.start_verification()
    drift = np.asarray(r1.x).copy()
    drift[0] = np.nextafter(drift[0], np.inf)   # one-ulp drift
    with pytest.raises(DeterminismError):
        chk2.observe("x", drift)
    assert fingerprint(r1.x) == fingerprint(np.asarray(r1.x))


# -- complex -> real ERF ----------------------------------------------

def _random_complex_system(n=24, seed=0):
    rng = np.random.default_rng(seed)
    A5 = gallery.poisson("5pt", 6, 4)
    rows, cols, _ = [np.asarray(v) for v in A5.init().coo()]
    vals = rng.standard_normal(rows.size) + 1j * rng.standard_normal(
        rows.size)
    # make it solvable: diagonally dominant complex
    vals[rows == cols] = 8.0 + 2.0j
    A = CsrMatrix.from_coo(rows, cols, jnp.asarray(vals), n, n)
    z = rng.standard_normal(n) + 1j * rng.standard_normal(n)
    return A.init(), jnp.asarray(z)


@pytest.mark.parametrize("mode", [1, 2, 3, 4, 221, 222, 223, 224])
def test_erf_conversion_matches_dense_form(mode):
    """Every K-form reproduces its dense equivalent exactly, and the
    converted system is consistent: M x_erf = b_erf for the known
    complex solution."""
    from amgx_tpu.io.complex import complex_system_to_real
    A, zsol = _random_complex_system()
    Ad = np.asarray(A.to_dense())
    b = Ad @ np.asarray(zsol)
    A2, b2, x2 = complex_system_to_real(A, b, zsol, mode=mode)
    M = np.asarray(A2.init().to_dense())
    R, I = np.real(Ad), np.imag(Ad)
    forms = {1: np.block([[R, -I], [I, R]]),
             2: np.block([[R, I], [I, -R]]),
             3: np.block([[I, R], [R, -I]]),
             4: np.block([[I, -R], [R, I]])}
    m0 = mode - 220 if mode > 220 else mode
    ref = forms[m0]
    if mode > 220:
        n = Ad.shape[0]
        p = np.arange(2 * n).reshape(2, n).T.ravel()   # interleave blocks
        ref = ref[np.ix_(p, p)]
    np.testing.assert_allclose(M, ref, atol=0)
    # consistency: the converted solution solves the converted system
    np.testing.assert_allclose(M @ np.asarray(x2), np.asarray(b2),
                               rtol=1e-12, atol=1e-12)


def test_erf_k1_end_to_end_solve():
    """Solve the K1 real system and recover the complex solution."""
    from amgx_tpu.io.complex import (complex_system_to_real,
                                     real_solution_to_complex)
    A, zsol = _random_complex_system()
    b = np.asarray(A.to_dense()) @ np.asarray(zsol)
    A2, b2, _ = complex_system_to_real(A, b, None, mode=1)
    solver = make_solver("FGMRES", Config.from_string(
        "solver=FGMRES, max_iters=300, gmres_n_restart=60, "
        "tolerance=1e-12, monitor_residual=1, "
        "convergence=RELATIVE_INI_CORE"), "default").setup(A2.init())
    res = solver.solve(b2)
    z = np.asarray(real_solution_to_complex(res.x, mode=1))
    np.testing.assert_allclose(z, np.asarray(zsol), rtol=1e-7, atol=1e-8)


def test_capi_complex_read(tmp_path):
    """A complex MatrixMarket file + complex_conversion config reads as
    the ERF real system through the C API (readers.cu:221 analog)."""
    from amgx_tpu import capi
    from amgx_tpu.io import write_system
    A, zsol = _random_complex_system()
    b = np.asarray(A.to_dense()) @ np.asarray(zsol)
    p = str(tmp_path / "c.mtx")
    write_system(p, A, b=jnp.asarray(b))
    assert capi.AMGX_initialize() == capi.RC.OK
    rc, cfg = capi.AMGX_config_create(
        "config_version=2, solver=FGMRES, complex_conversion=1")
    rc, rsc = capi.AMGX_resources_create_simple(cfg)
    rc, mh = capi.AMGX_matrix_create(rsc, "dDDI")
    rc, bh = capi.AMGX_vector_create(rsc, "dDDI")
    assert capi.AMGX_read_system(mh, bh, None, p) == capi.RC.OK
    rc, n, bx, by = capi.AMGX_matrix_get_size(mh)
    assert n == 48 and bx == 1      # 2n scalar ERF
    capi.AMGX_finalize()


def test_convergence_analysis_report():
    """convergence_analysis=k runs the instrumented error-propagation
    cycle (convergence_analysis.cu analog) and reports per-level phase
    reductions; smoothing and the full cycle must actually reduce the
    error on Poisson."""
    from amgx_tpu.amg.hierarchy import AMG
    from amgx_tpu.amg.analysis import convergence_analysis
    from amgx_tpu.config import Config
    from amgx_tpu import gallery
    cfg = Config.from_string(
        "algorithm=AGGREGATION, selector=SIZE_2, smoother=BLOCK_JACOBI,"
        " relaxation_factor=0.9, presweeps=1, postsweeps=1,"
        " coarse_solver=DENSE_LU_SOLVER, min_coarse_rows=16,"
        " convergence_analysis=2")
    amg = AMG(cfg)
    amg.setup(gallery.poisson("7pt", 10, 10, 10).init())
    report = convergence_analysis(amg)
    lines = [ln for ln in report.splitlines()[2:] if ln.strip()]
    assert len(lines) == 2          # two instrumented levels
    for ln in lines:
        cols = ln.split()
        pre, total = float(cols[2]), float(cols[5])
        assert pre < 1.0 and total < 1.0
