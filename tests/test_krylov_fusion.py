"""Krylov-shell fusion test suite (solvers/krylov.py fused iterations,
ops/spmv.spmv_pdot / spmv_ddot, ops/blas.cg_update / psum_bundle, the
cycle-borne r.z dot through amg/cycles.run_cycle_dot).

Kernels run through the Pallas interpreter (force_pallas_interpret, the
CPU test path); the compiled path runs on real TPU via bench.py.
Covers: iterate-for-iterate parity of the fused shell against the
unfused SpMV + BLAS-1 composition for CG/PCG/PCGF/BiCGStab/PBiCGStab
(f32 through the kernels, f64 through the exact-expression XLA
fallback); the jaxpr census gate — a fused-hierarchy PCG iteration is
the cycle's fused kernels plus EXACTLY two shell kernels with zero
standalone full-vector reductions, and `krylov_fusion=0` emits a jaxpr
identical to the pre-fusion composition; the CG dead-norm regression
(internal_res_norm kills the monitor's standalone blas.norm(r) pass on
BOTH routes); the GMRES CGS2 projection vs the sequential MGS loop at
1e-12 f64; solve_many slab-route parity; the pAp <= 0 breakdown read
from the kernel epilogue scalar; and the distributed packed-psum
contract — parity on a multi-shard mesh with the per-iteration
collective count independent of how many dots the method needs."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

import amgx_tpu as amgx
from amgx_tpu import gallery
from amgx_tpu.batch import BatchedSolver
from amgx_tpu.config import Config
from amgx_tpu.distributed import DistributedSolver, default_mesh
from amgx_tpu.ops import blas
from amgx_tpu.ops import pallas_spmv as ps
from amgx_tpu.ops.spmv import spmv
from amgx_tpu.resilience import SolveStatus

import _census

amgx.initialize()


BASE = ("solver(s)={name}, s:max_iters=25, s:tolerance=1e-8,"
        " s:convergence=RELATIVE_INI, s:monitor_residual=1,"
        " s:store_res_history=1")
AMG_PRE = (", s:preconditioner(amg)=AMG, amg:algorithm=AGGREGATION,"
           " amg:selector=GEO, amg:smoother=JACOBI_L1, amg:presweeps=2,"
           " amg:postsweeps=1, amg:max_iters=1,"
           " amg:coarse_solver=DENSE_LU_SOLVER, amg:min_coarse_rows=16,"
           " amg:max_levels=10")


def _solve(name, pre, n=10, dtype=jnp.float32, fusion=1, extra=""):
    A = gallery.poisson("7pt", n, n, n, dtype=dtype).init()
    rng = np.random.default_rng(1)
    b = jnp.asarray(rng.standard_normal(A.num_rows), dtype)
    cfg = (BASE.format(name=name) + (AMG_PRE if pre else "")
           + f", s:krylov_fusion={fusion}" + extra)
    with ps.force_pallas_interpret():
        slv = amgx.create_solver(Config.from_string(cfg))
        slv.setup(A)
        return slv.solve(b)


SOLVERS = [("CG", False), ("PCG", True), ("PCGF", True),
           ("BICGSTAB", False), ("PBICGSTAB", True)]


# ---------------------------------------------------------------------------
# fused-vs-unfused parity (iterate-for-iterate)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("name,pre", SOLVERS)
def test_parity_f32_kernels(name, pre):
    """Fused shell kernels (interpret) vs the unfused composition:
    identical iteration counts / statuses, matching iterates and
    residual histories within f32 reassociation noise."""
    r1 = _solve(name, pre, dtype=jnp.float32, fusion=1)
    r0 = _solve(name, pre, dtype=jnp.float32, fusion=0)
    assert int(r1.iterations) == int(r0.iterations)
    assert r1.status_code == r0.status_code
    xrel = float(jnp.linalg.norm(r1.x - r0.x) /
                 jnp.linalg.norm(r0.x))
    assert xrel < 1e-4, xrel
    it = int(r1.iterations)
    h1 = np.asarray(r1.res_history)[:it + 1]
    h0 = np.asarray(r0.res_history)[:it + 1]
    # absolute floor scaled by norm0: near-stagnation tail entries are
    # ~1e-5 * norm0 where f32 reassociation noise dominates relatively
    np.testing.assert_allclose(h1, h0, rtol=1e-3, atol=1e-4 * h0[0])


@pytest.mark.parametrize("name,pre", SOLVERS)
def test_parity_f64_exact(name, pre):
    """f64 declines the kernels into the XLA fallback, whose
    expressions are the unfused composition verbatim — iterates must
    match to the last bit (well under the 1e-12 acceptance bar)."""
    r1 = _solve(name, pre, dtype=jnp.float64, fusion=1)
    r0 = _solve(name, pre, dtype=jnp.float64, fusion=0)
    assert int(r1.iterations) == int(r0.iterations)
    assert r1.status_code == r0.status_code
    np.testing.assert_allclose(np.asarray(r1.x), np.asarray(r0.x),
                               rtol=1e-12, atol=1e-14)
    it = int(r1.iterations)
    np.testing.assert_allclose(
        np.asarray(r1.res_history)[:it + 1],
        np.asarray(r0.res_history)[:it + 1], rtol=1e-12)


# ---------------------------------------------------------------------------
# jaxpr census: the fused iteration's kernel inventory
# ---------------------------------------------------------------------------


def _pcg_iteration_jaxpr(fusion=1, n=16):
    """Trace ONE PCG iteration on a fused GEO/DIA hierarchy sized so
    the whole cycle collapses into the VMEM coarse-tail kernel (which
    then must carry the cycle-borne r.z epilogue)."""
    A = gallery.poisson("7pt", n, n, n, dtype=jnp.float32).init()
    b = jnp.ones(A.num_rows, jnp.float32)
    cfg = (BASE.format(name="PCG") + AMG_PRE
           + f", s:krylov_fusion={fusion}")
    with ps.force_pallas_interpret():
        slv = amgx.create_solver(Config.from_string(cfg))
        slv.setup(A)
        d = slv.solve_data()
        st = {"x": jnp.zeros_like(b), "r": b}
        st.update(slv.solve_init(d, b, jnp.zeros_like(b), b))
        jaxpr = jax.make_jaxpr(
            lambda dd, ss: slv.solve_iteration(dd, b, ss))(d, st)
    return jaxpr, A.num_rows


def test_census_fused_pcg_iteration():
    """The fused-hierarchy PCG iteration = the cycle's fused kernels +
    EXACTLY two shell kernels, with ZERO standalone full-vector
    reductions outside the kernels (every dot is an epilogue)."""
    jaxpr, n = _pcg_iteration_jaxpr(fusion=1)
    counts = _census.kernel_counts(jaxpr)
    assert counts == {"_dia_spmv_dot_call": 1, "_cg_update_call": 1,
                      "_dia_coarse_tail_call": 1}, counts
    hits = _census.full_vector_reductions(jaxpr, n)
    assert hits == [], hits


def test_census_unfused_pcg_iteration():
    """krylov_fusion=0: no shell kernels anywhere in the trace; the
    iteration is the plain SpMV kernel + the cycle's tail kernel with
    the dots as standalone XLA reductions."""
    jaxpr, n = _pcg_iteration_jaxpr(fusion=0)
    counts = _census.kernel_counts(jaxpr)
    assert counts == {"_dia_spmv_call": 1,
                      "_dia_coarse_tail_call": 1}, counts
    s = str(jaxpr)
    assert "_dia_spmv_dot_call" not in s
    assert "_cg_update_call" not in s
    # the unfused composition's standalone dots ARE there (pAp and
    # r.z; the direction/iterate updates run as XLA ops)
    assert len(_census.full_vector_reductions(jaxpr, n)) == 2


# ---------------------------------------------------------------------------
# krylov_fusion=0 is the pre-fusion composition, jaxpr-identical
# ---------------------------------------------------------------------------


def _setup_solver(name, pre, n=10, dtype=jnp.float64, fusion=0):
    A = gallery.poisson("7pt", n, n, n, dtype=dtype).init()
    cfg = (BASE.format(name=name) + (AMG_PRE if pre else "")
           + f", s:krylov_fusion={fusion}")
    slv = amgx.create_solver(Config.from_string(cfg))
    slv.setup(A)
    return slv, A


def test_knob_off_jaxpr_identical_cg():
    """krylov_fusion=0 CG emits a jaxpr identical to the pre-fusion
    iteration written out by hand (the escape hatch is bit-for-bit,
    not merely numerically close)."""
    from amgx_tpu.solvers.krylov import _safe_div
    slv, A = _setup_solver("CG", False)
    d = slv.solve_data()
    b = jnp.ones(A.num_rows)
    st = {"x": jnp.zeros_like(b), "r": b, "p": b,
          "rz": jnp.asarray(float(b @ b)),
          "breakdown": jnp.asarray(False)}

    def reference(data, st):
        # the pre-fusion CG iteration, verbatim
        A = data["A"]
        x, r, p, rz = st["x"], st["r"], st["p"], st["rz"]
        Ap = spmv(A, p)
        pAp = blas.dot(p, Ap)
        alpha = _safe_div(rz, pAp)
        x = x + alpha * p
        r = r - alpha * Ap
        rz_new = blas.dot(r, r)
        beta = _safe_div(rz_new, rz)
        p = r + beta * p
        out = {**st, "x": x, "r": r, "p": p, "rz": rz_new}
        out["breakdown"] = pAp <= 0
        return out

    got = str(jax.make_jaxpr(
        lambda dd, ss: slv.solve_iteration(dd, b, ss))(d, st))
    want = str(jax.make_jaxpr(reference)(d, st))
    assert got == want


def test_knob_off_jaxpr_identical_pcg():
    slv, A = _setup_solver("PCG", True)
    from amgx_tpu.solvers.krylov import _safe_div
    d = slv.solve_data()
    b = jnp.ones(A.num_rows)
    st = {"x": jnp.zeros_like(b), "r": b, "p": b, "z": b,
          "rz": jnp.asarray(float(b @ b)),
          "breakdown": jnp.asarray(False)}

    def reference(data, st):
        A = data["A"]
        x, r, p, rz = st["x"], st["r"], st["p"], st["rz"]
        Ap = spmv(A, p)
        pAp = blas.dot(p, Ap)
        alpha = _safe_div(rz, pAp)
        x = x + alpha * p
        r = r - alpha * Ap
        z = slv.preconditioner.apply(data["precond"], r)
        rz_new = blas.dot(r, z)
        beta = _safe_div(rz_new, rz)
        p = z + beta * p
        out = {**st, "x": x, "r": r, "p": p, "z": z, "rz": rz_new}
        out["breakdown"] = pAp <= 0
        return out

    got = str(jax.make_jaxpr(
        lambda dd, ss: slv.solve_iteration(dd, b, ss))(d, st))
    want = str(jax.make_jaxpr(reference)(d, st))
    assert got == want


# ---------------------------------------------------------------------------
# satellite: CG's monitor norm is dead code (internal_res_norm)
# ---------------------------------------------------------------------------


def _cg_solve_reduction_count(fusion, n=10):
    """Full-vector reductions in the WHOLE traced CG solve (init +
    while-loop body), f32 DIA through the kernels."""
    A = gallery.poisson("7pt", n, n, n, dtype=jnp.float32).init()
    b = jnp.ones(A.num_rows, jnp.float32)
    cfg = BASE.format(name="CG") + f", s:krylov_fusion={fusion}"
    with ps.force_pallas_interpret():
        slv = amgx.create_solver(Config.from_string(cfg))
        slv.setup(A)
        fn = slv._build_solve_fn(diag=False)
        jaxpr = jax.make_jaxpr(fn)(slv.solve_data(), b,
                                   jnp.zeros_like(b))
    return _census.full_vector_reductions(jaxpr, A.num_rows)


def test_cg_monitor_norm_dead():
    """CG's rz IS the monitored ||r||^2, so the driver's standalone
    per-iteration blas.norm(r) is dead code on BOTH routes.

    Census over the whole solve trace: fused = the two init-time
    reductions only (norm0 + the seed r.r dot — the loop body is all
    epilogues); unfused = those two + the body's pAp and r.r dots.
    Before this PR the unfused body also traced the monitor's norm
    reduction (5 total); 4 proves it DCE'd away."""
    assert len(_cg_solve_reduction_count(fusion=1)) == 2
    assert len(_cg_solve_reduction_count(fusion=0)) == 4


# ---------------------------------------------------------------------------
# satellite: GMRES CGS2 projection vs the sequential MGS loop (f64)
# ---------------------------------------------------------------------------


def test_gmres_cgs2_matches_sequential_mgs_f64():
    """The batched CGS2 projection (two blas.mdot matvec pairs — the
    solver's Arnoldi step, solvers/gmres.py) agrees with the
    reference's sequential MGS loop to 1e-12 in f64 on both the
    Hessenberg coefficients and the deflated vector."""
    rng = np.random.default_rng(7)
    n, m, j = 500, 10, 6
    Q, _ = np.linalg.qr(rng.standard_normal((n, j)))
    V = jnp.zeros((m + 1, n), jnp.float64).at[:j].set(Q.T)
    w0 = jnp.asarray(rng.standard_normal(n))

    # solver expressions (gmres.py solve_iteration), zero rows no-ops
    h = blas.mdot(V, w0)
    w = w0 - V.T @ h
    h2 = blas.mdot(V, w)
    w = w - V.T @ h2
    h = h + h2

    # sequential modified Gram-Schmidt (the reference's fgmres loop)
    w_ref = np.asarray(w0, np.float64)
    h_ref = np.zeros(m + 1)
    for i in range(j):
        h_ref[i] = np.dot(Q.T[i], w_ref)
        w_ref = w_ref - h_ref[i] * Q.T[i]

    scale = float(jnp.linalg.norm(w0))
    np.testing.assert_allclose(np.asarray(h), h_ref, rtol=0,
                               atol=1e-12 * scale)
    np.testing.assert_allclose(np.asarray(w), w_ref, rtol=0,
                               atol=1e-12 * scale)


def test_gmres_solve_parity_f64():
    """End-to-end: fused-shell knob is a no-op for GMRES (its shell is
    the CGS2 panel, not the CG kernels) — knob 1 vs 0 bit-identical."""
    r1 = _solve("GMRES", True, dtype=jnp.float64, fusion=1,
                extra=", s:gmres_n_restart=15")
    r0 = _solve("GMRES", True, dtype=jnp.float64, fusion=0,
                extra=", s:gmres_n_restart=15")
    assert int(r1.iterations) == int(r0.iterations)
    np.testing.assert_allclose(np.asarray(r1.x), np.asarray(r0.x),
                               rtol=1e-12, atol=1e-14)


# ---------------------------------------------------------------------------
# batched solve_many rides the slab forms
# ---------------------------------------------------------------------------


def test_solve_many_fused_parity_f32():
    """vmapped fused CG routes the shell kernels to the ops/batched.py
    slab forms; batched-vs-unfused-batched parity plus per-system
    agreement with solo fused solves."""
    A = gallery.poisson("7pt", 8, 8, 8, dtype=jnp.float32).init()
    B = np.random.default_rng(3).standard_normal((3, A.num_rows))
    B = B.astype(np.float32)

    def run(fusion):
        cfg = Config.from_string(
            "solver(s)=PCG, s:max_iters=40, s:tolerance=1e-6,"
            " s:convergence=RELATIVE_INI, s:monitor_residual=1,"
            " s:preconditioner(amg)=AMG, amg:algorithm=AGGREGATION,"
            " amg:selector=SIZE_2, amg:smoother=JACOBI_L1,"
            " amg:presweeps=1, amg:postsweeps=1, amg:max_iters=1,"
            " amg:coarse_solver=DENSE_LU_SOLVER,"
            " amg:min_coarse_rows=32, amg:max_levels=10,"
            " amg:structure_reuse_levels=-1,"
            f" s:krylov_fusion={fusion}")
        with ps.force_pallas_interpret():
            bs = BatchedSolver(cfg)
            bs.setup(A)
            res = bs.solve_many(B)
            solo = [bs.solver.solve(B[i]) for i in range(B.shape[0])]
        return res, solo

    r1, solo1 = run(1)
    r0, _ = run(0)
    assert r1.all_converged
    for i in range(B.shape[0]):
        assert int(r1.iterations[i]) == int(r0.iterations[i])
        assert int(r1.iterations[i]) == int(solo1[i].iterations)
        np.testing.assert_allclose(np.asarray(r1.x[i]),
                                   np.asarray(solo1[i].x),
                                   rtol=2e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(r1.x[i]),
                                   np.asarray(r0.x[i]),
                                   rtol=2e-4, atol=1e-5)


# ---------------------------------------------------------------------------
# health guards read the epilogue scalar
# ---------------------------------------------------------------------------


def test_breakdown_from_epilogue_scalar():
    """Indefinite DIA system, f32 through the kernels: the pAp <= 0
    breakdown check reads the SpMV kernel's epilogue scalar and exits
    with the same status/iteration as the unfused composition."""
    n = 256
    d = np.ones(n, np.float32)
    d[::2] = -1.0
    rows = np.repeat(np.arange(n), 3)[1:-1]
    cols = np.clip(rows + np.tile([-1, 0, 1], n)[1:-1], 0, n - 1)
    vals = np.where(rows == cols, d[rows], np.float32(0.1))
    import scipy.sparse as sp
    Asp = sp.csr_matrix((vals, (rows, cols)), shape=(n, n))
    A = amgx.CsrMatrix.from_scipy_like(
        Asp.indptr, Asp.indices, Asp.data.astype(np.float32),
        n, n).init()
    assert A.dia_vals is not None  # tridiagonal -> DIA layout

    def run(fusion):
        cfg = Config.from_string(
            "solver(s)=CG, s:max_iters=30, s:tolerance=1e-10,"
            " s:convergence=RELATIVE_INI, s:monitor_residual=1,"
            f" s:krylov_fusion={fusion}")
        with ps.force_pallas_interpret():
            slv = amgx.create_solver(cfg)
            slv.setup(A)
            return slv.solve(np.ones(n, np.float32))

    r1, r0 = run(1), run(0)
    assert r1.status_code == SolveStatus.BREAKDOWN
    assert r0.status_code == SolveStatus.BREAKDOWN
    assert int(r1.iterations) == int(r0.iterations)
    assert np.all(np.isfinite(np.asarray(r1.x)))


# ---------------------------------------------------------------------------
# distributed: packed psum bundles
# ---------------------------------------------------------------------------


def _dist_cfg(name, fusion):
    return Config.from_string(
        f"solver={name}, max_iters=120, tolerance=1e-8,"
        " convergence=RELATIVE_INI, monitor_residual=1,"
        " preconditioner(j)=JACOBI_L1, j:max_iters=2,"
        f" krylov_fusion={fusion}")


@pytest.mark.parametrize("name", ["PCG", "PCGF"])
def test_dist_fused_parity(name):
    """Fused shell on a multi-shard mesh (local dots + packed psum
    bundles) matches the single-device fused solve and the unfused
    distributed composition: same iteration counts, same solution."""
    A = gallery.poisson("7pt", 8, 8, 24)
    b = np.ones(A.num_rows)
    ds = DistributedSolver(_dist_cfg(name, 1), default_mesh(4))
    ds.setup(A)
    res_d = ds.solve(b)
    ds0 = DistributedSolver(_dist_cfg(name, 0), default_mesh(4))
    ds0.setup(A)
    res_d0 = ds0.solve(b)
    s = amgx.solvers.make_solver(name, _dist_cfg(name, 1))
    s.setup(A.init())
    res_s = s.solve(jnp.asarray(b))
    assert res_d.converged
    assert res_d.iterations == res_s.iterations == res_d0.iterations
    np.testing.assert_allclose(np.asarray(res_d.x), np.asarray(res_s.x),
                               rtol=1e-8, atol=1e-10)
    np.testing.assert_allclose(np.asarray(res_d.x),
                               np.asarray(res_d0.x),
                               rtol=1e-8, atol=1e-10)


def _dist_psum_count(name, fusion):
    """psum eqns in the traced distributed solve program."""
    from amgx_tpu._compat import shard_map
    from amgx_tpu.distributed import comms
    from jax.sharding import PartitionSpec as P
    A = gallery.poisson("7pt", 8, 8, 24)
    ds = DistributedSolver(_dist_cfg(name, fusion), default_mesh(4))
    ds.setup(A)
    raw = ds.solver._build_solve_fn(diag=False)
    axis = ds.axis

    def shard_fn(data, b, x0):
        local = jax.tree.map(lambda a: a[0], data)
        with comms.collective_axis(axis):
            x, stats = raw(local, b[0], x0[0])
        return x[None], stats

    pspec = jax.tree.map(lambda _: P(axis), ds._data)
    mapped = shard_map(shard_fn, mesh=ds.mesh,
                       in_specs=(pspec, P(axis), P(axis)),
                       out_specs=(P(axis), P()), check_vma=False)
    R, nl = ds.n_ranks, ds.part.n_local
    dt = ds.shard_A.dtype
    s = str(jax.make_jaxpr(mapped)(ds._data, jnp.ones((R, nl), dt),
                                   jnp.zeros((R, nl), dt)))
    return s.count("psum")


def test_dist_collective_count_independent_of_dots():
    """The packed-bundle contract: fused PCGF needs one MORE dot per
    iteration than fused PCG (the Polak-Ribiere numerator) yet traces
    the SAME number of psum collectives — extra scalars ride existing
    bundles. The unfused PCGF composition psums every dot separately
    (plus the monitor's norm), so it must trace strictly more."""
    pcg_f = _dist_psum_count("PCG", 1)
    pcgf_f = _dist_psum_count("PCGF", 1)
    pcgf_u = _dist_psum_count("PCGF", 0)
    assert pcgf_f == pcg_f, (pcgf_f, pcg_f)
    assert pcgf_f < pcgf_u, (pcgf_f, pcgf_u)
