"""Fleet router + mixed bucket-width ladder tests
(amgx_tpu/serving/fleet.py, ladder.py): fingerprint-affine routing
(stickiness, least-loaded cold placement, overload spill with a
handoff flight event, quarantine spill with rehoming), fleet-wide
deadline-infeasibility consults over merged per-replica metrics,
drain-all-terminal under an injected replica build crash, trace-chain
replica attribution, the replica-label collision regression
(auto-assigned ids + metrics.merge_snapshots), ladder width selection
and per-width AOT-key separation, and the AMGX_fleet_* capi surface.
No reference analog — AMGX ships no multi-replica router; the fleet
layer is new."""
import numpy as np
import pytest

import amgx_tpu as amgx
from amgx_tpu import gallery
from amgx_tpu.batch.queue import pattern_fingerprint
from amgx_tpu.config import Config
from amgx_tpu.errors import BadParametersError
from amgx_tpu.presets import BATCHED_CG
from amgx_tpu.resilience import faultinject
from amgx_tpu.resilience.status import SolveStatus
from amgx_tpu.serving import (AotStore, BucketEngine, FleetRouter,
                              SolveService, choose_slots, parse_ladder)
from amgx_tpu.serving.fleet import _rendezvous_score
from amgx_tpu.telemetry import flightrec as _frec
from amgx_tpu.telemetry import metrics
from amgx_tpu.telemetry import spans as _spans

amgx.initialize()


@pytest.fixture(scope="module")
def poisson16():
    return gallery.poisson("5pt", 16, 16).init()


@pytest.fixture(scope="module")
def poisson14():
    return gallery.poisson("5pt", 14, 14).init()


def _shift(A, c):
    vals = np.asarray(A.values).copy()
    vals[np.asarray(A.diag_idx)] += c
    return A.with_values(vals)


def _rhs(A, seed=0):
    return np.random.default_rng(seed).standard_normal(A.num_rows)


def _svc_cfg(extra=""):
    return Config.from_string(
        BATCHED_CG + ", serving_bucket_slots=2, serving_chunk_iters=4"
        + (", " + extra if extra else ""))


def _key(A, b):
    return f"{pattern_fingerprint(A)}/{np.asarray(b).dtype}"


def _fleet(extra="", n=2):
    return FleetRouter.build(_svc_cfg(extra=extra), n)


# ---------------------------------------------------------------------------
# ladder: parsing + width selection + AOT-key separation
# ---------------------------------------------------------------------------


def test_ladder_parse():
    assert parse_ladder("1|4|16") == (1, 4, 16)
    assert parse_ladder(" 2 | 8 ") == (2, 8)
    assert parse_ladder("4") == (4,)
    assert parse_ladder("") == ()
    for bad in ("0|2", "4|2", "2|2", "a|b", "-1"):
        with pytest.raises(BadParametersError):
            parse_ladder(bad)


def test_choose_slots():
    assert choose_slots((1, 4, 16), 1, 8) == 1
    assert choose_slots((1, 4, 16), 3, 8) == 4
    assert choose_slots((1, 4, 16), 4, 8) == 4
    assert choose_slots((1, 4, 16), 99, 8) == 16   # burst > top rung
    assert choose_slots((), 99, 8) == 8            # ladder off
    assert choose_slots((2, 4), 0, 8) == 2         # pending clamps >= 1


def test_ladder_width_follows_queue_composition(poisson16):
    """A singleton fingerprint builds the narrowest rung; a burst
    queued at build time gets the smallest rung that seats it."""
    ladder = "serving_bucket_ladder=1|2|4"
    svc = SolveService(_svc_cfg(extra=ladder))
    b = _rhs(poisson16, 1)
    t = svc.submit(poisson16, b)
    svc.drain(timeout_s=300)
    assert t.done and t.result.converged
    eng = svc.buckets.peek(_key(poisson16, b))
    assert eng is not None and eng.slots == 1

    svc2 = SolveService(_svc_cfg(extra=ladder))
    ts = [svc2.submit(_shift(poisson16, 0.1 * i), _rhs(poisson16, i))
          for i in range(3)]
    done = svc2.drain(timeout_s=300)
    assert len(done) == 3 and all(t.result.converged for t in ts)
    eng = svc2.buckets.peek(_key(poisson16, _rhs(poisson16, 0)))
    assert eng is not None and eng.slots == 4    # smallest rung >= 3


def test_ladder_off_keeps_fixed_width(poisson16):
    svc = SolveService(_svc_cfg())
    assert svc.ladder == ()
    b = _rhs(poisson16, 2)
    svc.submit(poisson16, b)
    svc.drain(timeout_s=300)
    eng = svc.buckets.peek(_key(poisson16, b))
    assert eng is not None and eng.slots == svc.slots == 2


def test_ladder_widths_get_distinct_aot_keys(poisson16, tmp_path):
    """`slots` is part of the AOT key: every rung keeps its own
    exported executable, widths never cross-serve traces."""
    aot = AotStore(str(tmp_path))
    cfg = _svc_cfg()
    e1 = BucketEngine(cfg, "default", poisson16, slots=1, chunk=4,
                      dtype=np.float64, fingerprint="fpX")
    e2 = BucketEngine(cfg, "default", poisson16, slots=2, chunk=4,
                      dtype=np.float64, fingerprint="fpX")
    e1b = BucketEngine(cfg, "default", poisson16, slots=1, chunk=4,
                       dtype=np.float64, fingerprint="fpX")
    assert e1._aot_key(aot) != e2._aot_key(aot)
    assert e1._aot_key(aot) == e1b._aot_key(aot)


def test_engine_rejects_nonpositive_width(poisson16):
    with pytest.raises(BadParametersError):
        BucketEngine(_svc_cfg(), "default", poisson16, slots=0,
                     chunk=4, dtype=np.float64)


# ---------------------------------------------------------------------------
# router: affinity, cold placement, spill, rehoming
# ---------------------------------------------------------------------------


def test_affinity_stickiness(poisson16, poisson14):
    """Same fingerprint -> same replica across submits; distinct
    fingerprints spread by least-loaded cold placement."""
    fleet = _fleet()
    tickets = []
    for i in range(8):
        A = poisson16 if i % 2 == 0 else poisson14
        tickets.append(fleet.submit(_shift(A, 0.05 * i), _rhs(A, i)))
    fleet.drain(timeout_s=300)
    assert all(t.done and t.result.converged for t in tickets)
    homes = {}
    for t in tickets:
        fp = t.fingerprint
        homes.setdefault(fp, t.replica)
        assert t.replica == homes[fp]          # sticky
    assert len(homes) == 2
    routes = fleet.stats()["routes"]
    warm = sum(c["warm"] for c in routes.values())
    cold = sum(c["cold"] for c in routes.values())
    spill = sum(c["spill"] for c in routes.values())
    assert cold == 2 and warm == 6 and spill == 0
    # the two patterns spread across both replicas (the second cold
    # placement saw the first one's queued load)
    assert len(set(homes.values())) == 2


def test_rendezvous_is_stable():
    a = _rendezvous_score("fp1", "r0")
    assert a == _rendezvous_score("fp1", "r0")
    assert a != _rendezvous_score("fp1", "r1")


def test_spill_on_overload_writes_handoff(poisson16):
    """An overloaded home (queue depth past fleet_spill_depth, with a
    strictly less-loaded candidate) spills to the next rendezvous
    candidate; the flight recorder gets the affinity-handoff note and
    the placement map keeps the original home (no rehome on load)."""
    seq0 = _frec.last_seq()
    fleet = _fleet(extra="fleet_spill_depth=1")
    t1 = fleet.submit(poisson16, _rhs(poisson16, 1))
    home = t1.replica
    assert t1.route == "cold"
    t2 = fleet.submit(_shift(poisson16, 0.1), _rhs(poisson16, 2))
    assert t2.route == "spill" and t2.replica != home
    ev = _frec.events(kind="fleet.handoff", since_seq=seq0)
    assert len(ev) == 1
    assert ev[0]["from_replica"] == home
    assert ev[0]["to_replica"] == t2.replica
    assert ev[0]["reason"] == "overload"
    assert fleet._placed[t1.fingerprint] == home   # not rehomed
    fleet.drain(timeout_s=300)
    assert t1.done and t2.done
    routes = fleet.stats()["routes"]
    assert routes[t2.replica]["spill"] == 1


def test_saturated_fleet_keeps_affinity(poisson16):
    """No spill ping-pong: when EVERY replica is loaded past the
    spill depth, requests stay home (warm) instead of bouncing cold
    builds between equally-overloaded replicas."""
    fleet = _fleet(extra="fleet_spill_depth=1")
    A2 = gallery.poisson("5pt", 15, 15).init()
    t1 = fleet.submit(poisson16, _rhs(poisson16, 1))
    t2 = fleet.submit(A2, _rhs(A2, 2))
    assert t2.replica != t1.replica       # least-loaded cold split
    # both replicas now at depth 1 == spill limit: no candidate is
    # strictly less loaded, so same-fp traffic must stay home
    t3 = fleet.submit(_shift(poisson16, 0.1), _rhs(poisson16, 3))
    assert t3.route == "warm" and t3.replica == t1.replica
    fleet.drain(timeout_s=300)
    assert all(t.done for t in (t1, t2, t3))


def test_quarantine_spill_rehomes_and_drain_all_terminal(poisson16):
    """A build crash on the home replica: its fault/backoff state
    makes the router spill same-fingerprint traffic to a healthy
    replica AND rehome the fingerprint there; the fleet drain still
    ends with every ticket terminal (the crashed replica retries
    behind its backoff window)."""
    seq0 = _frec.last_seq()
    fleet = _fleet(extra="serving_fault_policy=BUILD_FAILED>"
                         "retry_backoff, serving_retry_backoff_s=0.05")
    b = _rhs(poisson16, 5)
    with faultinject.inject("build_crash", fires=1):
        t1 = fleet.submit(poisson16, b)
        home = t1.replica
        # step until the injected crash lands in the home's fault state
        for _ in range(50):
            fleet.step()
            if t1.fingerprint in fleet.replicas[home]._faulted:
                break
        assert t1.fingerprint in fleet.replicas[home]._faulted
        t2 = fleet.submit(_shift(poisson16, 0.2), _rhs(poisson16, 6))
    assert t2.route == "spill" and t2.replica != home
    assert fleet._placed[t1.fingerprint] == t2.replica   # rehomed
    ev = _frec.events(kind="fleet.handoff", since_seq=seq0)
    assert ev and ev[-1]["reason"] == "quarantine"
    done = fleet.drain(timeout_s=300)
    assert t1.done and t2.done                 # all-terminal
    assert t1.result.converged and t2.result.converged
    assert len(done) == 2


def test_fleet_shed_consults_fleetwide_estimates(poisson16, poisson14):
    """When EVERY replica's feasibility estimate says a deadline is
    unmeetable, the router records the fleet-wide consult (estimates +
    merged per-tenant quantiles) and routes home for the honest
    OVERLOADED shed."""
    fleet = _fleet(extra="serving_shed_policy=deadline")
    # train BOTH replicas' estimators (>= 3 completions each)
    for i in range(4):
        fleet.submit(_shift(poisson16, 0.1 * i), _rhs(poisson16, i),
                     tenant="acme")
        fleet.submit(_shift(poisson14, 0.1 * i), _rhs(poisson14, i),
                     tenant="acme")
    fleet.drain(timeout_s=300)
    for svc in fleet.replicas.values():
        assert len(svc._exec_recent) >= 3
    seq0 = _frec.last_seq()
    before = metrics.get("fleet.shed.infeasible")
    t = fleet.submit(poisson16, _rhs(poisson16, 9), tenant="acme",
                     deadline_s=1e-9)
    assert t.route == "warm"                   # stayed home
    assert t.done and t.result.status_code == int(SolveStatus.OVERLOADED)
    assert metrics.get("fleet.shed.infeasible") == before + 1
    ev = _frec.events(kind="fleet.shed", since_seq=seq0)
    assert len(ev) == 1 and ev[0]["verdict"] == "infeasible"
    assert set(ev[0]["estimates_s"]) == set(fleet.replicas)
    assert all(e is not None and e > 1e-9
               for e in ev[0]["estimates_s"].values())
    assert ev[0]["tenant_p99_s"] is not None   # merged per-tenant read


def test_trace_chain_records_serving_replica(poisson16):
    """Replica attribution on the flow chain: the fleet.route instant
    event carries the ticket's trace id, serving replica and route
    class — what a cross-replica flightrec/Perfetto postmortem pivots
    on."""
    fleet = _fleet()
    t = fleet.submit(poisson16, _rhs(poisson16, 7))
    assert t.trace_id
    fleet.drain(timeout_s=300)
    recs = [r for r in _spans.records()
            if r["name"] == "fleet.route"
            and r.get("args", {}).get("trace") == t.trace_id]
    assert len(recs) == 1
    assert recs[0]["args"]["replica"] == t.replica
    assert recs[0]["args"]["route"] == t.route == "cold"


# ---------------------------------------------------------------------------
# replica labels + snapshot merging (the collision regression)
# ---------------------------------------------------------------------------


def test_auto_assigned_replica_ids_keep_series_distinct(poisson16,
                                                        poisson14):
    """Two services constructed WITHOUT serving_replica_id used to
    scrape identically; the router must assign distinct ids and the
    merged snapshot must keep their series apart."""
    s0, s1 = SolveService(_svc_cfg()), SolveService(_svc_cfg())
    assert s0.replica == "" and s1.replica == ""
    fleet = FleetRouter([s0, s1])
    assert {s0.replica, s1.replica} == {"r0", "r1"}
    fleet.submit(poisson16, _rhs(poisson16, 1), tenant="dupes")
    fleet.submit(poisson14, _rhs(poisson14, 2), tenant="dupes")
    fleet.drain(timeout_s=300)
    views = fleet.snapshots()
    assert all(views[rid] for rid in ("r0", "r1"))
    assert not set(views["r0"]) & set(views["r1"])   # disjoint series
    merged = fleet.fleet_snapshot()
    k0 = 'serving.solve_latency_s{replica="r0",tenant="dupes"}'
    k1 = 'serving.solve_latency_s{replica="r1",tenant="dupes"}'
    assert merged[k0]["count"] == 1 and merged[k1]["count"] == 1
    # the synthesized fleet-wide aggregate equals the per-replica sum
    per_replica = sum(
        v["count"] for k, v in merged.items()
        if k.startswith("serving.solve_latency_s{"))
    assert merged["serving.solve_latency_s"]["count"] == per_replica


def test_router_rejects_duplicate_replica_ids(poisson16):
    s0, s1 = SolveService(_svc_cfg()), SolveService(_svc_cfg())
    s0.replica = s1.replica = "twin"
    with pytest.raises(BadParametersError):
        FleetRouter([s0, s1])


def test_merge_snapshots_unit():
    def h(counts, total):
        return {"count": sum(counts), "sum": total,
                "edges": [0.5, 1.0], "counts": list(counts)}
    snaps = {
        "a": {"c": 2, "g": 1.5, 'h{tenant="x"}': h([1, 0, 0], 0.2),
              "h": h([1, 0, 0], 0.2)},
        "b": {"c": 3, 'h{tenant="x"}': h([0, 2, 0], 1.4),
              "h": h([0, 2, 0], 1.4)},
    }
    m = metrics.merge_snapshots(snaps)
    assert m["c"] == 5 and m["g"] == 1.5           # scalars sum
    # same-named labeled series gained the snapshot's replica id
    ka = 'h{replica="a",tenant="x"}'
    kb = 'h{replica="b",tenant="x"}'
    assert m[ka]["count"] == 1 and m[kb]["count"] == 2
    # bare entries merged bucket-wise, quantiles recomputed
    assert m["h"]["count"] == 3 and m["h"]["counts"] == [1, 2, 0]
    assert 0.5 <= m["h"]["p50"] <= 1.0
    # an entry already carrying a replica label keeps it
    m2 = metrics.merge_snapshots(
        {"z": {'h{replica="keep",tenant="x"}': h([1, 0, 0], 0.1)}})
    assert 'h{replica="keep",tenant="x"}' in m2


def test_merge_snapshots_edge_mismatch_raises():
    e1 = {"count": 1, "sum": 0.1, "edges": [0.5, 1.0],
          "counts": [1, 0, 0]}
    e2 = {"count": 1, "sum": 0.1, "edges": [0.25, 1.0],
          "counts": [1, 0, 0]}
    with pytest.raises(ValueError):
        metrics.merge_snapshots({"a": {"h": e1}, "b": {"h": e2}})


def test_quantile_where_subset_match():
    metrics.observe("serving.solve_latency_s", 0.011,
                    labels={"tenant": "qw_only", "replica": "qz0"})
    metrics.observe("serving.solve_latency_s", 0.013,
                    labels={"tenant": "qw_only", "replica": "qz1"})
    q = metrics.quantile_where("serving.solve_latency_s", 0.50,
                               {"tenant": "qw_only"})
    assert q is not None and 0.005 <= q <= 0.05
    assert metrics.quantile_where("serving.solve_latency_s", 0.50,
                                  {"tenant": "qw_nobody"}) is None


# ---------------------------------------------------------------------------
# capi surface
# ---------------------------------------------------------------------------


def test_capi_fleet_roundtrip(poisson16):
    from amgx_tpu import capi
    assert capi.AMGX_initialize() == 0
    rc, cfg_h = capi.AMGX_config_create(
        BATCHED_CG + ", serving_bucket_slots=2, fleet_replicas=2")
    assert rc == 0
    rc, rsrc_h = capi.AMGX_resources_create_simple(cfg_h)
    assert rc == 0
    rc, fleet_h = capi.AMGX_fleet_create(rsrc_h, "dDDI", cfg_h)
    assert rc == 0
    rc, m_h = capi.AMGX_matrix_create(rsrc_h, "dDDI")
    rc, b_h = capi.AMGX_vector_create(rsrc_h, "dDDI")
    rc, x_h = capi.AMGX_vector_create(rsrc_h, "dDDI")
    ro = np.asarray(poisson16.row_offsets)
    ci = np.asarray(poisson16.col_indices)
    v = np.asarray(poisson16.values)
    assert capi.AMGX_matrix_upload_all(
        m_h, poisson16.num_rows, v.size, 1, 1, ro, ci, v, None) == 0
    b = _rhs(poisson16, 21)
    assert capi.AMGX_vector_upload(b_h, b.size, 1, b) == 0
    rc, t1 = capi.AMGX_fleet_submit(fleet_h, m_h, b_h, "acme", None)
    assert rc == 0
    rc, t2 = capi.AMGX_fleet_submit(fleet_h, m_h, b_h, "acme", None)
    assert rc == 0
    rc, n_done = capi.AMGX_fleet_drain(fleet_h, 300)
    assert rc == 0 and n_done == 2
    rc, done, st = capi.AMGX_service_ticket_status(t1)
    assert rc == 0 and done == 1 and st == 0      # AMGX_SOLVE_SUCCESS
    rc, rid1 = capi.AMGX_fleet_ticket_replica(t1)
    rc, rid2 = capi.AMGX_fleet_ticket_replica(t2)
    assert rid1 in ("r0", "r1") and rid2 == rid1  # affine
    assert capi.AMGX_service_ticket_download(t1, x_h) == 0
    rc, stats = capi.AMGX_fleet_stats(fleet_h)
    assert rc == 0 and set(stats["routes"]) == {"r0", "r1"}
    total_routes = sum(sum(c.values())
                       for c in stats["routes"].values())
    assert total_routes == 2
    rc, tr = capi.AMGX_ticket_trace(t1)
    assert rc == 0 and tr          # trace id works on fleet tickets
    assert capi.AMGX_service_ticket_destroy(t1) == 0
    assert capi.AMGX_service_ticket_destroy(t2) == 0
    assert capi.AMGX_fleet_destroy(fleet_h) == 0


# ---------------------------------------------------------------------------
# fleet journaling isolation
# ---------------------------------------------------------------------------


def test_fleet_build_splits_journal_dirs(poisson16, tmp_path):
    """FleetRouter.build gives every replica its own journal
    subdirectory — two replicas must never replay each other's
    records."""
    fleet = _fleet(extra=f"serving_journal_dir={tmp_path}")
    dirs = {rid: svc.journal.root if hasattr(svc.journal, "root")
            else getattr(svc.journal, "directory", None)
            for rid, svc in fleet.replicas.items()}
    vals = set(str(d) for d in dirs.values())
    assert len(vals) == 2 and all(v is not None for v in vals)
    t = fleet.submit(poisson16, _rhs(poisson16, 3))
    fleet.drain(timeout_s=300)
    assert t.done and t.result.converged
