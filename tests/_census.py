"""Shared jaxpr census helpers for the kernel-fusion test suites.

The fusion PRs prove their HBM-pass claims by *counting* what a traced
program contains: how many Pallas kernels of which kind, which XLA
primitives run standalone between them, and which reductions touch
full-length vectors outside any kernel. These helpers used to be
copy-pasted across tests/test_fused_smoother.py, test_cycle_fusion.py
and test_matrix_free.py; they live here once so every census gate
counts the same way.
"""
import re

import numpy as np
import jax

KERNEL_NAME_RE = re.compile(r"name=\"?([A-Za-z_0-9]+)\"?")

# the package's fused Pallas entry points, as their names appear on
# pallas_call eqns (ops/pallas_spmv.py); extend here when a PR adds a
# kernel so every suite's counts see it
KERNEL_KEYS = (
    "_dia_smooth_restrict_call",
    "_dia_prolong_smooth_call",
    "_dia_coarse_tail_call",
    "_dia_smooth_call",
    "_dia_spmv_call",
    "_dia_spmv_dot_call",
    "_cg_update_call",
)


def kernel_names(jaxpr):
    """Every `name=...` occurrence in the stringified jaxpr, in trace
    order (pallas_call kernel names plus any other named eqns)."""
    return KERNEL_NAME_RE.findall(str(jaxpr))


def kernel_counts(jaxpr, keys=KERNEL_KEYS):
    """{kernel name: count} over `keys` (exact matches only; names not
    present are absent from the dict, so use .get(k, 0))."""
    out = {}
    for nm in kernel_names(jaxpr):
        if nm in keys:
            out[nm] = out.get(nm, 0) + 1
    return out


def _subjaxprs(eqn):
    for p in eqn.params.values():
        for q in (p if isinstance(p, (tuple, list)) else (p,)):
            if isinstance(q, jax.core.ClosedJaxpr):
                yield q.jaxpr
            elif isinstance(q, jax.core.Jaxpr):
                yield q


def outer_prims(closed_jaxpr):
    """All primitive names reachable from the trace WITHOUT descending
    into pallas_call bodies — what runs as standalone XLA ops between
    the kernels."""
    prims = []

    def walk(jaxpr):
        for eqn in jaxpr.eqns:
            if eqn.primitive.name == "pallas_call":
                continue
            prims.append(eqn.primitive.name)
            for sub in _subjaxprs(eqn):
                walk(sub)

    walk(closed_jaxpr.jaxpr)
    return prims


def full_vector_reductions(closed_jaxpr, n,
                           prims=("reduce_sum", "reduce_max",
                                  "reduce_min", "dot_general")):
    """Reduction/contraction eqns OUTSIDE pallas_call bodies that
    consume an operand of at least `n` elements — the standalone
    full-vector HBM passes the Krylov-shell fusion removes. Returns
    [(prim_name, [operand shapes])]."""
    hits = []

    def walk(jaxpr):
        for eqn in jaxpr.eqns:
            if eqn.primitive.name == "pallas_call":
                continue
            if eqn.primitive.name in prims and any(
                    getattr(v, "aval", None) is not None
                    and v.aval.size >= n for v in eqn.invars):
                hits.append((eqn.primitive.name,
                             [tuple(v.aval.shape) for v in eqn.invars
                              if hasattr(v, "aval")]))
            for sub in _subjaxprs(eqn):
                walk(sub)

    walk(closed_jaxpr.jaxpr)
    return hits


def slab_consts(jaxpr, k, lanes=128):
    """Constants shaped like a k-diagonal DIA value slab (k, rows,
    lanes) — the operand a matrix-free trace must not carry."""
    return [v.aval.shape for v in jaxpr.consts
            if np.ndim(v) == 3 and np.shape(v)[0] == k
            and np.shape(v)[-1] == lanes]
