"""Force the CPU backend with N virtual devices.

Shared by tests/conftest.py and __graft_entry__.dryrun_multichip so the
XLA_FLAGS / JAX_PLATFORMS / jax.config dance exists exactly once. This
module lives OUTSIDE the amgx_tpu package on purpose: importing it must
not execute any package __init__ (which imports jax submodules), so the
"importable before jax initializes" guarantee is structural.

Environment gotcha this encodes: the axon TPU plugin ignores the
JAX_PLATFORMS env var, but the `jax_platforms` config flag does stick —
both must be set, and they must be set before the backend initializes
(after that every override silently no-ops, so force_cpu verifies the
resulting platform and fails loudly).
"""
from __future__ import annotations

import os
import re

_COUNT_FLAG = "--xla_force_host_platform_device_count"


def force_cpu(n_devices: int) -> None:
    """Force the CPU backend with `n_devices` virtual devices; raise if a
    jax backend already initialized on a different platform or with fewer
    devices."""
    flags = os.environ.get("XLA_FLAGS", "")
    if _COUNT_FLAG in flags:
        # replace a pre-existing count (it may be smaller than n_devices;
        # silently keeping it would shrink the mesh under test)
        flags = re.sub(rf"{_COUNT_FLAG}=\d+", f"{_COUNT_FLAG}={n_devices}",
                       flags)
    else:
        flags = (flags + f" {_COUNT_FLAG}={n_devices}").strip()
    os.environ["XLA_FLAGS"] = flags
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    devs = jax.devices()
    if devs[0].platform != "cpu" or len(devs) < n_devices:
        raise RuntimeError(
            f"force_cpu({n_devices}): jax backend was already initialized "
            f"({len(devs)} x {devs[0].platform}); call force_cpu before any "
            f"jax operation")
