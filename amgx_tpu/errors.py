"""Error codes and exceptions.

TPU-native analog of the reference error system (include/error.h,
src/error.cu): AMGX_RC return codes for the C-style API layer plus a rich
exception type used internally.
"""
from __future__ import annotations

import enum
import traceback


class RC(enum.IntEnum):
    """API return codes (parity with AMGX_RC in include/amgx_c.h)."""

    OK = 0
    BAD_PARAMETERS = 1
    UNKNOWN = 2
    NOT_SUPPORTED_TARGET = 3
    NOT_SUPPORTED_BLOCKSIZE = 4
    CUDA_FAILURE = 5          # kept for API parity; maps to device failures
    IO_ERROR = 6
    BAD_MODE = 7
    CORE = 8
    PLUGIN = 9
    BAD_CONFIGURATION = 10
    NOT_IMPLEMENTED = 11
    LICENSE_NOT_FOUND = 12
    INTERNAL = 13


_RC_STRINGS = {
    RC.OK: "No error.",
    RC.BAD_PARAMETERS: "Incorrect parameters for amgx call.",
    RC.UNKNOWN: "Unknown error.",
    RC.NOT_SUPPORTED_TARGET: "Unsupported target.",
    RC.NOT_SUPPORTED_BLOCKSIZE: "Unsupported block size.",
    RC.CUDA_FAILURE: "Device failure.",
    RC.IO_ERROR: "I/O error.",
    RC.BAD_MODE: "Incorrect mode.",
    RC.CORE: "Error initializing amgx core.",
    RC.PLUGIN: "Error initializing plugin.",
    RC.BAD_CONFIGURATION: "Incorrect configuration provided.",
    RC.NOT_IMPLEMENTED: "Requested feature is not implemented.",
    RC.LICENSE_NOT_FOUND: "License not found.",
    RC.INTERNAL: "Internal error.",
}


def get_error_string(rc: RC) -> str:
    return _RC_STRINGS.get(RC(rc), "Unknown error code.")


class AMGXError(Exception):
    """Internal exception carrying an RC code and a `where` location
    (analog of amgx_exception, include/error.h)."""

    def __init__(self, message: str, rc: RC = RC.UNKNOWN):
        super().__init__(message)
        self.rc = RC(rc)
        # capture the raising site, like amgx_exception::where(): the
        # innermost frame outside this module (works for direct raises and
        # subclass constructors alike)
        self._where = "?"
        for fr in reversed(traceback.extract_stack()):
            if not fr.filename.endswith("errors.py"):
                self._where = f"{fr.filename}:{fr.lineno}"
                break

    def where(self) -> str:
        return self._where


class BadParametersError(AMGXError):
    def __init__(self, message: str):
        super().__init__(message, RC.BAD_PARAMETERS)


class BadConfigurationError(AMGXError):
    def __init__(self, message: str):
        super().__init__(message, RC.BAD_CONFIGURATION)


class IOError_(AMGXError):
    def __init__(self, message: str):
        super().__init__(message, RC.IO_ERROR)


class NotImplementedError_(AMGXError):
    def __init__(self, message: str):
        super().__init__(message, RC.NOT_IMPLEMENTED)


def did_you_mean(name: str, candidates) -> str:
    """A ' (did you mean ...?)' suffix for unknown-key errors, or ''
    when nothing is close. Used by the config registry and the
    component factories so a typo'd parameter or solver name fails
    with a suggestion instead of a bare rejection."""
    import difflib
    matches = difflib.get_close_matches(
        str(name), [str(c) for c in candidates], n=2, cutoff=0.6)
    if not matches:
        return ""
    return " (did you mean " + " or ".join(
        repr(m) for m in matches) + "?)"


def fatal_error(message: str, rc: RC = RC.INTERNAL):
    """FatalError analog (include/error.h): raise an AMGXError."""
    raise AMGXError(message, rc)
