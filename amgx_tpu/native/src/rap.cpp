// Fused host Galerkin triple product C = R * A * P (scalar CSR).
//
// The csr_galerkin_product analog (include/csr_multiply.h:96,
// src/csr_multiply_detail.cu) for the host-setup path: the reference
// fuses RAP on the GPU with hash tables; here one Gustavson sweep per
// coarse row chains both products through dense stamp accumulators, so
// the R*A intermediate never materializes (and never crosses the
// Python boundary, which is what made two spgemm calls slow).
//
// Handle-based build/fetch like amgx_d2_*: output nnz is data-dependent.
#include <algorithm>
#include <cstdint>
#include <vector>

namespace {

struct RapResult {
    std::vector<int64_t> ptr;
    std::vector<int32_t> col;
    std::vector<double> val;
};

}  // namespace

extern "C" {

// R: nc x n, A: n x n, P: n x ncp. Columns of each output row emitted
// sorted. Returns C's nnz and a handle for amgx_rap_fetch, -1 on error.
long long amgx_rap_build(
    int32_t nc, int32_t n, int32_t ncp,
    const int32_t* r_ptr, const int32_t* r_col, const double* r_val,
    const int32_t* a_ptr, const int32_t* a_col, const double* a_val,
    const int32_t* p_ptr, const int32_t* p_col, const double* p_val,
    void** out_handle) {
    *out_handle = nullptr;
    auto* res = new RapResult();
    res->ptr.assign(static_cast<size_t>(nc) + 1, 0);
    std::vector<int32_t> stamp_a(static_cast<size_t>(n), -1);
    std::vector<double> acc_a(static_cast<size_t>(n), 0.0);
    std::vector<int32_t> touched_a;
    touched_a.reserve(256);
    std::vector<int32_t> stamp_c(static_cast<size_t>(ncp), -1);
    std::vector<double> acc_c(static_cast<size_t>(ncp), 0.0);
    std::vector<int32_t> touched_c;
    touched_c.reserve(256);

    auto fail = [&]() -> long long { delete res; return -1; };
    for (int32_t i = 0; i < nc; ++i) {
        res->ptr[static_cast<size_t>(i)] =
            static_cast<int64_t>(res->col.size());
        touched_a.clear();
        for (int32_t e = r_ptr[i]; e < r_ptr[i + 1]; ++e) {
            const int32_t k = r_col[e];
            if (k < 0 || k >= n) return fail();
            const double rv = r_val[e];
            for (int32_t f = a_ptr[k]; f < a_ptr[k + 1]; ++f) {
                const int32_t m = a_col[f];
                if (m < 0 || m >= n) return fail();
                if (stamp_a[static_cast<size_t>(m)] != i) {
                    stamp_a[static_cast<size_t>(m)] = i;
                    acc_a[static_cast<size_t>(m)] = 0.0;
                    touched_a.push_back(m);
                }
                acc_a[static_cast<size_t>(m)] += rv * a_val[f];
            }
        }
        touched_c.clear();
        for (const int32_t m : touched_a) {
            const double ra = acc_a[static_cast<size_t>(m)];
            for (int32_t f = p_ptr[m]; f < p_ptr[m + 1]; ++f) {
                const int32_t j = p_col[f];
                if (j < 0 || j >= ncp) return fail();
                if (stamp_c[static_cast<size_t>(j)] != i) {
                    stamp_c[static_cast<size_t>(j)] = i;
                    acc_c[static_cast<size_t>(j)] = 0.0;
                    touched_c.push_back(j);
                }
                acc_c[static_cast<size_t>(j)] += ra * p_val[f];
            }
        }
        std::sort(touched_c.begin(), touched_c.end());
        for (const int32_t j : touched_c) {
            res->col.push_back(j);
            res->val.push_back(acc_c[static_cast<size_t>(j)]);
        }
    }
    res->ptr[static_cast<size_t>(nc)] =
        static_cast<int64_t>(res->col.size());
    *out_handle = res;
    return static_cast<long long>(res->col.size());
}

void amgx_rap_fetch(void* handle, int64_t* ptr, int32_t* col, double* val) {
    auto* res = static_cast<RapResult*>(handle);
    std::copy(res->ptr.begin(), res->ptr.end(), ptr);
    std::copy(res->col.begin(), res->col.end(), col);
    std::copy(res->val.begin(), res->val.end(), val);
    delete res;
}

void amgx_rap_free(void* handle) { delete static_cast<RapResult*>(handle); }

}  // extern "C"
