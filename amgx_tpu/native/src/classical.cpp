// Host classical-AMG setup kernels: PMIS CF-splitting and distance-two
// (extended+i) interpolation.
//
// The reference runs these on the GPU with hash-table kernels
// (src/classical/selectors/pmis.cu, src/classical/interpolators/
// distance2.cu); on a remote TPU the setup-phase index math is
// latency-bound, so the host-setup path (amg_host_setup) runs them here
// as serial sweeps with stamp arrays — the same row-local structure the
// reference's per-CTA hash tables express, without the hardware hash.
//
// amgx_pmis is a bit-exact replica of the synchronous fixed point in
// amg/classical/selectors.py::pmis_split (same weights — exact halves
// plus the same integer hash — and the same two-phase round structure),
// so the CF-splitting is identical with or without the native library.
//
// amgx_d2_* implements the formula of amg/classical/interpolators.py::
// Distance2Interpolator (De Sterck et al. distance-two ext+i) with a
// handle-based build/fetch pair: the output size is data-dependent, so
// build computes and stashes the CSR, fetch copies it out and frees.
#include <algorithm>
#include <cmath>
#include <cstdint>
#include <vector>

namespace {

const int32_t FINE = 0, COARSE = 1, UNDECIDED = -1;

double hash01(uint32_t i) {
    uint32_t h = i * 2654435761u;
    h = (h ^ (h >> 16)) * 0x45D9F3Bu;
    h = h ^ (h >> 16);
    return static_cast<double>(h & 0xFFFFFu) / 1048576.0;
}

}  // namespace

extern "C" {

// PMIS fixed point over the symmetrized strength graph. `init` may be
// null (all points start UNDECIDED) or hold {-1,0,1} seeds (HMIS).
// Writes cf[n] in {0,1}. Returns 0 on success.
int amgx_pmis(
    int32_t n, const int32_t* ro, const int32_t* ci,
    const uint8_t* strong, const int32_t* init, int32_t max_iters,
    int32_t* cf) {
    // symmetrized adjacency S | S^T with duplicates kept (duplicates are
    // harmless for max/any reductions and keep deg identical to the
    // segment-sum formulation: deg = 0.5 * (outdeg + indeg))
    // strong edges only, cols within [0, n) — strength masks can mark
    // edges to halo/rectangular columns (same guard as rs.cpp)
    std::vector<int64_t> off(static_cast<size_t>(n) + 2, 0);
    for (int32_t i = 0; i < n; ++i)
        for (int32_t e = ro[i]; e < ro[i + 1]; ++e) {
            const int32_t j = ci[e];
            if (strong[e] && j >= 0 && j < n) {
                ++off[static_cast<size_t>(i) + 2];
                ++off[static_cast<size_t>(j) + 2];
            }
        }
    for (size_t i = 2; i < off.size(); ++i) off[i] += off[i - 1];
    std::vector<int32_t> adj(static_cast<size_t>(off[off.size() - 1]));
    for (int32_t i = 0; i < n; ++i)
        for (int32_t e = ro[i]; e < ro[i + 1]; ++e) {
            const int32_t j = ci[e];
            if (strong[e] && j >= 0 && j < n) {
                adj[static_cast<size_t>(off[static_cast<size_t>(i) + 1]++)] = j;
                adj[static_cast<size_t>(off[static_cast<size_t>(j) + 1]++)] = i;
            }
        }

    std::vector<double> w(static_cast<size_t>(n));
    std::vector<int32_t> state(static_cast<size_t>(n));
    for (int32_t i = 0; i < n; ++i) {
        const int64_t d = off[static_cast<size_t>(i) + 1] -
                          off[static_cast<size_t>(i)];
        w[static_cast<size_t>(i)] =
            0.5 * static_cast<double>(d) + hash01(static_cast<uint32_t>(i));
        int32_t s = init ? init[i] : UNDECIDED;
        if (s == UNDECIDED && d == 0) s = COARSE;  // isolated point
        state[static_cast<size_t>(i)] = s;
    }

    std::vector<uint8_t> new_c(static_cast<size_t>(n));
    for (int32_t it = 0; it < max_iters; ++it) {
        bool any_und = false;
        // phase 1: undecided local maxima over undecided strong
        // neighbours become COARSE (synchronous: decided against the
        // round-entry state)
        for (int32_t i = 0; i < n; ++i) {
            new_c[static_cast<size_t>(i)] = 0;
            if (state[static_cast<size_t>(i)] != UNDECIDED) continue;
            any_und = true;
            double nbr_max = -1.0;  // weights are >= 0; -1 == -inf here
            for (int64_t t = off[static_cast<size_t>(i)];
                 t < off[static_cast<size_t>(i) + 1]; ++t) {
                const int32_t j = adj[static_cast<size_t>(t)];
                if (state[static_cast<size_t>(j)] == UNDECIDED &&
                    w[static_cast<size_t>(j)] > nbr_max)
                    nbr_max = w[static_cast<size_t>(j)];
            }
            if (w[static_cast<size_t>(i)] > nbr_max)
                new_c[static_cast<size_t>(i)] = 1;
        }
        if (!any_und) break;
        for (int32_t i = 0; i < n; ++i)
            if (new_c[static_cast<size_t>(i)])
                state[static_cast<size_t>(i)] = COARSE;
        // phase 2: undecided neighbours of (any, including new) COARSE
        // points become FINE
        for (int32_t i = 0; i < n; ++i) {
            if (state[static_cast<size_t>(i)] != UNDECIDED) continue;
            for (int64_t t = off[static_cast<size_t>(i)];
                 t < off[static_cast<size_t>(i) + 1]; ++t)
                if (state[static_cast<size_t>(adj[static_cast<size_t>(t)])] ==
                    COARSE) {
                    state[static_cast<size_t>(i)] = FINE;
                    break;
                }
        }
    }
    for (int32_t i = 0; i < n; ++i)
        cf[i] = state[static_cast<size_t>(i)] == COARSE ? COARSE : FINE;
    return 0;
}

// AHAT strength-of-connection mask (strength.py _strong_mask_host
// semantics; src/classical/strength/strength_base.cu analog):
//   strong_ij = offdiag & (-a_ij * sgn_i >= theta * rowmax_i) & (> 0)
// with max_row_sum weakening (rows with |rowsum| > mrs*|diag| lose all
// connections). Diagonal = FIRST in-row occurrence (padded-duplicate
// CSR convention). Writes strong[nnz] (uint8).
void amgx_strength_ahat(
    int32_t n, const int32_t* ro, const int32_t* ci, const double* vals,
    double theta, double max_row_sum, uint8_t* strong) {
    for (int32_t i = 0; i < n; ++i) {
        double diag = 0.0;
        bool have_diag = false;
        double rowsum = 0.0;
        for (int32_t e = ro[i]; e < ro[i + 1]; ++e) {
            rowsum += vals[e];
            if (!have_diag && ci[e] == i) { diag = vals[e]; have_diag = true; }
        }
        const double sgn = diag < 0.0 ? -1.0 : 1.0;
        double rowmax = 0.0;
        for (int32_t e = ro[i]; e < ro[i + 1]; ++e) {
            if (ci[e] == i) continue;
            const double c = -vals[e] * sgn;
            if (c > rowmax) rowmax = c;
        }
        const bool weak_row = max_row_sum < 1.0 &&
            std::abs(rowsum) > max_row_sum * std::abs(diag);
        for (int32_t e = ro[i]; e < ro[i + 1]; ++e) {
            if (ci[e] == i || weak_row) { strong[e] = 0; continue; }
            const double c = -vals[e] * sgn;
            strong[e] = (c > 0.0 && c >= theta * rowmax) ? 1 : 0;
        }
    }
}

// L1-strengthened Jacobi diagonal (jacobi_l1_solver.cu semantics;
// relaxation.py l1_strengthened_diag): d_i + sign(d_i) * sum|offdiag|,
// sign(0) = 0 so zero diagonals stay inert.
void amgx_l1_diag(
    int32_t n, const int32_t* ro, const int32_t* ci, const double* vals,
    double* out) {
    for (int32_t i = 0; i < n; ++i) {
        double diag = 0.0;
        bool have_diag = false;
        double l1 = 0.0;
        for (int32_t e = ro[i]; e < ro[i + 1]; ++e) {
            if (ci[e] == i) {
                if (!have_diag) { diag = vals[e]; have_diag = true; }
            } else {
                l1 += std::abs(vals[e]);
            }
        }
        const double s = diag > 0.0 ? 1.0 : (diag < 0.0 ? -1.0 : 0.0);
        out[i] = diag + s * l1;
    }
}

struct D2Result {
    std::vector<int64_t> ptr;
    std::vector<int32_t> col;
    std::vector<double> val;
};

// Distance-two ext+i interpolation. Inputs: scalar CSR (diagonal stored
// in-line), per-entry strength mask, cf map in {0,1}. Truncation
// (trunc_factor <= 1.0 and/or max_elements > 0; truncate.cu semantics —
// keep the max_elements largest |w| per row, drop entries below
// trunc_factor * rowmax, rescale survivors to preserve the row sum) is
// fused into the per-row emit so the untruncated P never materializes.
// Returns P's nnz and a handle for amgx_d2_fetch; -1 on failure.
long long amgx_d2_build(
    int32_t n, const int32_t* ro, const int32_t* ci, const double* vals,
    const uint8_t* strong, const int32_t* cf, double trunc_factor,
    int32_t max_elements, void** out_handle) {
    *out_handle = nullptr;
    std::vector<double> diag(static_cast<size_t>(n), 0.0);
    std::vector<double> sgn(static_cast<size_t>(n), 1.0);
    std::vector<int32_t> cidx(static_cast<size_t>(n));
    int32_t nc = 0;
    for (int32_t i = 0; i < n; ++i) {
        for (int32_t e = ro[i]; e < ro[i + 1]; ++e)
            if (ci[e] == i) {  // FIRST occurrence wins (padded-duplicate
                diag[static_cast<size_t>(i)] = vals[e];  // CSR stores the
                break;  // coalesced sum first, trailing duplicates zero)
            }
        sgn[static_cast<size_t>(i)] =
            diag[static_cast<size_t>(i)] < 0.0 ? -1.0 : 1.0;
        cidx[static_cast<size_t>(i)] = nc;
        if (cf[i] == COARSE) ++nc;
    }

    auto* res = new D2Result();
    res->ptr.assign(static_cast<size_t>(n) + 1, 0);
    // stamp[l] == current row marks l in C-hat_i; acc holds the row's
    // coalesced interpolatory weights (pre -1/D scaling)
    std::vector<int32_t> stamp(static_cast<size_t>(n), -1);
    std::vector<int32_t> tstamp(static_cast<size_t>(n), -1);
    std::vector<double> acc(static_cast<size_t>(n), 0.0);
    std::vector<int32_t> touched;
    touched.reserve(64);
    std::vector<double> row_w;             // fused-truncation scratch
    std::vector<uint8_t> row_keep;
    std::vector<size_t> row_rank;

    // Pre-filtered per-row sublists, built once in O(nnz): the two-hop
    // loops below re-scan each strong-F neighbour's full row up to
    // three times per fine row; on D2 operators most entries fail the
    // filter every time. strongC = entries with strong && C (feeds the
    // C-hat stamping); neg = in-graph off-diagonal entries with
    // vals*sgn(k) < 0 (feeds the distribution sums). Entry order is
    // preserved, so the float accumulation order — and the emitted P —
    // is bit-identical to the unfiltered sweeps.
    std::vector<int64_t> sc_off(static_cast<size_t>(n) + 1, 0);
    std::vector<int64_t> ng_off(static_cast<size_t>(n) + 1, 0);
    for (int32_t k = 0; k < n; ++k) {
        int64_t csc = 0, cng = 0;
        const double sk = sgn[static_cast<size_t>(k)];
        for (int32_t f = ro[k]; f < ro[k + 1]; ++f) {
            const int32_t l = ci[f];
            if (l < 0 || l >= n) continue;
            if (strong[f] && cf[l] == COARSE) ++csc;
            if (l != k && vals[f] * sk < 0.0) ++cng;
        }
        sc_off[static_cast<size_t>(k) + 1] =
            sc_off[static_cast<size_t>(k)] + csc;
        ng_off[static_cast<size_t>(k) + 1] =
            ng_off[static_cast<size_t>(k)] + cng;
    }
    std::vector<int32_t> sc_col(static_cast<size_t>(sc_off[n]));
    std::vector<int32_t> ng_col(static_cast<size_t>(ng_off[n]));
    std::vector<double> ng_val(static_cast<size_t>(ng_off[n]));
    {
        std::vector<int64_t> ps = sc_off, pn = ng_off;
        for (int32_t k = 0; k < n; ++k) {
            const double sk = sgn[static_cast<size_t>(k)];
            for (int32_t f = ro[k]; f < ro[k + 1]; ++f) {
                const int32_t l = ci[f];
                if (l < 0 || l >= n) continue;
                if (strong[f] && cf[l] == COARSE)
                    sc_col[static_cast<size_t>(
                        ps[static_cast<size_t>(k)]++)] = l;
                if (l != k && vals[f] * sk < 0.0) {
                    const int64_t t = pn[static_cast<size_t>(k)]++;
                    ng_col[static_cast<size_t>(t)] = l;
                    ng_val[static_cast<size_t>(t)] = vals[f];
                }
            }
        }
    }

    for (int32_t i = 0; i < n; ++i) {
        res->ptr[static_cast<size_t>(i)] =
            static_cast<int64_t>(res->col.size());
        if (cf[i] == COARSE) {  // injection row
            res->col.push_back(cidx[static_cast<size_t>(i)]);
            res->val.push_back(1.0);
            continue;
        }
        // C-hat_i: strong C neighbours + strong-C neighbours of strong-F
        // neighbours (all members are C points)
        for (int64_t t = sc_off[static_cast<size_t>(i)];
             t < sc_off[static_cast<size_t>(i) + 1]; ++t)
            stamp[static_cast<size_t>(sc_col[static_cast<size_t>(t)])] = i;
        for (int32_t e = ro[i]; e < ro[i + 1]; ++e) {
            const int32_t k = ci[e];
            if (k < 0 || k >= n) continue;
            if (!(strong[e] && cf[k] == FINE && k != i)) continue;
            for (int64_t t = sc_off[static_cast<size_t>(k)];
                 t < sc_off[static_cast<size_t>(k) + 1]; ++t)
                stamp[static_cast<size_t>(
                    sc_col[static_cast<size_t>(t)])] = i;
        }
        touched.clear();
        double D = diag[static_cast<size_t>(i)];
        auto acc_add = [&](int32_t j, double v) {
            if (tstamp[static_cast<size_t>(j)] != i) {
                tstamp[static_cast<size_t>(j)] = i;
                acc[static_cast<size_t>(j)] = 0.0;
                touched.push_back(j);
            }
            acc[static_cast<size_t>(j)] += v;
        };
        // direct entries + weak lumping
        for (int32_t e = ro[i]; e < ro[i + 1]; ++e) {
            const int32_t j = ci[e];
            if (j == i) continue;
            if (j < 0 || j >= n) {  // out-of-graph column: weak-lump
                D += vals[e];
                continue;
            }
            const bool in_chat = stamp[static_cast<size_t>(j)] == i;
            const bool strong_f = strong[e] && cf[j] == FINE;
            if (in_chat && cf[j] == COARSE) acc_add(j, vals[e]);
            if (!in_chat && !strong_f) D += vals[e];
        }
        // two-hop terms through strong F neighbours (the negative
        // in-graph sublist of row k is exactly the entry set the
        // original full-row scans kept — same entries, same order)
        for (int32_t e = ro[i]; e < ro[i + 1]; ++e) {
            const int32_t k = ci[e];
            if (k < 0 || k >= n) continue;
            if (!(strong[e] && cf[k] == FINE && k != i)) continue;
            const double aik = vals[e];
            const int64_t f0 = ng_off[static_cast<size_t>(k)];
            const int64_t f1 = ng_off[static_cast<size_t>(k) + 1];
            double d = 0.0;
            for (int64_t f = f0; f < f1; ++f) {
                const int32_t l = ng_col[static_cast<size_t>(f)];
                if (stamp[static_cast<size_t>(l)] == i || l == i)
                    d += ng_val[static_cast<size_t>(f)];
            }
            if (d == 0.0) {  // k distributes nowhere: lump a_ik
                D += aik;
                continue;
            }
            for (int64_t f = f0; f < f1; ++f) {
                const int32_t l = ng_col[static_cast<size_t>(f)];
                const double v = ng_val[static_cast<size_t>(f)];
                if (l == i)
                    D += aik * v / d;  // "+i" feedback
                else if (stamp[static_cast<size_t>(l)] == i)
                    acc_add(l, aik * v / d);
            }
        }
        std::sort(touched.begin(), touched.end());
        const double dsafe = D == 0.0 ? 1.0 : D;
        const bool truncate = trunc_factor <= 1.0 || max_elements > 0;
        if (!truncate) {
            for (const int32_t j : touched) {
                res->col.push_back(cidx[static_cast<size_t>(j)]);
                res->val.push_back(-acc[static_cast<size_t>(j)] / dsafe);
            }
            continue;
        }
        // fused truncation (matches _truncate_host: stable top-k by
        // descending |w| with earlier-column tie wins, trunc_factor
        // drop, row-sum-preserving rescale; sums in column order)
        row_w.clear();
        double rowsum = 0.0, wmax = 0.0;
        for (const int32_t j : touched) {
            const double w = -acc[static_cast<size_t>(j)] / dsafe;
            row_w.push_back(w);
            rowsum += w;
            if (std::abs(w) > wmax) wmax = std::abs(w);
        }
        const size_t m = row_w.size();
        row_keep.assign(m, 1);
        if (trunc_factor <= 1.0)
            for (size_t t = 0; t < m; ++t)
                if (std::abs(row_w[t]) < trunc_factor * wmax)
                    row_keep[t] = 0;
        if (max_elements > 0 && m > static_cast<size_t>(max_elements)) {
            row_rank.resize(m);
            for (size_t t = 0; t < m; ++t) row_rank[t] = t;
            std::stable_sort(row_rank.begin(), row_rank.end(),
                             [&](size_t a, size_t b) {
                                 return std::abs(row_w[a]) >
                                        std::abs(row_w[b]);
                             });
            for (size_t r = static_cast<size_t>(max_elements); r < m; ++r)
                row_keep[row_rank[r]] = 0;
        }
        double keptsum = 0.0;
        for (size_t t = 0; t < m; ++t)
            if (row_keep[t]) keptsum += row_w[t];
        const double scale = keptsum == 0.0 ? 1.0 : rowsum / keptsum;
        for (size_t t = 0; t < m; ++t) {
            if (!row_keep[t]) continue;
            res->col.push_back(cidx[static_cast<size_t>(touched[t])]);
            res->val.push_back(row_w[t] * scale);
        }
    }
    res->ptr[static_cast<size_t>(n)] = static_cast<int64_t>(res->col.size());
    *out_handle = res;
    return static_cast<long long>(res->col.size());
}

void amgx_d2_fetch(void* handle, int64_t* ptr, int32_t* col, double* val) {
    auto* res = static_cast<D2Result*>(handle);
    std::copy(res->ptr.begin(), res->ptr.end(), ptr);
    std::copy(res->col.begin(), res->col.end(), col);
    std::copy(res->val.begin(), res->val.end(), val);
    delete res;
}

void amgx_d2_free(void* handle) { delete static_cast<D2Result*>(handle); }

}  // extern "C"
