// Serial Ruge-Stueben first-pass coarsening (bucket priority queue).
//
// TPU-native-framework native component: the reference itself declares RS
// "a sequential algorithm" and refuses to run it on the GPU
// (src/classical/selectors/rs.cu:269-277 raises); its HMIS selector copies
// the matrix to the HOST and runs this exact serial pass there
// (src/classical/selectors/hmis.cu:55-82). This C++ implementation is the
// analog of that host path: it runs once per setup on the controller CPU.
//
// Algorithm (classical RS first pass):
//   lambda_i = |S^T_i|  (number of points strongly depending on i)
//   repeat: pick unassigned i with max lambda -> COARSE;
//           unassigned j in S^T_i -> FINE;
//           for each new FINE j: lambda_k += 1 for unassigned k in S_j.
//   points left with lambda == 0 -> FINE.
//
// Buckets are doubly-linked lists indexed by lambda, giving O(n + nnz).

#include <cstddef>
#include <cstdint>
#include <vector>

namespace {

struct BucketQueue {
    // node lists per weight; a node's weight is bounded by
    // 2*|S^T_i| <= 2(n-1): the initial in-degree plus at most one bump
    // per in-edge (each neighbor turns FINE once)
    std::vector<int32_t> head;   // head[w] = first node with weight w
    std::vector<int32_t> prev, next, weight;
    int32_t maxw;

    explicit BucketQueue(int32_t n)
        : head(2 * static_cast<size_t>(n) + 2, -1), prev(n, -1),
          next(n, -1), weight(n, 0), maxw(0) {}

    void push(int32_t i, int32_t w) {
        weight[i] = w;
        prev[i] = -1;
        next[i] = head[w];
        if (head[w] >= 0) prev[head[w]] = i;
        head[w] = i;
        if (w > maxw) maxw = w;
    }

    void remove(int32_t i) {
        int32_t w = weight[i];
        if (prev[i] >= 0) next[prev[i]] = next[i];
        else head[w] = next[i];
        if (next[i] >= 0) prev[next[i]] = prev[i];
        prev[i] = next[i] = -1;
    }

    void bump(int32_t i) {  // weight[i] += 1
        remove(i);
        push(i, weight[i] + 1);
    }

    int32_t pop_max() {  // -1 when empty
        while (maxw >= 0 && head[maxw] < 0) --maxw;
        if (maxw < 0) return -1;
        int32_t i = head[maxw];
        remove(i);
        return i;
    }
};

}  // namespace

extern "C" {

// cf_map out: 0 = FINE, 1 = COARSE. strong: per-nnz boolean mask.
// Returns 0 on success.
int amgx_rs_coarsen(int32_t n, const int32_t* row_offsets,
                    const int32_t* col_indices, const uint8_t* strong,
                    int32_t* cf_map) {
    const int32_t UNASSIGNED = -1, FINE = 0, COARSE = 1;
    // S^T in CSR form (strong edges only, cols within [0, n))
    std::vector<int32_t> st_off(n + 1, 0);
    for (int32_t i = 0; i < n; ++i)
        for (int32_t j = row_offsets[i]; j < row_offsets[i + 1]; ++j)
            if (strong[j] && col_indices[j] < n && col_indices[j] != i)
                ++st_off[col_indices[j] + 1];
    for (int32_t i = 0; i < n; ++i) st_off[i + 1] += st_off[i];
    std::vector<int32_t> st_col(st_off[n]);
    {
        std::vector<int32_t> cur(st_off.begin(), st_off.end() - 1);
        for (int32_t i = 0; i < n; ++i)
            for (int32_t j = row_offsets[i]; j < row_offsets[i + 1]; ++j)
                if (strong[j] && col_indices[j] < n && col_indices[j] != i)
                    st_col[cur[col_indices[j]]++] = i;
    }

    // strong out-degree (does i depend on anyone?) for the isolated test
    std::vector<int32_t> out_deg(n, 0);
    for (int32_t i = 0; i < n; ++i)
        for (int32_t j = row_offsets[i]; j < row_offsets[i + 1]; ++j)
            if (strong[j] && col_indices[j] < n && col_indices[j] != i)
                ++out_deg[i];

    BucketQueue q(n);
    std::vector<int32_t> state(n, UNASSIGNED);
    for (int32_t i = 0; i < n; ++i) {
        int32_t lam = st_off[i + 1] - st_off[i];
        if (lam == 0) {
            // nothing depends on it: FINE — unless it is fully strong-
            // isolated (no in- OR out-edges), which cannot interpolate
            // and must be COARSE (framework convention, matching
            // pmis_split's isolated-point handling)
            state[i] = (out_deg[i] == 0) ? COARSE : FINE;
        } else {
            q.push(i, lam);
        }
    }

    for (;;) {
        int32_t i = q.pop_max();
        if (i < 0) break;
        if (state[i] != UNASSIGNED) continue;
        state[i] = COARSE;
        for (int32_t t = st_off[i]; t < st_off[i + 1]; ++t) {
            int32_t j = st_col[t];
            if (state[j] != UNASSIGNED) continue;
            state[j] = FINE;
            q.remove(j);
            for (int32_t u = row_offsets[j]; u < row_offsets[j + 1]; ++u) {
                int32_t k = col_indices[u];
                if (strong[u] && k < n && state[k] == UNASSIGNED)
                    q.bump(k);
            }
        }
    }
    for (int32_t i = 0; i < n; ++i)
        cf_map[i] = (state[i] == COARSE) ? 1 : 0;
    return 0;
}

}  // extern "C"
