// Host CSR SpGEMM: Gustavson's algorithm with a dense accumulator row.
//
// The reference computes its Galerkin products with hash-table SpGEMM
// kernels (include/csr_multiply.h, src/csr_multiply.cu); this is the
// host-side analog for the hierarchy-construction phase, where the
// sort-based jnp formulation pays ~1 s per product at 32^3 scale and
// the serial Gustavson sweep runs in milliseconds.
//
// Two-pass contract (row counts, then fill) so the caller allocates
// exact-size outputs. Columns within each output row are emitted
// sorted (std::sort per row; rows are short for stencil-like inputs).
#include <algorithm>
#include <cstdint>
#include <vector>

extern "C" {

// Pass 1: C = A(n_a x k) * B(k x n_b) pattern row counts.
// c_ptr must hold n_a + 1 entries; returns total nnz of C.
long long amgx_spgemm_count(
    int32_t n_a, int32_t n_b,
    const int32_t* a_ptr, const int32_t* a_col,
    const int32_t* b_ptr, const int32_t* b_col,
    int64_t* c_ptr) {
    std::vector<int32_t> mark(static_cast<size_t>(n_b), -1);
    long long total = 0;
    c_ptr[0] = 0;
    for (int32_t i = 0; i < n_a; ++i) {
        long long row = 0;
        for (int32_t e = a_ptr[i]; e < a_ptr[i + 1]; ++e) {
            const int32_t j = a_col[e];
            for (int32_t f = b_ptr[j]; f < b_ptr[j + 1]; ++f) {
                const int32_t c = b_col[f];
                if (mark[c] != i) {
                    mark[c] = i;
                    ++row;
                }
            }
        }
        total += row;
        c_ptr[i + 1] = total;
    }
    return total;
}

// Pass 2: numeric fill into exact-size (c_col, c_val); c_ptr from pass 1.
void amgx_spgemm_fill(
    int32_t n_a, int32_t n_b,
    const int32_t* a_ptr, const int32_t* a_col, const double* a_val,
    const int32_t* b_ptr, const int32_t* b_col, const double* b_val,
    const int64_t* c_ptr, int32_t* c_col, double* c_val) {
    std::vector<int64_t> pos(static_cast<size_t>(n_b), -1);
    std::vector<int64_t> touched;
    for (int32_t i = 0; i < n_a; ++i) {
        touched.clear();
        int64_t out = c_ptr[i];
        for (int32_t e = a_ptr[i]; e < a_ptr[i + 1]; ++e) {
            const int32_t j = a_col[e];
            const double av = a_val[e];
            for (int32_t f = b_ptr[j]; f < b_ptr[j + 1]; ++f) {
                const int32_t c = b_col[f];
                if (pos[c] < 0) {
                    pos[c] = out;
                    c_col[out] = c;
                    c_val[out] = av * b_val[f];
                    ++out;
                    touched.push_back(c);
                } else {
                    c_val[pos[c]] += av * b_val[f];
                }
            }
        }
        // emit sorted columns: sort the (col, val) pairs of this row
        const int64_t lo = c_ptr[i], hi = c_ptr[i + 1];
        std::vector<std::pair<int32_t, double>> row(
            static_cast<size_t>(hi - lo));
        for (int64_t t = lo; t < hi; ++t)
            row[static_cast<size_t>(t - lo)] = {c_col[t], c_val[t]};
        std::sort(row.begin(), row.end(),
                  [](const auto& x, const auto& y)
                  { return x.first < y.first; });
        for (int64_t t = lo; t < hi; ++t) {
            c_col[t] = row[static_cast<size_t>(t - lo)].first;
            c_val[t] = row[static_cast<size_t>(t - lo)].second;
        }
        for (int64_t c : touched) pos[static_cast<size_t>(c)] = -1;
    }
}

}  // extern "C"
