// Fast MatrixMarket numeric-body parser.
//
// Native analog of the reference's C++ reader core (src/readers.cu
// ReadMatrixMarket): one pass over the raw text, strtod per token,
// '%'-comment lines skipped. The Python reader's per-line split()
// costs ~1us per token; this parses the same body at memory speed.
//
// Exported C ABI (ctypes):
//   amgx_mm_parse(buf, len, max_count, out) -> number of doubles
//   parsed (<= max_count), or -1 on malformed input.

#include <cctype>
#include <cstdlib>
#include <locale.h>
#if !defined(__GLIBC__) && (defined(__APPLE__) || defined(__FreeBSD__))
#include <xlocale.h>   // strtod_l lives here on macOS/BSD
#endif

extern "C" long long amgx_mm_parse(const char *buf, long long len,
                                   long long max_count, double *out) {
    // strtod is LC_NUMERIC-dependent; parse under the C locale so an
    // embedding app's setlocale() cannot corrupt values
    static locale_t c_loc = newlocale(LC_ALL_MASK, "C", (locale_t)0);
    const char *p = buf;
    const char *end = buf + len;
    long long count = 0;
    bool at_line_start = true;
    while (p < end && count < max_count) {
        char ch = *p;
        if (ch == '\n') {
            at_line_start = true;
            ++p;
            continue;
        }
        if (ch == ' ' || ch == '\t' || ch == '\r') {
            ++p;
            continue;
        }
        if (at_line_start && ch == '%') {        // comment line
            while (p < end && *p != '\n') ++p;
            continue;
        }
        at_line_start = false;
        char *next = nullptr;
        double v = c_loc ? strtod_l(p, &next, c_loc) : strtod(p, &next);
        if (next == p) return -1;                // not a number
        if (next > end) return -1;               // ran past the buffer
        out[count++] = v;
        p = next;
    }
    return count;
}
