// Host construction of the windowed-ELL (SWELL) layout
// (ops/pallas_swell.py) — the storage of the Pallas TPU gather SpMV for
// unstructured matrices (the csrmv analog, src/multiply.cu:74-121).
//
// The numpy formulation costs seconds per hierarchy at 64^3 scale
// (reduceat window scans + giant fancy-index scatters); these are the
// same sweeps as single O(nnz) passes.
//
// Layout contract (must match build_swell_host): rows tile into
// super-blocks of 1024 (8 sublane groups x 128 lanes); per block the
// column window starts at c0 = (min col // 128) * 128; entries store
// slot-major as (nb, 8, kpad, 128) with local columns ci - c0.
#include <algorithm>
#include <cstdint>

namespace {
constexpr int32_t LANES = 128;
constexpr int32_t SUBS = 8;
constexpr int32_t BLOCK_ROWS = SUBS * LANES;
}  // namespace

extern "C" {

// Per-super-block window scan. Writes c0row[nb] (window start in
// 128-rows) and nchunk[nb] (populated 128-chunks); *out_kmax gets the
// max row length. Returns the max window width in 128-chunks (w128),
// 0 when the matrix has no entries.
int32_t amgx_swell_windows(
    int32_t n, const int32_t* ro, const int32_t* ci,
    int32_t* c0row, int32_t* nchunk, int32_t* out_kmax) {
    const int32_t nb = (n + BLOCK_ROWS - 1) / BLOCK_ROWS;
    int32_t kmax = 0, w128 = 0;
    for (int32_t b = 0; b < nb; ++b) {
        const int32_t r0 = b * BLOCK_ROWS;
        const int32_t r1 = std::min(n, r0 + BLOCK_ROWS);
        int32_t bmin = INT32_MAX, bmax = -1;
        for (int32_t i = r0; i < r1; ++i) {
            const int32_t len = ro[i + 1] - ro[i];
            if (len > kmax) kmax = len;
            for (int32_t e = ro[i]; e < ro[i + 1]; ++e) {
                const int32_t c = ci[e];
                if (c < bmin) bmin = c;
                if (c > bmax) bmax = c;
            }
        }
        if (bmax < 0) { bmin = 0; bmax = 0; }  // empty block
        const int32_t c0 = (bmin / LANES) * LANES;
        const int32_t span = bmax - c0 + 1;
        const int32_t chunks = (span + LANES - 1) / LANES;
        c0row[b] = c0 / LANES;
        nchunk[b] = chunks;
        if (chunks > w128) w128 = chunks;
    }
    *out_kmax = kmax;
    return w128;
}

// Scatter entries into caller-zeroed (nb, 8, kpad, 128) slot-major
// buffers. Local column = ci - c0row[block] * 128.
#define SWELL_FILL(name, T)                                              \
    void name(int32_t n, int32_t kpad, const int32_t* ro,                \
              const int32_t* ci, const T* vals, const int32_t* c0row,    \
              int32_t* cols4, T* vals4) {                                \
        for (int32_t i = 0; i < n; ++i) {                                \
            const int32_t b = i / BLOCK_ROWS;                            \
            const int32_t sub = (i % BLOCK_ROWS) / LANES;                \
            const int32_t lane = i & (LANES - 1);                        \
            const int32_t c0 = c0row[b] * LANES;                         \
            const int64_t base =                                         \
                ((static_cast<int64_t>(b) * SUBS + sub) * kpad) * LANES  \
                + lane;                                                  \
            int64_t slot = 0;                                            \
            for (int32_t e = ro[i]; e < ro[i + 1]; ++e, ++slot) {        \
                const int64_t t = base + slot * LANES;                   \
                cols4[t] = ci[e] - c0;                                   \
                vals4[t] = vals[e];                                      \
            }                                                            \
        }                                                                \
    }

SWELL_FILL(amgx_swell_fill_f64, double)
SWELL_FILL(amgx_swell_fill_f32, float)

// Values-only re-scatter (replace_coefficients with structure reuse).
#define SWELL_REFILL(name, T)                                            \
    void name(int32_t n, int32_t kpad, const int32_t* ro, const T* vals, \
              T* vals4) {                                                \
        for (int32_t i = 0; i < n; ++i) {                                \
            const int32_t b = i / BLOCK_ROWS;                            \
            const int32_t sub = (i % BLOCK_ROWS) / LANES;                \
            const int64_t base =                                         \
                ((static_cast<int64_t>(b) * SUBS + sub) * kpad) * LANES  \
                + (i & (LANES - 1));                                     \
            int64_t slot = 0;                                            \
            for (int32_t e = ro[i]; e < ro[i + 1]; ++e, ++slot)          \
                vals4[base + slot * LANES] = vals[e];                    \
        }                                                                \
    }

SWELL_REFILL(amgx_swell_refill_f64, double)
SWELL_REFILL(amgx_swell_refill_f32, float)

}  // extern "C"
