// Plan-split Galerkin RAP: the VALUES-ONLY numeric sweep.
//
// The structure phase (ops/spgemm.py RapPlan) has already fixed the
// expansion gather indices, the lexsorted coalesce order and the
// per-entry segment boundaries, so — unlike amgx_rap_build's
// Gustavson sweep (rap.cpp), which rediscovers the output pattern
// with stamp/accumulator bookkeeping on every call — this sweep is
// two flat passes of pure fused multiply-adds through precomputed
// indices. This is the host-route payoff of the symbolic/numeric
// split: a warm setup or value resetup pays only this.
//
//   stage 1 (optional): t[k]   = sum_{e in [s1[k], s1[k+1])}
//                                    a[sa[e]] * p[sp[e]]
//   stage 2:            out[u] = sum_{f in [s2[u], s2[u+1])}
//                                    (r[sr[f]] *) base[st[f]]
//
// base = t (two-stage triple product) or a itself (the aggregation
// relabel form, has_stage1 = 0). Summation is strict left-to-right
// per segment, matching the numpy reduceat fallback's short-segment
// order.
#include <cstdint>
#include <vector>

extern "C" {

// Segment boundaries arrive int32 (candidate totals are guarded
// < 2^31 by the plan builders, and the int32 form halves the plan's
// index memory at 128^3 scale).
int32_t amgx_rap_plan_values(
    int64_t n_t, const int32_t* sa, const int32_t* sp,
    const int32_t* s1,
    int64_t n_u, const int32_t* sr, const int32_t* st,
    const int32_t* s2,
    const double* a, const double* p, const double* r,
    int32_t has_stage1, int32_t has_r, double* out) {
    std::vector<double> t_buf;
    const double* base = a;
    if (has_stage1) {
        t_buf.resize(static_cast<size_t>(n_t));
        for (int64_t k = 0; k < n_t; ++k) {
            double acc = 0.0;
            for (int32_t e = s1[k]; e < s1[k + 1]; ++e) {
                acc += a[sa[e]] * p[sp[e]];
            }
            t_buf[static_cast<size_t>(k)] = acc;
        }
        base = t_buf.data();
    }
    if (has_r) {
        for (int64_t u = 0; u < n_u; ++u) {
            double acc = 0.0;
            for (int32_t f = s2[u]; f < s2[u + 1]; ++f) {
                acc += r[sr[f]] * base[st[f]];
            }
            out[u] = acc;
        }
    } else {
        for (int64_t u = 0; u < n_u; ++u) {
            double acc = 0.0;
            for (int32_t f = s2[u]; f < s2[u + 1]; ++f) {
                acc += base[st[f]];
            }
            out[u] = acc;
        }
    }
    return 0;
}

}  // extern "C"
