"""Native (C++) runtime components.

The reference keeps inherently serial setup algorithms on the host in
C++ (e.g. Ruge-Stueben coarsening, src/classical/selectors/rs.cu:269
refuses the GPU path outright). This package holds the analogous native
pieces: small C++ translation units compiled once into a shared library
with the system toolchain and bound via ctypes — no Python stand-ins for
the serial hot paths.

`lib()` compiles on first use (cached in _build/, invalidated by source
mtime) and returns the loaded ctypes library, or None when no compiler
is available — callers fall back to their pure-Python equivalent.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "src")
_BUILD = os.path.join(_DIR, "_build")
_LIB_PATH = os.path.join(_BUILD, "libamgx_native.so")

_lock = threading.Lock()
_lib = None
_attempted_sig = None     # source signature of the last build attempt


def _src_signature():
    return tuple(sorted(
        (f, os.path.getmtime(os.path.join(_SRC, f)))
        for f in os.listdir(_SRC) if f.endswith(".cpp")))


def _lib_current(sig) -> bool:
    if not os.path.exists(_LIB_PATH):
        return False
    lib_mtime = os.path.getmtime(_LIB_PATH)
    return all(mtime <= lib_mtime for _, mtime in sig)


def _build() -> bool:
    os.makedirs(_BUILD, exist_ok=True)
    srcs = sorted(
        os.path.join(_SRC, f) for f in os.listdir(_SRC)
        if f.endswith(".cpp"))
    cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC",
           "-o", _LIB_PATH] + srcs
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return True
    except (subprocess.SubprocessError, FileNotFoundError):
        return False


def lib():
    """The loaded native library, or None if unavailable. A failed build
    is cached per source signature — no repeated compiler spawns."""
    global _lib, _attempted_sig
    with _lock:
        sig = _src_signature()
        if _attempted_sig == sig:
            return _lib
        _attempted_sig = sig
        _lib = None
        if not _lib_current(sig) and not _build():
            return None
        try:
            _lib = ctypes.CDLL(_LIB_PATH)
        except OSError:
            _lib = None
    return _lib


def rs_coarsen_native(n, row_offsets, col_indices, strong):
    """Native RS first-pass coarsening; returns cf_map (n,) int32 or
    None when the native library is unavailable."""
    import numpy as np
    L = lib()
    if L is None:
        return None
    fn = L.amgx_rs_coarsen
    fn.restype = ctypes.c_int
    ro = np.ascontiguousarray(row_offsets, np.int32)
    ci = np.ascontiguousarray(col_indices, np.int32)
    st = np.ascontiguousarray(strong, np.uint8)
    cf = np.empty(n, np.int32)
    i32p = ctypes.POINTER(ctypes.c_int32)
    u8p = ctypes.POINTER(ctypes.c_uint8)
    rc = fn(ctypes.c_int32(n),
            ro.ctypes.data_as(i32p), ci.ctypes.data_as(i32p),
            st.ctypes.data_as(u8p), cf.ctypes.data_as(i32p))
    if rc != 0:
        return None
    return cf
