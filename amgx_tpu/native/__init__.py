"""Native (C++) runtime components.

The reference keeps inherently serial setup algorithms on the host in
C++ (e.g. Ruge-Stueben coarsening, src/classical/selectors/rs.cu:269
refuses the GPU path outright). This package holds the analogous native
pieces: small C++ translation units compiled once into a shared library
with the system toolchain and bound via ctypes — no Python stand-ins for
the serial hot paths.

`lib()` compiles on first use and returns the loaded ctypes library, or
None when no compiler is available — callers fall back to their
pure-Python equivalents. Build artifacts live in _build/ (gitignored),
keyed by a content hash of the sources so stale binaries are never
loaded; the .so is written atomically so concurrent processes cannot
load a half-written file.
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
import warnings

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "src")
_BUILD = os.path.join(_DIR, "_build")

_lock = threading.Lock()
_lib = None
_attempted_hash = None    # content hash of the last build attempt


def _src_files():
    return sorted(
        os.path.join(_SRC, f) for f in os.listdir(_SRC)
        if f.endswith(".cpp"))


def _src_hash() -> str:
    h = hashlib.sha256()
    for path in _src_files():
        h.update(os.path.basename(path).encode())
        with open(path, "rb") as f:
            h.update(f.read())
    return h.hexdigest()[:16]


def _lib_path(src_hash: str) -> str:
    return os.path.join(_BUILD, f"libamgx_native-{src_hash}.so")


def _build(target: str) -> bool:
    os.makedirs(_BUILD, exist_ok=True)
    tmp = target + f".tmp{os.getpid()}"
    cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC",
           "-o", tmp] + _src_files()
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, target)          # atomic publish
    except (subprocess.SubprocessError, FileNotFoundError, OSError) as e:
        detail = ""
        stderr = getattr(e, "stderr", None)
        if stderr:
            detail = ": " + stderr.decode("utf-8", "replace")[-300:]
        warnings.warn(
            "native library build failed; native fast paths disabled, "
            "pure-Python fallbacks in use" + detail, RuntimeWarning)
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False
    # prune superseded builds; best-effort, must not fail the build
    try:
        for f in os.listdir(_BUILD):
            p = os.path.join(_BUILD, f)
            if f.endswith(".so") and p != target:
                try:
                    os.unlink(p)
                except OSError:
                    pass
    except OSError:
        pass
    return True


def lib():
    """The loaded native library, or None if unavailable. A failed build
    is cached per source hash — no repeated compiler spawns."""
    global _lib, _attempted_hash
    with _lock:
        h = _src_hash()
        if _attempted_hash == h:
            return _lib
        _attempted_hash = h
        _lib = None
        target = _lib_path(h)
        if not os.path.exists(target) and not _build(target):
            return None
        try:
            _lib = ctypes.CDLL(target)
        except OSError:
            _lib = None
    return _lib


_warned_fallback = False


def warn_python_fallback(component: str, n: int):
    """One-shot warning when a serial native component falls back to
    Python on a large problem."""
    global _warned_fallback
    if not _warned_fallback and n > 100_000:
        _warned_fallback = True
        warnings.warn(
            f"native library unavailable (no C++ toolchain?); {component} "
            f"is running its pure-Python fallback on n={n} rows — setup "
            "will be slow", RuntimeWarning)


def rs_coarsen_native(n, row_offsets, col_indices, strong):
    """Native RS first-pass coarsening; returns cf_map (n,) int32 or
    None when the native library is unavailable."""
    import numpy as np
    L = lib()
    if L is None:
        return None
    fn = L.amgx_rs_coarsen
    fn.restype = ctypes.c_int
    ro = np.ascontiguousarray(row_offsets, np.int32)
    ci = np.ascontiguousarray(col_indices, np.int32)
    st = np.ascontiguousarray(strong, np.uint8)
    cf = np.empty(n, np.int32)
    i32p = ctypes.POINTER(ctypes.c_int32)
    u8p = ctypes.POINTER(ctypes.c_uint8)
    rc = fn(ctypes.c_int32(n),
            ro.ctypes.data_as(i32p), ci.ctypes.data_as(i32p),
            st.ctypes.data_as(u8p), cf.ctypes.data_as(i32p))
    if rc != 0:
        return None
    return cf


def pmis_native(n, row_offsets, col_indices, strong, init=None,
                max_iters=30):
    """Native PMIS CF-splitting (bit-exact replica of the jnp fixed
    point in amg/classical/selectors.py::pmis_split); returns cf (n,)
    int32 or None when the native library is unavailable."""
    import numpy as np
    L = lib()
    if L is None:
        return None
    fn = L.amgx_pmis
    fn.restype = ctypes.c_int
    ro = np.ascontiguousarray(row_offsets, np.int32)
    ci = np.ascontiguousarray(col_indices, np.int32)
    st = np.ascontiguousarray(strong, np.uint8)
    cf = np.empty(n, np.int32)
    i32p = ctypes.POINTER(ctypes.c_int32)
    u8p = ctypes.POINTER(ctypes.c_uint8)
    if init is not None:
        init = np.ascontiguousarray(init, np.int32)
        init_p = init.ctypes.data_as(i32p)
    else:
        init_p = None
    rc = fn(ctypes.c_int32(int(n)),
            ro.ctypes.data_as(i32p), ci.ctypes.data_as(i32p),
            st.ctypes.data_as(u8p), init_p,
            ctypes.c_int32(int(max_iters)), cf.ctypes.data_as(i32p))
    if rc != 0:
        return None
    return cf


def strength_ahat_native(n, row_offsets, col_indices, values, theta,
                         max_row_sum):
    """Native AHAT strength mask; returns strong (nnz,) bool or None
    when the native library is unavailable."""
    import numpy as np
    L = lib()
    if L is None:
        return None
    fn = L.amgx_strength_ahat
    fn.restype = None
    i32p = ctypes.POINTER(ctypes.c_int32)
    f64p = ctypes.POINTER(ctypes.c_double)
    u8p = ctypes.POINTER(ctypes.c_uint8)
    ro = np.ascontiguousarray(row_offsets, np.int32)
    ci = np.ascontiguousarray(col_indices, np.int32)
    va = np.ascontiguousarray(values, np.float64)
    strong = np.empty(ci.shape[0], np.uint8)
    fn(ctypes.c_int32(int(n)), ro.ctypes.data_as(i32p),
       ci.ctypes.data_as(i32p), va.ctypes.data_as(f64p),
       ctypes.c_double(float(theta)), ctypes.c_double(float(max_row_sum)),
       strong.ctypes.data_as(u8p))
    return strong.view(np.bool_)


def l1_diag_native(n, row_offsets, col_indices, values):
    """Native L1-strengthened Jacobi diagonal; returns (n,) float64 or
    None when the native library is unavailable."""
    import numpy as np
    L = lib()
    if L is None:
        return None
    fn = L.amgx_l1_diag
    fn.restype = None
    i32p = ctypes.POINTER(ctypes.c_int32)
    f64p = ctypes.POINTER(ctypes.c_double)
    ro = np.ascontiguousarray(row_offsets, np.int32)
    ci = np.ascontiguousarray(col_indices, np.int32)
    va = np.ascontiguousarray(values, np.float64)
    out = np.empty(int(n), np.float64)
    fn(ctypes.c_int32(int(n)), ro.ctypes.data_as(i32p),
       ci.ctypes.data_as(i32p), va.ctypes.data_as(f64p),
       out.ctypes.data_as(f64p))
    return out


def d2_interp_native(n, row_offsets, col_indices, values, strong, cf,
                     trunc_factor=1.1, max_elements=-1):
    """Native distance-two ext+i interpolation (the host analog of
    src/classical/interpolators/distance2.cu) with fused truncation.
    Returns (p_ptr int64 (n+1,), p_col int32, p_val float64) or None."""
    import numpy as np
    L = lib()
    if L is None:
        return None
    build = L.amgx_d2_build
    build.restype = ctypes.c_longlong
    fetch = L.amgx_d2_fetch
    fetch.restype = None
    i32p = ctypes.POINTER(ctypes.c_int32)
    i64p = ctypes.POINTER(ctypes.c_int64)
    f64p = ctypes.POINTER(ctypes.c_double)
    u8p = ctypes.POINTER(ctypes.c_uint8)
    ro = np.ascontiguousarray(row_offsets, np.int32)
    ci = np.ascontiguousarray(col_indices, np.int32)
    va = np.ascontiguousarray(values, np.float64)
    st = np.ascontiguousarray(strong, np.uint8)
    cfm = np.ascontiguousarray(cf, np.int32)
    handle = ctypes.c_void_p()
    nnz = build(ctypes.c_int32(int(n)),
                ro.ctypes.data_as(i32p), ci.ctypes.data_as(i32p),
                va.ctypes.data_as(f64p), st.ctypes.data_as(u8p),
                cfm.ctypes.data_as(i32p),
                ctypes.c_double(float(trunc_factor)),
                ctypes.c_int32(int(max_elements)), ctypes.byref(handle))
    if nnz < 0 or not handle:
        return None
    p_ptr = np.empty(int(n) + 1, np.int64)
    p_col = np.empty(int(nnz), np.int32)
    p_val = np.empty(int(nnz), np.float64)
    fetch(handle, p_ptr.ctypes.data_as(i64p),
          p_col.ctypes.data_as(i32p), p_val.ctypes.data_as(f64p))
    return p_ptr, p_col, p_val


def rap_native(nc, n, ncp, r_ptr, r_col, r_val, a_ptr, a_col, a_val,
               p_ptr, p_col, p_val):
    """Fused native Galerkin triple product C = R@A@P (scalar CSR; the
    csr_galerkin_product analog). Returns (c_ptr int64 (nc+1,), c_col
    int32, c_val float64) with sorted columns per row, or None when the
    native library is unavailable."""
    import numpy as np
    L = lib()
    if L is None:
        return None
    i32p = ctypes.POINTER(ctypes.c_int32)
    i64p = ctypes.POINTER(ctypes.c_int64)
    f64p = ctypes.POINTER(ctypes.c_double)
    build = L.amgx_rap_build
    build.restype = ctypes.c_longlong
    fetch = L.amgx_rap_fetch
    fetch.restype = None
    rp = np.ascontiguousarray(r_ptr, np.int32)
    rc = np.ascontiguousarray(r_col, np.int32)
    rv = np.ascontiguousarray(r_val, np.float64)
    ap = np.ascontiguousarray(a_ptr, np.int32)
    ac = np.ascontiguousarray(a_col, np.int32)
    av = np.ascontiguousarray(a_val, np.float64)
    pp = np.ascontiguousarray(p_ptr, np.int32)
    pc = np.ascontiguousarray(p_col, np.int32)
    pv = np.ascontiguousarray(p_val, np.float64)
    handle = ctypes.c_void_p()
    nnz = build(ctypes.c_int32(int(nc)), ctypes.c_int32(int(n)),
                ctypes.c_int32(int(ncp)),
                rp.ctypes.data_as(i32p), rc.ctypes.data_as(i32p),
                rv.ctypes.data_as(f64p),
                ap.ctypes.data_as(i32p), ac.ctypes.data_as(i32p),
                av.ctypes.data_as(f64p),
                pp.ctypes.data_as(i32p), pc.ctypes.data_as(i32p),
                pv.ctypes.data_as(f64p), ctypes.byref(handle))
    if nnz < 0 or not handle:
        return None
    c_ptr = np.empty(int(nc) + 1, np.int64)
    c_col = np.empty(int(nnz), np.int32)
    c_val = np.empty(int(nnz), np.float64)
    fetch(handle, c_ptr.ctypes.data_as(i64p),
          c_col.ctypes.data_as(i32p), c_val.ctypes.data_as(f64p))
    return c_ptr, c_col, c_val


def rap_plan_values_native(stage1, sr, st, starts2, n_u, a_val, p_val,
                           r_val):
    """Values-only Galerkin RAP sweep through a RapPlan's precomputed
    indices (src/rap_values.cpp): two flat FMA passes, no structure
    discovery. `stage1` is the plan's stage-1 dict or None (the
    aggregation relabel form); `sr`/`r_val` / `p_val` may be None.
    Returns the (n_u,) float64 value vector or None when the native
    library is unavailable (callers fall back to the numpy reduceat
    route — same sums, same order)."""
    import numpy as np
    L = lib()
    if L is None:
        return None
    i32p = ctypes.POINTER(ctypes.c_int32)
    f64p = ctypes.POINTER(ctypes.c_double)
    fn = L.amgx_rap_plan_values
    fn.restype = ctypes.c_int32
    av = np.ascontiguousarray(a_val, np.float64)
    keep = []        # retain converted temporaries across the call

    def ip32(x):
        x = np.ascontiguousarray(x, np.int32)
        keep.append(x)
        return x.ctypes.data_as(i32p)

    null32 = ctypes.cast(None, i32p)
    null64f = ctypes.cast(None, f64p)
    if stage1 is not None:
        pv = np.ascontiguousarray(p_val, np.float64)
        args1 = (ctypes.c_int64(int(stage1["nT"])), ip32(stage1["sa"]),
                 ip32(stage1["sp"]), ip32(stage1["starts1"]),)
        pvp = pv.ctypes.data_as(f64p)
    else:
        pv = None
        args1 = (ctypes.c_int64(0), null32, null32, null32)
        pvp = null64f
    if sr is not None:
        rv = np.ascontiguousarray(r_val, np.float64)
        rvp = rv.ctypes.data_as(f64p)
        srp = ip32(sr)
    else:
        rv = None
        rvp = null64f
        srp = null32
    out = np.empty(int(n_u), np.float64)
    rc = fn(*args1, ctypes.c_int64(int(n_u)), srp, ip32(st),
            ip32(starts2), av.ctypes.data_as(f64p), pvp, rvp,
            ctypes.c_int32(1 if stage1 is not None else 0),
            ctypes.c_int32(1 if sr is not None else 0),
            out.ctypes.data_as(f64p))
    if rc != 0:
        return None
    return out


def swell_build_native(ro, ci, vals, num_rows):
    """Native SWELL layout build (ops/pallas_swell.py layout contract).
    Returns (cols4, vals4, c0row, nchunk, w128) with cols4/vals4 shaped
    (nb, 8, kpad, 128), None when the layout does not pay (budget
    decisions delegated to ops/pallas_swell.swell_budget), or False
    when the native library is unavailable."""
    import numpy as np
    from ..ops.pallas_swell import BLOCK_ROWS, LANES, SUBS, swell_budget
    L = lib()
    vals = np.asarray(vals)
    if L is None or vals.dtype not in (np.float32, np.float64):
        return False
    n = int(num_rows)
    nb = -(-n // BLOCK_ROWS)
    i32p = ctypes.POINTER(ctypes.c_int32)
    win = L.amgx_swell_windows
    win.restype = ctypes.c_int32
    ro = np.ascontiguousarray(ro, np.int32)
    ci = np.ascontiguousarray(ci, np.int32)
    c0row = np.empty(nb, np.int32)
    nchunk = np.empty(nb, np.int32)
    kmax = ctypes.c_int32()
    w128_raw = win(ctypes.c_int32(n), ro.ctypes.data_as(i32p),
                   ci.ctypes.data_as(i32p), c0row.ctypes.data_as(i32p),
                   nchunk.ctypes.data_as(i32p), ctypes.byref(kmax))
    # budget decisions live in ONE place (ops/pallas_swell.swell_budget)
    budget = swell_budget(int(kmax.value), w128_raw, nb, ci.shape[0])
    if budget is None:
        return None
    kpad, w128 = budget
    slots = nb * SUBS * kpad * LANES
    vals = np.ascontiguousarray(vals)
    if vals.dtype == np.float32:
        fill, fp = L.amgx_swell_fill_f32, ctypes.POINTER(ctypes.c_float)
    else:
        vals = np.ascontiguousarray(vals, np.float64)
        fill, fp = L.amgx_swell_fill_f64, ctypes.POINTER(ctypes.c_double)
    fill.restype = None
    cols4 = np.zeros(slots, np.int32)
    vals4 = np.zeros(slots, vals.dtype)
    fill(ctypes.c_int32(n), ctypes.c_int32(kpad),
         ro.ctypes.data_as(i32p), ci.ctypes.data_as(i32p),
         vals.ctypes.data_as(fp), c0row.ctypes.data_as(i32p),
         cols4.ctypes.data_as(i32p), vals4.ctypes.data_as(fp))
    return (cols4.reshape(nb, SUBS, kpad, LANES),
            vals4.reshape(nb, SUBS, kpad, LANES), c0row, nchunk, w128)


def swell_refill_native(ro, vals, num_rows, kpad):
    """Values-only SWELL re-scatter; returns (nb, 8, kpad, 128) vals4 or
    None when the native library is unavailable."""
    import numpy as np
    from ..ops.pallas_swell import BLOCK_ROWS, LANES, SUBS
    L = lib()
    vals = np.asarray(vals)
    if L is None or vals.dtype not in (np.float32, np.float64):
        return None
    n = int(num_rows)
    nb = -(-n // BLOCK_ROWS)
    i32p = ctypes.POINTER(ctypes.c_int32)
    ro = np.ascontiguousarray(ro, np.int32)
    vals = np.ascontiguousarray(vals)
    if vals.dtype == np.float32:
        fn, fp = L.amgx_swell_refill_f32, ctypes.POINTER(ctypes.c_float)
    else:
        vals = np.ascontiguousarray(vals, np.float64)
        fn, fp = L.amgx_swell_refill_f64, ctypes.POINTER(ctypes.c_double)
    fn.restype = None
    vals4 = np.zeros(nb * SUBS * kpad * LANES, vals.dtype)
    fn(ctypes.c_int32(n), ctypes.c_int32(kpad),
       ro.ctypes.data_as(i32p), vals.ctypes.data_as(fp),
       vals4.ctypes.data_as(fp))
    return vals4.reshape(nb, SUBS, kpad, LANES)


def spgemm_native(n_a, n_b, a_ptr, a_col, a_val, b_ptr, b_col, b_val):
    """Native Gustavson CSR SpGEMM (csr_multiply.h analog). Returns
    (c_ptr int64 (n_a+1,), c_col int32, c_val float64) with sorted
    columns per row, or None when the native library is unavailable."""
    import numpy as np
    L = lib()
    if L is None:
        return None
    i32p = ctypes.POINTER(ctypes.c_int32)
    i64p = ctypes.POINTER(ctypes.c_int64)
    f64p = ctypes.POINTER(ctypes.c_double)
    count = L.amgx_spgemm_count
    count.restype = ctypes.c_longlong
    fill = L.amgx_spgemm_fill
    fill.restype = None
    ap = np.ascontiguousarray(a_ptr, np.int32)
    ac = np.ascontiguousarray(a_col, np.int32)
    av = np.ascontiguousarray(a_val, np.float64)
    bp = np.ascontiguousarray(b_ptr, np.int32)
    bc = np.ascontiguousarray(b_col, np.int32)
    bv = np.ascontiguousarray(b_val, np.float64)
    cp = np.empty(int(n_a) + 1, np.int64)
    nnz = count(ctypes.c_int32(int(n_a)), ctypes.c_int32(int(n_b)),
                ap.ctypes.data_as(i32p), ac.ctypes.data_as(i32p),
                bp.ctypes.data_as(i32p), bc.ctypes.data_as(i32p),
                cp.ctypes.data_as(i64p))
    cc = np.empty(int(nnz), np.int32)
    cv = np.empty(int(nnz), np.float64)
    fill(ctypes.c_int32(int(n_a)), ctypes.c_int32(int(n_b)),
         ap.ctypes.data_as(i32p), ac.ctypes.data_as(i32p),
         av.ctypes.data_as(f64p),
         bp.ctypes.data_as(i32p), bc.ctypes.data_as(i32p),
         bv.ctypes.data_as(f64p),
         cp.ctypes.data_as(i64p), cc.ctypes.data_as(i32p),
         cv.ctypes.data_as(f64p))
    return cp, cc, cv
