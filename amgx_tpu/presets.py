"""Named solver presets (side-effect-free; safe to import anywhere).

FLAGSHIP is the configuration the benchmarks and the driver entry use:
full f64 accuracy via defect correction (REFINEMENT) around an f32
FGMRES + GEO-aggregation AMG V-cycle with Chebyshev-polynomial
smoothing — the TPU-optimal shape for structured (stencil) systems.
See README.md "TPU-first design" for why each piece is chosen.
"""

FLAGSHIP = (
    "solver=REFINEMENT, max_iters=20, monitor_residual=1, tolerance=1e-8,"
    " convergence=RELATIVE_INI, norm=L2,"
    " preconditioner(in)=FGMRES, in:max_iters=60, in:monitor_residual=1,"
    " in:tolerance=1e-6, in:gmres_n_restart=10, in:convergence=RELATIVE_INI,"
    " in:norm=L2, in:preconditioner(amg)=AMG, amg:algorithm=AGGREGATION,"
    " amg:selector=GEO, amg:smoother=CHEBYSHEV_POLY,"
    " amg:chebyshev_polynomial_order=2, amg:presweeps=1, amg:postsweeps=1,"
    " amg:max_iters=1, amg:cycle=V, amg:max_levels=50,"
    " amg:min_coarse_rows=32")
