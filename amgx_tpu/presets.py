"""Named solver presets (side-effect-free; safe to import anywhere).

FLAGSHIP is the configuration the benchmarks and the driver entry use:
full f64 accuracy via defect correction (REFINEMENT) around an f32
FGMRES + GEO-aggregation AMG V-cycle with Chebyshev-polynomial
smoothing — the TPU-optimal shape for structured (stencil) systems.
See README.md "TPU-first design" for why each piece is chosen.
"""

FLAGSHIP = (
    "solver=REFINEMENT, max_iters=20, monitor_residual=1, tolerance=1e-8,"
    " convergence=RELATIVE_INI, norm=L2,"
    " preconditioner(in)=FGMRES, in:max_iters=60, in:monitor_residual=1,"
    " in:tolerance=1e-6, in:gmres_n_restart=10, in:convergence=RELATIVE_INI,"
    " in:norm=L2, in:preconditioner(amg)=AMG, amg:algorithm=AGGREGATION,"
    " amg:selector=GEO, amg:smoother=CHEBYSHEV_POLY,"
    " amg:chebyshev_polynomial_order=2, amg:presweeps=1, amg:postsweeps=1,"
    " amg:max_iters=1, amg:cycle=V, amg:max_levels=50,"
    " amg:min_coarse_rows=32")

# Batched-serving presets (amgx_tpu/batch/): structure_reuse_levels=-1 is
# load-bearing — multi-matrix batches reuse ONE hierarchy structure and
# splice per-system values through the resetup path, and the request
# batcher assumes a resetup never re-coarsens.

# CG + aggregation-AMG V-cycle with Jacobi-L1 smoothing: every piece is
# value-parameterized through solve_data (no trace-baked spectra), so a
# whole bucket runs under one vmapped trace.
BATCHED_CG = (
    "solver(s)=PCG, s:max_iters=100, s:tolerance=1e-8,"
    " s:convergence=RELATIVE_INI, s:norm=L2, s:monitor_residual=1,"
    " s:preconditioner(amg)=AMG, amg:algorithm=AGGREGATION,"
    " amg:selector=SIZE_2, amg:smoother(sm)=JACOBI_L1, sm:max_iters=1,"
    " amg:presweeps=1, amg:postsweeps=1, amg:cycle=V, amg:max_iters=1,"
    " amg:coarse_solver=DENSE_LU_SOLVER, amg:min_coarse_rows=32,"
    " amg:max_levels=20, amg:structure_reuse_levels=-1")

# Resilient serving preset (amgx_tpu/resilience/): CG + AMG with the
# full guard stack on — NaN storms retry (transient-fault model), a CG
# breakdown re-runs as GMRES, a stall escalates the smoother sweeps.
# The status classification rides the residual the monitor already
# computes, so the guards add no per-iteration host syncs.
RESILIENT_CG = (
    "solver(s)=PCG, s:max_iters=100, s:tolerance=1e-8,"
    " s:convergence=RELATIVE_INI, s:norm=L2, s:monitor_residual=1,"
    " s:health_guards=1, s:stall_detection_window=10,"
    " s:preconditioner(amg)=AMG, amg:algorithm=AGGREGATION,"
    " amg:selector=SIZE_2, amg:smoother(sm)=JACOBI_L1, sm:max_iters=1,"
    " amg:presweeps=1, amg:postsweeps=1, amg:cycle=V, amg:max_iters=1,"
    " amg:coarse_solver=DENSE_LU_SOLVER, amg:min_coarse_rows=32,"
    " amg:max_levels=20,"
    " fallback_policy=NAN_DETECTED>retry|BREAKDOWN>switch_solver=GMRES"
    "|STALLED>escalate_sweeps, max_fallback_attempts=2")

# Serving preset (amgx_tpu/serving/): the continuous-batching service
# shape whose coefficient updates take the FUSED value-only resetup
# (amg/value_resetup.py — GEO/DIA hierarchy, CHEBYSHEV_POLY smoothing,
# DENSE_LU coarse): a hierarchy-cache hit then admits a repeat-pattern
# system through the one-dispatch value splice, the 0.43 s-vs-17 s
# routing decision the serving telemetry watches. Needs a structured
# grid (gallery matrices carry grid_shape); unstructured request
# streams should serve BATCHED_CG instead (same service, generic
# structure-reuse resetup routing).
SERVING_CG = (
    "solver(s)=PCG, s:max_iters=100, s:tolerance=1e-8,"
    " s:convergence=RELATIVE_INI, s:norm=L2, s:monitor_residual=1,"
    " s:preconditioner(amg)=AMG, amg:algorithm=AGGREGATION,"
    " amg:selector=GEO, amg:smoother=CHEBYSHEV_POLY,"
    " amg:chebyshev_polynomial_order=2, amg:presweeps=1,"
    " amg:postsweeps=1, amg:cycle=V, amg:max_iters=1,"
    " amg:coarse_solver=DENSE_LU_SOLVER, amg:min_coarse_rows=32,"
    " amg:max_levels=20, amg:structure_reuse_levels=-1")

# GMRES variant for nonsymmetric request streams (same AMG shape).
BATCHED_GMRES = (
    "solver(s)=GMRES, s:max_iters=100, s:tolerance=1e-8,"
    " s:convergence=RELATIVE_INI, s:norm=L2, s:monitor_residual=1,"
    " s:gmres_n_restart=20, s:preconditioner(amg)=AMG,"
    " amg:algorithm=AGGREGATION, amg:selector=SIZE_2,"
    " amg:smoother(sm)=JACOBI_L1, sm:max_iters=1, amg:presweeps=1,"
    " amg:postsweeps=1, amg:cycle=V, amg:max_iters=1,"
    " amg:coarse_solver=DENSE_LU_SOLVER, amg:min_coarse_rows=32,"
    " amg:max_levels=20, amg:structure_reuse_levels=-1")
