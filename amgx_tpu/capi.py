"""C-API-compatible surface (amgx_c shim).

TPU-native analog of the reference's public C API (include/amgx_c.h,
src/amgx_c.cu 5358 LoC; eigensolver API include/amgx_eig_c.h,
src/amgx_eig_c.cu). Every function keeps its AMGX_* name, its call
order, its handle-based object model, and its RC return-code contract
(exception -> RC translation, src/amgx_c_common.cu AMGX_CHECK_API_ERROR),
so a user porting from `amgx_capi.c` maps each call 1:1.

One deliberate Python adaptation: C output-pointer parameters become
return values AFTER the RC, i.e.

    AMGX_RC AMGX_config_create(AMGX_config_handle *cfg, const char *opt)
       ->   rc, cfg = AMGX_config_create(options)

Handles are opaque integers into a process-global registry, mirroring
the reference's CWrap shared_ptr handle registry. All math runs through
the same framework objects the Python API uses — this layer is pure
surface.
"""
from __future__ import annotations

import itertools
import sys
from typing import Any, Dict, Optional

import jax.numpy as jnp
import numpy as np

from . import initialize as _initialize_framework
from .config import Config
from .errors import AMGXError, RC, get_error_string
from .matrix import CsrMatrix
from .modes import parse_mode
from .resilience.status import (AMGX_SOLVE_DIVERGED, AMGX_SOLVE_FAILED,
                                AMGX_SOLVE_NOT_CONVERGED,
                                AMGX_SOLVE_SUCCESS, to_amgx_status)

# ---------------------------------------------------------------------------
# handle registry (CWrap analog, src/amgx_c_common.cu)
# ---------------------------------------------------------------------------

_handles: Dict[int, Any] = {}
_next_id = itertools.count(1)
_random_seed = itertools.count(1)    # AMGX_vector_set_random sequence

def _new_handle(obj) -> int:
    h = next(_next_id)
    _handles[h] = obj
    return h


def _get(h, cls=None):
    obj = _handles.get(h)
    if obj is None or (cls is not None and not isinstance(obj, cls)):
        raise AMGXError("invalid handle", RC.BAD_PARAMETERS)
    return obj


def _api(fn):
    """Exception -> RC translation (AMGX_CHECK_API_ERROR analog)."""
    import functools

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        try:
            out = fn(*args, **kwargs)
        except AMGXError as e:
            return e.rc if _single_rc(fn) else (e.rc,) + _none_tail(fn)
        except FileNotFoundError:
            return RC.IO_ERROR if _single_rc(fn) \
                else (RC.IO_ERROR,) + _none_tail(fn)
        except Exception:
            return RC.UNKNOWN if _single_rc(fn) \
                else (RC.UNKNOWN,) + _none_tail(fn)
        return out

    return wrapper


def _single_rc(fn):
    return getattr(fn, "_n_outputs", 0) == 0


def _none_tail(fn):
    return (None,) * getattr(fn, "_n_outputs", 0)


def _outputs(n):
    def deco(fn):
        fn._n_outputs = n
        return fn
    return deco


# ---------------------------------------------------------------------------
# library-level objects
# ---------------------------------------------------------------------------


class _CMatrix:
    def __init__(self, resources, mode):
        self.resources = resources
        self.mode = mode
        self.A: Optional[CsrMatrix] = None
        self.part_offsets = None
        self.row_perm = None

    def set_matrix(self, A, part_offsets=None, row_perm=None):
        """Replace the stored matrix; distributed renumbering and
        pieces-path metadata belong to a specific matrix, so they are
        reset together with it."""
        self.A = A
        self.part_offsets = part_offsets
        self.row_perm = row_perm
        self.part = None
        self.pieces = None
        self.piece_prefold = None
        self.piece_structure = None
        self.new_vals = None


class _CVector:
    def __init__(self, resources, mode):
        self.resources = resources
        self.mode = mode
        self.v: Optional[np.ndarray] = None
        self.block_dim = 1
        # batched extension (amgx_tpu/batch/): None = plain vector; an
        # int B means v is (B, n*block_dim) — one system per row
        self.batch: Optional[int] = None


class _CSolver:
    def __init__(self, resources, mode, cfg: Config):
        self.resources = resources
        self.mode = mode
        self.cfg = cfg
        self.solver = None
        self.result = None

    def build(self):
        # the package-level factory owns the tree build AND the
        # ResilientSolver wrapping rule (fallback_policy) — one site
        from . import create_solver
        self.solver = create_solver(self.cfg)


class _CEigenSolver:
    def __init__(self, resources, mode, cfg: Config):
        self.resources = resources
        self.mode = mode
        self.cfg = cfg
        from .eigen import create_eigensolver
        self.solver = create_eigensolver(cfg)
        self.result = None


class _CResources:
    def __init__(self, cfg: Optional[Config], device_num: int = 0,
                 devices=None):
        from .resources import Resources
        self.cfg = cfg
        self.res = Resources(cfg, device_num=device_num, devices=devices)


# ---------------------------------------------------------------------------
# init / version / error API
# ---------------------------------------------------------------------------


@_api
def AMGX_initialize():
    """src/amgx_c.cu:2360."""
    _initialize_framework()
    return RC.OK


@_api
def AMGX_initialize_plugins():
    return RC.OK           # plugin system removed upstream (CHANGELOG:14)


@_api
def AMGX_finalize():
    _handles.clear()
    return RC.OK


@_api
def AMGX_finalize_plugins():
    return RC.OK


def AMGX_get_api_version():
    """rc, major, minor."""
    from . import API_VERSION
    return RC.OK, API_VERSION[0], API_VERSION[1]


def AMGX_get_error_string(rc):
    return get_error_string(rc)


@_api
def AMGX_register_print_callback(callback):
    from .output import register_print_callback
    register_print_callback(callback)
    return RC.OK


@_api
def AMGX_install_signal_handler():
    import faulthandler
    faulthandler.enable()
    return RC.OK


@_api
def AMGX_reset_signal_handler():
    import faulthandler
    faulthandler.disable()
    return RC.OK


def AMGX_pin_memory(*_args):     # no-op: XLA owns transfers
    return RC.OK


def AMGX_unpin_memory(*_args):
    return RC.OK


# ---------------------------------------------------------------------------
# config API
# ---------------------------------------------------------------------------


@_api
@_outputs(1)
def AMGX_config_create(options: str):
    return RC.OK, _new_handle(Config.from_string(options or ""))


@_api
@_outputs(1)
def AMGX_config_create_from_file(path: str):
    return RC.OK, _new_handle(Config.from_file(path))


@_api
@_outputs(1)
def AMGX_config_create_from_file_and_string(path: str, options: str):
    cfg = Config.from_file(path)
    cfg.parse_parameter_string(options or "")
    return RC.OK, _new_handle(cfg)


@_api
def AMGX_config_add_parameters(cfg_h, options: str):
    _get(cfg_h, Config).parse_parameter_string(options)
    return RC.OK


@_api
def AMGX_config_destroy(cfg_h):
    _handles.pop(cfg_h, None)
    return RC.OK


# ---------------------------------------------------------------------------
# resources API
# ---------------------------------------------------------------------------


@_api
@_outputs(1)
def AMGX_resources_create_simple(cfg_h=None):
    cfg = _get(cfg_h, Config) if cfg_h is not None else None
    return RC.OK, _new_handle(_CResources(cfg))


@_api
@_outputs(1)
def AMGX_resources_create(cfg_h, _comm=None, device_num=0, devices=None):
    cfg = _get(cfg_h, Config) if cfg_h is not None else None
    return RC.OK, _new_handle(
        _CResources(cfg, device_num=device_num, devices=devices))


@_api
def AMGX_resources_destroy(rsrc_h):
    _handles.pop(rsrc_h, None)
    return RC.OK


@_api
@_outputs(2)
def AMGX_resources_get_memory_usage(rsrc_h):
    """rc, bytes_in_use, peak high-water mark (MemoryInfo analog;
    include/memory_info.h:33), both scoped to the resources' devices.
    Backends without allocator statistics (CPU) report zeros."""
    rs = _get(rsrc_h, _CResources)
    cur, peak = rs.res.update_memory_usage()
    return RC.OK, cur, peak


# ---------------------------------------------------------------------------
# matrix API
# ---------------------------------------------------------------------------


@_api
@_outputs(1)
def AMGX_matrix_create(rsrc_h, mode: str):
    rs = _get(rsrc_h, _CResources)
    return RC.OK, _new_handle(_CMatrix(rs, parse_mode(mode)))


@_api
def AMGX_matrix_destroy(mtx_h):
    _handles.pop(mtx_h, None)
    return RC.OK


@_api
def AMGX_matrix_upload_all(mtx_h, n, nnz, block_dimx, block_dimy,
                           row_ptrs, col_indices, data, diag_data=None):
    """AMGX_matrix_upload_all (src/amgx_c.cu:3039)."""
    m = _get(mtx_h, _CMatrix)
    dt = m.mode.mat_dtype
    ro = np.asarray(row_ptrs, dtype=np.int32)
    ci = np.asarray(col_indices, dtype=np.int32)
    vals = np.asarray(data, dtype=dt)
    if block_dimx * block_dimy > 1:
        vals = vals.reshape(nnz, block_dimx, block_dimy)
    diag = None
    if diag_data is not None:
        diag = np.asarray(diag_data, dtype=dt)
        if block_dimx * block_dimy > 1:
            diag = diag.reshape(n, block_dimx, block_dimy)
    with m.resources.res.device_context():
        m.set_matrix(CsrMatrix.from_scipy_like(
            ro, ci, vals, n, n, block_dims=(block_dimx, block_dimy),
            diag=diag).init())
    return RC.OK


@_api
def AMGX_matrix_replace_coefficients(mtx_h, n, nnz, data, diag_data=None):
    """Keep structure, replace values (src/amgx_c.cu; pairs with
    AMGX_solver_resetup). On the pieces path (a matrix uploaded with
    AMGX_matrix_upload_distributed), call once per rank with that
    rank's new values — after the last piece the arranger re-runs
    against the stored structure."""
    m = _get(mtx_h, _CMatrix)
    if getattr(m, "part", None) is not None:
        if getattr(m, "new_vals", None) is None:
            m.new_vals = []
        r = len(m.new_vals)
        ro_r, ci_r, had_diag = m.piece_structure[r]
        vals = np.asarray(data, m.mode.mat_dtype)
        if vals.shape[0] != ci_r.shape[0]:
            raise AMGXError(
                f"piece {r}: {vals.shape[0]} values, structure has "
                f"{ci_r.shape[0]} entries", RC.BAD_PARAMETERS)
        if had_diag != (diag_data is not None):
            raise AMGXError(
                f"piece {r}: diag_data presence must match the upload",
                RC.BAD_PARAMETERS)
        dg = None if diag_data is None else np.asarray(
            diag_data, m.mode.mat_dtype)
        m.new_vals.append((vals, dg))
        R = len(m.piece_structure)
        if len(m.new_vals) == R:
            new_vals, m.new_vals = m.new_vals, None
            from .distributed.partition import partition_from_pieces
            pieces = []
            for r2, ((ro_, ci_, hd), (v_, d_)) in enumerate(
                    zip(m.piece_structure, new_vals)):
                ro64 = ro_.astype(np.int64)
                ci64 = ci_.astype(np.int64)
                if hd:
                    ro64, ci64, v_ = _fold_piece_diag(
                        ro64, ci64, v_, d_, len(ro_) - 1,
                        int(m.part_offsets[r2]))
                pieces.append((ro64, ci64, v_))
            m.part = partition_from_pieces(
                pieces, m.piece_nglobal, dtype=m.mode.mat_dtype)
        return RC.OK
    if m.A is None:
        raise AMGXError("matrix not uploaded", RC.BAD_PARAMETERS)
    dt = m.mode.mat_dtype
    vals = np.asarray(data, dtype=dt)
    if m.A.is_block:
        vals = vals.reshape(nnz, m.A.block_dimx, m.A.block_dimy)
    diag = None
    if diag_data is not None:
        diag = np.asarray(diag_data, dtype=dt)
        if m.A.is_block:
            diag = diag.reshape(n, m.A.block_dimx, m.A.block_dimy)
    m.A = m.A.with_values(vals, diag=diag
                          if diag is not None else m.A.diag)
    if not m.A.initialized:
        m.A = m.A.init()
    return RC.OK


def AMGX_matrix_get_size(mtx_h):
    """rc, n, block_dimx, block_dimy."""
    try:
        m = _get(mtx_h, _CMatrix)
        if m.A is None:
            return RC.BAD_PARAMETERS, None, None, None
        return RC.OK, m.A.num_rows, m.A.block_dimx, m.A.block_dimy
    except AMGXError as e:
        return e.rc, None, None, None


@_api
@_outputs(1)
def AMGX_matrix_get_nnz(mtx_h):
    m = _get(mtx_h, _CMatrix)
    return RC.OK, (m.A.nnz if m.A is not None else 0)


@_api
def AMGX_matrix_attach_geometry(mtx_h, geox, geoy, geoz=None, n=None):
    """AMGX_matrix_attach_geometry (src/amgx_c.cu:3143): attach per-row
    coordinates so geometry-aware selectors (GEO) can run. TPU redesign:
    for a lexicographically-ordered structured grid the coordinates
    collapse to a (nx, ny, nz) grid annotation (CsrMatrix.grid_shape),
    which is what the structured-pairing GEO selector and the sort-free
    structured Galerkin consume. Non-grid coordinates are rejected."""
    import dataclasses
    m = _get(mtx_h, _CMatrix)
    if m.A is None:
        raise AMGXError("matrix not uploaded", RC.BAD_PARAMETERS)
    gx = np.asarray(geox, np.float64)
    gy = np.asarray(geoy, np.float64)
    gz = (np.asarray(geoz, np.float64) if geoz is not None
          else np.zeros_like(gx))
    if n is not None and n != m.A.num_rows:
        raise AMGXError("attach_geometry: n mismatch", RC.BAD_PARAMETERS)
    nx = np.unique(gx).size
    ny = np.unique(gy).size
    nz = np.unique(gz).size
    if nx * ny * nz != m.A.num_rows:
        raise AMGXError(
            "attach_geometry: coordinates do not form a structured "
            "nx*ny*nz grid", RC.BAD_PARAMETERS)
    # verify lexicographic ordering (x fastest) — the layout grid_shape
    # asserts; rank the coordinates and rebuild the linear index
    rx = np.searchsorted(np.unique(gx), gx)
    ry = np.searchsorted(np.unique(gy), gy)
    rz = np.searchsorted(np.unique(gz), gz)
    lin = (rz * ny + ry) * nx + rx
    if not np.array_equal(lin, np.arange(m.A.num_rows)):
        raise AMGXError(
            "attach_geometry: rows are not in lexicographic grid order "
            "(x fastest); renumber the system first", RC.BAD_PARAMETERS)
    m.A = dataclasses.replace(m.A, grid_shape=(int(nx), int(ny), int(nz)))
    return RC.OK


# ---------------------------------------------------------------------------
# vector API
# ---------------------------------------------------------------------------


@_api
@_outputs(1)
def AMGX_vector_create(rsrc_h, mode: str):
    rs = _get(rsrc_h, _CResources)
    return RC.OK, _new_handle(_CVector(rs, parse_mode(mode)))


@_api
def AMGX_vector_destroy(vec_h):
    _handles.pop(vec_h, None)
    return RC.OK


@_api
def AMGX_vector_upload(vec_h, n, block_dim, data):
    v = _get(vec_h, _CVector)
    v.v = np.asarray(data, dtype=v.mode.vec_dtype).reshape(n * block_dim)
    v.block_dim = block_dim
    v.batch = None
    return RC.OK


@_api
def AMGX_vector_upload_batched(vec_h, n_batch, n, block_dim, data):
    """Batched extension (no reference analog): upload `n_batch` systems'
    vectors at once — one per row of a (n_batch, n*block_dim) array. A
    batched vector pairs with AMGX_solver_solve_batched, which runs every
    system in ONE jitted program (amgx_tpu/batch/)."""
    v = _get(vec_h, _CVector)
    v.v = np.asarray(data, dtype=v.mode.vec_dtype).reshape(
        n_batch, n * block_dim)
    v.block_dim = block_dim
    v.batch = int(n_batch)
    return RC.OK


@_api
def AMGX_vector_set_zero(vec_h, n, block_dim):
    v = _get(vec_h, _CVector)
    v.v = np.zeros(n * block_dim, dtype=v.mode.vec_dtype)
    v.block_dim = block_dim
    v.batch = None
    return RC.OK


@_api
@_outputs(1)
def AMGX_vector_download(vec_h):
    v = _get(vec_h, _CVector)
    if v.v is None:
        raise AMGXError("vector not uploaded", RC.BAD_PARAMETERS)
    return RC.OK, np.asarray(v.v).copy()


def AMGX_vector_get_size(vec_h):
    """rc, n, block_dim (n is per system for batched vectors)."""
    try:
        v = _get(vec_h, _CVector)
        if v.v is None:
            return RC.OK, 0, v.block_dim
        n = v.v.shape[-1] if v.batch is not None else len(v.v)
        return RC.OK, n // v.block_dim, v.block_dim
    except AMGXError as e:
        return e.rc, None, None


# ---------------------------------------------------------------------------
# solver API
# ---------------------------------------------------------------------------


@_api
@_outputs(1)
def AMGX_solver_create(rsrc_h, mode: str, cfg_h):
    rs = _get(rsrc_h, _CResources)
    cfg = _get(cfg_h, Config)
    cs = _CSolver(rs, parse_mode(mode), cfg)
    cs.build()
    return RC.OK, _new_handle(cs)


@_api
def AMGX_solver_destroy(slv_h):
    _handles.pop(slv_h, None)
    return RC.OK


@_api
def AMGX_solver_setup(slv_h, mtx_h):
    """src/amgx_c.cu:2745. A matrix uploaded from per-rank pieces
    (AMGX_matrix_upload_distributed / upload_all_global) sets up a
    DistributedSolver over the device mesh from the arranger-built
    partition — no global matrix is assembled."""
    s = _get(slv_h, _CSolver)
    m = _get(mtx_h, _CMatrix)
    if getattr(m, "part", None) is not None:
        from .distributed import DistributedSolver, default_mesh
        with s.resources.res.device_context():
            ds = DistributedSolver(s.cfg, default_mesh(m.part.n_ranks))
            ds.setup_from_partition(m.part)
        s.solver = ds
        return RC.OK
    if m.A is None:
        raise AMGXError("matrix not uploaded", RC.BAD_PARAMETERS)
    with s.resources.res.device_context():
        s.solver.setup(m.A)
    return RC.OK


@_api
def AMGX_solver_resetup(slv_h, mtx_h):
    s = _get(slv_h, _CSolver)
    m = _get(mtx_h, _CMatrix)
    if getattr(m, "part", None) is not None:
        # pieces path: full rebuild from the stored partition (structure
        # reuse across resetup is a global-path feature)
        from .distributed import DistributedSolver, default_mesh
        with s.resources.res.device_context():
            ds = DistributedSolver(s.cfg, default_mesh(m.part.n_ranks))
            ds.setup_from_partition(m.part)
        s.solver = ds
        return RC.OK
    if m.A is None:
        raise AMGXError("matrix not uploaded", RC.BAD_PARAMETERS)
    s.solver.resetup(m.A)
    return RC.OK


def _do_solve(s, b_h, x_h, zero_guess):
    from .distributed import DistributedSolver
    b = _get(b_h, _CVector)
    x = _get(x_h, _CVector)
    distributed = isinstance(s.solver, DistributedSolver)
    if s.solver is None or (not distributed and s.solver.A is None):
        raise AMGXError("solver not set up", RC.BAD_PARAMETERS)
    if b.v is None:
        raise AMGXError("rhs not uploaded", RC.BAD_PARAMETERS)
    x0 = x.v if (x.v is not None and not zero_guess) else None
    with s.resources.res.device_context():
        if distributed:
            s.result = s.solver.solve(b.v, x0=x0)
        else:
            s.result = s.solver.solve(b.v, x0=x0,
                                      zero_initial_guess=zero_guess)
    x.v = np.asarray(s.result.x)
    x.block_dim = b.block_dim
    x.batch = None
    return RC.OK


@_api
def AMGX_solver_solve(slv_h, b_h, x_h):
    """src/amgx_c.cu:2813 (x holds the initial guess)."""
    return _do_solve(_get(slv_h, _CSolver), b_h, x_h, zero_guess=False)


@_api
def AMGX_solver_solve_with_0_initial_guess(slv_h, b_h, x_h):
    return _do_solve(_get(slv_h, _CSolver), b_h, x_h, zero_guess=True)


@_api
def AMGX_solver_solve_batched(slv_h, b_h, x_h):
    """Batched extension (no reference analog): solve every system in a
    batched rhs vector (AMGX_vector_upload_batched) against the set-up
    matrix in ONE jitted program. x may hold batched initial guesses;
    on return it holds the batched solutions. Per-system status lands in
    the usual getters (get_status reports success only when EVERY system
    converged; get_iterations_number reports the batch max)."""
    from .distributed import DistributedSolver
    s = _get(slv_h, _CSolver)
    b = _get(b_h, _CVector)
    x = _get(x_h, _CVector)
    if s.solver is None or isinstance(s.solver, DistributedSolver) \
            or s.solver.A is None:
        raise AMGXError("batched solve needs a set-up single-device "
                        "solver", RC.BAD_PARAMETERS)
    if b.v is None or b.batch is None:
        raise AMGXError("rhs is not a batched vector (use "
                        "AMGX_vector_upload_batched)", RC.BAD_PARAMETERS)
    if x.v is not None and x.batch != b.batch:
        # an uploaded guess that cannot pair with the rhs batch is a
        # caller bug — silently discarding it would hide the misuse
        raise AMGXError(
            f"initial-guess vector batch ({x.batch}) does not match the "
            f"rhs batch ({b.batch}); upload it with "
            f"AMGX_vector_upload_batched or leave it empty",
            RC.BAD_PARAMETERS)
    x0s = x.v
    with s.resources.res.device_context():
        s.result = s.solver.solve_many(b.v, x0s=x0s,
                                       zero_initial_guess=x0s is None)
    x.v = np.asarray(s.result.x)
    x.block_dim = b.block_dim
    x.batch = b.batch
    return RC.OK


def _result_status_codes(result) -> np.ndarray:
    """Per-system SolveStatus codes of a solve result (length 1 for a
    plain solve). Falls back to the converged bools for result types
    that predate status plumbing."""
    codes = getattr(result, "status_code", None)
    if codes is None:
        codes = getattr(result, "status", None)       # batched results
    if codes is None or isinstance(codes, str):
        conv = np.atleast_1d(np.asarray(result.converged))
        return np.where(conv, 0, 1).astype(np.int32)
    return np.atleast_1d(np.asarray(codes)).astype(np.int32)


@_api
@_outputs(1)
def AMGX_solver_get_status(slv_h):
    """rc, status: real AMGX_SOLVE_* codes (include/amgx_c.h) —
    AMGX_SOLVE_SUCCESS(0) / FAILED(1) / DIVERGED(2) /
    NOT_CONVERGED(3), mapped from the in-trace SolveStatus
    classification (resilience/status.py). A batched solve reports the
    WORST system (severity-ordered codes)."""
    s = _get(slv_h, _CSolver)
    if s.result is None:
        raise AMGXError("no solve performed", RC.BAD_PARAMETERS)
    return RC.OK, to_amgx_status(int(np.max(
        _result_status_codes(s.result))))


@_api
@_outputs(1)
def AMGX_solver_get_batch_status(slv_h):
    """rc, per-system AMGX_SOLVE_* statuses as an int array — batched
    extension pairing AMGX_solver_solve_batched (0 success / 1 failed /
    2 diverged / 3 not converged). A plain solve reports a length-1
    array."""
    s = _get(slv_h, _CSolver)
    if s.result is None:
        raise AMGXError("no solve performed", RC.BAD_PARAMETERS)
    return RC.OK, np.asarray(
        [to_amgx_status(c) for c in _result_status_codes(s.result)],
        np.int32)


@_api
@_outputs(1)
def AMGX_solver_get_report(slv_h):
    """rc, report: the last solve's structured SolveReport as a plain
    dict (telemetry/report.py; schema telemetry/report_schema.json) —
    per-iteration residuals, final status, per-level kernel activity,
    wall times. A batched solve returns a LIST of per-system report
    dicts. Telemetry extension (no reference analog; the reference
    exposes the same data only as printed tables). Raises
    BAD_PARAMETERS when no solve ran or telemetry=0 disabled reports."""
    s = _get(slv_h, _CSolver)
    if s.result is None:
        raise AMGXError("no solve performed", RC.BAD_PARAMETERS)
    reports = getattr(s.result, "reports", None)      # batched result
    if reports is not None:
        return RC.OK, [r.to_dict() for r in reports]
    report = getattr(s.result, "report", None)
    if report is None:
        raise AMGXError("no report on the last solve (telemetry=0?)",
                        RC.BAD_PARAMETERS)
    return RC.OK, report.to_dict()


@_api
@_outputs(1)
def AMGX_solver_get_grid_stats(slv_h):
    """rc, stats dict: the solver tree's AMG grid statistics as
    STRUCTURED data (AMG.grid_stats_dict(): per-level rows/nnz/layout,
    grid + operator complexity) — the machine-readable form of the
    reference's printed grid-statistics table (src/amg.cu:1231-1350;
    the `print_grid_stats=1` text renders from this same dict). Raises
    BAD_PARAMETERS when the tree owns no set-up AMG hierarchy."""
    from .telemetry.report import _amg_of
    s = _get(slv_h, _CSolver)
    amg = _amg_of(s.solver)
    if amg is None or not getattr(amg, "levels", None):
        raise AMGXError("no set-up AMG hierarchy in the solver tree",
                        RC.BAD_PARAMETERS)
    return RC.OK, amg.grid_stats_dict()


@_api
@_outputs(1)
def AMGX_read_metrics():
    """rc, metrics: snapshot of the process-wide telemetry
    counter/gauge/histogram registry (telemetry/metrics.py) — cache
    hit/miss, setup-routing, batcher occupancy, fallback events, jit
    retraces, memory watermarks, latency histograms. Telemetry
    extension (no reference analog)."""
    from .telemetry import metrics
    return RC.OK, metrics.snapshot()


@_api
@_outputs(1)
def AMGX_read_metrics_openmetrics():
    """rc, text: the whole metrics registry as an OpenMetrics text
    exposition (counters/gauges/histograms, `# EOF`-terminated) — the
    payload a /metrics scrape endpoint serves to Prometheus-compatible
    collectors (telemetry/metrics.py to_openmetrics)."""
    from .telemetry import metrics
    return RC.OK, metrics.to_openmetrics()


@_api
def AMGX_print_timers():
    """Print the accumulated trace-region timer table through the
    registered print callback (src/amgx_timer.cu print-tree role;
    profiling.format_timers)."""
    from .output import amgx_output
    from .profiling import format_timers
    amgx_output(format_timers())
    return RC.OK


@_api
@_outputs(1)
def AMGX_solver_get_iterations_number(slv_h):
    s = _get(slv_h, _CSolver)
    if s.result is None:
        raise AMGXError("no solve performed", RC.BAD_PARAMETERS)
    return RC.OK, int(np.max(s.result.iterations))


@_api
@_outputs(1)
def AMGX_solver_get_iteration_residual(slv_h, it: int, idx: int = 0):
    s = _get(slv_h, _CSolver)
    if s.result is None or s.result.res_history is None:
        raise AMGXError("no residual history (set store_res_history=1)",
                        RC.BAD_PARAMETERS)
    hist = np.asarray(s.result.res_history)   # (iters+1,) or (iters+1, b)
    if hasattr(s.result, "batch_size"):       # batched: (B, hist_len[, b])
        hist = np.moveaxis(hist, 0, 1)        # idx then selects the system
        if hist.ndim == 3:                    # block norms: reduce the
            hist = hist.max(axis=2)           # per-component width so idx
                                              # stays the system selector
        sysi = min(idx, hist.shape[1] - 1)
        # per-system range: an early-converged system's history rows
        # past its OWN stopping iteration are NaN-masked padding
        # (batch/core.py), not residuals — error like the single-solve
        # truncation does
        if not (0 <= it <= int(np.asarray(s.result.iterations)[sysi])):
            raise AMGXError("iteration out of range for this system",
                            RC.BAD_PARAMETERS)
    if not (0 <= it < hist.shape[0]):
        raise AMGXError("iteration out of range", RC.BAD_PARAMETERS)
    row = np.atleast_1d(hist[it])
    return RC.OK, float(row[min(idx, len(row) - 1)])


# ---------------------------------------------------------------------------
# serving API (amgx_tpu/serving/; no reference analog — the reference
# is consumed AS a service library behind this C surface, so the
# service loop always lived on the caller's side of the API. These
# entry points move it inside: continuous batching, the hierarchy
# cache, AOT warm paths and per-tenant deadlines behind handles.)
# ---------------------------------------------------------------------------


class _CService:
    def __init__(self, resources, mode, cfg: Config):
        self.resources = resources
        self.mode = mode
        self.cfg = cfg
        from .serving import SolveService
        self.service = SolveService(cfg)


@_api
@_outputs(1)
def AMGX_service_create(rsrc_h, mode: str, cfg_h):
    """rc, service handle. The config's serving_* parameters size the
    buckets, cache, AOT store and deadline semantics."""
    rs = _get(rsrc_h, _CResources)
    cfg = _get(cfg_h, Config)
    from . import initialize
    initialize()
    return RC.OK, _new_handle(_CService(rs, parse_mode(mode), cfg))


@_api
def AMGX_service_destroy(svc_h):
    svc = _handles.pop(svc_h, None)
    if svc is not None and isinstance(svc, _CService):
        svc.service.stop()
    return RC.OK


@_api
@_outputs(1)
def AMGX_service_submit(svc_h, mtx_h, rhs_h, tenant: str = "default",
                        deadline_s=None, request_key=None):
    """rc, ticket handle. Enqueues one system; issues no device work
    of its own and never waits on one (device cycles run outside the
    service's bookkeeping lock). `deadline_s` is a relative latency
    budget — expiry completes the ticket with DEADLINE_EXCEEDED
    instead of stalling its bucket. `request_key` makes the submit
    idempotent: a retry after a dropped response dedupes against the
    live ticket or the service journal instead of enqueueing twice."""
    svc = _get(svc_h, _CService)
    m = _get(mtx_h, _CMatrix)
    b = _get(rhs_h, _CVector)
    if m.A is None or b.v is None:
        raise AMGXError("matrix/rhs not uploaded", RC.BAD_PARAMETERS)
    ticket = svc.service.submit(m.A, b.v, tenant=tenant,
                                deadline_s=deadline_s,
                                request_key=request_key)
    return RC.OK, _new_handle(ticket)


@_api
@_outputs(1)
def AMGX_service_step(svc_h):
    """rc, completed count: run ONE scheduler cycle (expire / admit /
    advance every bucket by serving_chunk_iters / finalize)."""
    svc = _get(svc_h, _CService)
    with svc.resources.res.device_context():
        return RC.OK, len(svc.service.step())


@_api
@_outputs(1)
def AMGX_service_drain(svc_h, timeout_s=None):
    """rc, completed count: step until every queued and in-flight
    request completed (or timeout). Counts completions during the
    call whether the scheduler runs inline or on its thread."""
    svc = _get(svc_h, _CService)
    before = svc.service.completed_total
    with svc.resources.res.device_context():
        svc.service.drain(timeout_s=timeout_s)
    return RC.OK, svc.service.completed_total - before


@_api
@_outputs(2)
def AMGX_service_ticket_status(tkt_h):
    """rc, done (0/1), AMGX_SOLVE_* status (None while pending)."""
    from .serving import ServiceTicket
    t = _get(tkt_h, ServiceTicket)
    if not t.done:
        return RC.OK, 0, None
    return RC.OK, 1, to_amgx_status(t.result.status_code)


@_api
def AMGX_service_ticket_download(tkt_h, sol_h):
    """Download a completed ticket's solution into a vector handle."""
    from .serving import ServiceTicket
    t = _get(tkt_h, ServiceTicket)
    x = _get(sol_h, _CVector)
    if not t.done:
        raise AMGXError("ticket not completed (drain or step the "
                        "service first)", RC.BAD_PARAMETERS)
    x.v = np.asarray(t.result.x)
    x.batch = None
    return RC.OK


@_api
@_outputs(1)
def AMGX_ticket_trace(tkt_h):
    """rc, the ticket's request trace id (or None when
    serving_tracing=0): the correlation key connecting this request's
    Perfetto flow chain, its flight-recorder events and its journal
    record — hand it to tools/flightrec.py --trace for a per-request
    postmortem."""
    from .serving import ServiceTicket
    t = _get(tkt_h, ServiceTicket)
    return RC.OK, t.trace_id


@_api
def AMGX_service_ticket_destroy(tkt_h):
    _handles.pop(tkt_h, None)
    return RC.OK


@_api
@_outputs(1)
def AMGX_service_stats(svc_h):
    """rc, stats dict: queue depth, in-flight count, live buckets,
    cache bytes/evictions, per-tenant tallies (service-local; the
    process-wide serving.* counters live in AMGX_read_metrics)."""
    svc = _get(svc_h, _CService)
    return RC.OK, svc.service.stats()


@_api
@_outputs(1)
def AMGX_service_autotune(svc_h):
    """rc, the online tuner's live state ({'enabled': False} with
    autotune=0): per-fingerprint search phase, remaining shadow
    budget, the promoted overlay (knob + deltas) and whether it was
    restored from the hstore — the operator's view of WHAT config a
    fingerprint serves and why (the decision trail itself is on the
    flight recorder under the search's trace id)."""
    svc = _get(svc_h, _CService)
    t = svc.service._tuner
    return RC.OK, ({"enabled": False} if t is None else t.snapshot())


# ---------------------------------------------------------------------------
# fleet API (amgx_tpu/serving/fleet.py): N service replicas behind one
# fingerprint-affine submit/step/drain surface — the scale-out layer
# over the service handles above. Tickets are plain service tickets
# (AMGX_service_ticket_* applies) plus replica attribution.
# ---------------------------------------------------------------------------


class _CFleet:
    def __init__(self, resources, mode, cfg: Config, n_replicas):
        self.resources = resources
        self.mode = mode
        self.cfg = cfg
        from .serving import FleetRouter
        self.fleet = FleetRouter.build(cfg, n_replicas)


@_api
@_outputs(1)
def AMGX_fleet_create(rsrc_h, mode: str, cfg_h, n_replicas=None):
    """rc, fleet handle: `n_replicas` SolveService replicas (default:
    the config's fleet_replicas) fronted by the fingerprint-affine
    FleetRouter — rendezvous-hash affinity, least-loaded cold
    placement, overload/quarantine spill, fleet-wide shed consults."""
    rs = _get(rsrc_h, _CResources)
    cfg = _get(cfg_h, Config)
    from . import initialize
    initialize()
    return RC.OK, _new_handle(
        _CFleet(rs, parse_mode(mode), cfg, n_replicas))


@_api
def AMGX_fleet_destroy(fleet_h):
    fl = _handles.pop(fleet_h, None)
    if fl is not None and isinstance(fl, _CFleet):
        fl.fleet.stop()
    return RC.OK


@_api
@_outputs(1)
def AMGX_fleet_submit(fleet_h, mtx_h, rhs_h, tenant: str = "default",
                      deadline_s=None, request_key=None):
    """rc, ticket handle: route one system to its affine replica and
    enqueue it there (AMGX_service_submit semantics otherwise —
    deadline budget, idempotent request_key)."""
    fl = _get(fleet_h, _CFleet)
    m = _get(mtx_h, _CMatrix)
    b = _get(rhs_h, _CVector)
    if m.A is None or b.v is None:
        raise AMGXError("matrix/rhs not uploaded", RC.BAD_PARAMETERS)
    ticket = fl.fleet.submit(m.A, b.v, tenant=tenant,
                             deadline_s=deadline_s,
                             request_key=request_key)
    return RC.OK, _new_handle(ticket)


@_api
@_outputs(1)
def AMGX_fleet_step(fleet_h):
    """rc, completed count: ONE scheduler cycle on every replica."""
    fl = _get(fleet_h, _CFleet)
    with fl.resources.res.device_context():
        return RC.OK, len(fl.fleet.step())


@_api
@_outputs(1)
def AMGX_fleet_drain(fleet_h, timeout_s=None):
    """rc, completed count: step the fleet until every replica is
    idle (or timeout)."""
    fl = _get(fleet_h, _CFleet)
    before = fl.fleet.completed_total
    with fl.resources.res.device_context():
        fl.fleet.drain(timeout_s=timeout_s)
    return RC.OK, fl.fleet.completed_total - before


@_api
@_outputs(1)
def AMGX_fleet_ticket_replica(tkt_h):
    """rc, id of the replica that served this ticket (the trace
    chain's attribution for cross-replica postmortems), or None for a
    ticket submitted to a bare service."""
    from .serving import ServiceTicket
    t = _get(tkt_h, ServiceTicket)
    return RC.OK, getattr(t, "replica", None)


@_api
@_outputs(1)
def AMGX_fleet_stats(fleet_h):
    """rc, stats dict: per-replica service stats plus the per-replica
    warm|cold|spill route counters and placed-fingerprint count; the
    merged fleet metrics view lives in metrics.merge_snapshots /
    FleetRouter.fleet_snapshot."""
    fl = _get(fleet_h, _CFleet)
    return RC.OK, fl.fleet.stats()


@_api
@_outputs(1)
def AMGX_fleet_drain_replica(fleet_h, replica: str):
    """rc, handed-off queue count: administratively drain one replica
    for a rolling restart — no new placements land on it, its queued
    tickets move to survivors (the journal rides along), in-flight
    work finishes in place. `AMGX_fleet_restore_replica` re-enters it
    into the rendezvous."""
    fl = _get(fleet_h, _CFleet)
    return RC.OK, fl.fleet.drain_replica(str(replica))


@_api
def AMGX_fleet_restore_replica(fleet_h, replica: str):
    """rc: re-enter a drained/down replica into the rendezvous —
    breaker reset, captured error cleared, cold-placement warm-up
    grace started (rehomed fingerprints stay with their adopter until
    natural eviction)."""
    fl = _get(fleet_h, _CFleet)
    fl.fleet.restore_replica(str(replica))
    return RC.OK


@_api
@_outputs(1)
def AMGX_fleet_health(fleet_h):
    """rc, health dict per replica: breaker state
    (closed|open|half_open), down/draining flags, consecutive
    failures, last health event, live scheduler facts (cycle counter,
    thread aliveness, captured error, queue depth) — the
    serving/health.py monitor's view, for ops dashboards and the
    rolling-restart loop."""
    fl = _get(fleet_h, _CFleet)
    return RC.OK, fl.fleet.health_snapshot()


# ---------------------------------------------------------------------------
# system IO API
# ---------------------------------------------------------------------------


def _fill_vectors(m, rhs_h, sol_h, A, b, x):
    """Shared rhs/sol default-fill for the read paths (b=ones, x=zeros
    as in the reference reader)."""
    dt = m.mode.vec_dtype if m else np.float64
    if rhs_h is not None:
        rv = _get(rhs_h, _CVector)
        rv.v = np.asarray(b) if b is not None else np.ones(
            A.num_rows * A.block_dimy, dtype=dt)
        rv.block_dim = A.block_dimy
    if sol_h is not None:
        sv = _get(sol_h, _CVector)
        sv.v = np.asarray(x) if x is not None else np.zeros(
            A.num_rows * A.block_dimx, dtype=dt)
        sv.block_dim = A.block_dimx


@_api
def AMGX_read_system(mtx_h, rhs_h, sol_h, path: str):
    """src/amgx_c.cu read_system: fills matrix + rhs + solution (missing
    pieces default to b=ones/x=zeros as in the reference reader). A
    complex-valued file is converted to its K-formulation real system
    when the resources config sets complex_conversion (readers.cu:221)."""
    from .io import read_system as _read
    m = _get(mtx_h, _CMatrix) if mtx_h is not None else None
    A, b, x = _read(path, dtype=m.mode.mat_dtype if m else None)
    if np.issubdtype(A.values.dtype, np.complexfloating):
        conv = 0
        cfg = m.resources.cfg if m is not None and m.resources else None
        if cfg is not None:
            conv = int(cfg.get("complex_conversion", "default"))
        if conv:
            from .io.complex import complex_system_to_real
            A, b, x = complex_system_to_real(A, b, x, mode=conv)
    if m is not None:
        m.set_matrix(A if A.initialized else A.init())
    _fill_vectors(m, rhs_h, sol_h, A, b, x)
    return RC.OK


@_api
def AMGX_write_system(mtx_h, rhs_h, sol_h, path: str):
    from .io import write_system as _write
    m = _get(mtx_h, _CMatrix)
    if m.A is None:
        raise AMGXError("matrix not uploaded", RC.BAD_PARAMETERS)
    b = _get(rhs_h, _CVector).v if rhs_h is not None else None
    x = _get(sol_h, _CVector).v if sol_h is not None else None
    _write(path, m.A, b, x)
    return RC.OK


@_api
def AMGX_read_system_distributed(mtx_h, rhs_h, sol_h, path: str,
                                 allocated_halo_depth=1, num_partitions=None,
                                 partition_sizes=None, partition_vector=None):
    """src/amgx_c.cu read_system_distributed analog: global system +
    partition vector (array or `<path>` string) -> partition-contiguous
    renumbered system on the controller. part_offsets land on the matrix
    object for the distributed layer."""
    from .io.distributed import read_system_distributed
    m = _get(mtx_h, _CMatrix)
    kw = {}
    if isinstance(partition_vector, str):
        kw["partition_path"] = partition_vector
    elif partition_vector is not None:
        kw["partition_vector"] = np.asarray(partition_vector)
    elif partition_sizes is not None:
        kw["partition_sizes"] = partition_sizes
    if num_partitions is not None:
        kw["num_ranks"] = int(num_partitions)
    A, b, x, part_offsets, perm = read_system_distributed(
        path, dtype=m.mode.mat_dtype, **kw)
    m.set_matrix(A, part_offsets=part_offsets, row_perm=perm)
    _fill_vectors(m, rhs_h, sol_h, A, b, x)
    return RC.OK


@_api
def AMGX_write_system_distributed(mtx_h, rhs_h, sol_h, path: str,
                                  allocated_halo_depth=1,
                                  num_partitions=None, partition_sizes=None,
                                  partition_vector=None):
    from .io.distributed import write_system_distributed
    m = _get(mtx_h, _CMatrix)
    if m.A is None:
        raise AMGXError("matrix not uploaded", RC.BAD_PARAMETERS)
    b = _get(rhs_h, _CVector).v if rhs_h is not None else None
    x = _get(sol_h, _CVector).v if sol_h is not None else None
    pv = partition_vector
    if pv is None and partition_sizes is not None:
        from .io.distributed import sizes_to_partition_vector
        pv = sizes_to_partition_vector(partition_sizes, m.A.num_rows)
    if pv is not None and m.row_perm is not None:
        # The stored matrix is renumbered (row_perm: new -> old); the
        # caller's vector is in original order. Align the sidecar with
        # the written row order.
        pv = np.asarray(pv)[np.asarray(m.row_perm)]
    write_system_distributed(path, m.A, b, x, partition_vector=pv)
    return RC.OK


@_api
def AMGX_write_parameters_description(path: str):
    """Dump every registered parameter (include/amgx_c.h analog)."""
    from .config import describe_parameters
    with open(path, "w") as f:
        f.write(describe_parameters())
    return RC.OK


# ---------------------------------------------------------------------------
# generators (AMGX_generate_distributed_poisson_7pt, src/amgx_c.cu:4731)
# ---------------------------------------------------------------------------


@_api
def AMGX_generate_distributed_poisson_7pt(mtx_h, rhs_h, sol_h,
                                          allocated_halo_depth, num_import_rings,
                                          nx, ny, nz, px=1, py=1, pz=1):
    """Single-controller analog: generates the GLOBAL 7-pt Poisson (the
    mesh partitioning happens at solve time via the distributed layer,
    not per-process as in MPI)."""
    from .gallery import poisson
    m = _get(mtx_h, _CMatrix)
    A = poisson("7pt", nx * px, ny * py, nz * pz,
                dtype=m.mode.mat_dtype)
    m.set_matrix(A.init())
    n = m.A.num_rows
    if rhs_h is not None:
        rv = _get(rhs_h, _CVector)
        rv.v = np.ones(n, dtype=m.mode.vec_dtype)
        rv.block_dim = 1
    if sol_h is not None:
        sv = _get(sol_h, _CVector)
        sv.v = np.zeros(n, dtype=m.mode.vec_dtype)
        sv.block_dim = 1
    return RC.OK


# ---------------------------------------------------------------------------
# eigensolver API (include/amgx_eig_c.h:18-26, src/amgx_eig_c.cu)
# ---------------------------------------------------------------------------


@_api
@_outputs(1)
def AMGX_eigensolver_create(rsrc_h, mode: str, cfg_h):
    rs = _get(rsrc_h, _CResources)
    cfg = _get(cfg_h, Config)
    return RC.OK, _new_handle(_CEigenSolver(rs, parse_mode(mode), cfg))


@_api
def AMGX_eigensolver_destroy(es_h):
    _handles.pop(es_h, None)
    return RC.OK


@_api
def AMGX_eigensolver_setup(es_h, mtx_h):
    es = _get(es_h, _CEigenSolver)
    m = _get(mtx_h, _CMatrix)
    if m.A is None:
        raise AMGXError("matrix not uploaded", RC.BAD_PARAMETERS)
    es.solver.setup(m.A)
    return RC.OK


@_api
def AMGX_eigensolver_pagerank_setup(es_h, a_vec_h):
    return RC.OK          # dangling/teleport vectors built internally


@_api
def AMGX_eigensolver_solve(es_h, x_h):
    es = _get(es_h, _CEigenSolver)
    x = _get(x_h, _CVector)
    es.result = es.solver.solve(x.v if x.v is not None else None)
    if es.result.eigenvectors is not None:
        x.v = np.asarray(es.result.eigenvectors[:, 0])
    return RC.OK


@_api
@_outputs(1)
def AMGX_eigensolver_get_eigenvalues(es_h):
    es = _get(es_h, _CEigenSolver)
    if es.result is None:
        raise AMGXError("no solve performed", RC.BAD_PARAMETERS)
    return RC.OK, np.asarray(es.result.eigenvalues).copy()


# ---------------------------------------------------------------------------
# distributed upload API (include/amgx_c.h:235-586, src/amgx_c.cu:1805-4753)
#
# The reference's per-MPI-rank upload becomes a per-piece upload on the
# single controller: each call to AMGX_matrix_upload_distributed /
# AMGX_matrix_upload_all_global contributes ONE rank's piece (global
# column ids); after the last piece the arranger
# (distributed/partition.py partition_from_pieces) detects neighbors
# from the global column ids and builds the halo maps — no global
# matrix is ever assembled. AMGX_solver_setup on such a matrix builds a
# DistributedSolver over the device mesh, and (for eligible configs)
# the AMG hierarchy itself is built per-shard (distributed/setup.py).
# ---------------------------------------------------------------------------

AMGX_DIST_PARTITION_VECTOR = 0
AMGX_DIST_PARTITION_OFFSETS = 1


class _CDistribution:
    def __init__(self, cfg, n_ranks=None):
        self.cfg = cfg
        self.n_ranks = n_ranks           # explicit (zero-row ranks)
        self.partition_offsets = None    # (R+1,) contiguous row blocks
        self.partition_vector = None     # (n,) rank per row

        self.use32 = True

    def num_ranks(self):
        if self.n_ranks is not None:
            return self.n_ranks
        if self.partition_offsets is not None:
            return len(self.partition_offsets) - 1
        if self.partition_vector is not None:
            return int(self.partition_vector.max()) + 1
        raise AMGXError("distribution has no partition data",
                        RC.BAD_PARAMETERS)


@_api
@_outputs(1)
def AMGX_distribution_create(cfg_h=None, n_ranks=None):
    """n_ranks is a Python-surface extension: a partition VECTOR alone
    cannot reveal trailing ranks that own zero rows."""
    cfg = _get(cfg_h, Config) if cfg_h is not None else None
    return RC.OK, _new_handle(_CDistribution(cfg, n_ranks))


@_api
def AMGX_distribution_destroy(dist_h):
    _handles.pop(dist_h, None)
    return RC.OK


@_api
def AMGX_distribution_set_partition_data(dist_h, info, partition_data):
    d = _get(dist_h, _CDistribution)
    if info == AMGX_DIST_PARTITION_OFFSETS:
        d.partition_offsets = np.asarray(partition_data, np.int64)
        d.partition_vector = None
    elif info == AMGX_DIST_PARTITION_VECTOR:
        d.partition_vector = np.asarray(partition_data, np.int32)
        d.partition_offsets = None
    else:
        raise AMGXError(f"unknown partition info {info}",
                        RC.BAD_PARAMETERS)
    return RC.OK


@_api
def AMGX_distribution_set_32bit_colindices(dist_h, use32):
    _get(dist_h, _CDistribution).use32 = bool(use32)
    return RC.OK


def _pv_to_renumbering(pv, n_ranks=None):
    """Partition vector -> (offsets, iperm old->new, perm new->old).
    Rows of rank r become the contiguous block [offsets[r],
    offsets[r+1]) in ascending original order (the reference's
    renumbering, distributed_manager.cu renumberMatrixOneRing). Pass
    n_ranks when trailing ranks may own zero rows (a vector alone
    cannot reveal them)."""
    n = pv.shape[0]
    perm = np.argsort(pv, kind="stable")         # new -> old
    iperm = np.empty(n, np.int64)
    iperm[perm] = np.arange(n)
    counts = np.bincount(pv, minlength=n_ranks or int(pv.max()) + 1)
    offsets = np.concatenate([[0], np.cumsum(counts)])
    return offsets, iperm, perm


def _accumulate_piece(m, n_global, n, row_ptrs, col_indices_global,
                      data, diag_data, offsets, iperm, perm, dtype):
    """Store one rank's piece; assemble the DistPartition on the last."""
    if getattr(m, "pieces", None) is None or m.pieces_meta != \
            (int(n_global), len(offsets) - 1):
        m.pieces = []
        m.pieces_meta = (int(n_global), len(offsets) - 1)
    r = len(m.pieces)
    declared = int(offsets[r + 1]) - int(offsets[r])
    if int(n) != declared:
        raise AMGXError(
            f"piece {r} has {n} rows but the distribution assigns "
            f"rank {r} {declared} rows", RC.BAD_PARAMETERS)
    ro = np.asarray(row_ptrs, np.int64)
    if ro.shape[0] != n + 1:
        raise AMGXError(
            f"piece {r}: row_ptrs has {ro.shape[0]} entries, expected "
            f"{n + 1}", RC.BAD_PARAMETERS)
    ci = np.asarray(col_indices_global, np.int64)
    vals = np.asarray(data, dtype)
    if iperm is not None:
        ci = iperm[ci]          # renumber cols to partition-contiguous
    if getattr(m, "piece_prefold", None) is None or len(m.pieces) == 0:
        m.piece_prefold = []
    m.piece_prefold.append(
        (ro.astype(np.int32), ci.astype(np.int32),
         diag_data is not None))
    pre_fold = m.piece_prefold
    if diag_data is not None:
        ro, ci, vals = _fold_piece_diag(
            ro, ci, vals, np.asarray(diag_data, dtype), int(n),
            int(offsets[r]))
    m.pieces.append((ro, ci, vals))
    if len(m.pieces) == len(offsets) - 1:
        from .distributed.partition import partition_from_pieces
        part = partition_from_pieces(m.pieces, int(n_global), dtype=dtype)
        m.part = part
        m.part_offsets = np.asarray(offsets, np.int64)
        m.row_perm = perm
        m.A = None
        # keep the PRE-FOLD piece structure (int32 — half the retained
        # host memory): AMGX_matrix_replace_coefficients re-renumbers
        # and re-folds new values against it
        m.piece_structure = pre_fold
        m.piece_nglobal = int(n_global)
        m.piece_iperm = iperm
        m.pieces = None
    return RC.OK


def _fold_piece_diag(ro, ci, vals, dg, n: int, lo: int):
    """Fold an external diagonal into one rank's CSR piece (the
    distributed layer requires folded diagonals); in the renumbered
    space this rank's row i has global id lo + i."""
    rows_all = np.concatenate([np.repeat(np.arange(n), np.diff(ro)),
                               np.arange(n)])
    cols_all = np.concatenate([ci,
                               np.arange(lo, lo + n, dtype=np.int64)])
    vals_all = np.concatenate([vals, dg])
    order = np.lexsort((cols_all, rows_all))
    ro2 = np.zeros(n + 1, np.int64)
    np.cumsum(np.bincount(rows_all[order], minlength=n), out=ro2[1:])
    return ro2, cols_all[order], vals_all[order]


@_api
def AMGX_matrix_upload_distributed(mtx_h, n_global, n, nnz, block_dimx,
                                   block_dimy, row_ptrs,
                                   col_indices_global, data,
                                   diag_data, dist_h):
    """One rank's piece (src/amgx_c.cu:4615-4753). Call once per rank,
    in rank order; the arranger runs after the last piece."""
    m = _get(mtx_h, _CMatrix)
    d = _get(dist_h, _CDistribution)
    if block_dimx * block_dimy != 1:
        raise AMGXError(
            "upload_distributed: block systems not yet supported on the "
            "piece path (upload globally + AMGX_read_system_distributed)",
            RC.NOT_IMPLEMENTED)
    if d.partition_offsets is not None:
        offsets, iperm, perm = d.partition_offsets, None, None
    else:
        offsets, iperm, perm = _pv_to_renumbering(d.partition_vector,
                                                  d.n_ranks)
    return _accumulate_piece(m, n_global, n, row_ptrs,
                             col_indices_global, data, diag_data,
                             offsets, iperm, perm, m.mode.mat_dtype)


@_api
def AMGX_matrix_upload_all_global(mtx_h, n_global, n, nnz, block_dimx,
                                  block_dimy, row_ptrs,
                                  col_indices_global, data,
                                  diag_data=None, allocated_halo_depth=1,
                                  num_import_rings=1,
                                  partition_vector=None):
    """include/amgx_c.h:545 — upload_distributed with an inline
    partition vector (None = equal contiguous blocks over the mesh)."""
    m = _get(mtx_h, _CMatrix)
    if block_dimx * block_dimy != 1:
        raise AMGXError(
            "upload_all_global: block systems not yet supported on the "
            "piece path", RC.NOT_IMPLEMENTED)
    if partition_vector is not None:
        pv = np.asarray(partition_vector, np.int32)
        offsets, iperm, perm = _pv_to_renumbering(pv)
    else:
        import jax
        R = max(len(jax.devices()), 1)
        n_local = -(-int(n_global) // R)
        offsets = np.minimum(np.arange(R + 1) * n_local, int(n_global))
        iperm = perm = None
    return _accumulate_piece(m, n_global, n, row_ptrs,
                             col_indices_global, data, diag_data,
                             offsets, iperm, perm, m.mode.mat_dtype)


AMGX_matrix_upload_all_global_32 = AMGX_matrix_upload_all_global


@_api
def AMGX_vector_bind(vec_h, mtx_h):
    """Bind a vector to a matrix's distribution (src/amgx_c.cu:3704):
    subsequent uploads provide per-rank pieces."""
    v = _get(vec_h, _CVector)
    m = _get(mtx_h, _CMatrix)
    v.bound_matrix = m
    v.bound_pieces = []
    return RC.OK


@_api
def AMGX_vector_upload_distributed(vec_h, n, block_dim, data):
    """One rank's vector piece for a bound vector; assembles the global
    (renumbered) vector after the last piece."""
    v = _get(vec_h, _CVector)
    m = getattr(v, "bound_matrix", None)
    if m is None or getattr(m, "part_offsets", None) is None:
        raise AMGXError("vector not bound to a distributed matrix",
                        RC.BAD_PARAMETERS)
    v.bound_pieces.append(np.asarray(data, v.__dict__.get(
        "dtype", None) or m.mode.vec_dtype))
    R = len(m.part_offsets) - 1
    if len(v.bound_pieces) == R:
        v.v = np.concatenate(v.bound_pieces)
        v.block_dim = block_dim
        v.bound_pieces = []
    return RC.OK


@_api
@_outputs(1)
def AMGX_read_system_global(rsrc_h, mode: str, filename: str,
                            allocated_halo_depth=1, num_partitions=None,
                            partition_sizes=None,
                            partition_vector=None):
    """include/amgx_c.h:525 — read a global system and split it into
    per-rank pieces with GLOBAL column ids, ready for
    AMGX_matrix_upload_distributed / upload_all_global. The reference
    returns the calling rank's piece; the single-controller analog
    returns all pieces: rc, list of dicts with keys n, nnz, row_ptrs,
    col_indices_global, data, diag (None), rhs, sol, plus
    'partition_offsets'."""
    from .io import read_system as _read
    from .io.distributed import (renumber_by_partition,
                                 sizes_to_partition_vector)
    md = parse_mode(mode)
    A, b, x = _read(filename, dtype=md.mat_dtype)
    n = A.num_rows
    if partition_vector is not None:
        pv = np.asarray(partition_vector, np.int32)
    elif partition_sizes is not None:
        pv = sizes_to_partition_vector(partition_sizes, n)
    else:
        import jax
        R = int(num_partitions) if num_partitions else max(
            len(jax.devices()), 1)
        n_local = -(-n // R)
        pv = np.minimum(np.arange(n) // n_local, R - 1).astype(np.int32)
    A2, b2, x2, part_offsets, _perm = renumber_by_partition(A, pv, b, x)
    ro = np.asarray(A2.row_offsets)
    ci = np.asarray(A2.col_indices)
    va = np.asarray(A2.values)
    if b2 is None:
        b2 = np.ones(n, md.vec_dtype)
    if x2 is None:
        x2 = np.zeros(n, md.vec_dtype)
    pieces = []
    for r in range(len(part_offsets) - 1):
        lo, hi = int(part_offsets[r]), int(part_offsets[r + 1])
        s, e = int(ro[lo]), int(ro[hi])
        pieces.append({
            "n": hi - lo, "nnz": e - s,
            "row_ptrs": ro[lo:hi + 1] - ro[lo],
            "col_indices_global": ci[s:e], "data": va[s:e],
            "diag": None, "rhs": b2[lo:hi], "sol": x2[lo:hi],
            "partition_offsets": np.asarray(part_offsets),
        })
    return RC.OK, pieces


@_api
@_outputs(1)
def AMGX_read_system_maps_one_ring(rsrc_h, mode: str, filename: str,
                                   allocated_halo_depth=1,
                                   num_partitions=None,
                                   partition_sizes=None,
                                   partition_vector=None):
    """include/amgx_c.h:452 — read + partition a system and return each
    rank's piece in ONE-RING LOCAL numbering (owned columns first, then
    halo columns in sorted-global order) together with the B2L comm
    maps (neighbors, send/recv index maps). The reference returns the
    calling rank's piece via out-pointers; the single-controller analog
    returns rc plus a list of per-rank dicts with keys n, nnz,
    block_dimx, block_dimy, row_ptrs, col_indices (local one-ring),
    data, diag_data, rhs, sol, neighbors, send_sizes, send_maps,
    recv_sizes, recv_maps."""
    rc, pieces = AMGX_read_system_global(
        rsrc_h, mode, filename, allocated_halo_depth, num_partitions,
        partition_sizes, partition_vector)
    if rc != RC.OK:
        return rc, None
    offsets = np.asarray(pieces[0]["partition_offsets"], np.int64)
    R = len(pieces)
    halo_lists = []
    for r, p in enumerate(pieces):
        lo, hi = offsets[r], offsets[r + 1]
        cg = np.asarray(p["col_indices_global"], np.int64)
        halo_lists.append(np.unique(cg[(cg < lo) | (cg >= hi)]))
    out = []
    for r, p in enumerate(pieces):
        lo, hi = offsets[r], offsets[r + 1]
        n_r = int(hi - lo)
        cg = np.asarray(p["col_indices_global"], np.int64)
        hl = halo_lists[r]
        owned = (cg >= lo) & (cg < hi)
        local = np.where(owned, cg - lo,
                         n_r + np.searchsorted(hl, cg)).astype(np.int32)
        h_owner = np.searchsorted(offsets, hl, side="right") - 1
        # neighbors = union of recv-side owners and ranks whose halo
        # lists reference MY rows (on a pattern-asymmetric matrix a
        # rank can be send-only toward a peer it receives nothing from)
        send_only = [q for q in range(R) if q != r and np.any(
            (halo_lists[q] >= lo) & (halo_lists[q] < hi))]
        neighbors = np.unique(np.concatenate(
            [h_owner, np.asarray(send_only, np.int64)])).astype(np.int32)
        recv_maps = [
            (n_r + np.nonzero(h_owner == nb)[0]).astype(np.int32)
            for nb in neighbors]
        # send maps by symmetry: what each neighbor's halo list wants
        # from my owned range (the B2L maps of
        # distributed_arranger.h:28-117)
        send_maps = [
            (halo_lists[nb][(halo_lists[nb] >= lo)
                            & (halo_lists[nb] < hi)]
             - lo).astype(np.int32)
            for nb in neighbors]
        out.append({
            "n": n_r, "nnz": int(p["nnz"]), "block_dimx": 1,
            "block_dimy": 1, "row_ptrs": p["row_ptrs"],
            "col_indices": local, "data": p["data"],
            "diag_data": p["diag"], "rhs": p["rhs"], "sol": p["sol"],
            "num_neighbors": int(neighbors.shape[0]),
            "neighbors": neighbors,
            "send_sizes": np.asarray([m.shape[0] for m in send_maps],
                                     np.int32),
            "send_maps": send_maps,
            "recv_sizes": np.asarray([m.shape[0] for m in recv_maps],
                                     np.int32),
            "recv_maps": recv_maps,
        })
    return RC.OK, out


@_api
def AMGX_free_system_maps_one_ring(*_args):
    """include/amgx_c.h:478 — frees the buffers returned by
    AMGX_read_system_maps_one_ring. The Python analog's buffers are
    garbage-collected; provided for call-site parity."""
    return RC.OK


@_api
def AMGX_solver_register_print_callback(callback):
    """include/amgx_c.h:600 (deprecated tail) — per-solver print
    callback registration; the reference's implementation routes to the
    global callback, as does this analog."""
    from .output import register_print_callback
    register_print_callback(callback)
    return RC.OK


@_api
def AMGX_matrix_comm_from_maps_one_ring(mtx_h, allocated_halo_depth,
                                        num_neighbors, neighbors,
                                        send_sizes, send_maps,
                                        recv_sizes, recv_maps):
    """include/amgx_c.h:325 — explicit one-ring B2L maps for a matrix
    whose pieces were uploaded with LOCAL column indices (owned columns
    < n_local; halo columns numbered n_local.. in recv-map order).

    Single-controller convention: all per-rank map sets are passed at
    once as nested lists (maps[r][k] = rank r's map with its k-th
    neighbor), mirroring what each MPI rank would pass. The pieces must
    already be staged via AMGX_matrix_upload_distributed with a
    distribution whose offsets cover the LOCAL (owned) rows and local
    col ids; this call rewrites halo columns to global ids and re-runs
    the arranger."""
    m = _get(mtx_h, _CMatrix)
    if getattr(m, "part", None) is None or m.part_offsets is None:
        raise AMGXError(
            "comm_from_maps: upload the per-rank pieces first",
            RC.BAD_PARAMETERS)
    raise AMGXError(
        "comm_from_maps: the uploaded pieces already carried global "
        "column ids, so the arranger has built equivalent maps; "
        "explicit B2L override is not needed on this backend",
        RC.NOT_IMPLEMENTED)


AMGX_matrix_comm_from_maps = AMGX_matrix_comm_from_maps_one_ring


# ---------------------------------------------------------------------------
# C API tail (include/amgx_c.h misc functions)
# ---------------------------------------------------------------------------


@_api
@_outputs(4)
def AMGX_matrix_download_all(mtx_h):
    """include/amgx_c.h:294 — rc, row_ptrs, col_indices, data, diag
    (diag None: the container folds external diagonals on upload)."""
    m = _get(mtx_h, _CMatrix)
    if m.A is None:
        raise AMGXError("matrix not uploaded", RC.BAD_PARAMETERS)
    # flat value layout, matching what AMGX_matrix_upload_all accepts
    return (RC.OK, np.asarray(m.A.row_offsets).copy(),
            np.asarray(m.A.col_indices).copy(),
            np.asarray(m.A.values).reshape(-1).copy(),
            None if not m.A.has_external_diag
            else np.asarray(m.A.diag).reshape(-1).copy())


@_api
def AMGX_matrix_vector_multiply(mtx_h, x_h, y_h):
    """include/amgx_c.h:306 — y = A x."""
    from .ops.spmv import spmv
    m = _get(mtx_h, _CMatrix)
    x = _get(x_h, _CVector)
    y = _get(y_h, _CVector)
    if m.A is None or x.v is None:
        raise AMGXError("matrix/vector not uploaded", RC.BAD_PARAMETERS)
    with m.resources.res.device_context():
        y.v = np.asarray(spmv(m.A, jnp.asarray(
            np.asarray(x.v, m.mode.vec_dtype))))
    y.block_dim = m.A.block_dimx
    return RC.OK


@_api
@_outputs(1)
def AMGX_solver_calculate_residual_norm(slv_h, mtx_h, rhs_h, x_h):
    """include/amgx_c.h:410 — rc, per-block-component norm array (the
    solver's configured norm over b - A x)."""
    from .ops.spmv import residual
    s = _get(slv_h, _CSolver)
    m = _get(mtx_h, _CMatrix)
    b = _get(rhs_h, _CVector)
    x = _get(x_h, _CVector)
    if m.A is None or b.v is None or x.v is None:
        raise AMGXError("system not uploaded", RC.BAD_PARAMETERS)
    dt = m.mode.vec_dtype
    with m.resources.res.device_context():
        r = residual(m.A, jnp.asarray(np.asarray(x.v, dt)),
                     jnp.asarray(np.asarray(b.v, dt)))
        nrm = s.solver._norm(r) if s.solver is not None else \
            jnp.linalg.norm(r)
    return RC.OK, np.atleast_1d(np.asarray(nrm))


@_api
def AMGX_vector_set_random(vec_h, n):
    """include/amgx_c.h:355 — uniform [0, 1) entries (thrust random
    analog; deterministic per call counter for reproducibility). The
    vector's block dimension is preserved."""
    v = _get(vec_h, _CVector)
    seed = next(_random_seed)    # call-indexed, independent of handles
    v.batch = None
    v.v = np.random.default_rng(seed).random(n).astype(
        v.mode.vec_dtype)
    return RC.OK


@_api
@_outputs(2)
def AMGX_matrix_check_symmetry(mtx_h):
    """include/amgx_c.h:588 — rc, structurally_symmetric, symmetric."""
    m = _get(mtx_h, _CMatrix)
    if m.A is None:
        raise AMGXError("matrix not uploaded", RC.BAD_PARAMETERS)
    A = m.A
    ro = np.asarray(A.row_offsets)
    ci = np.asarray(A.col_indices)
    va = np.asarray(A.values)
    n = A.num_rows
    rows = np.repeat(np.arange(n), np.diff(ro))
    if A.is_block:
        va = va.reshape(va.shape[0], -1)
    order_f = np.lexsort((ci, rows))
    order_t = np.lexsort((rows, ci))
    struct = bool(np.array_equal(rows[order_f], ci[order_t]) and
                  np.array_equal(ci[order_f], rows[order_t]))
    sym = False
    if struct and A.block_dimx != A.block_dimy:
        sym = False               # non-square blocks: never symmetric
    elif struct:
        vt = va[order_t]
        if A.is_block:
            bx = A.block_dimx
            vt = vt.reshape(-1, bx, bx).transpose(0, 2, 1).reshape(
                vt.shape[0], -1)
        sym = bool(np.allclose(va[order_f], vt, rtol=1e-12, atol=0))
    if sym and A.has_external_diag and A.is_block:
        # non-symmetric external diagonal blocks break value symmetry
        d = np.asarray(A.diag)
        sym = bool(np.allclose(d, d.transpose(0, 2, 1), rtol=1e-12,
                               atol=0))
    return RC.OK, int(struct), int(sym)


@_api
def AMGX_matrix_attach_coloring(mtx_h, row_coloring, num_rows,
                                num_colors):
    """include/amgx_c.h:512 — user-supplied row coloring consumed by the
    multicolor smoothers instead of a computed scheme."""
    m = _get(mtx_h, _CMatrix)
    if m.A is None:
        raise AMGXError("matrix not uploaded", RC.BAD_PARAMETERS)
    colors = np.asarray(row_coloring, np.int32)
    if colors.shape[0] != num_rows or num_rows != m.A.num_rows:
        raise AMGXError("coloring size mismatch", RC.BAD_PARAMETERS)
    if colors.size and (colors.min() < 0 or colors.max() >= num_colors):
        raise AMGXError(
            f"coloring values must lie in [0, {num_colors})",
            RC.BAD_PARAMETERS)
    import dataclasses
    m.A = dataclasses.replace(m.A, user_colors=jnp.asarray(colors),
                              user_num_colors=int(num_colors))
    return RC.OK


@_api
def AMGX_matrix_set_boundary_separation(mtx_h, boundary_separation):
    """include/amgx_c.h:310 — accepted-inert by design: the latency
    hiding here is structural (owned/halo entry split,
    distributed/dist_matrix.py), not a reorder flag."""
    _get(mtx_h, _CMatrix)
    return RC.OK


def AMGX_abort(rsrc_h=None, err=1):
    """include/amgx_c.h:173 — hard process abort (no cleanup), the
    MPI_Abort analog."""
    import os
    sys.stderr.write(f"AMGX_abort: err={err}\n")
    sys.stderr.flush()
    os._exit(int(err))


def AMGX_get_build_info_strings():
    """include/amgx_c.h:154 — rc, version, build date, build system."""
    from . import __version__
    import jax
    return (RC.OK, f"amgx_tpu {__version__}",
            f"jax {jax.__version__}",
            f"backend {jax.devices()[0].platform}")


@_api
@_outputs(1)
def AMGX_config_get_default_number_of_rings(cfg_h):
    """include/amgx_c.h:210 — halo-ring requirement of the configured
    solver stack (2 for classical AMG's distributed RAP, 1 otherwise —
    the reference's selector-driven rule)."""
    cfg = _get(cfg_h, Config)
    classical = any(
        name == "algorithm" and str(v).upper() == "CLASSICAL"
        for (scope, name), v in cfg.values.items())
    return RC.OK, (2 if classical else 1)
