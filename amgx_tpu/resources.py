"""Per-process platform context (Resources analog).

The reference's `Resources` (include/resources.h:21-59, src/resources.cu)
carries the config, the CUDA devices, streams, and memory-pool handles
for every object created against it. The TPU-native equivalent carries
the JAX platform/device selection and the device mesh used by the
distributed layer — streams and memory pools are owned by XLA, so what
remains is *placement*: which chip(s) arrays created through the C API
land on, and where device memory statistics come from.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from .config import Config
from .errors import BadParametersError


class Resources:
    """Device/platform context: config + device selection + mesh."""

    def __init__(self, cfg: Optional[Config] = None, device_num: int = 0,
                 devices=None):
        import jax
        self.cfg = cfg
        all_devices = jax.devices()
        if devices:                      # explicit device-ordinal list
            try:
                self.devices = [all_devices[int(d)] for d in devices]
            except IndexError:
                raise BadParametersError(
                    f"Resources: device ordinals {devices} out of range "
                    f"({len(all_devices)} visible)")
            self._primary = 0
        else:
            # own every visible device; device_num selects the primary
            # one for single-device objects (resources_create semantics)
            if not (0 <= device_num < len(all_devices)):
                raise BadParametersError(
                    f"Resources: device_num {device_num} out of range "
                    f"({len(all_devices)} visible)")
            self.devices = list(all_devices)
            self._primary = device_num

    @property
    def device(self):
        """Primary device for single-device objects."""
        return self.devices[self._primary]

    @property
    def platform(self) -> str:
        return self.device.platform

    @property
    def num_devices(self) -> int:
        return len(self.devices)

    def device_context(self):
        """Context manager placing newly created arrays on this
        resources' primary device (jax.default_device)."""
        import jax
        return jax.default_device(self.device)

    def mesh(self, n_devices: Optional[int] = None, axis: str = "p"):
        """1-D device mesh over this resources' devices (the distributed
        layer's domain-decomposition axis; SURVEY §2.6)."""
        from .distributed.solver import default_mesh
        return default_mesh(n_devices, axis, devices=self.devices)

    def memory_stats(self) -> dict:
        """Summed memory statistics over this resources' devices
        (bytes_in_use / peak_bytes_in_use where the backend reports
        them; empty dict otherwise)."""
        from .memory_info import sum_device_stats
        return sum_device_stats(self.devices)

    def update_memory_usage(self):
        """(current, peak) bytes over this resources' devices
        (MemoryInfo::updateMaxMemoryUsage analog). Peaks are per-device
        and process-wide, so samples taken elsewhere (e.g. during a
        solve's stats print) are visible here too."""
        from .memory_info import usage_over
        return usage_over(self.devices)
