"""Declarative fallback/retry chains (the resilience policy engine).

AMGCL-style composable solver fallbacks (PAPERS.md) on top of the
structured `SolveStatus` the in-trace health guards produce: when a
solve ends in a failure status, a host-orchestrated, BOUNDED chain of
recovery actions runs — each action either retries the same solver
(reusing the AMG hierarchy and cached traces when the matrix is
unchanged) or rebuilds a stronger/alternative configuration and
re-solves.

Grammar (`fallback_policy` config parameter)::

    STATUS>action[=arg] | STATUS>action[=arg] | ...

- STATUS: a SolveStatus name (NAN_DETECTED / BREAKDOWN / DIVERGED /
  STALLED / MAX_ITERS / DEADLINE_EXCEEDED; NAN and DEADLINE are
  accepted as aliases), or ANY. DEADLINE_EXCEEDED is produced by the
  serving layer (amgx_tpu/serving/) when a request's deadline expires
  mid-flight; a chain keyed on it lets a sync re-solve of the expired
  system run a recovery action like any other failure class.
- actions:
  * ``retry``            — re-solve with the SAME solver from a zero
    guess (no setup cost: hierarchy + traces reused; a consumed
    transient fault retraces clean via the faultinject epoch);
  * ``rescale_retry``    — rebuild with DIAGONAL_SYMMETRIC equation
    scaling and re-solve (the NaN/ill-scaling recovery);
  * ``switch_solver=X``  — rebuild the tree with solver X in the same
    scope (e.g. BREAKDOWN on CG -> rerun as GMRES);
  * ``escalate_sweeps``  — double (min 1) every configured presweeps/
    postsweeps and re-solve (the STALLED recovery: more smoothing).

Multiple steps for the SAME status form a chain tried in order across
attempts; `max_fallback_attempts` bounds the total. The `|` separator
keeps the spec safe inside flat config strings (which split on commas).

Example::

    fallback_policy=NAN_DETECTED>retry|BREAKDOWN>switch_solver=GMRES,
    max_fallback_attempts=2
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..config import Config
from ..errors import BadConfigurationError, BadParametersError, did_you_mean
from ..solvers.base import Solver, SolveResult, make_solver
from .status import SolveStatus

ACTIONS = ("retry", "rescale_retry", "switch_solver", "escalate_sweeps")

# service-level grammar (serving_fault_policy, serving/service.py): the
# same 'EVENT>action|...' spec shape, keyed on service events instead
# of solve statuses. Multiple steps for one event form a chain tried in
# order across that fingerprint's consecutive failures (bounded by
# serving_retry_max_attempts, after which the tickets reject).
SERVICE_EVENTS = ("BUILD_FAILED", "STEP_FAILED", "WEDGED")
SERVICE_ACTIONS = ("retry_backoff", "requeue", "reject")

# fleet-level grammar (fleet_fault_policy, serving/health.py): the same
# 'EVENT>action|...' shape one level up — keyed on REPLICA health
# events instead of per-fingerprint service events. Multiple steps for
# one event form a chain tried in order across that replica's
# consecutive verdicts (the last step repeats once the chain is
# exhausted, so a 'probe_backoff|failover' wedge chain probes once and
# then fails over for good).
FLEET_EVENTS = ("REPLICA_DEAD", "REPLICA_WEDGED", "REPLICA_SLOW")
FLEET_ACTIONS = ("failover", "probe_backoff", "ignore")

ANY = "ANY"

_STATUS_ALIASES = {"NAN": "NAN_DETECTED", "DEADLINE": "DEADLINE_EXCEEDED"}

Chain = List[Tuple[str, str]]


def parse_fallback_policy(spec: str) -> Dict[object, Chain]:
    """Parse the policy grammar into {status_code_or_ANY: [(action,
    arg), ...]}. Raises BadConfigurationError (with a did-you-mean
    suggestion) on unknown statuses or actions."""
    policy: Dict[object, Chain] = {}
    for step in str(spec or "").split("|"):
        step = step.strip()
        if not step:
            continue
        if ">" not in step:
            raise BadConfigurationError(
                f"fallback_policy step {step!r}: expected "
                f"'STATUS>action[=arg]'")
        sname, action = (p.strip() for p in step.split(">", 1))
        sname = _STATUS_ALIASES.get(sname.upper(), sname.upper())
        if sname == ANY:
            key: object = ANY
        else:
            try:
                key = int(SolveStatus[sname])
            except KeyError:
                names = [s.name for s in SolveStatus] + \
                    [ANY] + list(_STATUS_ALIASES)
                raise BadConfigurationError(
                    f"fallback_policy: unknown status {sname!r}"
                    f"{did_you_mean(sname, names)}") from None
        act, _, arg = action.partition("=")
        act = act.strip().lower()
        arg = arg.strip()
        if act not in ACTIONS:
            raise BadConfigurationError(
                f"fallback_policy: unknown action {act!r}"
                f"{did_you_mean(act, ACTIONS)}")
        if act == "switch_solver" and not arg:
            raise BadConfigurationError(
                "fallback_policy: switch_solver needs '=SOLVER_NAME'")
        policy.setdefault(key, []).append((act, arg))
    return policy


def parse_service_policy(spec: str) -> Dict[str, List[str]]:
    """Parse the service-level grammar into {event: [action, ...]}.
    Events: BUILD_FAILED (a bucket's hierarchy build / engine trace
    raised), STEP_FAILED (a device-step cycle raised mid-flight),
    WEDGED (the supervisor's progress heartbeat flatlined). Actions:

    * ``retry_backoff`` — keep the tickets queued and retry the build
      after a bounded exponential backoff (serving_retry_backoff_s *
      2^attempt, capped at serving_retry_max_attempts total);
    * ``requeue``       — retry immediately (same attempt bound);
    * ``reject``        — complete the affected tickets with BREAKDOWN
      + the error on ticket.error.

    Raises BadConfigurationError (with a did-you-mean) on unknown
    events or actions, mirroring parse_fallback_policy."""
    policy: Dict[str, List[str]] = {}
    for step in str(spec or "").split("|"):
        step = step.strip()
        if not step:
            continue
        if ">" not in step:
            raise BadConfigurationError(
                f"serving_fault_policy step {step!r}: expected "
                f"'EVENT>action'")
        ev, action = (p.strip() for p in step.split(">", 1))
        ev = ev.upper()
        if ev not in SERVICE_EVENTS:
            raise BadConfigurationError(
                f"serving_fault_policy: unknown event {ev!r}"
                f"{did_you_mean(ev, SERVICE_EVENTS)}")
        action = action.strip().lower()
        if action not in SERVICE_ACTIONS:
            raise BadConfigurationError(
                f"serving_fault_policy: unknown action {action!r}"
                f"{did_you_mean(action, SERVICE_ACTIONS)}")
        policy.setdefault(ev, []).append(action)
    return policy


def parse_fleet_policy(spec: str) -> Dict[str, List[str]]:
    """Parse the fleet-level grammar into {event: [action, ...]}.
    Events: REPLICA_DEAD (the replica's scheduler thread died with a
    captured exception, or an inline step() raised), REPLICA_WEDGED
    (the replica is busy but its cycle counter flatlined across
    consecutive health checks), REPLICA_SLOW (cycles advance, but
    slower than `fleet_slow_cycle_s` per cycle). Actions:

    * ``failover``      — declare the replica DOWN: rehome its
      fingerprints along rendezvous order, move its queued/in-flight
      tickets to survivors, adopt its journal;
    * ``probe_backoff`` — open the circuit breaker (no new placements)
      for a bounded exponential backoff (fleet_probe_backoff_s *
      2^failures), then HALF_OPEN: exactly one trial fingerprint is
      admitted until the replica proves progress;
    * ``ignore``        — count the event, change nothing.

    Raises BadConfigurationError (with a did-you-mean) on unknown
    events or actions, mirroring parse_service_policy."""
    policy: Dict[str, List[str]] = {}
    for step in str(spec or "").split("|"):
        step = step.strip()
        if not step:
            continue
        if ">" not in step:
            raise BadConfigurationError(
                f"fleet_fault_policy step {step!r}: expected "
                f"'EVENT>action'")
        ev, action = (p.strip() for p in step.split(">", 1))
        ev = ev.upper()
        if ev not in FLEET_EVENTS:
            raise BadConfigurationError(
                f"fleet_fault_policy: unknown event {ev!r}"
                f"{did_you_mean(ev, FLEET_EVENTS)}")
        action = action.strip().lower()
        if action not in FLEET_ACTIONS:
            raise BadConfigurationError(
                f"fleet_fault_policy: unknown action {action!r}"
                f"{did_you_mean(action, FLEET_ACTIONS)}")
        policy.setdefault(ev, []).append(action)
    return policy


class ResilientSolver:
    """Wrap a solver tree with the configured fallback chains.

    Duck-types the `Solver` surface (setup / resetup / solve /
    solve_many and attribute reads delegate to the wrapped tree), so it
    drops into every call site `create_solver` feeds — including the C
    API's _CSolver. A successful fallback that rebuilt the tree ADOPTS
    the rebuilt solver, so subsequent solves keep the recovered
    configuration (and its hierarchy) instead of re-failing first.
    """

    def __init__(self, cfg: Config, scope: str = "default",
                 solver: Optional[Solver] = None):
        if solver is None:
            name, child_scope = cfg.get_solver("solver", scope)
            solver = make_solver(name, cfg, child_scope)
        self.solver = solver
        self.cfg = cfg
        self.policy = parse_fallback_policy(
            cfg.get("fallback_policy", solver.scope))
        self.max_attempts = int(cfg.get("max_fallback_attempts",
                                        solver.scope))
        self._A = None

    # -- Solver surface ---------------------------------------------------
    def setup(self, A):
        self._A = A
        self.solver.setup(A)
        return self

    def resetup(self, A):
        self._A = A
        self.solver.resetup(A)
        return self

    def __getattr__(self, name):
        # everything else (A, max_iters, solve_many, solve_data, ...)
        # reads through to the wrapped tree
        return getattr(self.solver, name)

    # -- the attempt loop -------------------------------------------------
    def _chain_for(self, code: int, used: Dict[object, int]):
        for key in (int(code), ANY):
            chain = self.policy.get(key, [])
            i = used.get(key, 0)
            if i < len(chain):
                used[key] = i + 1
                return chain[i]
        return None

    def solve(self, b, x0=None, zero_initial_guess: bool = False
              ) -> SolveResult:
        res = self.solver.solve(b, x0=x0,
                                zero_initial_guess=zero_initial_guess)
        history = [("initial", res.status)]
        used: Dict[object, int] = {}
        attempts = 0
        while (res.status_code != int(SolveStatus.CONVERGED)
               and attempts < self.max_attempts):
            step = self._chain_for(res.status_code, used)
            if step is None:
                break
            action, arg = step
            attempts += 1
            from ..telemetry import flightrec
            flightrec.record(
                "fallback.hop", action=action, arg=arg or None,
                attempt=attempts,
                from_status=SolveStatus(res.status_code).name)
            res = self._run_action(action, arg, b, x0,
                                   zero_initial_guess)
            history.append(
                (f"{action}={arg}" if arg else action, res.status))
        # attach the audit trail (which chain steps ran, and how each
        # attempt ended) without widening the SolveResult contract
        res.fallback_history = history
        return res

    def _run_action(self, action: str, arg: str, b, x0,
                    zero_initial_guess: bool) -> SolveResult:
        from ..telemetry import metrics as _tm
        _tm.inc("resilience.fallback_attempts")
        _tm.inc(f"resilience.fallback.{action}")
        if action == "retry":
            # same tree, zero guess: hierarchy and cached traces are
            # reused (the matrix is unchanged); a consumed injected
            # fault retraces clean via the faultinject epoch in the
            # solver's jit cache key
            return self.solver.solve(b, zero_initial_guess=True)
        if self._A is None:
            raise BadParametersError(
                f"fallback action {action!r} needs the matrix from "
                "setup(); this solver was set up through a path that "
                "bypassed ResilientSolver.setup")
        scope = self.solver.scope
        name = self.solver.name
        cfg2 = self.cfg.clone()
        if action == "rescale_retry":
            cfg2.set("scaling", "DIAGONAL_SYMMETRIC", scope=scope)
        elif action == "switch_solver":
            name = arg
            if not self._cfg_names_preconditioner(scope):
                # don't let the registered default ("AMG") silently
                # bolt a multigrid preconditioner onto the substitute
                cfg2.set("preconditioner", "NOSOLVER", scope=scope)
        elif action == "escalate_sweeps":
            self._escalate_sweeps(cfg2)
        new = make_solver(name, cfg2, scope)
        new.setup(self._A)
        res = new.solve(b, x0=x0, zero_initial_guess=zero_initial_guess)
        self.solver = new          # adopt the recovered configuration
        return res

    def _cfg_names_preconditioner(self, scope: str) -> bool:
        vals = self.cfg.values
        return (scope, "preconditioner") in vals or \
            ("default", "preconditioner") in vals

    def _escalate_sweeps(self, cfg2: Config):
        """Double every configured presweeps/postsweeps (min 1); when a
        config never set them, install 2 sweeps in the default scope so
        every AMG member smooths harder."""
        hit = False
        for (s, n), v in list(cfg2.values.items()):
            if n in ("presweeps", "postsweeps"):
                cfg2.set(n, max(1, 2 * int(v)), scope=s)
                hit = True
        if not hit:
            cfg2.set("presweeps", 2)
            cfg2.set("postsweeps", 2)
