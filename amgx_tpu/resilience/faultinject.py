"""Deterministic fault injection for resilience testing.

Proves every `SolveStatus` code and every fallback edge actually fires
(tests/test_resilience.py) by corrupting the numerics at three seams:

- **SpMV output** (`corrupt_spmv`, hooked in ops/spmv.py): poison one
  element of y = A x with a non-finite value at a configured solve
  iteration — the NaN then storms through the Krylov state and must be
  caught by the in-trace health guards.
- **Galerkin values** (`perturb_galerkin`, hooked in amg/hierarchy.py):
  scale one level's coarse operator during the hierarchy build,
  wrecking the AMG preconditioner without touching the fine system.
- **Halo exchange** (`corrupt_halo`, hooked in
  distributed/dist_matrix.py): poison one received halo entry at a
  configured iteration — the distributed analog of a link fault; every
  shard must agree on the resulting status.

Injection is TRACE-TIME: an armed spec bakes the (iteration-gated)
corruption into the next trace that crosses a hook, then `fires`
decrements. The injection `epoch()` participates in the solver-side jit
cache keys, so arming/consuming/disarming naturally invalidates traces
— a consumed spec's retry gets a CLEAN fresh trace (the transient-fault
model the fallback engine's plain `retry` action exploits), and a
never-armed process pays nothing (epoch stays 0 forever).

The in-loop hooks fire only while an iteration scope is active (set by
the solve-loop body around `solve_iteration`), so setup-phase SpMVs and
halo exchanges are never corrupted by a loop-targeted spec.

Arm programmatically::

    with faultinject.inject("spmv_nan", iteration=3):
        res = slv.solve(b)          # status == NAN_DETECTED

or via the environment (AMGX_TPU_DEBUG_RESETUP-style toggle)::

    AMGX_TPU_FAULT_INJECT="spmv_nan:iteration=3:fires=1"

**Service-level chaos** (the serving fault-tolerance harness,
serving/service.py + tests/test_serving.py) extends the same arming
machinery with HOST-side faults — no tracing involved, so `fires`
counts straight occurrences:

- ``build_crash``    — raise ChaosInjected inside the next bucket
  build(s) (the builder-thread/inline-build failure drill);
- ``step_crash``     — raise ChaosInjected inside the next engine
  device-step cycle(s) (the quarantine drill);
- ``step_wedge``     — the next engine cycle(s) silently make NO
  progress (iteration counters frozen): the wedged-bucket heartbeat
  detector's food;
- ``journal_corrupt`` / ``aot_corrupt`` — corrupt the next blob
  written to the solve journal / AOT store (torn-write model: the
  damage is discovered at read time, which must degrade, never hang);
- ``clock_skew``     — `service_now()` returns monotonic time shifted
  by `value` seconds (deadline bookkeeping under a skewed clock).

**Fleet-level chaos** (the replica health/failover layer,
serving/fleet.py + serving/health.py) targets a whole REPLICA's
scheduler instead of one bucket; `target` names the replica id the
fault lands on (empty = the first replica whose scheduler crosses the
hook):

- ``replica_kill``  — raise ChaosInjected at the top of the targeted
  replica's next step() cycle(s): a background scheduler thread dies
  with the captured exception, an inline-driven fleet surfaces it
  through the router — either way the health monitor must declare the
  replica DEAD and fail over;
- ``replica_wedge`` — the targeted replica's next cycle(s) return
  without doing anything and WITHOUT advancing the cycle counter: the
  replica-level heartbeat flatline the breaker opens on;
- ``replica_slow``  — sleep `value` seconds at the top of the targeted
  replica's next cycle(s): per-cycle wall blows past
  `fleet_slow_cycle_s` and the health monitor counts REPLICA_SLOW.
"""
from __future__ import annotations

import contextlib
import dataclasses
import math
import os
import time
from typing import Optional

KINDS = ("spmv_nan", "halo_corrupt", "galerkin_perturb",
         # service-level (host-side) chaos kinds — serving/
         "build_crash", "step_crash", "step_wedge",
         "shadow_crash",
         "journal_corrupt", "aot_corrupt", "clock_skew",
         # fleet-level chaos kinds (whole-replica faults) — serving/
         # fleet.py + serving/health.py failover drills
         "replica_kill", "replica_wedge", "replica_slow")


class ChaosInjected(RuntimeError):
    """Raised by service_crash hooks: a scripted service-level fault
    (never produced by real code paths — tests and the chaos bench
    assert the service survives it, not that it happened)."""

_ENV_VAR = "AMGX_TPU_FAULT_INJECT"


@dataclasses.dataclass
class FaultSpec:
    kind: str              # one of KINDS
    iteration: int = 0     # 0-based solve iteration the fault fires at
    index: int = 0         # flat element (spmv/halo) or level (galerkin)
    value: float = math.nan  # poison value for spmv/halo corruption
    scale: float = 100.0   # multiplicative perturbation for galerkin
    fires: Optional[int] = 1  # armed traces/applications left; None = always
    target: str = ""       # replica id for replica_* kinds ("" = any)

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(
                f"faultinject: unknown kind {self.kind!r} "
                f"(choose from {KINDS})")


_SPEC: Optional[FaultSpec] = None
_EPOCH = 0
_ENV_CHECKED = False
# traced iteration counter of the solve loop currently being traced
# (None outside a loop body — setup-phase hooks then stay inert)
_ITER = None


def epoch() -> int:
    """Monotone counter bumped on every arm/consume/disarm. Folded into
    solve-side jit cache keys so injection state changes retrace."""
    _check_env()
    return _EPOCH


def evict_stale_epochs(cache: dict, current_epoch: int):
    """Drop cache entries keyed under older injection epochs (the epoch
    is the LAST element of every participating cache key). They are
    unreachable — the epoch only moves forward — and may be
    deliberately poisoned traces; periodic fault drills must not grow
    the solve caches without bound. Owned here so every epoch-keyed
    cache (solvers/base.py, batch/core.py) evicts by the same rule."""
    for k in [k for k in cache if k[-1] != current_epoch]:
        del cache[k]


def _bump():
    global _EPOCH
    _EPOCH += 1


def _check_env():
    """Arm a spec from AMGX_TPU_FAULT_INJECT on first use:
    `kind[:key=value[:key=value...]]` with keys iteration/index/value/
    scale/fires (fires=none for an always-on fault)."""
    global _ENV_CHECKED, _SPEC
    if _ENV_CHECKED:
        return
    _ENV_CHECKED = True
    raw = os.environ.get(_ENV_VAR, "").strip()
    if not raw or _SPEC is not None:
        return
    parts = raw.split(":")
    kw = {}
    for item in parts[1:]:
        k, _, v = item.partition("=")
        k = k.strip()
        if k == "fires":
            kw[k] = None if v.strip().lower() in ("none", "inf") else int(v)
        elif k in ("iteration", "index"):
            kw[k] = int(v)
        elif k in ("value", "scale"):
            kw[k] = float(v)
        elif k == "target":
            kw[k] = v.strip()
    _SPEC = FaultSpec(parts[0].strip(), **kw)
    _bump()
    # the env path is how a LIVE process gets a drill — its arming
    # must hit the postmortem trail exactly like a programmatic arm()
    _flightrec(_SPEC.kind, armed=True, fires=_SPEC.fires)


def _flightrec(kind: str, **fields):
    """Record a chaos event on the flight recorder — the postmortem
    trail must name the injected cause (lazy import: telemetry must
    stay importable without resilience and vice versa)."""
    try:
        from ..telemetry import flightrec
        flightrec.record("chaos", fault=kind, **fields)
    except Exception:
        pass


def arm(spec: FaultSpec):
    """Install `spec` as the active fault (replacing any previous)."""
    global _SPEC, _ENV_CHECKED
    _ENV_CHECKED = True          # explicit arming overrides the env
    _SPEC = spec
    _bump()
    # the arming itself is a state transition worth a postmortem line
    # (an always-on fault like clock_skew never "fires" countably)
    _flightrec(spec.kind, armed=True, fires=spec.fires)


def disarm():
    global _SPEC
    if _SPEC is not None:
        _SPEC = None
        _bump()


@contextlib.contextmanager
def inject(kind: str, **kw):
    """Arm a fault for the duration of the block (disarmed on exit even
    if already consumed)."""
    arm(FaultSpec(kind, **kw))
    try:
        yield
    finally:
        disarm()


def active(kind: str) -> Optional[FaultSpec]:
    """The armed spec for `kind`, if it has fires left."""
    _check_env()
    s = _SPEC
    if s is None or s.kind != kind:
        return None
    if s.fires is not None and s.fires <= 0:
        return None
    return s


def consume(kind: str):
    """Record one firing (one poisoned trace, or one applied galerkin
    perturbation). Called at trace/apply time by the hooks' owners."""
    s = active(kind)
    if s is None:
        return
    _flightrec(kind, fired=True)
    if s.fires is not None:
        s.fires -= 1
        _bump()


# kinds whose corruption hooks were actually reached while tracing the
# current solve loop — a fires-limited fault must only be spent by a
# trace that really contains its injection site (an armed halo fault
# must survive unrelated single-device solves untouched)
_HOOK_HITS = set()


def any_loop_fault_armed() -> bool:
    """Is an in-loop fault (spmv/halo) armed? The solve-loop tracer
    consumes one firing per trace when this is true."""
    return active("spmv_nan") is not None or \
        active("halo_corrupt") is not None


def consume_loop_faults():
    """Spend one firing for each in-loop kind whose hook fired during
    the trace that just completed."""
    for kind in ("spmv_nan", "halo_corrupt"):
        if kind in _HOOK_HITS:
            consume(kind)
    _HOOK_HITS.clear()


# -- iteration scope (links the loop counter to the deep hooks) ---------


@contextlib.contextmanager
def iteration_scope(it):
    """Declare the traced iteration counter while `solve_iteration` is
    being traced, so hooks buried under spmv/halo can gate on it."""
    global _ITER
    prev = _ITER
    _ITER = it
    try:
        yield
    finally:
        _ITER = prev


# -- hooks (trace-time no-ops when nothing is armed) --------------------


def corrupt_spmv(y):
    """Poison y[index] with `value` at the configured iteration. Inert
    outside a solve loop (no iteration scope)."""
    spec = active("spmv_nan")
    if spec is None or _ITER is None:
        return y
    import jax.numpy as jnp
    _HOOK_HITS.add("spmv_nan")
    hit = _ITER == spec.iteration
    return y.at[spec.index].set(
        jnp.where(hit, jnp.asarray(spec.value, y.dtype), y[spec.index]))


def corrupt_halo(halo):
    """Poison one received halo entry at the configured iteration."""
    spec = active("halo_corrupt")
    if spec is None or _ITER is None or halo.shape[0] == 0:
        return halo
    import jax.numpy as jnp
    _HOOK_HITS.add("halo_corrupt")
    idx = min(spec.index, halo.shape[0] - 1)
    hit = _ITER == spec.iteration
    return halo.at[idx].set(
        jnp.where(hit, jnp.asarray(spec.value, halo.dtype), halo[idx]))


# -- service-level hooks (host-side; serving/) --------------------------


def service_crash(point: str):
    """Raise ChaosInjected when the `point` kind ('build_crash' /
    'step_crash') is armed — one consumed firing per raise. Inert (and
    free) when nothing is armed."""
    spec = active(point)
    if spec is None:
        return
    consume(point)
    raise ChaosInjected(f"chaos: injected {point}")


def step_wedged() -> bool:
    """True while a 'step_wedge' fault is armed: the engine cycle makes
    no progress this cycle (consumes one firing per wedged cycle)."""
    spec = active("step_wedge")
    if spec is None:
        return False
    consume("step_wedge")
    return True


def corrupt_blob(kind: str, blob: bytes) -> bytes:
    """Torn-write model for 'journal_corrupt' / 'aot_corrupt': when
    armed, the blob about to be persisted is truncated and bit-flipped
    (one firing per corrupted write). The read path must detect the
    damage and degrade — skip the record / retrace — never hang."""
    spec = active(kind)
    if spec is None:
        return blob
    consume(kind)
    half = bytes(b ^ 0xFF for b in blob[:max(1, len(blob) // 2)])
    return half


def service_now() -> float:
    """time.monotonic(), shifted by `value` seconds while a
    'clock_skew' fault is armed (arm with fires=None for a persistent
    skew). Every serving-layer deadline computation reads the clock
    through this hook so skew drills are deterministic."""
    spec = active("clock_skew")
    now = time.monotonic()
    if spec is None:
        return now
    return now + float(spec.value)


# -- fleet-level hooks (whole-replica faults; serving/fleet.py) ---------


def _replica_spec(kind: str, replica: str) -> Optional[FaultSpec]:
    """The armed replica-fault spec for `kind` when it targets THIS
    replica (spec.target empty = any replica's scheduler may trip it)."""
    spec = active(kind)
    if spec is None:
        return None
    if spec.target and spec.target != str(replica):
        return None
    return spec


def replica_crash(replica: str):
    """Raise ChaosInjected at the top of the targeted replica's
    scheduler cycle while 'replica_kill' is armed — the whole-replica
    analog of service_crash (one consumed firing per raise)."""
    spec = _replica_spec("replica_kill", replica)
    if spec is None:
        return
    consume("replica_kill")
    raise ChaosInjected(
        f"chaos: injected replica_kill on {replica or 'replica'}")


def replica_wedged(replica: str) -> bool:
    """True while 'replica_wedge' targets this replica: the scheduler
    cycle returns without running AND without advancing the cycle
    counter — the replica-level heartbeat flatline (one firing per
    wedged cycle)."""
    spec = _replica_spec("replica_wedge", replica)
    if spec is None:
        return False
    consume("replica_wedge")
    return True


def replica_delay(replica: str) -> float:
    """Seconds to stall the targeted replica's cycle while
    'replica_slow' is armed (spec.value; one firing per slowed
    cycle), else 0.0."""
    spec = _replica_spec("replica_slow", replica)
    if spec is None:
        return 0.0
    consume("replica_slow")
    v = float(spec.value)
    return v if math.isfinite(v) and v > 0.0 else 0.0


def perturb_galerkin(Ac, level: int):
    """Scale a coarse-level operator's values during the hierarchy
    build (spec.index selects the level). Consumes one firing per
    applied perturbation — host-orchestrated, so no trace caching can
    replay it."""
    spec = active("galerkin_perturb")
    if spec is None or level != spec.index:
        return Ac
    consume("galerkin_perturb")
    diag = None
    if getattr(Ac, "has_external_diag", False):
        diag = Ac.diag * spec.scale
    return Ac.with_values(Ac.values * spec.scale, diag)
