"""Solver resilience subsystem.

Production serving needs solves that fail *diagnosably* and degrade
*gracefully*. This package supplies the three layers:

- `status`: the `SolveStatus` vocabulary (CONVERGED / MAX_ITERS /
  STALLED / DIVERGED / BREAKDOWN / NAN_DETECTED), carried in-trace by
  the solve loop (solvers/base.py) at zero extra device->host syncs,
  plus the AMGX_SOLVE_* mapping for the C API;
- `policy`: the declarative, bounded fallback/retry engine
  (`ResilientSolver`), configured via the `fallback_policy` config
  parameter;
- `faultinject`: the deterministic fault harness — solve-level (SpMV
  NaNs, Galerkin perturbation, halo corruption) and service-level
  (builder crashes, device-step exceptions, wedged cycles,
  journal/AOT-store corruption, clock skew) — that proves every status
  code, every fallback edge, and every serving recovery path is
  reachable.

`policy` is imported lazily: it pulls in the solver tree, while
`status`/`faultinject` are dependency-free and are imported by low
layers (ops/spmv.py, solvers/base.py).
"""
from __future__ import annotations

from . import faultinject  # noqa: F401
from .status import (  # noqa: F401
    AMGX_SOLVE_DIVERGED, AMGX_SOLVE_FAILED, AMGX_SOLVE_NOT_CONVERGED,
    AMGX_SOLVE_SUCCESS, SolveStatus, status_string, to_amgx_status)


def __getattr__(name):
    if name in ("policy", "ResilientSolver", "parse_fallback_policy",
                "parse_service_policy"):
        from . import policy
        if name == "policy":
            return policy
        return getattr(policy, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
