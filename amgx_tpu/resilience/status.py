"""Structured solve statuses.

The reference returns AMGX_SOLVE_SUCCESS / FAILED / DIVERGED /
NOT_CONVERGED from every solve (include/amgx_c.h AMGX_SOLVE_STATUS);
the port's original single `converged` bool collapsed a NaN storm, an
indefinite-matrix CG breakdown, and an honest max-iters exit into one
indistinguishable failure string. `SolveStatus` restores the
distinction — and refines it with the breakdown/stall classes the
fallback engine (resilience/policy.py) keys its chains on.

The integer codes are ordered by SEVERITY so that a cross-replica
`pmax` (distributed/solver.py) and a per-batch `max` (capi worst-case
reporting) both pick the worst outcome, and so the in-trace guard logic
can fold the classification into one int32 carried by the solve loop's
`while_loop` state (solvers/base.py) — no extra device->host syncs.
"""
from __future__ import annotations

import enum

# in-trace sentinel: the loop is still running / no terminal status has
# been assigned yet. Never escapes unpack_stats (a loop that exhausts
# max_iters is reported as MAX_ITERS).
RUNNING = -1


class SolveStatus(enum.IntEnum):
    """Terminal status of one solve, ordered by severity."""

    CONVERGED = 0      # residual met the convergence criterion
    MAX_ITERS = 1      # honest iteration-budget exit, residual finite
    STALLED = 2        # residual stopped improving over the stall window
    DIVERGED = 3       # residual grew past rel_div_tolerance * norm0
    BREAKDOWN = 4      # Krylov recurrence degenerated (p.Ap <= 0, rho/
    #                    omega underflow, Givens degeneracy, ...)
    NAN_DETECTED = 5   # non-finite residual norm reached the monitor
    DEADLINE_EXCEEDED = 6  # serving-layer deadline expired before the
    #                    solve reached a terminal status (the request
    #                    completes with its current iterate or a
    #                    rejection, never a hung bucket; serving/)
    OVERLOADED = 7     # serving-layer load shed: admission control
    #                    judged the request unserviceable (queue bound,
    #                    tenant quota, or a deadline the live latency
    #                    estimate says is unmeetable) and completed it
    #                    immediately with the initial iterate — the
    #                    honest early rejection, distinct from a
    #                    DEADLINE_EXCEEDED surprise after queueing


# AMGX_SOLVE_STATUS codes (include/amgx_c.h) for the C-API surface.
AMGX_SOLVE_SUCCESS = 0
AMGX_SOLVE_FAILED = 1
AMGX_SOLVE_DIVERGED = 2
AMGX_SOLVE_NOT_CONVERGED = 3

_TO_AMGX = {
    SolveStatus.CONVERGED: AMGX_SOLVE_SUCCESS,
    SolveStatus.MAX_ITERS: AMGX_SOLVE_NOT_CONVERGED,
    SolveStatus.STALLED: AMGX_SOLVE_NOT_CONVERGED,
    SolveStatus.DIVERGED: AMGX_SOLVE_DIVERGED,
    SolveStatus.BREAKDOWN: AMGX_SOLVE_FAILED,
    SolveStatus.NAN_DETECTED: AMGX_SOLVE_FAILED,
    SolveStatus.DEADLINE_EXCEEDED: AMGX_SOLVE_NOT_CONVERGED,
    SolveStatus.OVERLOADED: AMGX_SOLVE_NOT_CONVERGED,
}

_STRINGS = {
    SolveStatus.CONVERGED: "success",
    SolveStatus.MAX_ITERS: "max_iters",
    SolveStatus.STALLED: "stalled",
    SolveStatus.DIVERGED: "diverged",
    SolveStatus.BREAKDOWN: "breakdown",
    SolveStatus.NAN_DETECTED: "nan_detected",
    SolveStatus.DEADLINE_EXCEEDED: "deadline_exceeded",
    SolveStatus.OVERLOADED: "overloaded",
}


def coerce(code) -> SolveStatus:
    """Clamp an int-ish code (packed stats travel as floats) to a
    SolveStatus; unknown/sentinel values degrade to MAX_ITERS rather
    than raising inside result plumbing."""
    try:
        return SolveStatus(int(code))
    except ValueError:
        return SolveStatus.MAX_ITERS


def to_amgx_status(code) -> int:
    """SolveStatus -> AMGX_SOLVE_* (the C API's coarser vocabulary)."""
    return _TO_AMGX[coerce(code)]


def status_string(code) -> str:
    return _STRINGS[coerce(code)]
