"""Host worker threads for asynchronous setup (ThreadManager analog).

The reference's ThreadManager (src/thread_manager.cu) runs smoother
setup as `AsyncSolverSetupTask`s on worker threads so independent level
setups overlap (include/amg_level.h:25-39). The TPU-native analog uses a
shared thread pool: JAX dispatch is thread-safe and asynchronous, so a
background thread can drive the host-orchestration of one solver's
setup (eager dispatches, host syncs) while the caller keeps working —
the device work itself is serialized by the XLA runtime either way, but
the tunnel/host round trips overlap.
"""
from __future__ import annotations

import threading
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Optional

_lock = threading.Lock()
_pool: Optional[ThreadPoolExecutor] = None


def _get_pool() -> ThreadPoolExecutor:
    global _pool
    with _lock:
        if _pool is None:
            _pool = ThreadPoolExecutor(
                max_workers=4, thread_name_prefix="amgx-setup")
        return _pool


class AsyncSetupTask:
    """Handle to an in-flight setup (AsyncSolverSetupTask analog):
    `wait()` joins and re-raises any setup exception."""

    def __init__(self, future: Future, solver):
        self._future = future
        self.solver = solver

    def done(self) -> bool:
        return self._future.done()

    def wait(self):
        self._future.result()
        return self.solver


def setup_async(solver, A) -> AsyncSetupTask:
    """Run `solver.setup(A)` on a worker thread; returns a task handle.
    The solver must not be used until wait() returns."""
    return AsyncSetupTask(_get_pool().submit(solver.setup, A), solver)


def shutdown():
    global _pool
    with _lock:
        if _pool is not None:
            _pool.shutdown(wait=True)
            _pool = None
