"""Run-to-run determinism checker.

Analog of src/determinism_checker.cu(:121): record bit-exact
fingerprints of intermediate vectors at named checkpoints during one
run, then verify that a repeat run reproduces every fingerprint. The
framework's algorithms are deterministic by construction (no atomics,
smallest-index tie-breaking, fixed reduction orders), and this harness
is the tool that *proves* it for any given configuration.

Usage:
    chk = DeterminismChecker()
    chk.observe("residual", r)          # during run 1
    chk.start_verification()
    chk.observe("residual", r)          # during run 2 -> raises on drift
"""
from __future__ import annotations

import hashlib
from typing import Dict, List, Tuple

import numpy as np


class DeterminismError(AssertionError):
    pass


def fingerprint(x) -> str:
    """Bit-exact digest of an array (device arrays are pulled once)."""
    a = np.ascontiguousarray(np.asarray(x))
    h = hashlib.sha256()
    h.update(str(a.dtype).encode())
    h.update(str(a.shape).encode())
    h.update(a.tobytes())
    return h.hexdigest()[:16]


class DeterminismChecker:
    """Record-then-verify fingerprint trace (determinism_checker.cu)."""

    def __init__(self):
        self._trace: Dict[str, List[str]] = {}
        self._pos: Dict[str, int] = {}
        self._verifying = False

    def observe(self, tag: str, x):
        fp = fingerprint(x)
        if not self._verifying:
            self._trace.setdefault(tag, []).append(fp)
            return
        seq = self._trace.get(tag)
        i = self._pos.get(tag, 0)
        if seq is None or i >= len(seq):
            raise DeterminismError(
                f"determinism: unexpected extra observation for {tag!r} "
                f"(call #{i})")
        if seq[i] != fp:
            raise DeterminismError(
                f"determinism: {tag!r} call #{i} fingerprint {fp} != "
                f"recorded {seq[i]}")
        self._pos[tag] = i + 1

    def start_verification(self):
        self._verifying = True
        self._pos = {}

    def finish(self):
        """Assert the verification run covered every recorded call."""
        for tag, seq in self._trace.items():
            if self._pos.get(tag, 0) != len(seq):
                raise DeterminismError(
                    f"determinism: {tag!r} observed "
                    f"{self._pos.get(tag, 0)}/{len(seq)} calls")

    def summary(self) -> List[Tuple[str, int]]:
        return [(t, len(s)) for t, s in sorted(self._trace.items())]
