"""Coarse-operator generation for aggregation AMG.

Analog of src/aggregation/coarseAgenerators/ (low_deg 1427 LoC, thrust,
hybrid). With piecewise-constant P (aggregates map), the Galerkin triple
product R A P collapses to relabeling A's COO entries by aggregate id and
coalescing duplicates — a sort + segmented-sum, the TPU-native analog of
the reference's hash-table kernels. Runs eagerly at setup with concrete
shapes.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...matrix import CsrMatrix


def coarse_a_from_aggregates(A: CsrMatrix, agg, nc: int) -> CsrMatrix:
    """A_c[I,J] = sum_{agg[i]==I, agg[j]==J} A[i,j]."""
    rows, cols, vals = A.coo()
    cr = agg[rows].astype(jnp.int64)
    cc = agg[cols].astype(jnp.int64)
    key = cr * nc + cc
    order = jnp.argsort(key, stable=True)
    key_s = key[order]
    vals_s = vals[order]
    newseg = jnp.concatenate([jnp.ones((1,), bool), key_s[1:] != key_s[:-1]])
    seg = jnp.cumsum(newseg) - 1
    nuniq = int(seg[-1]) + 1
    first = jnp.nonzero(newseg, size=nuniq)[0]
    v = jax.ops.segment_sum(vals_s, seg, num_segments=nuniq,
                            indices_are_sorted=True)
    kk = key_s[first]
    out_rows = (kk // nc).astype(jnp.int32)
    out_cols = (kk % nc).astype(jnp.int32)
    counts = jnp.bincount(out_rows, length=nc)
    row_offsets = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(counts).astype(jnp.int32)])
    Ac = CsrMatrix.from_scipy_like(row_offsets, out_cols, v, nc, nc,
                                   (A.block_dimx, A.block_dimy))
    if A.has_external_diag:
        # fold external diagonal contributions into the coarse entries:
        # diag blocks land on (agg[i], agg[i])
        dr = agg.astype(jnp.int32)
        Dc = CsrMatrix.from_coo(dr, dr, A.diag, nc, nc,
                                block_dims=(A.block_dimx, A.block_dimy))
        from ...ops.spgemm import csr_add
        Ac = csr_add(Ac, Dc)
    return Ac


def restrict_vector(agg, nc: int, r, block_dim: int = 1):
    """b_c = R r with piecewise-constant restriction = segment-sum over
    aggregates (restrictResidualKernel analog,
    src/aggregation/aggregation_amg_level.cu:93)."""
    if block_dim > 1:
        rb = r.reshape(-1, block_dim)
        out = jax.ops.segment_sum(rb, agg, num_segments=nc)
        return out.reshape(-1)
    return jax.ops.segment_sum(r, agg, num_segments=nc)


def prolongate_corr(agg, xc, block_dim: int = 1):
    """x += P x_c = gather by aggregate id (prolongateAndApplyCorrection
    kernel analog, aggregation_amg_level.cu:158)."""
    if block_dim > 1:
        return xc.reshape(-1, block_dim)[agg].reshape(-1)
    return xc[agg]
