"""Coarse-operator generation for aggregation AMG.

Analog of src/aggregation/coarseAgenerators/ (low_deg 1427 LoC, thrust,
hybrid). With piecewise-constant P (aggregates map), the Galerkin triple
product R A P collapses to relabeling A's COO entries by aggregate id and
coalescing duplicates — a sort + segmented-sum, the TPU-native analog of
the reference's hash-table kernels.

The whole product is ONE compiled program with static shapes: instead of
compacting duplicates (data-dependent size), the coarse CSR keeps every
relabeled entry, with the coalesced sum stored on the first occurrence of
each (I, J) pair and zeros on the rest. Zero-valued duplicate entries are
inert in every consumer (SpMV adds 0; diag extraction is
first-occurrence; edge weights ignore w == 0).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ...matrix import CsrMatrix, lexsort_rc


@jax.jit
def _coarse_entries(A, agg):
    """Relabel + sort + coalesce: returns sorted COO with the summed
    value on each (I, J) pair's first occurrence (zeros on duplicates)
    and the traced unique-entry count."""
    rows, cols, vals = A.coo()
    r2 = agg[rows].astype(jnp.int32)
    c2 = agg[cols].astype(jnp.int32)
    if A.has_external_diag:
        # fold external diagonal contributions in: they land on
        # (agg[i], agg[i])
        da = agg.astype(jnp.int32)
        r2 = jnp.concatenate([r2, da])
        c2 = jnp.concatenate([c2, da])
        vals = jnp.concatenate([vals, A.diag])
    e = r2.shape[0]
    order = lexsort_rc(r2, c2)
    r_s = r2[order]
    c_s = c2[order]
    v_s = vals[order]
    first = jnp.concatenate(
        [jnp.ones((1,), bool),
         (r_s[1:] != r_s[:-1]) | (c_s[1:] != c_s[:-1])])
    seg = jnp.cumsum(first) - 1
    vsum = jax.ops.segment_sum(v_s, seg, num_segments=e,
                               indices_are_sorted=True)
    fexp = first if v_s.ndim == 1 else first[:, None, None]
    v_out = jnp.where(fexp, vsum[seg], 0.0)
    return r_s, c_s, v_out, first, seg[-1] + 1


@functools.partial(jax.jit, static_argnames=("bdims", "nc", "u"))
def _compact_coarse(r_s, c_s, v_out, first, bdims, nc: int, u: int):
    """Gather the u unique entries into an exact-size CSR (restores the
    geometric nnz decay of the hierarchy: each coarse level stores and
    sweeps only its real entries)."""
    e = r_s.shape[0]
    idx = jnp.nonzero(first, size=u, fill_value=e - 1)[0]
    r = r_s[idx]
    c = c_s[idx]
    v = v_out[idx]
    counts = jnp.bincount(r, length=nc)
    row_offsets = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32),
         jnp.cumsum(counts).astype(jnp.int32)])
    is_diag = c == r
    cand = jnp.where(is_diag, jnp.arange(u, dtype=jnp.int32), u)
    dmin = jax.ops.segment_min(cand, r, num_segments=nc,
                               indices_are_sorted=True)
    diag_idx = jnp.where(dmin >= u, -1, dmin).astype(jnp.int32)
    bx, by = bdims
    return CsrMatrix(
        row_offsets=row_offsets, col_indices=c, values=v,
        diag=None, row_ids=r, diag_idx=diag_idx,
        ell_cols=None, ell_vals=None, dia_offsets=None, dia_vals=None,
        num_rows=nc, num_cols=nc, block_dimx=bx, block_dimy=by,
        initialized=True)


def coarse_a_from_aggregates(A: CsrMatrix, agg, nc: int) -> CsrMatrix:
    """A_c[I,J] = sum_{agg[i]==I, agg[j]==J} A[i,j] — two jitted
    sort/segmented-sum programs with static shapes. The per-level host
    materializations are exactly two scalars: `nc` (from the selector)
    and the unique-entry count `u`."""
    r_s, c_s, v_out, first, u = _coarse_entries(A, agg)
    return _compact_coarse(r_s, c_s, v_out, first,
                           (A.block_dimx, A.block_dimy), int(nc), int(u))


def restrict_vector(agg, nc: int, r, block_dim: int = 1):
    """b_c = R r with piecewise-constant restriction = segment-sum over
    aggregates (restrictResidualKernel analog,
    src/aggregation/aggregation_amg_level.cu:93)."""
    if block_dim > 1:
        rb = r.reshape(-1, block_dim)
        out = jax.ops.segment_sum(rb, agg, num_segments=nc)
        return out.reshape(-1)
    return jax.ops.segment_sum(r, agg, num_segments=nc)


def prolongate_corr(agg, xc, block_dim: int = 1):
    """x += P x_c = gather by aggregate id (prolongateAndApplyCorrection
    kernel analog, aggregation_amg_level.cu:158)."""
    if block_dim > 1:
        return xc.reshape(-1, block_dim)[agg].reshape(-1)
    return xc[agg]
