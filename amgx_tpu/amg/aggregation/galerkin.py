"""Coarse-operator generation for aggregation AMG.

Analog of src/aggregation/coarseAgenerators/ (low_deg 1427 LoC, thrust,
hybrid). With piecewise-constant P (aggregates map), the Galerkin triple
product R A P collapses to relabeling A's COO entries by aggregate id and
coalescing duplicates — a sort + segmented-sum, the TPU-native analog of
the reference's hash-table kernels.

The whole product is ONE compiled program with static shapes: instead of
compacting duplicates (data-dependent size), the coarse CSR keeps every
relabeled entry, with the coalesced sum stored on the first occurrence of
each (I, J) pair and zeros on the rest. Zero-valued duplicate entries are
inert in every consumer (SpMV adds 0; diag extraction is
first-occurrence; edge weights ignore w == 0).
"""
from __future__ import annotations

import contextlib
import functools
import threading

import jax
import jax.numpy as jnp
import numpy as np

from ...matrix import CsrMatrix, lexsort_rc


@jax.jit
def _coarse_entries(A, agg):
    """Relabel + sort + coalesce: returns sorted COO with the summed
    value on each (I, J) pair's first occurrence (zeros on duplicates)
    and the traced unique-entry count."""
    rows, cols, vals = A.coo()
    r2 = agg[rows].astype(jnp.int32)
    c2 = agg[cols].astype(jnp.int32)
    if A.has_external_diag:
        # fold external diagonal contributions in: they land on
        # (agg[i], agg[i])
        da = agg.astype(jnp.int32)
        r2 = jnp.concatenate([r2, da])
        c2 = jnp.concatenate([c2, da])
        vals = jnp.concatenate([vals, A.diag])
    e = r2.shape[0]
    order = lexsort_rc(r2, c2)
    r_s = r2[order]
    c_s = c2[order]
    v_s = vals[order]
    first = jnp.concatenate(
        [jnp.ones((1,), bool),
         (r_s[1:] != r_s[:-1]) | (c_s[1:] != c_s[:-1])])
    seg = jnp.cumsum(first) - 1
    vsum = jax.ops.segment_sum(v_s, seg, num_segments=e,
                               indices_are_sorted=True)
    fexp = first if v_s.ndim == 1 else first[:, None, None]
    v_out = jnp.where(fexp, vsum[seg], 0.0)
    return r_s, c_s, v_out, first, seg[-1] + 1


@functools.partial(jax.jit, static_argnames=("bdims", "nc", "u"))
def _compact_coarse(r_s, c_s, v_out, first, bdims, nc: int, u: int):
    """Gather the u unique entries into an exact-size CSR (restores the
    geometric nnz decay of the hierarchy: each coarse level stores and
    sweeps only its real entries)."""
    e = r_s.shape[0]
    idx = jnp.nonzero(first, size=u, fill_value=e - 1)[0]
    r = r_s[idx]
    c = c_s[idx]
    v = v_out[idx]
    counts = jnp.bincount(r, length=nc)
    row_offsets = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32),
         jnp.cumsum(counts).astype(jnp.int32)])
    is_diag = c == r
    cand = jnp.where(is_diag, jnp.arange(u, dtype=jnp.int32), u)
    dmin = jax.ops.segment_min(cand, r, num_segments=nc,
                               indices_are_sorted=True)
    diag_idx = jnp.where(dmin >= u, -1, dmin).astype(jnp.int32)
    bx, by = bdims
    return CsrMatrix(
        row_offsets=row_offsets, col_indices=c, values=v,
        diag=None, row_ids=r, diag_idx=diag_idx,
        ell_cols=None, ell_vals=None, dia_offsets=None, dia_vals=None,
        num_rows=nc, num_cols=nc, block_dimx=bx, block_dimy=by,
        initialized=True)


def coarse_a_from_aggregates(A: CsrMatrix, agg, nc: int) -> CsrMatrix:
    """A_c[I,J] = sum_{agg[i]==I, agg[j]==J} A[i,j] — two jitted
    sort/segmented-sum programs with static shapes. The per-level host
    materializations are exactly two scalars: `nc` (from the selector)
    and the unique-entry count `u`."""
    r_s, c_s, v_out, first, u = _coarse_entries(A, agg)
    return _compact_coarse(r_s, c_s, v_out, first,
                           (A.block_dimx, A.block_dimy), int(nc), int(u))


# ---------------------------------------------------------------------------
# structured (GEO) Galerkin fast path
# ---------------------------------------------------------------------------

def _decompose(d: int, nx: int, ny: int, nz: int):
    """Split a linear DIA offset into (dx, dy, dz) grid shifts; returns
    None when the offset is not a small stencil shift."""
    for dz in (0, -1, 1, -2, 2):
        if abs(dz) > min(2, nz - 1):
            continue
        for dy in (0, -1, 1, -2, 2):
            if abs(dy) > min(2, ny - 1):
                continue
            dx = d - dz * nx * ny - dy * nx
            if abs(dx) <= min(3, nx - 1):
                return dx, dy, dz
    return None


def pair_sum_axis(v3, e, axis):
    """Pair-sum a (nz, ny, nx) array along ONE grid axis of extent `e`
    (odd extents keep a singleton tail) — the single source of truth for
    the structured aggregation map agg(x,y,z) = (x//2, y//2, z//2),
    shared by the GEO transfer operators and the structured Galerkin.

    Implemented as two strided slices + add: a `(..., e//2, 2)` reshape
    would put the pair in the minor dimension, which TPU tiling pads
    128x (a 4 GB temp at 256^3)."""
    dims = 2 - axis

    def sl(start, stop):
        s = [slice(None)] * 3
        s[dims] = slice(start, stop, 2)
        return v3[tuple(s)]

    out = sl(0, e - 1) + sl(1, e)
    if e % 2:
        s = [slice(None)] * 3
        s[dims] = slice(e - 1, e)
        out = jnp.concatenate([out, v3[tuple(s)]], axis=dims)
    return out


def geo_shapes(fine_shape, axes):
    """Intermediate grid shapes of the per-axis pairing sequence."""
    shapes = [tuple(fine_shape)]
    for a in axes:
        s = list(shapes[-1])
        s[a] = (s[a] + 1) // 2
        shapes.append(tuple(s))
    return shapes


def _pair_sum3(v3, axes, shapes):
    out = v3
    for k, a in enumerate(axes):
        out = pair_sum_axis(out, shapes[k][a], a)
    return out


class _DeferredChecks(threading.local):
    """Per-thread accumulator for the wrap checks of a whole hierarchy
    build: each level appends its device flag; the owner fetches them
    in ONE device round trip at the end (a per-level bool() costs a
    full ~170 ms tunnel round trip on the bench rig). `disable_fast`
    turns the DIA fast path off during the rare rebuild after a failed
    deferred check."""

    def __init__(self):
        self.items = None
        self.disable_fast = False


_deferred = _DeferredChecks()


@contextlib.contextmanager
def deferred_wrap_checks():
    """Collect wrap-check flags instead of blocking per level. Yields a
    `flush()` callable returning True when ANY collected check failed
    (single device fetch)."""
    prev = _deferred.items
    _deferred.items = []

    def flush() -> bool:
        flags = _deferred.items
        _deferred.items = []
        if not flags:
            return False
        return bool(jnp.any(jnp.stack(flags)))

    try:
        yield flush
    finally:
        _deferred.items = prev


@contextlib.contextmanager
def geo_dia_disabled():
    """Force the generic relabel Galerkin (rebuild path after a failed
    deferred wrap check)."""
    prev = _deferred.disable_fast
    _deferred.disable_fast = True
    try:
        yield
    finally:
        _deferred.disable_fast = prev


@functools.partial(jax.jit, static_argnames=("shifts", "shape"))
def _any_wrapped(vals, shifts, shape):
    """True when any nonzero lies where its geometric shift exits the
    grid (the classification would be wrong). `shifts`/`shape` are
    hashable statics so this caches across setups and levels."""
    nx, ny, nz = shape
    n = nx * ny * nz
    sh = jnp.asarray(shifts, jnp.int32)
    ix = jnp.arange(n, dtype=jnp.int32)
    gx = ix % nx
    gy = (ix // nx) % ny
    gz = ix // (nx * ny)
    dx = sh[:, 0][:, None]
    dy = sh[:, 1][:, None]
    dz = sh[:, 2][:, None]
    ok = ((gx + dx >= 0) & (gx + dx < nx) & (gy + dy >= 0)
          & (gy + dy < ny) & (gz + dz >= 0) & (gz + dz < nz))
    return jnp.any(jnp.where(ok, 0.0, vals) != 0)


@functools.lru_cache(maxsize=256)
def _geo_contrib_table(dia_offsets, shifts, axes, coarse_shape):
    """Static contribution table: which fine diagonals (with which
    parity masks) land on which coarse diagonals."""
    cnx, cny, cnz = coarse_shape
    paired = set(axes)

    def splits(delta, axis):
        if axis not in paired:
            return [(delta, None)]
        lo = delta // 2                      # x even: (x+d)//2 - x//2
        hi = (delta + 1) // 2                # x odd
        if lo == hi:
            return [(lo, None)]
        return [(lo, 0), (hi, 1)]            # (coarse shift, fine parity)

    table = {}
    for t in range(len(dia_offsets)):
        dx, dy, dz = shifts[t]
        for cdx, px in splits(dx, 0):
            for cdy, py in splits(dy, 1):
                for cdz, pz in splits(dz, 2):
                    cd = (cdz * cny + cdy) * cnx + cdx
                    table.setdefault((cd, cdx, cdy, cdz), []).append(
                        (t, px, py, pz))
    coffsets = tuple(sorted(table, key=lambda k: k[0]))
    contribs = tuple(tuple(table[k]) for k in coffsets)
    return coffsets, contribs


@functools.partial(jax.jit, static_argnames=("coffsets", "contribs",
                                             "fine_shape", "axes"))
def _geo_compute(vals, coffsets, contribs, fine_shape, axes):
    """The whole structured Galerkin numeric phase as one cached jitted
    program: parity-masked accumulation + reshape pair-sums."""
    nx, ny, nz = fine_shape
    shapes = geo_shapes(fine_shape, axes)
    v3 = vals.reshape(len(vals), nz, ny, nx)
    xpar = jnp.arange(nx, dtype=jnp.int32) % 2
    ypar = jnp.arange(ny, dtype=jnp.int32) % 2
    zpar = jnp.arange(nz, dtype=jnp.int32) % 2
    outs = []
    for entries in contribs:
        acc = jnp.zeros((nz, ny, nx), vals.dtype)
        for (t, px, py, pz) in entries:
            m = v3[t]
            if px is not None:
                m = m * (xpar == px)[None, None, :]
            if py is not None:
                m = m * (ypar == py)[None, :, None]
            if pz is not None:
                m = m * (zpar == pz)[:, None, None]
            acc = acc + m
        outs.append(_pair_sum3(acc, axes, shapes).reshape(-1))
    return jnp.stack(outs)               # (kc, nc)


@functools.lru_cache(maxsize=256)
def _geo_csr_structure(coffsets, coarse_shape):
    """CSR structure of the coarse stencil (host numpy, vectorized;
    cached so resetup rebuilds only the numeric phase)."""
    cnx, cny, cnz = coarse_shape
    nc = cnx * cny * cnz
    ci = np.arange(nc, dtype=np.int32)
    cx = ci % cnx
    cy = (ci // cnx) % cny
    cz = ci // (cnx * cny)
    valid = np.stack([
        (cx + cdx >= 0) & (cx + cdx < cnx) & (cy + cdy >= 0)
        & (cy + cdy < cny) & (cz + cdz >= 0) & (cz + cdz < cnz)
        for (_, cdx, cdy, cdz) in coffsets])          # (kc, nc)
    counts = valid.sum(axis=0).astype(np.int32)
    row_offsets = np.zeros(nc + 1, np.int32)
    np.cumsum(counts, out=row_offsets[1:])
    # entries ordered (row, offset-rank) = (row, ascending column)
    off_idx, rows = np.nonzero(valid)
    order = np.lexsort((off_idx, rows))
    off_e = off_idx[order].astype(np.int32)
    row_e = rows[order].astype(np.int32)
    col_e = row_e + np.asarray([k[0] for k in coffsets], np.int32)[off_e]
    # diagonal position within each row (-1 when offset 0 is not stored)
    zero_rank = next((i for i, k in enumerate(coffsets) if k[0] == 0),
                     None)
    diag_idx = np.full(nc, -1, np.int32)
    if zero_rank is not None:
        is_diag = off_e == zero_rank
        diag_idx[row_e[is_diag]] = np.nonzero(is_diag)[0].astype(np.int32)
    return row_offsets, off_e, row_e, col_e, diag_idx


def geo_coarse_values(A: CsrMatrix, fine_shape, axes, coarse_shape):
    """Numeric phase of the structured (GEO) Galerkin product: the
    coarse diagonal slab (kc, nc) computed WITHOUT sorts or scatters.

    For a fine entry A[i, i+d] with grid shift (dx, dy, dz), the coarse
    offset along each paired axis is floor((x+dx)/2) - floor(x/2) — a
    parity-dependent split into at most two coarse shifts per axis. Each
    fine diagonal therefore scatters into a statically-known set of
    coarse diagonals with parity masks, and the aggregate summation is
    the same reshape pair-sum as the restriction operator. One jitted
    program; numerically identical to the generic COO relabel+sum (both
    compute sum over fine pairs), so iteration counts are unchanged.

    Returns (cvals, coffsets) or None when the fast path does not apply
    (non-stencil offsets, or entries that wrap grid rows).
    """
    nx, ny, nz = fine_shape
    cnx, cny, cnz = coarse_shape
    if A.dia_offsets is None or A.grid_shape != tuple(fine_shape) \
            or A.is_block:
        return None
    decomp = {}
    for d in A.dia_offsets:
        g = _decompose(int(d), nx, ny, nz)
        if g is None:
            return None
        decomp[int(d)] = g

    if _deferred.disable_fast:
        return None
    n = A.num_rows
    vals = A.dia_vals.reshape(len(A.dia_offsets), -1)[:, :n]
    # wrap check: a geometric shift must keep every nonzero inside the
    # grid — entries crossing a grid row boundary would be
    # misclassified. Inside a hierarchy build the flag is DEFERRED
    # (batched single fetch, deferred_wrap_checks); standalone calls
    # block here as before.
    shifts = tuple(decomp[int(d)] for d in A.dia_offsets)
    wrapped = _any_wrapped(vals, shifts, tuple(fine_shape))
    if _deferred.items is not None:
        _deferred.items.append(wrapped)
    elif bool(wrapped):
        return None

    coffsets, contribs = _geo_contrib_table(
        tuple(int(d) for d in A.dia_offsets), shifts, tuple(axes),
        (cnx, cny, cnz))
    cvals = _geo_compute(vals, coffsets, contribs, tuple(fine_shape),
                         tuple(axes))
    return cvals, coffsets


# Device-resident twin of _geo_csr_structure, keyed additionally by the
# ambient device. The structure arrays are pure functions of the offset
# pattern — identical across every warm setup, resetup, and bench
# iteration of the same hierarchy — yet each jnp.asarray used to
# re-cross the host->device wire: at 256^3 the per-setup re-upload of
# the O(nnz) off_e/row_e/col_e/row_ids arrays is ~1 GB of tunnel
# traffic, the dominant share of the PR-3-era warm-setup regression
# (BENCH_r05 northstar_256^3_setup_warm_s 17.37 s vs 5.87 s). Bounded
# explicit cache (the arrays are live in the hierarchy anyway, so a
# cache hit adds no HBM beyond one generation).
_GEO_STRUCT_DEV = {}          # insertion-ordered: oldest evicts first
_GEO_STRUCT_DEV_MAX_BYTES = 2 << 30


def _geo_csr_structure_device(coffsets, coarse_shape):
    import jax as _jax
    from ...telemetry import metrics as _tm
    dev = _jax.config.jax_default_device or _jax.devices()[0]
    key = (coffsets, coarse_shape, dev)
    hit = _GEO_STRUCT_DEV.get(key)
    if hit is not None:
        _GEO_STRUCT_DEV[key] = _GEO_STRUCT_DEV.pop(key)   # LRU bump
        _tm.inc("amg.geo_struct_cache.hit")
        return hit
    _tm.inc("amg.geo_struct_cache.miss")
    out = tuple(jnp.asarray(a) for a in _geo_csr_structure(
        coffsets, coarse_shape))
    _GEO_STRUCT_DEV[key] = out
    # bound by BYTES, not entry count: one 256^3-grade entry is
    # hundreds of MB, so a count bound could pin many GB of HBM for
    # hierarchies no longer alive. Entries still referenced by a live
    # hierarchy survive eviction as arrays (only the cache slot goes).
    total = 0
    for k in reversed(list(_GEO_STRUCT_DEV)):
        total += sum(int(a.nbytes) for a in _GEO_STRUCT_DEV[k])
        if total > _GEO_STRUCT_DEV_MAX_BYTES and k != key:
            del _GEO_STRUCT_DEV[k]
    return out


def geo_assemble_dia(cvals, coffsets, coarse_shape) -> CsrMatrix:
    """Layout phase of the structured Galerkin: pack the coarse slab
    into the exact-size CSR + tile-aligned DIA storage (the coarse
    operator's solve layout, built straight from device arrays — this
    is the packing the amg.L*.layout timer wraps). The CSR structure
    arrays come from the device-resident cache above: only the NUMERIC
    slab is new work per setup."""
    cnx, cny, cnz = coarse_shape
    nc = cnx * cny * cnz
    (row_offsets, off_e, row_e, col_e, diag_idx) = \
        _geo_csr_structure_device(coffsets, (cnx, cny, cnz))
    values = cvals[off_e, row_e]
    from ...ops.pallas_spmv import LANES, dia_padded_rows
    kc = len(coffsets)
    rows_pad = dia_padded_rows(kc, nc)
    dia_vals = jnp.zeros((kc, rows_pad * LANES), cvals.dtype
                         ).at[:, :nc].set(cvals).reshape(kc, rows_pad,
                                                         LANES)
    return CsrMatrix(
        row_offsets=row_offsets,
        col_indices=col_e, values=values, diag=None,
        row_ids=row_e, diag_idx=diag_idx,
        ell_cols=None, ell_vals=None,
        dia_offsets=tuple(int(k[0]) for k in coffsets),
        dia_vals=dia_vals, num_rows=nc, num_cols=nc,
        block_dimx=1, block_dimy=1, initialized=True,
        grid_shape=tuple(coarse_shape))




# ---------------------------------------------------------------------------
# planned GEO route (the structured fast path's RapPlan analog)
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("coffsets", "contribs",
                                             "fine_shape", "axes",
                                             "nc"))
def _geo_value_phase(vals, off_e, row_e, coffsets, contribs,
                     fine_shape, axes, nc: int):
    """The WHOLE structured-Galerkin numeric phase as one jitted
    program: parity-masked accumulation + pair-sums (_geo_compute's
    math), the CSR entry gather, and the tile-aligned DIA pack —
    `geo_assemble_dia` feeds straight from this output. Shared by the
    planned setup route AND the value-resetup plan (value_resetup.py),
    so the first resetup hits the setup's own compile cache."""
    from ...ops.pallas_spmv import LANES, dia_padded_rows
    cvals = _geo_compute(vals, coffsets, contribs, fine_shape, axes)
    values_c = cvals[off_e, row_e]
    kc = len(coffsets)
    rows_pad = dia_padded_rows(kc, nc)
    dia_c = jnp.zeros((kc, rows_pad * LANES), cvals.dtype
                      ).at[:, :nc].set(cvals).reshape(kc, rows_pad,
                                                      LANES)
    return values_c, dia_c


class GeoRapPlan:
    """Static recipe of one structured (GEO) Galerkin product: the
    offset decomposition, contribution table and coarse CSR/DIA
    structure, memoized once per (offsets, shapes, axes) pattern so a
    warm setup or value resetup re-derives NOTHING — the numeric phase
    is the one jitted `_geo_value_phase` program feeding the assembled
    coarse operator next to the existing device-structure cache
    (`_geo_csr_structure_device`). The plan object itself is
    device-free; the structure arrays resolve through the bounded
    device cache at use, so device changes can never serve stale
    uploads."""

    def __init__(self, dia_offsets, shifts, fine_shape, axes,
                 coarse_shape):
        self.dia_offsets = dia_offsets
        self.shifts = shifts
        self.fine_shape = fine_shape
        self.axes = axes
        self.coarse_shape = coarse_shape
        self.coffsets, self.contribs = _geo_contrib_table(
            dia_offsets, shifts, axes, coarse_shape)
        self.kc = len(self.coffsets)
        self.nc = int(np.prod(coarse_shape))

    def structure(self):
        """(row_offsets, off_e, row_e, col_e, diag_idx) device arrays
        through the bounded GEO structure cache."""
        return _geo_csr_structure_device(self.coffsets,
                                         self.coarse_shape)

    def values(self, vals2d):
        """(values_c, dia_c) from the current fine DIA slab — one
        jitted dispatch, zero symbolic work."""
        (_ro, off_e, row_e, _col_e, _diag) = self.structure()
        return _geo_value_phase(vals2d, off_e, row_e, self.coffsets,
                                self.contribs, self.fine_shape,
                                self.axes, self.nc)

    def assemble(self, values_c, dia_c) -> CsrMatrix:
        (row_offsets, _off_e, row_e, col_e, diag_idx) = self.structure()
        return CsrMatrix(
            row_offsets=row_offsets, col_indices=col_e,
            values=values_c, diag=None, row_ids=row_e,
            diag_idx=diag_idx, ell_cols=None, ell_vals=None,
            dia_offsets=tuple(int(k[0]) for k in self.coffsets),
            dia_vals=dia_c, num_rows=self.nc, num_cols=self.nc,
            block_dimx=1, block_dimy=1, initialized=True,
            grid_shape=tuple(self.coarse_shape))

    def coarse_coeffs(self, coeffs):
        """Coarse constant-stencil coefficients (kc,) straight from the
        fine ones (k,) — the matrix-free twin of `values`: when the fine
        level is a constant-coefficient stencil (ops/stencil.py), every
        in-grid coarse entry is the same static contraction of the fine
        coefficients, so the whole Galerkin numeric phase collapses to a
        (kc, k) matmul on O(k) numbers. Per contribution the weight is
        the number of fine cells in a coarse aggregate that carry it: 2
        for each paired axis whose parity mask is None (both parities
        contribute), 1 otherwise. None when a paired axis has an odd
        fine extent — the last aggregate is then a singleton along that
        axis and the coarse operator is no longer constant."""
        for a in self.axes:
            if self.fine_shape[a] % 2:
                return None
        M = getattr(self, "_coeff_mat", None)
        if M is None:
            M = np.zeros((self.kc, len(self.dia_offsets)))
            for ci, entries in enumerate(self.contribs):
                for (t, px, py, pz) in entries:
                    w = 1
                    for a, p in zip((0, 1, 2), (px, py, pz)):
                        if a in self.axes and p is None:
                            w *= 2
                    M[ci, t] += w
            self._coeff_mat = M
        return jnp.asarray(M, coeffs.dtype) @ coeffs

    def coarse_matrix(self, A: CsrMatrix):
        """Planned numeric phase with the same wrap-check discipline
        as `geo_coarse_values`: deferred inside a hierarchy build
        (batched single fetch), blocking standalone. None when the
        values violate the geometric invariant (standalone mode) —
        the caller falls back to the relabel Galerkin."""
        n = A.num_rows
        vals = A.dia_vals.reshape(len(A.dia_offsets), -1)[:, :n]
        wrapped = _any_wrapped(vals, self.shifts, self.fine_shape)
        if _deferred.items is not None:
            _deferred.items.append(wrapped)
        elif bool(wrapped):
            return None
        values_c, dia_c = self.values(vals)
        return self.assemble(values_c, dia_c)


_GEO_PLAN_CACHE = {}
_GEO_PLAN_CACHE_MAX = 256


def get_geo_plan(A: CsrMatrix, fine_shape, axes, coarse_shape):
    """Memoized GeoRapPlan for A's offset pattern, or None when the
    structured fast path does not apply (non-stencil offsets, blocks,
    a disabled fast path after a failed wrap check). Eligibility
    mirrors `geo_coarse_values`; the wrap check — which depends on the
    VALUES — stays in `GeoRapPlan.coarse_matrix`."""
    from ...telemetry import metrics as _tm
    nx, ny, nz = fine_shape
    if A.dia_offsets is None or A.grid_shape != tuple(fine_shape) \
            or A.is_block or _deferred.disable_fast:
        return None
    shifts = []
    for d in A.dia_offsets:
        g = _decompose(int(d), nx, ny, nz)
        if g is None:
            return None
        shifts.append(g)
    key = (tuple(int(d) for d in A.dia_offsets), tuple(fine_shape),
           tuple(axes), tuple(coarse_shape))
    plan = _GEO_PLAN_CACHE.get(key)
    if plan is not None:
        _tm.inc("amg.spgemm.plan_hit")
        return plan
    _tm.inc("amg.spgemm.plan_build")
    plan = GeoRapPlan(key[0], tuple(shifts), key[1], tuple(axes),
                      key[3])
    _GEO_PLAN_CACHE[key] = plan
    while len(_GEO_PLAN_CACHE) > _GEO_PLAN_CACHE_MAX:
        del _GEO_PLAN_CACHE[next(iter(_GEO_PLAN_CACHE))]
    return plan


def restrict_vector(agg, nc: int, r, block_dim: int = 1):
    """b_c = R r with piecewise-constant restriction = segment-sum over
    aggregates (restrictResidualKernel analog,
    src/aggregation/aggregation_amg_level.cu:93)."""
    if block_dim > 1:
        rb = r.reshape(-1, block_dim)
        out = jax.ops.segment_sum(rb, agg, num_segments=nc)
        return out.reshape(-1)
    return jax.ops.segment_sum(r, agg, num_segments=nc)


def prolongate_corr(agg, xc, block_dim: int = 1):
    """x += P x_c = gather by aggregate id (prolongateAndApplyCorrection
    kernel analog, aggregation_amg_level.cu:158)."""
    if block_dim > 1:
        return xc.reshape(-1, block_dim)[agg].reshape(-1)
    return xc[agg]
