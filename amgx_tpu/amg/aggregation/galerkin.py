"""Coarse-operator generation for aggregation AMG.

Analog of src/aggregation/coarseAgenerators/ (low_deg 1427 LoC, thrust,
hybrid). With piecewise-constant P (aggregates map), the Galerkin triple
product R A P collapses to relabeling A's COO entries by aggregate id and
coalescing duplicates — a sort + segmented-sum, the TPU-native analog of
the reference's hash-table kernels. Runs eagerly at setup with concrete
shapes.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...matrix import CsrMatrix


def coarse_a_from_aggregates(A: CsrMatrix, agg, nc: int) -> CsrMatrix:
    """A_c[I,J] = sum_{agg[i]==I, agg[j]==J} A[i,j]: relabel the COO
    entries by aggregate id and let from_coo coalesce duplicates."""
    rows, cols, vals = A.coo()
    Ac = CsrMatrix.from_coo(agg[rows], agg[cols], vals, nc, nc,
                            block_dims=(A.block_dimx, A.block_dimy))
    if A.has_external_diag:
        # fold external diagonal contributions into the coarse entries:
        # diag blocks land on (agg[i], agg[i])
        dr = agg.astype(jnp.int32)
        Dc = CsrMatrix.from_coo(dr, dr, A.diag, nc, nc,
                                block_dims=(A.block_dimx, A.block_dimy))
        from ...ops.spgemm import csr_add
        Ac = csr_add(Ac, Dc)
    return Ac


def restrict_vector(agg, nc: int, r, block_dim: int = 1):
    """b_c = R r with piecewise-constant restriction = segment-sum over
    aggregates (restrictResidualKernel analog,
    src/aggregation/aggregation_amg_level.cu:93)."""
    if block_dim > 1:
        rb = r.reshape(-1, block_dim)
        out = jax.ops.segment_sum(rb, agg, num_segments=nc)
        return out.reshape(-1)
    return jax.ops.segment_sum(r, agg, num_segments=nc)


def prolongate_corr(agg, xc, block_dim: int = 1):
    """x += P x_c = gather by aggregate id (prolongateAndApplyCorrection
    kernel analog, aggregation_amg_level.cu:158)."""
    if block_dim > 1:
        return xc.reshape(-1, block_dim)[agg].reshape(-1)
    return xc[agg]
