"""Aggregation selectors: parallel-matching aggregation.

Analogs of src/aggregation/selectors/ (size2_selector.cu 920 LoC,
size4/size8, dummy). The reference's handshaking matching is re-expressed
as fixed-point iterations of segmented gather/argmax ops (TPU-friendly:
no atomics, deterministic by construction via smallest-index
tie-breaking):

  repeat:
    every unaggregated vertex proposes its strongest unaggregated
    neighbor (segment-max of edge weights + segment-min index tiebreak);
    mutual proposals (handshakes) become aggregates of two.

SIZE_4 / SIZE_8 run 2 / 3 matching passes, pairing *aggregates* in later
passes through the coarse graph (same machinery as the Galerkin product).
All of this is setup-time eager device code with concrete shapes.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ... import registry
from ...config import Config
from ...matrix import CsrMatrix


def _edge_weights(A: CsrMatrix, formula: int = 0):
    """Symmetrized edge weights (reference weight_formula 0:
    w_ij = 0.5(|a_ij|+|a_ji|)/max(|a_ii|,|a_jj|))."""
    rows, cols, vals = A.coo()
    if A.is_block:
        # reference uses one block component (aggregation_edge_weight_
        # component); the (0,0) entry
        v = vals[:, 0, 0]
        d = A.diagonal()[:, 0, 0]
    else:
        v = vals
        d = A.diagonal()
    absd = jnp.abs(d)
    n = A.num_rows
    # |a_ji| via scatter of |a_ij| into the transpose position: build a
    # dense-free lookup by sorting the transposed key
    key_t = cols.astype(jnp.int64) * n + rows.astype(jnp.int64)
    key = rows.astype(jnp.int64) * n + cols.astype(jnp.int64)
    order = jnp.argsort(key_t, stable=True)
    # sorted transpose keys == sorted forward keys where symmetric pattern;
    # look up |a_ji| by searching key in sorted key_t
    sorted_kt = key_t[order]
    pos = jnp.searchsorted(sorted_kt, key)
    pos = jnp.clip(pos, 0, rows.shape[0] - 1)
    match = sorted_kt[pos] == key
    v_t = jnp.where(match, jnp.abs(v[order][pos]), 0.0)
    if formula == 1:
        w = -0.5 * (v / jnp.where(d[rows] == 0, 1.0, d[rows])
                    + v_t / jnp.where(d[cols] == 0, 1.0, d[cols]))
    else:
        denom = jnp.maximum(absd[rows], absd[cols])
        w = 0.5 * (jnp.abs(v) + v_t) / jnp.where(denom == 0, 1.0, denom)
    w = jnp.where(rows == cols, 0.0, w)
    return rows, cols, w


def _edge_hash(rows, cols):
    """Symmetric per-edge pseudo-random value in [0, 1): hash of the
    unordered pair. Breaks weight ties so handshaking matches a constant
    fraction per round (Luby-style) instead of forming chains; being a
    pure hash it is deterministic across runs (determinism_flag for free)."""
    a = jnp.minimum(rows, cols).astype(jnp.uint32)
    b = jnp.maximum(rows, cols).astype(jnp.uint32)
    h = a * jnp.uint32(73856093) ^ b * jnp.uint32(19349663)
    h = (h ^ (h >> 13)) * jnp.uint32(0x5BD1E995)
    return (h & jnp.uint32(0xFFFFF)).astype(jnp.float64) / float(1 << 20)


def _matching_pass(rows, cols, w, n, max_iters: int,
                   deterministic: bool = True):
    """One size-2 matching: returns aggregate ids (pairs + singletons).
    Unmatched vertices keep their own id; ids are NOT yet renumbered."""
    agg = jnp.full((n,), -1, jnp.int32)          # -1 = unaggregated
    INF_NEG = jnp.asarray(-1.0, w.dtype)
    # tie-breaking perturbation, small relative to the weight scale
    scale = float(jnp.max(w)) if w.shape[0] else 1.0
    w = w * (1.0 + 1e-3 * _edge_hash(rows, cols).astype(w.dtype)) \
        if scale > 0 else w

    for _ in range(max_iters):
        un = agg < 0
        if not bool(jnp.any(un)):
            break
        # strongest unaggregated neighbor of each unaggregated vertex
        valid = un[rows] & un[cols] & (w > 0)
        we = jnp.where(valid, w, INF_NEG)
        wmax = jax.ops.segment_max(we, rows, num_segments=n,
                                   indices_are_sorted=True)
        has = wmax > 0
        is_best = valid & (we == wmax[rows])
        # smallest-index tiebreak -> determinism
        best = jax.ops.segment_min(jnp.where(is_best, cols, n), rows,
                                   num_segments=n, indices_are_sorted=True)
        best = jnp.where(has, best, n)
        # handshake: best[best[i]] == i
        best_of_best = jnp.where(best < n, best[jnp.clip(best, 0, n - 1)], n)
        idx = jnp.arange(n, dtype=best.dtype)
        paired = (best < n) & (best_of_best == idx)
        leader = paired & (idx < best)
        # aggregate id = leader index
        agg = jnp.where(leader, idx, agg)
        agg = jnp.where(paired & ~leader, best, agg)
    # leftovers become singletons
    idx = jnp.arange(n, dtype=jnp.int32)
    agg = jnp.where(agg < 0, idx, agg)
    return agg


def _merge_singletons(rows, cols, w, agg, n):
    """Merge singleton aggregates into their strongest neighbor aggregate
    (merge_singletons=1 semantics)."""
    sizes = jax.ops.segment_sum(jnp.ones((n,), jnp.int32), agg,
                                num_segments=n)
    is_singleton = sizes[agg] == 1
    valid = is_singleton[rows] & ~is_singleton[cols] & (w > 0)
    we = jnp.where(valid, w, -1.0)
    wmax = jax.ops.segment_max(we, rows, num_segments=n,
                               indices_are_sorted=True)
    has = wmax > 0
    is_best = valid & (we == wmax[rows])
    best = jax.ops.segment_min(jnp.where(is_best, cols, n), rows,
                               num_segments=n, indices_are_sorted=True)
    target = jnp.where(has & is_singleton,
                       agg[jnp.clip(best, 0, n - 1)], agg)
    return jnp.where(is_singleton, target, agg).astype(jnp.int32)


def _renumber(agg, n):
    """Compact aggregate ids to 0..nc-1 (order-preserving, determinstic)."""
    present = jnp.zeros((n,), jnp.int32).at[agg].set(1)
    new_id = jnp.cumsum(present) - 1
    nc = int(new_id[-1]) + 1
    return new_id[agg].astype(jnp.int32), nc


def _coarse_graph(rows, cols, w, agg, nc):
    """Collapse the weighted graph onto aggregates (for multi-pass
    matching): returns (crows, ccols, cw) with duplicates summed."""
    cr = agg[rows]
    cc = agg[cols]
    mask = cr != cc
    key = cr.astype(jnp.int64) * nc + cc.astype(jnp.int64)
    key = jnp.where(mask, key, -1)
    order = jnp.argsort(key, stable=True)
    key_s, cr_s, cc_s, w_s = key[order], cr[order], cc[order], w[order]
    start = int(jnp.searchsorted(key_s, 0))  # skip collapsed self-edges
    key_s, cr_s, cc_s, w_s = (key_s[start:], cr_s[start:], cc_s[start:],
                              w_s[start:])
    if key_s.shape[0] == 0:
        z = jnp.zeros((0,), jnp.int32)
        return z, z, jnp.zeros((0,), w.dtype)
    newseg = jnp.concatenate([jnp.ones((1,), bool), key_s[1:] != key_s[:-1]])
    seg = jnp.cumsum(newseg) - 1
    nuniq = int(seg[-1]) + 1
    first = jnp.nonzero(newseg, size=nuniq)[0]
    wsum = jax.ops.segment_sum(w_s, seg, num_segments=nuniq,
                               indices_are_sorted=True)
    return cr_s[first], cc_s[first], wsum


class AggregationSelector:
    """Base selector: setAggregates returns (aggregates (n,), num_aggregates)
    (agg_selector.cu analog)."""

    def __init__(self, cfg: Config, scope: str):
        self.cfg = cfg
        self.scope = scope
        self.max_matching_iterations = int(
            cfg.get("max_matching_iterations", scope))
        self.merge_singletons = int(cfg.get("merge_singletons", scope))
        self.weight_formula = int(cfg.get("weight_formula", scope))
        self.deterministic = bool(cfg.get("determinism_flag", scope))

    def set_aggregates(self, A: CsrMatrix):
        raise NotImplementedError


class _SizeNSelector(AggregationSelector):
    passes = 1  # SIZE_2; 2 -> SIZE_4; 3 -> SIZE_8

    def set_aggregates(self, A: CsrMatrix):
        n = A.num_rows
        rows, cols, w = _edge_weights(A, self.weight_formula)
        agg = _matching_pass(rows, cols, w, n,
                             self.max_matching_iterations)
        if self.merge_singletons:
            agg = _merge_singletons(rows, cols, w, agg, n)
        agg, nc = _renumber(agg, n)
        # later passes pair aggregates through the collapsed graph
        for _ in range(self.passes - 1):
            crows, ccols, cw = _coarse_graph(rows, cols, w, agg, nc)
            if crows.shape[0] == 0:
                break
            cagg = _matching_pass(crows, ccols, cw, nc,
                                  self.max_matching_iterations)
            if self.merge_singletons:
                cagg = _merge_singletons(crows, ccols, cw, cagg, nc)
            cagg, nc = _renumber(cagg, nc)
            agg = cagg[agg]
        return agg, nc


@registry.aggregation_selectors.register("SIZE_2")
class Size2Selector(_SizeNSelector):
    passes = 1


@registry.aggregation_selectors.register("SIZE_4")
class Size4Selector(_SizeNSelector):
    passes = 2


@registry.aggregation_selectors.register("SIZE_8")
class Size8Selector(_SizeNSelector):
    passes = 3


@registry.aggregation_selectors.register("MULTI_PAIRWISE")
class MultiPairwiseSelector(_SizeNSelector):
    """Pairwise aggregation repeated `aggregation_passes` times
    (multi_pairwise.cu analog; Notay-style weights via weight_formula)."""

    def __init__(self, cfg, scope):
        super().__init__(cfg, scope)
        self.passes = int(cfg.get("aggregation_passes", scope))


@registry.aggregation_selectors.register("DUMMY")
class DummySelector(AggregationSelector):
    """Blocks of `aggregate_size` consecutive rows (dummy selector)."""

    def set_aggregates(self, A: CsrMatrix):
        size = int(self.cfg.get("aggregate_size", self.scope))
        n = A.num_rows
        agg = (jnp.arange(n, dtype=jnp.int32) // size)
        nc = int(np.ceil(n / size))
        return agg, nc


@registry.aggregation_selectors.register("GEO")
@registry.aggregation_selectors.register("PARALLEL_GREEDY")
class ParallelGreedySelector(_SizeNSelector):
    """Greedy matching selector (parallel_greedy_selector.cu analog);
    shares the handshaking fixed-point with SIZE_2."""

    passes = 1
