"""Aggregation selectors: parallel-matching aggregation.

Analogs of src/aggregation/selectors/ (size2_selector.cu 920 LoC,
size4/size8, dummy). The reference's handshaking matching is re-expressed
as fixed-point iterations of segmented gather/argmax ops (TPU-friendly:
no atomics, deterministic by construction via smallest-index
tie-breaking):

  repeat:
    every unaggregated vertex proposes its strongest unaggregated
    neighbor (segment-max of edge weights + segment-min index tiebreak);
    mutual proposals (handshakes) become aggregates of two.

SIZE_4 / SIZE_8 run 2 / 3 matching passes, pairing *aggregates* in later
passes through the coarse graph (same machinery as the Galerkin product).
All of this is setup-time eager device code with concrete shapes.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ... import registry
from ...config import Config
from ...matrix import CsrMatrix, lexsort_rc


def _edge_weights(A: CsrMatrix, formula: int = 0):
    """Symmetrized edge weights (reference weight_formula 0:
    w_ij = 0.5(|a_ij|+|a_ji|)/max(|a_ii|,|a_jj|))."""
    rows, cols, vals = A.coo()
    if A.is_block:
        # reference uses one block component (aggregation_edge_weight_
        # component); the (0,0) entry
        v = vals[:, 0, 0]
        d = A.diagonal()[:, 0, 0]
    else:
        v = vals
        d = A.diagonal()
    absd = jnp.abs(d)
    n = A.num_rows
    # canonicalize to (row, col)-lexicographic order first — uploaded
    # CSR may have unsorted columns within a row, and the positional
    # alignment below requires the canonical order on both sides
    canon = lexsort_rc(rows, cols)
    rows, cols, v = rows[canon], cols[canon], v[canon]
    # |a_ji| via the positional transpose alignment: sorting the entries
    # by (col, row) puts the k-th entry's transpose partner at position
    # k of the canonical order whenever the sparsity pattern is
    # symmetric (two int32 sorts — no emulated 64-bit keys on TPU).
    # Where the pattern is one-sided the pairing check fails and that
    # edge's weight uses the present side only.
    order = lexsort_rc(cols, rows)       # (col, row)-lexicographic
    tr = rows[order]
    tc = cols[order]
    match = (tr == cols) & (tc == rows)
    v_t = jnp.where(match, v[order], 0.0)        # signed a_ji
    if formula == 1:
        # -0.5 (a_ij/a_ii + a_ji/a_jj) — Notay coupling
        # (common_selector.h:113-119, SIGNED values)
        w = -0.5 * (v / jnp.where(d[rows] == 0, 1.0, d[rows])
                    + v_t / jnp.where(d[cols] == 0, 1.0, d[cols]))
    else:
        denom = jnp.maximum(absd[rows], absd[cols])
        w = 0.5 * (jnp.abs(v) + jnp.abs(v_t)) / \
            jnp.where(denom == 0, 1.0, denom)
    w = jnp.where(rows == cols, 0.0, w)
    return rows, cols, w


def _edge_hash(rows, cols):
    """Symmetric per-edge pseudo-random value in [0, 1): hash of the
    unordered pair. Breaks weight ties so handshaking matches a constant
    fraction per round (Luby-style) instead of forming chains; being a
    pure hash it is deterministic across runs (determinism_flag for free)."""
    a = jnp.minimum(rows, cols).astype(jnp.uint32)
    b = jnp.maximum(rows, cols).astype(jnp.uint32)
    h = a * jnp.uint32(73856093) ^ b * jnp.uint32(19349663)
    h = (h ^ (h >> 13)) * jnp.uint32(0x5BD1E995)
    return (h & jnp.uint32(0xFFFFF)).astype(jnp.float64) / float(1 << 20)


def _matching_pass(rows, cols, w, n, max_iters: int, active=None,
                   rows_sorted: bool = True):
    """One size-2 matching: returns aggregate ids (pairs + singletons).
    Unmatched vertices keep their own id; ids are NOT yet renumbered.

    Fully jittable: lax.while_loop fixed point, static shapes. `rows`
    entries equal to n are drop sentinels (padded edges); `active`
    restricts matching to a traced vertex subset (padded coarse passes).
    """
    idx = jnp.arange(n, dtype=jnp.int32)
    if active is None:
        active = jnp.ones((n,), bool)
    # tie-breaking perturbation, small relative to the weight scale
    # (elementwise no-op for zero weights, so no host-side scale check)
    w = w * (1.0 + 1e-3 * _edge_hash(rows, cols).astype(w.dtype))
    INF_NEG = jnp.asarray(-1.0, w.dtype)

    def lookup(mask):
        """Vertex-property gather tolerant of the n sentinel."""
        return jnp.concatenate([mask, jnp.zeros((1,), mask.dtype)])[
            jnp.minimum(rows, n)], \
            jnp.concatenate([mask, jnp.zeros((1,), mask.dtype)])[
            jnp.minimum(cols, n)]

    def cond(state):
        it, agg = state
        return (it < max_iters) & jnp.any((agg < 0) & active)

    def body(state):
        it, agg = state
        un = (agg < 0) & active
        un_r, un_c = lookup(un)
        valid = un_r & un_c & (w > 0)
        we = jnp.where(valid, w, INF_NEG)
        wmax = jax.ops.segment_max(we, rows, num_segments=n,
                                   indices_are_sorted=rows_sorted)
        has = wmax > 0
        is_best = valid & (we == wmax[jnp.clip(rows, 0, n - 1)])
        # smallest-index tiebreak -> determinism
        best = jax.ops.segment_min(jnp.where(is_best, cols, n), rows,
                                   num_segments=n,
                                   indices_are_sorted=rows_sorted)
        best = jnp.where(has, best, n)
        # handshake: best[best[i]] == i
        best_of_best = jnp.where(best < n, best[jnp.clip(best, 0, n - 1)],
                                 n)
        paired = (best < n) & (best_of_best == idx)
        leader = paired & (idx < best)
        agg = jnp.where(leader, idx, agg)
        agg = jnp.where(paired & ~leader, best.astype(jnp.int32), agg)
        return it + 1, agg

    _, agg = jax.lax.while_loop(
        cond, body, (jnp.int32(0), jnp.full((n,), -1, jnp.int32)))
    # leftovers become singletons
    return jnp.where(agg < 0, idx, agg)


def _merge_singletons(rows, cols, w, agg, n, rows_sorted: bool = True):
    """Merge singleton aggregates into their strongest neighbor aggregate
    (merge_singletons=1 semantics). Jittable, sentinel-tolerant."""
    sizes = jax.ops.segment_sum(jnp.ones((n,), jnp.int32), agg,
                                num_segments=n)
    is_singleton = sizes[agg] == 1
    pad = jnp.concatenate([is_singleton, jnp.zeros((1,), bool)])
    s_r = pad[jnp.minimum(rows, n)]
    s_c = pad[jnp.minimum(cols, n)]
    valid = s_r & ~s_c & (w > 0) & (cols < n)
    we = jnp.where(valid, w, -1.0)
    wmax = jax.ops.segment_max(we, rows, num_segments=n,
                               indices_are_sorted=rows_sorted)
    has = wmax > 0
    is_best = valid & (we == wmax[jnp.clip(rows, 0, n - 1)])
    best = jax.ops.segment_min(jnp.where(is_best, cols, n), rows,
                               num_segments=n,
                               indices_are_sorted=rows_sorted)
    target = jnp.where(has & is_singleton,
                       agg[jnp.clip(best, 0, n - 1)], agg)
    return jnp.where(is_singleton, target, agg).astype(jnp.int32)


def _renumber(agg, n, active=None):
    """Compact aggregate ids to 0..nc-1 (order-preserving, deterministic).
    Returns a *traced* nc; the caller materializes it once per level."""
    if active is None:
        present = jnp.zeros((n,), jnp.int32).at[agg].set(1)
    else:
        present = jnp.zeros((n,), jnp.int32).at[
            jnp.where(active, agg, n)].set(1, mode="drop")
    new_id = jnp.cumsum(present) - 1
    nc = new_id[-1] + 1
    return new_id[agg].astype(jnp.int32), nc


def _coarse_graph(rows, cols, w, agg, nc, n):
    """Collapse the weighted graph onto aggregates (for multi-pass
    matching), static-shape: returns (crows, ccols, cw) of the same
    length as the input edge list, duplicates summed onto their first
    occurrence and non-first/invalid entries turned into drop sentinels
    (row == col == n, w == 0)."""
    e = rows.shape[0]
    aggp = jnp.concatenate([agg, jnp.full((1,), n, jnp.int32)])
    cr = aggp[jnp.minimum(rows, n)]
    cc = aggp[jnp.minimum(cols, n)]
    valid = (cr != cc) & (w > 0) & (rows < n)
    # invalid entries sort last: both coordinates forced to n (int32
    # two-pass lexsort — no emulated 64-bit keys)
    cr_k = jnp.where(valid, cr, n).astype(jnp.int32)
    cc_k = jnp.where(valid, cc, n).astype(jnp.int32)
    order = lexsort_rc(cr_k, cc_k)
    cr_s, cc_s, w_s = cr_k[order], cc_k[order], w[order]
    valid_s = cr_s < n
    first = jnp.concatenate(
        [jnp.ones((1,), bool),
         (cr_s[1:] != cr_s[:-1]) | (cc_s[1:] != cc_s[:-1])]) & valid_s
    seg = jnp.cumsum(first) - 1
    wsum = jax.ops.segment_sum(jnp.where(valid_s, w_s, 0.0), seg,
                               num_segments=e)
    keep = first
    crows = jnp.where(keep, cr_s, n).astype(jnp.int32)
    ccols = jnp.where(keep, cc_s, n).astype(jnp.int32)
    cw = jnp.where(keep, wsum[jnp.clip(seg, 0, e - 1)], 0.0)
    return crows, ccols, cw


class AggregationSelector:
    """Base selector: setAggregates returns (aggregates (n,), num_aggregates)
    (agg_selector.cu analog)."""

    def __init__(self, cfg: Config, scope: str):
        self.cfg = cfg
        self.scope = scope
        self.max_matching_iterations = int(
            cfg.get("max_matching_iterations", scope))
        self.merge_singletons = int(cfg.get("merge_singletons", scope))
        self.weight_formula = int(cfg.get("weight_formula", scope))
        self.deterministic = bool(cfg.get("determinism_flag", scope))

    def set_aggregates(self, A: CsrMatrix):
        raise NotImplementedError


@functools.partial(
    jax.jit,
    static_argnames=("passes", "max_iters", "merge", "formula"))
def _set_aggregates_impl(A, *, passes, max_iters, merge, formula):
    """The whole multi-pass matching as ONE compiled program (static
    shapes throughout; coarse passes run padded to the fine vertex count
    with an `active` mask). Returns (aggregates, traced nc)."""
    n = A.num_rows
    rows, cols, w = _edge_weights(A, formula)
    agg = _matching_pass(rows, cols, w, n, max_iters)
    if merge:
        agg = _merge_singletons(rows, cols, w, agg, n)
    agg, nc = _renumber(agg, n)
    # later passes pair aggregates through the collapsed (padded) graph
    for _ in range(passes - 1):
        crows, ccols, cw = _coarse_graph(rows, cols, w, agg, nc, n)
        active = jnp.arange(n) < nc
        cagg = _matching_pass(crows, ccols, cw, n, max_iters,
                              active=active, rows_sorted=False)
        if merge:
            cagg = _merge_singletons(crows, ccols, cw, cagg, n,
                                     rows_sorted=False)
        cagg, nc = _renumber(cagg, n, active=active)
        agg = cagg[agg]
    return agg, nc


class _SizeNSelector(AggregationSelector):
    passes = 1  # SIZE_2; 2 -> SIZE_4; 3 -> SIZE_8

    def set_aggregates(self, A: CsrMatrix):
        agg, nc = _set_aggregates_impl(
            A, passes=self.passes, max_iters=self.max_matching_iterations,
            merge=bool(self.merge_singletons), formula=self.weight_formula)
        return agg, int(nc)   # one host sync per level


@registry.aggregation_selectors.register("SIZE_2")
class Size2Selector(_SizeNSelector):
    passes = 1


@registry.aggregation_selectors.register("SIZE_4")
class Size4Selector(_SizeNSelector):
    passes = 2


@registry.aggregation_selectors.register("SIZE_8")
class Size8Selector(_SizeNSelector):
    passes = 3


@registry.aggregation_selectors.register("MULTI_PAIRWISE")
class MultiPairwiseSelector(_SizeNSelector):
    """Pairwise aggregation repeated `aggregation_passes` times
    (multi_pairwise.cu analog): each pass matches the weight graph of
    the previous pass's aggregates — the reference's default
    full_ghost_level=0 "weight matrix" scheme. notay_weights=1 switches
    the edge weights to Notay's signed coupling measure
    (multi_pairwise.cu:816, the weight_formula=1 formula); unmatched
    vertices merge into their strongest neighbor aggregate
    (mergeWithExistingAggregates analog = merge_singletons)."""

    def __init__(self, cfg, scope):
        super().__init__(cfg, scope)
        self.passes = int(cfg.get("aggregation_passes", scope))
        if int(cfg.get("notay_weights", scope)):
            self.weight_formula = 1


@registry.aggregation_selectors.register("DUMMY")
class DummySelector(AggregationSelector):
    """Blocks of `aggregate_size` consecutive rows (dummy selector)."""

    def set_aggregates(self, A: CsrMatrix):
        size = int(self.cfg.get("aggregate_size", self.scope))
        n = A.num_rows
        agg = (jnp.arange(n, dtype=jnp.int32) // size)
        nc = int(np.ceil(n / size))
        return agg, nc


@registry.aggregation_selectors.register("PARALLEL_GREEDY")
class ParallelGreedySelector(_SizeNSelector):
    """Greedy matching selector (parallel_greedy_selector.cu analog);
    shares the handshaking fixed-point with SIZE_2."""

    passes = 1


@registry.aggregation_selectors.register("GEO")
class GeoSelector(AggregationSelector):
    """Geometric aggregation (geo_selector.cu analog — the reference
    selector that aggregates by spatial position instead of matrix
    weights). TPU redesign: on a structured grid (CsrMatrix.grid_shape,
    set by the gallery / C-API Poisson generators) each aggregate is the
    2x2x2 block of grid points (every axis with extent >= 2 halved):

      agg(x, y, z) = linear coarse index of (x//2, y//2, z//2).

    The Galerkin product of a separable stencil operator under this
    blocking is again a stencil operator with the same diagonal
    structure, so every level of the hierarchy keeps the DIA roofline
    SpMV layout (no gathers or scatters anywhere in the cycle), and
    restriction/prolongation collapse to per-axis reshape-sums /
    broadcasts (amg/aggregation/__init__.py).
    """

    def set_aggregates(self, A: CsrMatrix):
        shape = A.grid_shape
        n = A.num_rows
        if shape is None or int(np.prod(shape)) != n:
            from ...errors import BadParametersError
            raise BadParametersError(
                "GEO selector requires a structured-grid matrix "
                "(CsrMatrix.grid_shape); use SIZE_2/PARALLEL_GREEDY for "
                "unstructured matrices")
        nx, ny, nz = shape
        axes = tuple(a for a, e in enumerate((nx, ny, nz)) if e >= 2)
        if not axes:
            self.fine_shape = shape
            self.pair_axes = None
            self.coarse_shape = shape
            return jnp.arange(n, dtype=jnp.int32), n
        cnx = (nx + 1) // 2 if 0 in axes else nx
        cny = (ny + 1) // 2 if 1 in axes else ny
        cnz = (nz + 1) // 2 if 2 in axes else nz
        # pure index arithmetic: host numpy (a single device transfer)
        # instead of ~10 eager device ops — on tunneled TPU rigs every
        # eager dispatch costs a full round trip
        i = np.arange(n, dtype=np.int32)
        x = i % nx
        t = i // nx
        y = t % ny
        z = t // ny
        cx = x // 2 if 0 in axes else x
        cy = y // 2 if 1 in axes else y
        cz = z // 2 if 2 in axes else z
        agg = (cz * cny + cy) * cnx + cx
        self.fine_shape = shape
        self.pair_axes = axes
        self.coarse_shape = (cnx, cny, cnz)
        # stays HOST numpy: the structured (paired) levels never touch
        # the aggregates map in the solve phase — restriction/
        # prolongation are reshape pair-sums and the Galerkin product is
        # the parity-mask fast path — so uploading it cost a pointless
        # n*4-byte transfer per level per setup (67 MB for L0 at 256^3
        # through the tunnel). The generic-fallback consumers
        # (coarse_a_from_aggregates, restrict_vector) accept numpy and
        # upload on first use only when that slow path actually runs.
        return agg.astype(np.int32), int(cnx * cny * cnz)


@registry.aggregation_selectors.register("SERIAL_GREEDY")
@registry.aggregation_selectors.register("SERIAL_GREEDY_BFS")
class SerialGreedySelector(AggregationSelector):
    """Serial greedy BFS aggregation (serial_greedy.cu, 319 LoC). The
    reference runs this selector on the HOST even in device builds
    (serial_greedy.cu:62-80 copies the matrix down); this is the same
    host-serial design: seed at the minimum-degree unaggregated vertex,
    grow the aggregate by the strongest edge until `aggregate_size`,
    repeat. Deterministic by construction."""

    def set_aggregates(self, A: CsrMatrix):
        import numpy as np
        size = max(int(self.cfg.get("aggregate_size", self.scope)), 2)
        n = A.num_rows
        rows_j, cols_j, w_j = _edge_weights(A, self.weight_formula)
        # _edge_weights returns (row, col)-lexicographically sorted edges
        rows = np.asarray(rows_j)
        cols = np.asarray(cols_j)
        w = np.asarray(w_j)
        starts = np.searchsorted(rows, np.arange(n + 1))
        agg = np.full(n, -1, np.int64)
        deg = np.diff(starts)
        for seed in np.argsort(deg, kind="stable"):
            if agg[seed] >= 0:
                continue
            agg[seed] = seed
            members = [seed]
            while len(members) < size:
                best_w, best_v = 0.0, -1
                for m in members:
                    lo, hi = starts[m], starts[m + 1]
                    for e in range(lo, hi):
                        v = cols[e]
                        if agg[v] < 0 and w[e] > best_w:
                            best_w, best_v = w[e], v
                if best_v < 0:
                    break
                agg[best_v] = seed
                members.append(best_v)
        agg_j, nc = _renumber(jnp.asarray(agg, jnp.int32), n)
        return agg_j, int(nc)


@registry.aggregation_selectors.register("ADAPTIVE")
class AdaptiveSelector(AggregationSelector):
    """Adaptive (smoothed-vector binning) aggregation. The reference
    registers this selector but its setAggregates raises
    NOT_IMPLEMENTED with the intended algorithm left in comments
    (adaptive.cu:142-211); this implements that documented algorithm
    for real: relax a random vector on A x = 0 (so x approaches the
    algebraically smooth error), then bin the entries into n/4 linear
    bins — vertices whose smooth-error values agree aggregate
    together."""

    def set_aggregates(self, A: CsrMatrix):
        import numpy as np
        n = A.num_rows
        ns = n * A.block_dimy          # scalar unknowns (block SpMV)
        rng = np.random.default_rng(1234 if self.deterministic else None)
        x = jnp.asarray(rng.uniform(-1.0, 1.0, ns), A.dtype)
        d = A.diagonal()
        if d.ndim == 3:
            d = jnp.diagonal(d, axis1=1, axis2=2).reshape(-1)
        dinv = jnp.where(d == 0, 0.0, 1.0 / jnp.where(d == 0, 1.0, d))

        from ...ops.spmv import spmv

        def sweep(_, x):
            return x - 0.66 * dinv * spmv(A, x)    # 15 Jacobi sweeps
        x = jax.lax.fori_loop(0, 15, sweep, x)
        if A.block_dimy > 1:
            # bin per block row by the mean smooth-error component
            x = x.reshape(n, A.block_dimy).mean(axis=1)
        lo = jnp.min(x)
        rng_w = jnp.maximum(jnp.max(x) - lo, 1e-30)
        n_bins = max(n // 4, 1)
        bins = jnp.clip(((x - lo) / rng_w * n_bins).astype(jnp.int32),
                        0, n_bins - 1)
        # stamp each bin with its first member (root id), then compact
        first = jnp.full((n_bins,), n, jnp.int32).at[bins].min(
            jnp.arange(n, dtype=jnp.int32))
        agg = first[bins]
        agg_j, nc = _renumber(agg, n)
        return agg_j, int(nc)
