"""Unsmoothed-aggregation AMG level.

Analog of src/aggregation/aggregation_amg_level.cu (2654 LoC): the
selector builds an `aggregates` map, restriction/prolongation are
segment-sum / gather with that map (no explicit CSR transfer operators),
and the coarse matrix is the COO-relabel Galerkin product.

GEO (structured pairing) levels additionally know the grid geometry:
restriction/prolongation become axis reshape-sums / broadcasts — pure
dense data movement with no gather/scatter at all (the TPU-optimal
shape) — and the coarse matrix inherits the coarse grid annotation so
the whole hierarchy stays banded/DIA.
"""
from __future__ import annotations

import dataclasses

import jax.numpy as jnp
import numpy as np

from ... import registry
from ...config import Config
from ...matrix import CsrMatrix
from ..hierarchy import AMGLevel
from . import selectors  # noqa: F401  (registers selectors)
from .galerkin import (coarse_a_from_aggregates, geo_shapes,
                       pair_sum_axis, prolongate_corr, restrict_vector)


def _geo_restrict(r, fine_shape, axis):
    """Pair-sum along one grid axis: the piecewise-constant restriction
    of a structured pairing, as a reshape + sum (no scatter). Shares
    pair_sum_axis with the structured Galerkin so the transfer operators
    and the coarse operator can never drift apart."""
    nx, ny, nz = fine_shape
    v = r.reshape(nz, ny, nx)                  # linear index: x fastest
    return pair_sum_axis(v, fine_shape[axis], axis).reshape(-1)


def _geo_prolongate(xc, fine_shape, coarse_shape, axis):
    """Broadcast along the paired grid axis (P = pairwise-constant).
    Implemented as two interior-padded copies (even + odd positions)
    instead of jnp.repeat: repeat's internal `(..., 2)` reshape puts the
    pair in the minor dimension, which TPU tiling pads 128x."""
    import jax
    nx, ny, nz = coarse_shape
    v = xc.reshape(nz, ny, nx)
    dims = 2 - axis
    fine_e = fine_shape[axis]
    cn = v.shape[dims]
    zero = jnp.zeros((), v.dtype)
    cfg_e = [(0, 0, 0)] * 3
    cfg_o = [(0, 0, 0)] * 3
    cfg_e[dims] = (0, fine_e - (2 * cn - 1), 1)   # values at even slots
    cfg_o[dims] = (1, fine_e - 2 * cn, 1)         # values at odd slots
    out = jax.lax.pad(v, zero, cfg_e) + jax.lax.pad(v, zero, cfg_o)
    return out.reshape(-1)


@registry.amg_levels.register("AGGREGATION")
class AggregationAMGLevel(AMGLevel):
    algorithm = "AGGREGATION"

    geo_axes = None          # set when the selector pairs geometrically
    geo_fine_shape = None
    geo_coarse_shape = None

    def create_coarse_vertices(self):
        from ...profiling import trace_region
        sel_name = str(self.cfg.get("selector", self.scope))
        sel = registry.aggregation_selectors.create(
            sel_name, self.cfg, self.scope)
        with trace_region(f"amg.L{self.level_index}.selector"):
            self.aggregates, self.coarse_size = sel.set_aggregates(self.A)
        if getattr(sel, "pair_axes", None) is not None and \
                not self.A.is_block:
            self.geo_axes = sel.pair_axes
            self.geo_fine_shape = sel.fine_shape
            self.geo_coarse_shape = sel.coarse_shape

    def _geo_shapes(self):
        """Intermediate grid shapes for the per-axis transfer sequence."""
        return geo_shapes(self.geo_fine_shape, self.geo_axes)

    def create_coarse_matrix(self) -> CsrMatrix:
        from ...ops import spgemm
        from ...profiling import trace_region
        k = self.level_index
        planned = spgemm.plan_enabled(self.cfg, self.scope)
        if self.geo_axes is not None:
            if planned:
                # planned GEO route: the memoized GeoRapPlan skips
                # every symbolic step; the numeric phase is one jitted
                # program feeding geo_assemble_dia's output shape next
                # to the device-structure cache
                from .galerkin import get_geo_plan
                with trace_region(f"amg.L{k}.rap_plan"):
                    plan = get_geo_plan(self.A, self.geo_fine_shape,
                                        self.geo_axes,
                                        self.geo_coarse_shape)
                if plan is not None:
                    with trace_region(f"amg.L{k}.rap_values"):
                        Ac = plan.coarse_matrix(self.A)
                    if Ac is not None:
                        self._geo_plan_memo = (plan,)
                        return Ac
            else:
                from .galerkin import (geo_assemble_dia,
                                       geo_coarse_values)
                with trace_region(f"amg.L{k}.galerkin"):
                    pre = geo_coarse_values(self.A,
                                            self.geo_fine_shape,
                                            self.geo_axes,
                                            self.geo_coarse_shape)
                if pre is not None:     # structured sort-free Galerkin
                    # the DIA pack is the coarse operator's LAYOUT
                    # build — timed as such, not hidden inside the
                    # galerkin bucket
                    with trace_region(f"amg.L{k}.layout"):
                        return geo_assemble_dia(pre[0], pre[1],
                                                self.geo_coarse_shape)
        if planned and not self.A.is_block \
                and self.aggregates is not None:
            Ac = self._relabel_planned(k)
            if Ac is not None:
                if self.geo_coarse_shape is not None:
                    Ac = dataclasses.replace(
                        Ac, grid_shape=self.geo_coarse_shape)
                return Ac
        with trace_region(f"amg.L{k}.galerkin"):
            Ac = coarse_a_from_aggregates(self.A, self.aggregates,
                                          self.coarse_size)
        if self.geo_coarse_shape is not None:
            Ac = dataclasses.replace(Ac, grid_shape=self.geo_coarse_shape)
        return Ac

    def _relabel_planned(self, k: int):
        """Plan-split relabel Galerkin: structure memoized on the level
        (carried across structure resetups — the aggregates map is the
        pattern) with the digest cache catching warm full setups of
        the same pattern; value phase through ops/spgemm.rap_values."""
        from ...ops import spgemm
        from ...profiling import trace_region
        plan = None
        # pattern proven by IDENTITY of A's structure arrays (retained
        # in the memo) — a same-nnz permuted pattern misses and takes
        # the content-keyed digest cache instead (see the classical
        # twin for the full rationale)
        memo = getattr(self, "_rap_plan_memo", None)
        if memo is not None and memo[0] is self.aggregates \
                and memo[1] is self.A.row_offsets \
                and memo[2] is self.A.col_indices \
                and memo[3] == self.A.has_external_diag:
            plan = memo[4]
        if plan is None:
            with trace_region(f"amg.L{k}.rap_plan"):
                plan = spgemm.get_agg_plan(self.A, self.aggregates,
                                           self.coarse_size)
            if plan is not None:
                self._rap_plan_memo = (
                    self.aggregates, self.A.row_offsets,
                    self.A.col_indices, self.A.has_external_diag,
                    plan)
        if plan is None:
            return None
        with trace_region(f"amg.L{k}.rap_values"):
            return spgemm.plan_coarse_matrix(plan, self.A)

    def reuse_structure(self, old):
        """structure_reuse_levels: keep the aggregates map; the Galerkin
        relabel-sum then runs against the new coefficients. The RAP
        plans ride along (same aggregates object = same pattern), so a
        structure resetup does zero symbolic RAP work."""
        self.aggregates = old.aggregates
        self.coarse_size = old.coarse_size
        self.geo_axes = old.geo_axes
        self.geo_fine_shape = old.geo_fine_shape
        self.geo_coarse_shape = old.geo_coarse_shape
        for attr in ("_rap_plan_memo", "_geo_plan_memo"):
            memo = getattr(old, attr, None)
            if memo is not None:
                setattr(self, attr, memo)

    def structure_snapshot(self):
        if self.coarse_size is None:
            return None
        meta = {"num_rows": int(self.A.num_rows),
                "coarse_size": int(self.coarse_size),
                "geo_axes": None if self.geo_axes is None
                else list(self.geo_axes),
                "geo_fine_shape": None if self.geo_fine_shape is None
                else list(self.geo_fine_shape),
                "geo_coarse_shape": None if self.geo_coarse_shape is None
                else list(self.geo_coarse_shape)}
        arrays = {}
        if self.aggregates is not None:
            arrays["aggregates"] = np.asarray(self.aggregates)
        return meta, arrays

    @classmethod
    def structure_restore(cls, meta, arrays):
        g = cls._ghost(meta["num_rows"])
        g.coarse_size = int(meta["coarse_size"])
        g.aggregates = arrays.get("aggregates")
        g.geo_axes = None if meta["geo_axes"] is None \
            else tuple(meta["geo_axes"])
        g.geo_fine_shape = None if meta["geo_fine_shape"] is None \
            else tuple(meta["geo_fine_shape"])
        g.geo_coarse_shape = None if meta["geo_coarse_shape"] is None \
            else tuple(meta["geo_coarse_shape"])
        return g

    def level_data(self):
        d = super().level_data()
        if self.geo_axes is None:
            # structured (paired) levels restrict/prolongate by reshape
            # pair-sums — the aggregates map is setup-only state there,
            # and carrying it in the solve pytree would re-upload an
            # n-sized host array per jitted call (the GEO selector keeps
            # it host-resident on purpose)
            d["aggregates"] = self.aggregates
        xfer = self._transfer_slabs()
        if xfer is not None:
            d["xfer"] = xfer
        return d

    def _transfer_slabs(self):
        """Structure-only transfer payloads for the fused grid-transfer
        and coarse-tail kernels (ops/smooth.py), memoized on the level
        (the aggregates map is fixed for the level's lifetime; a
        structure-reuse resetup builds NEW level objects and rebuilds).
        None off-TPU, with cycle_fusion=0, or for ineligible layouts —
        those rigs/configs build nothing and change nothing."""
        memo = getattr(self, "_xfer_memo", None)
        if memo is not None:
            return memo[0]
        from ...ops import smooth as fused
        slabs = None
        if bool(int(self.cfg.get("cycle_fusion", self.scope))) \
                and fused.fused_runtime_on() \
                and getattr(self, "aggregates", None) is not None \
                and self.coarse_size:
            slabs = fused.build_transfer_slabs(
                self.A, self.aggregates, int(self.coarse_size))
        self._xfer_memo = (slabs,)
        return slabs

    def supports_fusion(self, data):
        """Single-device aggregation levels advertise the fused
        grid-transfer kernels; distributed level-data (explicit sharded
        R/P) declines — the cycle's plain compose already runs the
        halo-folded per-shard smoother kernel through the smoother's
        own dispatch (ops/smooth.fused_smooth). Matrix-free levels
        (constant-coefficient stencil payload installed by the
        hierarchy's `matrix_free` detector) additionally advertise
        the "matrix_free" capability — the cycle's fused hooks then
        route through the coefficient kernels of ops/stencil.py with
        no A value-slab operand at all."""
        if "R" in data or "P" in data:
            return ()
        if self.smoother is None:
            return ()
        if "stencil" in data:
            return self.FUSION_CAPS | {"matrix_free"}
        return self.FUSION_CAPS

    def restrict_fused(self, data, b, x, sweeps: int):
        """Presmooth + restriction in one kernel (ops/smooth.py), or
        None (distributed levels with explicit R, unsupported layouts,
        smoothers without a fused form)."""
        if "R" in data or "P" in data or self.smoother is None:
            return None
        fn = getattr(self.smoother, "smooth_restrict", None)
        if fn is None:
            return None
        return fn(data["smoother"], b, x, sweeps, data.get("xfer"))

    def prolongate_smooth(self, data, b, x, xc, sweeps: int,
                          want_dot: bool = False):
        """Prolongation/correction folded into the postsmoother's first
        kernel application, or None. want_dot additionally requests the
        x'.b dot epilogue from the final kernel → (x', dot|None)."""
        if "R" in data or "P" in data or self.smoother is None:
            return None
        fn = getattr(self.smoother, "smooth_corr", None)
        if fn is None:
            return None
        return fn(data["smoother"], b, x, xc, sweeps, data.get("xfer"),
                  want_dot=want_dot)

    def restrict(self, data, r):
        if "R" in data:       # distributed: explicit sharded R = P^T
            from ...ops.spmv import spmv
            return spmv(data["R"], r)
        if self.geo_axes is not None:
            shapes = self._geo_shapes()
            for k, a in enumerate(self.geo_axes):
                r = _geo_restrict(r, shapes[k], a)
            return r
        return restrict_vector(data["aggregates"], self.coarse_size, r,
                               self.A.block_dimx)

    def prolongate(self, data, xc):
        if "P" in data:       # distributed: explicit sharded P
            from ...ops.spmv import spmv
            return spmv(data["P"], xc)
        if self.geo_axes is not None:
            shapes = self._geo_shapes()
            for k in range(len(self.geo_axes) - 1, -1, -1):
                xc = _geo_prolongate(xc, shapes[k], shapes[k + 1],
                                     self.geo_axes[k])
            return xc
        return prolongate_corr(data["aggregates"], xc, self.A.block_dimx)
