"""Unsmoothed-aggregation AMG level.

Analog of src/aggregation/aggregation_amg_level.cu (2654 LoC): the
selector builds an `aggregates` map, restriction/prolongation are
segment-sum / gather with that map (no explicit CSR transfer operators),
and the coarse matrix is the COO-relabel Galerkin product.
"""
from __future__ import annotations

from ... import registry
from ...config import Config
from ...matrix import CsrMatrix
from ..hierarchy import AMGLevel
from . import selectors  # noqa: F401  (registers selectors)
from .galerkin import (coarse_a_from_aggregates, prolongate_corr,
                       restrict_vector)


@registry.amg_levels.register("AGGREGATION")
class AggregationAMGLevel(AMGLevel):
    algorithm = "AGGREGATION"

    def create_coarse_vertices(self):
        sel_name = str(self.cfg.get("selector", self.scope))
        sel = registry.aggregation_selectors.create(
            sel_name, self.cfg, self.scope)
        self.aggregates, self.coarse_size = sel.set_aggregates(self.A)

    def create_coarse_matrix(self) -> CsrMatrix:
        return coarse_a_from_aggregates(self.A, self.aggregates,
                                        self.coarse_size)

    def reuse_structure(self, old):
        """structure_reuse_levels: keep the aggregates map; the Galerkin
        relabel-sum then runs against the new coefficients."""
        self.aggregates = old.aggregates
        self.coarse_size = old.coarse_size

    def level_data(self):
        d = super().level_data()
        d["aggregates"] = self.aggregates
        return d

    def restrict(self, data, r):
        if "R" in data:       # distributed: explicit sharded R = P^T
            from ...ops.spmv import spmv
            return spmv(data["R"], r)
        return restrict_vector(data["aggregates"], self.coarse_size, r,
                               self.A.block_dimx)

    def prolongate(self, data, xc):
        if "P" in data:       # distributed: explicit sharded P
            from ...ops.spmv import spmv
            return spmv(data["P"], xc)
        return prolongate_corr(data["aggregates"], xc, self.A.block_dimx)
