"""Energy-minimization AMG level — the third algorithm type.

TPU-native analog of src/energymin/ (energymin_amg_level.cu 431 LoC,
interpolators/em.cu 1280 LoC, selectors/em_selector.cu). The reference's
EM interpolator builds, for every coarse point, a local dense patch of A
over the column's fine-point support, inverts it on-device, and
assembles the inverses into the interpolation operator
(em.cu: extract_dense_Aijs_col_major -> init_dense_invAijs ->
init_Pvalues kernels).

TPU redesign of the same scheme: every coarse point's patch is padded to
one static size and the whole set is solved as ONE batched dense
QR solve (ops/dense.py) — (nc, k, k) patches ride the MXU, replacing the
reference's per-column warp kernels. Column j's values are the local
harmonic extension (energy minimizer with unit value at the coarse
point):

    p_F = - A[F_j, F_j]^{-1} A[F_j, c_j],   F_j = fine neighbors of c_j

which minimizes p^T A p over the patch subject to p[c_j] = 1. Fine rows
covered by several columns are row-rescaled to preserve constants (the
role of the reference's Ma row-sum system, em.cu count_Ma_* kernels).
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ... import registry
from ...matrix import CsrMatrix
from ..classical import ClassicalAMGLevel


class EnergyminInterpolator:
    def __init__(self, cfg, scope):
        self.cfg = cfg
        self.scope = scope

    def generate(self, A: CsrMatrix, cf_map, strong) -> CsrMatrix:
        raise NotImplementedError


@registry.energymin_interpolators.register("EM")
class EMInterpolator(EnergyminInterpolator):
    """Batched local energy-minimization interpolation (em.cu analog)."""

    def generate(self, A: CsrMatrix, cf_map, strong) -> CsrMatrix:
        n = A.num_rows
        rows_j, cols_j, vals_j = A.coo()
        rows = np.asarray(rows_j)
        cols = np.asarray(cols_j)
        vals = np.asarray(vals_j)
        cf = np.asarray(cf_map)
        is_C = cf == 1
        cidx = np.cumsum(is_C) - 1                # coarse ids
        c_rows = np.where(is_C)[0]                # fine index per column
        nc = len(c_rows)
        dt = vals.dtype
        ro = np.asarray(A.row_offsets)

        # column supports: fine neighbors of each coarse point (its A
        # row, restricted to F points) — distance-1 sparsity, matching
        # init_ProwInd_greedy_aggregation's neighborhood choice. Built
        # vectorized: mask the COO once, group by row.
        keep = is_C[rows] & ~is_C[cols] & (rows != cols)
        s_rows = rows[keep]                       # coarse fine-indices
        s_cols = cols[keep]                       # their fine neighbors
        cnt = np.zeros(n, np.int64)
        np.add.at(cnt, s_rows, 1)
        kmax = max(int(cnt.max()) if len(s_rows) else 0, 1)
        col_of = cidx[s_rows]                     # column id per entry
        # position of each entry within its column (entries are in row-
        # major COO order, so cumcount per s_rows run works)
        order = np.argsort(col_of, kind="stable")
        col_sorted = col_of[order]
        first = np.zeros(len(order), np.int64)
        if len(order):
            new_grp = np.ones(len(order), bool)
            new_grp[1:] = col_sorted[1:] != col_sorted[:-1]
            grp_start = np.where(new_grp)[0]
            gid = np.cumsum(new_grp) - 1
            first = np.arange(len(order)) - grp_start[gid]
        F = np.full((nc, kmax), -1, np.int64)
        if len(order):
            F[col_sorted, first] = s_cols[order]
        mask = F >= 0
        Fsafe = np.where(mask, F, c_rows[:, None] if nc else 0)

        # A-entry lookup by (row, col) key over the sorted COO keys
        keys = rows.astype(np.int64) * n + cols
        korder = np.argsort(keys)
        skeys = keys[korder]
        svals = vals[korder]

        def lookup(r_idx, c_idx):
            """A[r, c] (0 when absent) for broadcastable index arrays."""
            k = r_idx.astype(np.int64) * n + c_idx.astype(np.int64)
            pos = np.searchsorted(skeys, k)
            pos = np.clip(pos, 0, len(skeys) - 1)
            hit = skeys[pos] == k
            return np.where(hit, svals[pos], 0.0)

        # batched patches: A_FF (nc, k, k) and rhs a_Fc (nc, k)
        A_FF = lookup(Fsafe[:, :, None], Fsafe[:, None, :])
        rhs = lookup(Fsafe, c_rows[:, None])
        m2 = mask[:, :, None] & mask[:, None, :]
        eye = np.eye(kmax, dtype=dt)[None]
        # padded patch entries -> identity rows so the batched solve
        # stays well-posed and the padded unknowns come out zero
        A_FF = np.where(m2, A_FF, eye)
        rhs = np.where(mask, rhs, 0.0)

        # one batched dense solve on the MXU (the em.cu patch inverses)
        from ...ops.dense import solve_qr
        pF = -solve_qr(jnp.asarray(A_FF), jnp.asarray(rhs))
        pF = np.asarray(pF)
        # singular patches (zero diagonals, saddle blocks) come out
        # non-finite from the factorization: drop those columns' fine
        # entries so
        # the coarse point degrades to injection instead of poisoning
        # P and the Galerkin product with NaNs
        pF = np.where(np.isfinite(pF), pF, 0.0)

        # assemble P: injection for C rows + patch values for F rows
        pr = np.concatenate([c_rows, F[mask]])
        pc = np.concatenate([cidx[c_rows],
                             np.repeat(cidx[c_rows], mask.sum(1))])
        pv = np.concatenate([np.ones(nc, dt), pF[mask]])
        # row rescale: preserve constants where several columns overlap
        rowsum = np.zeros(n, dt)
        np.add.at(rowsum, pr, pv)
        scale = np.where(np.abs(rowsum) > 1e-12, 1.0 / np.where(
            rowsum == 0, 1.0, rowsum), 1.0)
        pv = pv * scale[pr]
        return CsrMatrix.from_coo(pr, pc, pv, n, nc)


@registry.amg_levels.register("ENERGYMIN")
class EnergyminAMGLevel(ClassicalAMGLevel):
    """Energymin_AMG_Level analog: the classical level flow (strength ->
    CF split -> P -> R=P^T -> RAP) with the energymin selector /
    interpolator registries (energymin_amg_level.cu:62-90)."""

    algorithm = "ENERGYMIN"
    selector_param = "energymin_selector"
    selector_fallback = "CR"
    interpolator_registry = registry.energymin_interpolators
    interpolator_param = "energymin_interpolator"
    interpolator_fallback = "EM"
