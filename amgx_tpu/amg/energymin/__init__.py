"""Energy-minimization AMG level — the third algorithm type.

TPU-native analog of src/energymin/ (energymin_amg_level.cu 431 LoC,
interpolators/em.cu 1280 LoC, selectors/em_selector.cu). The reference's
EM interpolator builds, for every coarse point, a local dense patch of A
over the column's fine-point support, inverts it on-device, and
assembles the inverses into the interpolation operator
(em.cu: extract_dense_Aijs_col_major -> init_dense_invAijs ->
init_Pvalues kernels).

TPU redesign of the same scheme: every coarse point's patch is padded to
one static size and the whole set is solved as ONE batched dense
`jnp.linalg.solve` — (nc, k, k) patches ride the MXU, replacing the
reference's per-column warp kernels. Column j's values are the local
harmonic extension (energy minimizer with unit value at the coarse
point):

    p_F = - A[F_j, F_j]^{-1} A[F_j, c_j],   F_j = fine neighbors of c_j

which minimizes p^T A p over the patch subject to p[c_j] = 1. Fine rows
covered by several columns are row-rescaled to preserve constants (the
role of the reference's Ma row-sum system, em.cu count_Ma_* kernels).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ... import registry
from ...matrix import CsrMatrix
from ...ops.spgemm import galerkin_rap
from ...ops.spmv import spmv
from ...ops.transpose import transpose
from ..hierarchy import AMGLevel


class EnergyminInterpolator:
    def __init__(self, cfg, scope):
        self.cfg = cfg
        self.scope = scope

    def generate(self, A: CsrMatrix, cf_map, strong) -> CsrMatrix:
        raise NotImplementedError


@registry.energymin_interpolators.register("EM")
class EMInterpolator(EnergyminInterpolator):
    """Batched local energy-minimization interpolation (em.cu analog)."""

    def generate(self, A: CsrMatrix, cf_map, strong) -> CsrMatrix:
        n = A.num_rows
        rows, cols, vals = [np.asarray(x) for x in A.coo()]
        valsj = A.coo()[2]
        cf = np.asarray(cf_map)
        is_C = cf == 1
        cidx = np.cumsum(is_C) - 1                # coarse ids
        c_rows = np.where(is_C)[0]                # fine index per column
        nc = len(c_rows)
        dt = np.asarray(A.values).dtype

        # column supports: fine neighbors of each coarse point (its A
        # row, restricted to F points) — greedy distance-1 sparsity,
        # matching init_ProwInd_greedy_aggregation's neighborhood choice
        ro = np.asarray(A.row_offsets)
        supports = []
        kmax = 1
        for fc in c_rows:
            nb = cols[ro[fc]: ro[fc + 1]]
            fnb = nb[(~is_C[nb]) & (nb != fc)]
            supports.append(fnb)
            kmax = max(kmax, len(fnb))

        # padded patch index array (nc, kmax); pad slot points at the
        # coarse point itself (masked out of the solve)
        F = np.full((nc, kmax), -1, np.int64)
        for j, fnb in enumerate(supports):
            F[j, : len(fnb)] = fnb
        mask = F >= 0
        Fsafe = np.where(mask, F, c_rows[:, None])

        # A-entry lookup by (row, col) key over the sorted COO keys
        keys = rows.astype(np.int64) * n + cols
        order = np.argsort(keys)
        skeys = keys[order]

        def lookup(r_idx, c_idx):
            """A[r, c] (0 when absent) for broadcastable index arrays."""
            k = r_idx.astype(np.int64) * n + c_idx.astype(np.int64)
            pos = np.searchsorted(skeys, k)
            pos = np.clip(pos, 0, len(skeys) - 1)
            hit = skeys[pos] == k
            v = np.asarray(valsj)[order][pos]
            return np.where(hit, v, 0.0)

        # batched patches: A_FF (nc, k, k) and rhs a_Fc (nc, k)
        A_FF = lookup(Fsafe[:, :, None], Fsafe[:, None, :])
        rhs = lookup(Fsafe, c_rows[:, None])
        m2 = mask[:, :, None] & mask[:, None, :]
        eye = np.eye(kmax, dtype=dt)[None]
        # padded patch entries -> identity rows so the batched solve
        # stays well-posed and the padded unknowns come out zero
        A_FF = np.where(m2, A_FF, eye)
        rhs = np.where(mask, rhs, 0.0)

        # one batched dense solve on the MXU (the em.cu patch inverses)
        pF = -jnp.linalg.solve(jnp.asarray(A_FF),
                               jnp.asarray(rhs)[..., None])[..., 0]
        pF = np.asarray(pF)

        # assemble P: injection for C rows + patch values for F rows
        pr = np.concatenate([c_rows, F[mask]])
        pc = np.concatenate([cidx[c_rows],
                             np.repeat(cidx[c_rows], mask.sum(1))])
        pv = np.concatenate([np.ones(nc, dt), pF[mask]])
        # row rescale: preserve constants where several columns overlap
        rowsum = np.zeros(n, dt)
        np.add.at(rowsum, pr, pv)
        scale = np.where(np.abs(rowsum) > 1e-12, 1.0 / np.where(
            rowsum == 0, 1.0, rowsum), 1.0)
        pv = pv * scale[pr]
        return CsrMatrix.from_coo(pr, pc, pv, n, nc)


@registry.amg_levels.register("ENERGYMIN")
class EnergyminAMGLevel(AMGLevel):
    """Energymin_AMG_Level analog: classical-style CF splitting (the
    `energymin_selector` parameter, CR by default) + EM interpolation +
    Galerkin RAP."""

    algorithm = "ENERGYMIN"

    def create_coarse_vertices(self):
        from ...errors import BadParametersError
        if self.A.is_block:
            raise BadParametersError(
                "ENERGYMIN AMG supports scalar matrices only")
        cfg, scope = self.cfg, self.scope
        st = registry.strength.create(str(cfg.get("strength", scope)),
                                      cfg, scope)
        self.strong = st.strong_mask(self.A)
        sel_name = str(cfg.get("energymin_selector", scope))
        if not registry.classical_selectors.has(sel_name):
            sel_name = "CR"
        sel = registry.classical_selectors.create(sel_name, cfg, scope)
        self.cf_map = sel.mark_coarse_fine_points(self.A, self.strong)
        self.coarse_size = int(jnp.sum(self.cf_map == 1))

    def create_coarse_matrix(self) -> CsrMatrix:
        cfg, scope = self.cfg, self.scope
        interp_name = str(cfg.get("energymin_interpolator", scope))
        if not registry.energymin_interpolators.has(interp_name):
            interp_name = "EM"
        interp = registry.energymin_interpolators.create(interp_name, cfg,
                                                         scope)
        self.P = interp.generate(self.A, self.cf_map, self.strong).init(
            ell="never")
        self.R = transpose(self.P).init(ell="never")
        return galerkin_rap(self.R, self.A, self.P)

    def level_data(self):
        d = super().level_data()
        d["P"] = self.P
        d["R"] = self.R
        return d

    def restrict(self, data, r):
        return spmv(data["R"], r)

    def prolongate(self, data, xc):
        return spmv(data["P"], xc)
