"""Multigrid cycles: V, W, F, CG (K-cycle), CG-flex.

Analog of src/cycles/ (fixed_cycle.cu:25-248 implements presmooth ->
residual -> restrict -> recurse -> prolongate+correct -> postsmooth;
v/w/f/cg_cycle.cu choose the recursion shape; registry
src/core.cu:631-635). Here the recursion is plain Python unrolled at
trace time over the static hierarchy depth, so a whole cycle is one XLA
program.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..ops import blas
from ..ops.spmv import residual, spmv
from ..ops.stencil import level_operator as _level_A
from ..telemetry import diagnostics as _diag


def _smooth(level, data, b, x, sweeps: int):
    if sweeps <= 0 or level.smoother is None:
        return x
    return level.smoother.smooth(data["smoother"], b, x, sweeps)


def _smooth_residual(level, data, b, x, sweeps: int):
    """Presmooth + residual as ONE smoother call: the damped-relaxation
    smoothers fuse the final sweep with the residual SpMV (and all
    sweeps with each other) into single-pass kernels on DIA/SWELL
    levels (ops/smooth.py), so the cycle's hottest pair costs one HBM
    pass over A instead of sweeps+1. Smoothers without a fused form
    compose exactly what this replaced (Solver.smooth_residual)."""
    if sweeps <= 0 or level.smoother is None:
        # matrix-free levels rebuild the operator in-trace
        # (ops/stencil.level_operator); slab levels pass through
        return x, residual(_level_A(data), x, b)
    return level.smoother.smooth_residual(data["smoother"], b, x, sweeps)


def _fusion_caps(level, data):
    """Fusion capabilities a level ADVERTISES for its solve-data — the
    single gate the cycle consults before invoking any fused hook
    (`restrict_fused` / `prolongate_smooth`). Levels declare support
    via `supports_fusion(data)` returning a capability collection
    ("restrict", "prolongate"). Resolved through the CLASS (MRO), not
    instance getattr: a `__getattr__`-delegating wrapper must define
    `supports_fusion` (and the hooks) EXPLICITLY to advertise anything
    — its inner level answering through delegation would claim the
    WRONG transfer space (the level's shard-local R/P instead of the
    wrapper's gather/compact). A class that defines neither advertises
    nothing and is never called, so new hooks cannot re-introduce the
    AttributeError-on-distributed-levels class of bug PR 5 fixed."""
    fn = getattr(type(level), "supports_fusion", None)
    if fn is None:
        return ()
    return fn(level, data)


def _smooth_restrict(amg, level, data, b, x, sweeps: int):
    """Presmooth + restriction: with cycle_fusion, aggregation/DIA
    levels emit the segment-summed coarse rhs from the presmoother
    kernel's epilogue (ops/smooth.py) — the residual never round-trips
    HBM and `level.restrict` disappears from the trace — classical
    DIA levels do the same through their WEIGHTED row-segment slabs
    (bc = R r summed inside the kernel, general CSR interpolation),
    and distributed DIA levels run the halo-folded per-shard kernel
    (distributed/fused.py) before their explicit sharded restriction.
    Everything else (cycle_fusion=0, non-DIA levels, unsupported
    layouts) composes exactly the prior smooth_residual -> restrict
    pair."""
    if amg.cycle_fusion and sweeps > 0 and \
            "restrict" in _fusion_caps(level, data):
        out = level.restrict_fused(data, b, x, sweeps)
        if out is not None:
            return out
    x, r = _smooth_residual(level, data, b, x, sweeps)
    return x, level.restrict(data, r)


def _prolongate_smooth(amg, level, data, b, x, xc, sweeps: int,
                       want_dot: bool = False):
    """Prolongation + correction + postsmooth: with cycle_fusion,
    aggregation AND classical DIA levels fold x + P xc into the
    postsmoother kernel's first application (ops/smooth.py —
    aggregate-id gather or the weighted multi-entry CSR-row gather),
    removing the correction add's full-vector pass. Falls back to the
    prior x + prolongate -> smooth compose bit-for-bit.

    With want_dot (the cycle-borne reduction, Krylov shell fusion) the
    return is (x', dot) where dot = x'.b from the postsmoother kernel's
    epilogue — PCG reads it as r.z since the cycle's rhs is r and its
    output is z — or (x', None) when no fused hook carries it; the
    want_dot kwarg is only passed to level hooks when True, so hook
    signatures that predate it keep working un-updated."""
    if amg.cycle_fusion and sweeps > 0 and \
            "prolongate" in _fusion_caps(level, data):
        if want_dot:
            out = level.prolongate_smooth(data, b, x, xc, sweeps,
                                          want_dot=True)
        else:
            out = level.prolongate_smooth(data, b, x, xc, sweeps)
        if out is not None:
            return out
    x = x + level.prolongate(data, xc)
    x = _smooth(level, data, b, x, sweeps)
    return (x, None) if want_dot else x


def apply_coarse_solver(cs, data, bc, xc, coarsest_sweeps: int):
    """Coarsest-level dispatch (launchCoarseSolver analog,
    include/amg_level.h:229-242). Relaxation-type coarse solvers run
    `coarsest_sweeps` sweeps (reference parameter); direct/Krylov coarse
    solvers use their own apply. Shared with the distributed coarse
    solver so both paths stay in lockstep."""
    if cs.name in ("NOSOLVER", "DUMMY"):
        # Dummy_Solver zero-fills x (dummy_solver.cu:22-31): NOSOLVER as
        # coarse solver means *no coarse correction*, not identity —
        # injecting the raw coarse residual destabilizes the cycle
        return xc
    if cs.is_smoother and cs.name != "DENSE_LU_SOLVER":
        return cs.smooth(data, bc, xc, coarsest_sweeps)
    return cs.apply(data, bc)


def _coarse_solve(amg, data, bc, xc):
    if bc.dtype == jnp.bfloat16:
        # the coarse tail stays f32+ (precision.py policy keeps the
        # coarse-solver payload at f32): a bf16 cycle upcasts the
        # coarse rhs around the solve and rounds the correction back
        out = apply_coarse_solver(
            amg.coarse_solver, data["coarse"],
            bc.astype(jnp.float32), xc.astype(jnp.float32),
            amg.coarsest_sweeps)
        return out.astype(bc.dtype)
    return apply_coarse_solver(amg.coarse_solver, data["coarse"], bc, xc,
                               amg.coarsest_sweeps)


def _cycle(amg, shape: str, data, lvl: int, b, x, want_dot: bool = False):
    """FixedCycle::cycle analog. `shape` in {V, W, F}; recursion count per
    level: V=1, W=2, F=(F then V). want_dot asks the ENTRY level's final
    kernel (postsmoother or whole-cycle VMEM tail) for the x'.b dot
    epilogue; recursion below the entry level never requests it."""
    levels = amg.levels
    if lvl == len(levels):
        out = _coarse_solve(amg, data, b, x)
        return (out, None) if want_dot else out
    # convergence diagnostics (telemetry/diagnostics.py): while a probe
    # cycle is being traced, record the level's stage residual norms
    # and compose the correction/postsmooth boundary explicitly so each
    # stage exists to measure. `rec` is None for every normal cycle
    # trace — the probe is a separate trace at the end of the solve
    # program, so the solve iterations keep their fused kernels.
    rec = _diag.current()
    if amg.cycle_fusion and rec is None:
        # VMEM-resident coarse tail: when every level from here down
        # fits VMEM together, the whole sub-cycle (smooth -> restrict
        # -> ... -> coarsest solve -> ... -> prolongate -> smooth) is
        # ONE pallas_call instead of ~10 tiny dispatches per cycle
        from ..ops.smooth import coarse_tail_cycle
        out = coarse_tail_cycle(amg, shape, data, lvl, b, x,
                                want_dot=want_dot)
        if out is not None:
            return out
    level = levels[lvl]
    ldata = data["levels"][lvl]
    if rec is not None:
        rec.record(lvl, 0, _level_A(ldata), x, b)
    x, bc = _smooth_restrict(amg, level, ldata, b, x,
                             amg._sweeps(lvl, pre=True))
    if rec is not None:
        rec.record(lvl, 1, _level_A(ldata), x, b)
    xc = jnp.zeros_like(bc)
    if shape == "V":
        xc = _cycle(amg, "V", data, lvl + 1, bc, xc)
    elif shape == "W":
        xc = _cycle(amg, "W", data, lvl + 1, bc, xc)
        if lvl + 1 < len(levels):   # second visit (W shape)
            xc = _cycle(amg, "W", data, lvl + 1, bc, xc)
    elif shape == "F":
        xc = _cycle(amg, "F", data, lvl + 1, bc, xc)
        if lvl + 1 < len(levels):   # F = one F-visit then one V-visit
            xc = _cycle(amg, "V", data, lvl + 1, bc, xc)
    else:
        raise ValueError(f"unknown fixed cycle {shape!r}")
    if rec is not None:
        x = x + level.prolongate(ldata, xc)
        rec.record(lvl, 2, _level_A(ldata), x, b)
        x = _smooth(level, ldata, b, x, amg._sweeps(lvl, pre=False))
        rec.record(lvl, 3, _level_A(ldata), x, b)
        return (x, None) if want_dot else x
    return _prolongate_smooth(amg, level, ldata, b, x, xc,
                              amg._sweeps(lvl, pre=False),
                              want_dot=want_dot)


def _kcycle(amg, data, lvl: int, b, x, flex: bool):
    """CG / CGF cycle (cg_cycle.cu, cg_flex_cycle.cu): the coarse-grid
    correction is accelerated by `cycle_iters` steps of (flexible) CG
    whose preconditioner is the next-coarser cycle."""
    levels = amg.levels
    if lvl == len(levels):
        return _coarse_solve(amg, data, b, x)
    level = levels[lvl]
    ldata = data["levels"][lvl]
    rec = _diag.current()
    if rec is not None:
        rec.record(lvl, 0, _level_A(ldata), x, b)
    x, bc = _smooth_restrict(amg, level, ldata, b, x,
                             amg._sweeps(lvl, pre=True))
    if rec is not None:
        rec.record(lvl, 1, _level_A(ldata), x, b)
    Ac_data_lvl = lvl + 1

    def M(v):
        return _kcycle(amg, data, Ac_data_lvl, v, jnp.zeros_like(v), flex)

    def Ac_mv(v):
        if Ac_data_lvl == len(levels):
            if v.dtype == jnp.bfloat16:
                # the coarsest operator stays f32+ under a bf16 cycle
                # (precision policy) — upcast the matvec and round
                # back so the K-cycle recurrence keeps one dtype
                return spmv_coarsest(
                    amg, data, v.astype(jnp.float32)).astype(v.dtype)
            return spmv_coarsest(amg, data, v)
        # matrix-free coarse levels materialize in-trace for the
        # K-cycle matvec (VPU work instead of a resident slab)
        return spmv(_level_A(data["levels"][Ac_data_lvl]), v)

    # a few steps of preconditioned CG on the coarse equation
    xc = jnp.zeros_like(bc)
    rc = bc
    z = M(rc)
    p = z
    rz = blas.dot(rc, z)
    k_iters = max(amg.cycle_iters, 1)
    for it in range(k_iters):
        Ap = Ac_mv(p)
        denom = blas.dot(p, Ap)
        alpha = rz / jnp.where(denom == 0, 1.0, denom) * (denom != 0)
        xc = xc + alpha * p
        rc_old = rc
        rc = rc - alpha * Ap
        if it + 1 == k_iters:
            break   # last update: skip the unused trailing M()/beta/p
        z = M(rc)
        rz_new = blas.dot(rc, z)
        if flex:
            # flexible (Polak-Ribiere) beta tolerates a varying M
            num = blas.dot(rc - rc_old, z)
        else:
            # Fletcher-Reeves: the beta numerator IS the next rz —
            # reuse it instead of computing the same reduction twice
            num = rz_new
        beta = num / jnp.where(rz == 0, 1.0, rz) * (rz != 0)
        rz = rz_new
        p = z + beta * p
    if rec is not None:
        x = x + level.prolongate(ldata, xc)
        rec.record(lvl, 2, _level_A(ldata), x, b)
        x = _smooth(level, ldata, b, x, amg._sweeps(lvl, pre=False))
        rec.record(lvl, 3, _level_A(ldata), x, b)
        return x
    return _prolongate_smooth(amg, level, ldata, b, x, xc,
                              amg._sweeps(lvl, pre=False))


def spmv_coarsest(amg, data, v):
    """SpMV with the coarsest matrix (its CSR lives in the coarse-solver
    data only when that solver keeps it; fall back to the stored matrix).
    Under a DistributedCoarseSolver the coarsest matrix is replicated
    while v is shard-local: gather, apply, keep the local slice (the
    K-cycle's coarse-grid matvec, exact_coarse_solve layout)."""
    cd = data["coarse"]
    cs = amg.coarse_solver
    from ..distributed.amg import DistributedCoarseSolver
    if isinstance(cs, DistributedCoarseSolver):
        return cs.gather_apply_slice(lambda bc: spmv(cd["A"], bc), v)
    return spmv(cd["A"], v)


def run_cycle(amg, name: str, data, b, x):
    name = name.upper()
    if name in ("V", "W", "F"):
        return _cycle(amg, name, data, 0, b, x)
    if name == "CG":
        return _kcycle(amg, data, 0, b, x, flex=False)
    if name == "CGF":
        return _kcycle(amg, data, 0, b, x, flex=True)
    raise ValueError(f"unknown cycle {name!r}")


def run_cycle_dot(amg, name: str, data, b, x):
    """Cycle application that ALSO asks for the x'.b dot epilogue from
    the cycle's last kernel (the Krylov shell's cycle-borne r.z).
    Returns (x', dot) with dot=None whenever the cycle cannot carry it
    — K-cycles, diagnostics probes, unfused last levels — so callers
    fall back to an explicit reduction."""
    name = name.upper()
    if name in ("V", "W", "F"):
        return _cycle(amg, name, data, 0, b, x, want_dot=True)
    return run_cycle(amg, name, data, b, x), None
