"""AMG as a Solver (registry name "AMG").

Analog of AlgebraicMultigrid_Solver (src/solvers/
algebraic_multigrid_solver.cu:34-59): setup delegates to AMG::setup, one
solve iteration is one multigrid cycle.
"""
from __future__ import annotations

import jax.numpy as jnp

from .. import registry
from ..solvers.base import Solver
from .hierarchy import AMG


@registry.solvers.register("AMG")
class AlgebraicMultigridSolver(Solver):
    is_smoother = False

    def __init__(self, cfg, scope="default", name="AMG"):
        super().__init__(cfg, scope, name)
        self.amg = AMG(cfg, scope)

    def solver_setup(self):
        self.amg.setup(self.A)

    def solver_resetup(self):
        self.amg.resetup(self.A)

    def _resetup_kept_static(self):
        # the hierarchy's depth/level shapes depend on the values; only
        # the fused value-only resetup guarantees they were kept
        return bool(getattr(self.amg, "_last_resetup_value_only", False))

    def solve_data(self):
        d = super().solve_data()
        d["amg"] = self.amg.solve_data()
        return d

    def computes_residual(self):
        return False

    def solve_init(self, data, b, x, r):
        return self._guard_init()

    def apply_dot(self, data, rhs):
        """One cycle with the x'.rhs dot riding its last kernel's
        epilogue (AMG.cycle_dot). Only the single-cycle shape
        qualifies: apply() with max_iters > 1 loops cycles whose
        intermediate outputs the epilogue cannot represent, so that
        declines to (apply, None) and callers reduce explicitly.
        (apply() never monitors and its breakdown flag is dead, so
        max_iters is the whole gate.)"""
        if self.max_iters != 1:
            return self.apply(data, rhs), None
        return self.amg.cycle_dot(data["amg"], rhs,
                                  jnp.zeros_like(rhs))

    def solve_iteration(self, data, b, st):
        out = dict(st)
        x_new = self.amg.cycle(data["amg"], b, st["x"])
        out["x"] = x_new
        if self.health_guards:
            # a non-finite cycle output means the hierarchy itself is
            # broken (singular coarse factor, corrupted Galerkin
            # values): BREAKDOWN, not a NaN storm at max_iters. Unused
            # (and DCE'd by XLA) when AMG runs as a preconditioner.
            out["breakdown"] = ~jnp.all(jnp.isfinite(x_new))
        return out

    def grid_stats(self):
        return self.amg.grid_stats()

    def grid_stats_dict(self):
        return self.amg.grid_stats_dict()
