"""AMG hierarchy driver.

Analog of AMG<> + the AMG_Level linked list (src/amg.cu:152-421 setup
loop, include/amg_level.h:51). Redesign for XLA:

- setup is host-orchestrated, device-math (each level's coarsening is
  eager jnp with concrete shapes);
- the finished hierarchy is a *list of level pytrees* with static shapes,
  so one multigrid cycle traces into a single fused XLA program with the
  recursion unrolled over the (static) depth;
- levels own their smoother's solve-data; the coarsest level owns the
  coarse solver's data (DENSE_LU by default).
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import jax
import numpy as np

from .. import registry
from ..config import Config
from ..errors import BadConfigurationError
from ..matrix import CsrMatrix


def _record_route(route: str, A):
    """Flight-recorder trail of the setup-routing decision (full build
    vs value/structure resetup vs restored-from-snapshot) — ONE event
    shape for all four routes (telemetry/flightrec.py; lazy import:
    telemetry must stay importable without the amg package)."""
    from ..telemetry import flightrec
    flightrec.record("resetup.route", route=route,
                     rows=int(A.num_rows))


class AMGLevel:
    """One hierarchy level: fine matrix + transfer operators + smoother.

    Subclasses (aggregation / classical / energymin) implement
    create_coarse_vertices / create_coarse_matrix / restrict / prolongate
    (the pure-virtual interface of include/amg_level.h:51-215).
    """

    algorithm = "?"

    def __init__(self, A: CsrMatrix, cfg: Config, scope: str,
                 level_index: int):
        self.A = A
        self.cfg = cfg
        self.scope = scope
        self.level_index = level_index
        self.smoother = None           # set by AMG.setup
        self.coarse_size: Optional[int] = None

    # -- build interface -------------------------------------------------
    def create_coarse_vertices(self):
        raise NotImplementedError

    def create_coarse_matrix(self) -> CsrMatrix:
        raise NotImplementedError

    def reuse_structure(self, old: "AMGLevel"):
        """Adopt the coarsening structure of a previous setup of this
        level (structure_reuse_levels); create_coarse_matrix then only
        recomputes the Galerkin product against the new coefficients."""
        raise NotImplementedError(
            f"{type(self).__name__} does not support structure reuse")

    # -- persistent structure (serving/hstore.py) ------------------------
    def structure_snapshot(self):
        """(meta, arrays) capturing exactly what `reuse_structure`
        reads — the host-persistable form of this level's coarsening
        structure (deterministic from the sparsity pattern, ROADMAP
        3d). `meta` is JSON-able scalars, `arrays` numpy arrays. None
        when this level class does not support persistence (the store
        then skips the whole hierarchy)."""
        return None

    @classmethod
    def structure_restore(cls, meta, arrays):
        """Rebuild a 'ghost' level from a persisted snapshot: an
        instance carrying ONLY the attributes `reuse_structure` reads
        (plus A.num_rows for the reuse-loop compatibility check) — it
        is never solved with, only adopted from."""
        raise NotImplementedError(
            f"{cls.__name__} does not support structure restore")

    @classmethod
    def _ghost(cls, num_rows: int):
        import types
        g = cls.__new__(cls)
        g.A = types.SimpleNamespace(num_rows=int(num_rows))
        g.smoother = None
        return g

    # -- solve-phase (pure) ----------------------------------------------
    def level_data(self) -> Dict[str, Any]:
        # slim matrices: the cycle only SpMVs against level operators,
        # so layout-only views keep multi-GB unused CSR payloads out of
        # the solve program's HBM arguments
        A = self.A.slim_for_spmv()
        d = {"A": A}
        if self.smoother is not None:
            # the smoother's solve_data already slims its own A when its
            # sweeps only SpMV (Solver.slim_A_ok)
            d["smoother"] = self.smoother.solve_data()
            st = d["smoother"].get("stencil") if isinstance(
                d["smoother"], dict) else None
            if st is not None:
                # matrix-free level: the LEVEL operator view drops its
                # value slab too (the stencil payload is the operator;
                # consumers that need a matrix rebuild it in-trace via
                # ops/stencil.level_operator)
                from ..ops.stencil import mf_slim
                d["A"] = mf_slim(A)
                d["stencil"] = st
        return d

    def restrict(self, data, r):
        raise NotImplementedError

    def prolongate(self, data, xc):
        raise NotImplementedError

    # -- cycle fusion hooks (amg/cycles.py) ------------------------------
    # The cycle NEVER calls restrict_fused / prolongate_smooth blindly:
    # it first consults `supports_fusion(data)` (cycles._fusion_caps,
    # resolved through the CLASS so `__getattr__`-delegating wrappers
    # advertise nothing unless they define the surface explicitly) and
    # invokes a hook only when its capability is advertised — a level
    # class that does not implement a future hook is simply skipped
    # instead of raising. Aggregation levels override the hooks with
    # the fused grid-transfer kernels (presmooth+restrict in one
    # pallas_call, prolongate+correction folded into the postsmoother's
    # first application); classical levels do the same through the
    # WEIGHTED row-segment slabs of their general CSR interpolation
    # (amg/classical). Distributed levels advertise NOTHING here on
    # purpose: their fusion — the halo-folded per-shard smoother
    # kernel (distributed/fused.py) — rides inside the smoother's own
    # smooth/smooth_residual dispatch (ops/smooth.fused_smooth sees the
    # "dist_fused" payload), so the plain compose the cycle falls back
    # to IS the fused distributed path; transfer-space-changing
    # wrappers (consolidation) need no overrides at all.
    FUSION_CAPS = frozenset({"restrict", "prolongate"})

    def supports_fusion(self, data):
        """Capabilities of the fused cycle hooks for this level's
        solve-data: a collection drawn from {"restrict", "prolongate"}
        (empty = always compose unfused)."""
        return ()

    def restrict_fused(self, data, b, x, sweeps: int):
        """(x', bc) with the presmooth+residual fused into one kernel,
        or None when unsupported."""
        return None

    def prolongate_smooth(self, data, b, x, xc, sweeps: int,
                          want_dot: bool = False):
        """smooth(b, x + P xc) with the correction folded into the
        postsmoother's kernel prologue, or None when unsupported. With
        want_dot, (x', dot) where dot is the kernel's x'.b epilogue
        (the Krylov shell's cycle-borne r.z) or None when the fused
        form cannot carry it."""
        return None


_PENDING = object()    # _put_cache placeholder: (src, (_PENDING, fut, i))


class AMG:
    """Hierarchy owner + setup loop (AMG<>::setup analog, src/amg.cu)."""

    def __init__(self, cfg: Config, scope: str = "default"):
        self.cfg = cfg
        self.scope = scope
        self.algorithm = str(cfg.get("algorithm", scope)).upper()
        self.max_levels = int(cfg.get("max_levels", scope))
        self.min_coarse_rows = int(cfg.get("min_coarse_rows", scope))
        self.min_fine_rows = int(cfg.get("min_fine_rows", scope))
        self.coarsen_threshold = float(cfg.get("coarsen_threshold", scope))
        self.presweeps = int(cfg.get("presweeps", scope))
        self.postsweeps = int(cfg.get("postsweeps", scope))
        self.finest_sweeps = int(cfg.get("finest_sweeps", scope))
        self.coarsest_sweeps = int(cfg.get("coarsest_sweeps", scope))
        self.dense_lu_num_rows = int(cfg.get("dense_lu_num_rows", scope))
        self.cycle_name = str(cfg.get("cycle", scope)).upper()
        self.cycle_iters = int(cfg.get("cycle_iters", scope))
        self.cycle_fusion = bool(int(cfg.get("cycle_fusion", scope)))
        self.cycle_fusion_tail_rows = int(
            cfg.get("cycle_fusion_tail_rows", scope))
        # matrix-free GEO levels (ops/stencil.py): auto = only on a
        # real TPU backend (CPU rigs stay bit-identical to the slab
        # build), 1 = force the detector everywhere, 0 = never
        self.matrix_free = str(cfg.get("matrix_free", scope))
        # effective hierarchy/cycle precision: the shared policy
        # resolves amg_precision / solve_precision / tpu_dtype into one
        # answer (precision.py) and rejects contradictory combinations
        from ..precision import resolve_precision
        self.precision_policy = resolve_precision(cfg, scope)
        self.precision = self.precision_policy.name
        self.print_grid_stats = bool(cfg.get("print_grid_stats", scope))
        self.intensive_smoothing = bool(cfg.get("intensive_smoothing", scope))
        self.host_setup = str(cfg.get("amg_host_setup", scope))
        self.setup_backend = str(cfg.get("setup_backend", scope)).lower()
        self.setup_device_min_rows = int(
            cfg.get("setup_device_min_rows", scope))
        self.convergence_analysis = int(cfg.get("convergence_analysis",
                                                scope))
        # convergence diagnostics (telemetry/diagnostics.py): when on,
        # the solve driver appends one instrumented probe cycle whose
        # per-level stage norms ride the packed stats
        self.diagnostics = bool(int(cfg.get("diagnostics", scope)))
        self.levels: List[AMGLevel] = []
        self.coarse_solver = None
        self.setup_time = 0.0
        self._data_cache = None
        self._ship_device = None
        # host-setup transfer overlap: id(host leaf) -> (host leaf,
        # device leaf); filled by _prefetch_level as levels finish
        # building so the tunnel transfer hides behind the remaining
        # host compute
        self._put_cache: Dict[int, tuple] = {}
        self._ship_pool = None
        # which implementations the last setup used ("host" pull-and-ship,
        # "device" forced pipeline, "auto" residency-driven)
        self._setup_backend_used = None
        # distributed setup builds the replicated tail through
        # _build_levels but owns its smoother assignment
        self._defer_smoothers = False

    # -- setup -----------------------------------------------------------
    def _host_setup_device(self, A: CsrMatrix):
        """Host-CPU hierarchy construction (the TPU answer to the
        reference's host-level machinery, src/amg.cu:152-421): the
        classical/energymin setup is hundreds of small eager index ops,
        each costing a full device round trip on a remote accelerator —
        built on the host CPU backend the same code runs in milliseconds,
        and the finished hierarchy ships to the accelerator once (cached
        solve-data). mode: auto (host when the default backend is a
        remote accelerator and the algorithm's setup is index-heavy),
        always, never. `setup_backend` outranks `amg_host_setup`:
        device never pulls, host always does (on an accelerator)."""
        import jax
        if self.setup_backend == "device":
            return None          # device-resident pipeline: never pull
        mode = self.host_setup
        if mode == "never" and self.setup_backend != "host":
            return None
        try:
            cpu = jax.devices("cpu")[0]
        except RuntimeError:
            return None
        ambient = jax.config.jax_default_device or jax.devices()[0]
        if ambient.platform == "cpu":
            return None          # already on host
        if self.setup_backend == "host" or mode == "always" \
                or self.algorithm in ("CLASSICAL", "ENERGYMIN"):
            return cpu
        return None

    def adopt_structure(self, ghost_levels):
        """Install a persisted structure snapshot (serving/hstore.py):
        the NEXT setup() call routes through the structure-reuse
        rebuild (`_resetup_impl` — Galerkin values + smoothers only,
        the cheap path) instead of a full coarsening, counted as
        amg.setup.restored. One-shot: consumed (or discarded on a
        shape mismatch) by that setup."""
        self._ghost_levels = list(ghost_levels)

    def setup(self, A: CsrMatrix):
        import jax
        from ..telemetry import metrics as _tm
        ghosts = getattr(self, "_ghost_levels", None)
        if ghosts is not None:
            self._ghost_levels = None
            if ghosts and ghosts[0].A.num_rows == A.num_rows:
                return self._setup_restored(A, ghosts)
        _tm.inc("amg.setup.full")
        _record_route("full", A)
        t0 = time.perf_counter()
        self.levels = []
        self._data_cache = None
        self._put_cache = {}
        self._l0_seed = None     # dropped unless this setup re-registers
        self._resetup_precast = None
        self._vr_plan = None     # value-resetup plan re-derives lazily
        self._last_resetup_value_only = False
        self._tail_entry_level = None   # re-recorded at cycle trace time
        self._telemetry_level_cache = None
        host = self._host_setup_device(A)
        if host is not None:
            self._setup_backend_used = "host"
            # decide BEFORE init: the SpMV-layout build is itself eager
            # device work that belongs on the host in this mode; ship to
            # the device the caller's context selected
            self._ship_device = (jax.config.jax_default_device
                                 or jax.devices()[0])
            # cast OUTSIDE the host default-device block: orig's arrays
            # are uncommitted accelerator data, and an astype dispatched
            # under default_device(cpu) would pull them over the tunnel
            from ..profiling import trace_region
            l0_dev = self._l0_device_cast(A)
            with jax.default_device(host):
                with trace_region("amg.host_pull"):
                    Af = self._pull_host_l0(A)
                self._register_device_l0(A, Af, l0_dev)
                self._build_levels_checked(Af, 0)
                self._finalize_setup(t0)
            return self
        self._ship_device = None
        # "host" here means setup_backend=host on a host-ambient rig
        # (no pull needed — the build IS on the host)
        self._setup_backend_used = self.setup_backend
        from ..matrix import forced_device_setup
        from ..profiling import trace_region
        with forced_device_setup(self._level_device_forced(A.num_rows)):
            with trace_region("amg.l0_layout"):
                Af = A if A.initialized else A.init()
        self._build_levels_checked(Af, 0)
        self._finalize_setup(t0)
        return self

    def _setup_restored(self, A: CsrMatrix, ghosts):
        """setup() against a persisted structure snapshot: install the
        ghost levels as the reuse source and run the structure-reuse
        rebuild — values-only Galerkin + fresh smoothers, no coarsening
        selection. The restart path's answer to the 17 s cold setup."""
        import jax
        from ..profiling import trace_region
        from ..telemetry import metrics as _tm
        _tm.inc("amg.setup.restored")
        _record_route("restored", A)
        self.levels = list(ghosts)
        self._data_cache = None
        self._put_cache = {}
        self._l0_seed = None
        self._resetup_precast = None
        self._vr_plan = None
        self._last_resetup_value_only = False
        self._tail_entry_level = None
        self._telemetry_level_cache = None
        host = self._host_setup_device(A)
        if host is not None:
            self._setup_backend_used = "host"
            self._ship_device = (jax.config.jax_default_device
                                 or jax.devices()[0])
            l0_dev = self._l0_device_cast(A)
            with jax.default_device(host):
                with trace_region("amg.host_pull"):
                    Af = self._pull_host_l0(A)
                self._register_device_l0(A, Af, l0_dev)
                return self._resetup_impl(Af, -1)
        self._ship_device = None
        self._setup_backend_used = self.setup_backend
        Af = A if A.initialized else A.init()
        return self._resetup_impl(Af, -1)

    def _level_device_forced(self, n: int) -> bool:
        """setup_backend=device forces the jnp/device implementations
        for this level; levels under setup_device_min_rows lift the
        forcing (dispatch overhead loses against tiny host numpy)."""
        return (self.setup_backend == "device"
                and self._ship_device is None
                and n >= self.setup_device_min_rows)

    def _pull_numpy(self, A: CsrMatrix) -> CsrMatrix:
        """Pull a (layout-stripped) matrix's arrays to host numpy. The
        host hierarchy build runs on numpy end to end: every native
        component (PMIS/D2/RAP/SWELL) consumes and produces numpy, so
        staying off jax CPU arrays avoids one full copy of every array
        at every native-call boundary. Arrays uploaded from host data
        resolve through the retained host mirror (matrix.py
        _HOST_MIRROR) — no accelerator->host transfer at all."""
        import dataclasses
        from ..matrix import host_mirror_asarray as pull
        return dataclasses.replace(
            A, row_offsets=pull(A.row_offsets),
            col_indices=pull(A.col_indices),
            values=pull(A.values),
            diag=None if A.diag is None else pull(A.diag))

    # L0 SpMV-layout payload fields and which of them carry float data
    # (the others are structure arrays the amg_precision cast ignores)
    _L0_PAYLOADS = ("dia_vals", "ell_vals", "ell_cols", "swell_vals",
                    "swell_cols", "swell_c0row", "swell_nchunk")

    def _pull_host_l0(self, A: CsrMatrix) -> CsrMatrix:
        """Host-numpy finest-level matrix for the host build. When the
        caller's device matrix already carries its SpMV layout (DIA/
        ELL/SWELL) with retained host mirrors, the layout arrays are
        REUSED instead of rebuilt — the pre-layout strip + numpy
        re-pack only runs when some piece cannot be served host-side."""
        import dataclasses as _dc
        from ..matrix import host_arrays
        if A.initialized:
            fields = ("row_offsets", "col_indices", "values", "diag",
                      "row_ids", "diag_idx") + self._L0_PAYLOADS
            arrs = host_arrays(*[getattr(A, f) for f in fields])
            if arrs is not None:
                return _dc.replace(A, **dict(zip(fields, arrs)))
        Af = self._pull_numpy(self._strip_layouts(A))
        return Af.init()

    def _l0_device_cast(self, orig: CsrMatrix):
        """Device twins of the caller's finest-level SpMV-layout
        payloads: precision casts for the float slabs (dispatched on
        the caller's device — must run OUTSIDE the host default-device
        block, see setup()), the resident arrays themselves for the
        integer structure."""
        if orig is None or not orig.initialized:
            return None
        import jax.numpy as jnp
        out = {}
        for f in self._L0_PAYLOADS:
            v = getattr(orig, f)
            if v is None:
                continue
            out[f] = (self._cast_leaf(v)
                      if jnp.issubdtype(v.dtype, jnp.inexact) else v)
        return out or None

    def _register_device_l0(self, orig: CsrMatrix, Af_host: CsrMatrix,
                            dev):
        """The caller's device matrix already holds the finest level's
        SpMV layout; pre-seeding the transfer cache with its (precision-
        cast, cast ON device) payloads makes the ship skip the arrays
        that are both the largest and already resident — a host-held
        L0 layout never crosses the wire. A payload seeds when the host
        array IS the device array's retained mirror (layout reused by
        _pull_host_l0), or — for DIA — when the host rebuild provably
        produced the same packing (identical offset tuple)."""
        self._l0_seed = None
        if dev is None:
            return
        from ..matrix import _HOST_MIRROR
        seeds = []
        for f, d in dev.items():
            h = getattr(Af_host, f, None)
            if h is None or not isinstance(h, np.ndarray):
                continue
            ok = h is _HOST_MIRROR.get(id(getattr(orig, f)))
            if not ok and f == "dia_vals":
                ok = Af_host.dia_offsets == orig.dia_offsets
            if ok:
                seeds.append((h, d))
        if seeds:
            self._l0_seed = tuple(seeds)
            self._seed_put_cache()

    def _seed_put_cache(self):
        """(Re)apply the L0 device-payload seeds after any _put_cache
        reset (resetup, abandoned GEO builds)."""
        for src, dev in getattr(self, "_l0_seed", None) or ():
            self._put_cache[id(src)] = (src, dev)

    @staticmethod
    def _strip_layouts(A: CsrMatrix) -> CsrMatrix:
        """Drop SpMV auxiliaries before pulling a device matrix to the
        host: the host setup rebuilds them in numpy anyway, and the
        accelerator->host transfer of row_ids/ELL/DIA payloads costs
        multiple seconds through a tunnel."""
        import dataclasses
        return dataclasses.replace(
            A, row_ids=None, diag_idx=None, ell_cols=None, ell_vals=None,
            dia_offsets=None, dia_vals=None, swell_cols=None,
            swell_vals=None, swell_c0row=None, swell_nchunk=None,
            swell_w128=0, initialized=False)

    def _build_levels_checked(self, Af: CsrMatrix, lvl: int):
        """_build_levels with the GEO fast path's wrap checks deferred
        to ONE batched device fetch (each per-level bool() costs a full
        tunnel round trip); the rare failure rebuilds without the fast
        path."""
        from .aggregation.galerkin import (deferred_wrap_checks,
                                           geo_dia_disabled)
        base = list(self.levels)
        with deferred_wrap_checks() as flush:
            self._build_levels(Af, lvl)
            if flush():
                self.levels = base
                # drop transfers prefetched for the abandoned build (they
                # pin both host and HBM copies of every shipped level)
                self._put_cache = {}
                self._seed_put_cache()
                with geo_dia_disabled():
                    self._build_levels(Af, lvl)

    def resetup(self, A: CsrMatrix):
        """Coefficient-replace re-setup honoring structure_reuse_levels
        (AMG_Setup structure-reuse path, src/amg.cu:232-262): the first
        `structure_reuse_levels` levels (-1 = all) keep their coarsening
        structure (aggregates / CF-split + transfer operators) and only
        recompute the Galerkin products; deeper levels rebuild fully."""
        reuse = int(self.cfg.get("structure_reuse_levels", self.scope))
        if reuse == 0 or not self.levels or \
                A.num_rows != self.levels[0].A.num_rows:
            return self.setup(A)
        self._last_resetup_value_only = False
        from ..telemetry import metrics as _tm
        if (reuse < 0 or reuse >= len(self.levels)) \
                and self._ship_device is None:
            from .value_resetup import try_value_resetup
            from ..profiling import trace_region
            with trace_region("amg.value_resetup"):
                if try_value_resetup(self, A):
                    self._last_resetup_value_only = True
                    _tm.inc("amg.resetup.value")
                    _record_route("value", A)
                    return self
        _tm.inc("amg.resetup.structure")
        _record_route("structure", A)
        # a structure resetup rebuilds levels and retraces the cycle:
        # the recorded tail boundary and the memoized report level
        # table are for the OLD hierarchy (the value-only path above
        # keeps both valid — structure and traces survive)
        self._tail_entry_level = None
        self._telemetry_level_cache = None
        self._data_cache = None
        if self._ship_device is not None:
            host = jax.devices("cpu")[0]
            l0_dev = self._l0_device_cast(A)        # see setup()
            with jax.default_device(host):
                from ..profiling import trace_region
                with trace_region("amg.host_pull"):
                    Af = self._pull_host_l0(A)
                # refresh the L0 seeds: a rebuilt host hierarchy has
                # NEW layout arrays (stale seeds would both miss the
                # ship skip and pin the previous payloads for the
                # object's lifetime)
                self._register_device_l0(A, Af, l0_dev)
                return self._resetup_impl(Af, reuse)
        Af = A if A.initialized else A.init()
        return self._resetup_impl(Af, reuse)

    def _resetup_impl(self, Af: CsrMatrix, reuse: int):
        t0 = time.perf_counter()
        k = len(self.levels) if reuse < 0 else min(reuse, len(self.levels))
        old_levels, self.levels = self.levels, []
        self._resetup_precast = None
        self._vr_plan = None
        self._put_cache = {}
        self._seed_put_cache()
        from .aggregation.galerkin import (deferred_wrap_checks,
                                           geo_dia_disabled)

        from ..matrix import forced_device_setup

        def reuse_loop(Af):
            lvl = 0
            while lvl < k:
                old = old_levels[lvl]
                if Af.num_rows != old.A.num_rows:
                    break
                level = type(old)(Af, self.cfg, self.scope, lvl)
                level.reuse_structure(old)
                forced = self._level_device_forced(Af.num_rows)
                from ..matrix import host_resident
                level.built_backend = "device" if forced or \
                    not host_resident(Af.row_offsets, Af.values) else "host"
                with forced_device_setup(forced):
                    Ac = level.create_coarse_matrix()
                    self.levels.append(level)
                    if not self._defer_smoothers:
                        self._attach_level_smoother(level)
                    self._prefetch_level(level)
                    Af = (Ac.build_spmv_layout() if Ac.initialized
                          else Ac.init())
                lvl += 1
            return Af, lvl

        Af0 = Af
        with deferred_wrap_checks() as flush:
            Af, lvl = reuse_loop(Af0)
            failed = flush()
        if failed:
            # rare: the new coefficients break the GEO fast path's
            # geometric invariant — redo the reuse loop with the generic
            # relabel Galerkin (same reused aggregates, one extra pass)
            self.levels = []
            self._put_cache = {}
            self._seed_put_cache()
            with geo_dia_disabled():
                Af, lvl = reuse_loop(Af0)
        self._build_levels_checked(Af, lvl)
        self._finalize_setup(t0)
        return self

    def _build_levels(self, Af: CsrMatrix, lvl: int):
        from ..matrix import forced_device_setup, host_resident
        from ..profiling import trace_region
        level_cls = registry.amg_levels.get(self.algorithm)
        while True:
            n = Af.num_rows
            stop = (lvl + 1 >= self.max_levels
                    or n <= max(self.min_coarse_rows, 1)
                    or n < self.min_fine_rows
                    or n <= self.dense_lu_num_rows and lvl > 0)
            if stop:
                break
            level = level_cls(Af, self.cfg, self.scope, lvl)
            forced = self._level_device_forced(n)
            level.built_backend = "device" if forced or not host_resident(
                Af.row_offsets, Af.values) else "host"
            with forced_device_setup(forced):
                # selector/interpolation/Galerkin phase timers live in
                # the level classes (disjoint amg.L*.{selector,strength,
                # cfsplit,interp,transposeR,rap,galerkin,...} leaves)
                level.create_coarse_vertices()
                nc = level.coarse_size
                # stalling coarsening -> stop (coarsen_threshold
                # semantics: the grid must shrink at least that factor)
                if nc <= 0 or nc >= n or \
                        (n / max(nc, 1)) < self.coarsen_threshold:
                    break
                Ac = level.create_coarse_matrix()
                # resilience fault harness: a `galerkin_perturb` spec
                # scales this level's coarse values (host-orchestrated —
                # no cached trace can replay it); inert when unarmed
                from ..resilience import faultinject as _fault
                Ac = _fault.perturb_galerkin(Ac, lvl)
                self.levels.append(level)
                # per-level pipeline: the smoother is set up as soon as
                # its level finishes, so its solve-data (and the level's
                # operators) ship while the NEXT level is coarsening.
                # Trade-off: a build abandoned by a failed deferred GEO
                # wrap check (rare — values violating the geometric
                # invariant) now discards this smoother work too and
                # pays it again on the rebuild.
                if not self._defer_smoothers:
                    self._attach_level_smoother(level)
                self._prefetch_level(level)
                with trace_region(f"amg.L{lvl}.layout"):
                    Af = (Ac.build_spmv_layout() if Ac.initialized
                          else Ac.init())
            lvl += 1
        self.coarsest_A = Af

    def _smoother_spec(self, level_index: int):
        """Smoother (name, scope) for one level: with fine_levels >= 0,
        levels < fine_levels use fine_smoother and the rest use
        coarse_smoother (the reference's fine/coarse algorithm split);
        fine_levels=-1 (default) disables the split and every level
        uses `smoother`."""
        fine_levels = int(self.cfg.get("fine_levels", self.scope))
        if fine_levels < 0:
            return self.cfg.get_solver("smoother", self.scope)
        if level_index < fine_levels:
            return self.cfg.get_solver("fine_smoother", self.scope)
        return self.cfg.get_solver("coarse_smoother", self.scope)

    # known TPU-runtime fault (README "Known limitations"): the
    # combined PCG+V-cycle program with MULTICOLOR_DILU smoothing
    # faults on single-chip TPU at 128^3 scale — every level's DILU
    # passes in isolation and the config validates through 96^3, so
    # the guard trips strictly above the validated size. The benched
    # workaround is JACOBI_L1; routing it HERE (config-validation /
    # setup time, before any trace) replaces a solve-time runtime
    # fault with a warned, counted fallback.
    DILU_TPU_FAULT_MIN_ROWS = 96 ** 3 + 1

    def _guard_known_faults(self, name: str) -> str:
        if name != "MULTICOLOR_DILU" or not self.levels:
            return name
        n_fine = self.levels[0].A.num_rows
        if n_fine < self.DILU_TPU_FAULT_MIN_ROWS:
            return name
        import jax
        if jax.default_backend() != "tpu" or jax.device_count() > 1:
            return name          # sharded/CPU DILU paths are unaffected
        if not getattr(self, "_fault_fallback_warned", False):
            # once per hierarchy: the guard fires for every level, but
            # one rerouted CONFIGURATION is one counted event — a
            # per-level count would inflate the series by the depth
            self._fault_fallback_warned = True
            from ..output import amgx_output
            from ..telemetry import metrics as _tm
            _tm.inc("resilience.config_fallback")
            amgx_output(
                f"amgx_tpu warning: MULTICOLOR_DILU at {n_fine} rows "
                f"on a single TPU chip hits a known runtime fault "
                f"(validated clean through 96^3); smoothing falls "
                f"back to JACOBI_L1 (resilience.config_fallback)\n")
        return "JACOBI_L1"

    def _attach_level_smoother(self, level: AMGLevel):
        from ..solvers.base import make_solver
        from ..profiling import trace_region
        name, scope = self._smoother_spec(level.level_index)
        name = self._guard_known_faults(name)
        level.smoother = make_solver(name, self.cfg, scope)
        level.smoother._owns_scaling = False
        # fused operand slabs emit directly in the hierarchy's
        # effective precision (ops/smooth.solver_fused_slabs): the
        # solve-data cast then finds them already narrow — no
        # full-precision twin ever materializes
        level.smoother._slab_dtype = self._PRECISIONS[self.precision]
        if getattr(level.smoother, "needs_cf_map", False) and \
                getattr(level, "cf_map", None) is not None:
            level.smoother.set_cf_map(level.cf_map)
        with trace_region(f"amg.L{level.level_index}.smoother_setup"):
            level.smoother.setup(level.A)
        self._maybe_install_stencil(level)

    def _maybe_install_stencil(self, level: AMGLevel):
        """Matrix-free install (`matrix_free` knob): when this level's
        operator is a constant-coefficient grid stencil and its
        smoother can run from coefficients alone, attach a
        StencilOperator to the smoother — its solve_data then drops
        the DIA value slab (and dinv vector / fused slabs) and every
        smooth entry routes through ops/stencil.py. `_mf_stencil` is
        ALWAYS (re)assigned so a stale stencil from a previous install
        can never survive a resetup with new (variable) values."""
        sm = level.smoother
        if sm is None:
            return
        mode = getattr(self, "matrix_free", "auto")
        on = mode == "1" or (mode == "auto"
                             and jax.default_backend() == "tpu")
        if not on or not getattr(type(sm), "supports_matrix_free",
                                 False) \
                or not getattr(sm, "fused_smoother", False):
            sm._mf_stencil = None
            return
        from ..ops.stencil import detect_stencil
        from ..profiling import trace_region
        with trace_region(f"amg.L{level.level_index}.mf_detect"):
            sm._mf_stencil = detect_stencil(
                level.A, dinv_mode=sm.matrix_free_dinv)

    def _finalize_setup(self, t0: float):
        from ..solvers.base import make_solver
        from ..profiling import trace_region
        # smoothers normally attach per level during the build (the
        # overlapped-shipping pipeline); this catches levels built by
        # paths that defer (distributed tails restore their own)
        for level in self.levels:
            if level.smoother is None:
                self._attach_level_smoother(level)
        cs_name, cs_scope = self.cfg.get_solver("coarse_solver", self.scope)
        self.coarse_solver = make_solver(cs_name, self.cfg, cs_scope)
        self.coarse_solver._owns_scaling = False
        with trace_region("amg.coarse_solver_setup"):
            self.coarse_solver.setup(self.coarsest_A)
        if self._ship_device is not None:
            # completion barrier of the per-level ship pipeline: every
            # prefetched transfer resolves before setup returns
            with trace_region("amg.ship_resolve"):
                self._resolve_put_cache()
        self.num_levels = len(self.levels) + 1
        self.setup_time = time.perf_counter() - t0
        if self.print_grid_stats:
            from ..output import amgx_printf
            amgx_printf(self.grid_stats())
        if self.convergence_analysis > 0 and self.levels:
            # convergence_analysis.cu: instrumented error-propagation
            # cycle over the first `convergence_analysis` levels
            from ..output import amgx_printf
            from .analysis import convergence_analysis
            amgx_printf(convergence_analysis(self) + "\n")

    # -- solve-phase data -------------------------------------------------
    _PRECISIONS = {"double": None, "float": "float32", "bfloat16": "bfloat16"}

    def _cast_leaf(self, leaf, dt=False):
        """Precision cast of one solve-data leaf (identity for
        structure arrays and full-precision mode). `dt` overrides the
        target dtype name — the coarse-solver subtree casts to the
        policy's f32+ coarse dtype while the levels take the full
        reduced precision."""
        import jax.numpy as jnp
        if dt is False:
            dt = self._PRECISIONS[self.precision]
        if dt is not None and hasattr(leaf, "dtype") and \
                jnp.issubdtype(leaf.dtype, jnp.inexact):
            return leaf.astype(dt)
        return leaf

    def _prefetch_leaves(self, tree):
        """Start host->device transfers of a solve-data subtree's unique
        leaves, keyed by the PRE-cast host leaf identity so solve_data
        can pick them up. The cast + device_put run on a single worker
        thread: device_put to a tunneled accelerator blocks for the
        wire time, while the build thread spends its time inside
        GIL-releasing native sweeps — threading the ship overlaps the
        two (the reference gets the same overlap from CUDA async memcpy,
        e.g. matrix_upload's streamed transfers)."""
        import jax
        todo = []
        for leaf in jax.tree.leaves(tree):
            if hasattr(leaf, "dtype") and id(leaf) not in self._put_cache:
                todo.append(leaf)
        if not todo:
            return
        if self._ship_pool is None:
            from concurrent.futures import ThreadPoolExecutor
            self._ship_pool = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="amgx-ship")
        dev = self._ship_device

        def _ship(leaves=todo):
            # leaves are numpy on the native host path, so the casts are
            # host-side regardless of this thread's default device; the
            # rare no-toolchain fallback can leave jnp-backed leaves
            # that transfer uncast (full precision) — acceptable for a
            # path that is already warning-slow. The region is
            # deliberately NOT amg.-prefixed: it runs on the ship
            # worker, overlapped with the main-thread build — summing
            # it with the amg.* regions would double-count wall time
            # (the non-overlapped remainder shows up in
            # amg.ship_resolve instead).
            from ..profiling import trace_region
            with trace_region("ship.cast_put"):
                return jax.device_put(
                    [self._cast_leaf(x) for x in leaves], dev)

        fut = self._ship_pool.submit(_ship)
        for i, src in enumerate(todo):
            self._put_cache[id(src)] = (src, (_PENDING, fut, i))

    def _resolve_put_cache(self):
        """Wait for in-flight ship futures and replace placeholders with
        device arrays."""
        for key, (src, dev) in list(self._put_cache.items()):
            if isinstance(dev, tuple) and dev[0] is _PENDING:
                self._put_cache[key] = (src, dev[1].result()[dev[2]])

    def _prefetch_level(self, level: AMGLevel):
        """Ship a finished level's solve data while the rest of the
        hierarchy is still building (device_put is async; the transfer
        rides the tunnel behind the remaining host compute): the level
        operators, the transfer operators, and — now that smoothers
        attach per level — the smoother's solve-data payloads (layout
        slabs, damping tables, color maps)."""
        if self._ship_device is None:
            return
        A_slim = level.A.slim_for_spmv()
        if getattr(level.smoother, "_mf_stencil", None) is not None:
            # matrix-free level: never ship the value slab — the
            # solve-data tree carries only the stencil coefficients
            from ..ops.stencil import mf_slim
            A_slim = mf_slim(A_slim)
        pieces = [A_slim]
        for name in ("P", "R"):
            op = getattr(level, name, None)
            if op is not None and op.initialized:
                pieces.append(op.slim_for_spmv())
        # fused-cycle transfer slabs (built at setup by the level
        # classes): ship with the level instead of as a first-solve
        # straggler
        memo = getattr(level, "_xfer_memo", None)
        if memo is not None and memo[0] is not None:
            pieces.append(memo[0])
        if level.smoother is not None:
            pieces.append(level.smoother.solve_data())
        self._prefetch_leaves(pieces)

    def solve_data(self) -> Dict[str, Any]:
        import jax
        if self._ship_device is not None and self._data_cache is not None:
            return self._data_cache
        data = {
            "levels": [lv.level_data() for lv in self.levels],
            "coarse": self.coarse_solver.solve_data(),
        }
        if self._ship_device is not None:
            # host-built hierarchy: transfer the UNIQUE arrays (each
            # level's matrix arrays appear twice in the tree by object
            # identity — level data + smoother data; per-leaf transfer
            # would double tunnel traffic and HBM). Leaves prefetched by
            # _prefetch_level during the build are already on (or in
            # flight to) the accelerator; only the stragglers (smoother
            # and coarse-solver payloads) transfer here. amg_precision
            # casting happens host-side before the wire.
            from ..profiling import trace_region
            # ship.-prefixed (NOT amg.): solve_data may run inside a
            # caller's amg.device_sync span — an amg.* region here would
            # double-count against the disjoint-leaf attribution sum.
            # The setup-side barrier (amg.ship_resolve in
            # _finalize_setup) already accounts the level transfers.
            with trace_region("ship.resolve_stragglers"):
                self._prefetch_leaves(data)
                self._resolve_put_cache()
                self._data_cache = jax.tree.map(
                    lambda leaf: self._put_cache[id(leaf)][1]
                    if hasattr(leaf, "dtype") else leaf, data)
            return self._data_cache
        dt = self._PRECISIONS[self.precision]
        if dt is not None:
            # mixed-precision preconditioning (the dDFI-mode analog,
            # include/amgx_config.h:102-131): the whole stored hierarchy
            # and cycle run in reduced precision inside an f64 flexible
            # Krylov outer loop — on TPU this halves (or quarters) HBM
            # traffic and turns on the f32/bf16 Pallas kernel suite.
            # The COARSE-solver subtree casts to the policy's f32+
            # coarse dtype (precision.py): the dense factorization,
            # back-substitution and the K-cycle coarse matvec never
            # run below f32 even when the levels stream bf16
            memo = {}
            pre = getattr(self, "_resetup_precast", None) or {}
            cdt = self.precision_policy.coarse_dtype

            import jax.numpy as jnp

            def mk(target):
                tgt = jnp.dtype(target)

                def cast(leaf):
                    key = (id(leaf), target)
                    if key not in memo:
                        # the one-dispatch value-resetup emits the
                        # reduced-precision twins inside its own
                        # program; reuse a twin only when its dtype
                        # matches THIS subtree's target (the coarse
                        # subtree's f32+ target can differ from the
                        # level target under bf16)
                        tw = pre.get(id(leaf))
                        if tw is not None and tw.dtype == tgt:
                            out = tw
                        else:
                            out = self._cast_leaf(leaf, target)
                        memo[key] = (leaf, out)
                    return memo[key][1]
                return cast
            data = {"levels": jax.tree.map(mk(dt), data["levels"]),
                    "coarse": jax.tree.map(mk(cdt), data["coarse"])}
        return data

    def _sweeps(self, level_index: int, pre: bool) -> int:
        s = self.presweeps if pre else self.postsweeps
        if level_index == 0 and self.finest_sweeps >= 0:
            s = self.finest_sweeps
        if self.intensive_smoothing:
            s = max(4 * s, 4)
        return s

    def cycle(self, data, b, x):
        """One multigrid cycle (CycleFactory::generate analog). With
        amg_precision=float/bfloat16 the cycle computes in the reduced
        precision and the correction is returned in the caller's dtype."""
        from .cycles import run_cycle
        dt = self._PRECISIONS[self.precision]
        if dt is None:
            return run_cycle(self, self.cycle_name, data, b, x)
        out_dtype = x.dtype
        x = run_cycle(self, self.cycle_name, data,
                      b.astype(dt), x.astype(dt))
        return x.astype(out_dtype)

    def cycle_dot(self, data, b, x):
        """One cycle PLUS the x'.b dot epilogue from its final kernel
        ((x', dot), dot None when unavailable). A reduced-precision
        cycle declines the dot: the epilogue would reduce the rounded
        product while callers need the caller-dtype x'.b, so the cheap
        explicit reduction stays correct there."""
        from .cycles import run_cycle_dot
        if self._PRECISIONS[self.precision] is not None:
            return self.cycle(data, b, x), None
        return run_cycle_dot(self, self.cycle_name, data, b, x)

    # -- observability ----------------------------------------------------
    @staticmethod
    def _layout_of(M) -> str:
        if getattr(M, "dia_vals", None) is not None:
            return "dia"
        if getattr(M, "swell_vals", None) is not None:
            return "swell"
        if getattr(M, "ell_vals", None) is not None:
            return "ell"
        return "csr"

    def grid_stats_dict(self) -> Dict[str, Any]:
        """Grid statistics as STRUCTURED data (the single source of
        truth — `grid_stats()` renders its text from this, and it feeds
        `SolveReport.hierarchy` + the C API's
        `AMGX_solver_get_grid_stats`). Everything reads host metadata
        (shapes, layout presence) — building the dict issues no device
        transfers, so the per-solve report path may call it freely."""
        mats = [lv.A for lv in self.levels]
        coarsest = getattr(self, "coarsest_A", None)
        if coarsest is not None:
            mats = mats + [coarsest]
        rows: List[Dict[str, Any]] = []
        total_nnz = 0
        total_rows = 0
        for i, M in enumerate(mats):
            nnz = M.nnz * M.block_size + (
                M.num_rows * M.block_size if M.has_external_diag else 0)
            rows.append({
                "level": i,
                "rows": int(M.num_rows),
                "nnz": int(nnz),
                "sparsity": nnz / max(M.num_rows, 1) ** 2,
                "layout": self._layout_of(M),
            })
            total_nnz += nnz
            total_rows += M.num_rows
        fine_rows = rows[0]["rows"] if rows else 0
        fine_nnz = rows[0]["nnz"] if rows else 0
        return {
            "algorithm": self.algorithm,
            "cycle": self.cycle_name,
            "num_levels": len(mats),
            "levels": rows,
            "total_rows": int(total_rows),
            "total_nnz": int(total_nnz),
            "grid_complexity": total_rows / max(fine_rows, 1),
            "operator_complexity": total_nnz / max(fine_nnz, 1),
        }

    def grid_stats(self) -> str:
        """Grid-statistics report (print_grid_stats analog,
        src/amg.cu:1231-1350). Rendered from `grid_stats_dict()` so the
        text and the structured surface can never drift apart."""
        d = self.grid_stats_dict()
        lines = ["AMG Grid:",
                 f"         Number of Levels: {d['num_levels']}",
                 "            LVL         ROWS               NNZ    SPRSTY",
                 "         " + "-" * 50]
        for row in d["levels"]:
            lines.append(f"           {row['level']:3d}  "
                         f"{row['rows']:11d}  {row['nnz']:16d}  "
                         f"{row['sparsity']:8.3g}")
        lines.append("         " + "-" * 50)
        lines.append(f"         Grid Complexity: "
                     f"{d['grid_complexity']:.5g}")
        lines.append(f"         Operator Complexity: "
                     f"{d['operator_complexity']:.5g}")
        return "\n".join(lines)
