"""Classical Ruge-Stuben AMG level.

Analog of src/classical/classical_amg_level.cu (987 LoC): strength of
connection -> CF-splitting (selector) -> interpolation P -> R = P^T ->
Galerkin RAP (createCoarseVertices :213, createCoarseMatrices :254-341).
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ... import registry
from ...matrix import CsrMatrix
from ...ops.spgemm import galerkin_rap
from ...ops.spmv import spmv
from ...ops.transpose import transpose
from ..hierarchy import AMGLevel
from . import strength as _strength  # noqa: F401
from . import selectors as _selectors  # noqa: F401
from . import interpolators as _interpolators  # noqa: F401


@registry.amg_levels.register("CLASSICAL")
class ClassicalAMGLevel(AMGLevel):
    """Shared strength -> CF-split -> P -> R=P^T -> RAP level flow.
    Subclasses (energymin) retarget the selector/interpolator registries
    via the class attributes below."""

    algorithm = "CLASSICAL"
    selector_param = "selector"
    selector_fallback = "PMIS"
    interpolator_registry = registry.interpolators
    interpolator_param = "interpolator"
    interpolator_fallback = "D1"

    def create_coarse_vertices(self):
        """Strength + CF-split (markCoarseFinePoints analog,
        classical_amg_level.cu:345)."""
        if self.A.is_block:
            from ...errors import BadParametersError
            raise BadParametersError(
                f"{self.algorithm} AMG supports scalar matrices only (the "
                "reference has the same restriction); use "
                "algorithm=AGGREGATION for block matrices")
        from ...profiling import trace_region
        cfg, scope = self.cfg, self.scope
        st = registry.strength.create(str(cfg.get("strength", scope)),
                                      cfg, scope)
        with trace_region(f"amg.L{self.level_index}.strength"):
            self.strong = st.strong_mask(self.A)
        sel_name = str(cfg.get(self.selector_param, scope))
        # aggressive coarsening on the first `aggressive_levels` levels
        aggressive = self.level_index < int(cfg.get("aggressive_levels",
                                                    scope))
        if aggressive:
            agg_sel = str(cfg.get("aggressive_selector", scope))
            if agg_sel == "DEFAULT":
                agg_sel = "AGGRESSIVE_" + sel_name if not \
                    sel_name.startswith("AGGRESSIVE") else sel_name
            sel_name = agg_sel
        if not registry.classical_selectors.has(sel_name):
            sel_name = self.selector_fallback
        sel = registry.classical_selectors.create(sel_name, cfg, scope)
        with trace_region(f"amg.L{self.level_index}.cfsplit"):
            self.cf_map = sel.mark_coarse_fine_points(self.A, self.strong)
            self.coarse_size = int(jnp.sum(self.cf_map == 1))
        self._aggressive = aggressive

    def create_coarse_matrix(self) -> CsrMatrix:
        """P (interpolator), R = P^T, RAP
        (computeProlongationOperator :406, computeRestrictionOperator
        :441, csr_galerkin_product)."""
        from ...profiling import trace_region
        if getattr(self, "_reused", False):
            # structure reuse: transfer operators kept, only the
            # Galerkin product sees the new coefficients (the RAP plan
            # rides the reuse — zero symbolic work, value phase only)
            return self._galerkin_rap()
        cfg, scope = self.cfg, self.scope
        interp_name = str(cfg.get(self.interpolator_param, scope))
        if self._aggressive:
            interp_name = str(cfg.get("aggressive_interpolator", scope))
        if not self.interpolator_registry.has(interp_name):
            interp_name = self.interpolator_fallback
        interp = self.interpolator_registry.create(interp_name, cfg, scope)
        # host path: ell='auto' gives P and R the windowed-ELL (SWELL)
        # layout, the Pallas gather kernel's storage — transfer operators
        # are the other half of the unstructured cycle's SpMV traffic.
        # setup_backend=device also uses ell='auto': the DIA/ELL layouts
        # build from the device CSR directly (_choose_layout's jnp path,
        # no host round trip). Only the legacy in-place accelerator path
        # keeps ell='never' (its layout probe would block per level).
        from ...matrix import device_setup_forced, host_resident
        k = self.level_index
        with trace_region(f"amg.L{k}.interp"):
            P = interp.generate(self.A, self.cf_map, self.strong)
        ell = "auto" if device_setup_forced() or host_resident(
            P.row_offsets, P.col_indices, P.values) else "never"
        with trace_region(f"amg.L{k}.layoutP"):
            self.P = P.init(ell=ell)
        with trace_region(f"amg.L{k}.transposeR"):
            self.R = transpose(self.P).init(ell=ell)
        # weighted transfer slabs for the fused cycle kernels: built at
        # SETUP (inside the accounted span) so the first solve pays no
        # slab assembly and the host-ship pipeline can prefetch them
        with trace_region(f"amg.L{k}.xfer_slabs"):
            self._transfer_slabs()
        return self._galerkin_rap()

    def _galerkin_rap(self) -> CsrMatrix:
        """RAP through the plan split (ops/spgemm.py): the structure
        phase is memoized on the level (structure resetups carry it —
        P/R survive with their values) and in the digest-keyed cache
        (warm full setups of the same pattern hit it), so only the
        VALUE phase runs per setup — through the fused kernel / slab /
        host-reduceat route regardless of backend forcing. The plan
        lookup precedes the host-native dispatch on purpose: a warm
        host setup used to rebuild the whole product from numpy even
        when the pattern was already planned. spgemm_plan=0 (or
        ineligible operands) short-circuits to the eager
        `galerkin_rap` composition, bit-for-bit."""
        from ...ops import spgemm
        from ...profiling import trace_region
        k = self.level_index
        if spgemm.plan_enabled(self.cfg, self.scope) \
                and not self.A.is_block:
            plan = None
            # the memo shortcut must prove the PATTERN unchanged, not
            # just the sizes: A's structure arrays are compared by
            # identity (retained in the memo — id() alone could alias
            # a freed array). A value-splice resetup keeps the objects
            # (and a planned product's output structure arrays are the
            # plan's own cached uploads, identical across resetups);
            # anything else falls through to the digest cache, which
            # keys on content — a same-nnz permuted pattern can never
            # be served a stale plan.
            memo = getattr(self, "_rap_plan_memo", None)
            if memo is not None and memo[0] is self.P \
                    and memo[1] is self.R \
                    and memo[2] is self.A.row_offsets \
                    and memo[3] is self.A.col_indices \
                    and memo[4] == self.A.has_external_diag:
                plan = memo[5]
            if plan is None:
                with trace_region(f"amg.L{k}.rap_plan"):
                    plan = spgemm.get_rap_plan(self.R, self.A, self.P)
                if plan is not None:
                    self._rap_plan_memo = (
                        self.P, self.R, self.A.row_offsets,
                        self.A.col_indices, self.A.has_external_diag,
                        plan)
            if plan is not None:
                with trace_region(f"amg.L{k}.rap_values"):
                    return spgemm.plan_coarse_matrix(plan, self.A,
                                                     self.R, self.P)
        with trace_region(f"amg.L{k}.rap"):
            return galerkin_rap(self.R, self.A, self.P)

    def reuse_structure(self, old):
        """structure_reuse_levels: keep strength/CF-split and the
        transfer operators from the prior setup."""
        self.strong = old.strong
        self.cf_map = old.cf_map
        self.coarse_size = old.coarse_size
        self._aggressive = old._aggressive
        self.P = old.P
        self.R = old.R
        # the transfer slabs are a function of (A's DIA offsets, P, R)
        # — all kept by structure reuse — so the memo carries over
        # when the new coefficients kept the offset packing (a
        # restored ghost has none; the lazy level_data path rebuilds)
        memo = getattr(old, "_xfer_memo", None)
        if memo is not None and getattr(self.A, "dia_offsets", None) \
                == getattr(old.A, "dia_offsets", None):
            self._xfer_memo = memo
        # the RAP plan is a function of (A pattern, P, R) — all kept by
        # structure reuse — so a resetup's Galerkin is value-phase only
        memo = getattr(old, "_rap_plan_memo", None)
        if memo is not None:
            self._rap_plan_memo = memo
        self._reused = True

    def structure_snapshot(self):
        P = getattr(self, "P", None)
        if P is None or self.coarse_size is None or P.is_block:
            return None
        meta = {"num_rows": int(self.A.num_rows),
                "coarse_size": int(self.coarse_size),
                "aggressive": bool(self._aggressive),
                "p_rows": int(P.num_rows), "p_cols": int(P.num_cols)}
        # R = P^T is recomputed on restore (bit-exact, and exactly how
        # create_coarse_matrix built it); `strong` is only consulted by
        # a FRESH interpolation, which the reuse path never runs
        arrays = {"cf_map": np.asarray(self.cf_map),
                  "p_row_offsets": np.asarray(P.row_offsets),
                  "p_col_indices": np.asarray(P.col_indices),
                  "p_values": np.asarray(P.values)}
        return meta, arrays

    @classmethod
    def structure_restore(cls, meta, arrays):
        from ...matrix import device_setup_forced, host_resident
        g = cls._ghost(meta["num_rows"])
        g.coarse_size = int(meta["coarse_size"])
        g._aggressive = bool(meta["aggressive"])
        g.cf_map = arrays["cf_map"]
        g.strong = None
        P = CsrMatrix(row_offsets=arrays["p_row_offsets"],
                      col_indices=arrays["p_col_indices"],
                      values=arrays["p_values"],
                      num_rows=int(meta["p_rows"]),
                      num_cols=int(meta["p_cols"]))
        ell = "auto" if device_setup_forced() or host_resident(
            P.row_offsets, P.col_indices, P.values) else "never"
        g.P = P.init(ell=ell)
        g.R = transpose(g.P).init(ell=ell)
        return g

    def level_data(self):
        d = super().level_data()
        # the cycle only SpMVs against the transfer operators — layout
        # views keep their CSR payloads out of the solve program's HBM
        d["P"] = self.P.slim_for_spmv()
        d["R"] = self.R.slim_for_spmv()
        xfer = self._transfer_slabs()
        if xfer is not None:
            d["xfer"] = xfer
        return d

    def _transfer_slabs(self):
        """Weighted row-segment transfer payloads for the fused cycle
        kernels (ops/smooth.py build_csr_transfer_slabs), memoized on
        the level. Built at setup inside amg.L*.xfer_slabs (and kept
        across structure reuse — P/R survive value resetups on
        classical levels, weights included, so the slabs are
        structure-lifetime payloads). None off-TPU, with
        cycle_fusion=0, for non-DIA fine operators, or when a P/R row
        exceeds the kernel child caps — those configs build nothing
        and the cycle composes the explicit R/P SpMVs unchanged."""
        memo = getattr(self, "_xfer_memo", None)
        if memo is not None:
            return memo[0]
        from ...ops import smooth as fused
        slabs = None
        if bool(int(self.cfg.get("cycle_fusion", self.scope))) \
                and fused.fused_runtime_on() \
                and getattr(self, "P", None) is not None \
                and getattr(self, "R", None) is not None \
                and self.coarse_size:
            # weight slabs emit in the hierarchy's effective precision
            # (precision.py) so the solve-data cast never materializes
            # a full-precision twin of the cwt/pwt payloads
            from ...precision import resolve_precision
            dt = resolve_precision(self.cfg, self.scope).cast_dtype
            slabs = fused.build_csr_transfer_slabs(self.A, self.P,
                                                   self.R, dtype=dt)
        self._xfer_memo = (slabs,)
        return slabs

    # -- cycle fusion (amg/cycles.py _fusion_caps dispatch) ------------
    def supports_fusion(self, data):
        """Classical levels advertise the fused grid-transfer kernels
        when their weighted row-segment slabs built (DIA fine
        operator, rows within the child caps); everything else — and
        every smoother without a fused form — composes the explicit
        R/P SpMVs exactly as before. Distributed classical levels are
        a different class and advertise nothing (the capability is
        resolved through the CLASS, see cycles._fusion_caps)."""
        if data.get("xfer") is None or self.smoother is None:
            return ()
        return self.FUSION_CAPS

    def restrict_fused(self, data, b, x, sweeps: int):
        """Presmooth + weighted-restriction epilogue in one kernel
        (bc = R(b - A x') summed in VMEM), or None (caller composes
        smooth_residual -> spmv(R, r))."""
        fn = getattr(self.smoother, "smooth_restrict", None)
        if fn is None:
            return None
        return fn(data["smoother"], b, x, sweeps, data["xfer"])

    def prolongate_smooth(self, data, b, x, xc, sweeps: int,
                          want_dot: bool = False):
        """Weighted prolongation/correction (x + P xc) folded into the
        postsmoother's first kernel application, or None. want_dot
        additionally requests the x'.b dot epilogue → (x', dot|None)."""
        fn = getattr(self.smoother, "smooth_corr", None)
        if fn is None:
            return None
        return fn(data["smoother"], b, x, xc, sweeps, data["xfer"],
                  want_dot=want_dot)

    def restrict(self, data, r):
        return spmv(data["R"], r)

    def prolongate(self, data, xc):
        return spmv(data["P"], xc)
