"""CF-splitting selectors: PMIS / RS / HMIS / CR and aggressive variants.

Analogs of src/classical/selectors/ (pmis.cu 657 LoC, rs.cu, hmis.cu,
cr.cu 663 LoC, aggressive_*.cu, selector.cu).

- PMIS (parallel modified independent set) is a natural TPU fit — it is
  already a data-parallel fixed point:

    weight w_i = strong-degree(i) + hash(i)      (deterministic "random")
    repeat: undecided i with w_i greater than every undecided strong
            neighbor's weight becomes COARSE; undecided neighbors of new
            COARSE points become FINE.

  expressed as segment-max sweeps over the symmetrized strength graph.
- RS is the classical serial first pass. The reference itself refuses to
  run it on the GPU ("it's a sequential algorithm", rs.cu:269-277) and
  runs it on the HOST; here it is a native C++ bucket-queue component
  (amgx_tpu/native/src/rs.cpp) with a Python fallback.
- HMIS = host RS pass, then PMIS initialized from that result — exactly
  the reference composition (hmis.cu:55-82). On one device the PMIS pass
  is a no-op fixup (every point is already assigned); under domain
  decomposition it resolves boundary inconsistencies.
- CR (compatible relaxation): smooth the homogeneous system on the
  current F-set; slow-to-decay points are coarse-grid candidates, and an
  independent subset joins C each round (cr.cu structure: presmooth
  fine-error + update cf_map from smoother colors).
- AGGRESSIVE_* run the PMIS fixed point on the two-hop strength graph
  S@S, giving the reference's aggressive-coarsening grid sizes.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ... import registry
from ...matrix import CsrMatrix

FINE, COARSE, UNDECIDED = 0, 1, -1


def _hash01(n):
    i = jnp.arange(n, dtype=jnp.uint32)
    h = i * jnp.uint32(2654435761)
    h = (h ^ (h >> 16)) * jnp.uint32(0x45D9F3B)
    h = h ^ (h >> 16)
    return (h & jnp.uint32(0xFFFFF)).astype(jnp.float64) / float(1 << 20)


def _symmetrize(rows, cols, mask, n):
    """Edges of S | S^T as (rows2, cols2) with duplicates kept (harmless
    for max/any reductions)."""
    r = jnp.concatenate([rows[mask], cols[mask]])
    c = jnp.concatenate([cols[mask], rows[mask]])
    order = jnp.argsort(r, stable=True)
    return r[order], c[order]


def pmis_split(A: CsrMatrix, strong, max_iters: int = 30, init=None):
    """Returns cf_map (n,) in {FINE, COARSE}. `init` (optional) seeds the
    fixed point with already-decided assignments (cf_map_init=1 analog,
    pmis.cu:508): entries in {FINE, COARSE} are kept, UNDECIDED entries
    are resolved by the PMIS sweeps."""
    n = A.num_rows
    from ...ops.spgemm import _on_host
    if _on_host(A):
        # host-setup path: the synchronous fixed point as a native C++
        # sweep (bit-exact: same weights, same round structure)
        from ...native import pmis_native
        cf = pmis_native(
            n, np.asarray(A.row_offsets), np.asarray(A.col_indices),
            np.asarray(strong, np.uint8),
            None if init is None else np.asarray(init, np.int32),
            max_iters)
        if cf is not None:
            # numpy on purpose: the host hierarchy build stays off jax
            # CPU arrays (jnp consumers accept numpy transparently)
            return cf
    rows, cols, _ = A.coo()
    sr, sc = _symmetrize(rows, cols, strong, n)
    deg = jnp.zeros((n,), jnp.float64).at[sr].add(1.0) * 0.5
    w = deg + _hash01(n)
    if init is None:
        state = jnp.full((n,), UNDECIDED, jnp.int32)
    else:
        state = jnp.asarray(init, jnp.int32)
    # isolated points (no strong connections): they cannot interpolate —
    # make them COARSE (kept exactly, matches Dirichlet-row handling)
    has_nbr = jnp.zeros((n,), bool).at[sr].set(True)
    state = jnp.where((state == UNDECIDED) & ~has_nbr, COARSE, state)

    for _ in range(max_iters):
        und = state == UNDECIDED
        if not bool(jnp.any(und)):
            break
        active_edge = und[sr] & und[sc]
        nbr_max = jax.ops.segment_max(
            jnp.where(active_edge, w[sc], -jnp.inf), sr, num_segments=n,
            indices_are_sorted=True)
        new_c = und & (w > nbr_max)
        state = jnp.where(new_c, COARSE, state)
        # undecided points strongly connected to any C point become FINE
        c_nbr = jnp.zeros((n,), bool).at[sr].max(state[sc] == COARSE)
        state = jnp.where((state == UNDECIDED) & c_nbr, FINE, state)
    state = jnp.where(state == UNDECIDED, FINE, state)
    return state.astype(jnp.int32)


def rs_split_python(n, row_offsets, col_indices, strong):
    """Pure-Python RS first pass (fallback when the native lib is
    unavailable). Bit-identical port of native/src/rs.cpp — same bucket
    queue with the same LIFO tie-breaking, so the CF splitting (and
    every hierarchy built on it) is identical with or without the native
    library."""
    ro = np.asarray(row_offsets)
    ci = np.asarray(col_indices)
    st = np.asarray(strong, bool)
    row_ids = np.repeat(np.arange(n), np.diff(ro))
    mask = st & (ci < n) & (ci != row_ids)
    # S (per-row) and S^T (per-col) adjacency, numpy-built
    s_r, s_c = row_ids[mask], ci[mask]
    order = np.argsort(s_c, kind="stable")
    st_c, st_r = s_c[order], s_r[order]
    st_off = np.zeros(n + 1, np.int64)
    np.add.at(st_off, st_c + 1, 1)
    np.cumsum(st_off, out=st_off)
    s_off = np.zeros(n + 1, np.int64)
    np.add.at(s_off, s_r + 1, 1)
    np.cumsum(s_off, out=s_off)

    # bucket queue: head per weight + doubly-linked node lists (rs.cpp);
    # weights are bounded by 2*|S^T_i| (initial in-degree + one bump per
    # in-edge), hence the 2n+2 sizing
    head = np.full(2 * n + 2, -1, np.int64)
    prev = np.full(n, -1, np.int64)
    nxt = np.full(n, -1, np.int64)
    weight = np.zeros(n, np.int64)
    maxw = 0

    def push(i, w):
        nonlocal maxw
        weight[i] = w
        prev[i] = -1
        nxt[i] = head[w]
        if head[w] >= 0:
            prev[head[w]] = i
        head[w] = i
        if w > maxw:
            maxw = w

    def remove(i):
        w = weight[i]
        if prev[i] >= 0:
            nxt[prev[i]] = nxt[i]
        else:
            head[w] = nxt[i]
        if nxt[i] >= 0:
            prev[nxt[i]] = prev[i]
        prev[i] = nxt[i] = -1

    lam = np.diff(st_off).astype(np.int64)
    out_deg = np.diff(s_off)
    state = np.full(n, UNDECIDED, np.int32)
    in_q = lam > 0
    # lam==0: FINE, except fully strong-isolated points (no in- or
    # out-edges) which cannot interpolate -> COARSE (pmis convention)
    state[~in_q] = np.where(out_deg[~in_q] == 0, COARSE, FINE)
    # push in ascending node order, exactly like the C++ loop
    for i in range(n):
        if in_q[i]:
            push(i, lam[i])
    while True:
        while maxw >= 0 and head[maxw] < 0:
            maxw -= 1
        if maxw < 0:
            break
        i = head[maxw]
        remove(i)
        if state[i] != UNDECIDED:
            continue
        state[i] = COARSE
        for t in range(st_off[i], st_off[i + 1]):
            j = st_r[t]
            if state[j] != UNDECIDED:
                continue
            state[j] = FINE
            remove(j)
            for u in range(s_off[j], s_off[j + 1]):
                k = s_c[u]
                if state[k] == UNDECIDED:
                    remove(k)
                    push(k, weight[k] + 1)
    return np.where(state == COARSE, 1, 0).astype(np.int32)


def rs_split(A: CsrMatrix, strong):
    """RS first-pass coarsening: native C++ bucket queue, Python
    fallback."""
    from ...native import rs_coarsen_native, warn_python_fallback
    n = A.num_rows
    ro = np.asarray(A.row_offsets)
    ci = np.asarray(A.col_indices)
    st = np.asarray(strong, np.uint8)
    cf = rs_coarsen_native(n, ro, ci, st)
    if cf is None:
        warn_python_fallback("RS coarsening", n)
        cf = rs_split_python(n, ro, ci, st)
    return jnp.asarray(cf, jnp.int32)


def _hash_key(n):
    """The PMIS integer hash (same mixing as _hash01, kept as int64):
    a deterministic per-vertex tie-break that is bit-identical on
    every backend (pure uint32 arithmetic, no float rounding)."""
    i = jnp.arange(n, dtype=jnp.uint32)
    h = i * jnp.uint32(2654435761)
    h = (h ^ (h >> 16)) * jnp.uint32(0x45D9F3B)
    h = h ^ (h >> 16)
    return (h & jnp.uint32(0xFFFFF)).astype(jnp.int64)


def rs_sweep(A: CsrMatrix, strong, max_rounds: int = 200):
    """Device-parallel RS first pass: a PMIS-style independent-set
    FIXPOINT with the RS weight as priority (SParSH-AMG's CPU-GPU
    split taken all the way onto the device, arXiv:2007.00056; CLJP
    family). Eager jnp over concrete shapes, so it runs inside the
    setup_backend=device pipeline with zero host-serial work.

    Per round, over the current UNDECIDED set:

      key_i  = lambda_i * 2^20 + hash(i)         (int64, lambda_i =
               the LIVE RS weight: S^T in-degree plus one bump per
               strong neighbor already turned FINE — the bucket
               queue's exact weight function, updated per round
               instead of per pop)
      C:       undecided i whose key beats every undecided neighbor
               in S | S^T (the serial pop's conflict set: a selection
               can only FINE its S^T-dependents, so strict local
               maxima are simultaneously safe)
      F:       undecided j with a new COARSE point in S(j)
      bump:    +1 per (newly FINE j -> undecided k in S(j)) edge

    Initialization matches the queue: lambda=0 vertices start FINE
    (COARSE when fully isolated) and never bump their neighbors.

    NOT bit-equivalent to the serial bucket queue: the queue's
    dynamic LIFO tie-break makes its pop order inherently serial (a
    weight bump re-queues a vertex at its bucket's head), so the host
    path (`selector_device_sweep=0`, or setup_backend=host with
    `auto`) keeps the queue as the reference implementation and
    quality oracle, while this sweep is bit-deterministic ACROSS
    BACKENDS — host-jnp and device runs produce identical splits
    (integer arithmetic only), which is what the device-setup parity
    contract checks. Leftover UNDECIDED vertices after `max_rounds`
    (hash-collision stalemates, < 2^-20 per adjacent pair) turn FINE
    exactly like the PMIS fixpoint's tail."""
    n = A.num_rows
    rows, cols, _ = A.coo()
    rows = jnp.asarray(rows)
    cols = jnp.asarray(cols)
    st = jnp.asarray(strong, bool)
    mask = st & (cols < n) & (cols != rows)
    er = rows[mask]          # directed strength edges: ec in S(er)
    ec = cols[mask]
    one = jnp.ones(er.shape, jnp.int64)
    lam = jnp.zeros((n,), jnp.int64).at[ec].add(one)   # S^T in-degree
    out_deg = jnp.zeros((n,), jnp.int64).at[er].add(one)
    idx = jnp.arange(n, dtype=jnp.int64)
    key_base = _hash_key(n)
    state = jnp.full((n,), UNDECIDED, jnp.int32)
    # lambda == 0: never queued — FINE, except fully isolated points
    # (no edges either way) which cannot interpolate -> COARSE
    no_in = lam == 0
    state = jnp.where(no_in & (out_deg == 0), COARSE,
                      jnp.where(no_in, FINE, state))
    for _ in range(max_rounds):
        und = state == UNDECIDED
        if not bool(jnp.any(und)):
            break
        key = lam * jnp.int64(1 << 20) + key_base
        live = und[er] & und[ec]
        km = jnp.where(live, key[ec], jnp.int64(-1))
        nbr = jnp.full((n,), jnp.int64(-1)).at[er].max(km)
        nbr = nbr.at[ec].max(jnp.where(live, key[er], jnp.int64(-1)))
        new_c = und & (key > nbr)
        state = jnp.where(new_c, COARSE, state)
        # undecided j strongly depending on a new C point -> FINE
        f_hit = jnp.zeros((n,), bool).at[er].max(new_c[ec])
        newly_f = und & ~new_c & f_hit
        state = jnp.where(newly_f, FINE, state)
        # RS weight update: each newly-FINE j bumps its still-
        # undecided strong neighbors k in S(j) by one per edge
        und2 = state == UNDECIDED
        lam = lam.at[ec].add(jnp.where(newly_f[er] & und2[ec],
                                       jnp.int64(1), jnp.int64(0)))
    return jnp.where(state == COARSE, 1, 0).astype(jnp.int32)


def _rs_first_pass(cfg, scope, A: CsrMatrix, strong):
    """RS/HMIS first-pass dispatch: the host bucket queue (the
    reference), or the device-parallel sweep. `selector_device_sweep`
    auto = sweep exactly when the setup pipeline is device-forced
    (setup_backend=device, PR-3 threadlocal), 1 = always sweep (the
    cross-backend parity shape), 0 = always the bucket queue (the
    escape hatch that restores bit-identical splits vs host builds)."""
    mode = str(cfg.get("selector_device_sweep", scope))
    from ...matrix import device_setup_forced
    if mode == "1" or (mode == "auto" and device_setup_forced()):
        from ...profiling import trace_region
        from ...telemetry import metrics as _tm
        _tm.inc("amg.selector.device_sweep")
        with trace_region("selector.device_sweep"):
            return rs_sweep(A, strong)
    return rs_split(A, strong)


def _two_hop_strength(A: CsrMatrix, strong):
    """Boolean S@S (distance-2 strength) as a COO edge list, built with
    the sort-based expand machinery (aggressive coarsening graph)."""
    from ...ops.spgemm import csr_multiply
    rows, cols, vals = A.coo()
    sv = jnp.where(strong, 1.0, 0.0)
    S = CsrMatrix(row_offsets=A.row_offsets, col_indices=A.col_indices,
                  values=sv, num_rows=A.num_rows, num_cols=A.num_cols)
    S2 = csr_multiply(S, S)
    return S2


class ClassicalSelector:
    def __init__(self, cfg, scope):
        self.cfg = cfg
        self.scope = scope

    def mark_coarse_fine_points(self, A: CsrMatrix, strong):
        raise NotImplementedError


@registry.classical_selectors.register("PMIS")
class PMISSelector(ClassicalSelector):
    def mark_coarse_fine_points(self, A, strong):
        return pmis_split(A, strong)


@registry.classical_selectors.register("RS")
class RSSelector(ClassicalSelector):
    """Ruge-Stueben first pass: the serial bucket queue (rs.cu host
    path) or, under the device setup pipeline, the device-parallel
    independent-set sweep (`selector_device_sweep`)."""

    def mark_coarse_fine_points(self, A, strong):
        return _rs_first_pass(self.cfg, self.scope, A, strong)


@registry.classical_selectors.register("HMIS")
class HMISSelector(ClassicalSelector):
    """RS first pass, then PMIS seeded with the RS result
    (hmis.cu:55-82). Single-device the PMIS pass keeps the first
    pass's assignment; it exists to resolve partition-boundary
    points. The first pass routes like RSSelector: the host bucket
    queue by default, the device-parallel sweep under the device
    setup pipeline (`selector_device_sweep`)."""

    def mark_coarse_fine_points(self, A, strong):
        cf = _rs_first_pass(self.cfg, self.scope, A, strong)
        return pmis_split(A, strong, init=cf)


@registry.classical_selectors.register("AGGRESSIVE_PMIS")
@registry.classical_selectors.register("AGGRESSIVE_HMIS")
class AggressivePMISSelector(ClassicalSelector):
    """PMIS on the two-hop strength graph -> much smaller coarse grids
    (aggressive_pmis.cu behavior)."""

    def mark_coarse_fine_points(self, A, strong):
        S2 = _two_hop_strength(A, strong)
        r2, c2, v2 = S2.coo()
        strong2 = (v2 > 0) & (r2 != c2)
        return pmis_split(S2, strong2)


@registry.classical_selectors.register("CR")
class CRSelector(ClassicalSelector):
    """Compatible-relaxation selector (cr.cu). Starting from an empty
    (or tiny) C-set, repeatedly:

      1. relax the homogeneous system A e = 0 on the F-points (weighted
         Jacobi sweeps with e zeroed at C — the reference presmooths with
         MULTICOLOR_GS, cr.cu:366-435; Jacobi keeps it one XLA program);
      2. the normalized surviving error mu_i = |e_i| / max|e| measures
         how badly relaxation alone handles point i;
      3. slow points (mu_i >= theta) above the global convergence target
         join C as an independent set weighted by mu (the reference uses
         smoother colors for independence, cr.cu:123-144).

    Stops when the CR convergence factor is below 0.7 or the candidate
    set is empty.
    """

    NU = 4              # relaxation sweeps per round
    THETA = 0.5         # candidate threshold on normalized error
    MAX_ROUNDS = 10
    TARGET_RATE = 0.7

    def mark_coarse_fine_points(self, A, strong):
        n = A.num_rows
        rows, cols, _ = A.coo()
        sr, sc = _symmetrize(rows, cols, strong, n)
        diag = A.diagonal()
        dinv = jnp.where(diag != 0, 1.0 / jnp.where(diag == 0, 1.0, diag),
                         0.0)
        from ...ops.spmv import spmv
        state = jnp.full((n,), UNDECIDED, jnp.int32)
        has_nbr = jnp.zeros((n,), bool).at[sr].set(True)
        state = jnp.where(~has_nbr, COARSE, state)  # isolated rows
        rng = np.random.default_rng(5)
        e0 = jnp.asarray(rng.standard_normal(n), A.dtype)

        for _ in range(self.MAX_ROUNDS):
            is_c = state == COARSE
            e = jnp.where(is_c, 0.0, e0)
            e = e / jnp.maximum(jnp.linalg.norm(e), 1e-30)
            norm_prev = jnp.linalg.norm(e)
            for _ in range(self.NU):
                norm_prev = jnp.linalg.norm(e)
                e = e - 0.666 * dinv * spmv(A, e)
                e = jnp.where(is_c, 0.0, e)
            # asymptotic measure: ratio of the LAST sweep (early sweeps
            # only show the fast high-frequency decay)
            rate = jnp.linalg.norm(e) / jnp.maximum(norm_prev, 1e-30)
            if float(rate) < self.TARGET_RATE:
                break
            mu = jnp.abs(e) / jnp.maximum(jnp.max(jnp.abs(e)), 1e-30)
            cand = (state == UNDECIDED) & (mu >= self.THETA)
            if not bool(jnp.any(cand)):
                break
            # independent set among candidates, weighted by mu
            w = mu + _hash01(n) * 1e-6
            active = cand[sr] & cand[sc]
            nbr_max = jax.ops.segment_max(
                jnp.where(active, w[sc], -jnp.inf), sr, num_segments=n,
                indices_are_sorted=True)
            new_c = cand & (w > nbr_max)
            state = jnp.where(new_c, COARSE, state)
        # coverage completion: every F point needs at least one strong C
        # neighbor or classical interpolation has nothing to work with —
        # promote independent sets of uncovered points until covered
        deg = jnp.zeros((n,), jnp.float64).at[sr].add(1.0)
        wfix = deg + _hash01(n)
        for _ in range(30):
            is_c = state == COARSE
            covered = jnp.zeros((n,), bool).at[sr].max(is_c[sc])
            unc = ~is_c & has_nbr & ~covered
            if not bool(jnp.any(unc)):
                break
            active = unc[sr] & unc[sc]
            nbr_max = jax.ops.segment_max(
                jnp.where(active, wfix[sc], -jnp.inf), sr, num_segments=n,
                indices_are_sorted=True)
            state = jnp.where(unc & (wfix > nbr_max), COARSE, state)
        # everything not selected is FINE
        return jnp.where(state == COARSE, COARSE, FINE).astype(jnp.int32)


@registry.classical_selectors.register("DUMMY_CLASSICAL")
class DummyClassicalSelector(ClassicalSelector):
    """Every other point coarse (dummy_selector.cu analog)."""

    def mark_coarse_fine_points(self, A, strong):
        n = A.num_rows
        return (jnp.arange(n, dtype=jnp.int32) % 2 == 0).astype(jnp.int32)
