"""CF-splitting selectors: PMIS / HMIS and aggressive variants.

Analogs of src/classical/selectors/ (pmis.cu 657 LoC, hmis.cu,
aggressive_*.cu, selector.cu). PMIS (parallel modified independent set)
is a natural TPU fit — it is already a data-parallel fixed point:

  weight w_i = strong-degree(i) + hash(i)        (deterministic "random")
  repeat:  undecided i with w_i greater than every undecided strong
           neighbor's weight becomes COARSE; undecided neighbors of new
           COARSE points become FINE.

expressed as segment-max sweeps over the symmetrized strength graph.
HMIS runs PMIS on the distance-two strength graph restricted to a
first-pass independent set; here (round 1) HMIS shares the PMIS fixed
point on S, and the AGGRESSIVE_* variants run the same fixed point on
S@S (two-hop strength), giving the reference's aggressive-coarsening
grid-size behavior.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ... import registry
from ...matrix import CsrMatrix

FINE, COARSE, UNDECIDED = 0, 1, -1


def _hash01(n):
    i = jnp.arange(n, dtype=jnp.uint32)
    h = i * jnp.uint32(2654435761)
    h = (h ^ (h >> 16)) * jnp.uint32(0x45D9F3B)
    h = h ^ (h >> 16)
    return (h & jnp.uint32(0xFFFFF)).astype(jnp.float64) / float(1 << 20)


def _symmetrize(rows, cols, mask, n):
    """Edges of S | S^T as (rows2, cols2) with duplicates kept (harmless
    for max/any reductions)."""
    r = jnp.concatenate([rows[mask], cols[mask]])
    c = jnp.concatenate([cols[mask], rows[mask]])
    order = jnp.argsort(r, stable=True)
    return r[order], c[order]


def pmis_split(A: CsrMatrix, strong, max_iters: int = 30):
    """Returns cf_map (n,) in {FINE, COARSE}."""
    n = A.num_rows
    rows, cols, _ = A.coo()
    sr, sc = _symmetrize(rows, cols, strong, n)
    deg = jnp.zeros((n,), jnp.float64).at[sr].add(1.0) * 0.5
    w = deg + _hash01(n)
    state = jnp.full((n,), UNDECIDED, jnp.int32)
    # isolated points (no strong connections): they cannot interpolate —
    # make them COARSE (kept exactly, matches Dirichlet-row handling)
    has_nbr = jnp.zeros((n,), bool).at[sr].set(True)
    state = jnp.where(~has_nbr, COARSE, state)

    for _ in range(max_iters):
        und = state == UNDECIDED
        if not bool(jnp.any(und)):
            break
        active_edge = und[sr] & und[sc]
        nbr_max = jax.ops.segment_max(
            jnp.where(active_edge, w[sc], -jnp.inf), sr, num_segments=n,
            indices_are_sorted=True)
        new_c = und & (w > nbr_max)
        state = jnp.where(new_c, COARSE, state)
        # undecided points strongly connected to any C point become FINE
        c_nbr = jnp.zeros((n,), bool).at[sr].max(state[sc] == COARSE)
        state = jnp.where((state == UNDECIDED) & c_nbr, FINE, state)
    state = jnp.where(state == UNDECIDED, FINE, state)
    return state.astype(jnp.int32)


def _two_hop_strength(A: CsrMatrix, strong):
    """Boolean S@S (distance-2 strength) as a COO edge list, built with
    the sort-based expand machinery (aggressive coarsening graph)."""
    from ...ops.spgemm import csr_multiply
    rows, cols, vals = A.coo()
    sv = jnp.where(strong, 1.0, 0.0)
    S = CsrMatrix(row_offsets=A.row_offsets, col_indices=A.col_indices,
                  values=sv, num_rows=A.num_rows, num_cols=A.num_cols)
    S2 = csr_multiply(S, S)
    return S2


class ClassicalSelector:
    def __init__(self, cfg, scope):
        self.cfg = cfg
        self.scope = scope

    def mark_coarse_fine_points(self, A: CsrMatrix, strong):
        raise NotImplementedError


@registry.classical_selectors.register("PMIS")
@registry.classical_selectors.register("HMIS")
class PMISSelector(ClassicalSelector):
    def mark_coarse_fine_points(self, A, strong):
        return pmis_split(A, strong)


@registry.classical_selectors.register("AGGRESSIVE_PMIS")
@registry.classical_selectors.register("AGGRESSIVE_HMIS")
class AggressivePMISSelector(ClassicalSelector):
    """PMIS on the two-hop strength graph -> much smaller coarse grids
    (aggressive_pmis.cu behavior)."""

    def mark_coarse_fine_points(self, A, strong):
        S2 = _two_hop_strength(A, strong)
        r2, c2, v2 = S2.coo()
        strong2 = (v2 > 0) & (r2 != c2)
        return pmis_split(S2, strong2)


@registry.classical_selectors.register("CR")
@registry.classical_selectors.register("DUMMY_CLASSICAL")
class DummyClassicalSelector(ClassicalSelector):
    """Every other point coarse (dummy selector analog; also stands in
    for CR until compatible relaxation lands)."""

    def mark_coarse_fine_points(self, A, strong):
        n = A.num_rows
        return (jnp.arange(n, dtype=jnp.int32) % 2 == 0).astype(jnp.int32)
